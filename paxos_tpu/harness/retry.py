"""Shared retry/backoff — exponential schedule with equal jitter.

Extracted from ``harness.soak._run_with_retries`` so every host-side
actor that talks to flaky infrastructure — the soak loop's campaign
replays, the fleet coordinator's worker dispatch, a worker's lease
renewals, the durable queue's file I/O — retries through one tested
policy instead of four ad-hoc loops.

The jitter is drawn from a REGISTERED pure-integer stream (the same
splitmix64 the fuzz mutator uses, forked under a fixed fold so it can
never collide with the mutation streams sharing a root seed) rather than
``random.random()``: no global-state or time-based randomness anywhere,
and a test can pin the exact sleep sequence by seed.  The sleep itself
still goes through ``time.sleep``, so tests patching the module-level
sleep observe every backoff.  Nothing here is schedule-relevant: retried
campaigns are deterministic replays, and lease/queue retries are pure
host I/O — jitter only desyncs concurrent actors sharing a backend.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

from paxos_tpu.fuzz.mutate import SplitMix64

# Stream-registry fold (fuzz.mutate idiom): jitter draws come from
# SplitMix64(seed).fork(_JITTER_FOLD), a lane the mutation ops never use.
_JITTER_FOLD = 0x6A17


def retry_schedule(
    retries: int, base_s: float = 5.0, cap_s: float = 60.0
) -> list[float]:
    """Planned pre-retry delays: exponential from ``base_s``, capped.

    Doubling per attempt models the two real failure modes: blips (first
    retry lands) and minutes-long outages (tunnel restart, preemption),
    where hammering a recovering endpoint every 5 s just extends the
    outage.  The cap keeps the worst wait ~1 min so a soak never stalls
    much longer than the thing it waited out.
    """
    return [min(base_s * (2.0 ** i), cap_s) for i in range(retries)]


def jitter_stream(seed: int) -> SplitMix64:
    """The registered pure-integer jitter stream for one actor."""
    return SplitMix64(seed).fork(_JITTER_FOLD)


def equal_jitter(delay: float, stream: SplitMix64) -> float:
    """One sleep drawn from [delay/2, delay] — equal jitter, so
    concurrent actors sharing a backend desync instead of re-colliding
    in lockstep."""
    frac = stream.next_u64() / 2.0 ** 64
    return delay * (0.5 + frac / 2.0)


def run_with_retries(
    run_fn: Callable[[], Any],
    say: Callable[[str], None],
    retries: int,
    backoff_s: float = 5.0,
    cap_s: float = 60.0,
    *,
    retry_on: tuple = (OSError,),
    describe: str = "transient error",
    spans=None,
    jitter_seed: Optional[int] = None,
) -> "tuple[Any, int]":
    """Call ``run_fn``, retrying exceptions in ``retry_on``.

    Delays follow :func:`retry_schedule` with equal jitter from
    :func:`jitter_stream` — ``jitter_seed=None`` (the default) keys the
    stream by pid, so co-located actors draw different sequences while a
    test pinning the seed gets an exactly reproducible one.  Returns
    ``(result, retries_used)``; re-raises once the budget is exhausted.
    ``spans`` (an ``obs.host_spans.HostSpanRecorder``) records each
    backoff wait — purely observational.
    """
    from paxos_tpu.obs.host_spans import ensure_recorder

    sp = ensure_recorder(spans)
    if jitter_seed is None:
        import os

        jitter_seed = os.getpid()
    stream = jitter_stream(jitter_seed)
    schedule = retry_schedule(retries, backoff_s, cap_s)
    for attempt in range(retries + 1):
        try:
            return run_fn(), attempt
        except retry_on as e:
            if attempt >= retries:
                raise
            delay = schedule[attempt]
            sleep = equal_jitter(delay, stream)
            first_line = (str(e).splitlines() or [""])[0][:120]
            say(f"{describe} (attempt {attempt + 1}/{retries + 1}): "
                f"{first_line}; retrying in {sleep:.1f}s")
            with sp.span("retry_backoff", attempt=attempt + 1,
                         sleep_s=round(sleep, 3)):
                time.sleep(sleep)
    raise AssertionError("unreachable")
