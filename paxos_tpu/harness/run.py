"""The scan driver — bootstrap, round loop, and metric readback.

Reference parity (SURVEY.md §4.1): the reference's bootstrap (CLI → backend
init → node creation → spawn roles → run proposer → print decision) becomes:
build config → init state pytree → sample fault plan → `lax.scan` the
protocol step over chunks of ticks → read back reduced metrics.  The only
host↔device crossings are at chunk boundaries (SURVEY.md §8.4.5).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from paxos_tpu.core.state import DONE, PaxosState
from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.harness.config import SimConfig


class MeasurementCorrupted(RuntimeError):
    """A campaign's measurements stopped being trustworthy (e.g. packed
    ballots overflowed): distinct from infrastructure RuntimeErrors so CLI
    handlers can convert THIS to a clean failure without masking device or
    compiler errors."""


def get_step_fn(protocol: str) -> Callable:
    """Resolve a protocol name to its step function (shared signature)."""
    if protocol == "paxos":
        from paxos_tpu.protocols.paxos import paxos_step

        return paxos_step
    if protocol == "multipaxos":
        from paxos_tpu.protocols.multipaxos import multipaxos_step

        return multipaxos_step
    if protocol == "fastpaxos":
        from paxos_tpu.protocols.fastpaxos import fastpaxos_step

        return fastpaxos_step
    if protocol == "raftcore":
        from paxos_tpu.protocols.raftcore import raftcore_step

        return raftcore_step
    raise ValueError(f"unknown protocol: {protocol!r}")


def init_state(cfg: SimConfig):
    state = _init_protocol_state(cfg)
    if cfg.telemetry.enabled():
        from paxos_tpu.core.telemetry import TelemetryState

        state = state.replace(
            telemetry=TelemetryState.init(cfg.n_inst, cfg.telemetry)
        )
    return state


def _init_protocol_state(cfg: SimConfig):
    stale = cfg.fault.stale_k > 0  # allocate stale-snapshot shadow arrays
    if cfg.protocol == "multipaxos":
        from paxos_tpu.core.ballot import MAX_PROPOSERS
        from paxos_tpu.core.mp_state import BV_SHIFT, MultiPaxosState

        # Packed-pair bit budget (core.mp_state): command payloads are
        # own_slot_value(pid, base + slot) <= MAX_PROPOSERS*1000 + log_total
        # and must fit the value field, else pack_bv would bleed value bits
        # into the ballot and the agreement oracle would compare corrupted
        # pairs.  Fail at config time, not via silent corruption.
        max_val = MAX_PROPOSERS * 1000 + max(cfg.fault.log_total, cfg.log_len)
        if max_val >= (1 << BV_SHIFT):
            raise ValueError(
                f"log_total={cfg.fault.log_total} overflows the packed "
                f"(ballot, value) layout: own_slot_value can reach "
                f"{max_val} >= 2^{BV_SHIFT}; keep log_total <= "
                f"{(1 << BV_SHIFT) - MAX_PROPOSERS * 1000 - 1}"
            )
        return MultiPaxosState.init(
            cfg.n_inst,
            cfg.n_prop,
            cfg.n_acc,
            cfg.log_len,
            k=cfg.k_slots,
            lease_init=cfg.fault.lease_len,
            stale=stale,
        )
    if cfg.protocol == "fastpaxos":
        from paxos_tpu.core.fp_state import FastPaxosState

        return FastPaxosState.init(
            cfg.n_inst, cfg.n_prop, cfg.n_acc, cfg.k_slots, stale=stale
        )
    if cfg.protocol == "raftcore":
        from paxos_tpu.core.raft_state import RaftState

        return RaftState.init(
            cfg.n_inst, cfg.n_prop, cfg.n_acc, cfg.k_slots, stale=stale
        )
    return PaxosState.init(
        cfg.n_inst, cfg.n_prop, cfg.n_acc, cfg.k_slots, stale=stale
    )


def init_plan(cfg: SimConfig) -> FaultPlan:
    key = jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 1)
    return FaultPlan.sample(key, cfg.fault, cfg.n_inst, cfg.n_acc, cfg.n_prop)


def base_key(cfg: SimConfig) -> jax.Array:
    return jax.random.fold_in(jax.random.PRNGKey(cfg.seed), 0)


@functools.partial(
    jax.jit, static_argnames=("fault", "n_ticks", "step_fn"), donate_argnums=(0,)
)
def run_chunk(
    state: PaxosState,
    key: jax.Array,
    plan: FaultPlan,
    fault: FaultConfig,
    n_ticks: int,
    step_fn: Callable,
) -> PaxosState:
    """Advance ``n_ticks`` scheduler ticks fully on-device."""

    def body(s, _):
        return step_fn(s, key, plan, fault), None

    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


# Long-log variants: the chunk and the decided-prefix compaction trace into
# ONE module-level jitted computation — plan/key stay traced arguments, so
# every shrink probe, soak seed, and recheck hits the same compile cache
# (a per-call jit closure here caused a full retrace per probe).


@functools.partial(
    jax.jit, static_argnames=("fault", "n_ticks", "step_fn"), donate_argnums=(0,)
)
def run_chunk_compact(state, key, plan, fault, n_ticks, step_fn):
    from paxos_tpu.protocols.multipaxos import compact_mp_body

    def body(s, _):
        return step_fn(s, key, plan, fault), None

    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return compact_mp_body(state)[0]


@functools.partial(
    jax.jit,
    static_argnames=("fault", "n_ticks", "protocol", "block", "interpret"),
    donate_argnums=(0,),
)
def fused_chunk_compact(state, seed, plan, fault, n_ticks, protocol, block, interpret):
    from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS
    from paxos_tpu.protocols.multipaxos import compact_mp_body

    state = FUSED_CHUNKS[protocol](
        state, seed, plan, fault, n_ticks, block=block, interpret=interpret
    )
    return compact_mp_body(state)[0]


def make_advance(
    cfg: SimConfig,
    plan: FaultPlan,
    engine: str = "xla",
    block: "int | None" = None,
    interpret: "bool | None" = None,
    compact: bool = False,
    mesh=None,
) -> Callable:
    """Build ``advance(state, n_ticks)`` for an engine — THE engine dispatch.

    Every execution path (:func:`run`, the shrinker's replay, the CLI —
    sharded or not) goes through here so the (seed, stream) wiring cannot
    desynchronize between the engine that observes a violation and the one
    that replays it.

    ``"xla"`` scans the protocol step with ``jax.random`` masks; ``"fused"``
    runs whole chunks in one Pallas kernel with counter-PRNG masks
    (``kernels/fused_tick``).  ``block`` overrides the fused block size
    (stream-relevant: streams are keyed per (seed, tick, block)).
    ``interpret=None`` auto-enables the Pallas TPU interpreter off-TPU,
    which replays the fused stream bit-identically (tests/test_fused.py).

    ``compact=True`` (long-log Multi-Paxos) appends decided-prefix
    compaction to every chunk, traced into the same module-level jitted
    computation — the compaction cadence is the chunk cadence.

    ``mesh`` (a ``jax.sharding.Mesh`` over already-sharded state/plan)
    selects the multi-chip fused path: one kernel per shard under
    ``shard_map`` with globally-offset streams
    (``fused_chunk_sharded``), compaction composed between chunks.  The
    XLA engine needs no mesh plumbing — sharded inputs alone drive pjit.
    """
    if engine == "fused":
        from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS, fused_fns

        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"

        if mesh is not None:
            from paxos_tpu.kernels.fused_tick import fused_chunk_sharded

            apply_fn, mask_fn, dblk = fused_fns(cfg.protocol)

            def advance_sharded(state, n):
                return fused_chunk_sharded(
                    state, jnp.int32(cfg.seed), plan, cfg.fault, n,
                    apply_fn, mask_fn, mesh, block=block,
                    interpret=interpret, default=dblk,
                )

            if compact:
                from paxos_tpu.protocols.multipaxos import compact_mp

                def advance(state, n):
                    return compact_mp(advance_sharded(state, n))[0]

                return advance
            return advance_sharded

        if compact:
            # block=None flows through: FUSED_CHUNKS resolves the protocol
            # default (fused_fns) silently; explicit blocks warn on degrade.
            def advance(state, n):
                return fused_chunk_compact(
                    state, jnp.int32(cfg.seed), plan, cfg.fault, n,
                    cfg.protocol, block, interpret,
                )

            return advance
        fused = FUSED_CHUNKS[cfg.protocol]

        def advance(state, n):
            return fused(
                state, jnp.int32(cfg.seed), plan, cfg.fault, n,
                block=block, interpret=interpret,
            )

        return advance
    if engine == "xla":
        step_fn = get_step_fn(cfg.protocol)
        key = base_key(cfg)
        chunk_fn = run_chunk_compact if compact else run_chunk

        def advance(state, n):
            return chunk_fn(state, key, plan, cfg.fault, n, step_fn)

        return advance
    raise ValueError(f"unknown engine: {engine!r}")


class LongLog:
    """Chunk-boundary orchestration for long-log Multi-Paxos (SURVEY §6.7).

    The ONE owner of the terminate/report protocol shared by :func:`run`,
    the CLI loop, the bench, and the shrinker: decided prefixes compact
    out of the window after every chunk (``make_advance(compact=True)`` —
    traced into the chunk's own jitted computation so the module-level
    compile caches cover every probe and seed), a run is done when every
    instance's ``base`` reached ``log_total``, and reports carry the
    replicated-log fields.  ``make_longlog`` returns None for non-long-log
    configs so callers can write ``if ll:`` guards.
    """

    def __init__(self, cfg: SimConfig):
        self.log_total = cfg.fault.log_total

    def done(self, state) -> bool:
        return bool((state.base >= self.log_total).all())

    def report_fields(self, state) -> dict[str, Any]:
        import numpy as np

        base = np.asarray(jax.device_get(state.base))
        return {
            "log_total": self.log_total,
            "slots_replicated": int(base.sum()),  # compacted = decided
            "replicated_frac": float((base >= self.log_total).mean()),
        }


def make_longlog(cfg: SimConfig) -> "LongLog | None":
    if cfg.protocol == "multipaxos" and cfg.fault.log_total > 0:
        return LongLog(cfg)
    return None


def summarize(
    state: PaxosState, liveness: bool = False, log_total: int = 0
) -> dict[str, Any]:
    """Reduce on-device state to a host-side scalar report.

    Reductions run on-device (sharded states psum automatically under jit);
    only scalars come back to the host.  ``liveness`` appends the
    decided-by curve / latency histogram / stuck-lane count block
    (:func:`paxos_tpu.check.liveness.liveness_report`).  ``log_total > 0``
    (long-log Multi-Paxos) makes that block window-relative: compacted
    slots report as ``slots_compacted`` and never-decidable tail rows are
    masked out of the stuck count instead of misreported as livelocked.
    """
    lrn, prop = state.learner, state.proposer
    chosen = lrn.chosen  # (I,) single-decree, (L, I) multipaxos

    # Shared, shape-polymorphic fields.
    out = {
        "n_inst": chosen.shape[-1],
        "ticks": state.tick,
        "chosen_frac": chosen.mean(dtype=jnp.float32),
        "violations": lrn.violations.sum(),
        "evictions": lrn.evictions.sum(),
        "mean_choose_tick": jnp.where(
            chosen.any(),
            jnp.where(chosen, lrn.chosen_tick, 0).sum(dtype=jnp.float32)
            / jnp.maximum(chosen.sum(), 1),
            -1.0,
        ),
    }

    if chosen.ndim == 2:  # Multi-Paxos: chosen_frac is slot-level
        # Packed-pair bit budget, ballot side (core.mp_state: bal < 2^15
        # keeps bal << 16 | val non-negative so int32 compares stay
        # lexicographic).  The value side is guarded at config time in
        # init_state; ballots grow with elections, so the bound is enforced
        # on every report: an election-heavy campaign that overflowed would
        # otherwise corrupt recovery/learner compares SILENTLY.
        out["max_ballot"] = prop.bal.max()
        if log_total > 0:
            # Long-log: the window is a moving residual, so "fraction of
            # instances with a full window" reads ~0 on a HEALTHY run
            # (compacted rows left, tail rows can never decide).  Report
            # global replication progress instead: decided slot-lanes
            # (compacted prefix + in-window chosen rows that are real log
            # slots) over the whole log.
            from paxos_tpu.check.liveness import window_valid_mask

            valid = window_valid_mask(chosen.shape, state.base, log_total)
            out["decided_frac"] = (
                state.base.sum(dtype=jnp.float32)
                + (chosen & valid).sum(dtype=jnp.float32)
            ) / (chosen.shape[-1] * log_total)
        else:
            out["decided_frac"] = chosen.all(axis=0).mean(dtype=jnp.float32)
        out["proposer_disagree"] = jnp.zeros((), jnp.int32)  # n/a: leaders adopt
    else:
        out["decided_frac"] = (prop.phase == DONE).any(axis=0).mean(dtype=jnp.float32)
        # A proposer that believes it decided v while the learner chose v' != v
        # is a cross-role disagreement — counted as a safety signal.
        out["proposer_disagree"] = (
            (prop.phase == DONE)
            & chosen[None]
            & (prop.decided_val != lrn.chosen_val[None])
        ).any(axis=0).sum()

    out = {
        k: (v.item() if hasattr(v, "item") else v)
        for k, v in jax.device_get(out).items()
    }
    if "max_ballot" in out:
        from paxos_tpu.core.mp_state import BV_SHIFT

        bal_bits = 31 - BV_SHIFT  # sign bit must stay clear after bal << 16
        if out.pop("max_ballot") >= (1 << bal_bits):
            raise MeasurementCorrupted(
                "Multi-Paxos ballot overflowed the packed (ballot, value) "
                f"layout (bal >= 2^{bal_bits}): recovery/learner compares "
                "are no longer trustworthy for this campaign; shorten "
                "ticks_per_seed or raise lease_len (ADVICE r4)"
            )
    if state.telemetry is not None:
        from paxos_tpu.core.telemetry import telemetry_report

        # One readback per report (chunk cadence), host-side dict of totals.
        out["telemetry"] = telemetry_report(state.telemetry)
    if liveness:
        from paxos_tpu.check.liveness import liveness_report

        out.update(liveness_report(
            lrn, out["ticks"],
            base=getattr(state, "base", None), log_total=log_total,
        ))
    return out


def run(
    cfg: SimConfig,
    total_ticks: int = 64,
    chunk: int = 64,  # matches CLI run/soak/shrink: cadence-exact for long logs
    until_all_chosen: bool = False,
    max_ticks: int = 4096,
    return_state: bool = False,
    engine: str = "xla",
    liveness: bool = False,
):
    """Host loop: init, scan chunks, return the final report.

    With ``until_all_chosen`` the loop keeps scanning chunks until every
    instance's learner chose a value (or ``max_ticks``), the batch analog of
    the reference master's "wait for the decision, then print it".

    ``engine`` selects the execution path via :func:`make_advance`: ``"xla"``
    scans the step function (any protocol, any platform); ``"fused"`` runs
    the whole chunk inside one Pallas kernel with state resident in VMEM
    (any protocol; ~3-4x faster on TPU, interpreted — slowly, bit-
    identically — elsewhere; see ``kernels/fused_tick``).
    """
    state = init_state(cfg)
    plan = init_plan(cfg)
    # Long-log Multi-Paxos (SURVEY.md §6.7): decided prefixes compact out of
    # the window at every chunk boundary (traced into the chunk's dispatch),
    # so HBM stays O(window) while the log grows to cfg.fault.log_total.
    ll = make_longlog(cfg)
    advance = make_advance(cfg, plan, engine, compact=bool(ll))

    budget = max_ticks if until_all_chosen else total_ticks
    done = 0
    while done < budget:
        n = min(chunk, budget - done)
        state = advance(state, n)
        done += n
        if until_all_chosen:
            if ll:
                if ll.done(state):
                    break
            elif state.learner.chosen.all().item():
                break
    report = summarize(state, liveness=liveness, log_total=cfg.fault.log_total)
    report["config_fingerprint"] = cfg.fingerprint()
    report["engine"] = engine
    if ll:
        report.update(ll.report_fields(state))
    if return_state:
        return report, state
    return report
