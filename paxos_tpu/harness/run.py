"""The scan driver — bootstrap, round loop, and metric readback.

Reference parity (SURVEY.md §4.1): the reference's bootstrap (CLI → backend
init → node creation → spawn roles → run proposer → print decision) becomes:
build config → init state pytree → sample fault plan → `lax.scan` the
protocol step over chunks of ticks → read back reduced metrics.  The only
host↔device crossings are at *dispatch* boundaries (SURVEY.md §8.4.5): the
dispatch pipeline (``harness.pipeline``) groups up to ``pipeline_depth``
chunks per dispatch and termination probes fetch a tiny on-device done-flag
scalar asynchronously, so the big state pytree never round-trips mid-run
and a full report costs exactly one ``jax.device_get`` of one composite
pytree (:func:`summarize_device` / :func:`summarize_host`).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp

from paxos_tpu.core import streams as streams_mod
from paxos_tpu.core.state import DONE, PaxosState
from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.kernels.quorum import lane_reduce


class MeasurementCorrupted(RuntimeError):
    """A campaign's measurements stopped being trustworthy (e.g. packed
    ballots overflowed): distinct from infrastructure RuntimeErrors so CLI
    handlers can convert THIS to a clean failure without masking device or
    compiler errors."""


def get_step_fn(protocol: str) -> Callable:
    """Resolve a protocol name to its step function (shared signature)."""
    if protocol == "paxos":
        from paxos_tpu.protocols.paxos import paxos_step

        return paxos_step
    if protocol == "multipaxos":
        from paxos_tpu.protocols.multipaxos import multipaxos_step

        return multipaxos_step
    if protocol == "fastpaxos":
        from paxos_tpu.protocols.fastpaxos import fastpaxos_step

        return fastpaxos_step
    if protocol == "raftcore":
        from paxos_tpu.protocols.raftcore import raftcore_step

        return raftcore_step
    if protocol == "synchpaxos":
        from paxos_tpu.protocols.synchpaxos import synchpaxos_step

        return synchpaxos_step
    raise ValueError(f"unknown protocol: {protocol!r}")


def init_state(cfg: SimConfig):
    state = _init_protocol_state(cfg)
    if cfg.telemetry.enabled():
        from paxos_tpu.core.telemetry import TelemetryState

        state = state.replace(
            telemetry=TelemetryState.init(cfg.n_inst, cfg.telemetry)
        )
    if cfg.coverage.enabled():
        from paxos_tpu.obs.coverage import CoverageState

        state = state.replace(
            coverage=CoverageState.init(cfg.n_inst, cfg.coverage)
        )
    if cfg.exposure.enabled():
        from paxos_tpu.obs.exposure import FaultExposure

        state = state.replace(exposure=FaultExposure.init(cfg.n_inst))
    if cfg.margin.enabled():
        from paxos_tpu.obs.margin import MarginState

        state = state.replace(margin=MarginState.init(cfg.n_inst))
    if cfg.workload.enabled():
        from paxos_tpu.workload.generator import WloadState

        state = state.replace(
            wload=WloadState.init(
                cfg.n_inst, cfg.n_prop, cfg.workload, cfg.seed
            )
        )
    return state


def _check_packed_layout_bounds(cfg: SimConfig) -> None:
    """Config-time guards for the packed lane-state field widths.

    The fused engine stores lane state in the bit-packed layout tables
    (core/*_state.py, utils/bitops): values in 12/13-bit fields, retry
    timers in 13-bit signed (single-decree) / 12-bit unsigned (Multi-Paxos
    candidate) fields, and Multi-Paxos ``commit_idx`` in 6 bits.  A config
    that can exceed those bounds must fail HERE, not via silent wraparound
    inside a kernel (ballots are guarded at report time via ``max_ballot``
    — they grow with the schedule, not the config).
    """
    f = cfg.fault
    if f.timeout + max(f.timeout_skew, 0) >= 4095:
        raise ValueError(
            f"timeout={f.timeout} + timeout_skew={f.timeout_skew} overflows "
            "the packed 13-bit proposer timer (core/*_state layout tables); "
            "keep timeout + skew < 4095"
        )
    if f.backoff_max * max(f.backoff_skew, 1) > 2048:
        raise ValueError(
            f"backoff_max={f.backoff_max} * backoff_skew={f.backoff_skew} "
            "overflows the packed 13-bit signed proposer timer "
            "(core/*_state layout tables); keep the product <= 2048"
        )
    if cfg.protocol == "multipaxos" and cfg.log_len >= 64:
        raise ValueError(
            f"log_len={cfg.log_len} overflows the packed 6-bit commit_idx "
            "field (core/mp_state.MP_LAYOUT); keep the window < 64 slots"
        )


def check_tick_budget(protocol: str, ticks: int) -> None:
    """Ticks-per-campaign bound for the packed ``learner.chosen_tick`` field.

    ``chosen_tick`` records the global tick of first choice, so it grows to
    the campaign's tick budget — a run longer than the field's signed
    capacity (18-bit Multi-Paxos: 131071; 19-bit single-decree: 262143)
    would wrap it NEGATIVE on the fused engine, corrupting latency
    histograms and ``mean_choose_tick`` silently.  Enforced where the tick
    budget is accepted (:func:`run`, ``soak``) for both engines, like the
    other packed-layout bounds: config acceptance must not depend on the
    engine, or a campaign could pass on XLA and be unreplayable fused.
    """
    from paxos_tpu.utils.bitops import layout_field_width

    bits, signed = layout_field_width(protocol, "learner.chosen_tick")
    cap = (1 << (bits - 1)) - 1 if signed else (1 << bits) - 1
    if ticks > cap:
        raise ValueError(
            f"tick budget {ticks} overflows the packed {bits}-bit "
            f"learner.chosen_tick field for {protocol} (core layout tables); "
            f"keep ticks per campaign <= {cap}"
        )


def _init_protocol_state(cfg: SimConfig):
    stale = cfg.fault.stale_k > 0  # allocate stale-snapshot shadow arrays
    delay = cfg.fault.p_delay > 0.0  # allocate bounded-delay `until` stamps
    _check_packed_layout_bounds(cfg)
    if cfg.protocol == "multipaxos":
        from paxos_tpu.core.ballot import MAX_PROPOSERS
        from paxos_tpu.core.mp_state import BV_SHIFT, MultiPaxosState

        # Packed-pair bit budget (core.mp_state): command payloads are
        # own_slot_value(pid, base + slot) <= MAX_PROPOSERS*1000 + log_total
        # and must fit the value field, else pack_bv would bleed value bits
        # into the ballot and the agreement oracle would compare corrupted
        # pairs.  Fail at config time, not via silent corruption.
        max_val = MAX_PROPOSERS * 1000 + max(cfg.fault.log_total, cfg.log_len)
        if max_val >= (1 << BV_SHIFT):
            raise ValueError(
                f"log_total={cfg.fault.log_total} overflows the packed "
                f"(ballot, value) layout: own_slot_value can reach "
                f"{max_val} >= 2^{BV_SHIFT}; keep log_total <= "
                f"{(1 << BV_SHIFT) - MAX_PROPOSERS * 1000 - 1}"
            )
        # Tighter, lane-packed budget (core.mp_state.MP_LAYOUT): values ride
        # 13-bit fields in the fused engine's packed words.  Keyed to the
        # CONFIGURED proposer count — 8-proposer long logs genuinely overflow
        # 13 bits and must be rejected; the default 2-proposer configs don't.
        max_val = cfg.n_prop * 1000 + max(cfg.fault.log_total, cfg.log_len)
        if max_val >= (1 << 13):
            raise ValueError(
                f"n_prop={cfg.n_prop} with log_total={cfg.fault.log_total} "
                f"overflows the packed 13-bit value field "
                f"(core/mp_state.MP_LAYOUT): own_slot_value can reach "
                f"{max_val} >= 2^13; shrink the log or the proposer count"
            )
        return MultiPaxosState.init(
            cfg.n_inst,
            cfg.n_prop,
            cfg.n_acc,
            cfg.log_len,
            k=cfg.k_slots,
            lease_init=cfg.fault.lease_len,
            stale=stale,
            delay=delay,
        )
    if cfg.protocol == "fastpaxos":
        from paxos_tpu.core.fp_state import FastPaxosState

        return FastPaxosState.init(
            cfg.n_inst, cfg.n_prop, cfg.n_acc, cfg.k_slots, stale=stale,
            delay=delay,
        )
    if cfg.protocol == "raftcore":
        from paxos_tpu.core.raft_state import RaftState

        return RaftState.init(
            cfg.n_inst, cfg.n_prop, cfg.n_acc, cfg.k_slots, stale=stale,
            delay=delay,
        )
    if cfg.protocol == "synchpaxos":
        from paxos_tpu.core.sp_state import SynchPaxosState

        return SynchPaxosState.init(
            cfg.n_inst, cfg.n_prop, cfg.n_acc, cfg.k_slots, stale=stale,
            delay=delay,
        )
    return PaxosState.init(
        cfg.n_inst, cfg.n_prop, cfg.n_acc, cfg.k_slots, stale=stale,
        delay=delay,
    )


def init_plan(cfg: SimConfig) -> FaultPlan:
    key = streams_mod.root_plan_key(cfg.seed)
    return FaultPlan.sample(key, cfg.fault, cfg.n_inst, cfg.n_acc, cfg.n_prop)


def base_key(cfg: SimConfig) -> jax.Array:
    return streams_mod.root_step_key(cfg.seed)


@functools.partial(
    jax.jit, static_argnames=("fault", "n_ticks", "step_fn"), donate_argnums=(0,)
)
def run_chunk(
    state: PaxosState,
    key: jax.Array,
    plan: FaultPlan,
    fault: FaultConfig,
    n_ticks: int,
    step_fn: Callable,
) -> PaxosState:
    """Advance ``n_ticks`` scheduler ticks fully on-device."""

    def body(s, _):
        return step_fn(s, key, plan, fault), None

    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return state


# Long-log variants: the chunk and the decided-prefix compaction trace into
# ONE module-level jitted computation — plan/key stay traced arguments, so
# every shrink probe, soak seed, and recheck hits the same compile cache
# (a per-call jit closure here caused a full retrace per probe).


@functools.partial(
    jax.jit, static_argnames=("fault", "n_ticks", "step_fn"), donate_argnums=(0,)
)
def run_chunk_compact(state, key, plan, fault, n_ticks, step_fn):
    from paxos_tpu.protocols.multipaxos import compact_mp_body

    def body(s, _):
        return step_fn(s, key, plan, fault), None

    state, _ = jax.lax.scan(body, state, None, length=n_ticks)
    return compact_mp_body(state)[0]


@functools.partial(
    jax.jit,
    static_argnames=("fault", "n_ticks", "protocol", "block", "interpret"),
    donate_argnums=(0,),
)
def fused_chunk_compact(state, seed, plan, fault, n_ticks, protocol, block, interpret):
    from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS
    from paxos_tpu.protocols.multipaxos import compact_mp_body

    state = FUSED_CHUNKS[protocol](
        state, seed, plan, fault, n_ticks, block=block, interpret=interpret
    )
    return compact_mp_body(state)[0]


# Grouped variants (dispatch pipeline, harness.pipeline): ``groups`` chunk
# bodies — each with its decided-prefix compaction — trace into ONE jitted
# dispatch via an outer scan, so the per-dispatch host/tunnel cost is paid
# once per group while the compaction cadence stays the chunk cadence.
# Streams are bit-identical to the serial loop by construction: per-tick
# PRNG derives from state.tick (xla: fold_in(key, tick); fused: counter-PRNG
# keyed per (seed, tick, block)), never from dispatch boundaries
# (tests/test_pipeline.py pins this on both engines).


@functools.partial(
    jax.jit,
    static_argnames=("fault", "n_ticks", "step_fn", "groups"),
    donate_argnums=(0,),
)
def run_chunk_compact_grouped(state, key, plan, fault, n_ticks, step_fn, groups):
    from paxos_tpu.protocols.multipaxos import compact_mp_body

    def outer(s, _):
        def body(si, __):
            return step_fn(si, key, plan, fault), None

        s, _ = jax.lax.scan(body, s, None, length=n_ticks)
        return compact_mp_body(s)[0], None

    state, _ = jax.lax.scan(outer, state, None, length=groups)
    return state


@functools.partial(
    jax.jit,
    static_argnames=(
        "fault", "n_ticks", "protocol", "block", "interpret", "groups"
    ),
    donate_argnums=(0,),
)
def fused_chunk_compact_grouped(
    state, seed, plan, fault, n_ticks, protocol, block, interpret, groups
):
    from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS
    from paxos_tpu.protocols.multipaxos import compact_mp_body

    fused = FUSED_CHUNKS[protocol]

    def outer(s, _):
        s = fused(s, seed, plan, fault, n_ticks, block=block, interpret=interpret)
        return compact_mp_body(s)[0], None

    state, _ = jax.lax.scan(outer, state, None, length=groups)
    return state


def make_advance(
    cfg: SimConfig,
    plan: FaultPlan,
    engine: str = "xla",
    block: "int | None" = None,
    interpret: "bool | None" = None,
    compact: bool = False,
    mesh=None,
) -> Callable:
    """Build ``advance(state, n_ticks)`` for an engine — THE engine dispatch.

    Every execution path (:func:`run`, the shrinker's replay, the CLI —
    sharded or not) goes through here so the (seed, stream) wiring cannot
    desynchronize between the engine that observes a violation and the one
    that replays it.

    ``"xla"`` scans the protocol step with ``jax.random`` masks; ``"fused"``
    runs whole chunks in one Pallas kernel with counter-PRNG masks
    (``kernels/fused_tick``).  ``block`` overrides the fused block size
    (stream-relevant: streams are keyed per (seed, tick, block)).
    ``interpret=None`` auto-enables the Pallas TPU interpreter off-TPU,
    which replays the fused stream bit-identically (tests/test_fused.py).

    ``compact=True`` (long-log Multi-Paxos) appends decided-prefix
    compaction to every chunk, traced into the same module-level jitted
    computation — the compaction cadence is the chunk cadence.

    ``mesh`` (a ``jax.sharding.Mesh`` over already-sharded state/plan)
    selects the multi-chip fused path: one kernel per shard under
    ``shard_map`` with globally-offset streams
    (``fused_chunk_sharded``), compaction composed between chunks.  The
    XLA engine needs no mesh plumbing — sharded inputs alone drive pjit.
    """
    if engine == "fused":
        from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS

        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"

        if mesh is not None:
            from paxos_tpu.kernels.fused_tick import (
                _saturate_ballots, ballot_hoist_safe_ticks,
                fused_chunk_sharded, packed_fns,
            )
            from paxos_tpu.utils import bitops

            def advance_sharded(state, n):
                # Pack/unpack at the chunk boundary, like FUSED_CHUNKS:
                # both are elementwise or non-I-axis ops, so the instance
                # sharding propagates through them under pjit unchanged.
                # Same ballot-clamp hoist guard as _make_chunk: boundary
                # clamps when the chunk fits the packed headroom, per-tick
                # clamp otherwise.
                codec = bitops.codec_for(cfg.protocol, state)
                hoisted = n <= ballot_hoist_safe_ticks(cfg.protocol, codec)
                apply_fn, mask_fn, dblk = packed_fns(
                    cfg.protocol, clamp_per_tick=not hoisted
                )
                pst = bitops.pack_state(codec, _saturate_ballots(codec, state))
                pst = fused_chunk_sharded(
                    pst, jnp.int32(cfg.seed), plan, cfg.fault, n,
                    apply_fn, mask_fn, mesh, block=block,
                    interpret=interpret, default=dblk,
                )
                out = bitops.unpack_state(codec, pst)
                return _saturate_ballots(codec, out) if hoisted else out

            if compact:
                from paxos_tpu.protocols.multipaxos import compact_mp

                def advance(state, n):
                    return compact_mp(advance_sharded(state, n))[0]

                return advance
            return advance_sharded

        if compact:
            # block=None flows through: FUSED_CHUNKS resolves the protocol
            # default (fused_fns) silently; explicit blocks warn on degrade.
            def advance(state, n):
                return fused_chunk_compact(
                    state, jnp.int32(cfg.seed), plan, cfg.fault, n,
                    cfg.protocol, block, interpret,
                )

            return advance
        fused = FUSED_CHUNKS[cfg.protocol]

        def advance(state, n):
            return fused(
                state, jnp.int32(cfg.seed), plan, cfg.fault, n,
                block=block, interpret=interpret,
            )

        return advance
    if engine == "xla":
        step_fn = get_step_fn(cfg.protocol)
        key = base_key(cfg)
        chunk_fn = run_chunk_compact if compact else run_chunk

        def advance(state, n):
            return chunk_fn(state, key, plan, cfg.fault, n, step_fn)

        return advance
    raise ValueError(f"unknown engine: {engine!r}")


def make_advance_grouped(
    cfg: SimConfig,
    plan: FaultPlan,
    engine: str = "xla",
    block: "int | None" = None,
    interpret: "bool | None" = None,
    compact: bool = False,
) -> Callable:
    """Build ``advance(state, n_ticks, groups)`` — the pipelined dispatch.

    ``groups`` chunk bodies execute in ONE device dispatch
    (``harness.pipeline.pipelined_run`` drives the grouping).  Non-compact
    engines group by simply scanning ``n_ticks * groups`` ticks — ticks are
    chunk-invariant, so at groups=16 x chunk 64 the dispatched program IS
    the chunk-1024 program.  Compact (long-log) engines use the grouped
    jits above so the compaction cadence stays ``n_ticks`` inside the
    dispatch.  ``groups=1`` routes to the exact same module-level jit cache
    as :func:`make_advance` — the serial and pipelined loops share
    compilations and produce bit-identical streams.

    The sharded (mesh) path stays ungrouped: sharded compaction composes
    between dispatches on the host (:func:`make_advance`), so the CLI caps
    the pipeline depth at 1 under ``--shard``.
    """
    if engine == "fused":
        from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS

        if interpret is None:
            interpret = jax.devices()[0].platform != "tpu"

        if compact:
            def advance(state, n, g=1):
                if g == 1:
                    return fused_chunk_compact(
                        state, jnp.int32(cfg.seed), plan, cfg.fault, n,
                        cfg.protocol, block, interpret,
                    )
                return fused_chunk_compact_grouped(
                    state, jnp.int32(cfg.seed), plan, cfg.fault, n,
                    cfg.protocol, block, interpret, g,
                )

            return advance
        fused = FUSED_CHUNKS[cfg.protocol]

        def advance(state, n, g=1):
            return fused(
                state, jnp.int32(cfg.seed), plan, cfg.fault, n * g,
                block=block, interpret=interpret,
            )

        return advance
    if engine == "xla":
        step_fn = get_step_fn(cfg.protocol)
        key = base_key(cfg)

        if compact:
            def advance(state, n, g=1):
                if g == 1:
                    return run_chunk_compact(
                        state, key, plan, cfg.fault, n, step_fn
                    )
                return run_chunk_compact_grouped(
                    state, key, plan, cfg.fault, n, step_fn, g
                )

            return advance

        def advance(state, n, g=1):
            return run_chunk(state, key, plan, cfg.fault, n * g, step_fn)

        return advance
    raise ValueError(f"unknown engine: {engine!r}")


# On-device termination probes (dispatch pipeline): each returns a 0-d bool
# array — the ONLY thing that crosses to the host mid-run.  Jitted so the
# reduction fuses into one tiny program instead of eager per-op dispatches.


@jax.jit
def _all_true(x):
    return x.all()


def all_chosen_flag(state) -> jax.Array:
    """0-d bool device scalar: every lane's learner chose a value."""
    return _all_true(state.learner.chosen)


@functools.partial(jax.jit, static_argnames=("log_total",))
def _base_done(base, log_total):
    return (base >= log_total).all()


class LongLog:
    """Chunk-boundary orchestration for long-log Multi-Paxos (SURVEY §6.7).

    The ONE owner of the terminate/report protocol shared by :func:`run`,
    the CLI loop, the bench, and the shrinker: decided prefixes compact
    out of the window after every chunk (``make_advance(compact=True)`` —
    traced into the chunk's own jitted computation so the module-level
    compile caches cover every probe and seed), a run is done when every
    instance's ``base`` reached ``log_total``, and reports carry the
    replicated-log fields (:func:`summarize` folds them in).
    ``make_longlog`` returns None for non-long-log configs so callers can
    write ``if ll:`` guards.
    """

    def __init__(self, cfg: SimConfig):
        self.log_total = cfg.fault.log_total

    def done_flag(self, state) -> jax.Array:
        """0-d bool device scalar: every instance replicated the whole log."""
        return _base_done(state.base, self.log_total)

    def done(self, state) -> bool:
        return bool(jax.device_get(self.done_flag(state)))


def make_longlog(cfg: SimConfig) -> "LongLog | None":
    if cfg.protocol == "multipaxos" and cfg.fault.log_total > 0:
        return LongLog(cfg)
    return None


@lane_reduce("summarize")
def summarize_device(
    state: PaxosState, liveness: bool = False, log_total: int = 0
) -> tuple[dict, dict]:
    """Device half of :func:`summarize`: one composite pytree, no transfer.

    Allowlisted cross-lane region: report reductions legitimately mix
    lanes, so the whole function carries the ``lane_reduce`` tag the
    dataflow auditor (analysis/flow.py) accepts — the per-tick step
    itself must stay lane-independent.

    Every block of the report — headline scalars, telemetry totals, the
    liveness curve/histogram/stuck block, and long-log replication progress
    — reduces on-device into ONE pytree of small arrays, so the whole
    report crosses the host boundary in a single ``jax.device_get`` (or a
    single async transfer — ``harness.pipeline.AsyncSummary``).  Returns
    ``(device_pytree, meta)``; hand the fetched pytree plus ``meta`` to
    :func:`summarize_host`.
    """
    lrn, prop = state.learner, state.proposer
    chosen = lrn.chosen  # (I,) single-decree, (L, I) multipaxos

    # Shared, shape-polymorphic fields.
    dev = {
        "ticks": state.tick,
        "chosen_frac": chosen.mean(dtype=jnp.float32),
        "violations": lrn.violations.sum(),
        "evictions": lrn.evictions.sum(),
        "mean_choose_tick": jnp.where(
            chosen.any(),
            jnp.where(chosen, lrn.chosen_tick, 0).sum(dtype=jnp.float32)
            / jnp.maximum(chosen.sum(), 1),
            -1.0,
        ),
    }
    meta = {"n_inst": chosen.shape[-1], "log_total": log_total}

    # Ballot bit budget: ballots grow with the schedule (elections/retries),
    # so the bound is enforced on every report — a campaign that overflowed
    # would otherwise corrupt compares SILENTLY.  The limit is exactly the
    # packed field CAPACITY of proposer.bal — Multi-Paxos 2^11 - 1
    # (core/mp_state.MP_LAYOUT, tighter than the 2^15 pack_bv budget that
    # keeps bal << 16 | val sign-clear), single-decree 2^15 - 1
    # (core/state.py PAXOS_LAYOUT and kin, the last value with corrupt
    # msg_bal+1 headroom in the 12/15-bit message fields) — because the
    # fused engine SATURATES ballots there instead of letting the pack mask
    # wrap them (kernels/fused_tick._saturate_ballots): an overflowed
    # campaign reads max_ballot == capacity at the chunk boundary, so this
    # guard fires on both engines at the same threshold.
    dev["max_ballot"] = prop.bal.max()
    meta["ballot_limit"] = (
        (1 << 11) - 1 if chosen.ndim == 2 else (1 << 15) - 1
    )

    if chosen.ndim == 2:  # Multi-Paxos: chosen_frac is slot-level
        if log_total > 0:
            # Long-log: the window is a moving residual, so "fraction of
            # instances with a full window" reads ~0 on a HEALTHY run
            # (compacted rows left, tail rows can never decide).  Report
            # global replication progress instead: decided slot-lanes
            # (compacted prefix + in-window chosen rows that are real log
            # slots) over the whole log.
            from paxos_tpu.check.liveness import window_valid_mask

            valid = window_valid_mask(chosen.shape, state.base, log_total)
            dev["decided_frac"] = (
                state.base.sum(dtype=jnp.float32)
                + (chosen & valid).sum(dtype=jnp.float32)
            ) / (chosen.shape[-1] * log_total)
        else:
            dev["decided_frac"] = chosen.all(axis=0).mean(dtype=jnp.float32)
        dev["proposer_disagree"] = jnp.zeros((), jnp.int32)  # n/a: leaders adopt
    else:
        dev["decided_frac"] = (prop.phase == DONE).any(axis=0).mean(dtype=jnp.float32)
        # A proposer that believes it decided v while the learner chose v' != v
        # is a cross-role disagreement — counted as a safety signal.
        dev["proposer_disagree"] = (
            (prop.phase == DONE)
            & chosen[None]
            & (prop.decided_val != lrn.chosen_val[None])
        ).any(axis=0).sum()

    base = getattr(state, "base", None)
    if log_total > 0 and base is not None:
        # Long-log replication progress (previously LongLog.report_fields,
        # a separate blocking device_get of the whole base array).
        dev["longlog"] = {
            "slots_replicated": base.sum(),  # compacted = decided
            "replicated_frac": (base >= log_total).mean(dtype=jnp.float32),
        }
    if state.telemetry is not None:
        from paxos_tpu.core.telemetry import telemetry_device

        dev["telemetry"] = telemetry_device(state.telemetry)
    if getattr(state, "coverage", None) is not None:
        from paxos_tpu.obs.coverage import coverage_device

        dev["coverage"] = coverage_device(state.coverage)
        meta["coverage_words"] = int(state.coverage.bitmap.shape[0])
    if getattr(state, "exposure", None) is not None:
        from paxos_tpu.obs.exposure import exposure_device

        dev["exposure"] = exposure_device(state.exposure)
    if getattr(state, "margin", None) is not None:
        from paxos_tpu.obs.margin import margin_device

        dev["margin"] = margin_device(state.margin)
    if getattr(state, "wload", None) is not None:
        from paxos_tpu.obs.slo import slo_device

        dev["slo"] = slo_device(state.wload)
    if liveness:
        from paxos_tpu.check.liveness import liveness_device

        dev["liveness"] = liveness_device(
            lrn, state.tick, base=base, log_total=log_total
        )
    return dev, meta


def summarize_host(host: dict, meta: dict) -> dict[str, Any]:
    """Format a ``device_get``'d :func:`summarize_device` pytree.

    Runs the Multi-Paxos ballot-overflow guard (raises
    :class:`MeasurementCorrupted`) exactly as the synchronous path always
    did — the guard is host-side policy, so async readers
    (``AsyncSummary``) inherit it for free.
    """
    out = {"n_inst": meta["n_inst"]}
    for k in ("ticks", "chosen_frac", "violations", "evictions",
              "mean_choose_tick", "decided_frac", "proposer_disagree"):
        v = host[k]
        out[k] = v.item() if hasattr(v, "item") else v
    # Checker headroom (obs.margin plane, satellite gauge): an eviction means
    # the learner table dropped a row mid-campaign, so the safety oracle may
    # have MISSED a violation — the report says so explicitly instead of
    # leaving "evictions" as an easily-skimmed count.
    out["checker_complete"] = out["evictions"] == 0
    if "max_ballot" in host:
        limit = meta.get("ballot_limit", (1 << 15) - 1)
        if int(host["max_ballot"]) >= limit:
            raise MeasurementCorrupted(
                f"ballot overflowed the packed lane-state layout (bal >= "
                f"{limit}; core/*_state layout tables): ballot compares are "
                "no longer trustworthy for this campaign; shorten "
                "ticks_per_seed or raise lease_len (ADVICE r4)"
            )
    if "longlog" in host:
        out["log_total"] = meta["log_total"]
        out["slots_replicated"] = int(host["longlog"]["slots_replicated"])
        out["replicated_frac"] = float(host["longlog"]["replicated_frac"])
    if "telemetry" in host:
        from paxos_tpu.core.telemetry import telemetry_host

        out["telemetry"] = telemetry_host(host["telemetry"])
    if "coverage" in host:
        from paxos_tpu.obs.coverage import coverage_host

        out["coverage"] = coverage_host(
            host["coverage"], meta["coverage_words"]
        )
    if "exposure" in host:
        from paxos_tpu.obs.exposure import exposure_host

        out["exposure"] = exposure_host(host["exposure"])
    if "margin" in host:
        from paxos_tpu.obs.margin import margin_host

        out["margin"] = margin_host(host["margin"])
    if "slo" in host:
        from paxos_tpu.obs.slo import slo_host

        out["slo"] = slo_host(host["slo"])
    if "liveness" in host:
        from paxos_tpu.check.liveness import liveness_host

        out.update(liveness_host(host["liveness"]))
    return out


def summarize(
    state: PaxosState, liveness: bool = False, log_total: int = 0
) -> dict[str, Any]:
    """Reduce on-device state to a host-side scalar report.

    Reductions run on-device (sharded states psum automatically under jit)
    and the whole report — scalars, telemetry, liveness, long-log
    replication — comes back in ONE ``jax.device_get`` of one composite
    pytree (:func:`summarize_device`).  ``liveness`` appends the decided-by
    curve / latency histogram / stuck-lane count block
    (:func:`paxos_tpu.check.liveness.liveness_device`).  ``log_total > 0``
    (long-log Multi-Paxos) makes that block window-relative — compacted
    slots report as ``slots_compacted`` and never-decidable tail rows are
    masked out of the stuck count instead of misreported as livelocked —
    and adds the replication-progress fields (``slots_replicated``,
    ``replicated_frac``).
    """
    dev, meta = summarize_device(state, liveness=liveness, log_total=log_total)
    return summarize_host(jax.device_get(dev), meta)


def run(
    cfg: SimConfig,
    total_ticks: int = 64,
    chunk: int = 64,  # matches CLI run/soak/shrink: cadence-exact for long logs
    until_all_chosen: bool = False,
    max_ticks: int = 4096,
    return_state: bool = False,
    engine: str = "xla",
    liveness: bool = False,
    pipeline_depth: int = 1,
    spans=None,
    plan=None,
):
    """Host loop: init, scan chunks, return the final report.

    ``plan`` overrides the seed-sampled :class:`FaultPlan` (default
    ``init_plan(cfg)``) — the replay/fuzz path: an explicit plan threads a
    mutated or deserialized schedule through the same engine dispatch, and
    for identical ``(cfg, plan)`` the device schedule is bit-identical to
    the sampled path (the plan is a traced argument, never a compile key).

    With ``until_all_chosen`` the loop keeps scanning chunks until every
    instance's learner chose a value (or ``max_ticks``), the batch analog of
    the reference master's "wait for the decision, then print it".  The
    probe is an on-device done-flag scalar fetched per dispatch
    (``harness.pipeline``) — the state pytree never round-trips mid-run.

    ``engine`` selects the execution path via :func:`make_advance_grouped`:
    ``"xla"`` scans the step function (any protocol, any platform);
    ``"fused"`` runs the whole chunk inside one Pallas kernel with state
    resident in VMEM (any protocol; ~3-4x faster on TPU, interpreted —
    slowly, bit-identically — elsewhere; see ``kernels/fused_tick``).

    ``pipeline_depth`` groups up to that many chunks per device dispatch
    (default 1 = the serial per-chunk loop).  Grouping only regroups
    dispatches — the schedule stream is bit-identical at any depth — but an
    ``until_all_chosen`` exit is probed per dispatch, so the reported
    ``ticks`` may exceed the serial exit tick by < ``depth * chunk``.

    ``spans`` (an ``obs.host_spans.HostSpanRecorder``) adds wall-clock
    spans for every dispatch/probe to a merged Perfetto trace — purely
    observational, never schedule-relevant.
    """
    from paxos_tpu.harness.config import validate_pipeline_depth
    from paxos_tpu.harness.pipeline import pipelined_run

    depth = validate_pipeline_depth(pipeline_depth)
    check_tick_budget(cfg.protocol, max_ticks if until_all_chosen else total_ticks)
    state = init_state(cfg)
    if plan is None:
        plan = init_plan(cfg)
    # Long-log Multi-Paxos (SURVEY.md §6.7): decided prefixes compact out of
    # the window at every chunk boundary (traced into the chunk's dispatch),
    # so HBM stays O(window) while the log grows to cfg.fault.log_total.
    ll = make_longlog(cfg)
    advance = make_advance_grouped(cfg, plan, engine, compact=bool(ll))

    done_fn = None
    if until_all_chosen:
        done_fn = ll.done_flag if ll else all_chosen_flag
    budget = max_ticks if until_all_chosen else total_ticks
    state, _, exit_tick = pipelined_run(
        state, advance, budget=budget, chunk=chunk, depth=depth,
        done_fn=done_fn, spans=spans,
    )
    # The summarize readback is the moment async dispatch catches up with
    # the host, so it rides in a "report" span — without it the perf plane
    # (obs.perf) would clock a fully-async loop at enqueue speed.
    from paxos_tpu.obs.host_spans import ensure_recorder

    with ensure_recorder(spans).span("report"):
        report = summarize(
            state, liveness=liveness, log_total=cfg.fault.log_total
        )
    report["config_fingerprint"] = cfg.fingerprint()
    report["engine"] = engine
    if depth > 1:
        report["pipeline_depth"] = depth
    if return_state:
        return report, state
    return report
