"""Failing-schedule shrinker — delta-debug a fault plan to a minimal repro.

Reference parity: the reference stack has nothing like this (its failures
are reproduced by re-running binaries by hand); at fuzzing scale a
violation arrives as "lane 93142 of a million tripped the checker", and the
useful artifact is the *smallest fault schedule that still trips it* — the
batch-fuzzing twin of QuickCheck/Hypothesis shrinking and of Jepsen's
history minimization.

Determinism makes shrinking exact: per-tick chaos masks depend only on
(seed, tick, array shape), so keeping the batch shape fixed and editing only
the *static plan* replays the identical schedule around the edit.  The
shrinker therefore:

1. runs the config until the checker first lights up, and picks the first
   violating lane;
2. makes every OTHER lane's plan benign (lanes are independent, so this
   never changes the victim lane's behavior — verified by re-run);
3. greedily removes the victim's fault atoms (per-acceptor equivocation
   flags, per-acceptor crash windows, per-proposer crash windows, the
   partition window) keeping each removal only if the violation survives;
4. binary-searches the smallest tick budget that still reproduces.

The result is a full-width plan with a handful of live atoms in one lane,
a tick budget, and a JSON-able atom list — directly replayable via
``replay()`` (used by the CLI ``shrink`` subcommand and the tests).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from paxos_tpu.faults.injector import (
    NEVER,
    FaultPlan,
    atom_label,
    plan_to_atoms,
)
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.run import (
    init_plan,
    init_state,
    make_advance,
    make_longlog,
    summarize,
)


@dataclasses.dataclass
class ShrinkResult:
    lane: int  # victim instance index
    ticks: int  # smallest tick budget that reproduces
    atoms: list[str]  # surviving fault atoms, e.g. "equiv[acceptor=2]"
    removed: list[str]  # atoms removed while the violation persisted
    plan: FaultPlan  # minimized full-width plan (benign outside the lane)
    engine: str = "xla"  # the stream the repro is valid under
    block: Optional[int] = None  # fused block size (None = protocol default)
    # Chunk the repro was minimized at: schedule-relevant for long-log
    # configs (compaction cadence) and the granularity of ``ticks``.
    chunk: int = 64
    # Victim lane's decoded flight-recorder trace (core.telemetry), e.g.
    # [{"tick": 3, "events": ["corrupt", "accept"]}, ...] — so a shrunk
    # repro ships with a human-readable event history, not just atoms.
    timeline: Optional[list] = None
    # Round spans reconstructed from the timeline (obs.spans): per ballot
    # attempt, open/close ticks, outcome, and fault annotations — the
    # causal reading of the raw timeline.
    spans: Optional[list] = None
    # Victim-lane fault-exposure annotation (obs.exposure): per-class
    # injected/effective counts over the repro plus, per surviving atom,
    # whether its fault class actually touched the protocol — a kept atom
    # with zero effective events earned its keep through schedule timing
    # (occupying a PRNG draw), not through the fault itself.
    exposure: Optional[dict] = None
    # Victim-lane safety-margin annotation (obs.margin): the tightest
    # distance-to-violation the repro reached in its lane (quorum slack 0
    # on a violating repro, by construction) — tells the reader how close
    # the MINIMIZED schedule runs to the edge, not just that it crosses it.
    margin: Optional[dict] = None

    def to_json(self) -> dict[str, Any]:
        out = {
            "lane": self.lane,
            "ticks": self.ticks,
            "atoms": self.atoms,
            "removed": self.removed,
            "engine": self.engine,
            "block": self.block,
            "chunk": self.chunk,
        }
        if self.timeline is not None:
            out["timeline"] = self.timeline
        if self.spans is not None:
            out["spans"] = [s.to_json() for s in self.spans]
        if self.exposure is not None:
            out["exposure"] = self.exposure
        if self.margin is not None:
            out["margin"] = self.margin
        # The minimized plan itself, in the shared atom codec
        # (faults.injector.plan_to_atoms): a shrunk repro is replayable
        # from its JSON alone via atoms_to_plan + _violations_at.
        out["plan_atoms"] = plan_to_atoms(self.plan)
        return out


def _violations_at(
    cfg: SimConfig,
    plan: FaultPlan,
    ticks: int,
    chunk: int,
    engine: str = "xla",
    block: Optional[int] = None,
):
    """(I,) violations vector after ``ticks`` (fresh state, same key stream).

    ``engine`` must match the engine that OBSERVED the violation: the XLA
    engine draws masks from the jax.random stream while the fused engine
    draws from the counter PRNG keyed by (seed, tick, block), so the two
    explore different schedules for the same seed.  A fused-soak seed only
    reproduces under the fused stream at the SAME block size — pass
    ``block`` when the observing run's block differed from the protocol
    default (e.g. a sharded run whose per-shard block was clamped).
    Off-TPU the fused stream is replayed under the Pallas TPU interpreter,
    which is bit-identical to the compiled kernel (tests/test_fused.py).

    Long-log configs additionally compact at chunk boundaries — the
    compaction CADENCE is schedule-relevant (it decides when in-flight
    messages for compacted slots drop), so ``chunk`` must also match the
    observing run's chunk for an exact replay.
    """
    state = init_state(cfg)
    advance = make_advance(
        cfg, plan, engine, block=block, compact=bool(make_longlog(cfg))
    )
    done = 0
    while done < ticks:
        n = min(chunk, ticks - done)
        state = advance(state, n)
        done += n
    # Measurement audit: summarize runs the packed-ballot overflow guard —
    # minimizing against post-overflow violation bits would "shrink" noise
    # (MeasurementCorrupted propagates to the caller).
    summarize(state, log_total=cfg.fault.log_total)
    return jax.device_get(state.learner.violations)


def _lane_only(plan: FaultPlan, lane: int) -> FaultPlan:
    """Benign-ify every lane except ``lane`` (lanes are independent).

    Gray-failure fields stay structurally present (pytree structure is part
    of the compiled program) but collapse to their neutral elements outside
    the victim lane: threshold 0 (never drop/dup), direction 0 (two-way),
    patience 0, backoff multiplier 1.
    """
    n_inst = plan.part_start.shape[0]
    keep = jnp.arange(n_inst) == lane  # (I,)
    gray = {}
    if plan.part_dir is not None:
        gray["part_dir"] = jnp.where(keep, plan.part_dir, 0)
    if plan.link_drop is not None:
        gray["link_drop"] = jnp.where(keep[None, None], plan.link_drop, 0)
    if plan.link_dup is not None:
        gray["link_dup"] = jnp.where(keep[None, None], plan.link_dup, 0)
    if plan.ptimeout is not None:
        gray["ptimeout"] = jnp.where(keep[None], plan.ptimeout, 0)
    if plan.pboff is not None:
        gray["pboff"] = jnp.where(keep[None], plan.pboff, 1)
    if plan.link_delay is not None:
        gray["link_delay"] = jnp.where(keep[None, None], plan.link_delay, 0)
    return FaultPlan(
        crash_start=jnp.where(keep[None], plan.crash_start, NEVER),
        crash_end=jnp.where(keep[None], plan.crash_end, NEVER),
        equivocate=plan.equivocate & keep[None],
        pcrash_start=jnp.where(keep[None], plan.pcrash_start, NEVER),
        pcrash_end=jnp.where(keep[None], plan.pcrash_end, NEVER),
        part_start=jnp.where(keep, plan.part_start, NEVER),
        part_end=jnp.where(keep, plan.part_end, NEVER),
        aside=plan.aside,
        pside=plan.pside,
        **gray,
    )


def _atom_removals(plan: FaultPlan, lane: int) -> list[tuple[str, Callable]]:
    """(name, remover) for each live fault atom in ``lane``.

    Atom detection goes through the shared codec
    (``faults.injector.plan_to_atoms``, zero baselines: any nonzero gray
    value in the lane-isolated plan is a live atom) so the shrinker, the
    repro JSON, and the fuzz mutator agree on what an atom IS; the
    enumeration order below (equiv/crash interleaved per acceptor, then
    proposer crashes, partition, asymmetry, links, skew) is the greedy
    removal order earlier builds used and is kept for repro stability.
    """
    by_kind: dict[str, list] = {}
    for atom in plan_to_atoms(plan):
        if atom["lane"] == lane:
            by_kind.setdefault(atom["kind"], []).append(atom)
    acc_crash = {
        a["idx"] for a in by_kind.get("crash", []) if a["role"] == "acceptor"
    }
    prop_crash = sorted(
        a["idx"] for a in by_kind.get("crash", []) if a["role"] == "proposer"
    )
    equiv = {a["idx"] for a in by_kind.get("equiv", [])}
    part = (by_kind.get("partition") or [None])[0]
    atoms: list[tuple[str, Callable]] = []

    for a in sorted(equiv | acc_crash):
        if a in equiv:
            atoms.append((
                f"equiv[acceptor={a}]",
                lambda p, a=a: p.replace(
                    equivocate=p.equivocate.at[a, lane].set(False)
                ),
            ))
        if a in acc_crash:
            atoms.append((
                f"crash[acceptor={a}]",
                lambda p, a=a: p.replace(
                    crash_start=p.crash_start.at[a, lane].set(NEVER),
                    crash_end=p.crash_end.at[a, lane].set(NEVER),
                ),
            ))
    for pr in prop_crash:
        atoms.append((
            f"crash[proposer={pr}]",
            lambda p, pr=pr: p.replace(
                pcrash_start=p.pcrash_start.at[pr, lane].set(NEVER),
                pcrash_end=p.pcrash_end.at[pr, lane].set(NEVER),
            ),
        ))
    if part is not None:
        atoms.append((
            "partition",
            lambda p: p.replace(
                part_start=p.part_start.at[lane].set(NEVER),
                part_end=p.part_end.at[lane].set(NEVER),
            ),
        ))
    # Gray atoms: asymmetry -> symmetric, per-link rates -> zero, per-lane
    # timer skew -> neutral.  Each removal is independently revertible by
    # the greedy loop, so only load-bearing gray faults survive.
    if part is not None and part["dir"] and plan.part_dir is not None:
        atoms.append((
            "asym-partition",
            lambda p: p.replace(part_dir=p.part_dir.at[lane].set(0)),
        ))
    for link in by_kind.get("flaky", []):

        def calm(p, pr=link["prop"], a=link["acc"]):
            p = p.replace(link_drop=p.link_drop.at[pr, a, lane].set(0))
            if p.link_dup is not None:
                p = p.replace(link_dup=p.link_dup.at[pr, a, lane].set(0))
            return p

        atoms.append((atom_label(link), calm))
    for skw in by_kind.get("skew", []):

        def unskew(p, pr=skw["prop"]):
            if p.ptimeout is not None:
                p = p.replace(ptimeout=p.ptimeout.at[pr, lane].set(0))
            if p.pboff is not None:
                p = p.replace(pboff=p.pboff.at[pr, lane].set(1))
            return p

        atoms.append((atom_label(skw), unskew))
    for dly in by_kind.get("delay", []):

        def undelay(p, pr=dly["prop"], a=dly["acc"]):
            return p.replace(link_delay=p.link_delay.at[pr, a, lane].set(0))

        atoms.append((atom_label(dly), undelay))
    return atoms


def shrink(
    cfg: SimConfig,
    max_ticks: int = 512,
    chunk: int = 64,  # matches run/soak defaults: cadence-exact for long logs
    log: Optional[Callable[[str], None]] = None,
    engine: str = "xla",
    block: Optional[int] = None,
    plan: Optional[FaultPlan] = None,
) -> Optional[ShrinkResult]:
    """Minimize ``cfg``'s sampled fault plan; None if no violation in budget.

    Pass the ``engine`` under which the violation was observed (soak defaults
    to fused) — the two engines draw different random streams, so replaying a
    fused seed under the XLA stream explores a different schedule and may not
    reproduce — and ``block`` if the observing fused run used a non-default
    block size (see ``_violations_at``).

    ``plan`` overrides the seed-sampled fault plan — the fuzz scheduler's
    path, whose violating campaigns run mutated plans the seed alone
    cannot reconstruct (``fuzz.schedule`` passes the campaign's decoded
    atom plan here so the repro shrinks the schedule that actually
    violated).
    """
    say = log or (lambda s: None)
    if plan is None:
        plan = init_plan(cfg)

    viol = _violations_at(cfg, plan, max_ticks, chunk, engine, block)
    lanes = viol.nonzero()[0]
    if lanes.size == 0:
        return None
    lane = int(lanes[0])
    say(f"violation in {lanes.size} lanes; shrinking lane {lane}")

    def fails(p: FaultPlan, ticks: int) -> bool:
        return bool(_violations_at(cfg, p, ticks, chunk, engine, block)[lane] > 0)

    plan = _lane_only(plan, lane)
    assert fails(plan, max_ticks), (
        "isolating the victim lane lost the repro — lanes should be "
        "independent; this indicates a framework bug"
    )

    removed, kept = [], []
    for name, remove in _atom_removals(plan, lane):
        cand = remove(plan)
        if fails(cand, max_ticks):
            plan = cand
            removed.append(name)
            say(f"removed {name}")
        else:
            kept.append(name)
            say(f"kept {name} (needed)")

    # Smallest tick budget that still reproduces (violation is monotone in
    # ticks: counters never reset).  Searched in whole chunks: run_chunk's
    # tick count is a static jit argument, so probing arbitrary tick values
    # would recompile the full protocol scan per distinct tail size; chunk
    # granularity keeps every probe on the one already-compiled program.
    lo, hi = 1, -(-max_ticks // chunk)  # in chunks
    while lo < hi:
        mid = (lo + hi) // 2
        if fails(plan, mid * chunk):
            hi = mid
        else:
            lo = mid + 1
    ticks = min(lo * chunk, max_ticks)
    say(f"minimal ticks: {ticks} (chunk granularity {chunk})")

    result = ShrinkResult(
        lane=lane, ticks=ticks, atoms=kept, removed=removed, plan=plan,
        engine=engine, block=block, chunk=chunk,
    )
    result.timeline = violation_timeline(cfg, result)
    say(f"timeline: {len(result.timeline)} recorded ticks in lane {lane}")
    from paxos_tpu.obs.spans import build_spans

    result.spans = build_spans(result.timeline, lane)
    say(f"spans: {len(result.spans)} ballot rounds reconstructed")
    result.exposure = exposure_annotation(cfg, result)
    eff = [a for a, e in result.exposure["atoms_effective"].items() if e]
    say(f"exposure: {len(eff)}/{len(kept)} surviving atoms effective")
    result.margin = margin_annotation(cfg, result)
    say(
        "margin: min quorum slack "
        f"{result.margin['min_quorum_slack']} in lane {lane}"
    )
    return result


# Surviving-atom base name -> the exposure classes its fault can light up
# (obs.exposure.CLASSES).  crash/equiv atoms change state directly rather
# than perturbing messages/timers, so the exposure plane does not track
# them — they map to None in the annotation.
ATOM_CLASSES = {
    "partition": ("partition",),
    "asym-partition": ("partition",),
    "flaky": ("drop", "dup"),
    "skew": ("timeout",),
    "delay": ("delay",),
}


def exposure_annotation(cfg: SimConfig, result: ShrinkResult) -> dict:
    """Victim-lane injected-vs-effective counts for a minimized repro.

    Re-runs the repro with the exposure counters on — ``obs.exposure``
    draws no randomness, so the schedule is exactly the one the shrinker
    minimized — and reads the victim lane's per-class counters plus a
    per-surviving-atom effectiveness verdict (did the atom's fault class
    produce ANY effective event in this lane?).
    """
    from paxos_tpu.obs.exposure import CLASSES, ExposureConfig

    ecfg = dataclasses.replace(cfg, exposure=ExposureConfig(counters=True))
    state = init_state(ecfg)
    advance = make_advance(
        ecfg, result.plan, result.engine, block=result.block,
        compact=bool(make_longlog(ecfg)),
    )
    done = 0
    while done < result.ticks:
        n = min(result.chunk, result.ticks - done)
        state = advance(state, n)
        done += n
    inj = jax.device_get(state.exposure.injected[:, result.lane])
    eff = jax.device_get(state.exposure.effective[:, result.lane])
    classes = {
        name: {"injected": int(inj[c]), "effective": int(eff[c])}
        for c, name in enumerate(CLASSES)
    }
    atoms: dict[str, Optional[bool]] = {}
    for name in result.atoms:
        mapped = ATOM_CLASSES.get(name.split("[", 1)[0])
        atoms[name] = (
            None if mapped is None
            else any(classes[c]["effective"] > 0 for c in mapped)
        )
    out = {"lane_classes": classes, "atoms_effective": atoms}
    # Synchrony-window attribution (protocols/synchpaxos): each surviving
    # slow link is named with its sampled latency cap against the campaign
    # delta, so a SynchPaxos repro says WHICH link's latency breached the
    # window the fast path was betting on — not just "delay was involved".
    delay_atoms = [
        a for a in plan_to_atoms(result.plan)
        if a["kind"] == "delay" and a["lane"] == result.lane
    ]
    if delay_atoms:
        delta = int(cfg.fault.delta)
        out["delta_violations"] = [
            {
                "atom": atom_label(a),
                "latency_cap": int(a["cap"]),
                "delta": delta,
                "violates_delta": int(a["cap"]) > delta,
            }
            for a in delay_atoms
        ]
    return out


def margin_annotation(cfg: SimConfig, result: ShrinkResult) -> dict:
    """Victim-lane distance-to-violation minima for a minimized repro.

    Re-runs the repro with the margin counters on — ``obs.margin`` draws
    no randomness, so the schedule is exactly the one the shrinker
    minimized — and reads the victim lane's tightest quorum slack,
    near-split count, ballot-race gap, and promise slack.  Minima the lane
    never contested come back as ``None`` (the sentinel never folded).
    """
    from paxos_tpu.obs.margin import SENTINEL, MarginConfig

    mcfg = dataclasses.replace(cfg, margin=MarginConfig(counters=True))
    state = init_state(mcfg)
    advance = make_advance(
        mcfg, result.plan, result.engine, block=result.block,
        compact=bool(make_longlog(mcfg)),
    )
    done = 0
    while done < result.ticks:
        n = min(result.chunk, result.ticks - done)
        state = advance(state, n)
        done += n
    lane = result.lane
    mar = jax.device_get(state.margin)

    def _min(arr):
        v = int(arr[lane])
        return None if v >= SENTINEL else v

    return {
        "min_quorum_slack": _min(mar.qslack_min),
        "near_split_ticks": int(mar.near_split[lane]),
        "min_ballot_gap": _min(mar.bal_gap_min),
        "min_promise_slack": _min(mar.promise_slack_min),
    }


def violation_timeline(cfg: SimConfig, result: ShrinkResult) -> list:
    """Decode the victim lane's flight-recorder trace for a minimized repro.

    Re-runs the repro with the on-device recorder enabled — telemetry draws
    no randomness (core.telemetry; pinned by tests/test_telemetry.py), so
    the schedule is exactly the one the shrinker minimized — and decodes
    the victim lane's event ring into ``[{"tick": t, "events": [...]}]``.
    The ring is sized to the whole repro (tick budgets are chunk-granular
    and small), so the "last window" is the full history.
    """
    from paxos_tpu.core.telemetry import TelemetryConfig, decode_lane

    tcfg = dataclasses.replace(
        cfg,
        telemetry=TelemetryConfig(
            counters=True, ring_depth=min(result.ticks, 512)
        ),
    )
    state = init_state(tcfg)
    advance = make_advance(
        tcfg, result.plan, result.engine, block=result.block,
        compact=bool(make_longlog(tcfg)),
    )
    done = 0
    while done < result.ticks:
        n = min(result.chunk, result.ticks - done)
        state = advance(state, n)
        done += n
    return decode_lane(state.telemetry, result.lane)


def replay(cfg: SimConfig, result: ShrinkResult) -> bool:
    """True iff the minimized plan still trips the checker in its lane.

    Replays at the result's own recorded chunk — for long-log configs the
    compaction cadence is part of the schedule, so a different chunk could
    silently fail to reproduce.
    """
    viol = _violations_at(
        cfg, result.plan, result.ticks, result.chunk, result.engine,
        result.block,
    )
    return bool(viol[result.lane] > 0)
