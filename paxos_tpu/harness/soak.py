"""Soak campaigns — measure the north-star claim at scale.

BASELINE.md's safety target is "0 violations per 1e9 rounds".  A soak run
makes that claim an actual measurement: it loops fuzzing campaigns over
ROTATING seeds (a fresh fault plan and schedule stream per campaign — one
long run under a single seed would re-explore one plan forever), accumulates
instance-rounds and violations on-device, and reports the tally.

With the fused engine at ~3e8 rounds/sec/chip, 1e9 rounds is ~3 seconds and
1e11 is ~5 minutes — the claim is cheap to re-verify in CI-sized time
(`python -m paxos_tpu soak --target-rounds 1e11`).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

from paxos_tpu.harness.checkpoint import stream_id
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.retry import retry_schedule as _retry_schedule
from paxos_tpu.harness.retry import run_with_retries
from paxos_tpu.harness.run import MeasurementCorrupted, check_tick_budget, run


@dataclasses.dataclass
class CampaignSpec:
    """One schedulable campaign for the shared soak worker loop.

    ``cfg`` is the concrete config (seed included); ``plan`` is an
    explicit fault plan (``None`` = sample from the config seed, the
    plain-soak path — a non-None plan is the fuzz scheduler threading a
    mutated schedule through the same loop).  ``meta`` is scheduler-
    private (e.g. the corpus entry id) and is handed back untouched via
    ``feedback``.
    """

    cfg: SimConfig
    plan: Optional[Any] = None
    meta: Optional[dict] = None


class RotatingSeeds:
    """Default campaign source: ``cfg.seed + i`` until ``target_rounds``
    accumulate — exactly the pre-fuzz soak schedule (the planning gate is
    ``planned * campaign_rounds < target_rounds``, dispatching one final
    campaign whose tail rounds overshoot the target, as before).

    A campaign source is anything with this shape: ``next_campaign()``
    returning a :class:`CampaignSpec` or ``None`` (no more work), and
    ``feedback(spec, report, seed_rec)`` called once per finalized
    campaign, after the tally (under pipelining, one campaign behind the
    dispatch — the fuzz CLI defaults to depth 1 for fresh feedback).
    """

    def __init__(self, cfg: SimConfig, target_rounds: float,
                 campaign_rounds: int):
        self.cfg = cfg
        self.target_rounds = target_rounds
        self.campaign_rounds = campaign_rounds
        self.planned = 0

    def next_campaign(self) -> Optional[CampaignSpec]:
        if self.planned * self.campaign_rounds >= self.target_rounds:
            return None
        spec = CampaignSpec(
            cfg=dataclasses.replace(
                self.cfg, seed=self.cfg.seed + self.planned
            )
        )
        self.planned += 1
        return spec

    def feedback(self, spec, report, seed_rec) -> None:
        pass


def _run_with_retries(
    run_fn: Callable[[], dict],
    say: Callable[[str], None],
    transient_retries: int,
    backoff_s: float = 5.0,
    spans=None,
) -> tuple[dict, int]:
    """Call ``run_fn``, retrying transient runtime failures.

    Long soaks on a tunneled TPU backend die to occasional transient
    infra errors (remote-compile HTTP 500s, dropped response bodies) that
    have nothing to do with the campaign.  Campaigns are deterministic in
    (config, seed), so re-running one is an exact replay — retrying never
    changes what is measured.  This is ``harness.retry.run_with_retries``
    specialized to JAX backend errors, kept under the historical name
    (the campaign loop and tests call it directly): delays follow
    :func:`~paxos_tpu.harness.retry.retry_schedule` (exponential, capped)
    with equal jitter — sleep drawn from [delay/2, delay] — so concurrent
    soaks sharing one backend desync instead of re-colliding in lockstep.
    Returns (report, retries_used); re-raises once the budget is
    exhausted.
    """
    import jax

    return run_with_retries(
        run_fn, say, retries=transient_retries, backoff_s=backoff_s,
        retry_on=(jax.errors.JaxRuntimeError,),
        describe="transient backend error", spans=spans,
    )


def soak(
    cfg: SimConfig,
    target_rounds: float = 1e9,
    ticks_per_seed: int = 256,
    chunk: int = 64,
    engine: str = "xla",
    log: Optional[Callable[[str], None]] = None,
    recheck_doublings: int = 4,
    transient_retries: int = 2,
    retry_backoff_s: float = 5.0,
    min_slots_per_lane_tick: Optional[float] = None,
    pipeline_depth: int = 1,
    spans=None,
    plateau_seeds: int = 3,
    plateau_min_new: int = 1,
    plateau_stop: bool = False,
    vacuous_seeds: int = 3,
    on_seed: Optional[Callable[[dict], None]] = None,
    campaigns: Optional[Any] = None,
) -> dict[str, Any]:
    """Run campaigns over rotating seeds until ``target_rounds`` accumulate.

    Each campaign is one :func:`~paxos_tpu.harness.run.run` call (the single
    place engine dispatch lives).  Returns a report with total
    instance-rounds, violations, evictions, seeds exhausted, and throughput.
    ``cfg.seed`` is the first seed; campaign ``i`` uses ``seed + i``.

    **Campaign source (``campaigns``):** the worker loop pulls its work
    from a campaign source (:class:`RotatingSeeds` protocol) — ``None``
    (the default) is the rotating-seed source above, bit-identical to the
    pre-source loop; the fuzz scheduler (``paxos_tpu.fuzz.schedule``)
    passes its corpus-driven source so ``paxos_tpu fuzz`` and plain
    ``soak`` execute campaigns through this one code path.  A spec's
    explicit ``plan`` rides through dispatch, serial replay, and eviction
    rechecks; ``feedback`` fires once per finalized campaign after its
    seed record (coverage/exposure/margin enrichments included) lands.

    **Dispatch pipelining (``pipeline_depth > 1``):** campaigns overlap by
    one — seed N+1's fault plan is sampled, its state initialized, and all
    its chunk dispatches enqueued while seed N's campaign is still
    executing on-device, and seed N's tally comes from an asynchronously
    transferred composite report pytree (``harness.pipeline.AsyncSummary``)
    instead of a blocking full ``summarize`` between campaigns.  Each
    campaign's chunks are also grouped ``pipeline_depth`` per dispatch.
    The schedule streams, seed set, and tally are identical to the serial
    loop (campaigns are deterministic in (config, seed)); a transiently
    failed async campaign is replayed serially under the usual retry
    budget.  Depth 1 (the default) is the exact serial campaign loop.

    **Liveness accounting (VERDICT r2 missing#6):** every campaign runs
    with the liveness block on, and the report aggregates
    ``stuck_lanes`` (total and per-campaign max) plus the
    ``decided_frac`` mean/min across campaigns — a livelock regression
    (lanes stuck forever under partitions) now shows in the headline soak
    tally instead of only in a manual ``run --liveness``.  Campaigns are
    fixed-length, so partition-heavy configs legitimately report stuck
    lanes; the signal to watch across soaks is the TREND of
    ``stuck_frac`` for a fixed config, not its absolute value.

    Long-log caveat: ``stuck`` means "not decided by the campaign budget",
    and a long-log campaign deliberately truncates mid-log — worse, the
    final chunk's compaction removes every decided row from the window, so
    the residual rows are undecided by construction and ``stuck_frac``
    reads ~1.0 on a perfectly healthy config3long soak (measured).  For
    long-log configs the livelock signal is the REPLICATION RATE, and it
    is gated, not a trend (VERDICT r3 #8): each campaign's
    ``slots_replicated / (n_inst * ticks_per_seed)`` aggregates into
    ``slots_per_lane_tick_mean/min``, and when ``min_slots_per_lane_tick``
    is set (the CLI defaults it to 0.7x the recorded rate for known
    long-log configs, like the perf gate's 0.7x band) the report carries
    ``replication_ok`` — False fails the soak loudly (CLI exit 3) instead
    of drifting a statistic nobody gates on.

    **Transient-failure resilience:** each campaign retries up to
    ``transient_retries`` times on backend runtime errors (tunnel
    remote-compile 500s and the like) — campaigns are deterministic in
    (config, seed), so a retry is an exact replay, never new coverage.
    The report counts retries in ``transient_retries_used``; an error
    that persists past the budget still raises.

    **Eviction recheck (completeness):** a campaign whose learner table hit
    its K-slot bound (``evictions > 0``) has lanes whose agreement
    accounting is incomplete — "0 violations" would silently exclude them.
    Such campaigns are re-run with ``k_slots`` doubled (up to
    ``recheck_doublings`` times) until clean.  The schedule is IDENTICAL —
    mask streams and fault plans derive from ``(n_prop, n_acc, n_inst)``
    shapes and the seed, never from ``k_slots`` — so the re-run *re-checks
    the same execution* with a bigger table rather than exploring a new one.
    The tally counts each campaign's final (most complete) report;
    ``rechecked_seeds`` records the escalations, and the report's
    ``evictions`` is the post-recheck residual — nonzero only if a campaign
    still evicts at the largest table (``evictions_first_pass`` keeps the
    raw pre-escalation count).

    ``spans`` (an ``obs.host_spans.HostSpanRecorder``) records wall-clock
    spans for each campaign's dispatch, report drain, recheck replays, and
    retry backoffs — purely observational, never schedule-relevant.

    **Per-seed throughput (perf plane):** every finalized seed appends
    ``{"seed", "wall_s", "rounds", "rounds_per_sec"}`` to the report's
    ``per_seed`` list — the throughput TREND over a long campaign, the
    perf twin of the coverage curve (a soak that silently slows down now
    shows it seed-by-seed, not just in the final average).  ``wall_s`` is
    the host wall between consecutive finalizations, so under pipelining
    it includes the overlapped next-seed dispatch — exactly the effective
    cadence of the campaign loop.  Any recheck replays a seed triggered
    are counted in that seed's ``rounds``.  ``on_seed`` (a callback taking
    the record) streams each one as it lands — the CLI emits them into the
    metrics JSONL so ``paxos_tpu stats --follow`` can watch the trend
    live.

    **Coverage plateau (``cfg.coverage`` enabled):** each campaign's report
    carries its on-device Bloom sketch union (``obs.coverage``), and the
    digest is lane-position-free, so ORing the per-seed union bitmaps is
    the Bloom sketch of the union of all visited state sets across seeds.
    The soak tally keeps that running cross-seed union, records the
    new-union-bits each seed contributed (the coverage curve), and flags a
    plateau after ``plateau_seeds`` consecutive seeds each adding fewer
    than ``plateau_min_new`` bits — the "more seeds stopped buying new
    states" signal.  With ``plateau_stop`` the loop ends at the plateau
    (like the corrupted-measurement path, an in-flight next campaign is
    discarded unfinalized); by default the plateau is report-only.

    **Fault exposure (``cfg.exposure`` enabled):** each campaign's report
    carries its per-class injected-vs-effective counters (``obs.exposure``)
    and the tally sums them across seeds (``lanes_exposed`` becomes
    lane-campaigns exposed — each seed's lanes are a fresh population).
    A soaked-clean claim is only falsifiable against faults that actually
    TOUCHED the protocol, so after ``vacuous_seeds`` finalized seeds any
    lit fault knob whose cross-seed effective count is still zero raises a
    loud VACUOUS CHAOS warning, and the report's ``exposure`` block always
    lists ``lit``/``vacuous`` classes (``obs.exposure.annotate_lit``).

    **Near-miss margins (``cfg.margin`` enabled):** each campaign's report
    carries its distance-to-violation minima (``obs.margin``); the tally
    tightens the minima across seeds, sums the tick/lane tallies, and
    ranks the seeds by how close each came (``seed_ranking``: min quorum
    slack ascending, then near-miss lanes) — the shortlist of seeds worth
    re-fuzzing at higher fault rates even when every one soaked clean.
    """
    from paxos_tpu.harness.config import validate_pipeline_depth
    from paxos_tpu.obs.host_spans import ensure_recorder

    say = log or (lambda s: None)
    sp = ensure_recorder(spans)
    depth = validate_pipeline_depth(pipeline_depth)
    # Fail before the campaign loop: a per-seed tick budget beyond the
    # packed chosen_tick width would wrap latency measurements negative on
    # the fused engine (the pipelined path below bypasses run()'s check).
    check_tick_budget(cfg.protocol, ticks_per_seed)
    if min_slots_per_lane_tick is not None and not (
        cfg.protocol == "multipaxos" and cfg.fault.log_total
    ):
        # Fail BEFORE the (potentially hours-long) campaign loop: only
        # long-log configs report slots_replicated, so the gate would be
        # silently inert and report.get("replication_ok", True) a vacuous
        # pass for every other config.
        raise ValueError(
            "min_slots_per_lane_tick set but the config reports no "
            "replication rate (not a long-log config)"
        )

    rounds = 0
    violations = 0
    evictions = 0
    seeds = 0
    violating_seeds: list[int] = []
    rechecked_seeds: list[dict[str, int]] = []
    evictions_first_pass = 0
    recheck_rounds = 0  # re-examined rounds (not new coverage; see below)
    stuck_total = 0
    stuck_max = 0
    lanes_total = 0
    decided_fracs: list[float] = []
    # Cross-seed coverage union (Python big-int of the OR'd sketch words);
    # per-seed new-union-bits form the coverage curve.
    cov_union = 0
    cov_union_bits = 0
    cov_curve: list[int] = []
    cov_per_seed: list[int] = []
    cov_last: Optional[dict[str, Any]] = None
    cov_below = 0
    cov_plateau = False
    cov_stopped = False
    # Cross-seed exposure sums (per-class injected/effective/lanes_exposed).
    exp_classes: Optional[dict] = None
    exp_vacuous_warned = False
    # Per-seed margin snapshots (obs.margin): ranked at the end into the
    # which-seed-came-closest table.
    mar_rows: list = []
    # Per-seed SLO blocks (obs.slo): merged at the end (summed histograms,
    # recomputed percentiles) into the cross-seed client-latency tally.
    slo_rows: list = []
    slots_total = 0
    rep_rates: list[float] = []  # slots replicated per lane-tick, per campaign
    retries_used = 0
    t0 = time.perf_counter()
    # Per-seed throughput trend: wall between consecutive finalizations.
    per_seed: list[dict] = []
    seed_mark = t0
    recheck_mark = 0
    corrupted_seed: Optional[int] = None

    def serial_campaign(rcfg, plan=None):
        # Module-global `run` on purpose: tests monkeypatch soak.run to
        # model transient backend failures, and retries must hit the patch.
        # The explicit-plan kwarg is only passed when a campaign source
        # supplied one, so plain-soak replays keep the exact historical
        # call (and monkeypatched fakes keep their signature).
        kw = {} if plan is None else {"plan": plan}
        return run(
            rcfg, total_ticks=ticks_per_seed, chunk=chunk,
            engine=engine, liveness=True, pipeline_depth=depth,
            spans=spans, **kw,
        )

    def dispatch_campaign(spec):
        """Enqueue one whole campaign without blocking; returns the async
        report handle (or None if dispatch itself failed — the finalizer
        then replays serially under the retry budget)."""
        import jax

        from paxos_tpu.harness.pipeline import AsyncSummary, pipelined_run
        from paxos_tpu.harness.run import (
            init_plan,
            init_state,
            make_advance_grouped,
            make_longlog,
        )

        scfg = spec.cfg
        try:
            with sp.span("campaign_dispatch", seed=scfg.seed):
                state = init_state(scfg)
                plan = spec.plan if spec.plan is not None else init_plan(scfg)
                adv = make_advance_grouped(
                    scfg, plan, engine, compact=bool(make_longlog(scfg))
                )
                state, _, _ = pipelined_run(
                    state, adv, budget=ticks_per_seed, chunk=chunk,
                    depth=depth, spans=spans,
                )
                return AsyncSummary(
                    state, liveness=True, log_total=scfg.fault.log_total,
                    spans=spans,
                )
        except jax.errors.JaxRuntimeError as e:
            first_line = (str(e).splitlines() or [""])[0][:120]
            say(f"seed {scfg.seed}: async dispatch failed ({first_line}); "
                "replaying serially")
            return None

    def finalize(spec, handle):
        """Block on an async campaign's report.  A transient failure while
        draining it falls back to a serial replay — exact, campaigns being
        deterministic in (config, seed[, plan]) — under the normal retry
        budget."""
        attempt = {"n": 0}

        def run_fn():
            attempt["n"] += 1
            if attempt["n"] == 1 and handle is not None:
                return handle.get()
            return serial_campaign(spec.cfg, spec.plan)

        with sp.span("campaign_finalize", seed=spec.cfg.seed):
            return _run_with_retries(
                run_fn, say, transient_retries, retry_backoff_s, spans=spans
            )

    # Overlap-by-one campaign loop: the source plans campaigns (one ahead
    # of `seeds` when pipelined), `pending` is the campaign currently
    # executing on-device.  Serial mode (depth 1) dispatches and finalizes
    # in the same iteration — the exact pre-pipeline loop.
    overlap = depth > 1
    campaign_rounds = cfg.n_inst * ticks_per_seed
    source = (
        campaigns
        if campaigns is not None
        else RotatingSeeds(cfg, target_rounds, campaign_rounds)
    )
    cov_discarded: Optional[int] = None
    pending: "Optional[tuple]" = None
    while True:
        nxt = None
        spec = source.next_campaign()
        if spec is not None:
            nxt = (spec, dispatch_campaign(spec) if overlap else None)
        fin, pending = (pending, nxt) if overlap else (nxt, None)
        if fin is None:
            if spec is None and pending is None:
                break
            continue
        fspec, handle = fin
        fscfg = fspec.cfg
        try:
            report, used = finalize(fspec, handle)
        except MeasurementCorrupted as e:
            # One seed's measurements went untrustworthy (e.g. packed-MP
            # ballot overflow): stop the campaign loop but KEEP the tally
            # from completed seeds — the report records the corrupted seed
            # and the CLI fails loudly on it.  An in-flight next campaign
            # is discarded unfinalized.
            say(f"seed {fscfg.seed}: measurement corrupted — {e}")
            corrupted_seed = fscfg.seed
            break
        retries_used += used
        evictions_first_pass += report["evictions"]
        if report["evictions"]:
            k = fscfg.k_slots
            for _ in range(recheck_doublings):
                if not report["evictions"]:
                    break
                k *= 2
                say(f"seed {fscfg.seed}: {report['evictions']} evictions, "
                    f"rechecking at k_slots={k}")
                rcfg = dataclasses.replace(fscfg, k_slots=k)
                report, used = _run_with_retries(
                    lambda: serial_campaign(rcfg, fspec.plan),
                    say, transient_retries, retry_backoff_s, spans=spans,
                )
                retries_used += used
                recheck_rounds += fscfg.n_inst * ticks_per_seed
            rechecked_seeds.append({
                "seed": fscfg.seed,
                "k_slots": k,
                "evictions": report["evictions"],
            })
        violations += report["violations"]
        evictions += report["evictions"]
        if report["violations"]:
            # Reproducibility: these seeds feed straight into `shrink`.
            violating_seeds.append(fscfg.seed)
        stuck_total += report["stuck_lanes"]
        stuck_max = max(stuck_max, report["stuck_lanes"])
        lanes_total += sum(report["chosen_tick_hist"])  # valid slot-lanes
        decided_fracs.append(report["decided_frac"])
        if "slots_replicated" in report:  # long-log configs only
            slots_total += report["slots_replicated"]
            rep_rates.append(
                report["slots_replicated"] / (fscfg.n_inst * ticks_per_seed)
            )
        rounds += fscfg.n_inst * ticks_per_seed
        seeds += 1
        now = time.perf_counter()
        seed_rounds = (
            fscfg.n_inst * ticks_per_seed + recheck_rounds - recheck_mark
        )
        seed_wall = max(now - seed_mark, 1e-9)
        seed_rec = {
            "seed": fscfg.seed,
            "wall_s": round(now - seed_mark, 4),
            "rounds": seed_rounds,
            "rounds_per_sec": round(seed_rounds / seed_wall, 1),
        }
        # Observer-plane enrichments land in the seed record BEFORE it is
        # appended/streamed, so corpus fitness (fuzz.corpus) is
        # reconstructable from the JSONL `seed` event stream alone:
        # coverage -> new_bits, exposure -> per-class effective totals,
        # margin -> min quorum slack.  With the planes off (the default)
        # the record keeps its exact historical four keys.
        exp = report.get("exposure")
        if exp is not None:
            from paxos_tpu.faults.injector import exposure_lit
            from paxos_tpu.obs.exposure import CLASSES

            seed_rec["effective"] = {
                n: row["effective"] for n, row in exp["classes"].items()
            }
            if exp_classes is None:
                exp_classes = {
                    n: {"injected": 0, "effective": 0, "lanes_exposed": 0}
                    for n in CLASSES
                }
            for n, row in exp["classes"].items():
                for k in ("injected", "effective", "lanes_exposed"):
                    exp_classes[n][k] += row[k]
            if not exp_vacuous_warned and seeds >= vacuous_seeds:
                vac = sorted(
                    n for n, on in exposure_lit(cfg.fault).items()
                    if on and exp_classes[n]["effective"] == 0
                )
                if vac:
                    say(f"VACUOUS CHAOS: lit fault knobs {', '.join(vac)} "
                        f"produced 0 effective events over {seeds} seeds — "
                        "the soak is not exercising them; a clean tally "
                        "says nothing about these classes")
                    exp_vacuous_warned = True
        mar = report.get("margin")
        if mar is not None:
            seed_rec["min_quorum_slack"] = mar["min_quorum_slack"]
            mar_rows.append({"seed": fscfg.seed, **mar})
        slo = report.get("slo")
        if slo is not None:
            seed_rec["slo_p99_ticks"] = slo["p99_ticks"]
            slo_rows.append(slo)
        cov = report.get("coverage")
        if cov is not None:
            cov_last = cov
            cov_union |= int(cov["union_hex"], 16)
            new_bits = bin(cov_union).count("1") - cov_union_bits
            cov_union_bits += new_bits
            cov_per_seed.append(cov["bits_set"])
            cov_curve.append(new_bits)
            seed_rec["new_bits"] = new_bits
            cov_below = cov_below + 1 if new_bits < plateau_min_new else 0
            if cov_below >= plateau_seeds and not cov_plateau:
                cov_plateau = True
                say(f"coverage plateau: {cov_below} consecutive seeds under "
                    f"{plateau_min_new} new bits ({cov_union_bits} total)")
        per_seed.append(seed_rec)
        seed_mark = now
        recheck_mark = recheck_rounds
        if on_seed is not None:
            on_seed(seed_rec)
        say(f"seed {fscfg.seed}: {rounds:.3e} rounds, {violations} violations, "
            f"{report['stuck_lanes']} stuck, "
            f"{seed_rec['rounds_per_sec']:.3g} rounds/s")
        source.feedback(fspec, report, seed_rec)
        if cov_plateau and plateau_stop:
            # Stop like the corrupted path: keep the tally from finalized
            # seeds.  A pipelined loop has an in-flight next campaign that
            # cannot be kept without out-running the stop condition — it
            # is discarded unfinalized, but EXPLICITLY: the discarded seed
            # and the stop reason land in the report (coverage block) and
            # on stderr instead of vanishing silently.
            cov_stopped = True
            if pending is not None:
                cov_discarded = pending[0].cfg.seed
                say(f"plateau stop: discarding in-flight seed "
                    f"{cov_discarded} unfinalized (its rounds are not in "
                    "the tally)")
            break
    dt = time.perf_counter() - t0
    replication: dict[str, Any] = {}
    if rep_rates:
        replication = {
            "slots_replicated": slots_total,
            "slots_per_lane_tick_mean": round(
                sum(rep_rates) / len(rep_rates), 6
            ),
            "slots_per_lane_tick_min": round(min(rep_rates), 6),
        }
        if min_slots_per_lane_tick is not None:
            replication["replication_band"] = min_slots_per_lane_tick
            replication["replication_ok"] = (
                min(rep_rates) >= min_slots_per_lane_tick
            )
    if corrupted_seed is not None:
        replication["measurement_corrupted"] = corrupted_seed
    if depth > 1:
        replication["pipeline_depth"] = depth
    if cov_last is not None:
        from paxos_tpu.obs.coverage import K_HASHES, bloom_estimate

        m = cov_last["bits_total"]
        # Cross-seed union stats; the per-key shape matches coverage_host
        # so MetricsRegistry.ingest_coverage folds this block directly.
        replication["coverage"] = {
            "bits_set": cov_union_bits,
            "bits_total": m,
            "saturation": round(cov_union_bits / max(m, 1), 6),
            "est_states": bloom_estimate(m, K_HASHES, cov_union_bits),
            # The cross-seed union in its MERGEABLE form (obs.coverage.
            # union_hex): OR-ing two soaks' values is the Bloom union of
            # their visited sets — the fleet merges shard coverage this way.
            "union_hex": f"{cov_union:x}",
            "curve": cov_curve,  # new union bits contributed per seed
            "per_seed_bits": cov_per_seed,
            "plateau": cov_plateau,
            "plateau_seeds": plateau_seeds,
            "plateau_min_new": plateau_min_new,
            "stopped_early": cov_stopped,
            # Why the loop ended early and what it cost: a plateau stop
            # under pipelining discards the one in-flight campaign
            # unfinalized (its seed recorded here; None when nothing was
            # in flight or the loop ran to target).
            "stop_reason": "coverage_plateau" if cov_stopped else None,
            "discarded_seed": cov_discarded,
        }
    if exp_classes is not None:
        from paxos_tpu.obs.exposure import annotate_lit

        # Cross-seed exposure sums, annotated with the config's lit knobs;
        # the per-class shape matches exposure_host so
        # MetricsRegistry.ingest_exposure folds this block directly.
        replication["exposure"] = annotate_lit(
            {"classes": exp_classes}, cfg.fault
        )
    if mar_rows:
        # Cross-seed margin tally (obs.margin): minima tighten across
        # seeds, tick/lane tallies sum (lane-campaigns, like exposure's
        # lanes_exposed — each seed's lanes are a fresh population).  The
        # scalar keys match margin_host so MetricsRegistry.ingest_margin
        # folds this block directly; seed_ranking is report-only: which
        # seeds came closest to a violation, the re-fuzz shortlist.
        def _min(key):
            vals = [r[key] for r in mar_rows if r[key] is not None]
            return min(vals) if vals else None

        def _tightness(row):
            s = row["min_quorum_slack"]
            return (
                s if s is not None else 0x7FFFFFFF,
                -row["near_miss_lanes"],
                -row["near_split_ticks"],
            )

        replication["margin"] = {
            "min_quorum_slack": _min("min_quorum_slack"),
            "min_ballot_gap": _min("min_ballot_gap"),
            "min_promise_slack": _min("min_promise_slack"),
            **{
                key: sum(r[key] for r in mar_rows)
                for key in (
                    "near_miss_lanes", "zero_slack_lanes", "contested_lanes",
                    "near_split_ticks", "near_split_lanes",
                )
            },
            "seed_ranking": sorted(mar_rows, key=_tightness),
        }
    if slo_rows:
        from paxos_tpu.obs.slo import slo_merge

        replication["slo"] = slo_merge(slo_rows)
    return replication | {
        "metric": "soak",
        "rounds": rounds,
        "violations": violations,
        "violating_seeds": violating_seeds,
        "evictions": evictions,  # post-recheck: nonzero only if unresolved
        # False ⟺ rows were lost even at the largest recheck table, so the
        # safety oracle may have missed a violation (see run.summarize_host).
        "checker_complete": evictions == 0,
        "evictions_first_pass": evictions_first_pass,
        "rechecked_seeds": rechecked_seeds,
        # Rounds re-examined by escalations: real work in the wall-clock but
        # NOT new schedule coverage, so "rounds" (the safety-claim
        # denominator) excludes them while the throughput figure counts them.
        "recheck_rounds": recheck_rounds,
        "transient_retries_used": retries_used,
        # Planned pre-retry delays (pre-jitter), for post-mortem reading of
        # a soak that survived flaky infrastructure.
        "retry_schedule_s": _retry_schedule(transient_retries, retry_backoff_s),
        "stuck_lanes": stuck_total,
        "stuck_lanes_max": stuck_max,
        "stuck_frac": round(stuck_total / max(lanes_total, 1), 6),
        "decided_frac_mean": round(
            sum(decided_fracs) / max(len(decided_fracs), 1), 6
        ),
        "decided_frac_min": round(min(decided_fracs, default=0.0), 6),
        "seeds": seeds,
        "per_seed": per_seed,  # throughput trend: one record per seed
        "ticks_per_seed": ticks_per_seed,
        "n_inst": cfg.n_inst,
        "seconds": round(dt, 2),
        "rounds_per_sec": round((rounds + recheck_rounds) / dt, 1),
        "engine": engine,
        # Stream lineage (VERDICT r4 weak#3): replaying any of this soak's
        # seeds (e.g. through shrink) requires the SAME engine + fused
        # block, or the schedule silently differs.
        "stream": stream_id(cfg, engine),
        "config_fingerprint": cfg.fingerprint(),
    }
