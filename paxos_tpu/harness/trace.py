"""Tracing / profiling — the management-and-tracing subsystem's TPU twin.

Reference parity (SURVEY.md §6.1): `distributed-process` ships an Mx tracing
subsystem (per-event hooks on send/receive/spawn/died, trace-to-console)
[CH].  Here the equivalents are:

- :func:`profile`: a context manager around ``jax.profiler.trace`` — XLA op
  and memory timelines for a run window, viewable in TensorBoard/Perfetto
  (`--trace DIR` on the CLI).
- Named phases: every protocol step function wraps its reply-delivery,
  request-selection, and checker regions in ``jax.named_scope`` (scopes
  ``deliver`` / ``acceptor_select`` / ``learner_check``; the unscoped tail
  of a step is the proposer fold), so profiler timelines show protocol
  phases instead of a fused soup of HLO ops.
- :func:`event_dump`: an optional per-chunk host callback printing decided
  counts and active-ballot histograms — the batch analog of per-event trace
  logging, behind a flag because host callbacks serialize the device loop.
"""

from __future__ import annotations

import contextlib
import json
import sys
from typing import Iterator

import jax
import jax.numpy as jnp


@contextlib.contextmanager
def profile(logdir: str | None) -> Iterator[None]:
    """Wrap a run window in a JAX profiler trace (no-op when logdir is None)."""
    if not logdir:
        yield
        return
    with jax.profiler.trace(logdir):
        yield


def event_dump(state, stream=None, registry=None) -> dict:
    """One record of per-chunk protocol events (host-side readback).

    Works for any protocol state (single-decree or Multi-Paxos learner
    shapes); intended for debugging runs, not the hot path.  ``stream``
    defaults to the CURRENT ``sys.stderr`` at call time — a def-time
    default would bake in whatever stream was installed at first import
    (e.g. a long-closed pytest capture object).

    With a :class:`~paxos_tpu.harness.metrics.MetricsRegistry`, the record
    routes through the registry instead of raw stderr: the state's
    telemetry report (if the flight recorder is on) folds into the
    registry's counters/histograms, and the returned record is the
    caller's to emit into its MetricsLog.  Pass ``stream`` explicitly to
    ALSO print.
    """
    if stream is None and registry is None:
        stream = sys.stderr
    lrn = state.learner
    chosen = lrn.chosen
    bal = state.proposer.bal
    # Active-ballot histogram over proposer rounds (SURVEY.md §6.1).
    from paxos_tpu.core.ballot import ballot_round

    rounds = ballot_round(bal)
    rec = {
        "tick": int(state.tick),
        "chosen": int(chosen.sum()),
        "chosen_total": int(chosen.size),
        "violations": int(lrn.violations.sum()),
        "round_mean": float(jnp.mean(rounds.astype(jnp.float32))),
        "round_max": int(jnp.max(rounds)),
    }
    if registry is not None:
        registry.inc("event_dump_records_total")
        if getattr(state, "telemetry", None) is not None:
            from paxos_tpu.core.telemetry import telemetry_report

            registry.ingest(telemetry_report(state.telemetry))
    if stream is not None:
        print(json.dumps(rec), file=stream)
    return rec
