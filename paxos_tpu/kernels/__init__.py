"""Hot array kernels: quorum voting, majority reduction."""

from paxos_tpu.kernels.quorum import majority, quorum_reached  # noqa: F401
