"""Counter-based stateless PRNG shared by the fused engines.

A murmur3-finalizer hash of (seed, element position) in pure elementwise
int32 jnp: identical bits whether traced inside a Pallas kernel, under the
Pallas TPU interpreter, or in plain XLA.  That one property is what makes
the fused engines testable — a non-Pallas replay of the same stream is a
bit-exact oracle for the Mosaic lowering (``fused_tick.reference_chunk``).

All arithmetic is int32: wrapping int32 mul/add is arithmetic mod 2^32
(same bits as uint32), logical shifts go through
``lax.shift_right_logical``, and unsigned comparisons become biased-int32
comparisons — Mosaic handles signed vectors natively where unsigned ones
hit unimplemented lowering paths (no unsigned reductions, invalid register
casts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def i32(c: int) -> jnp.ndarray:
    """int32 constant with the bit pattern of the (possibly >2^31) literal."""
    c &= 0xFFFFFFFF
    return jnp.int32(c - (1 << 32) if c >= (1 << 31) else c)


def shr(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """Logical (not arithmetic) right shift on int32."""
    return jax.lax.shift_right_logical(x, jnp.int32(k))


def mix(seed: jnp.ndarray, tick: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """splitmix32-style scalar hash -> per-(seed, tick, block) stream seed."""
    h = (
        seed.astype(jnp.int32) * i32(0x9E3779B1)
        + tick.astype(jnp.int32) * i32(0x85EBCA77)
        + block.astype(jnp.int32) * i32(0xC2B2AE3D)
        + i32(0x165667B1)
    )
    h = h ^ shr(h, 16)
    h = h * i32(0x7FEB352D)
    h = h ^ shr(h, 15)
    return h


STREAM_SALT_MULT = 0x9E3779B9
"""Multiplier that turns a stream id into the per-stream salt literal.

The jaxpr auditor (``paxos_tpu.analysis``) recovers counter-stream ids from
traced programs by matching add-equation literals against
``stream_salt(s)`` — keep this in sync with :func:`counter_bits`.
"""


def stream_salt(stream: int) -> int:
    """The int32 bit pattern ``counter_bits`` salts stream ``stream`` with."""
    v = (STREAM_SALT_MULT * (stream + 1)) & 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


def _linear_index(shape) -> jnp.ndarray:
    """int32 linear position of every element (broadcasted_iota — TPU-safe).

    Built from one iota PER DIMENSION sized (1, …, d, …, 1) and
    broadcast-added with its stride — the same integer in every element as
    a full-shape row-major index (so every counter stream is bit-identical
    to the naive form), but the only full-shape traffic is the final
    broadcast+add instead of ndim full-shape iotas.
    """
    nd = len(shape)
    idx = None
    stride = 1
    for d in range(nd - 1, -1, -1):
        s = [1] * nd
        s[d] = shape[d]
        part = jax.lax.broadcasted_iota(jnp.int32, tuple(s), d)
        if stride != 1:
            part = part * jnp.int32(stride)
        idx = part if idx is None else idx + part
        stride *= shape[d]
    return jnp.broadcast_to(idx, shape)


def counter_bits(seed: jnp.ndarray, stream: int, shape) -> jnp.ndarray:
    """Stateless uniform int32 bits = hash of (seed, stream, position)."""
    x = _linear_index(shape) + i32(STREAM_SALT_MULT * (stream + 1))
    x = x ^ (seed.astype(jnp.int32) * i32(0x85EBCA6B))
    x = x ^ shr(x, 16)
    x = x * i32(0x7FEB352D)
    x = x ^ shr(x, 15)
    x = x * i32(0x846CA68B)
    x = x ^ shr(x, 16)
    return x


def bern(seed: jnp.ndarray, stream: int, shape, p: float):
    """bool, True w.p. ``p``; None when ``p <= 0`` (branch pruned at trace).

    ``p >= 1.0`` is special-cased to an all-True mask: the clamped threshold
    would otherwise fire w.p. 1 - 2^-32, and config authors writing
    ``drop=1.0`` mean *always*, not *almost always*.
    """
    if p <= 0.0:
        return None
    if p >= 1.0:
        return jnp.ones(shape, jnp.bool_)
    t = min(int(round(p * float(1 << 32))), (1 << 32) - 1)
    # Map the unsigned comparison bits_u < t into int32 order by flipping
    # the sign bit of both sides.
    bits = counter_bits(seed, stream, shape) ^ i32(0x80000000)
    return bits < i32(t ^ 0x80000000)


def bern_not(seed: jnp.ndarray, stream: int, shape, p: float):
    """bool, True w.p. ``1-p``; None when ``p <= 0``."""
    m = bern(seed, stream, shape, p)
    return None if m is None else ~m


def randint(seed: jnp.ndarray, stream: int, shape, n: int) -> jnp.ndarray:
    """int32 in [0, n) — non-negative bits modulo the (small) range.

    The modulo carries ~n/2^31 selection bias toward small values —
    negligible for fault-schedule fuzzing (n here is a handful of acceptors
    or tick offsets), and distinct from jax.random.randint's unbiased
    rejection path; do not reuse this for anything statistical.
    """
    return (counter_bits(seed, stream, shape) & jnp.int32(0x7FFFFFFF)) % jnp.int32(
        max(n, 1)
    )
