"""Fused multi-tick Pallas engine — whole chunks resident in VMEM.

The XLA engine (`harness.run.run_chunk`) scans a protocol's tick function
with the full state pytree as the scan carry: every tick reads and writes
the whole state in HBM (~1.6 GB/tick at 1M single-decree instances), which
bounds throughput at HBM bandwidth / tick.

This module removes that bound: one `pallas_call` keeps a block of
instances' ENTIRE state resident in VMEM and advances it `n_ticks` ticks
before writing back — HBM traffic drops from `2 * state * n_ticks` to
`2 * state` per chunk — with per-tick fault masks drawn on-core from the
counter PRNG (`kernels/counter_prng`).

The machinery is generic over protocols: :func:`fused_chunk` takes the
protocol's pure transition (``apply_fn(state, masks, plan, cfg)``) and its
counter-mask sampler (``mask_fn(cfg, tick_seed, state)``) as static
arguments, and per-protocol wrappers bind them.  Protocol semantics are NOT
reimplemented — each kernel traces the very same ``apply_*`` function the
XLA engine scans; only the mask source differs, so the two engines explore
the same adversarial schedule space with different (but equally
deterministic) random streams.

Determinism: the stream is reseeded per (seed, tick, block) via a splitmix
hash, so a chunk replays bit-identically regardless of chunk size, and
checkpoint/resume stays exact as long as the block size is kept.
:func:`reference_chunk` replays the identical stream in plain XLA — the
bit-exact oracle for the Mosaic lowering itself (tests/test_fused.py).

Reference parity (SURVEY.md §8.2.5, §8.4.4): this is the "Pallas fallback
if XLA doesn't reach the throughput target" milestone — generalized to the
whole tick, which profiling showed is the right fusion boundary (the scan
carry's HBM round-trip, not any single op, is the cost).

Mosaic notes (kept OUT of this file, in the shared protocol/transport code,
so both engines trace identical programs): no scatter (`.at[i].set` on a
static index becomes an iota-masked where), no bool `select_n` (monotone
bool updates use pure OR algebra), no unsigned reductions (selection scores
are int32 with an INT32_MIN absent sentinel), no cumsum/stack in
`first_true` (min-of-masked-iota instead), and no bool (i1) vectors in the
`scf.for` carry (this file round-trips bool leaves through int32 across the
tick loop).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.kernels.counter_prng import mix

# TPU interpret mode (emulates TPU-specific primitives on CPU) arrived after
# jax 0.4.x; the kernel body is Mosaic-clean int32/bool arithmetic with no
# TPU-only primitives, so the generic Pallas interpreter is an equivalent
# oracle on older versions.
_INTERPRET = (
    pltpu.InterpretParams() if hasattr(pltpu, "InterpretParams") else True
)

DEFAULT_BLOCK = 1024

# Largest instance count one pallas_call compiles at (measured: 4M compiles
# and runs on v5e-1 at any block; 8M fails the TPU compile at EVERY block
# size, so the limit is per-call lanes, not VMEM per block).  Bigger batches
# auto-split into sequential per-segment kernels with globally-offset
# counter-PRNG block ids — bit-identical to the (uncompilable) single call
# at the same block size (tests/test_fused.py::test_fused_segmented_*).
MAX_LANES_PER_CALL = 1 << 22


def fit_block(
    block: int,
    n: int,
    floor: "int | None" = None,
    interpret: bool = False,
    warn: bool = True,
) -> int:
    """A block that DIVIDES ``n``: the request if already valid, else the
    largest power of two <= the request that divides ``n``.

    An explicitly valid request (divides ``n`` and tiles: a multiple of
    the lane floor, or the full array — Mosaic exempts full-dimension
    blocks from alignment) is returned UNCHANGED: block is
    stream-relevant (streams key on the block id), so a replay passing
    the observing run's block must get exactly that block back.  Invalid
    requests degrade deterministically in (block, n), so replays of
    degraded runs reproduce too.

    ``floor`` defaults from ``interpret``: Mosaic requires the block's
    trailing dim divisible by 128 on a real TPU, while the Pallas TPU
    interpreter emulates with no minimum (floor 1, so every dividing
    block passes verbatim and the error branches below are compiled-mode
    only).  On hardware, a count like the literal 1,000,000 (2^6 x 5^6,
    largest power-of-two divisor 64) cannot host ANY aligned block:
    small such counts (<= DEFAULT_BLOCK) degrade to one full-array block,
    large ones get an error steering to a 128-divisible count (e.g.
    1<<20) or the XLA engine, which has no alignment constraint.
    """
    if floor is None:
        floor = 1 if interpret else 128
    if n % block == 0 and (block % floor == 0 or block == n):
        return block
    p2 = n & -n  # largest power-of-two divisor of n
    if p2 < floor:
        if n <= DEFAULT_BLOCK:
            if warn:
                _warn_degraded(block, n, n)
            return n  # one full-array block: tiles trivially, fits VMEM
        raise ValueError(
            f"n_inst={n} has largest power-of-two divisor {p2} (< {floor}, "
            f"the TPU lane-tiling minimum): the fused engine needs an "
            f"aligned instance count — use one divisible by {floor} (e.g. "
            f"1<<20 for '1M') or --engine xla (no alignment constraint)"
        )
    b = min(block, p2)
    b = 1 << (b.bit_length() - 1)  # round down to a power of two (divides n)
    if b < floor:
        raise ValueError(
            f"block={block} is below the lane-tiling minimum {floor}: pass "
            f"a block >= {floor} that divides n_inst={n}, or omit it for "
            f"the protocol default"
        )
    if warn:
        _warn_degraded(block, b, n)
    return b


def _warn_degraded(requested: int, got: int, n: int) -> None:
    """Loud signal when an EXPLICIT block request degrades (ADVICE r3:
    block is stream-relevant, so a typo'd block must not silently run a
    different PRNG schedule).  A warning — not an error — because
    degradation is deterministic in (block, n) and replays of degraded runs
    reproduce.  Default-block resolution (``block=None`` at the public
    entry points) degrades silently: the user typed nothing, so there is
    no typo to flag (callers pass ``warn=False``)."""
    if got != requested:
        import warnings

        warnings.warn(
            f"fused block={requested} does not tile n_inst={n}; degraded "
            f"deterministically to block={got} (a DIFFERENT schedule stream "
            f"than block={requested} at an n_inst it divides — pass "
            f"block={got} explicitly to silence)",
            stacklevel=3,
        )


# VMEM budget for the blocked state carry: ~24 MB usable VMEM per core,
# minus double-buffered plan blocks, loop temporaries, and the unpacked
# field values live inside the tick body.  384 KiB of PACKED state per
# block leaves comfortable headroom at every measured configuration while
# letting the estimator pick the largest useful block.
VMEM_STATE_BUDGET = 384 * 1024


def block_for_bytes(
    bytes_per_lane: float, default: int = DEFAULT_BLOCK, floor: int = 128
) -> int:
    """Largest power-of-two block <= ``default`` whose packed state fits
    :data:`VMEM_STATE_BUDGET` (never below ``floor``, the lane-tiling
    minimum).  This is the layout-table-driven half of the VMEM estimate:
    ``fit_block`` then reconciles the result with ``n_inst`` divisibility."""
    b = default
    while b > floor and b * bytes_per_lane > VMEM_STATE_BUDGET:
        b //= 2
    return b


def estimate_block(protocol: str, state, default: int = DEFAULT_BLOCK) -> int:
    """VMEM-estimated fused block for a concrete (unpacked) state: computes
    packed bytes/lane from the protocol's layout table (utils/bitops) and
    sizes the block against :data:`VMEM_STATE_BUDGET`.  The static
    per-protocol defaults in :func:`fused_fns` are pinned to this
    estimator's output for the library configs (asserted in
    tests/test_bitops.py) — they stay static because block is
    stream-relevant and must not drift with state shape details."""
    from paxos_tpu.utils import bitops

    codec = bitops.codec_for(protocol, state)
    return block_for_bytes(codec.bytes_per_lane(state), default=default)


def _split_tick(state: Any):
    """Flatten the state with the scalar ``tick`` leaf separated out.

    Returns (treedef, array_leaves, tick, tick_pos) where ``array_leaves``
    preserves flatten order minus the tick leaf.
    """
    leaves, treedef = jax.tree.flatten(state)
    tick_pos = [i for i, l in enumerate(leaves) if getattr(l, "ndim", None) == 0]
    assert len(tick_pos) == 1, "expected exactly one scalar leaf (tick)"
    ti = tick_pos[0]
    return treedef, leaves[:ti] + leaves[ti + 1 :], leaves[ti], ti


def _kernel(
    cfg, n_ticks, apply_fn, mask_fn, treedef, tick_pos, n_state, plan_def,
    s_1d, p_1d, *refs,
):
    seed_ref, tick_ref, blk0_ref = refs[0], refs[1], refs[2]
    state_refs = refs[3 : 3 + n_state]
    plan_refs = refs[3 + n_state : 3 + n_state + plan_def.num_leaves]
    out_refs = refs[3 + n_state + plan_def.num_leaves :]

    seed0 = seed_ref[0, 0]
    tick0 = tick_ref[0, 0]
    # Global block id: the shard's block offset (0 single-chip; set by the
    # sharded wrapper under shard_map) plus the grid position, so every
    # block across every chip draws a distinct stream.
    blk_id = blk0_ref[0, 0] + pl.program_id(0)

    # 1-D leaves ride as (1, I) so the block size is not pinned to the XLA
    # 1024-element 1-D tiling (see fused_chunk); squeeze them back here.
    plan: FaultPlan = jax.tree.unflatten(
        plan_def,
        [r[...][0] if i in p_1d else r[...] for i, r in enumerate(plan_refs)],
    )
    vals = [
        r[...][0] if i in s_1d else r[...] for i, r in enumerate(state_refs)
    ]
    leaves = vals[:tick_pos] + [tick0] + vals[tick_pos:]
    state = jax.tree.unflatten(treedef, leaves)

    # Mosaic cannot legalize bool (i1) vectors in the scf.for carry; round
    # bool leaves through int32 across the loop boundary (free-ish VPU
    # converts, same (8,128) tiling as the rest of the carry).
    def pack(st):
        return jax.tree.map(
            lambda x: x.astype(jnp.int32) if x.dtype == jnp.bool_ else x, st
        )

    def unpack(st_i, proto):
        return jax.tree.map(
            lambda x, p: x.astype(jnp.bool_) if p.dtype == jnp.bool_ else x,
            st_i,
            proto,
        )

    def body(t, st_i):
        st = unpack(st_i, state)
        tick_seed = mix(seed0, st.tick, blk_id)
        masks = mask_fn(cfg, tick_seed, st)
        return pack(apply_fn(st, masks, plan, cfg))

    state = unpack(jax.lax.fori_loop(0, n_ticks, body, pack(state)), state)

    out = treedef.flatten_up_to(state)
    new_tick = out.pop(tick_pos)
    for i, (r, v) in enumerate(zip(out_refs[:-1], out)):
        r[...] = v[None] if i in s_1d else v
    # Scalar tick rides in SMEM; every grid step writes the same value.
    out_refs[-1][0, 0] = new_tick


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "n_ticks", "apply_fn", "mask_fn", "block", "interpret",
        "default",
    ),
    donate_argnums=(0,),
)
def fused_chunk(
    state: Any,
    seed: jnp.ndarray,
    plan: FaultPlan,
    cfg: FaultConfig,
    n_ticks: int,
    apply_fn: Callable,
    mask_fn: Callable,
    block: "int | None" = None,
    interpret: bool = False,
    block_offset: "jnp.ndarray | int" = 0,
    default: int = DEFAULT_BLOCK,
) -> Any:
    """Advance ``n_ticks`` ticks fully in VMEM; returns the new state.

    ``seed`` is an int32 scalar (the campaign seed); per-(tick, block)
    streams are derived on-core.  ``block`` instances are processed per grid
    step; ``None`` resolves to ``default`` (the protocol's library block —
    silent degradation); an EXPLICIT request that doesn't divide ``n_inst``
    (or misses the tiling floor) degrades deterministically via
    :func:`fit_block` WITH a warning, since block is stream-relevant.  1-D
    state leaves pin it to the XLA 1024-element tiling at large sizes, so
    the default is rarely worth changing.
    """
    n_inst = jax.tree.leaves(state)[0].shape[-1]
    # Non-dividing blocks degrade to the largest power-of-two divisor
    # (deterministic, so the stream keying per (seed, tick, block id)
    # stays reproducible across replays at the same n_inst).  No pre-clamp:
    # fit_block handles block > n_inst itself, so oversized explicit
    # requests warn instead of silently snapping to the full array.
    explicit = block is not None
    block = fit_block(
        block if explicit else default, n_inst, interpret=interpret,
        warn=explicit,
    )
    grid = n_inst // block

    treedef, s_leaves, tick, tick_pos = _split_tick(state)
    p_leaves, plan_def = jax.tree.flatten(plan)

    # Lift 1-D (I,) leaves to (1, I): as 1-D operands their XLA layout tiles
    # in 1024-element units, which forbids any block != 1024; as (1, I) they
    # tile (8, 128) like everything else and any 128-multiple block works.
    # Only done when needed — the boundary reshapes cost ~10% on the paxos
    # path, and a 1024-aligned block matches the native 1-D tiling anyway.
    lift = block % 1024 != 0
    s_1d = frozenset(i for i, l in enumerate(s_leaves) if lift and l.ndim == 1)
    p_1d = frozenset(i for i, l in enumerate(p_leaves) if lift and l.ndim == 1)
    s_lift = [l[None] if i in s_1d else l for i, l in enumerate(s_leaves)]
    p_lift = [l[None] if i in p_1d else l for i, l in enumerate(p_leaves)]

    def vspec(leaf):
        lead = leaf.shape[:-1]
        return pl.BlockSpec(
            (*lead, block),
            lambda i, nl=len(lead): (0,) * nl + (i,),
            memory_space=pltpu.VMEM,
        )

    sspec = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)

    in_specs = (
        [sspec, sspec, sspec]
        + [vspec(l) for l in s_lift]
        + [vspec(l) for l in p_lift]
    )
    out_specs = [vspec(l) for l in s_lift] + [sspec]
    out_shape = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in s_lift] + [
        jax.ShapeDtypeStruct((1, 1), jnp.int32)
    ]
    # Donate state arrays into their output slots (in-place in HBM).
    aliases = {3 + k: k for k in range(len(s_lift))}

    kernel = functools.partial(
        _kernel, cfg, n_ticks, apply_fn, mask_fn, treedef, tick_pos,
        len(s_lift), plan_def, s_1d, p_1d,
    )
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        # TPU interpret mode where available (it emulates TPU-specific
        # primitives on CPU), generic interpreter otherwise — the CPU test
        # rig runs equivalence checks under whichever this build supports.
        interpret=_INTERPRET if interpret else False,
    )(
        jnp.reshape(jnp.asarray(seed, jnp.int32), (1, 1)),
        jnp.reshape(tick, (1, 1)),
        jnp.reshape(jnp.asarray(block_offset, jnp.int32), (1, 1)),
        *s_lift,
        *p_lift,
    )
    new_leaves = [
        o[0] if i in s_1d else o for i, o in enumerate(outs[:-1])
    ]
    new_leaves.insert(tick_pos, outs[-1][0, 0])
    return jax.tree.unflatten(treedef, new_leaves)


# Donation contract (ADVICE r3, re-verified on hardware): the fused engine
# CONSUMES its input state on BOTH sides of the MAX_LANES_PER_CALL
# threshold — fused_chunk's donate_argnums deletes the caller's buffers on
# TPU (measured: holding the input after a direct <=4M-lane call raises
# "Array has been deleted"), so _segmented_impl donating too is symmetric,
# not an asymmetry.  Donation is load-bearing at scale: 8M-lane state is
# ~6.5 GB (BASELINE.md), and without in-place reuse input+output copies
# double that against a 16 GB v5e.  Callers needing the pre-chunk state
# (before/after comparisons — see tests) must copy it first; every harness
# path reassigns `state = advance(state, n)` and never re-reads the input.
@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "n_ticks", "apply_fn", "mask_fn", "block", "segments",
        "interpret",
    ),
    donate_argnums=(0,),
)
def _segmented_impl(
    state, seed, plan, *, cfg, n_ticks, apply_fn, mask_fn, block, segments,
    interpret,
):
    n_inst = jax.tree.leaves(state)[0].shape[-1]
    seg = n_inst // segments
    bps = seg // block  # blocks per segment

    def slice_seg(tree, s):
        return jax.tree.map(
            lambda x: jax.lax.slice_in_dim(
                x, s * seg, (s + 1) * seg, axis=x.ndim - 1
            )
            if getattr(x, "ndim", 0) >= 1 and x.shape[-1] == n_inst
            else x,
            tree,
        )

    outs = [
        fused_chunk(
            slice_seg(state, s), seed, slice_seg(plan, s), cfg, n_ticks,
            apply_fn, mask_fn, block=block, interpret=interpret,
            block_offset=s * bps,
        )
        for s in range(segments)
    ]

    def recombine(*leaves):
        if getattr(leaves[0], "ndim", 0) == 0 or leaves[0].shape[-1] != seg:
            return leaves[0]  # tick (and any unsliced leaf): identical per seg
        return jnp.concatenate(leaves, axis=-1)

    return jax.tree.map(recombine, *outs)


def fused_chunk_auto(
    state: Any,
    seed: jnp.ndarray,
    plan: FaultPlan,
    cfg: FaultConfig,
    n_ticks: int,
    apply_fn: Callable,
    mask_fn: Callable,
    block: "int | None" = None,
    interpret: bool = False,
    max_lanes: int = MAX_LANES_PER_CALL,
    default: int = DEFAULT_BLOCK,
) -> Any:
    """:func:`fused_chunk` with the scale ceiling removed (VERDICT r2 #7).

    Up to ``max_lanes`` instances this IS ``fused_chunk``.  Beyond it, the
    batch splits into the fewest equal segments that fit, each advanced by
    its own kernel with ``block_offset = segment * blocks_per_segment`` —
    the global block ids the single kernel would use at the POST-FIT block
    — so the schedule stream is invariant to the segmentation and a
    campaign's replay/shrink/checkpoint contract (same seed + same block ->
    same schedule) survives the degradation.  The stream contract is keyed
    to the post-fit block (ADVICE r3): the block is fitted against the
    SEGMENT size, so a composite request that divides ``n_inst`` but not
    the segment (e.g. block=3072 at n_inst=12M, segment 4M) degrades —
    loudly, via :func:`fit_block`'s warning — to a block that divides the
    segment, and the resulting stream matches the single kernel at that
    degraded block, not at the request.  Power-of-two blocks (every
    default) always divide the segment and pass through unchanged.  Cost:
    one extra HBM copy of the state per chunk (slice + concat), amortized
    over ``n_ticks`` ticks.
    """
    n_inst = jax.tree.leaves(state)[0].shape[-1]
    if n_inst <= max_lanes:
        return fused_chunk(
            state, seed, plan, cfg, n_ticks, apply_fn, mask_fn,
            block=block, interpret=interpret, default=default,
        )
    segments = -(-n_inst // max_lanes)
    if n_inst % segments:
        raise ValueError(
            f"n_inst={n_inst} not divisible into {segments} segments of "
            f"<= {max_lanes} lanes; use a power-of-two instance count"
        )
    seg = n_inst // segments
    explicit = block is not None
    block = fit_block(
        block if explicit else default, seg, interpret=interpret,
        warn=explicit,
    )
    return _segmented_impl(
        state, jnp.asarray(seed, jnp.int32), plan,
        cfg=cfg, n_ticks=n_ticks, apply_fn=apply_fn, mask_fn=mask_fn,
        block=block, segments=segments, interpret=interpret,
    )


def reference_chunk(
    state: Any,
    seed: jnp.ndarray,
    plan: FaultPlan,
    cfg: FaultConfig,
    n_ticks: int,
    apply_fn: Callable | None = None,
    mask_fn: Callable | None = None,
    blk_id: "jnp.ndarray | int" = 0,
) -> Any:
    """Non-Pallas replay of the fused engine's exact schedule (single block).

    Runs the identical ``apply_fn`` + counter-PRNG stream in plain XLA for
    a state that fits one block: the fused kernel must produce bit-identical
    results — the equivalence oracle for the Pallas lowering itself
    (tests/test_fused.py).  Defaults to single-decree paxos.

    ``blk_id`` is the block's GLOBAL stream id (default 0: a single-block
    unsharded state).  Passing ``jax.lax.axis_index(...)`` inside a
    ``shard_map`` whose local shard is one block replays the sharded fused
    engine's stream — used by the multi-controller test, where the Pallas
    TPU-interpret emulation itself deadlocks across processes
    (tests/_dist_child.py documents the minimal repro).
    """
    if (apply_fn is None) != (mask_fn is None):
        raise ValueError(
            "pass apply_fn and mask_fn together: mixing one protocol's "
            "transition with another's mask sampler is never meaningful"
        )
    if apply_fn is None:
        from paxos_tpu.protocols.paxos import apply_tick, counter_masks

        apply_fn, mask_fn = apply_tick, counter_masks
    seed = jnp.asarray(seed, jnp.int32)
    blk_id = jnp.asarray(blk_id, jnp.int32)

    def body(t, st):
        tick_seed = mix(seed, st.tick, blk_id)
        return apply_fn(st, mask_fn(cfg, tick_seed, st), plan, cfg)

    return jax.lax.fori_loop(0, n_ticks, body, state)


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "n_ticks", "apply_fn", "mask_fn", "mesh", "block",
        "blocks_per_shard", "interpret",
    ),
    donate_argnums=(0,),
)
def _sharded_impl(
    state, seed, plan, *, cfg, n_ticks, apply_fn, mask_fn, mesh, block,
    blocks_per_shard, interpret,
):
    from jax.sharding import PartitionSpec as P

    from paxos_tpu.parallel.mesh import INSTANCES_AXIS

    try:
        from jax import shard_map as _shard_map

        def shard_map(f, **kw):
            return _shard_map(f, check_vma=False, **kw)
    except ImportError:  # older jax: experimental API, check_rep kwarg
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, **kw):
            return _shard_map(f, check_rep=False, **kw)

    n_inst = jax.tree.leaves(state)[0].shape[-1]

    def leaf_spec(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[-1] == n_inst:
            return P(*([None] * (x.ndim - 1)), INSTANCES_AXIS)
        return P()

    state_spec = jax.tree.map(leaf_spec, state)
    plan_spec = jax.tree.map(leaf_spec, plan)

    def local_fn(st, sd, pln):
        off = jax.lax.axis_index(INSTANCES_AXIS) * blocks_per_shard
        return fused_chunk(
            st, sd, pln, cfg, n_ticks, apply_fn, mask_fn,
            block=block, interpret=interpret, block_offset=off,
        )

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(state_spec, P(), plan_spec),
        out_specs=state_spec,
    )(state, seed, plan)


def fused_chunk_sharded(
    state: Any,
    seed: jnp.ndarray,
    plan: FaultPlan,
    cfg: FaultConfig,
    n_ticks: int,
    apply_fn: Callable,
    mask_fn: Callable,
    mesh,
    block: "int | None" = None,
    interpret: bool = False,
    default: int = DEFAULT_BLOCK,
) -> Any:
    """Multi-chip fused engine: one fused kernel per shard under shard_map.

    Instances are independent, so the mapped body needs no collectives; each
    shard's kernel gets its global block offset (``axis_index * blocks per
    shard``) so every block on every chip draws a distinct counter-PRNG
    stream — a sharded run equals an unsharded run at the same block size,
    shard-for-shard (tests/test_fused.py).  ``state``/``plan`` must already
    be sharded over the mesh's ``instances`` axis (``parallel.mesh``).

    The implementation is a module-level jit (all bindings static), so a
    campaign's per-chunk calls hit the compile cache and donate the state.
    """
    n_inst = jax.tree.leaves(state)[0].shape[-1]
    n_dev = int(mesh.devices.size)
    if n_inst % n_dev:
        # Checked eagerly: an uneven split would silently miscompute
        # blocks_per_shard (and thus the global PRNG block offsets) long
        # before any shape error surfaced.
        raise ValueError(f"n_inst={n_inst} not divisible by mesh size {n_dev}")
    local = n_inst // n_dev
    explicit = block is not None
    block = fit_block(
        block if explicit else default, local, interpret=interpret,
        warn=explicit,
    )
    return _sharded_impl(
        state, jnp.asarray(seed, jnp.int32), plan,
        cfg=cfg, n_ticks=n_ticks, apply_fn=apply_fn, mask_fn=mask_fn,
        mesh=mesh, block=block, blocks_per_shard=local // block,
        interpret=interpret,
    )


# ---- Per-protocol bindings -------------------------------------------------


@functools.lru_cache(maxsize=None)
def fused_fns(protocol: str, ablate: frozenset = frozenset()):
    """(apply_fn, mask_fn, default_block) for a protocol — the ONE place a
    protocol is bound to the fused engine (both the per-protocol wrappers in
    ``FUSED_CHUNKS`` and the sharded CLI path read from here).

    ``ablate`` (dev-only; scripts/ablate_fused.py) compiles the kernel with
    a component removed — flags are interpreted by the protocol's apply/mask
    functions ("learner", "sends", "select", "consume", "proposer" in
    apply; "prng" in masks).  Supported for paxos and multipaxos (the two
    roofline targets); other protocols accept only the empty set.  The
    lru_cache makes the returned partials identity-stable, so each variant
    compiles once per process (apply_fn/mask_fn are static jit arguments).
    """
    if ablate and protocol not in ("paxos", "multipaxos"):
        raise ValueError(f"ablation flags unsupported for {protocol!r}")
    unknown = set(ablate) - {
        "learner", "sends", "select", "consume", "proposer", "prng"
    }
    if unknown:
        raise ValueError(f"unknown ablate flags: {sorted(unknown)}")
    if protocol == "paxos":
        from paxos_tpu.protocols.paxos import apply_tick, counter_masks

        if ablate:
            return (
                functools.partial(apply_tick, ablate=ablate),
                functools.partial(counter_masks, ablate=ablate),
                DEFAULT_BLOCK,
            )
        return apply_tick, counter_masks, DEFAULT_BLOCK
    if protocol == "fastpaxos":
        from paxos_tpu.protocols.fastpaxos import apply_tick_fast
        from paxos_tpu.protocols.paxos import counter_masks

        return apply_tick_fast, counter_masks, DEFAULT_BLOCK
    if protocol == "raftcore":
        from paxos_tpu.protocols.paxos import counter_masks
        from paxos_tpu.protocols.raftcore import apply_tick_raft

        return apply_tick_raft, counter_masks, DEFAULT_BLOCK
    if protocol == "synchpaxos":
        from paxos_tpu.protocols.paxos import counter_masks
        from paxos_tpu.protocols.synchpaxos import apply_tick_sp

        return apply_tick_sp, counter_masks, DEFAULT_BLOCK
    if protocol == "multipaxos":
        from paxos_tpu.protocols.multipaxos import apply_tick_mp, mp_counter_masks

        # 256: the bit-packed layout (core/mp_state.MP_LAYOUT) cuts MP state
        # to 904 B/lane (config3; was 1400 unpacked), so the VMEM estimator
        # (block_for_bytes: 256 * 904 B <= 384 KiB budget, 512 overflows)
        # doubles the block the old unpacked footprint forced down to 128.
        # Kept static (not per-shape) because block is stream-relevant: this
        # default change starts a fresh schedule lineage for MP — replays of
        # pre-packing campaigns must pass block=128 explicitly.
        mp_block = 256
        if ablate:
            return (
                functools.partial(apply_tick_mp, ablate=ablate),
                functools.partial(mp_counter_masks, ablate=ablate),
                mp_block,
            )
        return apply_tick_mp, mp_counter_masks, mp_block
    raise ValueError(f"unknown protocol: {protocol!r}")


# Worst-case proposer.bal growth per tick: every ballot bump is
# make_ballot(round + 1, pid) = (round + 1) * MAX_PROPOSERS + pid + 1, so
# new - old <= MAX_PROPOSERS + (pid_new - pid_old) < 2 * MAX_PROPOSERS = 16
# (core/ballot.py; all four protocols bump through make_ballot).  The
# chunk-boundary clamp hoist sizes its headroom check with this bound.
BALLOT_GROWTH_PER_TICK = 16


def report_ballot_limit(protocol: str) -> int:
    """The report-time ``max_ballot >= limit`` threshold — the SAME constant
    ``harness/run.summarize_device`` hardcodes (11-bit Multi-Paxos, 15-bit
    single-decree).  The packed ``proposer.bal`` field is deliberately wider
    (v2 layouts) so mid-chunk growth cannot wrap; every clamp in this module
    pins at THIS limit, not the field capacity, keeping both engines'
    ``MeasurementCorrupted`` threshold identical to the v1 contract."""
    return (1 << 11) - 1 if protocol == "multipaxos" else (1 << 15) - 1


def _saturate_ballots(codec, state):
    """Pin ``proposer.bal`` at the report-time ballot limit before a pack.

    ``Codec.pack`` masks every field to its declared width, so a ballot
    that outgrew its field would WRAP to a small value and the report-time
    ``max_ballot >= limit`` guard (harness/run.summarize_host) could never
    observe the overflow — the exact silent corruption it exists to catch.
    Ballots are monotone, so clamping at the limit is sticky: once any
    proposer's ballot tries to exceed it, the unpacked state reads exactly
    the limit at every subsequent chunk boundary and the guard raises
    ``MeasurementCorrupted`` at the next report — same threshold the XLA
    engine trips by growing through it unmasked (``min(bal, limit) >=
    limit`` iff ``bal >= limit``).  Below the limit the clamp is the
    identity, so the fused(packed) == reference(unpacked) bit-exactness
    contract holds for every uncorrupted campaign.

    Since the v2 layouts this runs at chunk BOUNDARIES (entry pack + exit
    unpack in ``_make_chunk``), not in the per-tick body: ``proposer.bal``
    carries ``ceil(log2(chunk_ticks * BALLOT_GROWTH_PER_TICK))`` headroom
    bits over the limit, so un-clamped mid-chunk growth cannot wrap the
    field.  Chunks too long for the headroom fall back to the per-tick
    clamp (``packed_fns(clamp_per_tick=True)``).
    """
    cap = codec.field_capacity("proposer.bal")
    if cap is None:
        return state
    cap = min(cap, report_ballot_limit(codec.protocol))
    prop = state.proposer
    return state.replace(proposer=prop.replace(bal=jnp.minimum(prop.bal, cap)))


def ballot_hoist_safe_ticks(protocol: str, codec) -> int:
    """Largest per-chunk tick count for which the chunk-boundary ballot
    clamp cannot wrap the packed ``proposer.bal`` field mid-chunk.  Chunks
    beyond this use the per-tick clamp; campaign-level tick budgets are
    bounded separately by ``run.check_tick_budget``."""
    cap = codec.field_capacity("proposer.bal")
    if cap is None:
        return 0
    headroom = cap - report_ballot_limit(protocol)
    return max(0, headroom // BALLOT_GROWTH_PER_TICK)


@functools.lru_cache(maxsize=None)
def packed_fns(protocol: str, ablate: frozenset = frozenset(),
               clamp_per_tick: bool = False):
    """(apply_fn, mask_fn, default_block) lifted to the packed state.

    The raw :func:`fused_fns` pair operates on the unpacked pytree; these
    wrappers carry a ``bitops.PackedState`` across the fused engine's
    fori_loop instead, and the tick body unpacks exactly ONCE: the mask
    slot returns ``tick_seed`` unchanged (the generic kernel treats masks
    as an opaque value between ``mask_fn`` and ``apply_fn``), and
    ``packed_apply`` runs ``unpack_read -> mask_fn -> apply_fn ->
    pack_delta`` — the differential codec entry points (utils/bitops) that
    decode only the declared read-set and re-encode only the declared
    write-set, carrying untouched words through the fori_loop unchanged.
    PRNG streams are untouched: same mask fns, same (seed, tick, block)
    keying, so the composition is value-identical to the raw pair below the
    report-time ballot limit and fused(packed) == reference(unpacked)
    bit-exactly (tier1 PACKED_SMOKE / DELTA_SMOKE).

    ``clamp_per_tick`` re-inserts the v1-era per-tick ballot saturation for
    chunks longer than :func:`ballot_hoist_safe_ticks`; the default leaves
    the clamp hoisted to the chunk boundaries (``_make_chunk``), off the
    per-tick jaxpr entirely (audited by ``paxos_tpu audit``).
    """
    apply_fn, mask_fn, default_block = fused_fns(protocol, ablate)

    def packed_apply(pst, tick_seed, plan, cfg):
        codec = pst.codec
        st = codec.unpack_read(pst)
        masks = mask_fn(cfg, tick_seed, st)
        new = apply_fn(st, masks, plan, cfg)
        if clamp_per_tick:
            new = _saturate_ballots(codec, new)
        return codec.pack_delta(pst, new)

    def packed_mask(cfg, tick_seed, pst):
        # Opaque pass-through: the single unpack lives in packed_apply, fed
        # by this seed — the mask path's former second full unpack is gone
        # from the traced tick body (it was DCE'd at compile time before,
        # but censuses and trace size paid for it).
        return tick_seed

    packed_apply.__name__ = f"packed_{protocol}_apply"
    packed_mask.__name__ = f"packed_{protocol}_masks"
    return packed_apply, packed_mask, default_block


def _make_chunk(protocol: str) -> Callable:
    def chunk(state, seed, plan, cfg, n_ticks, block=None, interpret=False):
        from paxos_tpu.utils import bitops

        codec = bitops.codec_for(protocol, state)
        # Clamp hoist guard (trace-time, per chunk): the boundary-only clamp
        # is sound iff this chunk's un-clamped growth fits the headroom bits
        # of the packed proposer.bal field.  n_ticks is static here, so the
        # choice is baked into the compiled chunk; campaign budgets are
        # bounded separately (run.check_tick_budget).
        hoisted = n_ticks <= ballot_hoist_safe_ticks(protocol, codec)
        apply_fn, mask_fn, default_block = packed_fns(
            protocol, clamp_per_tick=not hoisted
        )
        # The entry pack saturates: a resumed/handed-in state whose ballots
        # already overflowed must read as at-limit (guard fires), not wrap
        # to a small value (guard blind).
        pst = bitops.pack_state(codec, _saturate_ballots(codec, state))
        pst = fused_chunk_auto(
            pst, seed, plan, cfg, n_ticks, apply_fn, mask_fn,
            block=block, interpret=interpret, default=default_block,
        )
        out = bitops.unpack_state(codec, pst)
        # Exit clamp: with the per-tick clamp hoisted, mid-chunk ballots may
        # sit between the report limit and the field capacity; pin them back
        # to the limit so summaries and the next chunk see the v1-identical
        # sticky saturation value.
        if hoisted:
            out = _saturate_ballots(codec, out)
        return out

    chunk.__name__ = f"fused_{protocol}_chunk"
    chunk.__doc__ = (
        f"{protocol} on the fused engine (binding: packed_fns over "
        f"fused_fns): state packs to dense words (utils/bitops) at the "
        f"chunk boundary, rides VMEM packed (differential pack/unpack per "
        f"tick, ballot clamp hoisted to the boundaries), and unpacks on "
        f"return; batches over MAX_LANES_PER_CALL auto-segment "
        f"(fused_chunk_auto)."
    )
    return chunk


FUSED_CHUNKS = {
    p: _make_chunk(p)
    for p in ("paxos", "fastpaxos", "raftcore", "multipaxos", "synchpaxos")
}
fused_paxos_chunk = FUSED_CHUNKS["paxos"]
fused_fastpaxos_chunk = FUSED_CHUNKS["fastpaxos"]
fused_raftcore_chunk = FUSED_CHUNKS["raftcore"]
fused_multipaxos_chunk = FUSED_CHUNKS["multipaxos"]
