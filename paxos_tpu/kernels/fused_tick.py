"""Fused multi-tick Pallas engine for single-decree Paxos.

The XLA engine (`harness.run.run_chunk`) scans `apply_tick` over ticks with
the full state pytree as the scan carry: every tick reads and writes the
whole state in HBM (~1.6 GB/tick at 1M instances), which bounds throughput
at HBM bandwidth / tick.

This module removes that bound: one `pallas_call` keeps a block of
instances' ENTIRE state resident in VMEM and advances it `n_ticks` ticks
before writing back — HBM traffic drops from `2 * state * n_ticks` to
`2 * state` per chunk, and the per-tick fault masks come from the on-core
hardware PRNG (`pltpu.prng_random_bits`) instead of materialized
`jax.random` draws.

Protocol semantics are NOT reimplemented: the kernel traces the very same
:func:`paxos_tpu.protocols.paxos.apply_tick` the XLA engine scans — only
the mask source differs, so the two engines explore the same adversarial
schedule space with different (but equally deterministic) random streams.
Determinism: the PRNG is reseeded per (seed, tick, block) via a splitmix
hash, so a chunk replays bit-identically regardless of chunk size, and
checkpoint/resume stays exact as long as the block size is kept.

Reference parity (SURVEY.md §8.2.5, §8.4.4): this is the "Pallas fallback
for deliver+vote if XLA doesn't reach the throughput target" milestone —
generalized to the whole tick, which profiling showed is the right fusion
boundary (the scan carry's HBM round-trip, not any single op, is the cost).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from paxos_tpu.core.state import PaxosState
from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.protocols.paxos import TickMasks, apply_tick


DEFAULT_BLOCK = 1024


def _i32(c: int) -> jnp.ndarray:
    """int32 constant with the bit pattern of the (possibly >2^31) literal."""
    c &= 0xFFFFFFFF
    return jnp.int32(c - (1 << 32) if c >= (1 << 31) else c)


def _shr(x: jnp.ndarray, k: int) -> jnp.ndarray:
    """Logical (not arithmetic) right shift on int32."""
    return jax.lax.shift_right_logical(x, jnp.int32(k))


def _mix(seed: jnp.ndarray, tick: jnp.ndarray, block: jnp.ndarray) -> jnp.ndarray:
    """splitmix32-style scalar hash -> per-(seed, tick, block) PRNG seed.

    All-int32: wrapping int32 mul/add is arithmetic mod 2^32 (same bits as
    uint32), and Mosaic handles signed vectors/scalars natively where
    unsigned ones hit unimplemented paths.
    """
    h = (
        seed.astype(jnp.int32) * _i32(0x9E3779B1)
        + tick.astype(jnp.int32) * _i32(0x85EBCA77)
        + block.astype(jnp.int32) * _i32(0xC2B2AE3D)
        + _i32(0x165667B1)
    )
    h = h ^ _shr(h, 16)
    h = h * _i32(0x7FEB352D)
    h = h ^ _shr(h, 15)
    return h


def _linear_index(shape) -> jnp.ndarray:
    """int32 linear position of every element (broadcasted_iota — TPU-safe)."""
    idx = jnp.zeros(shape, jnp.int32)
    stride = 1
    for d in range(len(shape) - 1, -1, -1):
        idx = idx + jax.lax.broadcasted_iota(jnp.int32, shape, d) * jnp.int32(stride)
        stride *= shape[d]
    return idx


def counter_bits(seed: jnp.ndarray, stream: int, shape) -> jnp.ndarray:
    """Stateless uniform int32 bits = murmur3-style hash of (seed, position).

    A counter-based PRNG in pure elementwise jnp (int32 arithmetic mod 2^32;
    logical shifts): identical results whether traced inside a Pallas
    kernel, under the Pallas TPU interpreter, or in plain XLA — which is
    what makes the fused engine's schedule stream testable bit-for-bit
    against a non-Pallas reference (the hardware PRNG
    `pltpu.prng_random_bits` is a zero stub under the interpreter, and
    Mosaic's unsigned-vector support is partial).
    """
    x = _linear_index(shape) + _i32(0x9E3779B9 * (stream + 1))
    x = x ^ (seed.astype(jnp.int32) * _i32(0x85EBCA6B))
    x = x ^ _shr(x, 16)
    x = x * _i32(0x7FEB352D)
    x = x ^ _shr(x, 15)
    x = x * _i32(0x846CA68B)
    x = x ^ _shr(x, 16)
    return x


def _bern(seed: jnp.ndarray, stream: int, shape, p: float) -> jnp.ndarray:
    """True w.p. ``p``: biased-int32 compare of counter bits vs threshold."""
    t = min(int(round(p * float(1 << 32))), (1 << 32) - 1)
    # Map the unsigned comparison bits_u < t into int32 order by flipping
    # the sign bit of both sides.
    bits = counter_bits(seed, stream, shape) ^ _i32(0x80000000)
    return bits < _i32(t ^ 0x80000000)


def _sample_masks_counter(
    cfg: FaultConfig, seed: jnp.ndarray, n_prop: int, n_acc: int, blk: int
) -> TickMasks:
    """A tick's masks from :func:`counter_bits` keyed by a per-tick seed."""
    slot = (2, n_prop, n_acc, blk)
    edge = (n_prop, n_acc, blk)

    def hit(stream, shape, p):
        if p <= 0.0:
            return None
        return _bern(seed, stream, shape, p)

    def miss(stream, shape, p):
        m = hit(stream, shape, p)
        return None if m is None else ~m

    return TickMasks(
        sel_score=counter_bits(seed, 0, slot),
        busy=miss(1, (1, 1, n_acc, blk), cfg.p_idle),
        deliver=miss(2, slot, cfg.p_hold),
        dup_req=hit(3, slot, cfg.p_dup),
        dup_rep=hit(4, slot, cfg.p_dup),
        keep_prom=miss(5, edge, cfg.p_drop),
        keep_accd=miss(6, edge, cfg.p_drop),
        keep_p1=miss(7, edge, cfg.p_drop),
        keep_p2=miss(8, edge, cfg.p_drop),
        # Non-negative int32 bits modulo the (small) backoff range.
        backoff=(
            (counter_bits(seed, 9, (n_prop, blk)) & jnp.int32(0x7FFFFFFF))
            % jnp.int32(max(cfg.backoff_max, 1))
        ),
    )


def _split_tick(state: PaxosState):
    """Flatten the state with the scalar ``tick`` leaf separated out.

    Returns (treedef, array_leaves, tick, tick_pos) where ``array_leaves``
    preserves flatten order minus the tick leaf.
    """
    leaves, treedef = jax.tree.flatten(state)
    tick_pos = [i for i, l in enumerate(leaves) if getattr(l, "ndim", None) == 0]
    assert len(tick_pos) == 1, "expected exactly one scalar leaf (tick)"
    ti = tick_pos[0]
    return treedef, leaves[:ti] + leaves[ti + 1 :], leaves[ti], ti


def _kernel(cfg, n_ticks, treedef, tick_pos, n_state, plan_def, *refs):
    seed_ref, tick_ref = refs[0], refs[1]
    state_refs = refs[2 : 2 + n_state]
    plan_refs = refs[2 + n_state : 2 + n_state + plan_def.num_leaves]
    out_refs = refs[2 + n_state + plan_def.num_leaves :]

    seed0 = seed_ref[0, 0]
    tick0 = tick_ref[0, 0]
    blk_id = pl.program_id(0)

    plan: FaultPlan = jax.tree.unflatten(plan_def, [r[...] for r in plan_refs])
    vals = [r[...] for r in state_refs]
    leaves = vals[:tick_pos] + [tick0] + vals[tick_pos:]
    state: PaxosState = jax.tree.unflatten(treedef, leaves)
    n_prop, blk = state.proposer.bal.shape
    n_acc = state.acceptor.promised.shape[0]

    # Mosaic cannot legalize bool (i1) vectors in the scf.for carry; round
    # bool leaves through int32 across the loop boundary (free-ish VPU
    # converts, same (8,128) tiling as the rest of the carry).
    def pack(st):
        return jax.tree.map(
            lambda x: x.astype(jnp.int32) if x.dtype == jnp.bool_ else x, st
        )

    def unpack(st_i, proto):
        return jax.tree.map(
            lambda x, p: x.astype(jnp.bool_) if p.dtype == jnp.bool_ else x,
            st_i,
            proto,
        )

    def body(t, st_i):
        st = unpack(st_i, state)
        tick_seed = _mix(seed0, st.tick, blk_id)
        masks = _sample_masks_counter(cfg, tick_seed, n_prop, n_acc, blk)
        return pack(apply_tick(st, masks, plan, cfg))

    state = unpack(jax.lax.fori_loop(0, n_ticks, body, pack(state)), state)

    out = treedef.flatten_up_to(state)
    new_tick = out.pop(tick_pos)
    for r, v in zip(out_refs[:-1], out):
        r[...] = v
    # Scalar tick rides in SMEM; every grid step writes the same value.
    out_refs[-1][0, 0] = new_tick


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_ticks", "block", "interpret"),
    donate_argnums=(0,),
)
def fused_paxos_chunk(
    state: PaxosState,
    seed: jnp.ndarray,
    plan: FaultPlan,
    cfg: FaultConfig,
    n_ticks: int,
    block: int = DEFAULT_BLOCK,
    interpret: bool = False,
) -> PaxosState:
    """Advance ``n_ticks`` ticks fully in VMEM; returns the new state.

    ``seed`` is an int32 scalar (the campaign seed); per-(tick, block)
    streams are derived on-core.  ``block`` instances are processed per grid
    step and must divide ``n_inst``.
    """
    n_inst = state.n_inst
    block = min(block, n_inst)
    if n_inst % block:
        raise ValueError(f"n_inst={n_inst} not divisible by block={block}")
    grid = n_inst // block

    treedef, s_leaves, tick, tick_pos = _split_tick(state)
    p_leaves, plan_def = jax.tree.flatten(plan)

    def vspec(leaf):
        lead = leaf.shape[:-1]
        return pl.BlockSpec(
            (*lead, block),
            lambda i, nl=len(lead): (0,) * nl + (i,),
            memory_space=pltpu.VMEM,
        )

    sspec = pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM)

    in_specs = (
        [sspec, sspec]
        + [vspec(l) for l in s_leaves]
        + [vspec(l) for l in p_leaves]
    )
    out_specs = [vspec(l) for l in s_leaves] + [sspec]
    out_shape = [jax.ShapeDtypeStruct(l.shape, l.dtype) for l in s_leaves] + [
        jax.ShapeDtypeStruct((1, 1), jnp.int32)
    ]
    # Donate state arrays into their output slots (in-place in HBM).
    aliases = {2 + k: k for k in range(len(s_leaves))}

    kernel = functools.partial(
        _kernel, cfg, n_ticks, treedef, tick_pos, len(s_leaves), plan_def
    )
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        input_output_aliases=aliases,
        # TPU interpret mode (not the generic interpreter): it emulates the
        # TPU-specific primitives (prng_seed/prng_random_bits) on CPU, which
        # is what the CPU test rig runs equivalence checks under.
        interpret=pltpu.InterpretParams() if interpret else False,
    )(
        jnp.reshape(jnp.asarray(seed, jnp.int32), (1, 1)),
        jnp.reshape(tick, (1, 1)),
        *s_leaves,
        *p_leaves,
    )
    new_leaves = list(outs[:-1])
    new_leaves.insert(tick_pos, outs[-1][0, 0])
    return jax.tree.unflatten(treedef, new_leaves)


def reference_chunk(
    state: PaxosState,
    seed: jnp.ndarray,
    plan: FaultPlan,
    cfg: FaultConfig,
    n_ticks: int,
) -> PaxosState:
    """Non-Pallas replay of the fused engine's exact schedule (single block).

    Runs the identical `apply_tick` + `counter_bits` stream in plain XLA for
    a state that fits one block (``blk_id = 0``): the fused kernel must
    produce bit-identical results — the equivalence oracle for the Pallas
    lowering itself (tests/test_fused.py).
    """
    n_prop = state.proposer.bal.shape[0]
    n_acc, n_inst = state.acceptor.promised.shape
    seed = jnp.asarray(seed, jnp.int32)

    def body(t, st):
        tick_seed = _mix(seed, st.tick, jnp.int32(0))
        masks = _sample_masks_counter(cfg, tick_seed, n_prop, n_acc, n_inst)
        return apply_tick(st, masks, plan, cfg)

    return jax.lax.fori_loop(0, n_ticks, body, state)
