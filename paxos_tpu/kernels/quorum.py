"""Quorum-vote kernel: ballot-compare + majority-reduce.

Reference parity (SURVEY.md §3.2 "intra-instance all-to-all"): the reference
proposer's `collectPromises`/`collectAccepted` loops — N point-to-point
`expect`s followed by a count — become a bitmask popcount per (instance,
proposer) lane.  Votes are accumulated as bits (so duplicate deliveries of
the same acceptor's reply cannot inflate the count), and "until majority"
becomes "recompute the quorum predicate each tick" under `lax.scan`.

The acceptors axis is small (3–7) and unsharded, so this is a segment
reduce, not a collective; XLA fuses it into the surrounding step.  A Pallas
variant exists for the fused deliver+vote path (`paxos_tpu.kernels` grows it
in M8) only if profiling shows XLA failed to fuse — SURVEY.md §8.2.5.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paxos_tpu.utils.bitops import popcount

# ---------------------------------------------------------------------------
# Lane-reduction allowlist (PR 14 dataflow auditor).  The flow pass
# (analysis/flow.py) proves every traced step eqn preserves the trailing
# instance axis; the only legitimate cross-lane mixers live OUTSIDE the
# per-tick step — summarize reductions, coverage unions, and (future)
# cross-lane quorum-system merges.  ``lane_reduce(name)`` is a zero-op
# ``jax.named_scope`` tag marking such a region; the auditor accepts a
# cross-lane reduction only under a tag whose name is in
# ``analysis.flow.LANE_REDUCE_SITES``.
_LANE_TAG = "__lane_ok__"


def lane_reduce(name: str):
    """Scope marking an allowlisted cross-lane reduction region ``name``."""
    return jax.named_scope(_LANE_TAG + name)


def majority(n_acc: int) -> int:
    """Size of a classic majority quorum."""
    return n_acc // 2 + 1


def fast_quorum(n_acc: int) -> int:
    """Size of a Fast Paxos fast quorum: ceil(3n/4)."""
    return -((-3 * n_acc) // 4)


def quorum_reached(heard_mask: jnp.ndarray, quorum: int) -> jnp.ndarray:
    """Elementwise: does the voter bitmask contain >= ``quorum`` voters?"""
    return popcount(heard_mask) >= quorum
