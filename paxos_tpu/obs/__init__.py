"""Causal round tracing — span reconstruction and standard-tooling export.

Model-checking practice treats the counterexample *trace* as the product,
not the verdict (PAPERS.md: Spin Paxos), and hardware-consensus designs
keep event accounting on the fast path so rich observability is free when
idle (NetPaxos).  ``core.telemetry`` (PR 2) is the raw material: packed
per-lane event rings, counters, histograms.  This package is the layer
above — it turns decoded rings into *causal, span-level* traces and emits
them in formats standard tooling loads:

- :mod:`spans` — replay a decoded flight-recorder timeline into per-lane,
  per-ballot round spans (phase-1 open -> promise quorum -> phase-2 ->
  decide/timeout/preemption), each annotated with the fault events that
  landed inside it, plus span-derived aggregates (round-latency
  percentiles, preemption depth, faults per decided round).
- :mod:`host_spans` — wall-clock spans for the host dispatch loop
  (dispatch groups, done-flag probes, device->host transfers, checkpoint
  writes, retry backoffs).  The clock is INJECTED by the harness layer:
  this package never reads the host clock or entropy itself, so it sits
  inside the static auditor's purity scope (``analysis/purity``).
- :mod:`export` — Chrome trace-event JSON (Perfetto-loadable: one track
  per lane, async spans per ballot, instant events for faults, host spans
  on a separate process track) and a compact JSONL span format for
  programmatic diffing, plus a schema validator.
- :mod:`capture` — drive a campaign with the recorder on and the host
  span layer wrapping the pipelined dispatch loop; backs the
  ``paxos_tpu trace`` CLI subcommand.
- :mod:`perf` — the performance plane: derive throughput (cumulative /
  steady-state / windowed rounds-per-sec), pipeline occupancy, chunk-
  latency percentiles, and compile-vs-steady splits from the host span
  stream; VMEM/roofline occupancy from the recorded ceilings; plus the
  bench-row provenance schema and the noise-aware regression comparison
  behind ``paxos_tpu bench-compare``.  Like the rest of the package it
  is pure decode over injected-clock spans — no clock, no IO, no device
  ops.
- :mod:`timeseries` — the fleet observatory (layer 9): a crash-safe
  append-only metrics time-series journal per worker (the ``fuzz.corpus``
  single-write + flush + fsync discipline, torn-tail-tolerant load),
  canonical ``(record, clock)``-ordered ``merge_series`` so the
  coordinator assembles one byte-deterministic fleet-wide series, and the
  ``compare_series`` trend gate (discovery stall, rounds/sec degradation,
  heartbeat gaps) beside the bench gate.  Clocks are injected logical
  clocks; the wall sidecar is diagnostic and stripped from the canonical
  merged form.

Everything here is host-side decode: zero new device ops, zero PRNG
draws, schedules bit-identical (the PR 4 auditor and the golden digests
confirm the layer cannot perturb a campaign).
"""

from paxos_tpu.obs.spans import (  # noqa: F401
    FAULT_EVENTS,
    RoundSpan,
    build_spans,
    span_aggregates,
)
