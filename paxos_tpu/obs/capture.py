"""Trace capture — run a campaign with the recorder on; decode into spans.

Backs the ``paxos_tpu trace`` CLI subcommand: enable the on-device flight
recorder (ring sized to the tick budget, so the "last window" is the full
history), drive the pipelined dispatch loop with the host span layer
wrapping every dispatch and probe, then decode the interesting lanes and
reconstruct round spans.

Telemetry draws no randomness and the host span layer only *observes* the
loop, so the captured schedule is bit-identical to an untraced run of the
same (config, seed, engine) — the whole point of tracing a fuzzer: the
trace IS the campaign, not a perturbed cousin.

Clock doctrine: this module takes an already-built
:class:`~paxos_tpu.obs.host_spans.HostSpanRecorder` (or ``None``) — the
harness layer owns wall clocks; ``obs`` stays clock-free for the purity
auditor.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax

from paxos_tpu.obs.host_spans import HostSpanRecorder, ensure_recorder
from paxos_tpu.obs.spans import RoundSpan, build_spans, span_aggregates

# Ring depth cap: (depth, n_inst) int32 per lane; 4096 x 64k lanes is 1 GiB,
# so campaigns longer than this must raise --n-inst trade-offs explicitly.
MAX_RING = 4096


@dataclasses.dataclass
class CaptureResult:
    report: dict[str, Any]  # the campaign's summarize() report
    lanes: list[int]  # decoded lanes (violating lanes first)
    timelines: dict[int, list]  # lane -> decode_lane output
    spans: dict[int, list[RoundSpan]]  # lane -> reconstructed rounds
    aggregates: dict[str, Any]  # span_aggregates over every decoded lane
    host: Optional[HostSpanRecorder]  # wall-clock dispatch spans
    # name -> [(tick, value)] device counter series (Perfetto `ph: C`),
    # e.g. the per-chunk coverage-bits curve; None when none captured.
    counters: Optional[dict[str, list]] = None


def recorder_config(cfg, ticks: int):
    """``cfg`` with the flight recorder sized for a full-history trace."""
    from paxos_tpu.core.telemetry import HIST_TICKS_PER_BIN, TelemetryConfig

    return dataclasses.replace(
        cfg,
        telemetry=TelemetryConfig(
            counters=True,
            ring_depth=min(ticks, MAX_RING),
            # One bin per HIST_TICKS_PER_BIN ticks covers the whole budget,
            # +1 catch-all so in-budget decides never saturate the tail.
            hist_bins=min(-(-ticks // HIST_TICKS_PER_BIN) + 1, 128),
        ),
    )


def pick_lanes(violations, n_inst: int, max_lanes: int) -> list[int]:
    """Lanes to decode: violating lanes first, then lane 0 upward."""
    chosen: list[int] = [int(i) for i in violations.nonzero()[0][:max_lanes]]
    lane = 0
    while len(chosen) < min(max_lanes, n_inst):
        if lane not in chosen:
            chosen.append(lane)
        lane += 1
    return chosen


def capture_round_trace(
    cfg,
    *,
    ticks: int,
    chunk: int = 64,
    engine: str = "xla",
    depth: int = 4,
    max_lanes: int = 8,
    recorder: Optional[HostSpanRecorder] = None,
    coverage=None,
    exposure=None,
    margin=None,
    workload=None,
) -> CaptureResult:
    """Run ``cfg`` for ``ticks`` with full tracing; decode ``max_lanes`` lanes.

    The loop is the pipelined dispatcher (``harness.pipeline``) so the
    host track shows real grouped dispatches; ``depth=1`` degrades to the
    serial per-chunk loop.  The returned spans are per-lane round
    reconstructions (``obs.spans``); aggregates cover every decoded lane.

    ``coverage`` (an ``obs.coverage.CoverageConfig``) additionally samples
    the union coverage-bits count at every chunk boundary into a counter
    series for the Perfetto timeline; ``exposure`` (an
    ``obs.exposure.ExposureConfig``) does the same for the per-class
    effective fault counters — one counter track per fault class, so the
    timeline shows WHEN each class started touching the protocol; and
    ``margin`` (an ``obs.margin.MarginConfig``) draws the
    ``min_quorum_slack`` / ``near_miss_lanes`` distance-to-violation
    curves, so the timeline shows WHEN the campaign got close; and
    ``workload`` (a ``workload.generator.WorkloadConfig``) draws the
    ``slo_p99_ticks`` / ``queue_depth`` client-latency curves, so the
    timeline shows WHEN the queues backed up.
    Sampling needs the state pytree at each boundary, so any sampler
    forces the serial per-chunk dispatcher (the sample itself is a small
    device_get, not a state round-trip); a trace run is a debug tool, so
    the pipelined host track is the price of the curves.
    """
    from paxos_tpu.core.telemetry import decode_lane
    from paxos_tpu.harness.pipeline import pipelined_run
    from paxos_tpu.harness.run import (
        init_plan,
        init_state,
        make_advance,
        make_advance_grouped,
        make_longlog,
        summarize,
    )

    sp = ensure_recorder(recorder)
    tcfg = recorder_config(cfg, ticks)
    sample_coverage = coverage is not None and coverage.enabled()
    sample_exposure = exposure is not None and exposure.enabled()
    sample_margin = margin is not None and margin.enabled()
    sample_workload = workload is not None and workload.enabled()
    if sample_coverage:
        tcfg = dataclasses.replace(tcfg, coverage=coverage)
    if sample_exposure:
        tcfg = dataclasses.replace(tcfg, exposure=exposure)
    if sample_margin:
        tcfg = dataclasses.replace(tcfg, margin=margin)
    if sample_workload:
        tcfg = dataclasses.replace(tcfg, workload=workload)
    with sp.span("init", n_inst=tcfg.n_inst, protocol=tcfg.protocol):
        state = init_state(tcfg)
        plan = init_plan(tcfg)
    counters: Optional[dict[str, list]] = None
    if sample_coverage or sample_exposure or sample_margin or sample_workload:
        if sample_coverage:
            from paxos_tpu.obs.coverage import coverage_device
        if sample_exposure:
            from paxos_tpu.obs.exposure import CLASSES, exposure_device
        if sample_margin:
            from paxos_tpu.obs.margin import SENTINEL, margin_device
        if sample_workload:
            from paxos_tpu.obs.slo import slo_device, slo_host

        advance = make_advance(
            tcfg, plan, engine, compact=bool(make_longlog(tcfg))
        )
        cov_samples: list = []
        exp_samples: dict[str, list] = (
            {name: [] for name in CLASSES} if sample_exposure else {}
        )
        mar_samples: dict[str, list] = {
            name: [] for name in ("min_quorum_slack", "near_miss_lanes")
        }
        slo_samples: dict[str, list] = {
            name: [] for name in ("slo_p99_ticks", "queue_depth")
        }
        done = 0
        while done < ticks:
            n = min(chunk, ticks - done)
            with sp.span("dispatch", tick_start=done, ticks=n, groups=1):
                state = advance(state, n)
            done += n
            if sample_coverage:
                with sp.span("coverage_sample", tick=done):
                    bits = int(jax.device_get(
                        coverage_device(state.coverage)["union_bits"]
                    ))
                cov_samples.append((done, bits))
            if sample_exposure:
                with sp.span("exposure_sample", tick=done):
                    eff = jax.device_get(
                        exposure_device(state.exposure)["effective"]
                    )
                for c, name in enumerate(CLASSES):
                    exp_samples[name].append((done, int(eff[c])))
            if sample_margin:
                with sp.span("margin_sample", tick=done):
                    md = jax.device_get(margin_device(state.margin))
                # Uncontested minima (SENTINEL) would flatten the counter
                # track's scale; the slack curve starts at first contact.
                slack = int(md["min_quorum_slack"])
                if slack < SENTINEL:
                    mar_samples["min_quorum_slack"].append((done, slack))
                mar_samples["near_miss_lanes"].append(
                    (done, int(md["near_miss_lanes"]))
                )
            if sample_workload:
                with sp.span("slo_sample", tick=done):
                    sd = slo_host(jax.device_get(slo_device(state.wload)))
                # No served traffic yet (-1) would draw a misleading
                # negative spike; the latency curve starts at first serve.
                if sd["p99_ticks"] >= 0:
                    slo_samples["slo_p99_ticks"].append(
                        (done, sd["p99_ticks"])
                    )
                slo_samples["queue_depth"].append(
                    (done, sd["queue_depth"])
                )
        counters = {}
        if sample_coverage:
            counters["coverage_bits_set"] = cov_samples
        for name, series in exp_samples.items():
            counters[f"exposure_effective_{name}"] = series
        if sample_margin:
            for name, series in mar_samples.items():
                if series:
                    counters[f"margin_{name}"] = series
        if sample_workload:
            for name, series in slo_samples.items():
                if series:
                    counters[name] = series
    else:
        advance = make_advance_grouped(
            tcfg, plan, engine, compact=bool(make_longlog(tcfg))
        )
        state, _, _ = pipelined_run(
            state, advance, budget=ticks, chunk=chunk, depth=depth,
            spans=recorder,
        )
    with sp.span("summarize"):
        report = summarize(state, log_total=tcfg.fault.log_total)
    with sp.span("violations_readback"):
        viol = jax.device_get(state.learner.violations)
    if viol.ndim > 1:  # multipaxos: (L, I) slot violations -> per-lane
        viol = viol.sum(axis=0)
    lanes = pick_lanes(viol, tcfg.n_inst, max_lanes)

    timelines: dict[int, list] = {}
    spans: dict[int, list[RoundSpan]] = {}
    with sp.span("decode", lanes=len(lanes)):
        for lane in lanes:
            timelines[lane] = decode_lane(state.telemetry, lane)
            spans[lane] = build_spans(timelines[lane], lane)
    agg = span_aggregates(s for lane in lanes for s in spans[lane])
    return CaptureResult(
        report=report, lanes=lanes, timelines=timelines, spans=spans,
        aggregates=agg, host=recorder, counters=counters,
    )
