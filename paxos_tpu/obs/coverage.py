"""Live on-device coverage plane (default OFF, off is free).

Coverage is the canonical observability signal of a fuzzer, but the exact
probe (``check/coverage.py``) only works at tiny exhaustive bounds on the
CPU.  This module is the any-scale twin: every lane hashes its post-tick
protocol state into a per-lane Bloom/bitmap sketch carried on-device
alongside telemetry, so a campaign reports how much *distinct* state it
explored — and whether round N explored anything round N-1 didn't — at
zero host round-trips (the sketch reduces at the existing pipelined
summarize boundary, ``harness/run.summarize_device``).

The default-off-is-free contract (``core.telemetry`` is the template):

- :class:`CoverageState` rides as an ``Optional`` leaf of every protocol
  state; ``None`` when disabled (pruned from the pytree), all leaves int32
  with trailing ``instances`` axis, no scalar leaves — the fused Pallas
  engine's generic pytree flattening (``utils/bitops`` passthrough words)
  carries it with ZERO kernel changes, and ``pjit`` shards it with the
  rest of the state.
- :func:`observe` is pure int32 arithmetic hashing (splitmix-style
  finalizers, the ``kernels/counter_prng`` idiom) computed from the state
  the tick already produced: **no PRNG draws**, so enabling coverage
  cannot perturb a schedule.  The static auditor holds the module to that
  (``prng_audit.audit_telemetry_parity`` wired for the "coverage" audit
  config).  The per-hash mixing deliberately uses only xor/multiply/shift
  — no scalar add literals — so the auditor's counter-stream recovery
  (which matches *add*-equation literals against stream salts) can never
  confuse a digest constant for a PRNG stream.
- Mosaic-clean: elementwise int32 ops, iota-masked ``where`` instead of
  scatter, ``lax.population_count`` — the same op diet as telemetry.

Semantics: the digest depends only on the lane's protocol state (never the
lane index or the tick), so two lanes in the same state set the same bits
and the cross-lane OR of the per-lane bitmaps is exactly the Bloom filter
of the UNION of all visited states.  :func:`bloom_estimate` inverts the
fill fraction into a distinct-state estimate; :func:`bloom_bound` gives
the matching confidence band, which the calibration tests use to check the
sketch against the exact ``V`` set from ``check/coverage.py`` at probe
bounds.
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from paxos_tpu.kernels.counter_prng import i32, shr
from paxos_tpu.kernels.quorum import lane_reduce

# Bloom hash count.  Fixed (not a config knob) because the in-tick update
# runs inside ``apply_tick``, which only sees the FaultConfig — and k=2 is
# the standard fill/FP sweet spot for the m/n ratios the default sketch
# targets.
K_HASHES = 2

# Per-hash xor salts (distinct odd constants; NOT stream salts — see the
# module docstring on add-literal avoidance).
_H_SALTS = (0x2545F491, 0x8B7F1C35)

# Leaf-mix and finalizer multipliers (FNV / splitmix32 family).
_FNV_PRIME = 0x01000193
_MIX1 = 0x7FEB352D
_MIX2 = 0x846CA68B

# State fields that constitute "the lane's protocol state" for the digest.
# Accounting is excluded on purpose: the learner carries ``chosen_tick``
# (wall-tick-dependent — equal protocol states at different ticks must hash
# equally) plus violation/eviction tallies, and telemetry/coverage are
# observers, not state.  ``base`` (long-log Multi-Paxos window offset) IS
# state: the same window contents at a different log position is a
# different point of the run.
_DIGEST_FIELDS = (
    "acceptor", "proposer", "requests", "replies", "promises", "accepted",
    "base",
)


@dataclasses.dataclass(frozen=True)
class CoverageConfig:
    """Static coverage knobs (frozen: rides ``SimConfig`` into jit).

    ``words`` is the per-lane bitmap size in int32 words (m = 32 * words
    Bloom bits); 0 — the default — disables the plane entirely (the state
    leaf prunes to ``None``).  Power-of-two words keep the in-kernel bit
    indexing to shifts and masks (no integer remainder on the Mosaic path).
    """

    words: int = 0

    def __post_init__(self):
        if self.words < 0:
            raise ValueError(f"coverage words must be >= 0, got {self.words}")
        if self.words and self.words & (self.words - 1):
            raise ValueError(
                f"coverage words must be a power of two (bit positions are "
                f"computed with masks, not remainders), got {self.words}"
            )

    def enabled(self) -> bool:
        return self.words > 0

    def bits(self) -> int:
        return 32 * self.words


@struct.dataclass
class CoverageState:
    """Per-lane coverage sketch (all int32, instance-minor, no scalars).

    ``bitmap`` is the lane's Bloom filter over its own visited-state
    digests; ``new_bits`` counts, cumulatively, how many bitmap bits each
    tick newly set — the on-device coverage-over-time signal whose
    per-chunk deltas draw the coverage curve.
    """

    bitmap: jnp.ndarray  # (W, I) int32 Bloom bit words
    new_bits: jnp.ndarray  # (I,) int32 cumulative newly-set bits

    @classmethod
    def init(cls, n_inst: int, ccfg: CoverageConfig) -> "CoverageState":
        return cls(
            bitmap=jnp.zeros((ccfg.words, n_inst), jnp.int32),
            new_bits=jnp.zeros((n_inst,), jnp.int32),
        )


def digest_tree(state) -> list:
    """The sub-pytree of ``state`` the coverage digest hashes.

    Collected by field name so all four protocols share one definition
    (fields a protocol lacks are skipped); see ``_DIGEST_FIELDS`` for the
    exclusion rationale.
    """
    return [
        leaf
        for name in _DIGEST_FIELDS
        if (leaf := getattr(state, name, None)) is not None
    ]


def lane_digest(tree) -> jnp.ndarray:
    """(I,) int32 hash of every array leaf's per-lane values.

    FNV-1a-style fold row by row (static leading indices, so the loop
    unrolls at trace time into elementwise xor/multiply — no reshapes, no
    gathers), then a splitmix32 finalizer.  Depends only on leaf VALUES:
    equal lane states produce equal digests regardless of lane or tick.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        raise ValueError("lane_digest needs at least one array leaf")
    n_inst = leaves[0].shape[-1]
    h = jnp.full((n_inst,), i32(0x811C9DC5))
    for leaf in leaves:
        x = leaf.astype(jnp.int32)
        for idx in itertools.product(*(range(d) for d in x.shape[:-1])):
            h = (h ^ x[idx]) * i32(_FNV_PRIME)
    h = h ^ shr(h, 16)
    h = h * i32(_MIX1)
    h = h ^ shr(h, 15)
    h = h * i32(_MIX2)
    h = h ^ shr(h, 16)
    return h


def _hash_pos(digest: jnp.ndarray, j: int, m: int) -> jnp.ndarray:
    """Bloom hash ``j`` of a digest -> bit position in [0, m) (m = 2^p)."""
    x = digest ^ i32(_H_SALTS[j])
    x = x * i32(_MIX1)
    x = x ^ shr(x, 15)
    x = x * i32(_MIX2)
    x = x ^ shr(x, 16)
    return x & jnp.int32(m - 1)


def observe(cov: CoverageState, state) -> CoverageState:
    """Fold the lane's post-tick state into its sketch (pure, PRNG-free).

    Bits are set with an iota-vs-word-index masked ``where`` (no scatter)
    and the newly-set count comes from one popcount of the xor delta —
    all Mosaic-clean elementwise int32 work.
    """
    digest = lane_digest(digest_tree(state))
    words = cov.bitmap.shape[0]
    m = 32 * words
    rows = jax.lax.broadcasted_iota(jnp.int32, cov.bitmap.shape, 0)
    bitmap = cov.bitmap
    for j in range(K_HASHES):
        pos = _hash_pos(digest, j, m)
        word_idx = shr(pos, 5)  # pos // 32
        bit = jnp.left_shift(jnp.int32(1), pos & jnp.int32(31))
        bitmap = bitmap | jnp.where(rows == word_idx[None], bit[None], 0)
    newly = jax.lax.population_count(bitmap ^ cov.bitmap).sum(
        axis=0, dtype=jnp.int32
    )
    return cov.replace(bitmap=bitmap, new_bits=cov.new_bits + newly)


# ---------------------------------------------------------------------------
# Bloom math (host side).


def bloom_estimate(m: int, k: int, bits_set: int) -> Optional[float]:
    """Distinct-insert estimate n̂ = -(m/k) ln(1 - X/m); None when saturated.

    The standard fill-fraction inversion: X of m bits set after n distinct
    k-hash inserts satisfies E[X] = m(1 - e^{-kn/m}).  A saturated sketch
    (X == m) carries no estimate — report the saturation fraction instead.
    """
    if bits_set >= m:
        return None
    if bits_set <= 0:
        return 0.0
    return -(m / k) * math.log(1.0 - bits_set / m)


def bloom_bound(m: int, k: int, n: int, z: float = 4.0) -> float:
    """Confidence band (±) on :func:`bloom_estimate` after n true inserts.

    The fill count X is approximately binomial with per-bit set probability
    p = 1 - e^{-kn/m}; propagating std(X) = sqrt(m p (1-p)) through the
    estimator's derivative dn̂/dX = m/(k(m-X)) gives the band.  ``z`` = 4
    keeps the calibration tests' false-failure odds negligible; the +2
    floor absorbs integer rounding at tiny n.
    """
    q = math.exp(-k * n / m)
    std_bits = math.sqrt(m * q * (1.0 - q))
    return z * std_bits / (k * q) + 2.0


# ---------------------------------------------------------------------------
# Host-side reference (pure-Python ints) — the calibration oracle.


def _u32(x: int) -> int:
    return x & 0xFFFFFFFF


def host_finalize(h: int) -> int:
    h = _u32(h)
    h ^= h >> 16
    h = _u32(h * _MIX1)
    h ^= h >> 15
    h = _u32(h * _MIX2)
    h ^= h >> 16
    return h


def host_hash_pos(digest: int, j: int, m: int) -> int:
    """Pure-Python mirror of :func:`_hash_pos` (same bits, no jax)."""
    x = _u32(digest) ^ _H_SALTS[j]
    x = _u32(x * _MIX1)
    x ^= x >> 15
    x = _u32(x * _MIX2)
    x ^= x >> 16
    return x & (m - 1)


def host_sketch_positions(values, words: int) -> set:
    """Exact union bit-position set after inserting every digest value."""
    m = 32 * words
    return {
        host_hash_pos(int(v), j, m)
        for v in values
        for j in range(K_HASHES)
    }


def host_sketch_estimate(values, words: int) -> Optional[float]:
    """Bloom estimate of ``len(set(values))`` via the exact host sketch."""
    return bloom_estimate(
        32 * words, K_HASHES, len(host_sketch_positions(values, words))
    )


# ---------------------------------------------------------------------------
# Summarize-boundary reductions (harness/run.py merges these into the one
# composite report pytree) and host formatting.


@lane_reduce("coverage_union")
def coverage_device(cov: CoverageState) -> dict:
    """Device half of the coverage report: reductions only, no transfer.

    Allowlisted cross-lane region (``lane_reduce`` tag): the union Bloom
    filter is the one place coverage legitimately mixes lanes.
    """
    # OR-reduce over lanes -> the union Bloom filter of every visited state.
    union = jax.lax.reduce(
        cov.bitmap, jnp.int32(0), jax.lax.bitwise_or, dimensions=[1]
    )
    return {
        "union_bits": jax.lax.population_count(union).sum(dtype=jnp.int32),
        "union_words": union,
        "lane_bits": jax.lax.population_count(cov.bitmap).sum(
            dtype=jnp.int32
        ),
        "new_bits": cov.new_bits.sum(dtype=jnp.int32),
    }


def union_hex(words_arr) -> str:
    """The union bitmap as one hex integer — the MERGEABLE sketch form.

    OR-ing two runs' values (``int(a, 16) | int(b, 16)``) is exactly the
    Bloom union of their visited sets; soak uses this for cross-seed
    coverage curves and a fleet aggregator can use it across hosts.
    """
    u = 0
    for i, w in enumerate(words_arr):
        u |= (int(w) & 0xFFFFFFFF) << (32 * i)
    return f"{u:x}"


def coverage_host(host: dict, words: int) -> dict:
    """Format a ``device_get``'d :func:`coverage_device` pytree."""
    m = 32 * words
    bits_set = int(host["union_bits"])
    est = bloom_estimate(m, K_HASHES, bits_set)
    return {
        "bits_set": bits_set,
        "bits_total": m,
        "words": words,
        "hashes": K_HASHES,
        "saturation": round(bits_set / m, 6) if m else 0.0,
        # None == saturated: the sketch can only lower-bound the state count.
        "est_states": None if est is None else round(est, 1),
        "lane_bits": int(host["lane_bits"]),
        "new_bits": int(host["new_bits"]),
        "union_hex": union_hex(host["union_words"]),
    }


def coverage_report(cov: CoverageState) -> dict:
    """Host-readable coverage summary (one blocking transfer; tests/CLI)."""
    return coverage_host(
        jax.device_get(coverage_device(cov)), int(cov.bitmap.shape[0])
    )
