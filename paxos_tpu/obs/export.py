"""Trace export — Chrome trace-event JSON (Perfetto-loadable) and span JSONL.

The Chrome trace-event format (loadable by Perfetto and chrome://tracing)
is the lingua franca of timeline tooling, so the span layer exports to it
directly:

- **Device track** (pid ``DEVICE_PID``): one thread per lane; each
  reconstructed round is an async begin/end pair (``ph: b/e``, one id per
  ballot attempt) so overlapping re-decodes nest cleanly, and every fault
  annotation is a thread-scoped instant event (``ph: i``).  Device time is
  tick-time: ``ts = tick * tick_us`` (default 1 tick = 1000 us, so
  Perfetto's ms ruler reads directly in ticks).
- **Host track** (pid ``HOST_PID``): the dispatch loop's wall-clock spans
  (``obs.host_spans``) as complete events (``ph: X``) plus instants —
  dispatch groups, done-flag probes, transfers, checkpoint writes, retry
  backoffs.  Host time is real microseconds from capture start.

The two tracks share one file but not one clock — dispatch spans carry
their tick window in ``args`` (``tick_start``/``ticks``), which is the
honest causal correlation between device-tick time and host wall time.

``validate_chrome_trace`` is the schema gate used by tests/test_obs.py and
``scripts/trace.sh``: required keys per phase, non-decreasing ``ts``, and
matched async begin/end pairs.
"""

from __future__ import annotations

import json
from typing import Any, Iterable, Optional

from paxos_tpu.obs.host_spans import HostSpanRecorder
from paxos_tpu.obs.spans import RoundSpan

DEVICE_PID = 0  # tick-time process track (one thread per lane)
HOST_PID = 1  # wall-clock process track (the dispatch loop)
TICK_US = 1000  # default device-time scale: 1 tick renders as 1 ms


def _meta(name: str, pid: int, tid: Optional[int] = None, label: str = "") -> dict:
    ev: dict[str, Any] = {
        "ph": "M", "name": name, "pid": pid, "ts": 0, "args": {"name": label},
    }
    if tid is not None:
        ev["tid"] = tid
    return ev


def chrome_trace_events(
    lane_spans: "dict[int, list[RoundSpan]]",
    host: Optional[HostSpanRecorder] = None,
    tick_us: int = TICK_US,
    counters: "Optional[dict[str, list[tuple[int, float]]]]" = None,
) -> list[dict]:
    """Flatten spans + host recorder into a sorted trace-event list.

    ``counters`` maps a series name to ``(tick, value)`` samples rendered
    as Chrome counter events (``ph: C``) on the device track — Perfetto
    draws each as a stepped area chart (e.g. the coverage-bits curve)
    aligned with the round spans in tick-time.
    """
    events: list[dict] = []
    if lane_spans or counters:
        events.append(_meta(
            "process_name", DEVICE_PID,
            label=f"device (ticks; 1 tick = {tick_us}us)",
        ))
    for name in sorted(counters or {}):
        for tick, value in counters[name]:
            events.append({
                "ph": "C", "cat": "counter", "name": name,
                "pid": DEVICE_PID, "ts": tick * tick_us,
                "args": {"value": value},
            })
    for lane in sorted(lane_spans):
        events.append(_meta("thread_name", DEVICE_PID, lane, f"lane {lane}"))
        for s in lane_spans[lane]:
            sid = f"L{lane}R{s.round}"
            args = {
                "outcome": s.outcome,
                "events": dict(sorted(s.events.items())),
                "faults": len(s.faults),
            }
            for k in ("p1_tick", "p2_tick", "leader_tick", "conflict_tick"):
                v = getattr(s, k)
                if v is not None:
                    args[k] = v
            common = {
                "cat": "round", "id": sid, "pid": DEVICE_PID, "tid": lane,
                "name": f"round {s.round}",
            }
            events.append({
                "ph": "b", "ts": s.start * tick_us, "args": args, **common,
            })
            # Exclusive end tick: a round closed the tick it opened still
            # renders one tick wide instead of vanishing at zero width.
            events.append({"ph": "e", "ts": (s.end + 1) * tick_us, **common})
            for f in s.faults:
                events.append({
                    "ph": "i", "s": "t", "cat": "fault", "name": f["kind"],
                    "pid": DEVICE_PID, "tid": lane, "ts": f["tick"] * tick_us,
                    "args": {"round": s.round},
                })
    if host is not None:
        events.append(_meta("process_name", HOST_PID, label="host (wall clock)"))
        events.append(_meta("thread_name", HOST_PID, 0, "dispatch loop"))
        for sp in host.spans:
            events.append({
                "ph": "X", "cat": "host", "name": sp["name"], "pid": HOST_PID,
                "tid": 0, "ts": sp["ts"], "dur": sp["dur"],
                "args": dict(sp["args"]),
            })
        for ins in host.instants:
            events.append({
                "ph": "i", "s": "t", "cat": "host", "name": ins["name"],
                "pid": HOST_PID, "tid": 0, "ts": ins["ts"],
                "args": dict(ins["args"]),
            })
    # Perfetto tolerates any order, but sorted-ts output makes the schema
    # check ("monotonic ts") and diffs deterministic.  Stable sort keeps
    # b-before-e for zero-length pairs and metadata first at ts 0.
    events.sort(key=lambda e: e["ts"])
    return events


def chrome_trace(
    lane_spans: "dict[int, list[RoundSpan]]",
    host: Optional[HostSpanRecorder] = None,
    tick_us: int = TICK_US,
    meta: Optional[dict] = None,
    counters: "Optional[dict[str, list[tuple[int, float]]]]" = None,
) -> dict:
    """The full Chrome trace JSON object (``traceEvents`` container)."""
    return {
        "traceEvents": chrome_trace_events(lane_spans, host, tick_us, counters),
        "displayTimeUnit": "ms",
        "otherData": dict(meta or {}),
    }


def write_chrome_trace(
    path: str,
    lane_spans: "dict[int, list[RoundSpan]]",
    host: Optional[HostSpanRecorder] = None,
    tick_us: int = TICK_US,
    meta: Optional[dict] = None,
    counters: "Optional[dict[str, list[tuple[int, float]]]]" = None,
) -> dict:
    """Write the trace to ``path``; returns the object written."""
    obj = chrome_trace(lane_spans, host, tick_us, meta, counters)
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return obj


FLEET_PID = 1  # the coordinator reuses the host (wall clock) pid slot
_WORKER_PID0 = 2  # worker process tracks start here, one pid per worker


def fleet_chrome_trace(
    timeline: dict,
    series_rows: "Iterable[dict]" = (),
    meta: Optional[dict] = None,
) -> dict:
    """One Perfetto trace for a whole fleet run — a track per worker.

    ``timeline`` is the coordinator's capture (all times epoch seconds):

    - ``t0`` — trace origin (everything renders relative to it),
    - ``instants`` — ``{"t", "name", "worker"?, "args"?}`` (spawn, claim,
      SIGKILL, reclaim, respawn, lease renewals); coordinator-side events
      (no worker) land on the coordinator track,
    - ``spans`` — ``{"worker", "record", "attempt", "t_start", "t_end"}``
      lease-held windows as async begin/end pairs (cat ``record``),
    - ``gauges`` — ``{"t", "gauges"}`` monitor-loop snapshots rendered as
      fleet-aggregate counter tracks (records_done / queue_depth /
      workers_alive).

    ``series_rows`` are RAW worker time-series rows (``obs.timeseries``,
    wall sidecar included): each worker gets ``union_bits`` and
    ``rounds_per_sec`` counter tracks stamped at the sidecar wall time.
    Output passes :func:`validate_chrome_trace`.
    """
    t0 = float(timeline.get("t0", 0.0))

    def ts(t: Any) -> int:
        return max(0, round((float(t) - t0) * 1e6))

    instants = list(timeline.get("instants", ()))
    spans = list(timeline.get("spans", ()))
    rows = [r for r in series_rows if r.get("event") == "sample"]
    workers = sorted(
        {str(e["worker"]) for e in instants if e.get("worker")}
        | {str(s["worker"]) for s in spans}
        | {str(r.get("worker", "?")) for r in rows}
    )
    pid_of = {w: _WORKER_PID0 + i for i, w in enumerate(workers)}

    events: list[dict] = [
        _meta("process_name", FLEET_PID, label="fleet coordinator"),
        _meta("thread_name", FLEET_PID, 0, "monitor loop"),
    ]
    for w in workers:
        events.append(_meta("process_name", pid_of[w], label=f"worker {w}"))
        events.append(_meta("thread_name", pid_of[w], 0, "lifecycle"))

    for snap in timeline.get("gauges", ()):
        g = snap.get("gauges", {})
        for key in ("records_done", "queue_depth", "workers_alive"):
            if key in g:
                events.append({
                    "ph": "C", "cat": "counter", "name": f"fleet_{key}",
                    "pid": FLEET_PID, "ts": ts(snap["t"]),
                    "args": {"value": g[key]},
                })

    for s in spans:
        w = str(s["worker"])
        b_ts = ts(s["t_start"])
        e_ts = max(b_ts, ts(s.get("t_end", s["t_start"])))
        common = {
            "cat": "record",
            "id": f"{w}/{s['record']}#{s.get('attempt', 0)}",
            "pid": pid_of[w], "tid": 0,
            "name": str(s["record"]),
        }
        events.append({
            "ph": "b", "ts": b_ts,
            "args": {"attempt": int(s.get("attempt", 0))}, **common,
        })
        events.append({"ph": "e", "ts": e_ts, **common})

    for ins in instants:
        w = ins.get("worker")
        events.append({
            "ph": "i", "s": "t", "cat": "fleet", "name": str(ins["name"]),
            "pid": pid_of[str(w)] if w else FLEET_PID, "tid": 0,
            "ts": ts(ins["t"]), "args": dict(ins.get("args", {})),
        })

    for r in rows:
        wall = r.get("wall")
        if not isinstance(wall, dict) or wall.get("t") is None:
            continue
        pid = pid_of[str(r.get("worker", "?"))]
        bits = r.get("gauges", {}).get("worker_union_bits")
        if bits is not None:
            events.append({
                "ph": "C", "cat": "counter", "name": "union_bits",
                "pid": pid, "ts": ts(wall["t"]), "args": {"value": bits},
            })
        if wall.get("rps") is not None:
            events.append({
                "ph": "C", "cat": "counter", "name": "rounds_per_sec",
                "pid": pid, "ts": ts(wall["t"]),
                "args": {"value": wall["rps"]},
            })

    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": dict(meta or {}),
    }


def spans_jsonl(spans: Iterable[RoundSpan]) -> str:
    """Compact one-span-per-line JSONL — the programmatic-diff format."""
    return "".join(
        json.dumps(s.to_json(), sort_keys=True) + "\n" for s in spans
    )


# Keys every event must carry, plus per-phase extras.
_REQUIRED_COMMON = ("ph", "name", "pid", "ts")
_REQUIRED_BY_PH = {
    "b": ("cat", "id", "tid"),
    "e": ("cat", "id", "tid"),
    "X": ("dur", "tid"),
    "i": ("s",),
    "M": ("args",),
    "C": ("args",),
}


def validate_chrome_trace(obj: Any) -> list[str]:
    """Schema-check a Chrome trace object; returns error strings (empty = ok).

    Checks: container shape, required keys per phase, non-decreasing
    ``ts`` across the event list, and async begin/end discipline (every
    ``e`` follows a matching ``b`` of the same (cat, id, pid); none left
    open at the end).
    """
    errors: list[str] = []
    if not isinstance(obj, dict) or not isinstance(obj.get("traceEvents"), list):
        return ["top level must be a dict with a 'traceEvents' list"]
    last_ts = None
    open_async: dict[tuple, int] = {}
    for i, ev in enumerate(obj["traceEvents"]):
        if not isinstance(ev, dict):
            errors.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        missing = [k for k in _REQUIRED_COMMON if k not in ev]
        missing += [k for k in _REQUIRED_BY_PH.get(ph, ()) if k not in ev]
        if missing:
            errors.append(f"event {i} (ph={ph!r}): missing keys {missing}")
            continue
        ts = ev["ts"]
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"event {i}: bad ts {ts!r}")
            continue
        if last_ts is not None and ts < last_ts:
            errors.append(f"event {i}: ts {ts} < previous {last_ts}")
        last_ts = ts
        if ph == "b":
            key = (ev["cat"], ev["id"], ev["pid"])
            open_async[key] = open_async.get(key, 0) + 1
        elif ph == "e":
            key = (ev["cat"], ev["id"], ev["pid"])
            if open_async.get(key, 0) <= 0:
                errors.append(f"event {i}: async end without begin for {key}")
            else:
                open_async[key] -= 1
        elif ph == "X" and ev["dur"] < 0:
            errors.append(f"event {i}: negative dur {ev['dur']}")
    for key, n in sorted(open_async.items()):
        if n:
            errors.append(f"async begin without end for {key} (x{n})")
    return errors
