"""Fault-exposure accounting plane (default OFF, off is free).

The fuzzer samples fault masks blind: a drop mask fires whether or not a
message was in flight, a corruption mask fires whether or not anything read
the corrupted payload.  A soak that reports "1e8 clean rounds under chaos"
is therefore unfalsifiable until someone counts how many sampled faults
actually *touched* the protocol.  This module makes that count a
first-class observable: per-lane packed counters of faults **injected**
(the mask fired) vs **effective** (the fault changed something a protocol
participant did or saw), per fault class — the measured denominator behind
any "soaked clean" claim and the prerequisite for feedback-directed fault
scheduling.

Class semantics (the injected / effective pair per class):

- ``drop``       sampled drop decisions on send edges / live in-flight
                 messages actually discarded by those decisions.
- ``dup``        slots flagged for redelivery / flagged slots that held a
                 message being consumed this tick (a duplicate actually
                 re-enters flight).
- ``corrupt``    corruption masks sampled / corruptions applied to a
                 payload some acceptor read this tick.
- ``partition``  link-directions cut this tick / in-flight messages the
                 cut actually stalled this tick.
- ``timeout``    proposer slots carrying a nonzero timer skew / slots
                 whose expiry decision this tick DIFFERS from the
                 unskewed timer's decision.
- ``stale``      stale-snapshot restores taken (injected == effective:
                 every restore rewrites durable state).
- ``delay``      nonzero delay latencies sampled on send edges / in-flight
                 messages actually stalled behind their ``until`` stamp
                 this tick.

The default-off-is-free contract (``core.telemetry`` / ``obs.coverage``
are the templates):

- :class:`FaultExposure` rides as an ``Optional`` leaf of every protocol
  state; ``None`` when disabled (pruned from the pytree), all leaves int32
  with a trailing ``instances`` axis, no scalar leaves — the fused Pallas
  engine's generic passthrough codec (``utils/bitops``) carries it with
  ZERO kernel changes, and ``pjit`` shards it with the rest of the state.
- :func:`record` is pure int32 arithmetic over signals the tick already
  produced: **no PRNG draws**, so enabling exposure cannot perturb a
  schedule.  The static auditor holds the module to that
  (``prng_audit.audit_exposure_parity`` on the "exposure" audit config).
- Mosaic-clean: elementwise int32 ops and an iota-masked ``where`` instead
  of scatter — the same op diet as telemetry.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from paxos_tpu.core.telemetry import lane_count

# Fault classes, in counter-row order.  The order is part of the on-device
# layout (row c of the packed counters is CLASSES[c]) — append only.
CLASSES = ("drop", "dup", "corrupt", "partition", "timeout", "stale",
           "delay")


@dataclasses.dataclass(frozen=True)
class ExposureConfig:
    """Static exposure knob (frozen: rides ``SimConfig`` into jit).

    ``counters=False`` — the default — disables the plane entirely (the
    state leaf prunes to ``None``, zero bytes on device, bit-identical
    schedules).
    """

    counters: bool = False

    def enabled(self) -> bool:
        return self.counters


@struct.dataclass
class FaultExposure:
    """Per-lane packed fault-exposure counters (int32, instance-minor).

    Row ``c`` of both arrays is fault class ``CLASSES[c]``; counts
    accumulate per tick and reduce at the summarize boundary.  No scalar
    leaves: the fused engine's packed-word passthrough requires every
    observer leaf to carry the trailing instances axis.
    """

    injected: jnp.ndarray  # (C, I) int32 — sampled fault events per class
    effective: jnp.ndarray  # (C, I) int32 — events that actually fired

    @classmethod
    def init(cls, n_inst: int) -> "FaultExposure":
        shape = (len(CLASSES), n_inst)
        return cls(
            injected=jnp.zeros(shape, jnp.int32),
            effective=jnp.zeros(shape, jnp.int32),
        )


def _accumulate(arr: jnp.ndarray, counts: dict) -> jnp.ndarray:
    """Add per-class (I,) counts into their rows (iota-select, no scatter)."""
    row = jax.lax.broadcasted_iota(jnp.int32, arr.shape, 0)
    inc = jnp.zeros_like(arr)
    for c, name in enumerate(CLASSES):
        v = counts.get(name)
        if v is None:
            continue
        v = lane_count(v)
        inc = inc + jnp.where(row == c, v[None], 0)
    return arr + inc


def record(exp: FaultExposure, **classes) -> FaultExposure:
    """Fold one tick's per-class ``(injected, effective)`` pairs into ``exp``.

    Each keyword is a fault class name from :data:`CLASSES` mapped to a
    2-tuple ``(injected, effective)``; each element is a bool event array
    (any leading axes, trailing instances axis — reduced via
    ``telemetry.lane_count``), an (I,) int32 count, or ``None`` for zero.
    Omitted classes (knob off this config) add nothing, so a disabled
    knob leaves zero extra work in the traced tick.
    """
    unknown = set(classes) - set(CLASSES)
    if unknown:
        raise ValueError(f"unknown exposure classes: {sorted(unknown)}")
    inj = {k: v[0] for k, v in classes.items() if v is not None}
    eff = {k: v[1] for k, v in classes.items() if v is not None}
    return exp.replace(
        injected=_accumulate(exp.injected, inj),
        effective=_accumulate(exp.effective, eff),
    )


# ---------------------------------------------------------------------------
# Summarize-boundary reductions (harness/run.py merges these into the one
# composite report pytree) and host formatting.


def exposure_device(exp: FaultExposure) -> dict:
    """Device half of the exposure report: reductions only, no transfer."""
    return {
        "injected": exp.injected.sum(axis=-1, dtype=jnp.int32),  # (C,)
        "effective": exp.effective.sum(axis=-1, dtype=jnp.int32),  # (C,)
        # Per class: how many lanes saw at least one effective fault — the
        # breadth of the exposure, vs the totals' depth.
        "lanes_exposed": (exp.effective > 0).astype(jnp.int32).sum(
            axis=-1, dtype=jnp.int32
        ),
    }


def exposure_host(host: dict) -> dict:
    """Format a ``device_get``'d :func:`exposure_device` pytree."""
    classes = {}
    for c, name in enumerate(CLASSES):
        classes[name] = {
            "injected": int(host["injected"][c]),
            "effective": int(host["effective"][c]),
            "lanes_exposed": int(host["lanes_exposed"][c]),
        }
    return {"classes": classes}


def exposure_report(exp: FaultExposure) -> dict:
    """Host-readable exposure summary (one blocking transfer; tests/CLI)."""
    return exposure_host(jax.device_get(exposure_device(exp)))


def annotate_lit(report: dict, fcfg) -> dict:
    """Join an exposure report with the config's lit fault knobs.

    Adds ``lit`` (classes whose knob is on) and ``vacuous`` (lit classes
    whose effective count is zero — "vacuous chaos": the knob burned
    randomness without ever touching the protocol).  Separated from
    :func:`exposure_host` because the summarize boundary sees only the
    state pytree; callers that hold the :class:`FaultConfig` (CLI, soak)
    apply the join.
    """
    from paxos_tpu.faults.injector import exposure_lit

    lit = exposure_lit(fcfg)
    out = dict(report)
    out["lit"] = sorted(n for n, on in lit.items() if on)
    out["vacuous"] = sorted(
        n
        for n, on in lit.items()
        if on and report["classes"][n]["effective"] == 0
    )
    return out


# ---------------------------------------------------------------------------
# Attribution: join per-chunk exposure deltas with the coverage plane and
# the safety checker (host side; the `paxos_tpu exposure` subcommand and
# soak build the chunk stream).


def effective_delta(prev: Optional[dict], cur: dict) -> dict:
    """Per-class effective-count delta between two exposure reports."""
    out = {}
    for name in CLASSES:
        before = prev["classes"][name]["effective"] if prev else 0
        out[name] = cur["classes"][name]["effective"] - before
    return out


def attribution(chunks: list) -> dict:
    """Per-class attribution table over a campaign's chunk stream.

    ``chunks`` is a list of per-chunk records, each carrying
    ``effective_delta`` (per-class effective counts this chunk, from
    :func:`effective_delta`), optional ``new_bits`` (coverage bits the
    chunk newly set), and optional ``violations_delta``.  A chunk's
    new_bits/violations are attributed to EVERY class effective in it —
    chunk-granular co-occurrence, not causality; the table answers "which
    fault classes were live while exploration/violations happened", which
    is the honest claim chunk-boundary sampling can support.
    """
    table = {
        name: {
            "chunks_active": 0,
            "effective": 0,
            "new_bits": 0,
            "violations": 0,
        }
        for name in CLASSES
    }
    for ch in chunks:
        for name in CLASSES:
            d = ch.get("effective_delta", {}).get(name, 0)
            if d <= 0:
                continue
            row = table[name]
            row["chunks_active"] += 1
            row["effective"] += d
            if ch.get("new_bits") is not None:
                row["new_bits"] += ch["new_bits"]
            row["violations"] += ch.get("violations_delta", 0)
    return table
