"""Host-side wall-clock spans for the dispatch loop.

The device timeline (``obs.spans``) is tick-time; the host loop — dispatch
groups, done-flag probes, device->host transfers, checkpoint writes, retry
backoffs — is wall-clock time.  :class:`HostSpanRecorder` captures the
host side so the exporter (``obs.export``) can merge both onto one
Perfetto view, each on its own process track.

The clock is INJECTED: this module never imports ``time`` — the harness
layer (which legitimately owns wall clocks) passes a monotonic clock
callable in, keeping the whole ``obs`` package inside the static
auditor's no-entropy/no-clock purity scope (``analysis/purity``).  Span
records are plain dicts with microsecond offsets from the recorder's
birth, ready for the Chrome trace-event ``X``/``i`` phases.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable, Iterator, Optional


class HostSpanRecorder:
    """Collect wall-clock spans and instants from the host loop.

    ``clock`` is a monotonic seconds-returning callable (the harness
    passes ``time.perf_counter``).  ``span`` is a context manager — spans
    may nest (rendered stacked on the host track); ``instant`` marks a
    point event.  All timestamps are integer microseconds since the
    recorder was constructed.
    """

    def __init__(self, clock: Callable[[], float]):
        self._clock = clock
        self._t0 = clock()
        self.spans: list[dict[str, Any]] = []  # {"name","ts","dur","args"}
        self.instants: list[dict[str, Any]] = []  # {"name","ts","args"}

    def now_us(self) -> int:
        return int(round((self._clock() - self._t0) * 1e6))

    @contextlib.contextmanager
    def span(self, name: str, **args: Any) -> Iterator[None]:
        ts = self.now_us()
        try:
            yield
        finally:
            self.spans.append({
                "name": name, "ts": ts,
                "dur": max(self.now_us() - ts, 0), "args": args,
            })

    def instant(self, name: str, **args: Any) -> None:
        self.instants.append({"name": name, "ts": self.now_us(), "args": args})


class NullSpanRecorder:
    """No-op stand-in so hot loops can write ``spans.span(...)`` unguarded."""

    @contextlib.contextmanager
    def span(self, name: str, **args: Any) -> Iterator[None]:
        yield

    def instant(self, name: str, **args: Any) -> None:
        pass


def ensure_recorder(
    spans: "Optional[HostSpanRecorder]",
) -> "HostSpanRecorder | NullSpanRecorder":
    """The harness-facing guard: ``None`` becomes the no-op recorder."""
    return spans if spans is not None else NullSpanRecorder()
