"""Near-miss safety-margin plane (default OFF, off is free).

The planes so far measure novelty (obs.coverage), fault effectiveness
(obs.exposure) and speed (harness.profile) — none of them measures
*danger*.  A campaign that drove a second value to within one accept of
being chosen is indistinguishable from one that never contested an
instance: both report ``violations == 0``.  This module tracks per-lane
**distance to violation** live on device, the fitness signal a
feedback-directed fuzzer rewards (ROADMAP item 1): how close did this
seed get, not just what did it find.

Counter semantics (all running extrema over ticks, per lane):

- ``qslack_min``   minimum **quorum slack**: ``slot_quorum - votes`` for
                   the best *competing* learner-table row — a live
                   (ballot, value) pair on a decided instance whose value
                   differs from the chosen one.  0 ⟺ an agreement
                   violation actually fired; 1 ⟺ one accept short of
                   disagreement.  ``SENTINEL`` while no competitor exists.
- ``near_split``   count of ticks where two distinct values each sat
                   within slack <= 1 on the same instance (same log slot
                   for Multi-Paxos) — contested razor-edge ticks.
- ``bal_gap_min``  minimum **ballot-race margin**: winning-row ballot
                   minus the best rival row's ballot, taken on the tick
                   an instance (or slot) decides.  Small gap = the decide
                   barely outran a competing ballot.  ``SENTINEL`` when
                   every decide was unopposed.
- ``promise_slack_min``  minimum **checker headroom** on the acceptor
                   invariant: ``promised - accepted_ballot`` over honest
                   acceptors with a live accepted pair (Raft:
                   ``voted - entry_term``).  0 = accepts landing exactly
                   at the promise fence; negative would already be an
                   invariant violation.

The fourth headroom signal — learner-table eviction pressure — already
lives in ``LearnerState.evictions`` and is surfaced (with the
``checker_complete`` gauge) at the summarize boundary, not duplicated
here.  Preemption depth at decide comes from the span plane
(``obs.spans.span_aggregates``) and is joined host-side by the CLI.

The default-off-is-free contract (``obs.exposure`` is the template):

- :class:`MarginState` rides as an ``Optional`` leaf of every protocol
  state; ``None`` when disabled (pruned from the pytree), all leaves
  int32 with a trailing ``instances`` axis, no scalar leaves — the fused
  Pallas engine's generic passthrough codec (``utils/bitops``) carries it
  with ZERO kernel changes.
- The fold (``check.safety.margin_observe`` /
  ``check.mp_safety.mp_margin_observe`` — beside the learner they read)
  is pure int32 arithmetic over the post-observe learner table and the
  post-tick acceptor state: **no PRNG draws**, so enabling the plane
  cannot perturb a schedule.  The static auditor holds it to that
  (``prng_audit.audit_margin_parity`` on the "margin" audit config).
- Mosaic-clean: elementwise int32 ops, masked min/max reductions over
  the small leading axes — no gathers, no scatters, no first_true.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

# "No competitor observed" marker for the running minima.  int32 max, so
# jnp.minimum folds replace it with the first real observation; kept raw
# on device (host formatting maps it to None) so the numpy replay oracle
# can compare leaves bit for bit.
SENTINEL = 0x7FFFFFFF


@dataclasses.dataclass(frozen=True)
class MarginConfig:
    """Static margin knob (frozen: rides ``SimConfig`` into jit).

    ``counters=False`` — the default — disables the plane entirely (the
    state leaf prunes to ``None``, zero bytes on device, bit-identical
    schedules).
    """

    counters: bool = False

    def enabled(self) -> bool:
        return self.counters


@struct.dataclass
class MarginState:
    """Per-lane distance-to-violation sketch (int32, instance-minor).

    Running minima start at :data:`SENTINEL`; ``near_split`` is a plain
    tick counter.  No scalar leaves: the fused engine's packed-word
    passthrough requires every observer leaf to carry the trailing
    instances axis.
    """

    qslack_min: jnp.ndarray  # (I,) int32 — min quorum slack of best rival
    near_split: jnp.ndarray  # (I,) int32 — ticks with a contested razor edge
    bal_gap_min: jnp.ndarray  # (I,) int32 — min winner-vs-rival ballot gap
    promise_slack_min: jnp.ndarray  # (I,) int32 — min promised - accepted

    @classmethod
    def init(cls, n_inst: int) -> "MarginState":
        # Fresh buffer per field: aliased leaves break buffer donation.
        def full():
            return jnp.full((n_inst,), SENTINEL, jnp.int32)

        return cls(
            qslack_min=full(),
            near_split=jnp.zeros((n_inst,), jnp.int32),
            bal_gap_min=full(),
            promise_slack_min=full(),
        )


# ---------------------------------------------------------------------------
# Summarize-boundary reductions (harness/run.py merges these into the one
# composite report pytree) and host formatting.


def margin_device(m: MarginState) -> dict:
    """Device half of the margin report: reductions only, no transfer."""
    return {
        "min_quorum_slack": m.qslack_min.min(),
        # Lanes whose tightest rival came within one accept of quorum —
        # the near-miss population (includes actual violations, slack 0).
        "near_miss_lanes": (m.qslack_min <= 1).astype(jnp.int32).sum(
            dtype=jnp.int32
        ),
        "zero_slack_lanes": (m.qslack_min == 0).astype(jnp.int32).sum(
            dtype=jnp.int32
        ),
        # Lanes where a competing (ballot, value) row existed at all.
        "contested_lanes": (m.qslack_min < SENTINEL).astype(jnp.int32).sum(
            dtype=jnp.int32
        ),
        "near_split_ticks": m.near_split.sum(dtype=jnp.int32),
        "near_split_lanes": (m.near_split > 0).astype(jnp.int32).sum(
            dtype=jnp.int32
        ),
        "min_ballot_gap": m.bal_gap_min.min(),
        "min_promise_slack": m.promise_slack_min.min(),
    }


# Report keys whose SENTINEL means "never observed" (host shows None).
_MIN_KEYS = ("min_quorum_slack", "min_ballot_gap", "min_promise_slack")


def margin_host(host: dict) -> dict:
    """Format a ``device_get``'d :func:`margin_device` pytree."""
    out = {}
    for k, v in host.items():
        v = int(v)
        out[k] = None if (k in _MIN_KEYS and v == SENTINEL) else v
    return out


def margin_report(m: MarginState) -> dict:
    """Host-readable margin summary (one blocking transfer; tests/CLI)."""
    return margin_host(jax.device_get(margin_device(m)))


def lane_ranking(m: MarginState, top: int = 8) -> list:
    """Host-side top-N tightest lanes: (lane, slack, near_split_ticks).

    One transfer; soak's per-seed near-miss ranking and the shrink
    annotation use this to name the lanes worth re-fuzzing.
    """
    import numpy as np

    qs = np.asarray(jax.device_get(m.qslack_min))
    ns = np.asarray(jax.device_get(m.near_split))
    order = np.lexsort((-ns, qs))  # tightest slack first, then most contested
    out = []
    for lane in order[: max(0, int(top))]:
        if qs[lane] >= SENTINEL and ns[lane] == 0:
            break  # rest of the order is uncontested lanes
        out.append(
            {
                "lane": int(lane),
                "min_quorum_slack": None if qs[lane] >= SENTINEL else int(qs[lane]),
                "near_split_ticks": int(ns[lane]),
            }
        )
    return out


# ---------------------------------------------------------------------------
# Correlation: join the per-chunk min-slack curve with the coverage plane
# and the exposure plane (host side; the `paxos_tpu margin` subcommand and
# soak build the chunk stream).


def correlation(chunks: list) -> dict:
    """Margin-vs-progress co-occurrence table over a campaign's chunks.

    ``chunks`` is a list of per-chunk records, each carrying
    ``tightened`` (did the running min slack drop or the near-miss lane
    count grow this chunk), optional ``new_bits`` (coverage),
    ``effective_total`` (exposure effective-fault delta) and
    ``violations_delta``.  Chunk-granular co-occurrence, not causality:
    the table answers "when margins tightened, were exploration and
    effective faults also moving" — the honest claim chunk-boundary
    sampling can support, and the shape the exposure attribution table
    established.
    """
    table = {
        key: {"chunks": 0, "new_bits": 0, "effective": 0, "violations": 0}
        for key in ("tightened", "flat")
    }
    for ch in chunks:
        row = table["tightened" if ch.get("tightened") else "flat"]
        row["chunks"] += 1
        if ch.get("new_bits") is not None:
            row["new_bits"] += ch["new_bits"]
        row["effective"] += ch.get("effective_total", 0)
        row["violations"] += ch.get("violations_delta", 0)
    return table


# ---------------------------------------------------------------------------
# Host numpy replay oracle (PR 9 style): the same fold, in numpy, over
# device_get'd learner/acceptor snapshots.  tests/test_margin.py replays a
# margin-OFF campaign tick by tick through these and compares the final
# device leaves bit for bit — margin-on cannot perturb the schedule, so
# the off-trajectory is the on-trajectory.


def np_margin_tick(
    counters: dict,
    pre: dict,
    post: dict,
    promised,
    acc_bal,
    honest,
    quorum: int,
    fast_quorum: Optional[int] = None,
    fast_round=None,
) -> dict:
    """One tick of the single-table margin fold, in numpy.

    ``pre``/``post`` are dicts of LearnerState leaves (numpy);
    ``fast_round`` is a (K, I) bool mask of fast-round table ballots when
    ``fast_quorum`` is set (the caller derives it from ``ballot_round``).
    Returns the updated ``counters`` dict of four (I,) int64/32 arrays.
    """
    import numpy as np

    lt_bal, lt_val, lt_mask = post["lt_bal"], post["lt_val"], post["lt_mask"]
    votes = _np_popcount(lt_mask)
    if fast_quorum is None:
        sq = np.full(lt_bal.shape, quorum, np.int32)
    else:
        sq = np.where(fast_round, fast_quorum, quorum).astype(np.int32)
    live = lt_bal > 0

    competing = live & post["chosen"][None] & (lt_val != post["chosen_val"][None])
    slack = np.maximum(sq - votes, 0)
    tick_slack = np.where(competing, slack, SENTINEL).min(axis=0)
    qslack_min = np.minimum(counters["qslack_min"], tick_slack)

    hot = live & (votes >= sq - 1)
    vmin = np.where(hot, lt_val, SENTINEL).min(axis=0)
    vmax = np.where(hot, lt_val, 0).max(axis=0)
    near = (hot.sum(axis=0) >= 2) & (vmin != vmax)
    near_split = counters["near_split"] + near.astype(np.int32)

    decided_now = post["chosen"] & ~pre["chosen"]
    chosen_rows = votes >= sq
    win_rows = chosen_rows & live & (lt_val == post["chosen_val"][None])
    win_bal = np.where(win_rows, lt_bal, 0).max(axis=0)
    rival_bal = np.where(live & ~win_rows, lt_bal, 0).max(axis=0)
    gap = np.maximum(win_bal - rival_bal, 0)
    tick_gap = np.where(decided_now & (rival_bal > 0), gap, SENTINEL)
    bal_gap_min = np.minimum(counters["bal_gap_min"], tick_gap)

    pslack = np.where(honest & (acc_bal > 0), promised - acc_bal, SENTINEL).min(
        axis=0
    )
    promise_slack_min = np.minimum(counters["promise_slack_min"], pslack)

    return {
        "qslack_min": qslack_min.astype(np.int32),
        "near_split": near_split.astype(np.int32),
        "bal_gap_min": bal_gap_min.astype(np.int32),
        "promise_slack_min": promise_slack_min.astype(np.int32),
    }


def np_mp_margin_tick(
    counters: dict,
    pre: dict,
    post: dict,
    promised,
    acc_bal,
    honest,
    quorum: int,
) -> dict:
    """One tick of the Multi-Paxos (L, K, I) margin fold, in numpy.

    ``pre``/``post`` hold MPLearnerState leaves (``lt_bv`` packed);
    ``acc_bal`` is the per-acceptor max accepted ballot over the log.
    """
    import numpy as np

    from paxos_tpu.core.mp_state import bv_bal, bv_val

    bal = bv_bal(post["lt_bv"])
    val = bv_val(post["lt_bv"])
    votes = _np_popcount(post["lt_mask"])
    live = post["lt_bv"] > 0

    competing = (
        live & post["chosen"][:, None] & (val != post["chosen_val"][:, None])
    )
    slack = np.maximum(quorum - votes, 0)
    tick_slack = np.where(competing, slack, SENTINEL).min(axis=(0, 1))
    qslack_min = np.minimum(counters["qslack_min"], tick_slack)

    hot = live & (votes >= quorum - 1)
    vmin = np.where(hot, val, SENTINEL).min(axis=1)  # (L, I)
    vmax = np.where(hot, val, 0).max(axis=1)
    near = ((hot.sum(axis=1) >= 2) & (vmin != vmax)).any(axis=0)
    near_split = counters["near_split"] + near.astype(np.int32)

    decided_now = post["chosen"] & ~pre["chosen"]  # (L, I)
    chosen_rows = votes >= quorum
    win_rows = chosen_rows & live & (val == post["chosen_val"][:, None])
    win_bal = np.where(win_rows, bal, 0).max(axis=1)  # (L, I)
    rival_bal = np.where(live & ~win_rows, bal, 0).max(axis=1)
    gap = np.maximum(win_bal - rival_bal, 0)
    tick_gap = np.where(decided_now & (rival_bal > 0), gap, SENTINEL).min(
        axis=0
    )
    bal_gap_min = np.minimum(counters["bal_gap_min"], tick_gap)

    pslack = np.where(honest & (acc_bal > 0), promised - acc_bal, SENTINEL).min(
        axis=0
    )
    promise_slack_min = np.minimum(counters["promise_slack_min"], pslack)

    return {
        "qslack_min": qslack_min.astype(np.int32),
        "near_split": near_split.astype(np.int32),
        "bal_gap_min": bal_gap_min.astype(np.int32),
        "promise_slack_min": promise_slack_min.astype(np.int32),
    }


def np_margin_init(n_inst: int) -> dict:
    import numpy as np

    return {
        "qslack_min": np.full((n_inst,), SENTINEL, np.int32),
        "near_split": np.zeros((n_inst,), np.int32),
        "bal_gap_min": np.full((n_inst,), SENTINEL, np.int32),
        "promise_slack_min": np.full((n_inst,), SENTINEL, np.int32),
    }


def _np_popcount(x):
    import numpy as np

    x = np.asarray(x, np.uint32)
    count = np.zeros(x.shape, np.int32)
    for shift in range(32):
        count += ((x >> shift) & 1).astype(np.int32)
    return count
