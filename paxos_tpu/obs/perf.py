"""Performance observability plane — host-side throughput and occupancy.

Six observability layers watch correctness and faults; this one watches
*performance*.  It is a pure decode layer over the host span stream
(``obs.host_spans.HostSpanRecorder``): the harness wraps every device
dispatch, done-flag probe, and report drain in wall-clock spans, and this
module derives the live gauges —

- instance-rounds/sec (cumulative, steady-state, and windowed),
- pipeline occupancy (fraction of loop wall time with a dispatch in
  flight or a device wait in progress, vs host bookkeeping gaps),
- per-chunk wall-time percentiles (p50/p95/p99),
- compile vs steady-state split (the first dispatch's enqueue blocks on
  JIT compilation; later enqueues do not),
- VMEM-footprint and roofline occupancy (from the ``fit_block`` budget
  and the recorded ROOFLINE.json ceilings).

It also owns the bench-provenance contract: the structured ``BENCH_r*``
row schema (:data:`BENCH_ROW_SCHEMA`, :func:`validate_bench_row`) and the
noise-aware regression comparison behind ``paxos_tpu bench-compare``
(:func:`compare_benches`).

Clock doctrine (purity lint): this module never reads a clock, a file, or
an RNG — it consumes span dicts whose timestamps came from the recorder's
*injected* clock, so the whole plane is replayable from a recorded span
list and ``obs`` stays in TRACED_PACKAGES.  Everything is host-side:
zero new device ops, zero PRNG draws, schedules untouched.

Async-dispatch caveat, documented once here: JAX dispatch is asynchronous,
so a "dispatch" span measures *enqueue* time (plus compile on the first
call) while the device keeps running; blocking spans ("probe", "report",
"report_drain") are where device time becomes visible to the host.  The
gauges are therefore the host's view of the pipeline — exactly the view
that matters for dispatch-boundary overhead, which is the gap the perf
roadmap items chase.
"""

from __future__ import annotations

import math
import statistics
from typing import Any, Optional

# Span names that mean "the host is driving or waiting on the device".
# These never nest inside one another (campaign_finalize nests report_drain,
# so only the inner one is counted), which makes their durations additive.
DISPATCH_SPAN = "dispatch"
WAIT_SPANS = frozenset(
    {"probe", "report", "report_transfer_start", "report_drain"}
)
BUSY_SPANS = frozenset({DISPATCH_SPAN}) | WAIT_SPANS


def _span_list(spans) -> list[dict]:
    """Accept a HostSpanRecorder or a raw span-dict list."""
    return list(getattr(spans, "spans", spans) or [])


def percentile(values, q: float):
    """Nearest-rank percentile (q in [0, 1]); None on empty input.

    Pure and deterministic — the same discipline as the tick-domain
    quantiles in ``obs.spans`` but over float microseconds.
    """
    vs = sorted(values)
    if not vs:
        return None
    k = max(0, min(len(vs) - 1, math.ceil(q * len(vs)) - 1))
    return vs[k]


def _dispatches(sl: list[dict]) -> list[dict]:
    return [s for s in sl if s["name"] == DISPATCH_SPAN]


def _busy(sl: list[dict]) -> list[dict]:
    return [s for s in sl if s["name"] in BUSY_SPANS]


def _loop_end_us(sl: list[dict]) -> int:
    """End of the last busy span — the loop's wall-clock end."""
    return max(s["ts"] + s["dur"] for s in _busy(sl))


def chunk_latencies_us(spans) -> list[float]:
    """Per-chunk wall time in µs, one sample per chunk body.

    A dispatch covering ``groups`` chunks contributes ``groups`` equal
    samples of (interval to the next dispatch start) / groups — the
    host-observed cadence, which folds in any blocking probe between the
    two dispatches.  The trailing dispatch's interval runs to the end of
    the last busy span (its drain), since no successor start exists.
    """
    sl = _span_list(spans)
    disp = _dispatches(sl)
    if not disp:
        return []
    end = _loop_end_us(sl)
    out: list[float] = []
    for s, nxt in zip(disp, disp[1:] + [None]):
        interval = (nxt["ts"] if nxt is not None else end) - s["ts"]
        g = max(1, int(s.get("args", {}).get("groups", 1)))
        out.extend([interval / g] * g)
    return out


def perf_summary(spans, n_inst: int, *, window: int = 8) -> dict[str, Any]:
    """Derive the perf-plane gauges from a recorded span stream.

    ``n_inst`` converts ticks to instance-rounds (one tick advances every
    instance by one protocol round).  ``window`` sizes the trailing-window
    throughput gauge (last ``window`` dispatches) — the live "now" signal
    a soak trend wants, vs the cumulative average that buries a slowdown.

    Returns a JSON-ready dict; ``{"dispatches": 0}`` when the stream holds
    no dispatch spans (perf off, or a loop that never ran).
    """
    sl = _span_list(spans)
    disp = _dispatches(sl)
    if not disp:
        return {"dispatches": 0, "rounds_total": 0}

    def rounds(s: dict) -> int:
        return n_inst * int(s.get("args", {}).get("ticks", 0))

    t0 = disp[0]["ts"]
    end = _loop_end_us(sl)
    wall_us = max(0, end - t0)
    busy_us = sum(s["dur"] for s in _busy(sl) if s["ts"] >= t0)
    dispatch_us = sum(s["dur"] for s in disp)
    wait_us = sum(
        s["dur"] for s in sl if s["name"] in WAIT_SPANS and s["ts"] >= t0
    )
    total_rounds = sum(rounds(s) for s in disp)

    def rate(r: int, us: float) -> float:
        return r / (us / 1e6) if us > 0 else 0.0

    out: dict[str, Any] = {
        "dispatches": len(disp),
        "chunks": sum(
            max(1, int(s.get("args", {}).get("groups", 1))) for s in disp
        ),
        "rounds_total": total_rounds,
        "wall_s": round(wall_us / 1e6, 6),
        # First enqueue blocks on JIT compile; steady enqueues don't.  An
        # upper-bound attribution (tracing work rides in the same span).
        "compile_s": round(disp[0]["dur"] / 1e6, 6),
        "dispatch_enqueue_s": round(dispatch_us / 1e6, 6),
        "probe_wait_s": round(wait_us / 1e6, 6),
        "occupancy": (
            round(min(1.0, max(0.0, busy_us / wall_us)), 4)
            if wall_us > 0
            else 0.0
        ),
        "rounds_per_sec": round(rate(total_rounds, wall_us), 1),
    }
    if len(disp) > 1:
        steady = disp[1:]
        steady_us = end - steady[0]["ts"]
        out["rounds_per_sec_steady"] = round(
            rate(sum(rounds(s) for s in steady), steady_us), 1
        )
    w = min(window, len(disp))
    tail = disp[-w:]
    out["window_dispatches"] = w
    out["rounds_per_sec_windowed"] = round(
        rate(sum(rounds(s) for s in tail), end - tail[0]["ts"]), 1
    )
    lats = chunk_latencies_us(sl)
    if lats:
        out["chunk_latency_us"] = {
            "p50": round(percentile(lats, 0.50), 1),
            "p95": round(percentile(lats, 0.95), 1),
            "p99": round(percentile(lats, 0.99), 1),
            "max": round(max(lats), 1),
            "mean": round(sum(lats) / len(lats), 1),
            "samples": len(lats),
        }
    return out


def perf_counter_tracks(
    spans, n_inst: int
) -> dict[str, list[tuple[int, float]]]:
    """Perfetto counter series for the unified timeline.

    Returns ``{name: [(tick, value), ...]}`` in the same shape as the
    coverage/exposure counter tracks (``obs.capture``): one sample per
    dispatch, stamped at the dispatch's END tick so the counter steps when
    its window completes.  Tracks: instantaneous ``host_rounds_per_sec``
    (this dispatch's rounds over its host interval) and cumulative
    ``host_occupancy_pct`` (busy/wall so far, 0-100).
    """
    sl = _span_list(spans)
    disp = _dispatches(sl)
    if not disp:
        return {}
    end = _loop_end_us(sl)
    busy = sorted(_busy(sl), key=lambda s: s["ts"])
    rps_track: list[tuple[int, float]] = []
    occ_track: list[tuple[int, float]] = []
    t0 = disp[0]["ts"]
    for s, nxt in zip(disp, disp[1:] + [None]):
        args = s.get("args", {})
        tick = int(args.get("tick_start", 0)) + int(args.get("ticks", 0))
        interval_us = (nxt["ts"] if nxt is not None else end) - s["ts"]
        rounds = n_inst * int(args.get("ticks", 0))
        rps = rounds / (interval_us / 1e6) if interval_us > 0 else 0.0
        horizon = s["ts"] + s["dur"]
        wall = horizon - t0
        busy_us = sum(
            min(b["dur"], max(0, horizon - b["ts"]))
            for b in busy
            if b["ts"] < horizon
        )
        occ = min(1.0, busy_us / wall) if wall > 0 else 0.0
        rps_track.append((tick, round(rps, 1)))
        occ_track.append((tick, round(100.0 * occ, 2)))
    return {
        "host_rounds_per_sec": rps_track,
        "host_occupancy_pct": occ_track,
    }


def vmem_gauges(
    state_bytes_per_lane: int,
    block: Optional[int],
    budget: Optional[int] = None,
) -> dict[str, Any]:
    """VMEM-footprint gauges for a fused-engine run.

    ``state_bytes_per_lane * block`` is what one fused grid step keeps
    resident (the quantity ``kernels.fused_tick.fit_block`` budgets);
    ``vmem_occupancy`` is its fraction of the planning budget — near 1.0
    means the block is VMEM-bound, small means dispatch-bound headroom.
    """
    if budget is None:
        from paxos_tpu.kernels.fused_tick import VMEM_STATE_BUDGET

        budget = VMEM_STATE_BUDGET
    if not block:
        return {}
    vmem = int(state_bytes_per_lane) * int(block)
    return {
        "vmem_state_bytes": vmem,
        "vmem_budget_bytes": int(budget),
        "vmem_occupancy": round(vmem / budget, 4) if budget else 0.0,
    }


def roofline_gauges(
    rounds_per_sec: float,
    case: dict[str, Any],
    ceilings: dict[str, Any],
) -> dict[str, Any]:
    """Roofline occupancy vs the recorded ceilings.

    ``case`` is a ROOFLINE.json per-case census dict (needs
    ``alu_per_lane_tick``; the delta-codec split's
    ``codec_alu_per_lane_tick`` is folded back in when present, so the
    ceiling keeps meaning "VPU ops the tick actually issues" across the
    r11 census-column split); ``ceilings`` the artifact's top-level device
    ceilings (``vpu_ops_per_sec``).  File loading stays with the caller —
    this function is pure so the plane replays from recorded inputs.
    """
    alu = case.get("alu_per_lane_tick")
    vpu = ceilings.get("vpu_ops_per_sec")
    if not alu or not vpu:
        return {}
    alu = float(alu) + float(case.get("codec_alu_per_lane_tick") or 0.0)
    ceiling_rps = float(vpu) / alu
    return {
        "roofline_ceiling_rps": round(ceiling_rps, 1),
        "roofline_occupancy": round(float(rounds_per_sec) / ceiling_rps, 4),
    }


# --------------------------------------------------------------------------
# Bench provenance: row schema + noise-aware regression comparison.

BENCH_ROW_SCHEMA = "paxos-tpu-bench-row-v2"

# Read-side compat: v1 rows (r5-r10 artifacts) predate ``ops_per_lane_tick``
# and stay valid — ``bench-compare`` must keep diffing against committed
# history.  New rows are always written at BENCH_ROW_SCHEMA.
BENCH_ROW_SCHEMAS = ("paxos-tpu-bench-row-v1", BENCH_ROW_SCHEMA)

# field -> required type(s).  The provenance core: anyone holding a row can
# tell WHAT was measured (config fingerprint + layout version + engine +
# platform) and HOW WELL (per-run samples, not just a mean).
_ROW_REQUIRED: dict[str, Any] = {
    "schema": str,
    "metric": str,
    "value": (int, float),
    "unit": str,
    "samples": list,
    "median": (int, float),
    "min": (int, float),
    "stdev": (int, float),
    "warmup_groups": int,
    "timed_groups": int,
    "n_instances": int,
    "chunk": int,
    "pipeline_depth": int,
    "ticks": int,
    "platform": str,
    "engine": str,
    "protocol": str,
    "layout_version": str,
    "config_fingerprint": str,
}


def validate_bench_row(row: Any) -> list[str]:
    """Schema-check one bench row; returns a list of problems (empty = ok)."""
    if not isinstance(row, dict):
        return [f"row is not a dict: {type(row).__name__}"]
    errs: list[str] = []
    for field, types in _ROW_REQUIRED.items():
        if field not in row:
            errs.append(f"missing field {field!r}")
        elif not isinstance(row[field], types):
            errs.append(
                f"field {field!r}: got {type(row[field]).__name__}"
            )
    if errs:
        return errs
    if row["schema"] not in BENCH_ROW_SCHEMAS:
        errs.append(
            f"schema {row['schema']!r} not in {BENCH_ROW_SCHEMAS!r}"
        )
    elif row["schema"] == BENCH_ROW_SCHEMA:
        # v2 additions: the census op count the row was measured under, so
        # a bench-compare delta can be attributed to op-count cuts vs clock.
        ops = row.get("ops_per_lane_tick")
        if not isinstance(ops, (int, float)) or isinstance(ops, bool):
            errs.append("ops_per_lane_tick must be a number (v2 row)")
        elif ops <= 0:
            errs.append("ops_per_lane_tick must be positive")
    if not row["samples"]:
        errs.append("samples is empty")
    elif not all(
        isinstance(s, (int, float)) and s > 0 for s in row["samples"]
    ):
        errs.append("samples must be positive numbers")
    if row["value"] <= 0:
        errs.append("value must be positive")
    return errs


def _row_key(row: dict) -> tuple:
    return (
        row.get("case") or row.get("protocol"),
        row.get("engine"),
        row.get("platform"),
    )


def _row_samples(row: dict) -> list[float]:
    """Per-run samples, tolerating pre-schema rows (throughput_runs/value)."""
    for field in ("samples", "throughput_runs"):
        vals = row.get(field)
        if vals:
            return [float(v) for v in vals]
    v = row.get("value")
    return [float(v)] if v else []


def compare_benches(
    baseline: list[dict],
    fresh: list[dict],
    *,
    tolerance: float = 0.10,
    noise_k: float = 3.0,
) -> dict[str, Any]:
    """Diff a fresh bench run against committed history.

    Tolerance model (documented in README §bench-compare): for each case
    matched on (case, engine, platform), the allowed relative drop is

        ``max(tolerance, noise_k * cv)``

    where ``cv`` is the coefficient of variation (stdev/median) of the
    BASELINE's own per-run samples — a case that historically wobbles 5%
    run-to-run gets a proportionally wider band than a quiet one, so the
    gate is noise-aware instead of flaking on shared-machine jitter.  The
    fresh side is judged by its BEST sample (min-time discipline: external
    noise only ever slows a run down), the baseline by its median.

    Cases present on only one side are reported but never gate (platform
    or sweep-set drift is provenance, not regression); zero overlapping
    cases is a failure (``ok: False``) — a vacuous pass must not gate CI.
    """
    base_map = {_row_key(r): r for r in baseline}
    fresh_keys = [_row_key(r) for r in fresh]
    rows: list[dict] = []
    regressions: list[dict] = []
    unmatched = [list(k) for k in fresh_keys if k not in base_map]
    for fr in fresh:
        br = base_map.get(_row_key(fr))
        if br is None:
            continue
        bs, fs = _row_samples(br), _row_samples(fr)
        if not bs or not fs:
            continue
        b_med = statistics.median(bs)
        cv = (
            statistics.stdev(bs) / b_med
            if len(bs) > 1 and b_med > 0
            else 0.0
        )
        allowed = max(tolerance, noise_k * cv)
        f_best = max(fs)
        ratio = f_best / b_med if b_med > 0 else 0.0
        entry = {
            "case": _row_key(fr)[0],
            "engine": fr.get("engine"),
            "platform": fr.get("platform"),
            "baseline_median": round(b_med, 1),
            "fresh_best": round(f_best, 1),
            "ratio": round(ratio, 4),
            "allowed_drop": round(allowed, 4),
            "baseline_cv": round(cv, 4),
            "regressed": ratio < 1.0 - allowed,
        }
        rows.append(entry)
        if entry["regressed"]:
            regressions.append(entry)
    missing_in_fresh = [
        list(k) for k in base_map if k not in set(fresh_keys)
    ]
    return {
        "compared": len(rows),
        "rows": rows,
        "regressions": regressions,
        "fresh_only": unmatched,
        "baseline_only": missing_in_fresh,
        "tolerance": tolerance,
        "noise_k": noise_k,
        "ok": bool(rows) and not regressions,
    }
