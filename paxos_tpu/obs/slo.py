"""SLO reductions for the client-workload plane (``workload.generator``).

The device half (:func:`slo_device`) reduces the per-lane queue counters
into one small pytree at the summarize boundary — per-class offered /
served / shed totals and the per-class log2 latency histogram — so the
whole SLO block rides the existing single ``device_get`` in
``harness.run.summarize``.  The host half (:func:`slo_host`) turns the
histograms into queue-delay-inclusive client-latency percentiles
(p50/p95/p99, reported as the bucket's inclusive upper edge in ticks) and
goodput-vs-offered ratios; :func:`slo_breach` applies the configured p99
SLO (exit 2 in the ``paxos_tpu slo`` subcommand), and
:func:`overload_knee` locates the first point of an offered-load sweep
where goodput stops tracking offered load.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paxos_tpu.workload.generator import CLASSES, WloadState

PERCENTILES = (50, 95, 99)


def slo_device(wl: WloadState) -> dict:
    """Device half of the SLO report: reductions only, no transfer."""
    n_classes = len(CLASSES)
    cls = (
        jax.lax.broadcasted_iota(
            jnp.int32, (n_classes,) + wl.mode.shape, 0
        )
        == wl.mode[None]
    )  # (C, P, I) — lane-class membership

    def per_class(x):
        return jnp.where(cls, x[None], 0).sum(axis=(1, 2), dtype=jnp.int32)

    return {
        "offered": per_class(wl.offered),  # (C,)
        "done": per_class(wl.done),  # (C,)
        "shed": per_class(wl.shed),  # (C,)
        "lanes": cls.astype(jnp.int32).sum(axis=(1, 2), dtype=jnp.int32),
        "hist": wl.hist.sum(axis=-1, dtype=jnp.int32),  # (C*B,)
        "queue_depth": wl.depth.sum(dtype=jnp.int32),  # () live depth now
        "depth_peak": wl.depth_peak.max(),  # () high-water mark
    }


def _bucket_edge(b: int) -> int:
    """Inclusive upper edge (ticks) of log2 bucket ``b``: [2^b, 2^(b+1))."""
    return (1 << (b + 1)) - 1


def _percentile_ticks(hist, q: int) -> int:
    """The q-th percentile latency from a log2-bucket histogram, in ticks.

    Reported as the holding bucket's upper edge (conservative); -1 when
    the class served nothing.
    """
    total = int(sum(hist))
    if total == 0:
        return -1
    need = (total * q + 99) // 100  # ceil(total * q / 100), int-exact
    cum = 0
    for b, n in enumerate(hist):
        cum += int(n)
        if cum >= need:
            return _bucket_edge(b)
    return _bucket_edge(len(hist) - 1)


def slo_host(host: dict) -> dict:
    """Format a ``device_get``'d :func:`slo_device` pytree."""
    n_classes = len(CLASSES)
    flat = [int(v) for v in host["hist"]]
    bins = len(flat) // n_classes
    classes = {}
    for c, name in enumerate(CLASSES):
        hist = flat[c * bins : (c + 1) * bins]
        offered = int(host["offered"][c])
        done = int(host["done"][c])
        row = {
            "lanes": int(host["lanes"][c]),
            "offered": offered,
            "done": done,
            "shed": int(host["shed"][c]),
            "goodput": (done / offered) if offered else 0.0,
            "hist": hist,
        }
        for q in PERCENTILES:
            row[f"p{q}_ticks"] = _percentile_ticks(hist, q)
        classes[name] = row
    offered = sum(r["offered"] for r in classes.values())
    done = sum(r["done"] for r in classes.values())
    return {
        "classes": classes,
        "offered": offered,
        "done": done,
        "shed": sum(r["shed"] for r in classes.values()),
        "goodput": (done / offered) if offered else 0.0,
        "queue_depth": int(host["queue_depth"]),
        "depth_peak": int(host["depth_peak"]),
        # Campaign-wide p99: the worst class that actually served traffic.
        "p99_ticks": max(
            (r["p99_ticks"] for r in classes.values() if r["done"] > 0),
            default=-1,
        ),
    }


def slo_merge(blocks: list) -> dict:
    """Merge per-campaign ``slo_host`` blocks into one cross-seed tally.

    Counters and histograms sum (each seed's lanes are a fresh client
    population, like exposure's ``lanes_exposed``); percentiles are
    recomputed from the summed histograms — NOT averaged, an average of
    percentiles is not a percentile.  ``queue_depth`` is point-in-time so
    the last block wins; ``depth_peak`` is a high-water mark so the max
    wins.  The key shape matches ``slo_host`` so
    ``MetricsRegistry.ingest_slo`` folds the merged block directly.
    """
    classes: dict = {}
    for blk in blocks:
        for name, row in blk["classes"].items():
            acc = classes.setdefault(name, {
                "lanes": 0, "offered": 0, "done": 0, "shed": 0,
                "hist": [0] * len(row["hist"]),
            })
            for k in ("lanes", "offered", "done", "shed"):
                acc[k] += row[k]
            acc["hist"] = [a + b for a, b in zip(acc["hist"], row["hist"])]
    for row in classes.values():
        row["goodput"] = (
            row["done"] / row["offered"] if row["offered"] else 0.0
        )
        for q in PERCENTILES:
            row[f"p{q}_ticks"] = _percentile_ticks(row["hist"], q)
    offered = sum(r["offered"] for r in classes.values())
    done = sum(r["done"] for r in classes.values())
    return {
        "classes": classes,
        "offered": offered,
        "done": done,
        "shed": sum(r["shed"] for r in classes.values()),
        "goodput": (done / offered) if offered else 0.0,
        "queue_depth": blocks[-1]["queue_depth"] if blocks else 0,
        "depth_peak": max((b["depth_peak"] for b in blocks), default=0),
        "p99_ticks": max(
            (r["p99_ticks"] for r in classes.values() if r["done"] > 0),
            default=-1,
        ),
    }


def slo_report(wl: WloadState) -> dict:
    """Host-readable SLO summary (one blocking transfer; tests/CLI)."""
    return slo_host(jax.device_get(slo_device(wl)))


def slo_breach(report: dict, p99_ticks: int) -> list:
    """Classes whose served-traffic p99 exceeds the SLO (empty = healthy).

    ``p99_ticks <= 0`` disables gating (no SLO configured).
    """
    if p99_ticks <= 0:
        return []
    return sorted(
        name
        for name, row in report["classes"].items()
        if row["done"] > 0 and row["p99_ticks"] > p99_ticks
    )


def overload_knee(points: list, floor: float = 0.9) -> Optional[dict]:
    """First point of an offered-load sweep where goodput/offered < floor.

    ``points`` is a list of dicts each carrying ``rate_scale``, ``offered``
    and ``done`` (the ``paxos_tpu slo`` sweep builds it); returns the knee
    point annotated with its goodput ratio, or ``None`` when the system
    kept up everywhere (no knee inside the swept range).
    """
    for pt in points:
        offered = pt.get("offered", 0)
        if offered <= 0:
            continue
        ratio = pt.get("done", 0) / offered
        if ratio < floor:
            return dict(pt, goodput=ratio)
    return None
