"""Span builder — replay a decoded flight-recorder ring into round spans.

The recorder (``core.telemetry``) emits at most one packed word per
(lane, tick): an event bitmask over ``promise / accept / decide / conflict
/ leader / timeout / drop / dup / corrupt / part_cut / part_heal /
recover``.  A consensus round is not one event but an *interval*: a ballot
opens, gathers a promise quorum, moves to phase 2, and ends in a decide, a
proposer timeout (retry at a higher ballot), or a preemption (another
leader/ballot takes over).  This module reconstructs those intervals from
the flat per-lane timeline that ``core.telemetry.decode_lane`` produces.

Reconstruction rules (shared by all four protocols; Raft rounds map to
elections/terms):

- ``decide`` closes the current span with outcome ``decided``.
- ``timeout`` closes it with outcome ``timeout`` and opens the successor
  at the same tick — the proposer retries with a higher ballot, so the
  ordinal ``round`` index is the lane's ballot-attempt counter.
- the FIRST ``leader`` event inside a span marks leadership established
  (phase-1 won / election won); a SECOND one without an intervening decide
  is a leadership change mid-round — the span closes ``preempted`` and the
  successor opens at that tick.
- fault events (``drop/dup/corrupt/part_cut/part_heal/recover``) never
  open or close spans; they annotate the span they land inside.
- a span still open when the timeline ends gets outcome ``open``.

The ring stores ballot *events*, not ballot numbers, so ``round`` is the
per-lane attempt ordinal — exactly the quantity preemption depth and
retry-storm analyses need.  Reconstruction is a pure function of the
decoded timeline: same ring, same spans, bit for bit (tests/test_obs.py
pins determinism across decodes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Optional

# Event kinds that annotate spans rather than delimit them (the fault
# layer's footprints inside a round).
FAULT_EVENTS = ("drop", "dup", "corrupt", "part_cut", "part_heal", "recover")

# Span outcomes, in the order a round can end.
OUTCOMES = ("decided", "timeout", "preempted", "open")


@dataclasses.dataclass
class RoundSpan:
    """One reconstructed consensus round (ballot attempt) in one lane."""

    lane: int
    round: int  # per-lane ballot-attempt ordinal, 0-based
    start: int  # tick the round opened
    end: int  # tick of the closing event (== start for 1-tick rounds)
    outcome: str  # one of OUTCOMES
    p1_tick: Optional[int] = None  # first promise recorded (phase-1 progress)
    p2_tick: Optional[int] = None  # first accept recorded (phase-2 progress)
    leader_tick: Optional[int] = None  # leadership established in this span
    conflict_tick: Optional[int] = None  # safety checker fired in this span
    events: dict = dataclasses.field(default_factory=dict)  # kind -> count
    faults: list = dataclasses.field(default_factory=list)  # {"tick","kind"}

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "lane": self.lane,
            "round": self.round,
            "start": self.start,
            "end": self.end,
            "outcome": self.outcome,
            "events": dict(sorted(self.events.items())),
            "faults": list(self.faults),
        }
        for k in ("p1_tick", "p2_tick", "leader_tick", "conflict_tick"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


def build_spans(timeline: Iterable[dict], lane: int) -> list[RoundSpan]:
    """Reconstruct ``RoundSpan``s from one lane's decoded timeline.

    ``timeline`` is ``core.telemetry.decode_lane`` output: an ordered list
    of ``{"tick": int, "events": [names]}`` records, at most one per tick.
    Pure and deterministic — no clock, no randomness, no device traffic.
    """
    spans: list[RoundSpan] = []
    cur: Optional[RoundSpan] = None
    next_start: Optional[int] = None  # successor opens here (timeout tick)

    def close(span: RoundSpan, tick: int, outcome: str) -> None:
        span.end = tick
        span.outcome = outcome
        spans.append(span)

    for rec in timeline:
        tick = int(rec["tick"])
        evs = rec["events"]
        if cur is None:
            start = next_start if next_start is not None else tick
            cur = RoundSpan(
                lane=lane, round=len(spans), start=start, end=start,
                outcome="open",
            )
            next_start = None

        for kind in evs:
            cur.events[kind] = cur.events.get(kind, 0) + 1
            if kind in FAULT_EVENTS:
                cur.faults.append({"tick": tick, "kind": kind})
        if "promise" in evs and cur.p1_tick is None:
            cur.p1_tick = tick
        if "accept" in evs and cur.p2_tick is None:
            cur.p2_tick = tick
        if "conflict" in evs and cur.conflict_tick is None:
            cur.conflict_tick = tick

        # Closing transitions, strongest first: a decide completes the
        # round even if a timeout or leader change shares its tick.
        if "decide" in evs:
            close(cur, tick, "decided")
            cur = None
        elif "timeout" in evs:
            close(cur, tick, "timeout")
            cur, next_start = None, tick
        elif "leader" in evs:
            if cur.leader_tick is None:
                cur.leader_tick = tick  # phase-1 won / election won
            else:
                close(cur, tick, "preempted")
                cur, next_start = None, tick
        if cur is not None:
            cur.end = tick

    if cur is not None:
        close(cur, cur.end, "open")
    return spans


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted list (deterministic)."""
    if not sorted_vals:
        return -1.0
    rank = max(1, -(-int(q * len(sorted_vals) * 100) // 100))  # ceil(q*n)
    rank = min(rank, len(sorted_vals))
    return float(sorted_vals[rank - 1])


def span_aggregates(spans: Iterable[RoundSpan]) -> dict[str, Any]:
    """Campaign-level aggregates over reconstructed spans (any lane mix).

    - ``round_latency_p50/p95/p99``: ticks from round open to decide,
      nearest-rank percentiles over decided rounds (-1.0 when none decided).
    - ``preemption_depth_max/mean``: ballot attempts burned before a
      decide — the length of each maximal run of non-decided spans that
      precedes a decided span, per lane.
    - ``faults_per_decided_round``: fault annotations across ALL spans per
      decided round.
    """
    by_lane: dict[int, list[RoundSpan]] = {}
    for s in spans:
        by_lane.setdefault(s.lane, []).append(s)

    latencies: list[int] = []
    depths: list[int] = []
    counts = {o: 0 for o in OUTCOMES}
    faults_total = 0
    for lane_spans in by_lane.values():
        depth = 0
        for s in sorted(lane_spans, key=lambda s: s.round):
            counts[s.outcome] = counts.get(s.outcome, 0) + 1
            faults_total += len(s.faults)
            if s.outcome == "decided":
                latencies.append(s.end - s.start)
                depths.append(depth)
                depth = 0
            else:
                depth += 1
    latencies.sort()
    decided = counts["decided"]
    return {
        "rounds_total": sum(counts.values()),
        "rounds_decided": decided,
        "rounds_timeout": counts["timeout"],
        "rounds_preempted": counts["preempted"],
        "rounds_open": counts["open"],
        "round_latency_p50": _percentile(latencies, 0.50),
        "round_latency_p95": _percentile(latencies, 0.95),
        "round_latency_p99": _percentile(latencies, 0.99),
        "preemption_depth_max": max(depths, default=0),
        "preemption_depth_mean": (
            round(sum(depths) / len(depths), 6) if depths else 0.0
        ),
        "faults_total": faults_total,
        "faults_per_decided_round": (
            round(faults_total / decided, 6) if decided else float(faults_total)
        ),
    }
