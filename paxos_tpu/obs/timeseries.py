"""Fleet observatory layer 9 — durable metrics time-series + trend gate.

PR 16's fleet gauges are snapshots: the coordinator's monitor loop emits
the *current* queue/lease state and a dead worker takes its metrics with
it.  This module makes worker metrics a durable TIME-SERIES with the same
crash-safety contract as every other fleet artifact:

- :class:`SeriesSampler` appends one ``sample`` row per logical-clock
  tick to a per-worker JSONL journal using the proven ``fuzz.corpus``
  append discipline (ONE write of the full line, then flush + fsync), so
  a SIGKILL can only ever truncate the final line and
  :func:`load_series` recovers everything before it.
- Each row is ``(worker, record, attempt, seq, clock, gauges[, wall])``.
  ``clock`` is an INJECTED logical clock (the seed index of a soak
  record, the campaign ordinal of a fuzz record) and ``gauges`` is the
  worker's :class:`harness.metrics.MetricsRegistry` gauge snapshot —
  the sampler reads the registry exactly the way ``stats`` does and
  never touches a wall clock or PRNG itself.  The optional ``wall``
  sidecar (epoch seconds, rounds/sec) is diagnostic only and is
  STRIPPED from the canonical merged form.
- :func:`merge_series` assembles one fleet-wide series from N worker
  journals in canonical ``(record, clock)`` order with dedup — the same
  merge contract as the PR 16 corpus merge (ordered by record, never by
  completion), so a chaos run's merged series is byte-identical to an
  uninterrupted run's: a re-run record re-emits the same clocks with
  the same deterministic gauges and dedup keeps one copy.
- :func:`compare_series` is the TREND gate beside the bench gate:
  discovery-rate stall, per-worker rounds/sec degradation, and
  heartbeat-gap anomalies, each finding naming the worker and record.

Like the rest of ``obs``: host-side only — zero new device ops, zero
PRNG draws, schedules bit-identical (sampling off writes nothing).
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Iterable, Optional

from paxos_tpu.fuzz.corpus import append_event, event_line, load_journal

SERIES_SCHEMA = 1


def sample_row(
    *,
    worker: str,
    record: str,
    attempt: int,
    seq: int,
    clock: int,
    gauges: dict,
    wall: Optional[dict] = None,
) -> dict:
    """One time-series journal row (the worker-journal wire form)."""
    row: dict[str, Any] = {
        "event": "sample", "schema": SERIES_SCHEMA, "worker": str(worker),
        "record": str(record), "attempt": int(attempt), "seq": int(seq),
        "clock": int(clock), "gauges": dict(gauges),
    }
    if wall is not None:
        row["wall"] = dict(wall)
    return row


def canonical_sample(row: dict) -> dict:
    """The merge-canonical form of a sample row.

    Worker identity, sequence number, attempt, and the wall sidecar are
    all *delivery* facts — which process happened to run the record, and
    when — so they are stripped; what remains (``record``, ``clock``,
    deterministic ``gauges``) is identical however the record was
    scheduled, killed, or replayed.  This is what makes the merged
    series byte-deterministic under chaos.
    """
    return {
        "event": "sample", "record": str(row["record"]),
        "clock": int(row["clock"]), "gauges": dict(row["gauges"]),
    }


class SeriesSampler:
    """Per-worker time-series sampler over an open journal file handle.

    The handle, the worker id, and every clock value are injected by the
    fleet layer; the sampler itself is pure bookkeeping + the crash-safe
    append.  ``seq`` increases monotonically per worker across records —
    the per-journal integrity check :func:`merge_series` verifies.
    """

    def __init__(self, fh, worker: str, every: int = 1) -> None:
        self.fh = fh
        self.worker = str(worker)
        self.every = int(every)
        self.seq = 0
        self.samples = 0

    def sample(
        self,
        *,
        record: str,
        attempt: int,
        clock: int,
        registry,
        wall: Optional[dict] = None,
    ) -> bool:
        """Append one row when ``clock`` lands on the sampling cadence.

        The cadence test is ``clock % every == 0`` — a function of the
        logical clock alone, so a resumed record samples exactly the
        clocks its uninterrupted twin would have.  Returns whether a row
        was written.
        """
        if self.every <= 0 or int(clock) % self.every != 0:
            return False
        gauges = registry.snapshot().get("gauges", {})
        append_event(self.fh, sample_row(
            worker=self.worker, record=record, attempt=attempt,
            seq=self.seq, clock=clock, gauges=gauges, wall=wall,
        ))
        self.seq += 1
        self.samples += 1
        return True


def load_series(path: Any) -> dict:
    """Read one worker journal back, tolerating a torn final line.

    Same contract as ``fuzz.corpus.load_journal`` (it IS that loader):
    a truncated tail is dropped and reported, mid-file corruption still
    raises.  Returns ``{"rows", "torn_tail"}`` with non-sample events
    filtered out.
    """
    loaded = load_journal(path)
    return {
        "rows": [
            e for e in loaded["events"] if e.get("event") == "sample"
        ],
        "torn_tail": loaded["torn_tail"],
    }


def merge_series(streams: "Iterable[list[dict]]") -> dict:
    """Merge N worker sample streams into one canonical fleet series.

    Rows canonicalize (:func:`canonical_sample`), dedup by ``(record,
    clock)`` — a record killed after a durable sample and replayed by
    its replacement re-emits the same clock with the same deterministic
    gauges, and the first copy wins — and sort by ``(record, clock)``:
    record order, never completion order.  The digest over the canonical
    lines is the series determinism pin (chaos == uninterrupted).

    Returns ``{"events", "lines", "digest", "samples", "dedup",
    "workers"}`` where ``workers`` maps each worker id to its raw sample
    count, last ``seq``, and whether its journal's ``seq`` was strictly
    monotone (the per-journal integrity bit).
    """
    canon: "dict[tuple, dict]" = {}
    dedup = 0
    workers: "dict[str, dict]" = {}
    for rows in streams:
        for r in rows:
            if r.get("event") != "sample":
                continue
            w = str(r.get("worker", "?"))
            stats = workers.setdefault(
                w, {"samples": 0, "last_seq": None, "seq_monotone": True}
            )
            stats["samples"] += 1
            seq = r.get("seq")
            if seq is not None:
                if (stats["last_seq"] is not None
                        and int(seq) <= stats["last_seq"]):
                    stats["seq_monotone"] = False
                stats["last_seq"] = int(seq)
            key = (str(r["record"]), int(r["clock"]))
            if key in canon:
                dedup += 1
                continue
            canon[key] = canonical_sample(r)
    events = [canon[k] for k in sorted(canon)]
    lines = [event_line(e) for e in events]
    h = hashlib.sha256()
    for line in lines:
        h.update(line.encode())
        h.update(b"\n")
    return {
        "events": events,
        "lines": lines,
        "digest": h.hexdigest(),
        "samples": len(events),
        "dedup": dedup,
        "workers": {w: dict(s) for w, s in sorted(workers.items())},
    }


def write_series(path: Any, merged: dict) -> str:
    """Write a merged canonical series (digest line last); returns the
    digest.  Temp file + fsync + rename — the whole-file twin of the
    per-row append discipline, same as ``Corpus.write_journal``."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        for line in merged["lines"]:
            f.write(line + "\n")
        f.write(event_line(
            {"event": "digest", "sha256": merged["digest"]}
        ) + "\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return merged["digest"]


# -- the trend gate -------------------------------------------------------

def _median(xs: "list[float]") -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def compare_series(
    rows: "Iterable[dict]",
    *,
    stall_samples: int = 5,
    rps_floor: float = 0.25,
    gap_k: float = 4.0,
    gap_min_s: float = 120.0,
    slo_k: float = 2.0,
) -> dict:
    """Trend-gate a fleet's RAW sample rows; mirrors ``compare_benches``.

    Four detectors, each finding naming the worker and record:

    - **discovery_stall** — a ``(worker, record)`` group with at least
      ``stall_samples`` samples whose coverage union never grew past its
      first sample: the worker kept burning campaigns without
      discovering a single new state bit.
    - **rps_degradation** — a worker whose LAST rounds/sec sample fell
      below ``rps_floor`` x its own median (>= 4 samples): the shard
      ended an order slower than it ran, which a fleet-total average
      would hide.
    - **heartbeat_gap** — a worker whose largest inter-sample wall gap
      exceeds both ``gap_k`` x its median gap and the ``gap_min_s``
      absolute floor: the worker went dark mid-record (the floor keeps
      honest compile stalls on slow CI out of the findings).
    - **slo_degradation** — a ``(worker, record)`` group whose LAST
      ``slo_p99_ticks`` sample exceeds ``slo_k`` x its own median
      (>= 4 samples): client latency blew past its steady state late in
      the campaign, which the campaign-total percentile would blur.

    The rps and gap detectors read the non-canonical ``wall`` sidecar,
    so they see real delivery behaviour; the stall detector reads only
    deterministic gauges.  Returns ``{"ok", "compared", "findings",
    "params"}`` — ``ok`` iff no findings over a nonzero sample set.
    """
    groups: "dict[tuple, list[dict]]" = {}
    by_worker: "dict[str, list[dict]]" = {}
    compared = 0
    for r in rows:
        if r.get("event") != "sample":
            continue
        compared += 1
        w = str(r.get("worker", "?"))
        groups.setdefault((w, str(r["record"])), []).append(r)
        by_worker.setdefault(w, []).append(r)
    findings: "list[dict]" = []
    union_key = "worker_union_bits"
    for (w, rec), g in sorted(groups.items()):
        g = sorted(g, key=lambda r: int(r["clock"]))
        bits = [r.get("gauges", {}).get(union_key) for r in g]
        bits = [b for b in bits if b is not None]
        if len(bits) >= stall_samples and max(bits) <= bits[0]:
            findings.append({
                "kind": "discovery_stall", "worker": w, "record": rec,
                "samples": len(bits), "union_bits": bits[0],
            })
        p99s = [r.get("gauges", {}).get("slo_p99_ticks") for r in g]
        p99s = [float(v) for v in p99s if v is not None]
        if len(p99s) >= 4:
            med = _median(p99s)
            if med > 0 and p99s[-1] > slo_k * med:
                findings.append({
                    "kind": "slo_degradation", "worker": w, "record": rec,
                    "last_p99_ticks": p99s[-1], "median_p99_ticks": med,
                })
    for w, g in sorted(by_worker.items()):
        g = sorted(g, key=lambda r: int(r.get("seq", 0)))
        rps = [
            (r["wall"].get("rps"), r)
            for r in g
            if isinstance(r.get("wall"), dict)
            and r["wall"].get("rps") is not None
        ]
        if len(rps) >= 4:
            med = _median([v for v, _ in rps])
            last_v, last_r = rps[-1]
            if med > 0 and last_v < rps_floor * med:
                findings.append({
                    "kind": "rps_degradation", "worker": w,
                    "record": str(last_r["record"]),
                    "last_rps": round(last_v, 3), "median_rps": round(med, 3),
                })
        ts = [
            (r["wall"]["t"], r)
            for r in g
            if isinstance(r.get("wall"), dict) and r["wall"].get("t") is not None
        ]
        if len(ts) >= 4:
            gaps = [
                (b[0] - a[0], b[1])
                for a, b in zip(ts, ts[1:])
            ]
            med_gap = _median([d for d, _ in gaps])
            worst, after = max(gaps, key=lambda x: x[0])
            if worst > gap_min_s and med_gap > 0 and worst > gap_k * med_gap:
                findings.append({
                    "kind": "heartbeat_gap", "worker": w,
                    "record": str(after["record"]),
                    "gap_s": round(worst, 2),
                    "median_gap_s": round(med_gap, 2),
                })
    return {
        "ok": compared > 0 and not findings,
        "compared": compared,
        "findings": findings,
        "params": {
            "stall_samples": stall_samples, "rps_floor": rps_floor,
            "gap_k": gap_k, "gap_min_s": gap_min_s, "slo_k": slo_k,
        },
    }
