"""Mesh sharding of the instances axis across chips."""

from paxos_tpu.parallel.mesh import make_mesh, shard_pytree  # noqa: F401
