"""Multi-host scale-out: process bootstrap + ICI/DCN-aware device ordering.

Reference parity (SURVEY.md §3.2, §6.8): the reference scales out by adding
OS processes found via SimpleLocalnet's UDP-multicast discovery and talks
TCP between them [B][CH].  The TPU twin scales out by adding *hosts* to one
multi-controller JAX program: :func:`init_distributed` is the discovery
step (coordinator rendezvous instead of multicast), and the mesh built by
:func:`make_instances_mesh` spans every chip of every host.

Because instances are embarrassingly parallel, the step function needs no
cross-chip traffic at all; the only collectives are the scalar metric
reductions in ``summarize``.  The mesh is still built DCN-aware: devices
are ordered slice-major (``mesh_utils.create_hybrid_device_mesh``), so a
tree-reduction runs over ICI within each slice first and crosses the
slow DCN once per slice — the standard multi-slice recipe.

Single-host (and the CPU test rig) passes through unchanged: with one
process and no slice metadata every helper degrades to the plain 1-D mesh
of ``paxos_tpu.parallel.mesh``.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from paxos_tpu.parallel.mesh import INSTANCES_AXIS


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join the multi-host program; returns this process's index.

    No-op (returns 0) when unconfigured — single-host runs and the unit-test
    rig never touch the distributed runtime.  On TPU pods the three
    arguments are normally inferred from the environment, so
    ``init_distributed()`` alone suffices; explicit values support
    DCN-connected CPU/GPU fleets.
    """
    if coordinator_address is None and jax.process_count() == 1:
        try:
            import jax._src.clusters as clusters

            env_ok = any(
                c.is_env_present() for c in clusters.ClusterEnv._cluster_types
            )
        except Exception:
            # The private probe moved/vanished: fall back to documented
            # cluster env vars rather than silently running single-process
            # on what is actually a pod (the failure mode would be N
            # identical unsharded runs, not an error).
            env_ok = any(
                v in os.environ
                for v in (
                    "TPU_WORKER_HOSTNAMES",  # TPU pod (GCE metadata mirror)
                    "MEGASCALE_COORDINATOR_ADDRESS",  # multislice
                    "JAX_COORDINATOR_ADDRESS",
                    "SLURM_JOB_ID",
                    "OMPI_MCA_orte_hnp_uri",
                )
            )
        if not env_ok:
            return 0
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index()


def slice_major_devices(
    devices: Optional[Sequence[jax.Device]] = None,
) -> list[jax.Device]:
    """All devices ordered slice-major: same-slice chips are adjacent.

    Shard k of the instances axis lands on ``devices[k]``, so adjacent
    shards share a slice and reductions tree up over ICI before touching
    DCN.  Devices without slice metadata (single slice, CPU) keep their
    default order.
    """
    devices = list(devices if devices is not None else jax.devices())
    if any(getattr(d, "slice_index", None) is None for d in devices):
        return devices
    return sorted(devices, key=lambda d: (d.slice_index, d.id))


def make_instances_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D ``instances`` mesh over every chip of every host, DCN-aware.

    Uses ``mesh_utils.create_hybrid_device_mesh`` when multiple slices are
    present (it validates per-slice symmetry), else a plain ordered mesh.
    """
    devices = slice_major_devices(devices)
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if len(slice_ids) > 1 and None not in slice_ids:
        from jax.experimental import mesh_utils

        per_slice = len(devices) // len(slice_ids)
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_slice,),
            dcn_mesh_shape=(len(slice_ids),),
            devices=devices,
        )
        return Mesh(arr.reshape(-1), (INSTANCES_AXIS,))
    return Mesh(np.asarray(devices), (INSTANCES_AXIS,))


def process_local_batch(n_inst: int) -> int:
    """Instances this process materializes under full sharding.

    With multi-controller JAX each process only allocates its addressable
    shard; host-side planning (e.g. checkpoint sizing) uses this.
    """
    return n_inst // jax.process_count()
