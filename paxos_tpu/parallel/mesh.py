"""Device-mesh scale-out — the SimpleLocalnet twin.

Reference parity (SURVEY.md §3.2): the reference scales out by adding nodes
(SimpleLocalnet master/slave over TCP [B]); here the scale-out axis is the
``instances`` dimension sharded over a 1-D `jax.sharding.Mesh`.  Instances
are independent, so the step function needs no cross-device communication at
all — XLA inserts collectives only for the scalar metric reductions in
`summarize` (psums over ICI intra-slice / DCN across slices).  There is no
NCCL/MPI anywhere: collectives are XLA's (SURVEY.md §6.8).

Tests exercise this on a virtual 8-device CPU mesh
(``--xla_force_host_platform_device_count=8``) — the TPU analog of the Cloud
Haskell ecosystem's ``network-transport-inmemory`` trick (SURVEY.md §5.2.4).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

INSTANCES_AXIS = "instances"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, named ``instances``."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), (INSTANCES_AXIS,))


def partition_devices(
    n_workers: int, devices: Optional[Sequence[jax.Device]] = None
) -> "list[list[jax.Device]]":
    """Contiguous split of the local devices across fleet workers.

    The fleet coordinator's device plan: worker ``i`` gets the ``i``-th
    contiguous slice (remainder devices spread over the leading workers),
    and each worker meshes its slice with :func:`make_mesh` exactly like
    a standalone run meshes all devices.  With fewer devices than
    workers — the single-chip and CPU-CI degenerate case — every worker
    shares device 0: instances are independent, so co-located workers
    only contend for the one chip's time, never for correctness.
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_workers <= 0:
        raise ValueError(f"n_workers must be positive, got {n_workers}")
    if len(devices) < n_workers:
        return [[devices[0]] for _ in range(n_workers)]
    base, extra = divmod(len(devices), n_workers)
    out, at = [], 0
    for i in range(n_workers):
        step = base + (1 if i < extra else 0)
        out.append(devices[at:at + step])
        at += step
    return out


def state_sharding(tree: Any, mesh: Mesh, n_inst: int) -> Any:
    """Per-leaf shardings: trailing ``instances`` axis sharded, scalars replicated.

    The framework's arrays are instance-minor (``core.messages``), so the
    sharded axis is the LAST one of every instance-carrying leaf.
    """

    def leaf_sharding(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[-1] == n_inst:
            return NamedSharding(mesh, P(*([None] * (x.ndim - 1)), INSTANCES_AXIS))
        return NamedSharding(mesh, P())

    return jax.tree.map(leaf_sharding, tree)


def shard_pytree(tree: Any, mesh: Mesh, n_inst: int) -> Any:
    """Place a host/state pytree onto the mesh with instance sharding."""
    return jax.device_put(tree, state_sharding(tree, mesh, n_inst))
