"""Protocol step functions: Paxos, Multi-Paxos, Fast Paxos, Raft-core.

All protocols share one step-fn shape so the cross-protocol sweep (BASELINE
config 5) can drive them under identical fault masks:

    step(state, base_key, plan, cfg) -> state
"""
