"""Multi-Paxos log replication — leader lease, crash, recovery (config 3).

Same fused-tick structure as :mod:`paxos_tpu.protocols.paxos` (one message
per acceptor per tick, commutative reply folds at proposers), extended with:

- **Whole-log phase 1**: a candidate's ``Prepare(b)`` covers all L slots;
  each ``Promise(b)`` carries the acceptor's full accepted-(ballot, value)
  log, max-folded per slot into the new leader's recovery arrays.
- **Slot-by-slot phase 2**: the leader re-proposes from slot 0, adopting the
  highest accepted value per slot (re-confirming chosen slots re-chooses the
  same value, so leadership changes are safe).  The leader re-broadcasts the
  current slot's ``Accept`` every tick — idempotent at acceptors and
  self-healing under message loss, so no per-slot retry machinery exists.
- **Progress leases**: failure detection by observed progress (SURVEY.md
  §4.4's declarative twin of monitors).  Every proposer watches the
  instance's chosen-slot count; ``lease_len`` ticks without progress make a
  follower start an election (staggered + jittered) and make a stale leader
  demote itself.
- **Leader crash windows** from the fault plan: a crashed proposer does
  nothing and drops to follower on recovery.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from paxos_tpu.check.mp_safety import mp_learner_observe
from paxos_tpu.core import ballot as bal_mod
from paxos_tpu.core.messages import ACCEPT, PREPARE
from paxos_tpu.core.mp_state import CANDIDATE, FOLLOW, LEAD, MultiPaxosState
from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.kernels.quorum import majority, quorum_reached
from paxos_tpu.transport import inmemory_tpu as net


def own_slot_value(pid: jnp.ndarray, slot: jnp.ndarray) -> jnp.ndarray:
    """Distinct per (proposer, slot) command payload: duels are observable."""
    return (pid + 1) * 1000 + slot


@struct.dataclass
class MPTickMasks:
    """One Multi-Paxos tick's pre-sampled randomness (instance-minor)."""

    sel_score: jnp.ndarray  # (2, P, A, I) int32 — request-selection entropy
    busy: Optional[jnp.ndarray]  # (1, 1, A, I) bool — False = acceptor idles
    dup_req: Optional[jnp.ndarray]  # (2, P, A, I) bool — request redelivered
    prom_deliver: Optional[jnp.ndarray]  # (P, A, I) bool — promise not held
    accd_deliver: Optional[jnp.ndarray]  # (P, A, I) bool — accepted not held
    keep_prom: Optional[jnp.ndarray]  # (P, A, I) bool — PROMISE not dropped
    keep_accd: Optional[jnp.ndarray]  # (P, A, I) bool — ACCEPTED not dropped
    keep_prep: Optional[jnp.ndarray]  # (P, A, I) bool — PREPARE not dropped
    keep_acc: Optional[jnp.ndarray]  # (P, A, I) bool — ACCEPT not dropped
    jitter: jnp.ndarray  # (P, I) int32 — election-threshold jitter
    backoff: jnp.ndarray  # (P, I) int32 — post-failure retreat draw


def sample_mp_masks(
    key: jax.Array, cfg: FaultConfig, n_prop: int, n_acc: int, n_inst: int
) -> MPTickMasks:
    """Draw a tick's masks with ``jax.random`` (the XLA engine's source)."""
    (k_sel, k_idle, k_dup_req, k_hold_pr, k_hold_ac, k_drop_pr, k_drop_ac,
     k_drop_prep, k_drop_acc, k_jit, k_back) = jax.random.split(key, 11)
    slot = (2, n_prop, n_acc, n_inst)
    edge = (n_prop, n_acc, n_inst)

    return MPTickMasks(
        sel_score=jax.random.bits(k_sel, slot, jnp.uint32).astype(jnp.int32),
        busy=net.keep_mask(k_idle, (1, 1, n_acc, n_inst), cfg.p_idle),
        dup_req=net.stay_mask(k_dup_req, slot, cfg.p_dup),
        prom_deliver=net.keep_mask(k_hold_pr, edge, cfg.p_hold),
        accd_deliver=net.keep_mask(k_hold_ac, edge, cfg.p_hold),
        keep_prom=net.keep_mask(k_drop_pr, edge, cfg.p_drop),
        keep_accd=net.keep_mask(k_drop_ac, edge, cfg.p_drop),
        keep_prep=net.keep_mask(k_drop_prep, edge, cfg.p_drop),
        keep_acc=net.keep_mask(k_drop_acc, edge, cfg.p_drop),
        jitter=jax.random.randint(
            k_jit, (n_prop, n_inst), 0, max(cfg.backoff_max, 1), jnp.int32
        ),
        backoff=jax.random.randint(
            k_back, (n_prop, n_inst), 0, 2 * max(cfg.backoff_max, 1), jnp.int32
        ),
    )


def mp_counter_masks(cfg: FaultConfig, tick_seed: jax.Array, state) -> MPTickMasks:
    """Draw a tick's masks from the counter PRNG (the fused engine's source)."""
    from paxos_tpu.kernels import counter_prng as cp

    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    slot = (2, n_prop, n_acc, n_inst)
    edge = (n_prop, n_acc, n_inst)
    return MPTickMasks(
        sel_score=cp.counter_bits(tick_seed, 0, slot),
        busy=cp.bern_not(tick_seed, 1, (1, 1, n_acc, n_inst), cfg.p_idle),
        dup_req=cp.bern(tick_seed, 2, slot, cfg.p_dup),
        prom_deliver=cp.bern_not(tick_seed, 3, edge, cfg.p_hold),
        accd_deliver=cp.bern_not(tick_seed, 4, edge, cfg.p_hold),
        keep_prom=cp.bern_not(tick_seed, 5, edge, cfg.p_drop),
        keep_accd=cp.bern_not(tick_seed, 6, edge, cfg.p_drop),
        keep_prep=cp.bern_not(tick_seed, 7, edge, cfg.p_drop),
        keep_acc=cp.bern_not(tick_seed, 8, edge, cfg.p_drop),
        jitter=cp.randint(tick_seed, 9, (n_prop, n_inst), max(cfg.backoff_max, 1)),
        backoff=cp.randint(
            tick_seed, 10, (n_prop, n_inst), 2 * max(cfg.backoff_max, 1)
        ),
    )


def apply_tick_mp(
    state: MultiPaxosState, masks: MPTickMasks, plan: FaultPlan, cfg: FaultConfig
) -> MultiPaxosState:
    """The pure Multi-Paxos transition for one tick over pre-sampled masks."""
    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    n_slots = state.log_len
    quorum = majority(n_acc)

    acc = state.acceptor
    prop = state.proposer
    alive = plan.alive(state.tick)  # (A, I)
    p_alive = plan.prop_alive(state.tick)  # (P, I)
    equiv = plan.equivocate  # (A, I)

    if cfg.amnesia:  # bug injection: acceptor forgets durable state on recovery
        rec = plan.recovering(state.tick)
        acc = acc.replace(
            promised=jnp.where(rec, 0, acc.promised),
            log_bal=jnp.where(rec[:, None], 0, acc.log_bal),
            log_val=jnp.where(rec[:, None], 0, acc.log_val),
        )

    # ---- Reply delivery decided & cleared before new writes (no clobber) ----
    link = plan.link_ok(state.tick) if cfg.p_part > 0.0 else None  # (P, A, I)

    prom_del = state.promises.present
    if masks.prom_deliver is not None:
        prom_del = prom_del & masks.prom_deliver
    accd_del = state.accepted.present
    if masks.accd_deliver is not None:
        accd_del = accd_del & masks.accd_deliver
    if link is not None:  # partitioned links stall replies in flight
        prom_del = prom_del & link
        accd_del = accd_del & link
    promises = state.promises.replace(present=state.promises.present & ~prom_del)
    accepted = state.accepted.replace(present=state.accepted.present & ~accd_del)

    # ---- Acceptor half-tick ----
    sel = net.select_from_scores(state.requests.present, masks.sel_score, masks.busy)
    sel = sel & alive[None, None]
    if link is not None:  # partitioned links stall requests in flight
        sel = sel & link[None]

    def gather(x):
        return jnp.where(sel, x, 0).sum(axis=(0, 1))

    msg_bal = gather(state.requests.bal)  # (A, I)
    msg_val = gather(state.requests.v1)  # (A, I)
    msg_slot = gather(state.requests.v2)  # (A, I)
    is_prep = sel[PREPARE].any(axis=0)
    is_acc = sel[ACCEPT].any(axis=0)

    ok_prep_h = is_prep & ~equiv & (msg_bal > acc.promised)
    ok_prep = ok_prep_h | (is_prep & equiv)
    ok_acc_h = is_acc & ~equiv & (msg_bal >= acc.promised)
    ok_acc = ok_acc_h | (is_acc & equiv)

    promised = jnp.where(ok_prep_h, msg_bal, acc.promised)
    promised = jnp.where(ok_acc_h, jnp.maximum(promised, msg_bal), promised)
    slot_ids = jnp.arange(n_slots, dtype=jnp.int32)[None, :, None]  # (1, L, 1)
    oh_slot = msg_slot[:, None] == slot_ids  # (A, L, I)
    wr = ok_acc[:, None] & oh_slot
    log_bal = jnp.where(wr, msg_bal[:, None], acc.log_bal)
    log_val = jnp.where(wr, msg_val[:, None], acc.log_val)

    # Promise replies carry the acceptor's full log (equivocators hide theirs).
    prom_send = sel[PREPARE] & ok_prep[None]  # (P, A, I)
    if masks.keep_prom is not None:
        prom_send = prom_send & masks.keep_prom
    payload_pb = jnp.where(equiv[:, None], 0, acc.log_bal)  # (A, L, I)
    payload_pv = jnp.where(equiv[:, None], 0, acc.log_val)
    promises = promises.replace(
        present=promises.present | prom_send,
        bal=jnp.where(prom_send, msg_bal[None], promises.bal),
        pb=jnp.where(prom_send[:, :, None], payload_pb[None], promises.pb),
        pv=jnp.where(prom_send[:, :, None], payload_pv[None], promises.pv),
    )

    accd_send = sel[ACCEPT] & ok_acc[None]  # (P, A, I)
    if masks.keep_accd is not None:
        accd_send = accd_send & masks.keep_accd
    accepted = accepted.replace(
        present=accepted.present | accd_send,
        bal=jnp.where(accd_send, msg_bal[None], accepted.bal),
        slot=jnp.where(accd_send, msg_slot[None], accepted.slot),
        val=jnp.where(accd_send, msg_val[None], accepted.val),
    )

    requests = net.consume(state.requests, sel, stay=masks.dup_req)
    acc = acc.replace(promised=promised, log_bal=log_bal, log_val=log_val)

    # ---- Learner / checker ----
    with jax.named_scope("learner_check"):
        learner = mp_learner_observe(
            state.learner, ok_acc, msg_bal, msg_slot, msg_val, state.tick, quorum
        )
        chosen_count = learner.chosen.sum(axis=0, dtype=jnp.int32)  # (I,)

    # ---- Proposer half-tick ----
    bits = (jnp.asarray(1, jnp.int32) << jnp.arange(n_acc, dtype=jnp.int32))[
        None, :, None
    ]  # (1, A, 1)
    cur_bal = prop.bal[:, None]  # (P, 1, I)

    # Promises (phase 1): voter bits + per-slot max-fold of recovery pairs.
    pv_ok = prom_del & (state.promises.bal == cur_bal) & (
        prop.phase == CANDIDATE
    )[:, None]  # (P, A, I)
    heard = prop.heard | jnp.where(pv_ok, bits, 0).sum(axis=1, dtype=jnp.int32)
    # Per-slot max-fold over acceptors; value rides along via the max-trick
    # (at a given ballot all honest acceptors store one value per slot, and
    # equivocators' payloads are zeroed; a zero max never improves).
    cand_pb = jnp.where(pv_ok[:, :, None], state.promises.pb, 0)  # (P, A, L, I)
    cand_bal = cand_pb.max(axis=1)  # (P, L, I)
    cand_val = jnp.where(
        (cand_pb == cand_bal[:, None]) & pv_ok[:, :, None], state.promises.pv, 0
    ).max(axis=1)
    improve = cand_bal > prop.recov_bal  # (P, L, I)
    recov_bal = jnp.where(improve, cand_bal, prop.recov_bal)
    recov_val = jnp.where(improve, cand_val, prop.recov_val)

    # Accepted (phase 2): only votes for the slot currently being driven.
    av_ok = (
        accd_del
        & (state.accepted.bal == cur_bal)
        & (state.accepted.slot == prop.commit_idx[:, None])
        & (prop.phase == LEAD)[:, None]
    )
    heard = heard | jnp.where(av_ok, bits, 0).sum(axis=1, dtype=jnp.int32)

    # Transitions.
    p1_done = (prop.phase == CANDIDATE) & quorum_reached(heard, quorum)
    slot_done = (
        (prop.phase == LEAD)
        & quorum_reached(heard, quorum)
        & (prop.commit_idx < n_slots)
    )

    # Progress lease: any new chosen slot in this instance resets every
    # proposer's suspicion timer.
    progressed = chosen_count[None] > prop.last_chosen_count  # (P, I)
    lease_timer = jnp.where(progressed, 0, prop.lease_timer + 1)
    last_chosen_count = jnp.maximum(prop.last_chosen_count, chosen_count[None])

    log_full = chosen_count[None] >= n_slots  # (P, I): nothing left to do
    lease_out = lease_timer > cfg.lease_len

    # Election trigger: staggered so proposers don't collide every time.
    pid = jnp.broadcast_to(
        jnp.arange(n_prop, dtype=jnp.int32)[:, None], prop.bal.shape
    )
    jitter = masks.jitter
    start_elec = (
        (prop.phase == FOLLOW)
        & p_alive
        & ~log_full
        & (lease_timer > cfg.lease_len + pid * 3 + jitter)
    )
    new_bal = bal_mod.make_ballot(bal_mod.ballot_round(prop.bal) + 1, pid)

    # Candidate timeout: back to follower, retry later with the next ballot.
    candidate_timer = jnp.where(prop.phase == CANDIDATE, prop.candidate_timer + 1, 0)
    cand_fail = (prop.phase == CANDIDATE) & (candidate_timer > cfg.timeout) & ~p1_done

    # Stale leader demotes itself after a lease of no progress.
    demote = (prop.phase == LEAD) & lease_out & ~slot_done & ~log_full

    phase = prop.phase
    phase = jnp.where(start_elec, CANDIDATE, phase)
    phase = jnp.where(p1_done, LEAD, phase)
    phase = jnp.where(cand_fail | demote, FOLLOW, phase)
    phase = jnp.where(~p_alive, FOLLOW, phase)  # crashed -> follower on recovery

    bal_next = jnp.where(start_elec, new_bal, prop.bal)
    commit_idx = jnp.where(p1_done, 0, prop.commit_idx)
    commit_idx = jnp.where(slot_done, commit_idx + 1, commit_idx)
    heard = jnp.where(p1_done | slot_done | start_elec | cand_fail | demote, 0, heard)
    recov_bal = jnp.where(start_elec[:, None], 0, recov_bal)
    recov_val = jnp.where(start_elec[:, None], 0, recov_val)
    lease_timer = jnp.where(start_elec | p1_done | slot_done, 0, lease_timer)
    # Failed candidacy / demotion: retreat below the election threshold by a
    # random backoff so rivals separate instead of re-colliding every tick.
    lease_timer = jnp.where(
        cand_fail | demote, cfg.lease_len - masks.backoff, lease_timer
    )
    candidate_timer = jnp.where(start_elec, 0, candidate_timer)

    # ---- Emit ----
    # New candidates broadcast Prepare(b) once (retries via cand_fail cycle).
    prep_mask = jnp.broadcast_to(
        (start_elec & p_alive)[:, None], (n_prop, n_acc, n_inst)
    )
    requests = net.send(
        requests, PREPARE,
        send_mask=prep_mask,
        bal=bal_next[:, None],
        v1=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        keep=masks.keep_prep,
    )
    # Leaders re-broadcast the current slot's Accept every tick (idempotent,
    # self-healing under loss).
    is_lead = (phase == LEAD) & p_alive & (commit_idx < n_slots)
    ci = jnp.minimum(commit_idx, n_slots - 1)  # (P, I)
    ci_hot = ci[:, None] == jnp.arange(n_slots, dtype=jnp.int32)[None, :, None]
    rb = jnp.where(ci_hot, recov_bal, 0).sum(axis=1)  # (P, I)
    rv = jnp.where(ci_hot, recov_val, 0).sum(axis=1)
    pval = jnp.where(rb > 0, rv, own_slot_value(pid, ci))  # (P, I)
    requests = net.send(
        requests, ACCEPT,
        send_mask=jnp.broadcast_to(is_lead[:, None], (n_prop, n_acc, n_inst)),
        bal=bal_next[:, None],
        v1=pval[:, None],
        v2=ci[:, None],
        keep=masks.keep_acc,
    )

    prop = prop.replace(
        bal=bal_next,
        phase=phase,
        heard=heard,
        commit_idx=commit_idx,
        recov_bal=recov_bal,
        recov_val=recov_val,
        lease_timer=lease_timer,
        last_chosen_count=last_chosen_count,
        candidate_timer=candidate_timer,
    )

    return state.replace(
        acceptor=acc,
        proposer=prop,
        learner=learner,
        requests=requests,
        promises=promises,
        accepted=accepted,
        tick=state.tick + 1,
    )


def multipaxos_step(
    state: MultiPaxosState, base_key: jax.Array, plan: FaultPlan, cfg: FaultConfig
) -> MultiPaxosState:
    """Advance every instance by one scheduler tick (XLA engine)."""
    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    key = jax.random.fold_in(base_key, state.tick)
    masks = sample_mp_masks(key, cfg, n_prop, n_acc, n_inst)
    return apply_tick_mp(state, masks, plan, cfg)
