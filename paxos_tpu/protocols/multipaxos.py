"""Multi-Paxos log replication — leader lease, crash, recovery (config 3).

Same fused-tick structure as :mod:`paxos_tpu.protocols.paxos` (one message
per acceptor per tick, commutative reply folds at proposers), extended with:

- **Whole-log phase 1**: a candidate's ``Prepare(b)`` covers all L slots;
  each ``Promise(b)`` carries the acceptor's full accepted-(ballot, value)
  log, max-folded per slot into the new leader's recovery arrays.
- **Slot-by-slot phase 2**: the leader re-proposes from slot 0, adopting the
  highest accepted value per slot (re-confirming chosen slots re-chooses the
  same value, so leadership changes are safe).  The leader re-broadcasts the
  current slot's ``Accept`` every tick — idempotent at acceptors and
  self-healing under message loss, so no per-slot retry machinery exists.
- **Progress leases**: failure detection by observed progress (SURVEY.md
  §4.4's declarative twin of monitors).  Every proposer watches the
  instance's chosen-slot count; ``lease_len`` ticks without progress make a
  follower start an election (staggered + jittered) and make a stale leader
  demote itself.
- **Leader crash windows** from the fault plan: a crashed proposer does
  nothing and drops to follower on recovery.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from paxos_tpu.check.mp_safety import mp_learner_observe, mp_margin_observe
from paxos_tpu.core import ballot as bal_mod
from paxos_tpu.core import streams as streams_mod
from paxos_tpu.core import telemetry as tel_mod
from paxos_tpu.obs import coverage as cov_mod
from paxos_tpu.obs import exposure as exp_mod
from paxos_tpu.core.messages import ACCEPT, PREPARE
from paxos_tpu.core.mp_state import (
    CANDIDATE,
    FOLLOW,
    LEAD,
    MultiPaxosState,
    bv_bal,
    bv_val,
    pack_bv,
)
from paxos_tpu.faults.injector import (
    FaultConfig,
    FaultPlan,
    bits_below,
    fault_site,
    links_dup,
    rate_threshold,
)
from paxos_tpu.kernels.quorum import majority, quorum_reached
from paxos_tpu.transport import inmemory_tpu as net
from paxos_tpu.workload import generator as wload_mod


def own_slot_value(pid: jnp.ndarray, slot: jnp.ndarray) -> jnp.ndarray:
    """Distinct per (proposer, slot) command payload: duels are observable."""
    return (pid + 1) * 1000 + slot


@struct.dataclass
class MPTickMasks:
    """One Multi-Paxos tick's pre-sampled randomness (instance-minor)."""

    sel_score: jnp.ndarray  # (2, P, A, I) int32 — request-selection entropy
    busy: Optional[jnp.ndarray]  # (1, 1, A, I) bool — False = acceptor idles
    dup_req: Optional[jnp.ndarray]  # (2, P, A, I) bool — request redelivered
    prom_deliver: Optional[jnp.ndarray]  # (P, A, I) bool — promise not held
    accd_deliver: Optional[jnp.ndarray]  # (P, A, I) bool — accepted not held
    keep_prom: Optional[jnp.ndarray]  # (P, A, I) bool — PROMISE not dropped
    keep_accd: Optional[jnp.ndarray]  # (P, A, I) bool — ACCEPTED not dropped
    keep_prep: Optional[jnp.ndarray]  # (P, A, I) bool — PREPARE not dropped
    keep_acc: Optional[jnp.ndarray]  # (P, A, I) bool — ACCEPT not dropped
    jitter: jnp.ndarray  # (P, I) int32 — election-threshold jitter
    backoff: jnp.ndarray  # (P, I) int32 — post-failure retreat draw
    # Gray-failure extensions (None unless the FaultConfig knob is on).
    # Raw PRNG bits, compared against the plan's per-link thresholds inside
    # apply_tick_mp — kind axis: 0=PROMISE 1=ACCEPTED 2=PREPARE 3=ACCEPT.
    link_bits: Optional[jnp.ndarray] = None  # (4, P, A, I) int32
    dup_bits: Optional[jnp.ndarray] = None  # (2, P, A, I) int32 — request dup
    corrupt: Optional[jnp.ndarray] = None  # (A, I) bool — in-flight bit flip
    # Bounded-delay (p_delay) raw bits, same kind axis as link_bits:
    # 0=PROMISE 1=ACCEPTED 2=PREPARE 3=ACCEPT.
    delay_bits: Optional[jnp.ndarray] = None  # (4, P, A, I) int32
    lat_bits: Optional[jnp.ndarray] = None  # (4, P, A, I) int32
    arrival_bits: Optional[jnp.ndarray] = None  # (P, I) int32 raw bits —
    #   client-arrival draws (workload plane; None unless the plane is on)


def sample_mp_masks(
    key: jax.Array, cfg: FaultConfig, n_prop: int, n_acc: int, n_inst: int,
    wload: bool = False,
) -> MPTickMasks:
    """Draw a tick's masks with ``jax.random`` (the XLA engine's source)."""
    (k_sel, k_idle, k_dup_req, k_hold_pr, k_hold_ac, k_drop_pr, k_drop_ac,
     k_drop_prep, k_drop_acc, k_jit, k_back) = jax.random.split(key, 11)
    slot = (2, n_prop, n_acc, n_inst)
    edge = (n_prop, n_acc, n_inst)
    # Per-link loss replaces the uniform keep/dup masks with raw bits the
    # tick compares against plan thresholds (fold_in via the registered
    # core.streams.TICK_FOLDS consts, never extra splits: the pre-gray
    # stream stays bit-identical when the knobs are off).  Gray folds are
    # gated on their knob so off knobs leave zero PRNG eqns in the trace
    # (audited at the jaxpr level by paxos_tpu/analysis).
    flaky = cfg.p_flaky > 0.0

    def raw_bits(name: str, shape):
        k = streams_mod.tick_fold(key, name)
        return jax.random.bits(k, shape, jnp.uint32).astype(jnp.int32)

    return MPTickMasks(
        sel_score=jax.random.bits(k_sel, slot, jnp.uint32).astype(jnp.int32),
        busy=net.keep_mask(k_idle, (1, 1, n_acc, n_inst), cfg.p_idle),
        dup_req=None if flaky else net.stay_mask(k_dup_req, slot, cfg.p_dup),
        prom_deliver=net.keep_mask(k_hold_pr, edge, cfg.p_hold),
        accd_deliver=net.keep_mask(k_hold_ac, edge, cfg.p_hold),
        keep_prom=None if flaky else net.keep_mask(k_drop_pr, edge, cfg.p_drop),
        keep_accd=None if flaky else net.keep_mask(k_drop_ac, edge, cfg.p_drop),
        keep_prep=None if flaky else net.keep_mask(k_drop_prep, edge, cfg.p_drop),
        keep_acc=None if flaky else net.keep_mask(k_drop_acc, edge, cfg.p_drop),
        jitter=jax.random.randint(
            k_jit, (n_prop, n_inst), 0, max(cfg.backoff_max, 1), jnp.int32
        ),
        backoff=jax.random.randint(
            k_back, (n_prop, n_inst), 0, 2 * max(cfg.backoff_max, 1), jnp.int32
        ),
        link_bits=raw_bits("LINK_BITS", (4,) + edge) if flaky else None,
        dup_bits=raw_bits("DUP_BITS", slot) if links_dup(cfg) else None,
        corrupt=(
            net.stay_mask(
                streams_mod.tick_fold(key, "CORRUPT"),
                (n_acc, n_inst),
                cfg.p_corrupt,
            )
            if cfg.p_corrupt > 0.0
            else None
        ),
        delay_bits=(
            raw_bits("DELAY_BITS", (4,) + edge)
            if cfg.p_delay > 0.0
            else None
        ),
        lat_bits=(
            raw_bits("LAT_BITS", (4,) + edge) if cfg.p_delay > 0.0 else None
        ),
        # Workload arrivals fold like the gray draws (off = zero eqns) but
        # on their own registered constant, gated on the wload plane.
        arrival_bits=(
            raw_bits("ARRIVAL_BITS", (n_prop, n_inst)) if wload else None
        ),
    )


def mp_counter_masks(
    cfg: FaultConfig, tick_seed: jax.Array, state,
    ablate: frozenset = frozenset(),
) -> MPTickMasks:
    """Draw a tick's masks from the counter PRNG (the fused engine's source).

    ``ablate={"prng"}``: constants instead of PRNG draws (timing-only; see
    ``protocols.paxos.counter_masks``)."""
    from paxos_tpu.kernels import counter_prng as cp

    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    slot = (2, n_prop, n_acc, n_inst)
    edge = (n_prop, n_acc, n_inst)
    if "prng" in ablate:
        return MPTickMasks(
            sel_score=jax.lax.broadcasted_iota(jnp.int32, slot, 3),
            busy=None, dup_req=None, prom_deliver=None, accd_deliver=None,
            keep_prom=None, keep_accd=None, keep_prep=None, keep_acc=None,
            jitter=jnp.zeros((n_prop, n_inst), jnp.int32),
            backoff=jnp.zeros((n_prop, n_inst), jnp.int32),
        )
    # Stream ids from the registry (core.streams.MULTI_PAXOS; gray_base=11
    # — BACKOFF landed on 10 before the gray layer and is frozen there).
    s = streams_mod.MULTI_PAXOS.streams
    flaky = cfg.p_flaky > 0.0
    return MPTickMasks(
        sel_score=cp.counter_bits(tick_seed, s["SEL"], slot),
        busy=cp.bern_not(
            tick_seed, s["BUSY"], (1, 1, n_acc, n_inst), cfg.p_idle
        ),
        dup_req=(
            None if flaky else cp.bern(tick_seed, s["DUP_REQ"], slot, cfg.p_dup)
        ),
        prom_deliver=cp.bern_not(tick_seed, s["PROM_DELIVER"], edge, cfg.p_hold),
        accd_deliver=cp.bern_not(tick_seed, s["ACCD_DELIVER"], edge, cfg.p_hold),
        keep_prom=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_PROM"], edge, cfg.p_drop)
        ),
        keep_accd=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_ACCD"], edge, cfg.p_drop)
        ),
        keep_prep=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_PREP"], edge, cfg.p_drop)
        ),
        keep_acc=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_ACC"], edge, cfg.p_drop)
        ),
        jitter=cp.randint(
            tick_seed, s["JITTER"], (n_prop, n_inst), max(cfg.backoff_max, 1)
        ),
        backoff=cp.randint(
            tick_seed, s["BACKOFF"], (n_prop, n_inst), 2 * max(cfg.backoff_max, 1)
        ),
        link_bits=(
            cp.counter_bits(tick_seed, s["LINK_BITS"], (4,) + edge)
            if flaky
            else None
        ),
        dup_bits=(
            cp.counter_bits(tick_seed, s["DUP_BITS"], slot)
            if links_dup(cfg)
            else None
        ),
        corrupt=cp.bern(
            tick_seed, s["CORRUPT"], (n_acc, n_inst), cfg.p_corrupt
        ),
        delay_bits=(
            cp.counter_bits(tick_seed, s["DELAY_BITS"], (4,) + edge)
            if cfg.p_delay > 0.0
            else None
        ),
        lat_bits=(
            cp.counter_bits(tick_seed, s["LAT_BITS"], (4,) + edge)
            if cfg.p_delay > 0.0
            else None
        ),
        arrival_bits=(
            cp.counter_bits(tick_seed, s["ARRIVAL"], (n_prop, n_inst))
            if state.wload is not None
            else None
        ),
    )


def apply_tick_mp(
    state: MultiPaxosState, masks: MPTickMasks, plan: FaultPlan, cfg: FaultConfig,
    ablate: frozenset = frozenset(),
) -> MultiPaxosState:
    """The pure Multi-Paxos transition for one tick over pre-sampled masks.

    ``ablate`` (dev-only; via ``fused_fns("multipaxos", ablate=...)``)
    disables a component at trace time for the fused-tick ablation tool —
    same flag set and caveats as ``protocols.paxos.apply_tick``
    ("learner", "sends", "select", "consume", "proposer"; ablated variants
    are timing-only, not the protocol)."""
    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    n_slots = state.log_len
    quorum = majority(n_acc)

    acc = state.acceptor
    prop = state.proposer
    alive = plan.alive(state.tick)  # (A, I)
    p_alive = plan.prop_alive(state.tick)  # (P, I)
    equiv = plan.equivocate  # (A, I)

    if cfg.stale_k > 0:  # bug injection: recovery restores a stale snapshot
        rec = plan.recovering(state.tick)
        acc = acc.replace(
            promised=jnp.where(rec, acc.snap_promised, acc.promised),
            log=jnp.where(rec[:, None], acc.snap_log, acc.log),
        )
        snap = jnp.broadcast_to(
            state.tick % jnp.int32(cfg.stale_k) == 0, rec.shape
        )
        acc = acc.replace(
            snap_promised=jnp.where(snap, acc.promised, acc.snap_promised),
            snap_log=jnp.where(snap[:, None], acc.log, acc.snap_log),
        )
    elif cfg.amnesia:  # bug injection: acceptor forgets durable state on recovery
        rec = plan.recovering(state.tick)
        acc = acc.replace(
            promised=jnp.where(rec, 0, acc.promised),
            log=jnp.where(rec[:, None], 0, acc.log),
        )

    # ---- Reply delivery decided & cleared before new writes (no clobber) ----
    if cfg.p_part > 0.0:
        if cfg.p_asym > 0.0:  # per-direction cuts (gray asymmetric links)
            link_req = plan.link_ok(state.tick, "req")  # (P, A, I)
            link_rep = plan.link_ok(state.tick, "rep")
        else:
            link_req = link_rep = plan.link_ok(state.tick)
    else:
        link_req = link_rep = None

    # Per-link loss/duplication: compare this tick's raw bits against the
    # plan's per-(p, a) thresholds; the uniform masks are the off path.
    if cfg.p_flaky > 0.0:
        with fault_site("flaky"):
            keep_prom = ~bits_below(masks.link_bits[0], plan.link_drop)
            keep_accd = ~bits_below(masks.link_bits[1], plan.link_drop)
            keep_prep = ~bits_below(masks.link_bits[2], plan.link_drop)
            keep_acc = ~bits_below(masks.link_bits[3], plan.link_drop)
            dup_req = (
                bits_below(masks.dup_bits, plan.link_dup[None])
                if masks.dup_bits is not None
                else None
            )
    else:
        keep_prom, keep_accd = masks.keep_prom, masks.keep_accd
        keep_prep, keep_acc = masks.keep_prep, masks.keep_acc
        dup_req = masks.dup_req

    # Bounded delay (p_delay): sample this tick's send latencies, capped by
    # the plan's per-link budget — the same arithmetic as
    # protocols.paxos.delay_stamps, inlined over MP's 4-kind edge shapes
    # (0=PROMISE 1=ACCEPTED 2=PREPARE 3=ACCEPT, matching link_bits).
    until_prom = until_accd = until_prep = until_acc = None
    delay_ext = None
    if cfg.p_delay > 0.0:
        with fault_site("delay"):
            lat = jnp.int32(1) + (
                masks.lat_bits & jnp.int32(0x7FFFFFFF)
            ) % jnp.int32(max(cfg.delay_max, 1))
            delay_ext = jnp.where(
                bits_below(masks.delay_bits, rate_threshold(cfg.p_delay)),
                jnp.minimum(lat, plan.link_delay[None]),
                0,
            )  # (4, P, A, I)
            stamps = jnp.where(delay_ext > 0, state.tick + 1 + delay_ext, 0)
            until_prom, until_accd = stamps[0], stamps[1]
            until_prep, until_acc = stamps[2], stamps[3]
    rdy_req = net.ready(state.requests, state.tick)  # (2, P, A, I) or None
    rdy_prom = net.ready(state.promises, state.tick)  # (P, A, I) or None
    rdy_accd = net.ready(state.accepted, state.tick)  # (P, A, I) or None

    prom_del = state.promises.present
    if masks.prom_deliver is not None:
        prom_del = prom_del & masks.prom_deliver
    accd_del = state.accepted.present
    if masks.accd_deliver is not None:
        accd_del = accd_del & masks.accd_deliver
    if rdy_prom is not None:  # delayed replies stay in flight, undelivered
        prom_del = prom_del & rdy_prom
        accd_del = accd_del & rdy_accd
    if link_rep is not None:  # partitioned links stall replies in flight
        prom_del = prom_del & link_rep
        accd_del = accd_del & link_rep
    if "consume" in ablate:
        promises, accepted = state.promises, state.accepted
    else:
        promises = state.promises.replace(
            present=state.promises.present & ~prom_del
        )
        accepted = state.accepted.replace(
            present=state.accepted.present & ~accd_del
        )

    # ---- Acceptor half-tick ----
    if "select" in ablate:
        # All-false via an iota compare rather than a constant: a folded
        # constant mask cascades constants through the whole kernel and
        # trips Mosaic's vector-layout pass (Check failed: limits <= dim).
        sel = (
            jax.lax.broadcasted_iota(
                jnp.int32, state.requests.present.shape,
                state.requests.present.ndim - 1,
            )
            < 0
        )
    else:
        req_present = state.requests.present
        if rdy_req is not None:  # delayed requests are invisible until due
            req_present = req_present & rdy_req
        sel = net.select_from_scores(
            req_present, masks.sel_score, masks.busy
        )
    sel = sel & alive[None, None]
    if link_req is not None:  # partitioned links stall requests in flight
        sel = sel & link_req[None]

    def gather(x):
        return jnp.where(sel, x, 0).sum(axis=(0, 1))

    msg_bal = gather(state.requests.bal)  # (A, I)
    msg_val = gather(state.requests.v1)  # (A, I)
    msg_slot = gather(state.requests.v2)  # (A, I)
    is_prep = sel[PREPARE].any(axis=0)
    is_acc = sel[ACCEPT].any(axis=0)

    if cfg.p_corrupt > 0.0:  # bug injection: in-flight bit flips, checker must flag
        msg_val = jnp.where(masks.corrupt & is_acc, msg_val ^ 64, msg_val)
        msg_bal = jnp.where(masks.corrupt & is_prep, msg_bal + 1, msg_bal)

    with fault_site("equivocate"):
        ok_prep_h = is_prep & ~equiv & (msg_bal > acc.promised)
        ok_prep = ok_prep_h | (is_prep & equiv)
        ok_acc_h = is_acc & ~equiv & (msg_bal >= acc.promised)
        ok_acc = ok_acc_h | (is_acc & equiv)

    promised = jnp.where(ok_prep_h, msg_bal, acc.promised)
    promised = jnp.where(ok_acc_h, jnp.maximum(promised, msg_bal), promised)
    slot_ids = jnp.arange(n_slots, dtype=jnp.int32)[None, :, None]  # (1, L, 1)
    oh_slot = msg_slot[:, None] == slot_ids  # (A, L, I)
    wr = ok_acc[:, None] & oh_slot
    log = jnp.where(wr, pack_bv(msg_bal, msg_val)[:, None], acc.log)

    # Promise replies carry the acceptor's full log (equivocators hide theirs).
    # (A lax.cond gate on "any promise sent this tick" was tried here and on
    # the recovery fold — elections are rare in steady state — but measured
    # SLOWER on hardware: 222.6M -> 205.4M r/s on config3.  The branchy
    # kernel costs more than the masked no-op writes it skips.)
    if "sends" not in ablate:
        prom_send = sel[PREPARE] & ok_prep[None]  # (P, A, I)
        if keep_prom is not None:
            prom_send = prom_send & keep_prom
        with fault_site("equivocate"):
            payload_bv = jnp.where(equiv[:, None], 0, acc.log)  # (A, L, I)
        new_prom_until = promises.until
        if promises.until is not None:
            new_prom_until = jnp.where(
                prom_send,
                until_prom if until_prom is not None else 0,
                promises.until,
            )
        promises = promises.replace(
            present=promises.present | prom_send,
            bal=jnp.where(prom_send, msg_bal[None], promises.bal),
            p_bv=jnp.where(
                prom_send[:, :, None], payload_bv[None], promises.p_bv
            ),
            until=new_prom_until,
        )

        accd_send = sel[ACCEPT] & ok_acc[None]  # (P, A, I)
        if keep_accd is not None:
            accd_send = accd_send & keep_accd
        new_accd_until = accepted.until
        if accepted.until is not None:
            new_accd_until = jnp.where(
                accd_send,
                until_accd if until_accd is not None else 0,
                accepted.until,
            )
        accepted = accepted.replace(
            present=accepted.present | accd_send,
            bal=jnp.where(accd_send, msg_bal[None], accepted.bal),
            slot=jnp.where(accd_send, msg_slot[None], accepted.slot),
            val=jnp.where(accd_send, msg_val[None], accepted.val),
            until=new_accd_until,
        )

    if "consume" in ablate:
        requests = state.requests
    else:
        requests = net.consume(state.requests, sel, stay=dup_req)
    acc = acc.replace(promised=promised, log=log)

    # ---- Learner / checker ----
    if "learner" in ablate:
        learner = state.learner
        chosen_count = jnp.zeros((n_inst,), jnp.int32)
    else:
        with jax.named_scope("learner_check"):
            learner = mp_learner_observe(
                state.learner, ok_acc, msg_bal, msg_slot, msg_val, state.tick,
                quorum,
            )
            chosen_count = learner.chosen.sum(axis=0, dtype=jnp.int32)  # (I,)

    if "proposer" in ablate:
        return state.replace(
            acceptor=acc,
            learner=learner,
            requests=requests,
            promises=promises,
            accepted=accepted,
            tick=state.tick + 1,
        )

    # ---- Proposer half-tick ----
    bits = (jnp.asarray(1, jnp.int32) << jnp.arange(n_acc, dtype=jnp.int32))[
        None, :, None
    ]  # (1, A, 1)
    cur_bal = prop.bal[:, None]  # (P, 1, I)

    # Promises (phase 1): voter bits + per-slot max-fold of recovery pairs.
    pv_ok = prom_del & (state.promises.bal == cur_bal) & (
        prop.phase == CANDIDATE
    )[:, None]  # (P, A, I)
    heard = prop.heard | jnp.where(pv_ok, bits, 0).sum(axis=1, dtype=jnp.int32)
    # Per-slot max-fold over acceptors.  Packed pairs order lexicographically
    # by (ballot, value), so ONE max replaces the old two-array max-trick
    # (ballot max + value ride-along): the ballot dominates, and at equal
    # ballot all honest acceptors store the same value per slot
    # (equivocators' payloads are zeroed), so the value tiebreak is inert.
    cand_bv = jnp.where(
        pv_ok[:, :, None], state.promises.p_bv, 0
    ).max(axis=1)  # (P, L, I)
    recov_bv = jnp.maximum(prop.recov_bv, cand_bv)

    # Accepted (phase 2): only votes for the slot currently being driven.
    av_ok = (
        accd_del
        & (state.accepted.bal == cur_bal)
        & (state.accepted.slot == prop.commit_idx[:, None])
        & (prop.phase == LEAD)[:, None]
    )
    heard = heard | jnp.where(av_ok, bits, 0).sum(axis=1, dtype=jnp.int32)

    # Transitions.
    p1_done = (prop.phase == CANDIDATE) & quorum_reached(heard, quorum)
    slot_done = (
        (prop.phase == LEAD)
        & quorum_reached(heard, quorum)
        & (prop.commit_idx < n_slots)
    )

    # Progress lease: any new chosen slot in this instance resets every
    # proposer's suspicion timer.
    progressed = chosen_count[None] > prop.last_chosen_count  # (P, I)
    lease_timer = jnp.where(progressed, 0, prop.lease_timer + 1)
    last_chosen_count = jnp.maximum(prop.last_chosen_count, chosen_count[None])

    log_full = chosen_count[None] >= n_slots  # (P, I): nothing left to do
    if cfg.log_total:
        # Long-log mode: the GLOBAL log is also exhausted once the compacted
        # prefix plus the window's chosen slots reach log_total (the window
        # refills after compaction, so window-full alone is transient).
        log_full = log_full | (
            (state.base + chosen_count)[None] >= cfg.log_total
        )
    lease_out = lease_timer > cfg.lease_len

    # Election trigger: staggered so proposers don't collide every time.
    pid = jnp.broadcast_to(
        jnp.arange(n_prop, dtype=jnp.int32)[:, None], prop.bal.shape
    )
    jitter = masks.jitter
    start_elec = (
        (prop.phase == FOLLOW)
        & p_alive
        & ~log_full
        & (lease_timer > cfg.lease_len + pid * 3 + jitter)
    )
    new_bal = bal_mod.make_ballot(
        bal_mod.ballot_round(prop.bal) + cfg.ballot_stride, pid
    )

    # Candidate timeout: back to follower, retry later with the next ballot.
    # Timeout skew (gray): each proposer lane runs its own deadline.
    with fault_site("skew"):
        timeout = (
            cfg.timeout
            if cfg.timeout_skew <= 0
            else cfg.timeout + plan.ptimeout
        )
    candidate_timer = jnp.where(prop.phase == CANDIDATE, prop.candidate_timer + 1, 0)
    cand_fail = (prop.phase == CANDIDATE) & (candidate_timer > timeout) & ~p1_done
    # Exposure (obs.exposure): a skewed timeout is EFFECTIVE only where the
    # candidacy-failure decision differs from the unskewed deadline's.
    # Taken here, before `candidate_timer` is reset below.
    exp_timeout_delta = None
    if state.exposure is not None and cfg.timeout_skew > 0:
        exp_timeout_delta = cand_fail ^ (
            (prop.phase == CANDIDATE) & (candidate_timer > cfg.timeout) & ~p1_done
        )

    # Stale leader demotes itself after a lease of no progress.
    demote = (prop.phase == LEAD) & lease_out & ~slot_done & ~log_full

    phase = prop.phase
    phase = jnp.where(start_elec, CANDIDATE, phase)
    phase = jnp.where(p1_done, LEAD, phase)
    phase = jnp.where(cand_fail | demote, FOLLOW, phase)
    phase = jnp.where(~p_alive, FOLLOW, phase)  # crashed -> follower on recovery

    bal_next = jnp.where(start_elec, new_bal, prop.bal)
    commit_idx = jnp.where(p1_done, 0, prop.commit_idx)
    commit_idx = jnp.where(slot_done, commit_idx + 1, commit_idx)
    heard = jnp.where(p1_done | slot_done | start_elec | cand_fail | demote, 0, heard)
    recov_bv = jnp.where(start_elec[:, None], 0, recov_bv)
    lease_timer = jnp.where(start_elec | p1_done | slot_done, 0, lease_timer)
    # Failed candidacy / demotion: retreat below the election threshold by a
    # random backoff so rivals separate instead of re-colliding every tick.
    # Backoff skew (gray): per-proposer multiplier stretches the retreat.
    with fault_site("skew"):
        backoff = (
            masks.backoff
            if cfg.backoff_skew <= 1
            else masks.backoff * plan.pboff
        )
    lease_timer = jnp.where(
        cand_fail | demote, cfg.lease_len - backoff, lease_timer
    )
    candidate_timer = jnp.where(start_elec, 0, candidate_timer)

    # ---- Emit ----
    # New candidates broadcast Prepare(b) once (retries via cand_fail cycle).
    prep_mask = jnp.broadcast_to(
        (start_elec & p_alive)[:, None], (n_prop, n_acc, n_inst)
    )
    if "sends" not in ablate:
        requests = net.send(
            requests, PREPARE,
            send_mask=prep_mask,
            bal=bal_next[:, None],
            v1=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
            v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
            keep=keep_prep,
            until=until_prep,
        )
    # Leaders re-broadcast the current slot's Accept every tick (idempotent,
    # self-healing under loss).
    is_lead = (phase == LEAD) & p_alive & (commit_idx < n_slots)
    if cfg.log_total:
        # Never drive a slot past the global log end: the window may extend
        # beyond log_total once most of the log is compacted away.
        is_lead = is_lead & (state.base[None] + commit_idx < cfg.log_total)
    ci = jnp.minimum(commit_idx, n_slots - 1)  # (P, I)
    ci_hot = ci[:, None] == jnp.arange(n_slots, dtype=jnp.int32)[None, :, None]
    rbv = jnp.where(ci_hot, recov_bv, 0).sum(axis=1)  # (P, I) packed
    # Command payloads are keyed by GLOBAL slot (base + window index), so a
    # slot's value is stable across window shifts (base is 0 in plain mode).
    pval = jnp.where(
        rbv > 0, bv_val(rbv), own_slot_value(pid, state.base[None] + ci)
    )
    if "sends" not in ablate:
        requests = net.send(
            requests, ACCEPT,
            send_mask=jnp.broadcast_to(is_lead[:, None], (n_prop, n_acc, n_inst)),
            bal=bal_next[:, None],
            v1=pval[:, None],
            v2=ci[:, None],
            keep=keep_acc,
            until=until_acc,
        )

    prop = prop.replace(
        bal=bal_next,
        phase=phase,
        heard=heard,
        commit_idx=commit_idx,
        recov_bv=recov_bv,
        lease_timer=lease_timer,
        last_chosen_count=last_chosen_count,
        candidate_timer=candidate_timer,
    )

    # ---- Observers (core.telemetry / obs.exposure): PRNG-free, from ----
    # signals the tick already produced, so enabling them cannot perturb
    # the schedule.  The effective-drop count is shared.
    tel = state.telemetry
    exp = state.exposure
    if tel is not None or exp is not None:
        dropped = None
        if keep_prom is not None:
            edge = (n_prop, n_acc, n_inst)
            dropped = (
                tel_mod.lane_count(sel[PREPARE] & ok_prep[None] & ~keep_prom)
                + tel_mod.lane_count(sel[ACCEPT] & ok_acc[None] & ~keep_accd)
                + tel_mod.lane_count(prep_mask & ~keep_prep)
                + tel_mod.lane_count(
                    jnp.broadcast_to(is_lead[:, None], edge) & ~keep_acc
                )
            )
    if tel is not None:
        tel = tel_mod.record(
            tel,
            state.tick,
            promise=ok_prep,
            accept=ok_acc,
            decide=learner.chosen & ~state.learner.chosen,
            conflict=learner.violations - state.learner.violations,
            leader=p1_done | demote,
            timeout=cand_fail,
            drop=dropped,
            dup=None if dup_req is None else sel & dup_req,
            corrupt=(
                masks.corrupt & (is_prep | is_acc)
                if cfg.p_corrupt > 0.0
                else None
            ),
            **tel_mod.fault_lane_events(plan, cfg, state.tick),
        )
    if exp is not None:
        # Injected-vs-effective per fault class (see obs.exposure).
        events = {}
        if keep_prom is not None:
            events["drop"] = (
                tel_mod.lane_count(~keep_prom)
                + tel_mod.lane_count(~keep_accd)
                + tel_mod.lane_count(~keep_prep)
                + tel_mod.lane_count(~keep_acc),
                dropped,
            )
        if dup_req is not None:
            events["dup"] = (
                tel_mod.lane_count(dup_req),
                tel_mod.lane_count(sel & dup_req),
            )
        if cfg.p_corrupt > 0.0:
            events["corrupt"] = (
                masks.corrupt,
                masks.corrupt & (is_prep | is_acc),
            )
        if link_req is not None:
            # Effective: in-flight messages the cut actually stalled (the
            # pre-tick present masks are the honest candidate set).
            events["partition"] = (
                tel_mod.lane_count(~link_req) + tel_mod.lane_count(~link_rep),
                tel_mod.lane_count(state.requests.present & ~link_req[None])
                + tel_mod.lane_count(state.promises.present & ~link_rep)
                + tel_mod.lane_count(state.accepted.present & ~link_rep),
            )
        if exp_timeout_delta is not None:
            events["timeout"] = (plan.ptimeout != 0, exp_timeout_delta)
        if cfg.stale_k > 0:
            events["stale"] = (rec, rec)
        if delay_ext is not None:
            # Effective: in-flight messages whose delivery this tick the
            # sampled delays actually stalled.
            events["delay"] = (
                tel_mod.lane_count(delay_ext > 0),
                tel_mod.lane_count(state.requests.present & ~rdy_req)
                + tel_mod.lane_count(state.promises.present & ~rdy_prom)
                + tel_mod.lane_count(state.accepted.present & ~rdy_accd),
            )
        exp = exp_mod.record(exp, **events)
    mar = state.margin
    if mar is not None:
        # Near-miss margin sketch (obs.margin): one promise fence covers
        # the whole log, so its slack partner is the per-acceptor max
        # accepted ballot over the (packed) log.
        mar = mp_margin_observe(
            mar, state.learner, learner, acc.promised,
            bv_bal(acc.log).max(axis=1), ~equiv, quorum,
        )
    wl = state.wload
    if wl is not None:
        # Client queue (workload.generator): a leader retires one queued
        # request per committed log slot (slot_done is the commit edge).
        with jax.named_scope(wload_mod.WLOAD_SCOPE):
            wl = wload_mod.observe(
                wl, state.tick, serve=slot_done,
                arrival_bits=masks.arrival_bits,
            )

    state = state.replace(
        acceptor=acc,
        proposer=prop,
        learner=learner,
        requests=requests,
        promises=promises,
        accepted=accepted,
        tick=state.tick + 1,
        telemetry=tel,
        exposure=exp,
        margin=mar,
        wload=wl,
    )
    # ---- Coverage sketch (obs.coverage): hash the post-tick state the ----
    # replace above just built (includes `base`, so the same window at a
    # different log position hashes differently).  PRNG-free.
    if state.coverage is not None:
        state = state.replace(coverage=cov_mod.observe(state.coverage, state))
    return state


def multipaxos_step(
    state: MultiPaxosState, base_key: jax.Array, plan: FaultPlan, cfg: FaultConfig
) -> MultiPaxosState:
    """Advance every instance by one scheduler tick (XLA engine)."""
    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    key = streams_mod.tick_key(base_key, state.tick)
    masks = sample_mp_masks(
        key, cfg, n_prop, n_acc, n_inst, wload=state.wload is not None
    )
    return apply_tick_mp(state, masks, plan, cfg)


# ---- Decided-prefix compaction (long-log mode; SURVEY.md §6.7, §8.4.6.6) ----


def _shift_slots(x: jnp.ndarray, shift: jnp.ndarray, axis: int, fill=0):
    """Shift the ``axis`` (log-slot) dimension down by a per-instance amount.

    ``shift`` is (I,) and broadcasts against ``x``'s trailing instances
    axis; vacated tail slots fill with ``fill``.  A shift, not a roll:
    compacted slots are gone, not wrapped.

    Implementation is an UNROLLED static-slice + select over the L+1
    possible shifts, not ``take_along_axis``: a gather along a middle axis
    with instance-varying indices lowers to per-element dynamic slices on
    TPU (measured 21 s per compaction at 1M instances — 125x the whole
    chunk it rode on).  L+1 statically-shifted copies of the SAME input
    folded through ``where`` fuse into one vectorized pass; a
    ceil(log2 L)-stage barrel shifter was tried and is ~3x SLOWER here —
    its stages chain sequentially (each reads the previous select's
    output), forcing XLA to materialize every intermediate, while the
    unrolled selects are all independent reads of ``x``.
    """
    L = x.shape[axis]
    fill_arr = jnp.full_like(x, fill)
    out = fill_arr  # shift == L (or anything >= L): everything vacated
    for k in range(L - 1, -1, -1):
        if k == 0:
            shifted = x
        else:
            shifted = jnp.concatenate(
                [
                    jax.lax.slice_in_dim(x, k, L, axis=axis),
                    jax.lax.slice_in_dim(fill_arr, 0, k, axis=axis),
                ],
                axis=axis,
            )
        out = jnp.where(shift == k, shifted, out)
    return out


def compact_mp_body(state: MultiPaxosState):
    """Compact each instance's contiguous chosen prefix out of the window.

    Returns ``(state', shift, evicted_vals)``: ``shift`` (I,) is the prefix
    length removed, ``evicted_vals`` (L, I) holds the evicted slots' chosen
    values (rows ``l < shift[i]``; callers needing the full replicated log
    accumulate these), and ``state'`` has every slot-indexed array shifted
    down with ``base += shift``.

    Soundness: only slots whose value is CHOSEN (and all slots below them)
    leave the window, so the agreement checker keeps sight of every slot
    that could still gain votes — except via in-flight ACCEPTs for
    compacted slots, which are dropped (their slot re-bases below 0).
    Dropping is indistinguishable from message loss, which the schedule
    space already contains; the finalized prefix is write-off-limits by
    construction.  Run between chunks, never inside one — either via the
    jitted :func:`compact_mp` or traced into the same dispatch as the
    chunk by ``harness.run.LongLog.wrap_advance``.
    """
    lrn, prop, acc = state.learner, state.proposer, state.acceptor
    L = state.log_len
    # Contiguous chosen prefix length per instance.
    shift = jnp.cumprod(lrn.chosen.astype(jnp.int32), axis=0).sum(axis=0)
    sl = jax.lax.broadcasted_iota(jnp.int32, lrn.chosen_val.shape, 0)
    evicted = jnp.where(sl < shift, lrn.chosen_val, 0)  # (L, I)

    def dec(x):  # window-relative cursors move down with the window
        return jnp.maximum(x - shift[None], 0)

    # In-flight ACCEPT slots re-base; those for compacted slots drop.
    req = state.requests
    acc_slot = req.v2[ACCEPT] - shift[None, None]
    req = req.replace(
        v2=req.v2.at[ACCEPT].set(acc_slot),
        present=req.present.at[ACCEPT].set(
            req.present[ACCEPT] & (acc_slot >= 0)
        ),
    )
    accd_slot = state.accepted.slot - shift[None, None]
    accepted = state.accepted.replace(
        slot=accd_slot,
        present=state.accepted.present & (accd_slot >= 0),
    )

    return (
        state.replace(
            acceptor=acc.replace(
                log=_shift_slots(acc.log, shift, 1),
            ),
            proposer=prop.replace(
                commit_idx=dec(prop.commit_idx),
                last_chosen_count=dec(prop.last_chosen_count),
                recov_bv=_shift_slots(prop.recov_bv, shift, 1),
                # A leader whose in-progress slot was compacted under it
                # (shift > commit_idx) clamps to window slot 0 — a DIFFERENT
                # global slot — so ACCEPTED votes folded for the old slot
                # must not count toward the new one's quorum: clear heard
                # and re-collect (leaders re-broadcast ACCEPT every tick).
                # Candidate heard is slot-agnostic (promises cover the whole
                # log) and keeps.  Liveness-only either way, but the honest
                # accounting costs nothing.
                heard=jnp.where(
                    (prop.phase == LEAD) & (shift[None] > prop.commit_idx),
                    0,
                    prop.heard,
                ),
            ),
            learner=lrn.replace(
                lt_bv=_shift_slots(lrn.lt_bv, shift, 0),
                lt_mask=_shift_slots(lrn.lt_mask, shift, 0),
                chosen=_shift_slots(lrn.chosen, shift, 0, fill=False),
                chosen_val=_shift_slots(lrn.chosen_val, shift, 0),
                chosen_tick=_shift_slots(lrn.chosen_tick, shift, 0, fill=-1),
            ),
            requests=req,
            # In-flight promises DROP on compaction instead of shifting:
            # their (P, A, L, I) packed payload is the largest array in the
            # state, and the 17-pass shift on it dominated compaction
            # cost.  Dropping is just message loss (a candidate re-elects on
            # timeout), which the schedule space already contains — never a
            # safety event.  Replies with zero shift keep flying.
            promises=state.promises.replace(
                present=state.promises.present & (shift == 0)
            ),
            accepted=accepted,
            base=state.base + shift,
        ),
        shift,
        evicted,
    )


compact_mp = functools.partial(jax.jit, donate_argnums=(0,))(compact_mp_body)
compact_mp.__doc__ = compact_mp_body.__doc__
