"""Single-decree Paxos as one fused array program per scheduler tick.

Reference parity (SURVEY.md §4.2-§4.3): the reference's proposer ballot round
— `send (Prepare b)` to every acceptor, `receiveWait` promises until
majority, adopt the highest-ballot accepted value, `send (Accept b v)`,
collect Accepted until majority, retry with a higher ballot on timeout — and
the acceptor/learner `expect` loops all collapse into :func:`paxos_step`:
one tick = deliver (masked gathers) → role transitions (pure elementwise
updates) → emit (masked scatters), batched over every instance at once.

Scheduling model (SURVEY.md §8.1): each acceptor processes at most ONE
in-flight request per tick, chosen uniformly at random — the asynchronous
adversarial scheduler.  Proposers fold ALL delivered replies per tick, which
is sound because the fold is a commutative monoid (voter-bitmask OR, running
max of prev-accepted ballots): any interleaving gives the same result, so
batching loses no adversarial coverage.  Delay, loss, duplication, crashes
and equivocation come from `paxos_tpu.faults` masks.

The famous killer interleavings survive vectorization:

- *accept-old-ballot-after-new-promise*: a stale ACCEPT slot can be selected
  after the acceptor promised a higher ballot; `msg_bal >= promised` rejects.
- *dueling proposers*: both proposers' PREPAREs race per tick; retries pick
  fresh ballots with randomized backoff.

Structure: the tick is split into :func:`sample_masks` (all of a tick's
randomness, drawn with ``jax.random``) and :func:`apply_tick` (the pure
protocol transition over pre-sampled masks).  The fused Pallas engine
(``kernels/fused_tick``) re-uses :func:`apply_tick` verbatim, swapping only
the mask source for the on-core hardware PRNG — one source of truth for the
protocol semantics.

Layout: every array is instance-minor — acceptors (A, I), proposers (P, I),
message slots (2, P, A, I) — so the whole tick is full-lane elementwise work
(see ``core.messages``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from paxos_tpu.check.safety import (
    acceptor_invariants,
    learner_observe,
    margin_observe,
)
from paxos_tpu.core import ballot as bal_mod
from paxos_tpu.core import streams as streams_mod
from paxos_tpu.core import telemetry as tel_mod
from paxos_tpu.obs import coverage as cov_mod
from paxos_tpu.obs import exposure as exp_mod
from paxos_tpu.core.messages import ACCEPT, ACCEPTED, PREPARE, PROMISE
from paxos_tpu.core.state import DONE, P1, P2, PaxosState
from paxos_tpu.faults.injector import (
    FaultConfig,
    FaultPlan,
    bits_below,
    fault_site,
    links_dup,
    rate_threshold,
)
from paxos_tpu.kernels.quorum import majority, quorum_reached
from paxos_tpu.transport import inmemory_tpu as net
from paxos_tpu.workload import generator as wload_mod


@struct.dataclass
class TickMasks:
    """One tick's worth of pre-sampled randomness (instance-minor shapes).

    ``None`` members mean "fault disabled" — the corresponding branch is
    skipped entirely at trace time (all mask presence is decided by the
    static :class:`FaultConfig`).
    """

    sel_score: jnp.ndarray  # (2, P, A, I) int32 — request-selection entropy
    busy: Optional[jnp.ndarray]  # (1, 1, A, I) bool — False = acceptor idles
    deliver: Optional[jnp.ndarray]  # (2, P, A, I) bool — reply not held
    dup_req: Optional[jnp.ndarray]  # (2, P, A, I) bool — request redelivered
    dup_rep: Optional[jnp.ndarray]  # (2, P, A, I) bool — reply redelivered
    keep_prom: Optional[jnp.ndarray]  # (P, A, I) bool — PROMISE not dropped
    keep_accd: Optional[jnp.ndarray]  # (P, A, I) bool — ACCEPTED not dropped
    keep_p1: Optional[jnp.ndarray]  # (P, A, I) bool — PREPARE not dropped
    keep_p2: Optional[jnp.ndarray]  # (P, A, I) bool — ACCEPT not dropped
    backoff: jnp.ndarray  # (P, I) int32 — retry backoff draw
    # Gray failures (None unless the owning FaultConfig knob is on).  With
    # p_flaky > 0 the keep_*/dup_* masks above are None and delivery draws
    # come from these raw bits, compared in apply_tick against the plan's
    # per-link thresholds (FaultPlan.link_drop / link_dup).
    link_bits: Optional[jnp.ndarray] = None  # (4, P, A, I) int32 raw bits,
    #   kind axis: 0=PROMISE 1=ACCEPTED 2=PREPARE 3=ACCEPT sends
    dup_bits: Optional[jnp.ndarray] = None  # (2, 2, P, A, I) int32 raw bits,
    #   leading axis: 0=requests 1=replies
    corrupt: Optional[jnp.ndarray] = None  # (A, I) bool — payload perturbed
    delay_bits: Optional[jnp.ndarray] = None  # (2, 2, P, A, I) int32 raw
    #   bits — per-send delay decision (p_delay); axis 0: 0=requests 1=replies
    lat_bits: Optional[jnp.ndarray] = None  # (2, 2, P, A, I) int32 raw bits
    #   — sampled latency, reduced mod delay_max and capped per link
    arrival_bits: Optional[jnp.ndarray] = None  # (P, I) int32 raw bits —
    #   client-arrival draws (workload plane; None unless the plane is on)


def sample_masks(
    key: jax.Array, cfg: FaultConfig, n_prop: int, n_acc: int, n_inst: int,
    wload: bool = False,
) -> TickMasks:
    """Draw a tick's masks with ``jax.random`` (the XLA engine's source)."""
    (k_sel, k_idle, k_dup_req, k_hold, k_dup_rep, k_drop_prom, k_drop_accd,
     k_drop_p1, k_drop_p2, k_backoff) = jax.random.split(key, 10)
    slot = (2, n_prop, n_acc, n_inst)
    edge = (n_prop, n_acc, n_inst)

    # Gray draws use fold_in-derived keys (core.streams.TICK_FOLDS), NOT
    # extra splits: the 10-way split above must keep producing the exact
    # pre-gray streams when every gray knob is off.  Gray folds are also
    # GATED on their knob — an off knob must leave zero PRNG eqns in the
    # traced tick, which the jaxpr auditor (paxos_tpu/analysis) enforces.
    flaky = cfg.p_flaky > 0.0

    def raw_bits(name: str, shape):
        k = streams_mod.tick_fold(key, name)
        return jax.random.bits(k, shape, jnp.uint32).astype(jnp.int32)

    return TickMasks(
        # int32 everywhere (matching the counter-PRNG path and Mosaic's
        # signed-only lowering); the uint32→int32 astype wraps bit-exactly.
        sel_score=jax.random.bits(k_sel, slot, jnp.uint32).astype(jnp.int32),
        busy=net.keep_mask(k_idle, (1, 1, n_acc, n_inst), cfg.p_idle),
        deliver=net.keep_mask(k_hold, slot, cfg.p_hold),
        dup_req=None if flaky else net.stay_mask(k_dup_req, slot, cfg.p_dup),
        dup_rep=None if flaky else net.stay_mask(k_dup_rep, slot, cfg.p_dup),
        keep_prom=(
            None if flaky else net.keep_mask(k_drop_prom, edge, cfg.p_drop)
        ),
        keep_accd=(
            None if flaky else net.keep_mask(k_drop_accd, edge, cfg.p_drop)
        ),
        keep_p1=None if flaky else net.keep_mask(k_drop_p1, edge, cfg.p_drop),
        keep_p2=None if flaky else net.keep_mask(k_drop_p2, edge, cfg.p_drop),
        backoff=jax.random.randint(
            k_backoff, (n_prop, n_inst), 0, max(cfg.backoff_max, 1), jnp.int32
        ),
        link_bits=raw_bits("LINK_BITS", (4,) + edge) if flaky else None,
        dup_bits=raw_bits("DUP_BITS", (2,) + slot) if links_dup(cfg) else None,
        corrupt=(
            net.stay_mask(
                streams_mod.tick_fold(key, "CORRUPT"),
                (n_acc, n_inst),
                cfg.p_corrupt,
            )
            if cfg.p_corrupt > 0.0
            else None
        ),
        delay_bits=(
            raw_bits("DELAY_BITS", (2,) + slot) if cfg.p_delay > 0.0 else None
        ),
        lat_bits=(
            raw_bits("LAT_BITS", (2,) + slot) if cfg.p_delay > 0.0 else None
        ),
        # Workload arrivals fold like the gray draws (off = zero eqns) but
        # on their own registered constant, gated on the wload plane.
        arrival_bits=(
            raw_bits("ARRIVAL_BITS", (n_prop, n_inst)) if wload else None
        ),
    )


def counter_masks(
    cfg: FaultConfig, tick_seed: jax.Array, state: PaxosState,
    ablate: frozenset = frozenset(),
) -> TickMasks:
    """Draw a tick's masks from the counter PRNG (the fused engine's source).

    Same mask shapes and probabilities as :func:`sample_masks`, different
    (but equally deterministic) stream; pure jnp, so it traces identically
    inside Pallas kernels and in plain XLA (``kernels/counter_prng``).

    ``ablate={"prng"}`` (dev-only, ``fused_fns(..., ablate=...)``): replace
    every PRNG draw with constants — a fixed selection-score pattern and
    fault-free None masks — to measure the counter-PRNG's share of the
    fused tick.  NOT a valid protocol schedule (selection entropy is the
    adversarial scheduler); timing-only.
    """
    from paxos_tpu.kernels import counter_prng as cp

    # Shapes from the request buffer: present in every protocol state that
    # shares these mask shapes (paxos, fastpaxos, raftcore).
    _, n_prop, n_acc, n_inst = state.requests.present.shape
    slot = (2, n_prop, n_acc, n_inst)
    edge = (n_prop, n_acc, n_inst)
    if "prng" in ablate:
        return TickMasks(
            sel_score=jax.lax.broadcasted_iota(jnp.int32, slot, 3),
            busy=None, deliver=None, dup_req=None, dup_rep=None,
            keep_prom=None, keep_accd=None, keep_p1=None, keep_p2=None,
            backoff=jnp.zeros((n_prop, n_inst), jnp.int32),
        )
    # Stream ids come from the registry (core.streams.SINGLE_DECREE): gray
    # draws live on streams >= gray_base (10) so streams 0-9 stay the exact
    # pre-gray schedule when every gray knob is off.
    s = streams_mod.SINGLE_DECREE.streams
    flaky = cfg.p_flaky > 0.0
    return TickMasks(
        sel_score=cp.counter_bits(tick_seed, s["SEL"], slot),
        busy=cp.bern_not(
            tick_seed, s["BUSY"], (1, 1, n_acc, n_inst), cfg.p_idle
        ),
        deliver=cp.bern_not(tick_seed, s["DELIVER"], slot, cfg.p_hold),
        dup_req=(
            None if flaky else cp.bern(tick_seed, s["DUP_REQ"], slot, cfg.p_dup)
        ),
        dup_rep=(
            None if flaky else cp.bern(tick_seed, s["DUP_REP"], slot, cfg.p_dup)
        ),
        keep_prom=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_PROM"], edge, cfg.p_drop)
        ),
        keep_accd=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_ACCD"], edge, cfg.p_drop)
        ),
        keep_p1=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_P1"], edge, cfg.p_drop)
        ),
        keep_p2=(
            None
            if flaky
            else cp.bern_not(tick_seed, s["KEEP_P2"], edge, cfg.p_drop)
        ),
        backoff=cp.randint(
            tick_seed, s["BACKOFF"], (n_prop, n_inst), max(cfg.backoff_max, 1)
        ),
        link_bits=(
            cp.counter_bits(tick_seed, s["LINK_BITS"], (4,) + edge)
            if flaky
            else None
        ),
        dup_bits=(
            cp.counter_bits(tick_seed, s["DUP_BITS"], (2,) + slot)
            if links_dup(cfg)
            else None
        ),
        corrupt=cp.bern(
            tick_seed, s["CORRUPT"], (n_acc, n_inst), cfg.p_corrupt
        ),
        delay_bits=(
            cp.counter_bits(tick_seed, s["DELAY_BITS"], (2,) + slot)
            if cfg.p_delay > 0.0
            else None
        ),
        lat_bits=(
            cp.counter_bits(tick_seed, s["LAT_BITS"], (2,) + slot)
            if cfg.p_delay > 0.0
            else None
        ),
        arrival_bits=(
            cp.counter_bits(tick_seed, s["ARRIVAL"], (n_prop, n_inst))
            if state.wload is not None
            else None
        ),
    )


def delay_stamps(masks: TickMasks, plan: FaultPlan, cfg: FaultConfig, tick):
    """Sampled bounded-delay stamps for this tick's sends (p_delay).

    Each send edge is delayed with probability ``p_delay`` by a latency
    ``1 + lat_bits % delay_max``, capped by the plan's per-link cap
    (``link_delay``; cap 0 = the link never delays).  Returns
    ``(until_req, until_rep, ext)``: per-direction (2, P, A, I) int32
    earliest-delivery ticks (0 = deliverable immediately) and the raw
    (2, 2, P, A, I) extra-latency draws for exposure accounting — or
    ``(None, None, None)`` when delay is off (zero traced eqns).

    Shared by paxos / fastpaxos / raftcore / synchpaxos (the single-decree
    mask shapes); multipaxos inlines the same arithmetic over its shapes.
    """
    if cfg.p_delay <= 0.0:
        return None, None, None
    with fault_site("delay"):
        # All-int32 arithmetic (Mosaic-safe): mask the sign bit before the
        # modulo so the latency draw stays in [1, delay_max].
        lat = jnp.int32(1) + (
            masks.lat_bits & jnp.int32(0x7FFFFFFF)
        ) % jnp.int32(max(cfg.delay_max, 1))
        ext = jnp.where(
            bits_below(masks.delay_bits, rate_threshold(cfg.p_delay)),
            jnp.minimum(lat, plan.link_delay[None, None]),
            0,
        )  # (2, 2, P, A, I); axis 0: 0=requests 1=replies
        until_req = jnp.where(ext[0] > 0, tick + 1 + ext[0], 0)
        until_rep = jnp.where(ext[1] > 0, tick + 1 + ext[1], 0)
    return until_req, until_rep, ext


def apply_tick(
    state: PaxosState, masks: TickMasks, plan: FaultPlan, cfg: FaultConfig,
    ablate: frozenset = frozenset(),
) -> PaxosState:
    """The pure protocol transition for one tick over pre-sampled masks.

    ``ablate`` (dev-only; reach it via ``fused_fns(protocol, ablate=...)``)
    disables a component AT TRACE TIME so the fused kernel compiles without
    it — the ablation tool for locating the hot spots of the fused tick
    (VERDICT r3 #7; scripts/ablate_fused.py), replacing the old
    monkeypatching approach with flags the compiler sees:

    - ``"learner"``: skip the omniscient checker + acceptor invariants;
    - ``"sends"``:   skip every ``net.send`` (replies AND request emits);
    - ``"select"``:  acceptors select nothing (no request processing);
    - ``"consume"``: delivered/selected buffers are never cleared;
    - ``"proposer"``: skip the proposer half-tick entirely.

    Ablated variants are NOT the protocol (safety/liveness meaningless);
    they exist to be timed against the full kernel.
    """
    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    quorum = majority(n_acc)
    # Flexible Paxos: explicit phase-1/phase-2 quorums (0 = classic majority).
    # Safe iff q1 + q2 > n_acc; unsafe pairs are a bug-injection mode the
    # checker must catch (see tests/test_flexpaxos.py).
    q1 = cfg.q1 or quorum
    q2 = cfg.q2 or quorum

    acc = state.acceptor
    alive = plan.alive(state.tick)  # (A, I)
    equiv = plan.equivocate  # (A, I)

    if cfg.stale_k > 0:
        # Bug injection: recovery restores the snapshot from the last
        # multiple of stale_k ticks — up to stale_k ticks of promises and
        # accepts silently lost (amnesia generalized from "lose all").
        # Restored BEFORE acc_pre: the checker must flag the protocol
        # consequences (conflicting choices), not the rollback write itself.
        rec = plan.recovering(state.tick)
        acc = acc.replace(
            promised=jnp.where(rec, acc.snap_promised, acc.promised),
            acc_bal=jnp.where(rec, acc.snap_bal, acc.acc_bal),
            acc_val=jnp.where(rec, acc.snap_val, acc.acc_val),
        )
        # Refresh AFTER restore: a snapshot boundary landing on the
        # recovery tick re-snapshots the (stale) restored state.
        snap = jnp.broadcast_to(
            state.tick % jnp.int32(cfg.stale_k) == 0, rec.shape
        )
        acc = acc.replace(
            snap_promised=jnp.where(snap, acc.promised, acc.snap_promised),
            snap_bal=jnp.where(snap, acc.acc_bal, acc.snap_bal),
            snap_val=jnp.where(snap, acc.acc_val, acc.snap_val),
        )
    elif cfg.amnesia:  # bug injection: acceptor forgets durable state on recovery
        rec = plan.recovering(state.tick)
        acc = acc.replace(
            promised=jnp.where(rec, 0, acc.promised),
            acc_bal=jnp.where(rec, 0, acc.acc_bal),
            acc_val=jnp.where(rec, 0, acc.acc_val),
        )
    acc_pre = acc

    # Reply delivery is decided (and delivered slots are cleared) BEFORE the
    # acceptor half-tick writes new replies: otherwise a reply written this
    # tick could land in a slot being consumed and be lost even on a
    # fault-free network.  Proposers read payloads from the pre-tick buffer.
    # Asymmetric cuts (p_asym) split the link view per traffic direction;
    # symmetric plans use one view for both (the identical trace).
    if cfg.p_part > 0.0:
        if cfg.p_asym > 0.0:
            link_req = plan.link_ok(state.tick, "req")  # (P, A, I)
            link_rep = plan.link_ok(state.tick, "rep")
        else:
            link_req = link_rep = plan.link_ok(state.tick)
    else:
        link_req = link_rep = None

    # Per-link loss/duplication (p_flaky): this tick's raw bits vs the
    # plan's per-link thresholds; p_flaky == 0 is the uniform special case
    # carried by the scalar-threshold masks.
    if cfg.p_flaky > 0.0:
        with fault_site("flaky"):
            keep_prom = ~bits_below(masks.link_bits[0], plan.link_drop)
            keep_accd = ~bits_below(masks.link_bits[1], plan.link_drop)
            keep_p1 = ~bits_below(masks.link_bits[2], plan.link_drop)
            keep_p2 = ~bits_below(masks.link_bits[3], plan.link_drop)
            if masks.dup_bits is not None:
                dup_req = bits_below(masks.dup_bits[0], plan.link_dup[None])
                dup_rep = bits_below(masks.dup_bits[1], plan.link_dup[None])
            else:
                dup_req = dup_rep = None
    else:
        keep_prom, keep_accd = masks.keep_prom, masks.keep_accd
        keep_p1, keep_p2 = masks.keep_p1, masks.keep_p2
        dup_req, dup_rep = masks.dup_req, masks.dup_rep

    # Bounded delay (p_delay): this tick's send stamps, and readiness gates
    # over the in-flight buffers.  A stalled slot is invisible to delivery
    # (requests) and folding (replies) but never cleared — delay alone can
    # not lose or duplicate a message (tests/test_delay.py pins this).
    until_req, until_rep, delay_ext = delay_stamps(
        masks, plan, cfg, state.tick
    )
    rdy_req = net.ready(state.requests, state.tick)
    rdy_rep = net.ready(state.replies, state.tick)

    delivered = state.replies.present
    if masks.deliver is not None:
        delivered = delivered & masks.deliver
    if rdy_rep is not None:  # delayed replies have not arrived yet
        delivered = delivered & rdy_rep
    if link_rep is not None:  # partitioned links stall replies in flight
        delivered = delivered & link_rep[None]
    if "consume" in ablate:
        replies = state.replies
    else:
        replies = net.consume(state.replies, delivered, stay=dup_rep)

    # ---- Acceptor half-tick: select one request per (instance, acceptor) ----
    if "select" in ablate:
        # All-false via an iota compare rather than a constant: a folded
        # constant mask cascades constants through the whole kernel and
        # trips Mosaic's vector-layout pass (Check failed: limits <= dim).
        sel = (
            jax.lax.broadcasted_iota(
                jnp.int32, state.requests.present.shape,
                state.requests.present.ndim - 1,
            )
            < 0
        )
    else:
        req_present = state.requests.present
        if rdy_req is not None:  # delayed requests have not arrived yet
            req_present = req_present & rdy_req
        sel = net.select_from_scores(
            req_present, masks.sel_score, masks.busy
        )
    sel = sel & alive[None, None]  # crashed acceptors process nothing
    if link_req is not None:  # partitioned links stall requests in flight
        sel = sel & link_req[None]

    # Gather the selected message's fields onto (A, I).
    def gather(x):
        return jnp.where(sel, x, 0).sum(axis=(0, 1))

    msg_bal = gather(state.requests.bal)  # (A, I)
    msg_val = gather(state.requests.v1)  # (A, I) (ACCEPT payload)
    is_prep = sel[PREPARE].any(axis=0)  # (A, I)
    is_acc = sel[ACCEPT].any(axis=0)  # (A, I)

    if cfg.p_corrupt > 0.0:
        # Bug injection: the payload is perturbed between send and process.
        # An ACCEPT's value flips bits (xor stays clear of every legitimate
        # value encoding) — acceptors then vote for a value nobody proposed,
        # which the agreement checker MUST flag; a PREPARE's ballot bumps,
        # impersonating a neighboring proposer's ballot (liveness chaos).
        msg_val = jnp.where(masks.corrupt & is_acc, msg_val ^ 64, msg_val)
        msg_bal = jnp.where(masks.corrupt & is_prep, msg_bal + 1, msg_bal)

    # PREPARE(b): honest promise iff b > promised; equivocators "promise"
    # unconditionally, never record it, and hide their accepted pair.
    with fault_site("equivocate"):
        ok_prep_h = is_prep & ~equiv & (msg_bal > acc.promised)
        ok_prep = ok_prep_h | (is_prep & equiv)
        # ACCEPT(b, v): honest iff b >= promised; equivocators accept all.
        ok_acc_h = is_acc & ~equiv & (msg_bal >= acc.promised)
        ok_acc = ok_acc_h | (is_acc & equiv)

        promised = jnp.where(ok_prep_h, msg_bal, acc.promised)
        promised = jnp.where(
            ok_acc_h, jnp.maximum(promised, msg_bal), promised
        )
        acc_bal = jnp.where(ok_acc, msg_bal, acc.acc_bal)
        acc_val = jnp.where(ok_acc, msg_val, acc.acc_val)

        # Replies routed back to the selected sender's slot.
        prom_payload_bal = jnp.where(equiv, 0, acc.acc_bal)  # pre-update
        prom_payload_val = jnp.where(equiv, 0, acc.acc_val)
    if "sends" not in ablate:
        replies = net.send(
            replies, PROMISE,
            send_mask=sel[PREPARE] & ok_prep[None],
            bal=msg_bal[None],
            v1=prom_payload_bal[None],
            v2=prom_payload_val[None],
            keep=keep_prom,
            until=None if until_rep is None else until_rep[PROMISE],
        )
        replies = net.send(
            replies, ACCEPTED,
            send_mask=sel[ACCEPT] & ok_acc[None],
            bal=msg_bal[None],
            v1=msg_val[None],
            v2=jnp.zeros_like(msg_val)[None],
            keep=keep_accd,
            until=None if until_rep is None else until_rep[ACCEPTED],
        )
    if "consume" in ablate:
        requests = state.requests
    else:
        requests = net.consume(state.requests, sel, stay=dup_req)
    acc = acc.replace(promised=promised, acc_bal=acc_bal, acc_val=acc_val)

    # ---- Learner / safety checker (omniscient: sees accept events directly) ----
    if "learner" in ablate:
        learner = state.learner
    else:
        with jax.named_scope("learner_check"):
            learner = learner_observe(
                state.learner, ok_acc, msg_bal, msg_val, state.tick, q2
            )
            with fault_site("equivocate"):
                inv_viol = acceptor_invariants(acc_pre, acc, honest=~equiv)
            learner = learner.replace(
                violations=learner.violations + inv_viol
            )

    if "proposer" in ablate:
        return state.replace(
            acceptor=acc,
            learner=learner,
            requests=requests,
            replies=replies,
            tick=state.tick + 1,
        )

    # ---- Proposer half-tick: fold all delivered replies ----
    prop = state.proposer
    # (1, A, 1) voter bit per acceptor, broadcast against (P, A, I).
    bits = (jnp.asarray(1, jnp.int32) << jnp.arange(n_acc, dtype=jnp.int32))[
        None, :, None
    ]

    cur_bal = prop.bal[:, None]  # (P, 1, I)
    prom_ok = (
        delivered[PROMISE]
        & (state.replies.bal[PROMISE] == cur_bal)
        & (prop.phase == P1)[:, None]
    )  # (P, A, I)
    accd_ok = (
        delivered[ACCEPTED]
        & (state.replies.bal[ACCEPTED] == cur_bal)
        & (prop.phase == P2)[:, None]
    )
    heard = (
        prop.heard
        | jnp.where(prom_ok, bits, 0).sum(axis=1, dtype=jnp.int32)
        | jnp.where(accd_ok, bits, 0).sum(axis=1, dtype=jnp.int32)
    )  # (P, I)

    # Highest previously-accepted (ballot, value) among valid promises.  The
    # value ride-along is a max-trick, not a gather: among slots achieving the
    # max ballot the values agree (honest acceptors store one value per
    # ballot; equivocators' payloads are zeroed), and a zero max means "none".
    prev_bal = jnp.where(prom_ok, state.replies.v1[PROMISE], 0)  # (P, A, I)
    cand_bal = prev_bal.max(axis=1)  # (P, I)
    cand_val = jnp.where(
        prev_bal == cand_bal[:, None], state.replies.v2[PROMISE], 0
    ).max(axis=1)
    upgrade = cand_bal > prop.best_bal
    best_bal = jnp.where(upgrade, cand_bal, prop.best_bal)
    best_val = jnp.where(upgrade, cand_val, prop.best_val)

    # Phase transitions.
    p1_done = (prop.phase == P1) & quorum_reached(heard, q1)
    p2_done = (prop.phase == P2) & quorum_reached(heard, q2)
    v_chosen_by_p1 = jnp.where(best_bal > 0, best_val, prop.own_val)

    timer = jnp.where(prop.phase == DONE, prop.timer, prop.timer + 1)
    # Timer skew (timeout_skew / backoff_skew): per-proposer extra patience
    # and backoff multipliers from the plan; off = the uniform timers.
    with fault_site("skew"):
        timeout = (
            cfg.timeout
            if cfg.timeout_skew <= 0
            else cfg.timeout + plan.ptimeout
        )
        backoff = (
            masks.backoff
            if cfg.backoff_skew <= 1
            else masks.backoff * plan.pboff
        )
    expired = (
        (prop.phase != DONE) & ~p1_done & ~p2_done & (timer > timeout)
    )
    # Exposure (obs.exposure): a skewed timeout is EFFECTIVE only where the
    # expiry decision differs from the unskewed timer's.  Must be taken
    # here, before `timer` is rebased below.
    exp_timeout_delta = None
    if state.exposure is not None and cfg.timeout_skew > 0:
        exp_timeout_delta = expired ^ (
            (prop.phase != DONE) & ~p1_done & ~p2_done & (timer > cfg.timeout)
        )
    pid = jnp.broadcast_to(
        jnp.arange(n_prop, dtype=jnp.int32)[:, None], timer.shape
    )
    new_bal = bal_mod.make_ballot(
        bal_mod.ballot_round(prop.bal) + cfg.ballot_stride, pid
    )

    phase = jnp.where(p1_done, P2, prop.phase)
    phase = jnp.where(p2_done, DONE, phase)
    phase = jnp.where(expired, P1, phase)
    prop_val = jnp.where(p1_done, v_chosen_by_p1, prop.prop_val)
    decided_val = jnp.where(p2_done, prop.prop_val, prop.decided_val)
    bal_next = jnp.where(expired, new_bal, prop.bal)
    heard = jnp.where(p1_done | expired, 0, heard)
    best_bal = jnp.where(expired, 0, best_bal)
    best_val = jnp.where(expired, 0, best_val)
    timer = jnp.where(p1_done, 0, timer)
    timer = jnp.where(expired, -backoff, timer)

    # Emit: ACCEPT broadcast on phase-1 completion, PREPARE broadcast on retry.
    if "sends" not in ablate:
        requests = net.send(
            requests, ACCEPT,
            send_mask=jnp.broadcast_to(p1_done[:, None], (n_prop, n_acc, n_inst)),
            bal=prop.bal[:, None],
            v1=prop_val[:, None],
            v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
            keep=keep_p2,
            until=None if until_req is None else until_req[ACCEPT],
        )
        requests = net.send(
            requests, PREPARE,
            send_mask=jnp.broadcast_to(expired[:, None], (n_prop, n_acc, n_inst)),
            bal=bal_next[:, None],
            v1=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
            v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
            keep=keep_p1,
            until=None if until_req is None else until_req[PREPARE],
        )

    prop = prop.replace(
        bal=bal_next,
        phase=phase,
        prop_val=prop_val,
        heard=heard,
        best_bal=best_bal,
        best_val=best_val,
        timer=timer,
        decided_val=decided_val,
    )

    # ---- Observers (core.telemetry / obs.exposure): PRNG-free, from ----
    # signals the tick already produced, so enabling them cannot perturb
    # the schedule.  The effective-drop/dup counts are shared.
    tel = state.telemetry
    exp = state.exposure
    if tel is not None or exp is not None:
        dropped = None
        if keep_prom is not None:
            dropped = (
                tel_mod.lane_count(sel[PREPARE] & ok_prep[None] & ~keep_prom)
                + tel_mod.lane_count(sel[ACCEPT] & ok_acc[None] & ~keep_accd)
                + tel_mod.lane_count(p1_done[:, None] & ~keep_p2)
                + tel_mod.lane_count(expired[:, None] & ~keep_p1)
            )
        dups = None
        if dup_rep is not None:
            dups = tel_mod.lane_count(delivered & dup_rep) + tel_mod.lane_count(
                sel & dup_req
            )
    if tel is not None:
        tel = tel_mod.record(
            tel,
            state.tick,
            promise=ok_prep,
            accept=ok_acc,
            decide=learner.chosen & ~state.learner.chosen,
            conflict=learner.violations - state.learner.violations,
            leader=p1_done,
            timeout=expired,
            drop=dropped,
            dup=dups,
            corrupt=(
                masks.corrupt & (is_prep | is_acc)
                if cfg.p_corrupt > 0.0
                else None
            ),
            **tel_mod.fault_lane_events(plan, cfg, state.tick),
        )
    if exp is not None:
        # Injected-vs-effective per fault class.  Injected counts every
        # sampled fault event; effective counts only events that changed
        # something the protocol did or saw this tick.  Off knobs are
        # omitted entirely (zero traced work).
        events = {}
        if keep_prom is not None:
            events["drop"] = (
                tel_mod.lane_count(~keep_prom)
                + tel_mod.lane_count(~keep_accd)
                + tel_mod.lane_count(~keep_p1)
                + tel_mod.lane_count(~keep_p2),
                dropped,
            )
        if dup_rep is not None:
            events["dup"] = (
                tel_mod.lane_count(dup_req) + tel_mod.lane_count(dup_rep),
                dups,
            )
        if cfg.p_corrupt > 0.0:
            events["corrupt"] = (
                masks.corrupt,
                masks.corrupt & (is_prep | is_acc),
            )
        if link_req is not None:
            # Effective: in-flight messages the cut actually stalled (the
            # pre-tick present masks are the honest candidate set).
            events["partition"] = (
                tel_mod.lane_count(~link_req) + tel_mod.lane_count(~link_rep),
                tel_mod.lane_count(state.requests.present & ~link_req[None])
                + tel_mod.lane_count(state.replies.present & ~link_rep[None]),
            )
        if exp_timeout_delta is not None:
            events["timeout"] = (plan.ptimeout != 0, exp_timeout_delta)
        if cfg.stale_k > 0:
            # Every restore rewrites durable state: injected == effective.
            events["stale"] = (rec, rec)
        if delay_ext is not None:
            # Injected: delays sampled this tick (nonzero extra latency);
            # effective: in-flight messages whose delivery tick actually
            # moved — slots present but stalled behind their stamp.
            events["delay"] = (
                tel_mod.lane_count(delay_ext > 0),
                tel_mod.lane_count(state.requests.present & ~rdy_req)
                + tel_mod.lane_count(state.replies.present & ~rdy_rep),
            )
        exp = exp_mod.record(exp, **events)
    mar = state.margin
    if mar is not None:
        # Near-miss margin sketch (obs.margin): distance-to-violation from
        # the post-observe learner table and the post-tick acceptor fence.
        mar = margin_observe(
            mar, state.learner, learner, acc.promised, acc.acc_bal,
            ~equiv, q2,
        )
    wl = state.wload
    if wl is not None:
        # Client queue (workload.generator): a lane retires one queued
        # request on its proposer's commit edge (phase -> DONE this tick).
        with jax.named_scope(wload_mod.WLOAD_SCOPE):
            wl = wload_mod.observe(
                wl, state.tick, serve=p2_done,
                arrival_bits=masks.arrival_bits,
            )

    state = state.replace(
        acceptor=acc,
        proposer=prop,
        learner=learner,
        requests=requests,
        replies=replies,
        tick=state.tick + 1,
        telemetry=tel,
        exposure=exp,
        margin=mar,
        wload=wl,
    )
    # ---- Coverage sketch (obs.coverage): hash the post-tick state the ----
    # replace above just built, so host-side digests of returned states
    # match the in-flight ones bit for bit.  PRNG-free, like telemetry.
    if state.coverage is not None:
        state = state.replace(coverage=cov_mod.observe(state.coverage, state))
    return state


def paxos_step(
    state: PaxosState, base_key: jax.Array, plan: FaultPlan, cfg: FaultConfig
) -> PaxosState:
    """Advance every instance by one scheduler tick (XLA engine)."""
    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    # Keys depend only on (seed, tick): checkpoint/resume replays bit-exactly.
    key = streams_mod.tick_key(base_key, state.tick)
    masks = sample_masks(
        key, cfg, n_prop, n_acc, n_inst, wload=state.wload is not None
    )
    return apply_tick(state, masks, plan, cfg)
