"""Raft-core vote kernel — leader election + single-entry commit (config 5).

Reference parity (SURVEY.md §3.3, §8.2 M7): the third vote kernel of the
cross-protocol sweep, behind the shared step-fn interface and driven by the
identical scheduler/transport/fault machinery as the Paxos variants.

What is Raft here (vs. the Paxos kernels):

- **Election restriction**: a voter grants ``RequestVote(term, cand_last)``
  only if the candidate's log is at least as up-to-date — in the
  single-slot case, ``cand_last >= voter.ent_term`` (integer compare on
  packed terms).  This is the Raft-distinctive admission rule the sweep is
  meant to contrast with Paxos' unconditional promise.
- **One vote per term**: terms are proposer-unique packed ballots, so
  "vote once per term" is "grant only strictly increasing terms"
  (``term > voted``); a voter also raises ``voted`` when accepting an
  append, fencing stale leaders (Raft's currentTerm bump).
- **Heartbeat-style replication**: an elected leader re-broadcasts
  ``AppendEntries(term, value)`` every tick (idempotent at voters,
  self-healing under loss); commit = majority of acks at the leader's term.

Vote replies (grants *and* denials) carry the voter's stored entry; a
candidate adopts the highest-term entry it hears.  Grants alone make the
adopted entry safe by the Paxos phase-1 argument (vote majorities intersect
stored majorities); denial-borne entries are gossip that only accelerates
convergence — any entry at term t was proposed by t's unique leader, whose
value is inductively safe, and the election restriction blocks candidates
whose adopted entry is staler than a committed majority's.

Safety oracle: the shared learner counts append-accept events per (term,
value) with majority quorums — agreement violations (two values committed)
and voter-local invariant breaks (``raft_voter_invariants``) both count.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paxos_tpu.check.safety import (
    learner_observe,
    margin_observe,
    raft_voter_invariants,
)
from paxos_tpu.core import ballot as bal_mod
from paxos_tpu.core import telemetry as tel_mod
from paxos_tpu.obs import coverage as cov_mod
from paxos_tpu.obs import exposure as exp_mod
from paxos_tpu.core.raft_state import (
    ACK,
    APPEND,
    CAND,
    DONE,
    LEAD,
    REQVOTE,
    VOTE,
    RaftState,
)
from paxos_tpu.faults.injector import (
    FaultConfig,
    FaultPlan,
    bits_below,
    fault_site,
)
from paxos_tpu.kernels.quorum import majority, quorum_reached
from paxos_tpu.protocols.paxos import delay_stamps
from paxos_tpu.transport import inmemory_tpu as net
from paxos_tpu.workload import generator as wload_mod


def apply_tick_raft(
    state: RaftState, masks, plan: FaultPlan, cfg: FaultConfig
) -> RaftState:
    """The pure Raft-core transition for one tick over pre-sampled masks.

    Mask roles map onto paxos' ``TickMasks`` fields: keep_prom -> VOTE,
    keep_accd -> ACK, keep_p1 -> REQVOTE, keep_p2 -> APPEND.
    """
    n_acc, n_inst = state.acceptor.voted.shape
    n_prop = state.proposer.bal.shape[0]
    quorum = majority(n_acc)

    voter = state.acceptor
    alive = plan.alive(state.tick)  # (A, I)
    equiv = plan.equivocate  # (A, I)

    if cfg.stale_k > 0:  # bug injection: recovery restores a stale snapshot
        rec = plan.recovering(state.tick)
        voter = voter.replace(
            voted=jnp.where(rec, voter.snap_voted, voter.voted),
            ent_term=jnp.where(rec, voter.snap_term, voter.ent_term),
            ent_val=jnp.where(rec, voter.snap_val, voter.ent_val),
        )
        snap = jnp.broadcast_to(
            state.tick % jnp.int32(cfg.stale_k) == 0, rec.shape
        )
        voter = voter.replace(
            snap_voted=jnp.where(snap, voter.voted, voter.snap_voted),
            snap_term=jnp.where(snap, voter.ent_term, voter.snap_term),
            snap_val=jnp.where(snap, voter.ent_val, voter.snap_val),
        )
    elif cfg.amnesia:  # bug injection: voter forgets durable state on recovery
        rec = plan.recovering(state.tick)
        voter = voter.replace(
            voted=jnp.where(rec, 0, voter.voted),
            ent_term=jnp.where(rec, 0, voter.ent_term),
            ent_val=jnp.where(rec, 0, voter.ent_val),
        )
    voter_pre = voter

    if cfg.p_part > 0.0:
        if cfg.p_asym > 0.0:  # per-direction cuts (gray asymmetric links)
            link_req = plan.link_ok(state.tick, "req")  # (P, A, I)
            link_rep = plan.link_ok(state.tick, "rep")
        else:
            link_req = link_rep = plan.link_ok(state.tick)
    else:
        link_req = link_rep = None

    # Per-link loss/duplication (p_flaky): this tick's raw bits vs the
    # plan's per-link thresholds; p_flaky == 0 is the uniform special case.
    if cfg.p_flaky > 0.0:
        with fault_site("flaky"):
            keep_prom = ~bits_below(masks.link_bits[0], plan.link_drop)
            keep_accd = ~bits_below(masks.link_bits[1], plan.link_drop)
            keep_p1 = ~bits_below(masks.link_bits[2], plan.link_drop)
            keep_p2 = ~bits_below(masks.link_bits[3], plan.link_drop)
            if masks.dup_bits is not None:
                dup_req = bits_below(masks.dup_bits[0], plan.link_dup[None])
                dup_rep = bits_below(masks.dup_bits[1], plan.link_dup[None])
            else:
                dup_req = dup_rep = None
    else:
        keep_prom, keep_accd = masks.keep_prom, masks.keep_accd
        keep_p1, keep_p2 = masks.keep_p1, masks.keep_p2
        dup_req, dup_rep = masks.dup_req, masks.dup_rep

    # Bounded delay (p_delay): send stamps + readiness gates (see
    # protocols.paxos.delay_stamps; stalled slots stay in flight).
    until_req, until_rep, delay_ext = delay_stamps(
        masks, plan, cfg, state.tick
    )
    rdy_req = net.ready(state.requests, state.tick)
    rdy_rep = net.ready(state.replies, state.tick)

    delivered = state.replies.present
    if masks.deliver is not None:
        delivered = delivered & masks.deliver
    if rdy_rep is not None:  # delayed replies have not arrived yet
        delivered = delivered & rdy_rep
    if link_rep is not None:  # partitioned links stall replies in flight
        delivered = delivered & link_rep[None]
    replies = net.consume(state.replies, delivered, stay=dup_rep)

    # ---- Voter half-tick: select one request per (instance, voter) ----
    req_present = state.requests.present
    if rdy_req is not None:  # delayed requests have not arrived yet
        req_present = req_present & rdy_req
    sel = net.select_from_scores(req_present, masks.sel_score, masks.busy)
    sel = sel & alive[None, None]
    if link_req is not None:  # partitioned links stall requests in flight
        sel = sel & link_req[None]

    def gather(x):
        return jnp.where(sel, x, 0).sum(axis=(0, 1))

    msg_bal = gather(state.requests.bal)  # (A, I)
    msg_v1 = gather(state.requests.v1)  # (A, I): REQVOTE cand_last / APPEND value
    is_rv = sel[REQVOTE].any(axis=0)  # (A, I)
    is_ap = sel[APPEND].any(axis=0)

    if cfg.p_corrupt > 0.0:  # bug injection: in-flight bit flips, checker must flag
        msg_v1 = jnp.where(masks.corrupt & is_ap, msg_v1 ^ 64, msg_v1)
        msg_bal = jnp.where(masks.corrupt & is_rv, msg_bal + 1, msg_bal)

    # RequestVote: one vote per term + election restriction.  Equivocators
    # grant everything and hide their entry (config-4-style double vote).
    with fault_site("equivocate"):
        grant_h = (
            is_rv & ~equiv & (msg_bal > voter.voted)
            & (msg_v1 >= voter.ent_term)
        )
        grant = grant_h | (is_rv & equiv)
        # AppendEntries: accept from any term not below the vote fence.
        ok_ap_h = is_ap & ~equiv & (msg_bal >= voter.voted)
        ok_ap = ok_ap_h | (is_ap & equiv)

        voted = jnp.where(grant_h, msg_bal, voter.voted)
        voted = jnp.where(ok_ap_h, jnp.maximum(voted, msg_bal), voted)
        ent_term = jnp.where(ok_ap, msg_bal, voter.ent_term)
        ent_val = jnp.where(ok_ap, msg_v1, voter.ent_val)

        # Vote replies go to every solicitor (grant or denial), carrying the
        # voter's pre-update entry: (ent_term << 1) | granted, entry value.
        vote_payload_t = jnp.where(equiv, 0, voter.ent_term)  # (A, I)
        vote_payload_v = jnp.where(equiv, 0, voter.ent_val)
    replies = net.send(
        replies, VOTE,
        send_mask=sel[REQVOTE],
        bal=msg_bal[None],
        v1=(vote_payload_t * 2 + grant.astype(jnp.int32))[None],
        v2=vote_payload_v[None],
        keep=keep_prom,
        until=None if until_rep is None else until_rep[VOTE],
    )
    replies = net.send(
        replies, ACK,
        send_mask=sel[APPEND] & ok_ap[None],
        bal=msg_bal[None],
        v1=msg_v1[None],
        v2=jnp.zeros_like(msg_v1)[None],
        keep=keep_accd,
        until=None if until_rep is None else until_rep[ACK],
    )
    requests = net.consume(state.requests, sel, stay=dup_req)
    voter = voter.replace(voted=voted, ent_term=ent_term, ent_val=ent_val)

    # ---- Learner / safety checker (append-accept events, majority commit) ----
    with jax.named_scope("learner_check"):
        learner = learner_observe(
            state.learner, ok_ap, msg_bal, msg_v1, state.tick, quorum
        )
        with fault_site("equivocate"):
            inv_viol = raft_voter_invariants(voter_pre, voter, honest=~equiv)
        learner = learner.replace(violations=learner.violations + inv_viol)

    # ---- Candidate half-tick: fold all delivered replies ----
    cand = state.proposer
    bits = (jnp.asarray(1, jnp.int32) << jnp.arange(n_acc, dtype=jnp.int32))[
        None, :, None
    ]  # (1, A, 1)

    cur_bal = cand.bal[:, None]  # (P, 1, I)
    vote_ok = (
        delivered[VOTE]
        & (state.replies.bal[VOTE] == cur_bal)
        & (cand.phase == CAND)[:, None]
    )  # (P, A, I)
    granted = vote_ok & (state.replies.v1[VOTE] % 2 == 1)
    ack_ok = (
        delivered[ACK]
        & (state.replies.bal[ACK] == cur_bal)
        & (cand.phase == LEAD)[:, None]
    )
    heard = (
        cand.heard
        | jnp.where(granted, bits, 0).sum(axis=1, dtype=jnp.int32)
        | jnp.where(ack_ok, bits, 0).sum(axis=1, dtype=jnp.int32)
    )

    # Adopt the highest-term entry among vote replies (grants and denials).
    # Max-trick value ride-along (one value per term — the term's unique
    # leader proposed it), no gathers; a zero max never upgrades.
    rep_t = jnp.where(vote_ok, state.replies.v1[VOTE] // 2, 0)  # (P, A, I)
    cand_t = rep_t.max(axis=1)  # (P, I)
    cand_v = jnp.where(
        (rep_t == cand_t[:, None]) & vote_ok, state.replies.v2[VOTE], 0
    ).max(axis=1)
    upgrade = cand_t > cand.ent_term
    ent_term_c = jnp.where(upgrade, cand_t, cand.ent_term)
    ent_val_c = jnp.where(upgrade, cand_v, cand.ent_val)

    # Phase transitions.
    elected = (cand.phase == CAND) & quorum_reached(heard, quorum)
    committed = (cand.phase == LEAD) & quorum_reached(heard, quorum)

    timer = jnp.where(cand.phase == DONE, cand.timer, cand.timer + 1)
    # Timer skew (gray): per-candidate extra patience / backoff multiplier.
    with fault_site("skew"):
        timeout = (
            cfg.timeout
            if cfg.timeout_skew <= 0
            else cfg.timeout + plan.ptimeout
        )
        backoff = (
            masks.backoff
            if cfg.backoff_skew <= 1
            else masks.backoff * plan.pboff
        )
    expired = (
        (cand.phase != DONE) & ~elected & ~committed & (timer > timeout)
    )
    # Exposure (obs.exposure): a skewed timeout is EFFECTIVE only where the
    # expiry decision differs from the unskewed timer's.  Must be taken
    # here, before `timer` is rebased below.
    exp_timeout_delta = None
    if state.exposure is not None and cfg.timeout_skew > 0:
        exp_timeout_delta = expired ^ (
            (cand.phase != DONE) & ~elected & ~committed & (timer > cfg.timeout)
        )
    pid = jnp.broadcast_to(
        jnp.arange(n_prop, dtype=jnp.int32)[:, None], timer.shape
    )
    new_bal = bal_mod.make_ballot(
        bal_mod.ballot_round(cand.bal) + cfg.ballot_stride, pid
    )

    # A new leader proposes its adopted entry if it has one, else its own
    # value, and records that proposal as its own log entry at its term.
    v_lead = jnp.where(ent_term_c > 0, ent_val_c, cand.own_val)
    phase = jnp.where(elected, LEAD, cand.phase)
    phase = jnp.where(committed, DONE, phase)
    phase = jnp.where(expired, CAND, phase)
    prop_val = jnp.where(elected, v_lead, cand.prop_val)
    decided_val = jnp.where(committed, cand.prop_val, cand.decided_val)
    ent_term_c = jnp.where(elected, cand.bal, ent_term_c)
    ent_val_c = jnp.where(elected, v_lead, ent_val_c)
    bal_next = jnp.where(expired, new_bal, cand.bal)
    heard = jnp.where(elected | expired, 0, heard)
    timer = jnp.where(elected, 0, timer)
    timer = jnp.where(expired, -backoff, timer)

    # Emit: leaders re-broadcast AppendEntries every tick; expired candidates
    # broadcast RequestVote at the next term, declaring their entry term.
    is_lead = phase == LEAD
    requests = net.send(
        requests, APPEND,
        send_mask=jnp.broadcast_to(is_lead[:, None], (n_prop, n_acc, n_inst)),
        bal=bal_next[:, None],
        v1=prop_val[:, None],
        v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        keep=keep_p2,
        until=None if until_req is None else until_req[APPEND],
    )
    requests = net.send(
        requests, REQVOTE,
        send_mask=jnp.broadcast_to(expired[:, None], (n_prop, n_acc, n_inst)),
        bal=bal_next[:, None],
        v1=ent_term_c[:, None],
        v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        keep=keep_p1,
        until=None if until_req is None else until_req[REQVOTE],
    )

    cand = cand.replace(
        bal=bal_next,
        phase=phase,
        prop_val=prop_val,
        heard=heard,
        ent_term=ent_term_c,
        ent_val=ent_val_c,
        timer=timer,
        decided_val=decided_val,
    )

    # ---- Observers (core.telemetry / obs.exposure): PRNG-free, from ----
    # signals the tick already produced, so enabling them cannot perturb
    # the schedule.  Raft mapping: grants -> promise, append acks ->
    # accept, elections -> leader (matching the mask-role mapping in the
    # docstring).  The effective-drop/dup counts are shared.
    tel = state.telemetry
    exp = state.exposure
    if tel is not None or exp is not None:
        dropped = None
        if keep_prom is not None:
            dropped = (
                tel_mod.lane_count(sel[REQVOTE] & ~keep_prom)
                + tel_mod.lane_count(sel[APPEND] & ok_ap[None] & ~keep_accd)
                + tel_mod.lane_count(is_lead[:, None] & ~keep_p2)
                + tel_mod.lane_count(expired[:, None] & ~keep_p1)
            )
        dups = None
        if dup_rep is not None:
            dups = tel_mod.lane_count(delivered & dup_rep) + tel_mod.lane_count(
                sel & dup_req
            )
    if tel is not None:
        tel = tel_mod.record(
            tel,
            state.tick,
            promise=grant,
            accept=ok_ap,
            decide=learner.chosen & ~state.learner.chosen,
            conflict=learner.violations - state.learner.violations,
            leader=elected,
            timeout=expired,
            drop=dropped,
            dup=dups,
            corrupt=(
                masks.corrupt & (is_rv | is_ap)
                if cfg.p_corrupt > 0.0
                else None
            ),
            **tel_mod.fault_lane_events(plan, cfg, state.tick),
        )
    if exp is not None:
        # Injected-vs-effective per fault class (see obs.exposure).
        events = {}
        if keep_prom is not None:
            events["drop"] = (
                tel_mod.lane_count(~keep_prom)
                + tel_mod.lane_count(~keep_accd)
                + tel_mod.lane_count(~keep_p1)
                + tel_mod.lane_count(~keep_p2),
                dropped,
            )
        if dup_rep is not None:
            events["dup"] = (
                tel_mod.lane_count(dup_req) + tel_mod.lane_count(dup_rep),
                dups,
            )
        if cfg.p_corrupt > 0.0:
            events["corrupt"] = (
                masks.corrupt,
                masks.corrupt & (is_rv | is_ap),
            )
        if link_req is not None:
            # Effective: in-flight messages the cut actually stalled (the
            # pre-tick present masks are the honest candidate set).
            events["partition"] = (
                tel_mod.lane_count(~link_req) + tel_mod.lane_count(~link_rep),
                tel_mod.lane_count(state.requests.present & ~link_req[None])
                + tel_mod.lane_count(state.replies.present & ~link_rep[None]),
            )
        if exp_timeout_delta is not None:
            events["timeout"] = (plan.ptimeout != 0, exp_timeout_delta)
        if cfg.stale_k > 0:
            events["stale"] = (rec, rec)
        if delay_ext is not None:
            events["delay"] = (
                tel_mod.lane_count(delay_ext > 0),
                tel_mod.lane_count(state.requests.present & ~rdy_req)
                + tel_mod.lane_count(state.replies.present & ~rdy_rep),
            )
        exp = exp_mod.record(exp, **events)
    mar = state.margin
    if mar is not None:
        # Near-miss margin sketch (obs.margin): the Raft promise-slack
        # analog is voted - ent_term (the vote fence vs the stored entry).
        mar = margin_observe(
            mar, state.learner, learner, voter.voted, voter.ent_term,
            ~equiv, quorum,
        )

    wl = state.wload
    if wl is not None:
        # Client queue (workload.generator): a lane retires one queued
        # request on its proposer's commit edge (leader commit this tick).
        with jax.named_scope(wload_mod.WLOAD_SCOPE):
            wl = wload_mod.observe(
                wl, state.tick, serve=committed,
                arrival_bits=masks.arrival_bits,
            )

    state = state.replace(
        acceptor=voter,
        proposer=cand,
        learner=learner,
        requests=requests,
        replies=replies,
        tick=state.tick + 1,
        telemetry=tel,
        exposure=exp,
        margin=mar,
        wload=wl,
    )
    # ---- Coverage sketch (obs.coverage): hash the post-tick state the ----
    # replace above just built.  PRNG-free, like telemetry.
    if state.coverage is not None:
        state = state.replace(coverage=cov_mod.observe(state.coverage, state))
    return state


def raftcore_step(
    state: RaftState, base_key: jax.Array, plan: FaultPlan, cfg: FaultConfig
) -> RaftState:
    """Advance every instance by one scheduler tick (XLA engine).

    Raft-core reuses single-decree paxos' mask samplers, so it draws from
    the same stream family (`core.streams.SINGLE_DECREE`).
    """
    from paxos_tpu.core import streams as streams_mod
    from paxos_tpu.protocols.paxos import sample_masks

    n_acc, n_inst = state.acceptor.voted.shape
    n_prop = state.proposer.bal.shape[0]
    key = streams_mod.tick_key(base_key, state.tick)
    masks = sample_masks(
        key, cfg, n_prop, n_acc, n_inst, wload=state.wload is not None
    )
    return apply_tick_raft(state, masks, plan, cfg)
