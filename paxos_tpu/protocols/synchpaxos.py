"""SynchPaxos — bounded-delay-exploiting consensus as one fused array program.

The fifth protocol of the sweep (see :mod:`paxos_tpu.core.sp_state` for the
protocol story): classic single-decree Paxos plus a leader fast path that
bets on the bounded-delay synchrony window ``FaultConfig.delta``:

- **Fast path**: proposer 0 owns the unique round-0 ballot and has its
  ``Accept(sync_bal, own_val)`` broadcast in flight at tick 0.  It decides
  on a majority of Accepted while ``timer <= delta`` — one round trip when
  the network honors the bound.  Round 0 has a single owner, so the
  majority quorum is just classic phase 2: blown synchrony costs latency,
  never safety.
- **Fallback**: past ``delta`` the leader abandons the fast round and runs
  classic rounds (>= 1) through the ordinary P1 -> P2 machinery; phase-1
  recovery adopts any reported round-0 value, so a late fast quorum can
  never contradict a fallback decision.  Followers are passive until the
  normal ``timeout`` expires, then compete classically.
- **Planted bug** (``FaultConfig.sp_unsafe_fast``): the leader commits on
  the FIRST Accepted heard — no quorum, no delta window — the bogus
  synchrony shortcut the checker must flag under delta-violating delays.

Everything else (acceptor rules, learner/checker, fault threading including
the bounded-delay channel itself) is classic paxos verbatim: SynchPaxos
shares the single-decree mask shapes, stream family
(``core.streams.SINGLE_DECREE`` via the ``synchpaxos`` protocol alias) and
samplers (``protocols.paxos.sample_masks`` / ``counter_masks``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paxos_tpu.check.safety import (
    acceptor_invariants,
    learner_observe,
    margin_observe,
)
from paxos_tpu.core import ballot as bal_mod
from paxos_tpu.core import telemetry as tel_mod
from paxos_tpu.obs import coverage as cov_mod
from paxos_tpu.obs import exposure as exp_mod
from paxos_tpu.core.messages import ACCEPT, ACCEPTED, PREPARE, PROMISE
from paxos_tpu.core.sp_state import DONE, FAST, P1, P2, SynchPaxosState, sync_ballot
from paxos_tpu.faults.injector import (
    FaultConfig,
    FaultPlan,
    bits_below,
    fault_site,
)
from paxos_tpu.kernels.quorum import majority, quorum_reached
from paxos_tpu.protocols.paxos import delay_stamps
from paxos_tpu.transport import inmemory_tpu as net
from paxos_tpu.workload import generator as wload_mod
from paxos_tpu.utils.bitops import popcount


def apply_tick_sp(
    state: SynchPaxosState, masks, plan: FaultPlan, cfg: FaultConfig
) -> SynchPaxosState:
    """The pure SynchPaxos transition for one tick over pre-sampled masks."""
    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    quorum = majority(n_acc)
    # Flexible quorums as in classic paxos (0 = majority).  The fast path
    # uses q2: round 0 is single-owner, so its decide IS a phase-2 quorum.
    q1 = cfg.q1 or quorum
    q2 = cfg.q2 or quorum

    acc = state.acceptor
    alive = plan.alive(state.tick)  # (A, I)
    equiv = plan.equivocate  # (A, I)

    if cfg.stale_k > 0:  # bug injection: recovery restores a stale snapshot
        rec = plan.recovering(state.tick)
        acc = acc.replace(
            promised=jnp.where(rec, acc.snap_promised, acc.promised),
            acc_bal=jnp.where(rec, acc.snap_bal, acc.acc_bal),
            acc_val=jnp.where(rec, acc.snap_val, acc.acc_val),
        )
        snap = jnp.broadcast_to(
            state.tick % jnp.int32(cfg.stale_k) == 0, rec.shape
        )
        acc = acc.replace(
            snap_promised=jnp.where(snap, acc.promised, acc.snap_promised),
            snap_bal=jnp.where(snap, acc.acc_bal, acc.snap_bal),
            snap_val=jnp.where(snap, acc.acc_val, acc.snap_val),
        )
    elif cfg.amnesia:  # bug injection: acceptor forgets durable state on recovery
        rec = plan.recovering(state.tick)
        acc = acc.replace(
            promised=jnp.where(rec, 0, acc.promised),
            acc_bal=jnp.where(rec, 0, acc.acc_bal),
            acc_val=jnp.where(rec, 0, acc.acc_val),
        )
    acc_pre = acc

    # Reply delivery decided & delivered slots cleared BEFORE new writes
    # (same no-clobber discipline as protocols.paxos).
    if cfg.p_part > 0.0:
        if cfg.p_asym > 0.0:  # per-direction cuts (gray asymmetric links)
            link_req = plan.link_ok(state.tick, "req")  # (P, A, I)
            link_rep = plan.link_ok(state.tick, "rep")
        else:
            link_req = link_rep = plan.link_ok(state.tick)
    else:
        link_req = link_rep = None

    # Per-link loss/duplication (p_flaky): this tick's raw bits vs the
    # plan's per-link thresholds; p_flaky == 0 is the uniform special case.
    if cfg.p_flaky > 0.0:
        with fault_site("flaky"):
            keep_prom = ~bits_below(masks.link_bits[0], plan.link_drop)
            keep_accd = ~bits_below(masks.link_bits[1], plan.link_drop)
            keep_p1 = ~bits_below(masks.link_bits[2], plan.link_drop)
            keep_p2 = ~bits_below(masks.link_bits[3], plan.link_drop)
            if masks.dup_bits is not None:
                dup_req = bits_below(masks.dup_bits[0], plan.link_dup[None])
                dup_rep = bits_below(masks.dup_bits[1], plan.link_dup[None])
            else:
                dup_req = dup_rep = None
    else:
        keep_prom, keep_accd = masks.keep_prom, masks.keep_accd
        keep_p1, keep_p2 = masks.keep_p1, masks.keep_p2
        dup_req, dup_rep = masks.dup_req, masks.dup_rep

    # Bounded delay (p_delay): send stamps + readiness gates (see
    # protocols.paxos.delay_stamps) — the very channel the fast path bets on.
    until_req, until_rep, delay_ext = delay_stamps(
        masks, plan, cfg, state.tick
    )
    rdy_req = net.ready(state.requests, state.tick)
    rdy_rep = net.ready(state.replies, state.tick)

    delivered = state.replies.present
    if masks.deliver is not None:
        delivered = delivered & masks.deliver
    if rdy_rep is not None:  # delayed replies have not arrived yet
        delivered = delivered & rdy_rep
    if link_rep is not None:  # partitioned links stall replies in flight
        delivered = delivered & link_rep[None]
    replies = net.consume(state.replies, delivered, stay=dup_rep)

    # ---- Acceptor half-tick (classic paxos verbatim) ----
    req_present = state.requests.present
    if rdy_req is not None:  # delayed requests have not arrived yet
        req_present = req_present & rdy_req
    sel = net.select_from_scores(req_present, masks.sel_score, masks.busy)
    sel = sel & alive[None, None]
    if link_req is not None:  # partitioned links stall requests in flight
        sel = sel & link_req[None]

    def gather(x):
        return jnp.where(sel, x, 0).sum(axis=(0, 1))

    msg_bal = gather(state.requests.bal)  # (A, I)
    msg_val = gather(state.requests.v1)  # (A, I)
    is_prep = sel[PREPARE].any(axis=0)
    is_acc = sel[ACCEPT].any(axis=0)

    if cfg.p_corrupt > 0.0:  # bug injection: in-flight bit flips, checker must flag
        msg_val = jnp.where(masks.corrupt & is_acc, msg_val ^ 64, msg_val)
        msg_bal = jnp.where(masks.corrupt & is_prep, msg_bal + 1, msg_bal)

    with fault_site("equivocate"):
        ok_prep_h = is_prep & ~equiv & (msg_bal > acc.promised)
        ok_prep = ok_prep_h | (is_prep & equiv)
        ok_acc_h = is_acc & ~equiv & (msg_bal >= acc.promised)
        ok_acc = ok_acc_h | (is_acc & equiv)

        promised = jnp.where(ok_prep_h, msg_bal, acc.promised)
        promised = jnp.where(
            ok_acc_h, jnp.maximum(promised, msg_bal), promised
        )
        acc_bal = jnp.where(ok_acc, msg_bal, acc.acc_bal)
        acc_val = jnp.where(ok_acc, msg_val, acc.acc_val)

        prom_payload_bal = jnp.where(equiv, 0, acc.acc_bal)  # pre-update
        prom_payload_val = jnp.where(equiv, 0, acc.acc_val)
    replies = net.send(
        replies, PROMISE,
        send_mask=sel[PREPARE] & ok_prep[None],
        bal=msg_bal[None],
        v1=prom_payload_bal[None],
        v2=prom_payload_val[None],
        keep=keep_prom,
        until=None if until_rep is None else until_rep[PROMISE],
    )
    replies = net.send(
        replies, ACCEPTED,
        send_mask=sel[ACCEPT] & ok_acc[None],
        bal=msg_bal[None],
        v1=msg_val[None],
        v2=jnp.zeros_like(msg_val)[None],
        keep=keep_accd,
        until=None if until_rep is None else until_rep[ACCEPTED],
    )
    requests = net.consume(state.requests, sel, stay=dup_req)
    acc = acc.replace(promised=promised, acc_bal=acc_bal, acc_val=acc_val)

    # ---- Learner / safety checker ----
    with jax.named_scope("learner_check"):
        learner = learner_observe(
            state.learner, ok_acc, msg_bal, msg_val, state.tick, q2
        )
        with fault_site("equivocate"):
            inv_viol = acceptor_invariants(acc_pre, acc, honest=~equiv)
        learner = learner.replace(violations=learner.violations + inv_viol)

    # ---- Proposer half-tick ----
    prop = state.proposer
    bits = (jnp.asarray(1, jnp.int32) << jnp.arange(n_acc, dtype=jnp.int32))[
        None, :, None
    ]  # (1, A, 1)

    cur_bal = prop.bal[:, None]  # (P, 1, I)
    prom_ok = (
        delivered[PROMISE]
        & (state.replies.bal[PROMISE] == cur_bal)
        & (prop.phase == P1)[:, None]
    )  # (P, A, I)
    accd_ok = (
        delivered[ACCEPTED]
        & (state.replies.bal[ACCEPTED] == cur_bal)
        & ((prop.phase == P2) | (prop.phase == FAST))[:, None]
    )
    heard = (
        prop.heard
        | jnp.where(prom_ok, bits, 0).sum(axis=1, dtype=jnp.int32)
        | jnp.where(accd_ok, bits, 0).sum(axis=1, dtype=jnp.int32)
    )

    # Phase-1 recovery fold (classic): highest previously-accepted pair.
    prev_bal = jnp.where(prom_ok, state.replies.v1[PROMISE], 0)  # (P, A, I)
    cand_bal = prev_bal.max(axis=1)  # (P, I)
    cand_val = jnp.where(
        prev_bal == cand_bal[:, None], state.replies.v2[PROMISE], 0
    ).max(axis=1)
    upgrade = cand_bal > prop.best_bal
    best_bal = jnp.where(upgrade, cand_bal, prop.best_bal)
    best_val = jnp.where(upgrade, cand_val, prop.best_val)

    # Phase transitions.  The timer advances first so the fast-path window
    # test sees this tick's age, not last tick's.
    timer = jnp.where(prop.phase == DONE, prop.timer, prop.timer + 1)
    in_window = timer <= jnp.int32(max(cfg.delta, 0))
    if cfg.sp_unsafe_fast:
        # Planted delay-unsafe bug: commit the fast value on the FIRST
        # Accepted heard — no quorum, no delta window.  The "one ack inside
        # the window implies everyone got it" shortcut is bogus once delays
        # exceed delta; the checker must flag the disagreement.
        fast_done = (prop.phase == FAST) & (popcount(heard) >= 1)
    else:
        fast_done = (
            (prop.phase == FAST) & quorum_reached(heard, q2) & in_window
        )
    p1_done = (prop.phase == P1) & quorum_reached(heard, q1)
    p2_done = (prop.phase == P2) & quorum_reached(heard, q2)
    v_chosen_by_p1 = jnp.where(best_bal > 0, best_val, prop.own_val)

    # Timer skew (gray): per-proposer extra patience / backoff multiplier.
    with fault_site("skew"):
        timeout = (
            cfg.timeout
            if cfg.timeout_skew <= 0
            else cfg.timeout + plan.ptimeout
        )
        backoff = (
            masks.backoff
            if cfg.backoff_skew <= 1
            else masks.backoff * plan.pboff
        )
    # The FAST round's deadline is the synchrony window delta, not the
    # classic timeout: a leader whose fast quorum missed the window falls
    # back to classic rounds immediately.
    deadline = jnp.where(
        prop.phase == FAST, jnp.int32(max(cfg.delta, 0)), timeout
    )
    expired = (
        (prop.phase != DONE)
        & ~p1_done & ~p2_done & ~fast_done
        & (timer > deadline)
    )
    # Exposure (obs.exposure): a skewed timeout is EFFECTIVE only where the
    # expiry decision differs from the unskewed deadline's.
    exp_timeout_delta = None
    if state.exposure is not None and cfg.timeout_skew > 0:
        deadline0 = jnp.where(
            prop.phase == FAST, jnp.int32(max(cfg.delta, 0)), cfg.timeout
        )
        exp_timeout_delta = expired ^ (
            (prop.phase != DONE)
            & ~p1_done & ~p2_done & ~fast_done
            & (timer > deadline0)
        )
    pid = jnp.broadcast_to(
        jnp.arange(n_prop, dtype=jnp.int32)[:, None], timer.shape
    )
    new_bal = bal_mod.make_ballot(
        bal_mod.ballot_round(prop.bal) + cfg.ballot_stride, pid
    )

    phase = jnp.where(p1_done, P2, prop.phase)
    phase = jnp.where(p2_done | fast_done, DONE, phase)
    phase = jnp.where(expired, P1, phase)
    prop_val = jnp.where(p1_done, v_chosen_by_p1, prop.prop_val)
    decided_val = jnp.where(p2_done, prop.prop_val, prop.decided_val)
    decided_val = jnp.where(fast_done, prop.own_val, decided_val)
    bal_next = jnp.where(expired, new_bal, prop.bal)
    heard = jnp.where(p1_done | expired, 0, heard)
    best_bal = jnp.where(expired, 0, best_bal)
    best_val = jnp.where(expired, 0, best_val)
    timer = jnp.where(p1_done, 0, timer)
    timer = jnp.where(expired, -backoff, timer)

    # Emit.  The leader's round-0 fast broadcast goes out at timer == 0
    # THROUGH the faulty network (keep_p2 / delay stamps apply): the fast
    # round must be as lossy as any other send, or the unsafe-fast planted
    # bug could never manifest.  Disjoint from p1_done (phase FAST vs P1),
    # so both ACCEPT sends compose.  Then the classics: ACCEPT on phase-1
    # completion, PREPARE on expiry (the leader's fast fallback and
    # follower activation share this path).
    fast_kick = (prop.phase == FAST) & (prop.timer == 0)
    requests = net.send(
        requests, ACCEPT,
        send_mask=jnp.broadcast_to(fast_kick[:, None], (n_prop, n_acc, n_inst)),
        bal=prop.bal[:, None],
        v1=prop.own_val[:, None],
        v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        keep=keep_p2,
        until=None if until_req is None else until_req[ACCEPT],
    )
    requests = net.send(
        requests, ACCEPT,
        send_mask=jnp.broadcast_to(p1_done[:, None], (n_prop, n_acc, n_inst)),
        bal=prop.bal[:, None],
        v1=prop_val[:, None],
        v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        keep=keep_p2,
        until=None if until_req is None else until_req[ACCEPT],
    )
    requests = net.send(
        requests, PREPARE,
        send_mask=jnp.broadcast_to(expired[:, None], (n_prop, n_acc, n_inst)),
        bal=bal_next[:, None],
        v1=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        v2=jnp.zeros((n_prop, 1, n_inst), jnp.int32),
        keep=keep_p1,
        until=None if until_req is None else until_req[PREPARE],
    )

    prop = prop.replace(
        bal=bal_next,
        phase=phase,
        prop_val=prop_val,
        heard=heard,
        best_bal=best_bal,
        best_val=best_val,
        timer=timer,
        decided_val=decided_val,
    )

    # ---- Observers (core.telemetry / obs.exposure): PRNG-free ----
    tel = state.telemetry
    exp = state.exposure
    if tel is not None or exp is not None:
        dropped = None
        if keep_prom is not None:
            dropped = (
                tel_mod.lane_count(sel[PREPARE] & ok_prep[None] & ~keep_prom)
                + tel_mod.lane_count(sel[ACCEPT] & ok_acc[None] & ~keep_accd)
                + tel_mod.lane_count(p1_done[:, None] & ~keep_p2)
                + tel_mod.lane_count(expired[:, None] & ~keep_p1)
            )
        dups = None
        if dup_rep is not None:
            dups = tel_mod.lane_count(delivered & dup_rep) + tel_mod.lane_count(
                sel & dup_req
            )
    if tel is not None:
        tel = tel_mod.record(
            tel,
            state.tick,
            promise=ok_prep,
            accept=ok_acc,
            decide=learner.chosen & ~state.learner.chosen,
            conflict=learner.violations - state.learner.violations,
            leader=p1_done | fast_done,
            timeout=expired,
            drop=dropped,
            dup=dups,
            corrupt=(
                masks.corrupt & (is_prep | is_acc)
                if cfg.p_corrupt > 0.0
                else None
            ),
            **tel_mod.fault_lane_events(plan, cfg, state.tick),
        )
    if exp is not None:
        # Injected-vs-effective per fault class (see obs.exposure).
        events = {}
        if keep_prom is not None:
            events["drop"] = (
                tel_mod.lane_count(~keep_prom)
                + tel_mod.lane_count(~keep_accd)
                + tel_mod.lane_count(~keep_p1)
                + tel_mod.lane_count(~keep_p2),
                dropped,
            )
        if dup_rep is not None:
            events["dup"] = (
                tel_mod.lane_count(dup_req) + tel_mod.lane_count(dup_rep),
                dups,
            )
        if cfg.p_corrupt > 0.0:
            events["corrupt"] = (
                masks.corrupt,
                masks.corrupt & (is_prep | is_acc),
            )
        if link_req is not None:
            events["partition"] = (
                tel_mod.lane_count(~link_req) + tel_mod.lane_count(~link_rep),
                tel_mod.lane_count(state.requests.present & ~link_req[None])
                + tel_mod.lane_count(state.replies.present & ~link_rep[None]),
            )
        if exp_timeout_delta is not None:
            events["timeout"] = (plan.ptimeout != 0, exp_timeout_delta)
        if cfg.stale_k > 0:
            events["stale"] = (rec, rec)
        if delay_ext is not None:
            events["delay"] = (
                tel_mod.lane_count(delay_ext > 0),
                tel_mod.lane_count(state.requests.present & ~rdy_req)
                + tel_mod.lane_count(state.replies.present & ~rdy_rep),
            )
        exp = exp_mod.record(exp, **events)
    mar = state.margin
    if mar is not None:
        mar = margin_observe(
            mar, state.learner, learner, acc.promised, acc.acc_bal,
            ~equiv, q2,
        )

    wl = state.wload
    if wl is not None:
        # Client queue (workload.generator): a lane retires one queued
        # request on its proposer's commit edge (phase -> DONE this tick).
        with jax.named_scope(wload_mod.WLOAD_SCOPE):
            wl = wload_mod.observe(
                wl, state.tick, serve=p2_done | fast_done,
                arrival_bits=masks.arrival_bits,
            )

    state = state.replace(
        acceptor=acc,
        proposer=prop,
        learner=learner,
        requests=requests,
        replies=replies,
        tick=state.tick + 1,
        telemetry=tel,
        exposure=exp,
        margin=mar,
        wload=wl,
    )
    # ---- Coverage sketch (obs.coverage): hash the post-tick state ----
    if state.coverage is not None:
        state = state.replace(coverage=cov_mod.observe(state.coverage, state))
    return state


def synchpaxos_step(
    state: SynchPaxosState, base_key: jax.Array, plan: FaultPlan, cfg: FaultConfig
) -> SynchPaxosState:
    """Advance every instance by one scheduler tick (XLA engine).

    SynchPaxos shares single-decree paxos' mask shapes, so it reuses its
    samplers (`protocols.paxos.sample_masks` / `counter_masks`) and draws
    from the same stream family (`core.streams.SINGLE_DECREE`).
    """
    from paxos_tpu.core import streams as streams_mod
    from paxos_tpu.protocols.paxos import sample_masks

    n_acc, n_inst = state.acceptor.promised.shape
    n_prop = state.proposer.bal.shape[0]
    key = streams_mod.tick_key(base_key, state.tick)
    masks = sample_masks(
        key, cfg, n_prop, n_acc, n_inst, wload=state.wload is not None
    )
    return apply_tick_sp(state, masks, plan, cfg)


def fast_path_rate(state: SynchPaxosState) -> float:
    """Fraction of instances the leader decided on the round-0 fast path.

    The leader's ballot only moves on fallback, so phase DONE at the sync
    ballot identifies a fast-path decide (host-side; one blocking transfer).
    """
    import numpy as np

    phase0 = np.asarray(jax.device_get(state.proposer.phase[0]))
    bal0 = np.asarray(jax.device_get(state.proposer.bal[0]))
    fast = (phase0 == DONE) & (bal0 == int(sync_ballot()))
    return float(fast.mean())
