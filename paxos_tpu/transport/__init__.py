"""Transports: how in-flight messages move. The `Network.Transport` seam."""
