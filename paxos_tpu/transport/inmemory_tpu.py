"""InMemoryTPU transport — message delivery as masked gathers in HBM.

Reference parity (SURVEY.md §2 L0, §4.3 [B][CH]): the reference's
``Network.Transport`` abstraction (endpoints, ordered-reliable connections
over TCP) is the declared plugin seam; this module is the plug.  There is no
wire: "in flight" means a populated slot in a :class:`~paxos_tpu.core.messages.MsgBuf`,
and a tick's delivery decisions are PRNG masks:

- **Request path (proposer→acceptor), one message per actor per tick**: each
  (instance, acceptor) *selects* at most one present request uniformly at
  random and processes it; unselected slots stay in flight.  This is the
  classic asynchronous-scheduler model (one enabled event per actor per
  step): arbitrary delay and arbitrary interleaving across senders and kinds
  fall out of the random selection, so the synchronous scan step explores the
  same interleaving space as the reference's nondeterministic mailbox order
  (SURVEY.md §8.1).
- **Reply path (acceptor→proposer), deliver-all-with-holds**: the proposer's
  handler is a commutative monoid action (bitmask-OR of voters, max of
  prev-accepted ballots), so processing any subset in any order equals any
  serialization — replies need no one-at-a-time discipline.  A per-slot
  *hold* mask keeps a reply in flight to realize delay/reordering; delivered
  slots clear (minus duplicates).

Send-time drop and duplication masks complete the fault model (SURVEY.md
§6.8).  Everything is fixed-shape; no host round-trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paxos_tpu.core.messages import MsgBuf


def select_one(present: jnp.ndarray, key: jax.Array, p_idle: float) -> jnp.ndarray:
    """Pick at most one present request per (instance, acceptor).

    Args:
      present: (I, 2, P, A) bool — occupied request slots.
      key: PRNG key for this tick.
      p_idle: probability an acceptor processes nothing despite pending mail.

    Returns:
      (I, 2, P, A) bool one-hot (per (I, A) fiber) selection mask.
    """
    i, k, p, a = present.shape
    k_sel, k_idle = jax.random.split(key)
    # Uniform scores; absent slots can never win.
    scores = jax.random.uniform(k_sel, present.shape)
    scores = jnp.where(present, scores, -1.0)
    # argmax over the flattened (kind, proposer) fiber for each (I, A).
    flat = jnp.moveaxis(scores, 3, 1).reshape(i, a, k * p)  # (I, A, 2P)
    winner = jnp.argmax(flat, axis=-1)  # (I, A)
    onehot = jax.nn.one_hot(winner, k * p, dtype=jnp.bool_)  # (I, A, 2P)
    onehot = jnp.moveaxis(onehot.reshape(i, a, k, p), 1, 3)  # (I, 2, P, A)
    busy = jax.random.uniform(k_idle, (i, 1, 1, a)) >= p_idle
    return onehot & present & busy


def hold_mask(present: jnp.ndarray, key: jax.Array, p_hold: float) -> jnp.ndarray:
    """(shape of present) bool: which present reply slots deliver this tick."""
    deliver = jax.random.uniform(key, present.shape) >= p_hold
    return present & deliver


def send(
    buf: MsgBuf,
    kind: int,
    send_mask: jnp.ndarray,
    bal: jnp.ndarray,
    v1: jnp.ndarray,
    v2: jnp.ndarray,
    key: jax.Array,
    p_drop: float,
) -> MsgBuf:
    """Write messages of ``kind`` into their slots (overwriting), minus drops.

    Args:
      buf: the target buffer family.
      kind: request/reply kind index (0 or 1).
      send_mask: (I, P, A) bool — which edges send this tick.
      bal, v1, v2: (I, P, A) int32 payloads (broadcastable).
      key: PRNG key; p_drop: send-time loss probability.
    """
    if p_drop > 0.0:
        kept = jax.random.uniform(key, send_mask.shape) >= p_drop
        send_mask = send_mask & kept
    zero = jnp.zeros_like(buf.bal[:, kind])
    return buf.replace(
        bal=buf.bal.at[:, kind].set(jnp.where(send_mask, bal + zero, buf.bal[:, kind])),
        v1=buf.v1.at[:, kind].set(jnp.where(send_mask, v1 + zero, buf.v1[:, kind])),
        v2=buf.v2.at[:, kind].set(jnp.where(send_mask, v2 + zero, buf.v2[:, kind])),
        present=buf.present.at[:, kind].set(buf.present[:, kind] | send_mask),
    )


def consume(
    buf: MsgBuf, taken: jnp.ndarray, key: jax.Array, p_dup: float
) -> MsgBuf:
    """Clear slots that were processed this tick, except duplicated ones.

    Args:
      taken: (I, 2, P, A) bool — slots whose message was processed.
      p_dup: probability a processed slot stays in flight (duplicate delivery).
    """
    if p_dup > 0.0:
        dup = jax.random.uniform(key, taken.shape) < p_dup
        taken = taken & ~dup
    return buf.replace(present=buf.present & ~taken)
