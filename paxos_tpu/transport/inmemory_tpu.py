"""InMemoryTPU transport — message delivery as masked gathers in HBM.

Reference parity (SURVEY.md §2 L0, §4.3 [B][CH]): the reference's
``Network.Transport`` abstraction (endpoints, ordered-reliable connections
over TCP) is the declared plugin seam; this module is the plug.  There is no
wire: "in flight" means a populated slot in a :class:`~paxos_tpu.core.messages.MsgBuf`,
and a tick's delivery decisions are PRNG masks:

- **Request path (proposer→acceptor), one message per actor per tick**: each
  (instance, acceptor) *selects* at most one present request uniformly at
  random and processes it; unselected slots stay in flight.  This is the
  classic asynchronous-scheduler model (one enabled event per actor per
  step): arbitrary delay and arbitrary interleaving across senders and kinds
  fall out of the random selection, so the synchronous scan step explores the
  same interleaving space as the reference's nondeterministic mailbox order
  (SURVEY.md §8.1).
- **Reply path (acceptor→proposer), deliver-all-with-holds**: the proposer's
  handler is a commutative monoid action (bitmask-OR of voters, max of
  prev-accepted ballots), so processing any subset in any order equals any
  serialization — replies need no one-at-a-time discipline.  A per-slot
  *hold* mask keeps a reply in flight to realize delay/reordering; delivered
  slots clear (minus duplicates).

Send-time drop and duplication masks complete the fault model (SURVEY.md
§6.8).  Everything is fixed-shape; no host round-trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paxos_tpu.core.messages import MsgBuf

# Bernoulli masks are thresholds on raw uint32 PRNG bits: P(bits < t) with
# t = round(p * 2^32).  Integer-exact, no float conversion pass, and ~2^-32
# probability resolution — far finer than any fuzzing config needs.
_TWO32 = float(1 << 32)


def _bernoulli_bits(key: jax.Array, shape, p: float) -> jnp.ndarray:
    """bool mask, True with probability ``p`` (uint32-threshold sampling)."""
    thresh = jnp.uint32(min(int(round(p * _TWO32)), (1 << 32) - 1))
    return jax.random.bits(key, shape, jnp.uint32) < thresh


def select_one(present: jnp.ndarray, key: jax.Array, p_idle: float) -> jnp.ndarray:
    """Pick at most one present request per (instance, acceptor).

    Selection is a max over per-slot random uint32 scores whose low bits are
    replaced by the slot's (kind, proposer) index: scores within a (A, I)
    fiber are therefore *distinct*, so ``score == fiber_max`` recovers the
    winner as a mask directly — no transpose, no argmax, no one_hot, all in
    the buffers' native instance-minor layout.  An all-zero fiber max means
    "nothing present" (a present slot scores 0 only with prob ~2^-27, a
    vanishing extra idle tick).

    Args:
      present: (2, P, A, I) bool — occupied request slots.
      key: PRNG key for this tick.
      p_idle: probability an acceptor processes nothing despite pending mail.

    Returns:
      (2, P, A, I) bool one-hot (per (A, I) fiber) selection mask.
    """
    k, p, a, i = present.shape
    k_sel, k_idle = jax.random.split(key)
    nbits = max((k * p - 1).bit_length(), 1)  # low bits reserved for slot id
    sid = (
        jax.lax.broadcasted_iota(jnp.uint32, present.shape, 0) * p
        + jax.lax.broadcasted_iota(jnp.uint32, present.shape, 1)
    )
    rnd = jax.random.bits(k_sel, present.shape, jnp.uint32)
    score = (rnd & jnp.uint32(~((1 << nbits) - 1) & 0xFFFFFFFF)) | sid
    score = jnp.where(present, score, jnp.uint32(0))
    fiber_max = score.max(axis=(0, 1), keepdims=True)  # (1, 1, A, I)
    sel = present & (score == fiber_max) & (fiber_max > 0)
    if p_idle > 0.0:
        busy = ~_bernoulli_bits(k_idle, (1, 1, a, i), p_idle)
        sel = sel & busy
    return sel


def hold_mask(present: jnp.ndarray, key: jax.Array, p_hold: float) -> jnp.ndarray:
    """(shape of present) bool: which present reply slots deliver this tick."""
    if p_hold <= 0.0:
        return present
    return present & ~_bernoulli_bits(key, present.shape, p_hold)


def send(
    buf: MsgBuf,
    kind: int,
    send_mask: jnp.ndarray,
    bal: jnp.ndarray,
    v1: jnp.ndarray,
    v2: jnp.ndarray,
    key: jax.Array,
    p_drop: float,
) -> MsgBuf:
    """Write messages of ``kind`` into their slots (overwriting), minus drops.

    Args:
      buf: the target buffer family.
      kind: request/reply kind index (0 or 1).
      send_mask: (P, A, I) bool — which edges send this tick.
      bal, v1, v2: (P, A, I) int32 payloads (broadcastable).
      key: PRNG key; p_drop: send-time loss probability.
    """
    if p_drop > 0.0:
        send_mask = send_mask & ~_bernoulli_bits(key, send_mask.shape, p_drop)
    zero = jnp.zeros_like(buf.bal[kind])
    return buf.replace(
        bal=buf.bal.at[kind].set(jnp.where(send_mask, bal + zero, buf.bal[kind])),
        v1=buf.v1.at[kind].set(jnp.where(send_mask, v1 + zero, buf.v1[kind])),
        v2=buf.v2.at[kind].set(jnp.where(send_mask, v2 + zero, buf.v2[kind])),
        present=buf.present.at[kind].set(buf.present[kind] | send_mask),
    )


def consume(
    buf: MsgBuf, taken: jnp.ndarray, key: jax.Array, p_dup: float
) -> MsgBuf:
    """Clear slots that were processed this tick, except duplicated ones.

    Args:
      taken: (2, P, A, I) bool — slots whose message was processed.
      p_dup: probability a processed slot stays in flight (duplicate delivery).
    """
    if p_dup > 0.0:
        taken = taken & ~_bernoulli_bits(key, taken.shape, p_dup)
    return buf.replace(present=buf.present & ~taken)
