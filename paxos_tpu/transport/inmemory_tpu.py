"""InMemoryTPU transport — message delivery as masked gathers in HBM.

Reference parity (SURVEY.md §2 L0, §4.3 [B][CH]): the reference's
``Network.Transport`` abstraction (endpoints, ordered-reliable connections
over TCP) is the declared plugin seam; this module is the plug.  There is no
wire: "in flight" means a populated slot in a :class:`~paxos_tpu.core.messages.MsgBuf`,
and a tick's delivery decisions are PRNG masks:

- **Request path (proposer→acceptor), one message per actor per tick**: each
  (instance, acceptor) *selects* at most one present request uniformly at
  random and processes it; unselected slots stay in flight.  This is the
  classic asynchronous-scheduler model (one enabled event per actor per
  step): arbitrary delay and arbitrary interleaving across senders and kinds
  fall out of the random selection, so the synchronous scan step explores the
  same interleaving space as the reference's nondeterministic mailbox order
  (SURVEY.md §8.1).
- **Reply path (acceptor→proposer), deliver-all-with-holds**: the proposer's
  handler is a commutative monoid action (bitmask-OR of voters, max of
  prev-accepted ballots), so processing any subset in any order equals any
  serialization — replies need no one-at-a-time discipline.  A per-slot
  *hold* mask keeps a reply in flight to realize delay/reordering; delivered
  slots clear (minus duplicates).

Send-time drop and duplication masks complete the fault model (SURVEY.md
§6.8).  Everything is fixed-shape; no host round-trips.

The randomness is split from the mechanics: the pure functions
(:func:`select_from_scores`, :func:`send`, :func:`consume`) consume
pre-sampled masks, so the same transport drives both the XLA path
(masks from ``jax.random``) and the fused Pallas path (masks from the
on-core hardware PRNG, ``kernels/fused_tick``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paxos_tpu.core.messages import MsgBuf

# Bernoulli masks are thresholds on raw uint32 PRNG bits: P(bits < t) with
# t = round(p * 2^32).  Integer-exact, no float conversion pass, and ~2^-32
# probability resolution — far finer than any fuzzing config needs.
_TWO32 = float(1 << 32)


def bern_threshold(p: float) -> jnp.ndarray:
    """uint32 threshold t with P(bits < t) = ``p`` for uniform uint32 bits."""
    return jnp.uint32(min(int(round(p * _TWO32)), (1 << 32) - 1))


def _bernoulli_bits(key: jax.Array, shape, p: float) -> jnp.ndarray:
    """bool mask, True with probability ``p`` (uint32-threshold sampling).

    ``p >= 1.0`` is exact (all True), matching the counter-PRNG's ``bern``:
    the clamped threshold would otherwise miss w.p. 2^-32, making drop=1.0
    mean "almost always" under this engine but "always" under fused.
    """
    if p >= 1.0:
        return jnp.ones(shape, jnp.bool_)
    return jax.random.bits(key, shape, jnp.uint32) < bern_threshold(p)


def keep_mask(key: jax.Array, shape, p_drop: float) -> Optional[jnp.ndarray]:
    """Send-time survival mask: None when lossless, else True = delivered."""
    if p_drop <= 0.0:
        return None
    return ~_bernoulli_bits(key, shape, p_drop)


def stay_mask(key: jax.Array, shape, p_dup: float) -> Optional[jnp.ndarray]:
    """Duplicate mask: None when off, else True = processed slot stays."""
    if p_dup <= 0.0:
        return None
    return _bernoulli_bits(key, shape, p_dup)


def select_from_scores(
    present: jnp.ndarray, score_bits: jnp.ndarray, busy: Optional[jnp.ndarray]
) -> jnp.ndarray:
    """Pick at most one present request per (instance, acceptor) — pure part.

    Selection is a max over per-slot random uint32 scores whose low bits are
    replaced by the slot's (kind, proposer) index: scores within a (A, I)
    fiber are therefore *distinct*, so ``score == fiber_max`` recovers the
    winner as a mask directly — no transpose, no argmax, no one_hot, all in
    the buffers' native instance-minor layout.  An all-zero fiber max means
    "nothing present" (a present slot scores 0 only with prob ~2^-27, a
    vanishing extra idle tick).

    Args:
      present: (2, P, A, I) bool — occupied request slots.
      score_bits: (2, P, A, I) uint32 — this tick's raw selection entropy.
      busy: optional (1, 1, A, I) bool — False = acceptor idles this tick.

    Returns:
      (2, P, A, I) bool one-hot (per (A, I) fiber) selection mask.
    """
    k, p, a, i = present.shape
    nbits = max((k * p - 1).bit_length(), 1)  # low bits reserved for slot id
    # Slot ids from two (k|p, 1, 1)-sized iotas broadcast-added — the same
    # integers as full-shape iotas, without two full-shape layout passes.
    sid = (
        jax.lax.broadcasted_iota(jnp.int32, (k, 1, 1, 1), 0) * p
        + jax.lax.broadcasted_iota(jnp.int32, (1, p, 1, 1), 1)
    )
    # All-int32 scoring (Mosaic has neither unsigned reductions nor clean
    # unsigned register casts): random int32 bits give a uniform total order
    # directly, the slot id in the low bits makes scores distinct per fiber,
    # and INT32_MIN is the exact "absent" sentinel.  A present slot whose
    # masked bits happen to equal the sentinel pattern simply idles one tick
    # (prob ~2^-27 per fiber — vanishing).
    bits_i = score_bits.astype(jnp.int32)  # wraps: bit-preserving
    score = (bits_i & jnp.int32(~((1 << nbits) - 1))) | sid
    neg_inf = jnp.iinfo(jnp.int32).min
    score = jnp.where(present, score, neg_inf)
    fiber_max = score.max(axis=(0, 1), keepdims=True)  # (1, 1, A, I)
    sel = present & (score == fiber_max) & (fiber_max > neg_inf)
    if busy is not None:
        sel = sel & busy
    return sel


def ready(buf: MsgBuf, tick: jnp.ndarray) -> Optional[jnp.ndarray]:
    """(2, P, A, I) bool: slot's delay window has passed.

    None when the ``until`` leaf is pruned (delay off) so callers can skip
    the gate entirely — delay off adds zero eqns to the traced step.
    """
    if buf.until is None:
        return None
    return tick >= buf.until


def send(
    buf: MsgBuf,
    kind: int,
    send_mask: jnp.ndarray,
    bal: jnp.ndarray,
    v1: jnp.ndarray,
    v2: jnp.ndarray,
    keep: Optional[jnp.ndarray] = None,
    until: Optional[jnp.ndarray] = None,
) -> MsgBuf:
    """Write messages of ``kind`` into their slots (overwriting), minus drops.

    Args:
      buf: the target buffer family.
      kind: request/reply kind index (0 or 1).
      send_mask: (P, A, I) bool — which edges send this tick.
      bal, v1, v2: (P, A, I) int32 payloads (broadcastable).
      keep: optional (P, A, I) bool — send-time survival (False = dropped).
      until: optional (P, A, I) int32 — earliest delivery tick for the
        written slots (bounded-delay stamp); requires the buffer to carry
        an ``until`` leaf.  Omitted = deliverable immediately.
    """
    if keep is not None:
        send_mask = send_mask & keep

    # One full-shape write mask, shared by every leaf: the kind one-hot AND
    # the broadcast send edges.  Kind-axis updates stay in the elementwise
    # where-over-iota form — NOT `.at[kind].set` (lowers to scatter) and NOT
    # stack/concat (invalid register casts): Mosaic, the Pallas TPU
    # compiler, only lowers the elementwise form cleanly.  Payloads land via
    # where's implicit broadcast, so there is no per-field slice/squeeze of
    # the old kind plane and no zero-broadcast to shape them.
    kind_hot = (
        jax.lax.broadcasted_iota(jnp.int32, buf.bal.shape, 0) == kind
    )  # (2, P, A, I)
    write = kind_hot & jnp.broadcast_to(send_mask[None], buf.present.shape)
    # `present` is monotone (old | sent), so its kind-axis update is pure
    # boolean algebra — Mosaic rejects select_n on bool vectors, which rules
    # out jnp.where for the bool leaf.
    new_until = buf.until
    if buf.until is not None:
        new_until = jnp.where(
            write, until if until is not None else 0, buf.until
        )
    return buf.replace(
        bal=jnp.where(write, bal, buf.bal),
        v1=jnp.where(write, v1, buf.v1),
        v2=jnp.where(write, v2, buf.v2),
        present=buf.present | write,
        until=new_until,
    )


def consume(
    buf: MsgBuf, taken: jnp.ndarray, stay: Optional[jnp.ndarray] = None
) -> MsgBuf:
    """Clear slots that were processed this tick, except duplicated ones.

    Args:
      taken: (2, P, A, I) bool — slots whose message was processed.
      stay: optional (2, P, A, I) bool — True = processed slot remains in
        flight anyway (duplicate delivery).
    """
    if stay is not None:
        taken = taken & ~stay
    return buf.replace(present=buf.present & ~taken)
