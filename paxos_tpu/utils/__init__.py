"""Small shared utilities (bit tricks, pytree helpers)."""

from paxos_tpu.utils.bitops import acceptor_bit, popcount  # noqa: F401
