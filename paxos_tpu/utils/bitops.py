"""Bit-field layout library: quorum masks + packed lane-state codecs.

Two layers live here:

1. The original bit-set helpers (``acceptor_bit``/``popcount``): "which
   acceptors have I heard from" is a set over at most ``MAX_ACCEPTORS``
   elements, so it lives in one int32 lane per (instance, proposer) — the
   struct-of-arrays analog of the reference proposer's list of collected
   Promise/Accepted replies (SURVEY.md §4.2 [P]).

2. A declarative field-layout library (ROADMAP item 3): per-protocol layout
   tables (``core/state.py`` etc.) declare how today's one-int32-per-field
   state leaves fuse into dense 32-bit words — ``F`` bit-fields grouped into
   ``Word``s, ``Stream``s of packed (ballot, value) log pairs, and ``Zero``
   leaves that are always-zero by protocol invariant and need no storage at
   all.  :func:`codec_for` resolves a table against a concrete state pytree
   into a :class:`Codec` whose ``pack``/``unpack`` compile to shifts+masks
   (ALU work, not layout shuffles), and :class:`PackedState` is the packed
   pytree the fused Pallas engine keeps resident in VMEM across ticks
   (``kernels/fused_tick.py``).  The XLA reference path and every golden
   compare on the *unpacked* pytree — packing is an engine-internal
   representation, not a semantic change.

Field widths are chosen from protocol invariants (ballot/value/timer bounds
enforced at config time in ``harness/run.py`` and at report time via the
``max_ballot`` guard); pack masks to the declared width, so an out-of-range
value wraps — the roundtrip property tests (tests/test_bitops.py) pin that
behavior at the field boundaries.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax import lax

MAX_ACCEPTORS = 16  # bitmask capacity; protocol configs use 3-7


def acceptor_bit(a):
    """int32 mask with bit ``a`` set."""
    return jnp.asarray(1, jnp.int32) << jnp.asarray(a, jnp.int32)


def popcount(mask):
    """Number of set bits, elementwise (int32 in, int32 out)."""
    return jax.lax.population_count(jnp.asarray(mask, jnp.int32))


# ---------------------------------------------------------------------------
# Low-level shift/mask helpers (Mosaic-safe: signed int32 arithmetic only).


def shr_logical(x, k: int):
    """Logical right shift of int32 by a static amount, without uint32.

    Mosaic vectors are signed int32, so ``>>`` sign-extends; masking off the
    ``k`` replicated sign bits recovers the logical shift.
    """
    if k == 0:
        return x
    return jnp.right_shift(x, k) & ((1 << (32 - k)) - 1)


def unpack_field(word, off: int, bits: int, signed: bool = False):
    """Extract a ``bits``-wide field at bit offset ``off`` from int32 words."""
    if signed:
        # Two's-complement sign extension: left-justify, arithmetic shift back.
        return jnp.right_shift(jnp.left_shift(word, 32 - off - bits), 32 - bits)
    return shr_logical(word, off) & ((1 << bits) - 1)


def pack_field(value, off: int, bits: int):
    """Mask ``value`` to ``bits`` and place it at ``off`` (OR into a word)."""
    v = value & ((1 << bits) - 1)
    return v if off == 0 else jnp.left_shift(v, off)


def set_field(word, value, off: int, bits: int):
    """Return ``word`` with the (off, bits) field replaced by ``value``."""
    hole = word & ~(((1 << bits) - 1) << off)
    return hole | pack_field(value, off, bits)


def pack_word(values_offs_bits):
    """OR a sequence of ``(value, off, bits)`` fields into one int32 word."""
    acc = None
    for value, off, bits in values_offs_bits:
        v = pack_field(value, off, bits)
        acc = v if acc is None else acc | v
    return acc


# (ballot, value) pair transcoding: core/mp_state.py packs pairs as
# bal << 16 | val for lexicographic int32 compares.  With bal < 2^bal_bits
# and val < 2^val_bits (config/report-time guards), the pair transcodes to a
# dense (bal_bits + val_bits)-bit integer and back, bit-exactly.


def bv_to_dense(bv, bal_bits: int, val_bits: int):
    """16-bit-aligned (bal << 16 | val) pair -> dense bal_bits+val_bits int."""
    bal = jnp.right_shift(bv, 16) & ((1 << bal_bits) - 1)  # bv >= 0
    return jnp.left_shift(bal, val_bits) | (bv & ((1 << val_bits) - 1))


def dense_to_bv(e, bal_bits: int, val_bits: int):
    """Inverse of :func:`bv_to_dense`."""
    bal = jnp.right_shift(e, val_bits) & ((1 << bal_bits) - 1)  # e >= 0
    return jnp.left_shift(bal, 16) | (e & ((1 << val_bits) - 1))


# ---------------------------------------------------------------------------
# Layout spec types — what the per-protocol tables in core/*.py are made of.


class F:
    """One bit-field: ``path`` (dotted attribute path into the state pytree),
    ``bits`` (int, or a str naming a layout dim resolved from state shapes),
    ``signed`` (two's-complement storage), ``bool_`` (1-bit flag leaves),
    ``bv`` ((bal_bits, val_bits): leaf holds bal<<16|val pairs, transcoded
    dense — see :func:`bv_to_dense`)."""

    __slots__ = ("path", "bits", "signed", "bool_", "bv")

    def __init__(self, path, bits, signed=False, bool_=False, bv=None):
        self.path, self.bits = path, bits
        self.signed, self.bool_, self.bv = signed, bool_, bv


class Word:
    """Named group of fields fused into 32-bit words.  A resolved group whose
    widths exceed 32 bits is split greedily (in declared order) into
    ``name_0, name_1, ...``.  ``optional`` words vanish when their leaves are
    pruned (e.g. snapshot shadows with ``stale_k=0``).  Layout rule: never
    declare a single-field word — an int32 passthrough is the same bytes with
    zero truncation risk, and unlisted leaves pass through automatically."""

    __slots__ = ("name", "fields", "optional")

    def __init__(self, name, *fields, optional=False):
        self.name, self.fields, self.optional = name, tuple(fields), optional


class Stream:
    """A (bal << 16 | val) log leaf packed 4 pairs -> 3 words along its slot
    axis (always axis -2: (..., L, I) -> (..., W, I)).  Each pair transcodes
    to bal_bits + val_bits == 24 dense bits; W = 3*(L//4) + (L%4)."""

    __slots__ = ("name", "path", "bal_bits", "val_bits", "optional")

    def __init__(self, name, path, bal_bits=11, val_bits=13, optional=False):
        if bal_bits + val_bits != 24:
            raise ValueError("Stream packing is specialized to 24-bit pairs")
        self.name, self.path = name, path
        self.bal_bits, self.val_bits, self.optional = bal_bits, val_bits, optional


class Zero:
    """A leaf that is identically zero by protocol invariant (e.g. paxos
    ``requests.v2``: every send writes 0 there).  Stores nothing; unpack
    re-materializes zeros shaped like the ``like`` word (which must share the
    leaf's shape)."""

    __slots__ = ("path", "like")

    def __init__(self, path, like):
        self.path, self.like = path, like


# ---------------------------------------------------------------------------
# Read/write-set declarations (the delta-codec contract).
#
# Each protocol's layout module declares which dotted leaf paths its tick
# (`apply_fn`, including the fused counter-PRNG mask source) may READ and
# which it may WRITE.  Entries are exact paths ("proposer.bal"), subtree
# globs ("acceptor.*"), or "*".  The codec's differential entry points key
# off these: ``unpack_read`` materializes only read leaves, ``pack_delta``
# re-encodes only written fields and carries every untouched word through
# unchanged.  The declarations are load-bearing, not documentation — the
# always-on audit (analysis/structure.py) traces each protocol's tick jaxpr
# and fails if an actual write escapes the declared write-set, and the
# layout goldens pin both sets, so edits require a version bump.


def path_matches(path: str, decls) -> bool:
    """True when dotted leaf ``path`` is covered by a declaration tuple."""
    for d in decls:
        if d == "*" or d == path:
            return True
        if d.endswith(".*") and path.startswith(d[:-1]):
            return True
    return False


def leaf_paths(state) -> "list[str]":
    """Dotted attribute paths for every leaf of a state pytree, aligned with
    ``jax.tree_util.tree_leaves`` order.  Works by unflattening integer
    tokens and walking dataclass fields — the same trick ``_build_codec``
    uses for single-path lookup, generalized to the full inventory (shared
    by the write-set audit and the delta-codec tests)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    tokens = jax.tree_util.tree_unflatten(treedef, list(range(len(leaves))))
    paths: list = [None] * len(leaves)

    def walk(obj, prefix):
        if isinstance(obj, int):
            paths[obj] = prefix.rstrip(".") or "<root>"
            return
        if dataclasses.is_dataclass(obj):
            for f in dataclasses.fields(obj):
                # Static aux fields (flax ``pytree_node=False``, e.g. the
                # workload plane's knob carrier) are treedef data, not
                # leaves — they never reach the codec.
                if not f.metadata.get("pytree_node", True):
                    continue
                v = getattr(obj, f.name)
                if v is not None:
                    walk(v, prefix + f.name + ".")
            return
        if isinstance(obj, (list, tuple)):
            for i, v in enumerate(obj):
                walk(v, f"{prefix}{i}.")
            return
        raise TypeError(f"cannot derive leaf paths through {type(obj)!r}")

    walk(tokens, "")
    return paths


# ---------------------------------------------------------------------------
# Resolved codec internals.


class _Slot:
    __slots__ = ("leaf", "off", "bits", "signed", "bool_", "bv", "path")

    def __init__(self, leaf, off, bits, signed, bool_, bv, path):
        self.leaf, self.off, self.bits = leaf, off, bits
        self.signed, self.bool_, self.bv = signed, bool_, bv
        self.path = path


class _PWord:
    __slots__ = ("name", "slots")

    def __init__(self, name, slots):
        self.name, self.slots = name, tuple(slots)


class _PStream:
    __slots__ = ("name", "leaf", "bal_bits", "val_bits", "length")

    def __init__(self, name, leaf, bal_bits, val_bits, length):
        self.name, self.leaf = name, leaf
        self.bal_bits, self.val_bits, self.length = bal_bits, val_bits, length


def stream_words(length: int) -> int:
    """Packed word count along the slot axis for an L-entry stream."""
    return 3 * (length // 4) + (length % 4)


def _stream_pack(x, bal_bits: int, val_bits: int):
    e = bv_to_dense(x, bal_bits, val_bits)  # (..., L, I), 24 bits per entry
    ax = x.ndim - 2
    length = x.shape[ax]

    def sl(i):
        return lax.slice_in_dim(e, i, i + 1, axis=ax)

    out = []
    for g in range(length // 4):
        e0, e1, e2, e3 = (sl(4 * g + j) for j in range(4))
        out.append(e0 | jnp.left_shift(e1, 24))
        out.append(shr_logical(e1, 8) | jnp.left_shift(e2, 16))
        out.append(shr_logical(e2, 16) | jnp.left_shift(e3, 8))
    r = length % 4
    b = 4 * (length // 4)
    if r >= 1:
        e0 = sl(b)
        if r == 1:
            out.append(e0)
        else:
            e1 = sl(b + 1)
            out.append(e0 | jnp.left_shift(e1, 24))
            if r == 2:
                out.append(shr_logical(e1, 8))
            else:
                e2 = sl(b + 2)
                out.append(shr_logical(e1, 8) | jnp.left_shift(e2, 16))
                out.append(shr_logical(e2, 16))
    return jnp.concatenate(out, axis=ax)


def _stream_unpack(w, bal_bits: int, val_bits: int, length: int):
    ax = w.ndim - 2

    def sl(i):
        return lax.slice_in_dim(w, i, i + 1, axis=ax)

    ents = []
    for g in range(length // 4):
        w0, w1, w2 = sl(3 * g), sl(3 * g + 1), sl(3 * g + 2)
        ents.append(w0 & 0xFFFFFF)
        ents.append(shr_logical(w0, 24) | jnp.left_shift(w1 & 0xFFFF, 8))
        ents.append(shr_logical(w1, 16) | jnp.left_shift(w2 & 0xFF, 16))
        ents.append(shr_logical(w2, 8))
    r = length % 4
    b = 3 * (length // 4)
    if r >= 1:
        w0 = sl(b)
        ents.append(w0 & 0xFFFFFF)
        if r >= 2:
            w1 = sl(b + 1)
            ents.append(shr_logical(w0, 24) | jnp.left_shift(w1 & 0xFFFF, 8))
            if r == 3:
                w2 = sl(b + 2)
                ents.append(shr_logical(w1, 16) | jnp.left_shift(w2 & 0xFF, 16))
    e = jnp.concatenate(ents, axis=ax)
    return dense_to_bv(e, bal_bits, val_bits)


# ---------------------------------------------------------------------------
# PackedState: the packed pytree the fused engine carries across ticks.


@jax.tree_util.register_pytree_node_class
class PackedState:
    """Dense word arrays + the tick scalar, as one pytree.

    Children are the word arrays in sorted-name order followed by ``tick``
    (so the fused engine's single-scalar-leaf invariant holds); aux data is
    the name tuple plus the :class:`Codec` (identity-hashed — codecs are
    cached per (protocol, structure), so treedefs stay jit-cache stable).
    """

    __slots__ = ("_names", "_values", "tick", "codec")

    def __init__(self, words: dict, tick, codec):
        self._names = tuple(sorted(words))
        self._values = tuple(words[n] for n in self._names)
        self.tick = tick
        self.codec = codec

    @property
    def words(self) -> dict:
        return dict(zip(self._names, self._values))

    def word(self, name: str):
        return self._values[self._names.index(name)]

    def tree_flatten(self):
        return self._values + (self.tick,), (self._names, self.codec)

    @classmethod
    def tree_unflatten(cls, aux, children):
        obj = object.__new__(cls)
        obj._names, obj.codec = aux
        obj._values = tuple(children[:-1])
        obj.tick = children[-1]
        return obj


class Codec:
    """A layout table resolved against one concrete state structure.

    Instances come from :func:`codec_for` only (cached), so identity
    equality/hashing is correct and cheap — the codec rides as a jit-static
    argument and inside ``PackedState`` treedefs.
    """

    def __init__(self, protocol, version, treedef, n_leaves, tick_leaf,
                 words, streams, zeros, passthroughs, dims, paths,
                 reads, writes):
        self.protocol, self.version = protocol, version
        self.treedef, self.n_leaves = treedef, n_leaves
        self.tick_leaf = tick_leaf
        self.words = tuple(words)  # _PWord
        self.streams = tuple(streams)  # _PStream
        self.zeros = tuple(zeros)  # (leaf_idx, like_name, dtype)
        self.passthroughs = tuple(passthroughs)  # (name, leaf_idx)
        self.dims = dict(dims)
        self.paths = tuple(paths)  # leaf index -> dotted path
        self.reads = tuple(reads)  # declared read-set (paths / globs)
        self.writes = tuple(writes)  # declared write-set (paths / globs)

    def is_read(self, path: str) -> bool:
        return path_matches(path, self.reads)

    def is_written(self, path: str) -> bool:
        return path_matches(path, self.writes)

    def __repr__(self):
        return (f"Codec({self.protocol!r}, {self.version!r}, "
                f"words={len(self.words)}, streams={len(self.streams)}, "
                f"zeros={len(self.zeros)}, pt={len(self.passthroughs)})")

    def pack(self, state) -> PackedState:
        leaves, treedef = jax.tree_util.tree_flatten(state)
        if treedef != self.treedef:
            raise ValueError(
                f"state structure does not match codec for {self.protocol!r}"
            )
        words = {}
        for w in self.words:
            acc = None
            for s in w.slots:
                x = leaves[s.leaf]
                if s.bool_:
                    x = x.astype(jnp.int32)
                if s.bv is not None:
                    x = bv_to_dense(x, *s.bv)
                v = pack_field(x, s.off, s.bits)
                acc = v if acc is None else acc | v
            words[w.name] = acc
        for st in self.streams:
            words[st.name] = _stream_pack(leaves[st.leaf], st.bal_bits,
                                          st.val_bits)
        for name, leaf in self.passthroughs:
            words[name] = leaves[leaf]
        return PackedState(words, leaves[self.tick_leaf], self)

    def unpack(self, pst: PackedState):
        vals = pst.words
        leaves: list = [None] * self.n_leaves
        for w in self.words:
            arr = vals[w.name]
            for s in w.slots:
                x = unpack_field(arr, s.off, s.bits, s.signed)
                if s.bv is not None:
                    x = dense_to_bv(x, *s.bv)
                if s.bool_:
                    x = x.astype(jnp.bool_)
                leaves[s.leaf] = x
        for st in self.streams:
            leaves[st.leaf] = _stream_unpack(vals[st.name], st.bal_bits,
                                             st.val_bits, st.length)
        for leaf, like, dtype in self.zeros:
            leaves[leaf] = jnp.zeros(vals[like].shape, dtype)
        for name, leaf in self.passthroughs:
            leaves[leaf] = vals[name]
        leaves[self.tick_leaf] = pst.tick
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def unpack_read(self, pst: PackedState):
        """Differential unpack: shift+mask only the declared read-set.

        Leaves outside the read-set materialize as zeros — reading one is a
        write-set-audit-class bug, not a supported path.  With every current
        protocol declaring a full read-set this is op-identical to
        :meth:`unpack`; the asymmetry that pays today is on the pack side
        (:meth:`pack_delta`), but the read filter keeps the contract
        symmetric for future sparse-read protocols.
        """
        vals = pst.words
        leaves: list = [None] * self.n_leaves
        for w in self.words:
            arr = vals[w.name]
            for s in w.slots:
                if not self.is_read(s.path):
                    leaves[s.leaf] = jnp.zeros(
                        arr.shape, jnp.bool_ if s.bool_ else jnp.int32
                    )
                    continue
                x = unpack_field(arr, s.off, s.bits, s.signed)
                if s.bv is not None:
                    x = dense_to_bv(x, *s.bv)
                if s.bool_:
                    x = x.astype(jnp.bool_)
                leaves[s.leaf] = x
        for st in self.streams:
            warr = vals[st.name]
            if self.is_read(self.paths[st.leaf]):
                leaves[st.leaf] = _stream_unpack(warr, st.bal_bits,
                                                 st.val_bits, st.length)
            else:
                shape = warr.shape[:-2] + (st.length, warr.shape[-1])
                leaves[st.leaf] = jnp.zeros(shape, jnp.int32)
        for leaf, like, dtype in self.zeros:
            leaves[leaf] = jnp.zeros(vals[like].shape, dtype)
        for name, leaf in self.passthroughs:
            leaves[leaf] = vals[name]
        leaves[self.tick_leaf] = pst.tick
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def pack_delta(self, pst: PackedState, new_state) -> PackedState:
        """Differential pack: merge only the declared write-set into the
        carried words of ``pst``.

        Per physical word: no written slot -> the carried word array passes
        through the fori_loop carry untouched (zero ops); every slot written
        -> full shift+OR rebuild (cheaper than clearing holes first); mixed
        -> :func:`set_field` merge per written slot, preserving the
        untouched bits in place.  Streams repack only when their leaf is in
        the write-set.  Bit-exactness contract (pinned by the write-set
        property fuzz in tests/test_bitops.py): whenever ``new_state``
        differs from ``unpack(pst)`` only at written leaves,
        ``pack_delta(pst, new_state)`` equals full ``pack(new_state)``.
        """
        leaves, treedef = jax.tree_util.tree_flatten(new_state)
        if treedef != self.treedef:
            raise ValueError(
                f"state structure does not match codec for {self.protocol!r}"
            )
        vals = pst.words
        words = {}
        for w in self.words:
            written = [self.is_written(s.path) for s in w.slots]
            if not any(written):
                words[w.name] = vals[w.name]
                continue

            def enc(s):
                x = leaves[s.leaf]
                if s.bool_:
                    x = x.astype(jnp.int32)
                if s.bv is not None:
                    x = bv_to_dense(x, *s.bv)
                return x

            if all(written):
                acc = None
                for s in w.slots:
                    v = pack_field(enc(s), s.off, s.bits)
                    acc = v if acc is None else acc | v
                words[w.name] = acc
            else:
                arr = vals[w.name]
                for s, wr in zip(w.slots, written):
                    if wr:
                        arr = set_field(arr, enc(s), s.off, s.bits)
                words[w.name] = arr
        for st in self.streams:
            if self.is_written(self.paths[st.leaf]):
                words[st.name] = _stream_pack(leaves[st.leaf], st.bal_bits,
                                              st.val_bits)
            else:
                words[st.name] = vals[st.name]
        for name, leaf in self.passthroughs:
            # Written passthroughs cost nothing either way; unwritten ones
            # are the same array by the write-set contract.
            words[name] = leaves[leaf]
        return PackedState(words, leaves[self.tick_leaf], self)

    def field_capacity(self, path: str) -> "int | None":
        """Largest value the packed field at ``path`` can hold, or None when
        the leaf is not a plain unsigned word field (passthrough / signed /
        bv-pair / stream).  The overflow-saturation policy in
        ``kernels/fused_tick.packed_fns`` keys off this: a monotone leaf
        clamped to its capacity survives pack/unpack as the capacity value,
        so report-time ``>= capacity`` guards stay satisfiable."""
        for w in self.words:
            for s in w.slots:
                if s.path == path:
                    if s.signed or s.bool_ or s.bv is not None:
                        return None
                    return (1 << s.bits) - 1
        return None

    def bytes_per_lane(self, state) -> float:
        """Packed VMEM bytes per instance lane (tick scalar excluded)."""
        p = jax.eval_shape(self.pack, state)
        arrs = p._values  # word arrays; last axis is always I
        n_inst = arrs[0].shape[-1]
        return sum(
            _size(a.shape) * jnp.dtype(a.dtype).itemsize for a in arrs
        ) / n_inst


def _size(shape):
    n = 1
    for d in shape:
        n *= d
    return n


def unpacked_bytes_per_lane(state) -> float:
    """Unpacked bytes per instance lane (tick scalar excluded) — the number
    ROOFLINE.json historically reported as ``state_bytes_per_lane``."""
    leaves = [l for l in jax.tree_util.tree_leaves(state) if l.ndim > 0]
    n_inst = leaves[0].shape[-1]
    return sum(
        _size(l.shape) * jnp.dtype(l.dtype).itemsize for l in leaves
    ) / n_inst


# ---------------------------------------------------------------------------
# Layout registry + codec builder.


def protocol_layout(protocol: str):
    """Resolve a protocol name to ``(version, entries, dims_spec)``.

    ``dims_spec`` maps symbolic width names (the str ``bits`` values in the
    table) to ``(leaf_path, axis)`` pairs resolved from state shapes.
    """
    if protocol == "paxos":
        from paxos_tpu.core import state as m

        return m.PAXOS_LAYOUT_VERSION, m.PAXOS_LAYOUT, m.PAXOS_LAYOUT_DIMS
    if protocol == "multipaxos":
        from paxos_tpu.core import mp_state as m

        return m.MP_LAYOUT_VERSION, m.MP_LAYOUT, m.MP_LAYOUT_DIMS
    if protocol == "fastpaxos":
        from paxos_tpu.core import fp_state as m

        return m.FP_LAYOUT_VERSION, m.FP_LAYOUT, m.FP_LAYOUT_DIMS
    if protocol == "raftcore":
        from paxos_tpu.core import raft_state as m

        return m.RAFT_LAYOUT_VERSION, m.RAFT_LAYOUT, m.RAFT_LAYOUT_DIMS
    if protocol == "synchpaxos":
        from paxos_tpu.core import sp_state as m

        return m.SP_LAYOUT_VERSION, m.SP_LAYOUT, m.SP_LAYOUT_DIMS
    raise ValueError(f"unknown protocol: {protocol!r}")


def layout_version(protocol: str) -> str:
    return protocol_layout(protocol)[0]


def protocol_rw(protocol: str) -> "tuple[tuple, tuple]":
    """Resolve a protocol name to its declared ``(read_set, write_set)``
    tick declarations (dotted paths / subtree globs — see the read/write-set
    section above)."""
    if protocol == "paxos":
        from paxos_tpu.core import state as m

        return m.PAXOS_TICK_READS, m.PAXOS_TICK_WRITES
    if protocol == "multipaxos":
        from paxos_tpu.core import mp_state as m

        return m.MP_TICK_READS, m.MP_TICK_WRITES
    if protocol == "fastpaxos":
        from paxos_tpu.core import fp_state as m

        return m.FP_TICK_READS, m.FP_TICK_WRITES
    if protocol == "raftcore":
        from paxos_tpu.core import raft_state as m

        return m.RAFT_TICK_READS, m.RAFT_TICK_WRITES
    if protocol == "synchpaxos":
        from paxos_tpu.core import sp_state as m

        return m.SP_TICK_READS, m.SP_TICK_WRITES
    raise ValueError(f"unknown protocol: {protocol!r}")


def layout_field_width(protocol: str, path: str) -> "tuple[int, bool]":
    """(bits, signed) for a fixed-width word field in a protocol's layout
    table — state-free, so config/argument-time bound checks (e.g. the
    ticks-per-campaign guard against ``learner.chosen_tick`` in
    ``harness/run.py``) can read the width without building a state."""
    _, entries, _ = protocol_layout(protocol)
    for e in entries:
        if isinstance(e, Word):
            for f in e.fields:
                if f.path == path:
                    if isinstance(f.bits, str):
                        raise ValueError(
                            f"{protocol}: field {path!r} width {f.bits!r} is "
                            "symbolic (state-shape-dependent)"
                        )
                    return int(f.bits), bool(f.signed)
    raise KeyError(f"{protocol}: no word field at {path!r}")


def layout_fields(protocol: str) -> dict:
    """Canonical per-field descriptors for the audit's layout goldens.

    Symbolic widths stay symbolic, so the golden is dimension-independent:
    resolving ``n_acc`` differently (auto-split) is not a layout change,
    editing the table is.
    """
    _, entries, dims_spec = protocol_layout(protocol)
    out = {}
    for e in entries:
        if isinstance(e, Word):
            for j, f in enumerate(e.fields):
                out[f.path] = (
                    f"word={e.name} slot={j} bits={f.bits} "
                    f"signed={int(f.signed)} bool={int(f.bool_)} bv={f.bv}"
                    + (" optional" if e.optional else "")
                )
        elif isinstance(e, Stream):
            out[e.path] = (
                f"stream={e.name} bal={e.bal_bits} val={e.val_bits}"
                + (" optional" if e.optional else "")
            )
        elif isinstance(e, Zero):
            out[e.path] = f"zero like={e.like}"
        else:  # pragma: no cover - spec bug
            raise TypeError(f"unknown layout entry: {e!r}")
    out["__dims__"] = repr(sorted(dims_spec.items()))
    reads, writes = protocol_rw(protocol)
    out["__reads__"] = repr(tuple(sorted(reads)))
    out["__writes__"] = repr(tuple(sorted(writes)))
    return out


_CODEC_CACHE: dict = {}


def codec_for(protocol: str, state) -> Codec:
    """Resolve (and cache) the packed codec for a concrete state pytree.

    The cache key is the full structural signature — treedef plus every
    leaf's (shape, dtype) — so codecs are identity-stable across calls and
    safe as jit-static arguments; tracers work as well as concrete arrays.
    """
    leaves, treedef = jax.tree_util.tree_flatten(state)
    sig = (
        protocol,
        treedef,
        tuple((tuple(l.shape), jnp.dtype(l.dtype).name) for l in leaves),
    )
    codec = _CODEC_CACHE.get(sig)
    if codec is None:
        codec = _build_codec(protocol, leaves, treedef)
        _CODEC_CACHE[sig] = codec
    return codec


def _build_codec(protocol, leaves, treedef) -> Codec:
    version, entries, dims_spec = protocol_layout(protocol)
    # Leaf-index lookup by dotted path: unflatten the treedef with integer
    # tokens as leaves, then attribute-walk.  Robust to how containers
    # register with the pytree machinery — no key-path API needed.
    token_state = jax.tree_util.tree_unflatten(treedef, list(range(len(leaves))))

    def leaf_index(path):
        obj = token_state
        for part in path.split("."):
            if obj is None:
                return None
            obj = getattr(obj, part, None)
        return obj if isinstance(obj, int) else None

    dims = {}
    for name, (path, axis) in dims_spec.items():
        i = leaf_index(path)
        if i is None:
            raise ValueError(f"{protocol}: dim {name!r} path {path!r} missing")
        dims[name] = int(leaves[i].shape[axis])

    def width(bits):
        w = dims[bits] if isinstance(bits, str) else bits
        if not 1 <= w <= 31:
            raise ValueError(f"{protocol}: field width {bits!r} -> {w} out of range")
        return w

    used: set = set()

    def consume(i, path):
        if i in used:
            raise ValueError(f"{protocol}: leaf {path!r} consumed twice")
        used.add(i)

    words, streams, zeros = [], [], []
    word_names: dict = {}  # logical name -> physical word count
    for e in entries:
        if isinstance(e, Word):
            idxs = [leaf_index(f.path) for f in e.fields]
            missing = [f.path for f, i in zip(e.fields, idxs) if i is None]
            if missing:
                if e.optional and len(missing) == len(idxs):
                    continue
                raise ValueError(
                    f"{protocol}: word {e.name!r} fields missing: {missing}"
                )
            shape = tuple(leaves[idxs[0]].shape)
            for f, i in zip(e.fields, idxs):
                if tuple(leaves[i].shape) != shape:
                    raise ValueError(
                        f"{protocol}: word {e.name!r} field {f.path!r} shape "
                        f"{tuple(leaves[i].shape)} != {shape}"
                    )
                consume(i, f.path)
            # Greedy split into <= 32-bit physical words, declared order.
            phys, slots, off = [], [], 0
            for f, i in zip(e.fields, idxs):
                b = width(f.bits)
                if off + b > 32:
                    phys.append(slots)
                    slots, off = [], 0
                slots.append(_Slot(i, off, b, f.signed, f.bool_, f.bv, f.path))
                off += b
            phys.append(slots)
            names = (
                [e.name] if len(phys) == 1
                else [f"{e.name}_{j}" for j in range(len(phys))]
            )
            word_names[e.name] = names
            for n, s in zip(names, phys):
                words.append(_PWord(n, s))
        elif isinstance(e, Stream):
            i = leaf_index(e.path)
            if i is None:
                if e.optional:
                    continue
                raise ValueError(f"{protocol}: stream leaf {e.path!r} missing")
            if len(leaves[i].shape) < 2:
                raise ValueError(f"{protocol}: stream {e.path!r} needs a slot axis")
            consume(i, e.path)
            streams.append(
                _PStream(e.name, i, e.bal_bits, e.val_bits,
                         int(leaves[i].shape[-2]))
            )
        elif isinstance(e, Zero):
            i = leaf_index(e.path)
            if i is None:
                raise ValueError(f"{protocol}: zero leaf {e.path!r} missing")
            consume(i, e.path)
            zeros.append((i, e.like, jnp.dtype(leaves[i].dtype)))
        else:
            raise TypeError(f"{protocol}: unknown layout entry {e!r}")

    # Zero `like` targets must resolve to exactly one same-shaped physical word.
    for leaf, like, _ in zeros:
        names = word_names.get(like)
        if not names or len(names) != 1:
            raise ValueError(
                f"{protocol}: Zero like={like!r} must name an unsplit word"
            )
        like_word = next(w for w in words if w.name == names[0])
        if tuple(leaves[leaf].shape) != tuple(leaves[like_word.slots[0].leaf].shape):
            raise ValueError(f"{protocol}: Zero like={like!r} shape mismatch")
    zeros = [(leaf, word_names[like][0], dt) for leaf, like, dt in zeros]

    # The tick scalar: the one 0-d leaf (the fused engine's invariant).
    scalar = [i for i, l in enumerate(leaves) if len(l.shape) == 0]
    if len(scalar) != 1:
        raise ValueError(f"{protocol}: expected exactly 1 scalar leaf, got {scalar}")
    tick_leaf = scalar[0]
    consume(tick_leaf, "tick")

    # Everything unlisted passes through unchanged (telemetry rings, bool
    # masks, full-range values) under a deterministic index-derived name.
    passthroughs = [
        (f"pt{i:03d}", i) for i in range(len(leaves)) if i not in used
    ]

    seen: set = set()
    for n in [w.name for w in words] + [s.name for s in streams] + [
        n for n, _ in passthroughs
    ]:
        if n in seen:
            raise ValueError(f"{protocol}: duplicate packed word name {n!r}")
        seen.add(n)

    paths = leaf_paths(token_state)
    reads, writes = protocol_rw(protocol)
    return Codec(protocol, version, treedef, len(leaves), tick_leaf,
                 words, streams, zeros, passthroughs, dims, paths,
                 reads, writes)


# Jitted adapters (static codec, so each codec gets its own cache entry).
# The XLA reference path and goldens stay on the unpacked pytree; these are
# the boundary crossings the fused wrappers (kernels/fused_tick.FUSED_CHUNKS)
# and benches use.


@functools.partial(jax.jit, static_argnums=(0,))
def pack_state(codec: Codec, state) -> PackedState:
    """Pack an unpacked state pytree (jitted; codec static)."""
    return codec.pack(state)


@functools.partial(jax.jit, static_argnums=(0,))
def unpack_state(codec: Codec, pst: PackedState):
    """Unpack a :class:`PackedState` (jitted; codec static)."""
    return codec.unpack(pst)
