"""Bit-level helpers for quorum bookkeeping.

"Which acceptors have I heard from this phase" is a set over at most
``MAX_ACCEPTORS`` elements, so it lives in one int32 lane per (instance,
proposer) — the struct-of-arrays analog of the reference proposer's list of
collected Promise/Accepted replies (SURVEY.md §4.2 [P]).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_ACCEPTORS = 16  # bitmask capacity; protocol configs use 3-7


def acceptor_bit(a):
    """int32 mask with bit ``a`` set."""
    return jnp.asarray(1, jnp.int32) << jnp.asarray(a, jnp.int32)


def popcount(mask):
    """Number of set bits, elementwise (int32 in, int32 out)."""
    return jax.lax.population_count(jnp.asarray(mask, jnp.int32))
