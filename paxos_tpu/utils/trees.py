"""Pytree comparison helpers shared by tests and the driver contract.

Bit-exact state equality is the framework's central testing move (fused vs
reference stream, sharded vs unsharded, segmented vs single-kernel, resumed
vs uninterrupted), so the compare-and-collect idiom lives here once instead
of being re-rolled per test file.
"""

from __future__ import annotations

from typing import Any

import jax


def tree_mismatches(a: Any, b: Any) -> list:
    """Key paths at which two pytrees are not elementwise equal.

    Both trees are fetched to host first.  ``tree_map_with_path`` raises on
    any tree-structure mismatch, so a future state-field change can never
    silently truncate the comparison.
    """
    ah, bh = jax.device_get(a), jax.device_get(b)
    mism: list = []
    jax.tree_util.tree_map_with_path(
        lambda p, x, y: mism.append(p) if not (x == y).all() else None, ah, bh
    )
    return mism


def assert_trees_equal(a: Any, b: Any, msg: str = "pytrees differ") -> None:
    """Assert bit-exact equality, naming the mismatching key paths."""
    mism = tree_mismatches(a, b)
    assert not mism, f"{msg}: {mism}"
