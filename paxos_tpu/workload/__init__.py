"""Client-workload plane: open-loop traffic + on-device queue accounting.

See ``workload.generator`` for the arrival processes and queue mechanics,
and ``obs.slo`` for the summarize-boundary SLO reductions.
"""

from paxos_tpu.workload.generator import (  # noqa: F401
    CLASSES,
    MIXES,
    WLOAD_SCOPE,
    WloadState,
    WorkloadConfig,
    arrival_threshold,
    np_arrival_threshold,
    np_replay_queue,
    observe,
    rate_to_threshold,
)
