"""Open-loop client-arrival generator and on-device queue accounting.

The tenth observability layer (ROADMAP item 5): every lane previously ran
the same duel-style proposer workload, so nothing measured how the
protocols behave under production-shaped traffic.  This module adds a
per-proposer *open-loop* client queue — arrivals keep coming whether or
not the system keeps up, which is what makes overload measurable at all
(a closed loop self-throttles and hides the knee).

Per (proposer, instance) lane:

- **Arrival process** (:func:`arrival_threshold`): one Bernoulli draw per
  tick against a per-lane uint32 threshold.  The threshold is modulated by
  the lane's workload *class* (``mode``): Poisson (constant baseline
  rate), bursty (a ``burst_len``-tick window of ``burst_rate`` every
  ``period`` ticks), or diurnal (a triangle wave between the two rates).
  Class and phase are sampled once per campaign from the dedicated
  ``ROOT_WLOAD`` key lineage (``core.streams``), exactly like the fault
  plan; the per-tick raw bits come from the protocol mask samplers on the
  registered ``ARRIVAL`` streams/folds, so both engines draw their own
  deterministic stream and the auditor can see every draw.
- **Bounded queue** (:func:`observe`): a ring of enqueue-tick stamps.
  Serves happen *before* enqueues each tick; an arrival finding the ring
  full is **shed** (counted — goodput < offered is the overload signal).
  A serve pops the head stamp and banks ``tick - stamp`` — the
  queue-delay-inclusive client latency — into a per-class log2-bucket
  histogram, reduced at the summarize boundary (``obs.slo``) into
  per-class p50/p95/p99 and goodput-vs-offered curves.

The default-off-is-free contract (``obs.exposure`` is the template):
:class:`WloadState` rides as an Optional ``wload`` leaf of every protocol
state — ``None`` when disabled (pruned pytree, zero PRNG draws, golden
schedule digests byte-identical on both engines).  All leaves are int32
with a trailing instances axis and no scalars, so the fused engine's
generic passthrough codec (``utils/bitops``) carries the plane with ZERO
layout-table changes — the packed LAYOUT goldens stay byte-identical.
Mosaic diet: elementwise int32, iota-masked ``where`` instead of scatter,
sign-flip unsigned compares (``faults.injector.bits_below``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from paxos_tpu.core import streams as streams_mod
from paxos_tpu.faults.injector import bits_below

# Workload classes, in mode order (mode c of a lane is CLASSES[c]) — the
# row order of the per-class histogram and SLO tables.  Append only.
CLASSES = ("poisson", "bursty", "diurnal")

MIXES = ("off",) + CLASSES + ("mixed",)

# Named-scope tag wrapping every protocol's client-queue fold — the flow
# auditor (analysis/flow.py) uses it to recognize the arrival-sampling /
# queue-accounting region in traced step functions.
WLOAD_SCOPE = "__wload__client_queue"


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """Static client-workload knobs (frozen: rides ``SimConfig`` into jit).

    ``mix="off"`` — the default — disables the plane entirely (the state
    leaf prunes to ``None``, zero PRNG draws, bit-identical schedules).
    A named mix pins every lane to that arrival class; ``"mixed"`` samples
    a class per lane from the ``ROOT_WLOAD`` lineage.
    """

    mix: str = "off"
    rate: float = 0.05  # baseline per-tick arrival probability
    burst_rate: float = 0.5  # peak probability (bursty window / diurnal crest)
    period: int = 32  # bursty/diurnal cycle length, ticks
    burst_len: int = 8  # in-burst window length (bursty class)
    queue_cap: int = 8  # bounded per-proposer queue depth
    hist_bins: int = 16  # log2 latency buckets (bucket b: [2^b, 2^(b+1)))
    slo_p99_ticks: int = 0  # per-class p99 SLO; 0 = no breach gating

    def enabled(self) -> bool:
        return self.mix != "off"

    def validate(self) -> None:
        if self.mix not in MIXES:
            raise ValueError(
                f"workload mix {self.mix!r} not in {MIXES}"
            )
        if self.enabled():
            if not 2 <= self.period:
                raise ValueError("workload period must be >= 2 ticks")
            if not 1 <= self.burst_len <= self.period:
                raise ValueError(
                    "workload burst_len must be in [1, period]"
                )
            if not 1 <= self.queue_cap <= 64:
                raise ValueError("workload queue_cap must be in [1, 64]")
            if not 2 <= self.hist_bins <= 24:
                raise ValueError("workload hist_bins must be in [2, 24]")
            if not 0.0 <= self.rate <= 1.0:
                raise ValueError("workload rate must be in [0, 1]")
            if not 0.0 <= self.burst_rate <= 1.0:
                raise ValueError("workload burst_rate must be in [0, 1]")


def rate_to_threshold(p: float) -> int:
    """uint32 Bernoulli threshold for rate ``p``, as a python int.

    Matches ``kernels.counter_prng.bern``'s quantization exactly so the
    numpy replay oracle and both device engines agree bit-for-bit.
    """
    return max(0, min(int(round(p * float(1 << 32))), (1 << 32) - 1))


def _i32(c: int) -> jnp.ndarray:
    """int32 constant with the bit pattern of a (possibly >2^31) literal."""
    c &= 0xFFFFFFFF
    return jnp.int32(c - (1 << 32) if c >= (1 << 31) else c)


@struct.dataclass
class WloadState:
    """Per-lane open-loop client queue (int32, instance-minor, no scalars).

    The plan half (``mode``/``phase``) is sampled once at init from the
    ``ROOT_WLOAD`` lineage and never rewritten; the queue half mutates
    every tick.  The static :class:`WorkloadConfig` rides as pytree aux
    data (``pytree_node=False``) so :func:`observe` — called from inside
    ``apply_tick`` with no access to ``SimConfig`` — sees the knobs at
    trace time; it is part of the treedef, which the structure goldens
    pin per audit config.
    """

    mode: jnp.ndarray  # (P, I) int32 — arrival class, index into CLASSES
    phase: jnp.ndarray  # (P, I) int32 — cycle phase offset in [0, period)
    ring: jnp.ndarray  # (Q, P, I) int32 — enqueue-tick stamps (circular)
    head: jnp.ndarray  # (P, I) int32 — ring read index in [0, Q)
    depth: jnp.ndarray  # (P, I) int32 — live queue depth in [0, Q]
    depth_peak: jnp.ndarray  # (P, I) int32 — running max of depth
    offered: jnp.ndarray  # (P, I) int32 — arrivals sampled (open-loop load)
    done: jnp.ndarray  # (P, I) int32 — requests served (goodput)
    shed: jnp.ndarray  # (P, I) int32 — arrivals dropped on a full ring
    hist: jnp.ndarray  # (C*B, I) int32 — per-class log2 latency buckets
    cfg: WorkloadConfig = struct.field(pytree_node=False)

    @classmethod
    def init(
        cls, n_inst: int, n_prop: int, cfg: WorkloadConfig, seed: int
    ) -> "WloadState":
        """Sample the workload plan and zero the queue (host-side, once).

        Both engines share this init (like the fault plan), so the plan
        half is engine-independent by construction.
        """
        cfg.validate()
        k_mode, k_phase = jax.random.split(
            streams_mod.root_wload_key(seed), 2
        )
        shape = (n_prop, n_inst)
        if cfg.mix == "mixed":
            mode = jax.random.randint(
                k_mode, shape, 0, len(CLASSES), jnp.int32
            )
        else:
            mode = jnp.full(shape, CLASSES.index(cfg.mix), jnp.int32)
        phase = jax.random.randint(k_phase, shape, 0, cfg.period, jnp.int32)

        def z():
            return jnp.zeros(shape, jnp.int32)

        return cls(
            mode=mode,
            phase=phase,
            ring=jnp.zeros((cfg.queue_cap,) + shape, jnp.int32),
            head=z(),
            depth=z(),
            depth_peak=z(),
            offered=z(),
            done=z(),
            shed=z(),
            hist=jnp.zeros((len(CLASSES) * cfg.hist_bins, n_inst), jnp.int32),
            cfg=cfg,
        )


def arrival_threshold(wl: WloadState, tick) -> jnp.ndarray:
    """(P, I) int32 uint32-bit-pattern Bernoulli threshold for this tick.

    All-int32 (Mosaic-safe): the diurnal interpolation multiplies a static
    per-step threshold increment by the triangle position — int32 wrapping
    arithmetic is arithmetic mod 2^32, so the bit pattern matches the
    uint32 math of the numpy oracle exactly.
    """
    cfg = wl.cfg
    t_lo = rate_to_threshold(cfg.rate)
    t_hi = rate_to_threshold(cfg.burst_rate)
    halfp = max(cfg.period // 2, 1)
    step = (t_hi - t_lo) // halfp  # static python int (can be negative)

    pos = (tick + wl.phase) % jnp.int32(cfg.period)  # (P, I), non-negative
    thr = jnp.full_like(wl.mode, _i32(t_lo))  # class 0: constant baseline
    thr = jnp.where(
        (wl.mode == 1) & (pos < jnp.int32(cfg.burst_len)), _i32(t_hi), thr
    )
    tri = jnp.minimum(pos, jnp.int32(cfg.period) - pos)  # [0, halfp]
    thr = jnp.where(wl.mode == 2, _i32(t_lo) + _i32(step) * tri, thr)
    return thr


def observe(
    wl: WloadState, tick, serve: jnp.ndarray, arrival_bits: jnp.ndarray
) -> WloadState:
    """Fold one tick into the queue: serve first, then enqueue arrivals.

    ``serve`` is the protocol's per-(P, I) commit edge this tick (a lane
    whose proposer just completed a decision can retire one queued
    request); ``arrival_bits`` the raw int32 bits drawn on the registered
    ``ARRIVAL`` stream/fold by the engine's mask sampler.  PRNG-free
    itself — all randomness arrives pre-sampled, like the fault masks —
    and serve-before-enqueue means a request can never be served on its
    arrival tick (minimum latency 1 tick).
    """
    cfg = wl.cfg
    cap = cfg.queue_cap
    bins = cfg.hist_bins
    rowq = jax.lax.broadcasted_iota(jnp.int32, wl.ring.shape, 0)

    # ---- Serve: pop the head stamp, bank the latency ----
    pop = serve & (wl.depth > 0)  # (P, I)
    stamp = jnp.where(rowq == wl.head[None], wl.ring, 0).sum(axis=0)
    latency = tick - stamp  # >= 1 where popped (serve-before-enqueue)
    # log2 bucket: b = #{k in [1, bins): latency >= 2^k}, clamped to bins-1.
    bucket = jnp.zeros_like(latency)
    for k in range(1, bins):
        bucket = bucket + (latency >= jnp.int32(1 << k)).astype(jnp.int32)
    hist_row = wl.mode * jnp.int32(bins) + bucket  # (P, I)
    rowh = jax.lax.broadcasted_iota(
        jnp.int32, (wl.hist.shape[0],) + wl.mode.shape, 0
    )
    hist = wl.hist + jnp.where(
        (rowh == hist_row[None]) & pop[None], 1, 0
    ).sum(axis=1, dtype=jnp.int32)
    head1 = wl.head + 1
    head = jnp.where(
        pop, jnp.where(head1 >= cap, head1 - cap, head1), wl.head
    )
    depth = wl.depth - pop.astype(jnp.int32)

    # ---- Enqueue: one Bernoulli arrival per lane per tick ----
    arrival = bits_below(arrival_bits, arrival_threshold(wl, tick))
    room = depth < jnp.int32(cap)
    enq = arrival & room
    slot = head + depth
    slot = jnp.where(slot >= cap, slot - cap, slot)
    ring = jnp.where(
        (rowq == slot[None]) & enq[None],
        jnp.broadcast_to(tick, wl.ring.shape).astype(jnp.int32),
        wl.ring,
    )
    depth = depth + enq.astype(jnp.int32)

    return wl.replace(
        ring=ring,
        head=head,
        depth=depth,
        depth_peak=jnp.maximum(wl.depth_peak, depth),
        offered=wl.offered + arrival.astype(jnp.int32),
        done=wl.done + pop.astype(jnp.int32),
        shed=wl.shed + (arrival & ~room).astype(jnp.int32),
        hist=hist,
    )


# ---------------------------------------------------------------------------
# Numpy replay oracle: the same arrival thresholds and queue mechanics in
# plain numpy uint32/int64 arithmetic — the bit-exact host-side twin the
# generator tests diff both device engines against (tests/test_workload.py).


def np_arrival_threshold(
    cfg: WorkloadConfig, mode: np.ndarray, phase: np.ndarray, tick: int
) -> np.ndarray:
    """uint32 thresholds for one tick (numpy twin of :func:`arrival_threshold`)."""
    t_lo = rate_to_threshold(cfg.rate)
    t_hi = rate_to_threshold(cfg.burst_rate)
    halfp = max(cfg.period // 2, 1)
    step = (t_hi - t_lo) // halfp
    pos = (tick + phase.astype(np.int64)) % cfg.period
    thr = np.full(mode.shape, t_lo, np.int64)
    thr[(mode == 1) & (pos < cfg.burst_len)] = t_hi
    tri = np.minimum(pos, cfg.period - pos)
    diur = (t_lo + step * tri) % (1 << 32)
    thr = np.where(mode == 2, diur, thr)
    return thr.astype(np.uint32)


def np_replay_queue(
    cfg: WorkloadConfig,
    mode: np.ndarray,
    arrivals: np.ndarray,
    serves: np.ndarray,
) -> dict:
    """Replay the queue over captured per-tick streams; exact counters.

    ``arrivals``/``serves`` are (T, P, I) bool; returns the final
    offered/done/shed/depth/depth_peak/head arrays and the (C*B, I)
    histogram, for bit-exact comparison with the device leaves.
    """
    cap, bins = cfg.queue_cap, cfg.hist_bins
    n_ticks, n_prop, n_inst = arrivals.shape
    ring = np.zeros((cap, n_prop, n_inst), np.int64)
    head = np.zeros((n_prop, n_inst), np.int64)
    depth = np.zeros((n_prop, n_inst), np.int64)
    depth_peak = np.zeros((n_prop, n_inst), np.int64)
    offered = np.zeros((n_prop, n_inst), np.int64)
    done = np.zeros((n_prop, n_inst), np.int64)
    shed = np.zeros((n_prop, n_inst), np.int64)
    hist = np.zeros((len(CLASSES) * bins, n_inst), np.int64)
    for t in range(n_ticks):
        pop = serves[t] & (depth > 0)
        for p, i in zip(*np.nonzero(pop)):
            lat = t - ring[head[p, i], p, i]
            b = min(int(lat).bit_length() - 1, bins - 1) if lat >= 1 else 0
            hist[int(mode[p, i]) * bins + b, i] += 1
            head[p, i] = (head[p, i] + 1) % cap
            depth[p, i] -= 1
            done[p, i] += 1
        arr = arrivals[t]
        offered += arr
        room = depth < cap
        shed += arr & ~room
        for p, i in zip(*np.nonzero(arr & room)):
            ring[(head[p, i] + depth[p, i]) % cap, p, i] = t
            depth[p, i] += 1
        depth_peak = np.maximum(depth_peak, depth)
    return {
        "head": head,
        "depth": depth,
        "depth_peak": depth_peak,
        "offered": offered,
        "done": done,
        "shed": shed,
        "hist": hist,
    }
