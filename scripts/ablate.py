"""Ablation timing of paxos_step pieces on the current backend.

Times run_chunk with parts of the step disabled to locate the hot spot.
Not part of the library API; dev tool only.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

jax.config.update("jax_default_prng_impl", "rbg")

from paxos_tpu.check.safety import acceptor_invariants, learner_observe  # noqa: E402
from paxos_tpu.harness.config import config2_dueling_drop  # noqa: E402
from paxos_tpu.harness.run import base_key, init_plan, init_state, run_chunk  # noqa: E402
from paxos_tpu.protocols import paxos as px  # noqa: E402


def timed(tag, step, cfg, chunk=32, reps=2):
    step = functools.partial(step)  # fresh identity => run_chunk recompiles
    state = init_state(cfg)
    plan = init_plan(cfg)
    key = base_key(cfg)
    state = run_chunk(state, key, plan, cfg.fault, chunk, step)
    int(state.tick)  # sync
    t0 = time.perf_counter()
    for _ in range(reps):
        state = run_chunk(state, key, plan, cfg.fault, chunk, step)
    _ = int(state.tick) + int(state.learner.violations.sum())
    dt = (time.perf_counter() - t0) / (reps * chunk)
    print(f"{tag:28s} {dt * 1e3:8.2f} ms/tick")
    return dt


def main():
    n_inst = 1 << 20 if jax.devices()[0].platform != "cpu" else 1 << 14
    cfg = config2_dueling_drop(n_inst=n_inst, seed=0)

    timed("full", px.paxos_step, cfg)

    # no learner/checker
    real_observe = px.learner_observe
    real_inv = px.acceptor_invariants
    px.learner_observe = lambda l, *a, **k: l
    px.acceptor_invariants = lambda *a, **k: jnp.int32(0)
    timed("no-learner", px.paxos_step, cfg)
    px.learner_observe = real_observe
    px.acceptor_invariants = real_inv

    # no transport sends (emit disabled)
    real_send = px.net.send
    px.net.send = lambda buf, *a, **k: buf
    timed("no-sends", px.paxos_step, cfg)
    px.net.send = real_send

    # no acceptor select (nothing processed); apply_tick selects via
    # select_from_scores (the pure half of the old select_one)
    real_sel = px.net.select_from_scores
    px.net.select_from_scores = lambda present, bits, busy: jnp.zeros_like(present)
    timed("no-select", px.paxos_step, cfg)
    px.net.select_from_scores = real_sel

    # no consume (buffers never cleared)
    real_consume = px.net.consume
    px.net.consume = lambda buf, *a, **k: buf
    timed("no-consume", px.paxos_step, cfg)
    px.net.consume = real_consume

    # learner only (everything else identity-ish): approximate by full minus others


if __name__ == "__main__":
    main()
