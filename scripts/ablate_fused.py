"""Ablation timing of the FUSED engine's tick components (VERDICT r3 #7).

The old ``scripts/ablate.py`` times the XLA engine by monkeypatching module
globals; it predates the fused Pallas engine that carries every headline
number.  This tool ablates the fused kernel itself via the feature flags
threaded through ``fused_fns(protocol, ablate=...)`` — each variant is a
DIFFERENT traced program the compiler sees (no runtime branches, no
monkeypatching), so the deltas measure what Mosaic actually schedules.

Flags (interpreted in ``protocols/paxos.apply_tick`` /
``multipaxos.apply_tick_mp`` and the ``counter_masks`` samplers):

- ``prng``:     constant masks instead of counter-PRNG draws
- ``select``:   acceptors select nothing (no request processing)
- ``sends``:    no reply/request writes
- ``consume``:  delivered/selected buffers never cleared
- ``learner``:  no omniscient checker / invariants
- ``proposer``: no proposer half-tick

Ablated kernels are NOT the protocol (an ablated run's schedule is
meaningless); the only valid use is comparing their wall-clock against the
full kernel at identical shapes.  Component "shares" are reported as
``1 - t_ablated / t_full`` — overlapping work (e.g. sends feed consume)
means shares need not sum to 1.

Usage (TPU; CPU-interpret works but measures nothing real):

    python scripts/ablate_fused.py --protocol multipaxos --n-inst 1048576
    python scripts/ablate_fused.py --protocol paxos --record ablate.json
"""

from __future__ import annotations

import argparse
import json
import time

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from paxos_tpu.harness.cli import CONFIGS
from paxos_tpu.harness.run import init_plan, init_state
from paxos_tpu.kernels.fused_tick import fused_chunk, fused_fns

FLAGS = ("prng", "select", "sends", "consume", "learner", "proposer")


def time_variant(cfg, ablate, n_ticks, reps, interpret):
    apply_fn, mask_fn, block = fused_fns(cfg.protocol, frozenset(ablate))
    plan = init_plan(cfg)

    def chunk(state):
        return fused_chunk(
            state, jnp.int32(cfg.seed), plan, cfg.fault, n_ticks,
            apply_fn, mask_fn, block=None, interpret=interpret,
            default=block,
        )

    state = chunk(init_state(cfg))  # compile + warm
    int(state.tick)  # device->host readback (axon: block_until_ready lies)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        state = chunk(state)
        int(state.tick)
        best = min(best, time.perf_counter() - t0)
    return best / n_ticks


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--protocol", choices=["paxos", "multipaxos"],
                    default="paxos")
    ap.add_argument("--config", default=None,
                    help="config name (default: config2 for paxos, "
                    "config3 for multipaxos)")
    ap.add_argument("--n-inst", type=int, default=None)
    ap.add_argument("--ticks", type=int, default=256)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--record", default=None, help="write the table as JSON")
    args = ap.parse_args()

    on_tpu = jax.devices()[0].platform == "tpu"
    interpret = not on_tpu
    default_inst = (1 << 20) if on_tpu else (1 << 10)
    n_inst = args.n_inst or default_inst
    name = args.config or ("config2" if args.protocol == "paxos" else "config3")
    cfg = CONFIGS[name](n_inst=n_inst, seed=0)
    if cfg.protocol != args.protocol:
        raise SystemExit(f"config {name} is {cfg.protocol}, not {args.protocol}")
    if not on_tpu:
        print("# WARNING: not on TPU — interpret-mode times are meaningless; "
              "this run only validates that every variant compiles+runs")

    rows = []
    full = time_variant(cfg, (), args.ticks, args.reps, interpret)
    rows.append({"variant": "full", "us_per_tick": full * 1e6, "share": 0.0})
    print(f"{'full':12s} {full * 1e6:9.2f} us/tick")
    for flag in FLAGS:
        t = time_variant(cfg, (flag,), args.ticks, args.reps, interpret)
        share = 1.0 - t / full
        rows.append({"variant": f"no-{flag}",
                     "us_per_tick": t * 1e6, "share": share})
        print(f"{'no-' + flag:12s} {t * 1e6:9.2f} us/tick   "
              f"share {share * 100:5.1f}%")

    out = {
        "protocol": args.protocol,
        "config": name,
        "n_inst": n_inst,
        "ticks_per_chunk": args.ticks,
        "platform": jax.devices()[0].platform,
        "rows": rows,
    }
    print(json.dumps(out))
    if args.record:
        with open(args.record, "w") as f:
            json.dump(out, f, indent=1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
