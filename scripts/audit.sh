#!/usr/bin/env bash
# Full static determinism audit: every protocol x config cell through the
# jaxpr auditor (PRNG stream registry, purity lint, AST host-entropy pass)
# plus the default-off structural verifier and golden diffs.  Trace-time
# only — no campaign executes; a clean tree exits 0, findings exit 2.
#
# Usage: scripts/audit.sh [extra `paxos_tpu audit` flags...]
#   scripts/audit.sh --json            # machine-readable report
#   scripts/audit.sh --protocol paxos  # one protocol only
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu audit --structure "$@"
