#!/usr/bin/env bash
# Full static determinism audit: every protocol x config cell through the
# jaxpr auditor (PRNG stream registry, purity lint, AST host-entropy pass,
# the dataflow non-interference theorems of analysis/flow.py — observer
# isolation, fault-channel confinement, checker isolation, lane
# independence — and the eqn-size budget) plus the default-off structural
# verifier and golden diffs.  The flow pass is always-on, no flag needed.
# Trace-time only — no campaign executes; a clean tree exits 0, findings
# exit 2.  `--json` reports carry each finding's structured `data`
# (source leaf, sink, primitive) for machine consumers.
#
# Usage: scripts/audit.sh [extra `paxos_tpu audit` flags...]
#   scripts/audit.sh --json            # machine-readable report
#   scripts/audit.sh --protocol paxos  # one protocol only
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu audit --structure "$@"
