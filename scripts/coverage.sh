#!/usr/bin/env bash
# Coverage plane wrapper: on-device sketch campaign (default) or exact
# probe + sketch calibration (--exact).  One JSON report on stdout; the
# sketch mode exits 2 on safety violations, the exact mode exits 2 on a
# soundness or sketch-calibration failure.
#
# Usage: scripts/coverage.sh [paxos_tpu coverage flags...]
#   scripts/coverage.sh --config config2 --n-inst 256 --ticks 128
#   scripts/coverage.sh --exact --seeds 24 --record COVERAGE.json
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu coverage "$@"
