"""Thin wrapper for the exact fuzz-coverage probe (VERDICT r3 #3).

The probe now lives in the CLI — ``python -m paxos_tpu coverage --exact``
(see ``paxos_tpu/harness/cli.py``); this script survives only so recorded
invocations (`python scripts/coverage_probe.py --seeds 24 --record ...`)
keep working.  It re-execs the CLI module from the repo root, so there is
no ``sys.path`` surgery and exactly one argument parser owns the flags.

    python scripts/coverage_probe.py                      # default bounds
    python scripts/coverage_probe.py --seeds 24 --record COVERAGE.json
"""

from __future__ import annotations

import os
import subprocess
import sys


def main() -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.call(
        [sys.executable, "-m", "paxos_tpu", "coverage", "--exact",
         *sys.argv[1:]],
        cwd=repo_root,
    )


if __name__ == "__main__":
    raise SystemExit(main())
