"""CLI for the fuzz-coverage probe (VERDICT r3 #3; `check/coverage.py`).

Measures what fraction of the exhaustively-enumerated bounded schedule
space the TPU-style fuzzer actually occupies, the EXACT transport-excluded
remainder (multiset-only states the fixed-slot transport cannot represent),
and the soundness dual (every in-bounds fuzz state must be model-reachable:
``out_of_space`` must print 0).

    python scripts/coverage_probe.py                      # default bounds
    python scripts/coverage_probe.py --seeds 24 --record COVERAGE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n-prop", type=int, default=2)
    ap.add_argument("--n-acc", type=int, default=3)
    ap.add_argument(
        "--max-round", type=int, nargs="+", default=[1, 0],
        help="retry bounds (one per proposer, or one for all)",
    )
    ap.add_argument("--n-inst", type=int, default=4096)
    ap.add_argument("--ticks", type=int, default=48)
    ap.add_argument("--seeds", type=int, default=12)
    ap.add_argument("--seed0", type=int, default=0)
    ap.add_argument("--max-states", type=int, default=50_000_000)
    ap.add_argument("--record", default=None)
    ap.add_argument(
        "--analyze-residue", action="store_true",
        help="append residue_analysis (what the UNREACHED states share) "
        "to the report — the design input for targeted adversaries",
    )
    ap.add_argument(
        "--profile", type=int, default=None,
        help="pin ONE portfolio profile index for every seed (default: "
        "rotate the full portfolio)",
    )
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")  # the probe is a CPU tool

    from paxos_tpu.check.coverage import PORTFOLIO, coverage_probe

    if args.profile is not None and not 0 <= args.profile < len(PORTFOLIO):
        ap.error(f"--profile must be in [0, {len(PORTFOLIO) - 1}]")
    mr = args.max_round[0] if len(args.max_round) == 1 else tuple(args.max_round)
    out = coverage_probe(
        n_prop=args.n_prop,
        n_acc=args.n_acc,
        max_round=mr,
        n_inst=args.n_inst,
        ticks=args.ticks,
        seeds=args.seeds,
        seed0=args.seed0,
        max_states=args.max_states,
        log=lambda s: print(f"# {s}", file=sys.stderr),
        probe_cfg_kw=(
            None if args.profile is None else PORTFOLIO[args.profile]
        ),
        analyze_residue=args.analyze_residue,
    )
    sample = out.pop("out_of_space_sample")
    print(json.dumps(out))
    if args.record:
        with open(args.record, "w") as f:
            json.dump(out, f, indent=1)
    if out["out_of_space"]:
        print(f"# SOUNDNESS FAILURE — sample state: {sample[0]}",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
