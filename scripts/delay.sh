#!/usr/bin/env bash
# Bounded-delay wrapper: the delay-chaos config through both synchrony
# regimes.  First the default cell (latencies capped under the window
# delta — SynchPaxos' one-round fast path should land), then the
# violate-delta cell via --fault overrides (latencies sampled ABOVE the
# window — the synchrony bet loses and the honest protocol must fall back
# with zero violations).  Extra flags pass through to BOTH runs, so e.g.
# `scripts/delay.sh --exposure` accounts the delay class's
# injected-vs-effective ratio in each regime, and
# `scripts/delay.sh --fault sp_unsafe_fast=true` arms the planted bug the
# proposer-disagree checker must flag in the violated regime.
#
# Usage: scripts/delay.sh [paxos_tpu run flags...]
#   scripts/delay.sh --n-inst 4096 --ticks 256
#   scripts/delay.sh --exposure
cd "$(dirname "$0")/.." || exit 1
set -o pipefail
echo "== delay-chaos (delta respected: delay_max 2 < delta 6) =="
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m paxos_tpu run \
  --config delay-chaos "$@" || exit $?
echo "== delay-chaos (delta violated: delay_max 8 > delta 4) =="
exec env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m paxos_tpu run \
  --config delay-chaos --fault p_delay=0.8 --fault delay_max=8 \
  --fault delta=4 "$@"
