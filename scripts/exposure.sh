#!/usr/bin/env bash
# Fault-exposure wrapper: injected-vs-effective fault accounting over one
# campaign — the exposure matrix (per class: injected, effective,
# lanes_exposed, lit/vacuous) plus the chunk-granular attribution table
# (which classes were live while coverage/violations moved).  One report
# on stdout (--json for machines); exits 2 on safety violations.
#
# Usage: scripts/exposure.sh [paxos_tpu exposure flags...]
#   scripts/exposure.sh --config gray-chaos --n-inst 4096 --ticks 256
#   scripts/exposure.sh --config corrupt --coverage --json
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu exposure "$@"
