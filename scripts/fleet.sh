#!/usr/bin/env bash
# Fault-tolerant fuzzing fleet wrapper: a durable file-backed campaign
# queue (paxos_tpu/fleet/) sharded over N worker subprocesses with
# lease-based crash recovery — a worker that dies (SIGKILL, OOM,
# preemption) stops renewing its lease and the coordinator re-dispatches
# its record; campaigns are deterministic in (config, seed, plan), so
# the merged report (coverage unions OR'd, corpus journals deduped,
# repros globally deduped) is byte-identical to an uninterrupted run's.
# --chaos proves exactly that on a seeded SIGKILL schedule.  One merged
# report on stdout; exits 2 on safety violations or a bench-gate
# regression, 1 if the budget did not complete before --timeout-s.
#
# Usage: scripts/fleet.sh --dir DIR [paxos_tpu fleet flags...]
#   scripts/fleet.sh --dir /tmp/fleet --config config2 --mode soak \
#     --workers 4 --records 8 --seeds-per-record 4
#   scripts/fleet.sh --dir /tmp/fleet --mode fuzz --records 4 --chaos \
#     --bench-baseline BENCH_SWEEP.json
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu fleet "$@"
