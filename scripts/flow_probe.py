"""Flow-auditor probe: one audit cell through the taint/lane theorems.

Two modes, mirroring the auditor's exit discipline (0 clean, 2 findings):

  python scripts/flow_probe.py                       # clean cell -> exit 0
  python scripts/flow_probe.py --plant observer-leak # planted bug -> exit 2

``--plant`` wraps the protocol step with a known violation and expects
the auditor to name the leaked leaf — the tier-1 FLOW_SMOKE uses both
modes as the end-to-end acceptance of the dataflow non-interference
pass (a detector that cannot find a planted leak guards nothing).

Plants: ``observer-leak`` (telemetry counter folded into proposer.bal),
``fault-offsite`` (plan.equivocate applied outside any fault_site),
``lane-roll`` (cross-lane jnp.roll of ballot state).
"""

from __future__ import annotations

import argparse
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from paxos_tpu.analysis import flow
from paxos_tpu.analysis import trace as trace_mod
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state


def _plant_observer_leak(step, cfg):
    def leaky(st, key, pl):
        out = step(st, key, pl, cfg.fault)
        leak = out.telemetry.counters[0].astype(jnp.int32)
        return out.replace(
            proposer=out.proposer.replace(bal=out.proposer.bal + leak[None])
        )

    return leaky


def _plant_fault_offsite(step, cfg):
    def offsite(st, key, pl):
        out = step(st, key, pl, cfg.fault)
        return out.replace(
            acceptor=out.acceptor.replace(
                promised=out.acceptor.promised + pl.equivocate.astype(jnp.int32)
            )
        )

    return offsite


def _plant_lane_roll(step, cfg):
    def rolled(st, key, pl):
        out = step(st, key, pl, cfg.fault)
        return out.replace(
            proposer=out.proposer.replace(
                bal=jnp.roll(out.proposer.bal, 1, axis=-1)
            )
        )

    return rolled


PLANTS = {
    "observer-leak": ("telemetry", _plant_observer_leak),
    "fault-offsite": ("default", _plant_fault_offsite),
    "lane-roll": ("default", _plant_lane_roll),
}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--protocol", default="paxos", choices=trace_mod.PROTOCOLS)
    ap.add_argument("--config", default="default",
                    choices=tuple(trace_mod.CONFIG_MATRIX))
    ap.add_argument("--plant", default=None, choices=tuple(PLANTS))
    args = ap.parse_args()

    protocol = args.protocol
    if args.plant is None:
        cfg = trace_mod.build_config(protocol, args.config)
        xla = trace_mod.trace_xla_step(protocol, cfg)
        ctr = trace_mod.trace_counter_tick(protocol, cfg)
        findings = flow.audit_flow(protocol, args.config, cfg, xla, ctr)
        where = f"{protocol}/{args.config}"
    else:
        config, wrap = PLANTS[args.plant]
        cfg = trace_mod.build_config(protocol, config)
        fn = wrap(get_step_fn(protocol), cfg)
        closed = jax.make_jaxpr(fn)(
            init_state(cfg), base_key(cfg), init_plan(cfg)
        )
        where = f"{protocol}/{config} plant={args.plant}"
        findings = flow.analyze_step_jaxpr(
            closed, flow.build_spec(protocol, cfg), where
        )

    if findings:
        print(f"flow-probe: {where}: {len(findings)} finding(s)")
        for f in findings:
            print(f"  {f}")
        return 2
    print(f"flow-probe: {where}: OK (no findings)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
