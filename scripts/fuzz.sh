#!/usr/bin/env bash
# Feedback-directed fuzzing wrapper: corpus-driven campaigns scheduled
# AFL-style over the soak worker loop — coverage new_bits weighted by
# effective fault exposure and boosted by near-miss margins decide which
# entries earn mutation energy (paxos_tpu/fuzz/).  One report on stdout;
# --corpus-out records the wall-clock-free corpus journal (two runs of
# the same command are byte-identical — the replay-determinism pin).
# Exits 2 on safety violations, with the violating campaign's plan
# shrunk to a minimal margin- and exposure-annotated repro in the report.
#
# Usage: scripts/fuzz.sh [paxos_tpu fuzz flags...]
#   scripts/fuzz.sh --config config2 --campaigns 64 --corpus-out corpus.jsonl
#   scripts/fuzz.sh --config gray-chaos --n-inst 4096 --ticks-per-seed 256
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu fuzz "$@"
