"""Micro-probe: elementwise-chain throughput vs array layout (minor dim size).

Hypothesis: (I, 2, P, A) state arrays (minor dim A=5) waste 123/128 lanes;
instance-minor (2, P, A, I) layouts should run ~an order of magnitude faster.
Dev tool only.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

jax.config.update("jax_default_prng_impl", "rbg")


def chain(x, mask):
    # A representative mix: compares, wheres, a small-axis reduce.
    for _ in range(8):
        y = jnp.where(mask, x + 1, x)
        m = y.max(axis=REDUCE_AXES, keepdims=True)
        x = jnp.where(y == m, x, y)
    return x


def bench(shape, reduce_axes, reps=10):
    global REDUCE_AXES
    REDUCE_AXES = reduce_axes
    key = jax.random.PRNGKey(0)
    x = jax.random.randint(key, shape, 0, 1000, jnp.int32)
    mask = jax.random.bits(key, shape, jnp.uint32) < jnp.uint32(1 << 31)
    f = jax.jit(chain)
    r = f(x, mask)
    r.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(reps):
        r = f(x, mask)
    int(r.ravel()[0])
    dt = (time.perf_counter() - t0) / reps
    n = 1
    for s in shape:
        n *= s
    print(f"shape={str(shape):24s} reduce={str(reduce_axes):8s} "
          f"{dt * 1e3:7.2f} ms  ({n / dt / 1e9:6.1f} Gelem/s)")


def main():
    i = 1 << 20
    bench((i, 2, 2, 5), (1, 2))     # current layout, fiber reduce
    bench((2, 2, 5, i), (0, 1))     # instance-minor
    bench((i, 8), (1,))             # learner table, current
    bench((8, i), (0,))             # learner table, instance-minor
    bench((i, 2, 5), (1,))          # acceptor-ish
    bench((2, 5, i), (0,))


if __name__ == "__main__":
    main()
