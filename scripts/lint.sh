#!/usr/bin/env bash
# Lint lane: ruff + mypy when installed (config in pyproject.toml), with
# always-available fallbacks for the hermetic CI image, which ships
# NEITHER tool and forbids installs:
#   - python -m compileall  (syntax over the whole package)
#   - the analysis AST pass (host-entropy/wall-clock ban in traced modules,
#     including obs/ — span reconstruction is held to the same purity bar;
#     its wall clock is injected by the harness, never imported)
# Missing tools are reported as SKIPPED, not failures — the fallbacks are
# the floor, the real linters are the ceiling.
#
# Usage: scripts/lint.sh
cd "$(dirname "$0")/.." || exit 1
rc=0

if command -v ruff >/dev/null 2>&1; then
  ruff check paxos_tpu/ && echo RUFF=ok || { echo RUFF=FAILED; rc=1; }
else
  echo "RUFF=SKIPPED (not installed; config ready in pyproject.toml)"
fi

if command -v mypy >/dev/null 2>&1; then
  mypy paxos_tpu/ && echo MYPY=ok || { echo MYPY=FAILED; rc=1; }
else
  echo "MYPY=SKIPPED (not installed; config ready in pyproject.toml)"
fi

python -m compileall -q paxos_tpu/ tests/ scripts/ \
  && echo COMPILEALL=ok || { echo COMPILEALL=FAILED; rc=1; }

env JAX_PLATFORMS=cpu python - <<'EOF' && echo AST_LINT=ok || { echo AST_LINT=FAILED; rc=1; }
from paxos_tpu.analysis.purity import audit_traced_sources
findings = audit_traced_sources()
for f in findings:
    print(f)
raise SystemExit(2 if findings else 0)
EOF

exit $rc
