#!/usr/bin/env bash
# Safety-margin wrapper: distance-to-violation accounting over one
# campaign — the tightest quorum slack / ballot gap / promise slack the
# schedule reached, the per-chunk min-slack curve, the tightest-lane
# ranking, and the correlation of margin tightening against coverage
# growth and effective-fault deltas.  One report on stdout (--json for
# machines); exits 2 on safety violations (slack 0 that FIRED).
#
# Usage: scripts/margin.sh [paxos_tpu margin flags...]
#   scripts/margin.sh --config corrupt --n-inst 4096 --ticks 256
#   scripts/margin.sh --config gray-chaos --coverage --exposure --json
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu margin "$@"
