#!/usr/bin/env bash
# Fleet observatory wrapper: run a sampled chaos fleet, then read back
# every observatory artifact — the merged metrics time-series, the
# Perfetto fleet timeline, the corpus lineage table — and finish on the
# trend gate (`stats --series-gate`), mirroring perf.sh's
# record-then-gate pattern.  Exit codes follow the fleet family: 0 =
# clean, 1 = operational failure (budget incomplete, unreadable
# artifacts), 2 = safety violations or a trend-gate finding
# (discovery stall / rounds-per-sec degradation / heartbeat gap).
#
# Usage: scripts/observatory.sh [DIR] [fleet flags...]
#   scripts/observatory.sh                    # CPU chaos fuzz fleet in /tmp
#   scripts/observatory.sh /tmp/obs --records 4 --workers 3
#
# Artifacts land under DIR: q/ (the queue root, q/merged_series.jsonl
# inside), trace.json (load in https://ui.perfetto.dev), corpus.jsonl
# (feed to `paxos_tpu lineage`).
cd "$(dirname "$0")/.." || exit 1
dir="${1:-/tmp/paxos_observatory}"
case "$dir" in
  --*) dir="/tmp/paxos_observatory" ;;  # first arg is a fleet flag
  *) shift ;;
esac
rm -rf "$dir"
mkdir -p "$dir"

python -m paxos_tpu fleet \
  --config config2 --n-inst 64 --mode fuzz --records 2 \
  --campaigns-per-record 4 --ticks-per-seed 32 --chunk 16 \
  --coverage-words 64 --workers 2 --dir "$dir/q" --lease-s 6 \
  --poll-s 0.2 --timeout-s 420 --chaos --chaos-kills 1 --chaos-seed 7 \
  --hold-s 1.0 --sample-every 1 --timeline "$dir/trace.json" \
  --corpus-out "$dir/corpus.jsonl" "$@" >"$dir/report.json"
fleet_rc=$?
[ "$fleet_rc" -eq 1 ] && exit 1

echo "# merged time-series ($dir/q/merged_series.jsonl)"
python -m paxos_tpu stats --fleet-root "$dir/q" || exit 1
echo "# corpus lineage ($dir/corpus.jsonl)"
python -m paxos_tpu lineage "$dir/corpus.jsonl" --tree || exit 1
echo "# trend gate"
python -m paxos_tpu stats --fleet-root "$dir/q" --series-gate >/dev/null
gate_rc=$?
[ "$gate_rc" -ne 0 ] && exit "$gate_rc"
exit "$fleet_rc"
