"""Probe fixed per-dispatch overhead vs marginal compute on this backend."""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp


def main():
    x = jnp.zeros((8, 128), jnp.float32)
    f = jax.jit(lambda x: x + 1)
    r = f(x)
    r.block_until_ready()
    for reps in (1, 10, 100):
        t0 = time.perf_counter()
        for _ in range(reps):
            r = f(r)
        float(r[0, 0])
        dt = time.perf_counter() - t0
        print(f"tiny-op reps={reps:4d}: {dt * 1e3:8.2f} ms total, "
              f"{dt / reps * 1e3:7.2f} ms/call")

    # Marginal cost of a big elementwise chain, amortized inside one call.
    big = jax.random.bits(jax.random.PRNGKey(0), (2, 2, 5, 1 << 20), jnp.uint32)

    def chain_n(x, n):
        def body(i, x):
            y = x ^ (x >> 7)
            return y + jnp.uint32(i)

        return jax.lax.fori_loop(0, n, body, x)

    for n in (16, 256):
        g = jax.jit(lambda x, n=n: chain_n(x, n))
        r = g(big)
        r.block_until_ready()
        t0 = time.perf_counter()
        r = g(big)
        int(r.ravel()[0])
        dt = time.perf_counter() - t0
        per_pass = dt / n
        gbps = big.size * 4 * 2 / per_pass / 1e9
        print(f"fori chain n={n:4d}: {dt * 1e3:8.2f} ms, {per_pass * 1e6:7.1f} us/pass, "
              f"~{gbps:6.0f} GB/s effective")


if __name__ == "__main__":
    main()
