#!/usr/bin/env bash
# Perf plane wrapper: record a fresh provenance-carrying bench artifact
# (bench.py rows — per-run samples, warm-up/timed split, layout version,
# config fingerprint) and gate it through `paxos_tpu bench-compare`'s
# noise-aware tolerance model.  Exit codes follow bench-compare: 0 = no
# regression, 1 = nothing comparable / bad artifact, 2 = regression
# beyond max(tolerance, noise_k * baseline CV).
#
# Usage: scripts/perf.sh [BASELINE.json] [bench.py flags...]
#   scripts/perf.sh                       # fresh flagship row, self-compare
#                                         # (measurement+gate end-to-end)
#   scripts/perf.sh BENCH_SWEEP.json --sweep
#                                         # fresh sweep vs committed baseline
#   scripts/perf.sh --n-inst 1024 --pipeline-depth 2
#                                         # small smoke-sized self-compare
#
# NOTE: the committed BENCH_SWEEP.json holds TPU rows; a CPU measurement
# has zero (case, engine, platform) overlap with it and bench-compare
# exits 1 BY DESIGN — a vacuous pass must never gate CI.  On a CPU rig,
# run without a baseline (self-compare) or against a CPU-recorded one.
cd "$(dirname "$0")/.." || exit 1
baseline=""
case "${1:-}" in
  *.json) baseline="$1"; shift ;;
esac
fresh="${PERF_FRESH:-/tmp/paxos_tpu_bench_fresh.json}"
python bench.py --record "$fresh" "$@" || exit 1
if [ -n "$baseline" ]; then
  exec python -m paxos_tpu bench-compare --baseline "$baseline" --fresh "$fresh"
fi
exec python -m paxos_tpu bench-compare --baseline "$fresh"
