"""Roofline / utilization model for both engines (VERDICT r3 #1).

Answers "how close to the chip's ceiling is each sweep case?" with three
measurements and one static analysis:

1. **Op census** (static): count the VPU work one protocol tick compiles to —
   walk the jaxpr of ``apply_tick`` + ``counter_masks`` at the fused block's
   shapes and tally elementwise-ALU output elements, reduction input
   elements, and layout-op elements per instance-tick.  This is the work XLA
   *must* schedule on the 8x128 VPU (int32 lanes); fusion can eliminate
   layout ops but not ALU math.
2. **VPU ceiling** (measured): a Pallas kernel with the fused engine's exact
   structure (state resident in VMEM, a serial tick loop, elementwise int32
   ops over (8, block) tiles) but pure ALU chains — the attainable
   int32-op/s ceiling for THIS kernel shape, measured on the chip rather
   than taken from a spec sheet.
3. **HBM ceiling** (measured): a big jnp copy — the streaming bound the XLA
   engine (whole state through HBM every tick) runs against.

Utilization = measured throughput x ops-per-lane-tick / VPU ceiling (fused)
or x bytes-per-lane-tick / HBM ceiling (XLA).  Recorded in BASELINE.md's
utilization table; the fused multipaxos "gap" question (169.6M vs 377.9M
r/s) is answered by comparing WORK per tick, not just throughput.

Usage (TPU for the measured legs; census-only works anywhere):

    python scripts/roofline.py                  # census + ceilings + table
    python scripts/roofline.py --census-only    # no TPU needed
    python scripts/roofline.py --record ROOFLINE.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

# Primitive classes for the census.  ALU = one VPU op per output element;
# REDUCE = roughly one op per INPUT element (tree-reduced on the VPU);
# LAYOUT = copies/moves the compiler can often fold away (tracked separately
# so the ALU count is a lower bound on scheduled work, not an upper).
ALU = {
    "add", "sub", "mul", "max", "min", "and", "or", "xor", "not", "neg",
    "shift_left", "shift_right_logical", "shift_right_arithmetic",
    "select_n", "eq", "ne", "lt", "le", "gt", "ge", "convert_element_type",
    "rem", "div", "clamp", "population_count", "sign", "abs",
}
REDUCE = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "argmax", "argmin", "reduce_prod",
}
LAYOUT = {
    "broadcast_in_dim", "reshape", "transpose", "slice", "concatenate",
    "iota", "squeeze", "dynamic_slice", "dynamic_update_slice", "pad",
    "rev", "copy",
}


def _elems(v) -> int:
    return int(np.prod(v.aval.shape)) if v.aval.shape else 1


def census_jaxpr(jaxpr, counts):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        nested = [
            p for p in eqn.params.values()
            if hasattr(p, "eqns") or hasattr(p, "jaxpr")
        ]
        if nested:
            for p in nested:
                census_jaxpr(getattr(p, "jaxpr", p), counts)
            continue
        if name in ALU:
            counts["alu"] += sum(_elems(v) for v in eqn.outvars)
        elif name in REDUCE:
            counts["reduce"] += sum(_elems(v) for v in eqn.invars)
        elif name in LAYOUT:
            counts["layout"] += sum(_elems(v) for v in eqn.outvars)
        else:
            counts.setdefault("other", {}).setdefault(name, 0)
            counts["other"][name] += sum(_elems(v) for v in eqn.outvars)
    return counts


def tick_census(cfg, block: int) -> dict:
    """Per-instance-tick op counts for a config's fused tick at ``block``.

    Censuses the PACKED tick — the program the kernel actually runs:
    unpack-on-use (shifts+masks, counted as ALU), the protocol body, pack
    at the end.  ``state_bytes_per_lane`` is the packed VMEM-resident
    footprint; ``unpacked_bytes_per_lane`` keeps the one-int32-per-field
    size alongside — it is what the XLA engine (which runs on the unpacked
    pytree) still streams through HBM, and the packed/unpacked ratio is
    the layout win itself.
    """
    import dataclasses

    from paxos_tpu.harness.run import init_plan, init_state
    from paxos_tpu.kernels.fused_tick import packed_fns
    from paxos_tpu.utils import bitops

    apply_fn, mask_fn, _ = packed_fns(cfg.protocol)
    small = dataclasses.replace(cfg, n_inst=block)
    state, plan = init_state(small), init_plan(small)
    codec = bitops.codec_for(cfg.protocol, state)
    pst = bitops.pack_state(codec, state)

    def tick(st):
        masks = mask_fn(cfg.fault, jnp.int32(1), st)
        return apply_fn(st, masks, plan, cfg.fault)

    closed = jax.make_jaxpr(tick)(pst)
    counts = census_jaxpr(closed.jaxpr, {"alu": 0, "reduce": 0, "layout": 0})

    # Codec attribution: trace the differential pack/unpack legs the tick
    # actually runs (packed_fns: unpack_read -> body -> pack_delta) in
    # isolation and pull their shift/mask ALU out of the body's column.
    # Their (tiny) layout residue — the Zero-leaf re-materialization — stays
    # lumped in layout_per_lane_tick, so alu + codec_alu + reduce + layout
    # still partitions the same total the v1 census counted.
    codec_alu = 0
    for traced in (
        jax.make_jaxpr(codec.unpack_read)(pst),
        jax.make_jaxpr(codec.pack_delta)(pst, state),
    ):
        codec_alu += census_jaxpr(
            traced.jaxpr, {"alu": 0, "reduce": 0, "layout": 0}
        )["alu"]
    unpacked_bytes = sum(
        np.prod(l.shape) * l.dtype.itemsize for l in jax.tree.leaves(state)
        if getattr(l, "ndim", 0)
    )
    return {
        "alu_per_lane_tick": (counts["alu"] - codec_alu) / block,
        "codec_alu_per_lane_tick": codec_alu / block,
        "reduce_per_lane_tick": counts["reduce"] / block,
        "layout_per_lane_tick": counts["layout"] / block,
        "other": {k: v / block for k, v in counts.get("other", {}).items()},
        "state_bytes_per_lane": float(codec.bytes_per_lane(state)),
        "unpacked_bytes_per_lane": float(unpacked_bytes) / block,
    }


# ---- Measured ceilings ------------------------------------------------------

_PROBE_OPS_PER_ITER = 8  # keep in sync with the kernel body below

# The axon tunnel adds ~110 ms of FIXED latency to every dispatch+readback
# (measured; independent of payload size), which would swamp any one-shot
# probe.  Both ceilings therefore time the SAME program at two iteration
# counts and divide the work delta by the time delta — the overhead cancels
# exactly, the same discipline the bench uses (amortize, then best-of-N).


def _delta_time(make_call, work_of, k1: int, k2: int, reps: int) -> float:
    """work/sec from the (k2 - k1) iteration delta; overhead-free."""
    c1, c2 = make_call(k1), make_call(k2)
    c1()
    c2()  # compile + warm both
    best = float("inf")
    for _r in range(reps):
        t0 = time.perf_counter()
        c1()
        t1 = time.perf_counter()
        c2()
        t2 = time.perf_counter()
        best = min(best, (t2 - t1) - (t1 - t0))
    return (work_of(k2) - work_of(k1)) / best


def vpu_ceiling(block: int = 1024, rows: int = 256, grid: int = 16,
                reps: int = 5) -> float:
    """Attainable int32 VPU ops/sec for a fused-engine-shaped kernel.

    Mirrors the fused tick's structure — VMEM-resident carry, a serial
    fori_loop over "ticks", elementwise int32 ops — with ample ILP per op
    ((rows, block) = 2048 vregs of independent lanes; a narrow dependent
    chain measures op LATENCY, ~12x below throughput).  The body is 8
    dependent ALU ops per element per iteration (adds, xors, shifts, a
    mul+max) matching the protocol mix.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x = jnp.ones((rows, block * grid), jnp.int32)

    def make_call(iters):
        def kern(x_ref, o_ref):
            def body(i, x):
                x = x + jnp.int32(-1640531527)        # 1 (0x9E3779B9 as i32)
                x = x ^ (x << 13)                     # 2 (xor + shift)
                x = x ^ (x >> 7)                      # 2
                x = jnp.maximum(x, x * jnp.int32(5))  # 2 (mul + max)
                return x + i                          # 1  -> 8 ops total

            o_ref[...] = jax.lax.fori_loop(0, iters, body, x_ref[...])

        call = pl.pallas_call(
            kern,
            grid=(grid,),
            in_specs=[pl.BlockSpec((rows, block), lambda i: (0, i),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((rows, block), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        )
        # Readback through a full reduction: on the axon tunnel an
        # element-[0] fetch (like block_until_ready) can return BEFORE the
        # whole buffer is computed, which once inflated the HBM probe 30x;
        # the sum depends on every element, so int() really is the sync.
        return lambda: int(jnp.sum(call(x), dtype=jnp.int32))

    def work_of(iters):
        return rows * block * grid * iters * _PROBE_OPS_PER_ITER

    return _delta_time(make_call, work_of, 1024, 9216, reps)


def hbm_ceiling(mb: int = 512, reps: int = 5) -> float:
    """Attainable HBM streaming bytes/sec (read+write) via chained big adds.

    Each iteration reads and writes the whole ``mb``-MiB array (far beyond
    VMEM, so every round trips HBM); iteration-count delta-timing cancels
    the tunnel's fixed dispatch+readback latency.
    """
    n = mb * (1 << 20) // 4
    x = jnp.ones((n,), jnp.int32)

    def make_call(iters):
        @jax.jit
        def f(a):
            def body(i, y):
                return y + 1

            return jax.lax.fori_loop(0, iters, body, a)

        # Full-reduction readback: see vpu_ceiling — a [0] fetch can return
        # before the streaming computation finishes on the tunnel backend.
        return lambda: int(jnp.sum(f(x), dtype=jnp.int32))

    def work_of(iters):
        return 2 * n * 4 * iters  # read + write per iteration

    return _delta_time(make_call, work_of, 8, 72, reps)


# ---- Table ------------------------------------------------------------------


def build_table(census_only: bool, sweep_path: str) -> dict:
    from bench import _configs
    from paxos_tpu.kernels.fused_tick import packed_fns

    on_tpu = (not census_only) and jax.devices()[0].platform == "tpu"
    out: dict = {"platform": jax.devices()[0].platform if on_tpu else "census"}

    if on_tpu:
        out["vpu_ops_per_sec"] = vpu_ceiling()
        out["hbm_bytes_per_sec"] = hbm_ceiling()

    recorded = {}
    if os.path.exists(sweep_path):
        for c in json.loads(open(sweep_path).read()):
            if c["platform"] == "tpu":
                recorded[(c["case"], c["engine"])] = c["value"]

    uniq: dict = {}
    for name, cfg, _eng, _chunk, _depth in _configs("tpu"):
        uniq.setdefault(name, cfg)
    rows = []
    for name, cfg in uniq.items():
        _, _, dblk = packed_fns(cfg.protocol)
        cen = tick_census(cfg, dblk)
        row = {"case": name, "block": dblk, **cen}
        for engine in ("fused", "xla"):
            val = recorded.get((name, engine))
            if val is None:
                continue
            row[f"{engine}_rps"] = val
            if engine == "fused" and "vpu_ops_per_sec" in out:
                # codec shifts/masks are scheduled VPU work like any other
                # ALU; the split is attribution, not exclusion.
                ops = val * (cen["alu_per_lane_tick"]
                             + cen["codec_alu_per_lane_tick"]
                             + cen["reduce_per_lane_tick"])
                row["fused_alu_ops_per_sec"] = ops
                row["fused_vpu_utilization"] = ops / out["vpu_ops_per_sec"]
            if engine == "xla" and "hbm_bytes_per_sec" in out:
                # The XLA engine streams the full state through HBM twice a
                # tick (scan carry in + out); masks/temporaries add more, so
                # this is a LOWER bound on its achieved bandwidth.  It runs
                # on the UNPACKED pytree (packing is fused-engine-only), so
                # the unpacked footprint is the right byte count here.
                by = val * 2 * cen["unpacked_bytes_per_lane"]
                row["xla_hbm_bytes_per_sec"] = by
                row["xla_hbm_utilization"] = by / out["hbm_bytes_per_sec"]
        rows.append(row)
    out["cases"] = rows
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--census-only", action="store_true",
                    help="skip the TPU-measured ceilings")
    ap.add_argument("--sweep", default=os.path.join(
        os.path.dirname(__file__), "..", "BENCH_SWEEP.json"))
    ap.add_argument("--record", default=None)
    ap.add_argument("--re-census", default=None, metavar="ROOFLINE_JSON",
                    help="census-only re-record: recompute the static census "
                         "columns of an existing record in place, preserving "
                         "every TPU-measured field (platform, ceilings, rps, "
                         "utilization) byte-for-byte — the update mode for "
                         "CPU-side op-count changes between TPU sessions")
    args = ap.parse_args()

    if args.re_census:
        from bench import _configs

        with open(args.re_census) as f:
            prev = json.load(f)
        uniq: dict = {}
        for name, cfg, _eng, _chunk, _depth in _configs("tpu"):
            uniq.setdefault(name, cfg)
        census_keys = (
            "alu_per_lane_tick", "codec_alu_per_lane_tick",
            "reduce_per_lane_tick", "layout_per_lane_tick", "other",
            "state_bytes_per_lane", "unpacked_bytes_per_lane",
        )
        for row in prev["cases"]:
            cen = tick_census(uniq[row["case"]], row["block"])
            for k in census_keys:
                row[k] = cen[k]
            print(f"{row['case']:30s} alu {cen['alu_per_lane_tick']:8.1f} "
                  f"codec {cen['codec_alu_per_lane_tick']:7.1f} "
                  f"layout {cen['layout_per_lane_tick']:7.1f}")
        with open(args.re_census, "w") as f:
            json.dump(prev, f, indent=1)
        return 0

    out = build_table(args.census_only, args.sweep)
    if "vpu_ops_per_sec" in out:
        print(f"# VPU ceiling: {out['vpu_ops_per_sec']:.3e} int32 ops/s   "
              f"HBM ceiling: {out['hbm_bytes_per_sec'] / 1e9:.0f} GB/s")
    for r in out["cases"]:
        line = (f"{r['case']:22s} alu/lane-tick {r['alu_per_lane_tick']:8.1f} "
                f"state {r['state_bytes_per_lane']:7.1f} B "
                f"(unpacked {r['unpacked_bytes_per_lane']:.0f})")
        if "fused_vpu_utilization" in r:
            line += (f"  fused {r['fused_rps'] / 1e6:6.1f}M r/s = "
                     f"{r['fused_vpu_utilization'] * 100:5.1f}% VPU")
        if "xla_hbm_utilization" in r:
            line += (f"  xla {r['xla_rps'] / 1e6:5.1f}M = "
                     f"{r['xla_hbm_utilization'] * 100:5.1f}% HBM")
        print(line)
    print(json.dumps(out))
    if args.record:
        with open(args.record, "w") as f:
            json.dump(out, f, indent=1)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
