#!/usr/bin/env bash
# Roofline re-record: scripts/roofline.py against the current BENCH_SWEEP
# recording, written to ROOFLINE.json.  On a TPU rig this measures the VPU
# and HBM ceilings fresh; anywhere else pass --census-only to refresh only
# the static op-census fields (per-lane-tick ALU/layout counts and the
# packed/unpacked state bytes) while a later TPU run re-measures ceilings.
#
# Usage: scripts/roofline.sh [--census-only] [extra roofline.py flags...]
cd "$(dirname "$0")/.." || exit 1
exec env python scripts/roofline.py --record ROOFLINE.json "$@"
