#!/usr/bin/env bash
# Client-workload SLO wrapper: one campaign per offered-load scale, the
# per-class queue-delay-inclusive latency table, the goodput-vs-offered
# curve, and the overload knee.  One report on stdout (--json for
# machines); exits 2 when a served class's p99 breaches --slo-p99.
#
# Usage: scripts/slo.sh [paxos_tpu slo flags...]
#   scripts/slo.sh --config config3 --mix poisson --slo-p99 64
#   scripts/slo.sh --config config2 --sweep 0.5 1.0 2.0 --json
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m paxos_tpu slo "$@"
