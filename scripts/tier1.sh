#!/usr/bin/env bash
# Tier-1 verify — the ROADMAP.md command, verbatim, so "run tier-1" is one
# invocation instead of a copy-paste from prose.  Prints DOTS_PASSED=<n>
# (progress-dot count from the pytest tail) and exits with pytest's rc.
#
# Usage: scripts/tier1.sh   (from anywhere; cd's to the repo root)
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
# `stats` smoke: a tiny telemetry-on run must produce a JSONL stream the
# stats subcommand can summarize (and render as Prometheus text).
if [ "$rc" -eq 0 ]; then
  m=/tmp/_t1_metrics.jsonl; rm -f "$m"
  timeout -k 10 120 env JAX_PLATFORMS=cpu python -m paxos_tpu run \
    --config config1 --n-inst 64 --ticks 16 --chunk 8 \
    --telemetry --record 8 --hist-bins 4 --log "$m" >/dev/null 2>&1 \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python -m paxos_tpu stats "$m" \
       | grep '"telemetry"' >/dev/null \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python -m paxos_tpu stats "$m" --prometheus \
       | grep '^paxos_tpu_events_total' >/dev/null \
  && echo STATS_SMOKE=ok || { echo STATS_SMOKE=FAILED; rc=1; }
fi
# Dispatch-pipeline smoke: a pipelined run (grouped dispatches + async
# done-flag probe) and a pipelined soak (overlap-by-one campaigns) must
# both complete clean — the depth knob is load-bearing in CI, not just in
# the unit suite.
if [ "$rc" -eq 0 ]; then
  timeout -k 10 180 env JAX_PLATFORMS=cpu python -m paxos_tpu run \
    --config config1 --n-inst 256 --ticks 64 --chunk 16 \
    --pipeline-depth 2 --until-all-chosen >/dev/null 2>&1 \
  && timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu soak \
       --config config1 --engine xla --n-inst 4096 --target-rounds 1e6 \
       --ticks-per-seed 64 --chunk 32 --pipeline-depth 2 >/dev/null 2>&1 \
  && echo PIPELINE_SMOKE=ok || { echo PIPELINE_SMOKE=FAILED; rc=1; }
fi
# Trace-export smoke: a short corrupt campaign through the `trace`
# subcommand must yield a schema-valid Perfetto trace (per-lane round
# spans + fault instants on the device track, dispatch spans on the host
# track) — the causal-tracing acceptance path, kept cheap.
if [ "$rc" -eq 0 ]; then
  t=/tmp/_t1_trace.json; rm -f "$t"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu trace \
    --config corrupt --n-inst 128 --ticks 64 --chunk 16 --lanes 4 \
    --out "$t" >/dev/null 2>&1 \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python - "$t" <<'EOF' \
  && echo TRACE_SMOKE=ok || { echo TRACE_SMOKE=FAILED; rc=1; }
import json, sys
from paxos_tpu.obs.export import validate_chrome_trace
obj = json.load(open(sys.argv[1]))
errs = validate_chrome_trace(obj)
pids = {e["pid"] for e in obj["traceEvents"]}
assert not errs, errs
assert pids == {0, 1}, f"expected device+host tracks, got pids {pids}"
assert any(e["ph"] == "b" for e in obj["traceEvents"]), "no round spans"
assert any(e["ph"] == "i" and e.get("cat") == "fault"
           for e in obj["traceEvents"]), "no fault instants"
EOF
fi
# Static-audit smoke: one protocol x two configs through the full jaxpr
# auditor (PRNG registry + purity + structure goldens) — trace-time only,
# so seconds, but it catches stream/structure drift the runtime suite
# can't see until a schedule silently forks.
if [ "$rc" -eq 0 ]; then
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu audit \
    --protocol paxos --config default --config gray-chaos --structure \
    >/dev/null 2>&1 \
  && echo AUDIT_SMOKE=ok || { echo AUDIT_SMOKE=FAILED; rc=1; }
fi
# Coverage smoke: a tiny sketch campaign through the `coverage`
# subcommand must draw a sane coverage curve — cumulative bits_set
# monotone nondecreasing, nonzero by the end, and consistent with the
# final report's union popcount (the zero-round-trip coverage plane's
# end-to-end acceptance, kept cheap).
if [ "$rc" -eq 0 ]; then
  c=/tmp/_t1_coverage.json; rm -f "$c"
  timeout -k 10 180 env JAX_PLATFORMS=cpu python -m paxos_tpu coverage \
    --config config1 --n-inst 64 --ticks 32 --chunk 8 --words 8 \
    >"$c" 2>/dev/null \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python - "$c" <<'EOF' \
  && echo COVERAGE_SMOKE=ok || { echo COVERAGE_SMOKE=FAILED; rc=1; }
import json, sys
out = json.load(open(sys.argv[1]))
curve = [c["bits_set"] for c in out["curve"]]
assert curve, "empty coverage curve"
assert curve == sorted(curve), f"curve not monotone: {curve}"
assert curve[-1] > 0, "coverage curve never left zero"
assert curve[-1] == out["coverage"]["bits_set"], "curve/report mismatch"
assert out["coverage"]["bits_total"] == 8 * 32
EOF
fi
# Exposure smoke: a short gray-chaos campaign through the `exposure`
# subcommand must account its faults honestly — every LIT class (drop,
# dup, partition, timeout under gray-chaos) shows a nonzero effective
# count, every unlit class (corrupt, stale) shows exactly zero, and
# effective never exceeds injected (the injected-vs-effective plane's
# end-to-end acceptance, kept cheap).
if [ "$rc" -eq 0 ]; then
  e=/tmp/_t1_exposure.json; rm -f "$e"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu exposure \
    --config gray-chaos --n-inst 1024 --ticks 128 --chunk 32 --json \
    >"$e" 2>/dev/null
  erc=$?
  if [ "$erc" -eq 0 ] || [ "$erc" -eq 2 ]; then  # 2 = violations, still a report
    timeout -k 10 30 env JAX_PLATFORMS=cpu python - "$e" <<'EOF' \
    && echo EXPOSURE_SMOKE=ok || { echo EXPOSURE_SMOKE=FAILED; rc=1; }
import json, sys
out = json.load(open(sys.argv[1]))
classes = out["exposure"]["classes"]
lit, vacuous = out["exposure"]["lit"], out["exposure"]["vacuous"]
assert lit == ["drop", "dup", "partition", "timeout"], lit
assert vacuous == [], f"vacuous chaos in the smoke config: {vacuous}"
for name, row in classes.items():
    assert 0 <= row["effective"] <= row["injected"], (name, row)
    if name in lit:
        assert row["effective"] > 0, (name, row)
    else:
        assert row["injected"] == 0 == row["effective"], (name, row)
assert set(out["attribution"]) == set(classes)
EOF
  else
    echo EXPOSURE_SMOKE=FAILED; rc=1
  fi
fi
# Margin smoke: the distance-to-violation plane's end-to-end acceptance,
# kept cheap.  A corrupt campaign must drive min quorum slack to 0 at or
# before the chunk where the safety checker first fires (slack 0 is the
# violation boundary, not a lagging echo); a default (healthy) campaign
# must never report slack below 1 (healthy lanes are typically never
# contested at all, so None — sentinel never folded — also passes); and
# a margin-off run must prune the state leaf to None (default-off-is-free).
if [ "$rc" -eq 0 ]; then
  g=/tmp/_t1_margin.json; rm -f "$g"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu margin \
    --config corrupt --n-inst 512 --ticks 128 --chunk 32 --json \
    >"$g" 2>/dev/null
  grc=$?
  if [ "$grc" -eq 0 ] || [ "$grc" -eq 2 ]; then  # 2 = violations, still a report
    timeout -k 10 300 env JAX_PLATFORMS=cpu python - "$g" "$grc" <<'EOF' \
    && echo MARGIN_SMOKE=ok || { echo MARGIN_SMOKE=FAILED; rc=1; }
import json, sys
out = json.load(open(sys.argv[1]))
assert out["violations"] > 0, "corrupt smoke campaign never violated"
assert int(sys.argv[2]) == 2, "violations present but exit code was not 2"
assert out["margin"]["min_quorum_slack"] == 0, out["margin"]
first_viol = next(c for c in out["curve"] if c["violations_delta"] > 0)
hit = [c for c in out["curve"] if c["tick"] <= first_viol["tick"]
       and c["min_quorum_slack"] == 0]
assert hit, f"slack never hit 0 at-or-before first violation chunk: {out['curve']}"
ranked = out["lane_ranking"]
assert ranked and ranked[0]["min_quorum_slack"] == 0, ranked

import dataclasses
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.run import init_state, make_advance, init_plan, summarize
from paxos_tpu.obs.margin import MarginConfig
cfg = SimConfig(n_inst=256, seed=5)
mcfg = dataclasses.replace(cfg, margin=MarginConfig(counters=True))
state = init_state(mcfg)
state = make_advance(mcfg, init_plan(mcfg), "xla")(state, 64)
rep = summarize(state, log_total=mcfg.fault.log_total)
assert rep["violations"] == 0, rep
s = rep["margin"]["min_quorum_slack"]
assert s is None or s >= 1, f"healthy campaign reported slack {s}"
off = init_state(cfg)
assert off.margin is None, "margin-off state leaf not pruned to None"
EOF
  else
    echo MARGIN_SMOKE=FAILED; rc=1
  fi
fi
# Packed-state smoke: the fused engine now carries lane state bit-packed
# through VMEM (utils/bitops layout tables); this replays one config per
# protocol through the packed fused kernel (interpret) AND the unpacked
# reference_chunk oracle (same counter-PRNG stream, plain XLA) and
# digests both end states — any packing drift (a field re-binned, a
# width wrong, an overflow clipped) breaks bit-equality here on CPU CI.
if [ "$rc" -eq 0 ]; then
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF' >/dev/null 2>&1 \
  && echo PACKED_SMOKE=ok || { echo PACKED_SMOKE=FAILED; rc=1; }
import hashlib
import jax
import jax.numpy as jnp
import numpy as np
from paxos_tpu.harness.config import (
    config2_dueling_drop, config3_multipaxos, config5_sweep)
from paxos_tpu.harness.run import init_plan, init_state
from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS, fused_fns, reference_chunk

def digest(state):
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()

sweep = {c.protocol: c for c in config5_sweep(n_inst=256)}
cases = {
    "paxos": config2_dueling_drop(n_inst=256),
    "multipaxos": config3_multipaxos(n_inst=256),
    "fastpaxos": sweep["fastpaxos"],
    "raftcore": sweep["raftcore"],
}
for protocol, cfg in cases.items():
    plan = init_plan(cfg)
    seed = jnp.int32(cfg.seed)
    fused = FUSED_CHUNKS[protocol](
        init_state(cfg), seed, plan, cfg.fault, 16,
        block=256, interpret=True,
    )
    apply_fn, mask_fn, _ = fused_fns(protocol)
    ref = reference_chunk(
        init_state(cfg), seed, plan, cfg.fault, 16, apply_fn, mask_fn,
    )
    assert digest(fused) == digest(ref), f"{protocol}: packed fused != XLA reference"
EOF
fi
# Delta-codec smoke: the fused tick now unpacks ONCE per tick through the
# declared read-set and merges only the declared write-set back
# (bitops.unpack_read / pack_delta), with the ballot saturation clamp
# hoisted to chunk boundaries.  Replays every protocol through TWO fused
# chunks (interpret) vs the unpacked reference — two entry/exit clamp
# crossings ride the stream — and then pre-seeds a near-limit paxos
# campaign on BOTH engines: each must saturate/grow to the identical
# report threshold and trip the same MeasurementCorrupted guard.
if [ "$rc" -eq 0 ]; then
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF' >/dev/null 2>&1 \
  && echo DELTA_SMOKE=ok || { echo DELTA_SMOKE=FAILED; rc=1; }
import hashlib
import jax
import jax.numpy as jnp
import numpy as np
from paxos_tpu.harness.config import (
    FaultConfig, SimConfig,
    config2_dueling_drop, config3_multipaxos, config5_sweep)
from paxos_tpu.harness.run import MeasurementCorrupted, init_plan, init_state, summarize
from paxos_tpu.kernels.fused_tick import (
    FUSED_CHUNKS, fused_fns, reference_chunk, report_ballot_limit)

def digest(state):
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()

sweep = {c.protocol: c for c in config5_sweep(n_inst=256)}
cases = {
    "paxos": config2_dueling_drop(n_inst=256),
    "multipaxos": config3_multipaxos(n_inst=256),
    "fastpaxos": sweep["fastpaxos"],
    "raftcore": sweep["raftcore"],
}
for protocol, cfg in cases.items():
    plan = init_plan(cfg)
    seed = jnp.int32(cfg.seed)
    apply_fn, mask_fn, _ = fused_fns(protocol)
    fused, ref = init_state(cfg), init_state(cfg)
    for _chunk in range(2):
        fused = FUSED_CHUNKS[protocol](
            fused, seed, plan, cfg.fault, 8, block=256, interpret=True,
        )
        ref = reference_chunk(ref, seed, plan, cfg.fault, 8, apply_fn, mask_fn)
    assert digest(fused) == digest(ref), f"{protocol}: delta-codec fused != reference"

# Overflow-guard threshold identity: all-drop + fast timeouts force ballot
# growth; pre-seeded 64 below the report limit, 64 ticks cross it on both
# engines — fused saturates AT the limit, reference grows through it, and
# summarize condemns both.
limit = report_ballot_limit("paxos")
cfg = SimConfig(n_inst=32, n_prop=2, n_acc=3, seed=9,
                fault=FaultConfig(p_drop=1.0, timeout=2, backoff_max=2))
plan = init_plan(cfg)

def preseed():
    s = init_state(cfg)
    bump = jnp.int32(limit - 64)
    return s.replace(
        proposer=s.proposer.replace(bal=s.proposer.bal + bump),
        requests=s.requests.replace(bal=s.requests.bal + bump),
    )

fused = FUSED_CHUNKS["paxos"](
    preseed(), jnp.int32(9), plan, cfg.fault, 64, block=32, interpret=True)
ref = reference_chunk(preseed(), jnp.int32(9), plan, cfg.fault, 64)
assert int(fused.proposer.bal.max()) == limit, "fused did not saturate at limit"
assert int(ref.proposer.bal.max()) >= limit, "reference never crossed limit"
for name, st in (("fused", fused), ("reference", ref)):
    try:
        summarize(st)
    except MeasurementCorrupted:
        pass
    else:
        raise AssertionError(f"{name}: overflow guard did not fire")
EOF
fi
# Perf-plane smoke: a --perf run must carry throughput/occupancy gauges
# (occupancy in [0,1]) into both the report and the Prometheus export; a
# smoke-sized bench row must validate against the provenance schema
# (per-run samples, warm-up/timed split, layout version, fingerprint);
# and bench-compare against the freshly recorded artifact must exit 0.
# The committed BENCH_SWEEP.json is TPU-recorded, so the CPU gate
# self-compares — zero-overlap exits 1 and can never pass vacuously.
if [ "$rc" -eq 0 ]; then
  p=/tmp/_t1_perf.jsonl; b=/tmp/_t1_bench.json; pr=/tmp/_t1_perf_report.json
  rm -f "$p" "$b" "$pr"
  timeout -k 10 180 env JAX_PLATFORMS=cpu python -m paxos_tpu run \
    --config config1 --n-inst 128 --ticks 64 --chunk 32 \
    --pipeline-depth 2 --perf --log "$p" >"$pr" 2>/dev/null \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python -m paxos_tpu stats "$p" --prometheus \
       | grep '^paxos_tpu_perf_occupancy' >/dev/null \
  && timeout -k 10 300 env JAX_PLATFORMS=cpu python bench.py \
       --n-inst 512 --pipeline-depth 2 --record "$b" >/dev/null 2>&1 \
  && timeout -k 10 60 env JAX_PLATFORMS=cpu python -m paxos_tpu bench-compare \
       --baseline "$b" >/dev/null 2>&1 \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python - "$b" "$pr" <<'EOF' \
  && echo PERF_SMOKE=ok || { echo PERF_SMOKE=FAILED; rc=1; }
import json, sys
from paxos_tpu.obs.perf import validate_bench_row
rows = json.load(open(sys.argv[1]))
assert rows, "bench artifact empty"
for row in rows:
    errs = validate_bench_row(row)
    assert not errs, errs
    assert row["warmup_groups"] >= 1 and row["warmup_runs"], row
report = json.loads(open(sys.argv[2]).read().strip().splitlines()[-1])
p = report["perf"]
assert p["dispatches"] >= 1 and p["rounds_total"] > 0, p
assert 0.0 <= p["occupancy"] <= 1.0, p["occupancy"]
assert {"p50", "p95", "p99"} <= set(p["chunk_latency_us"]), p
EOF
fi
# Flow smoke: the dataflow non-interference auditor end-to-end.  A clean
# cell must exit 0; a planted observer leak (telemetry counter folded
# into ballot state) must exit 2 AND name the leaked leaf — a taint pass
# that cannot find a planted leak guards nothing.
if [ "$rc" -eq 0 ]; then
  timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/flow_probe.py \
    >/dev/null 2>&1 \
  && { timeout -k 10 180 env JAX_PLATFORMS=cpu python scripts/flow_probe.py \
         --plant observer-leak >/tmp/_t1_flow.log 2>&1; [ "$?" -eq 2 ]; } \
  && grep -q "telemetry.counters" /tmp/_t1_flow.log \
  && echo FLOW_SMOKE=ok || { echo FLOW_SMOKE=FAILED; rc=1; }
fi
# Feedback-directed fuzzing smoke (fuzz subcommand + paxos_tpu/fuzz/):
# (a) two identical guided runs must write byte-identical corpus journals
# (replay determinism — the journal is wall-clock-free by construction);
# (b) at an EQUAL campaign budget the guided scheduler's cross-seed
# coverage union must strictly exceed uniform rotating-seed sampling's;
# (c) a fuzz run over a violating config must exit 2 with the repro
# shrunk, replay-verified, and margin- + exposure-annotated.
if [ "$rc" -eq 0 ]; then
  fj1=/tmp/_t1_fz1.jsonl; fj2=/tmp/_t1_fz2.jsonl
  fr=/tmp/_t1_fuzz.json; ur=/tmp/_t1_uni.json; vr=/tmp/_t1_fzv.json
  rm -f "$fj1" "$fj2" "$fr" "$ur" "$vr"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu fuzz \
    --config config1 --n-inst 64 --campaigns 6 --ticks-per-seed 32 \
    --chunk 16 --coverage-words 64 --corpus-out "$fj1" >"$fr" 2>/dev/null \
  && timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu fuzz \
    --config config1 --n-inst 64 --campaigns 6 --ticks-per-seed 32 \
    --chunk 16 --coverage-words 64 --corpus-out "$fj2" >/dev/null 2>&1 \
  && cmp -s "$fj1" "$fj2" \
  && timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu soak \
    --config config1 --n-inst 64 --engine xla --target-rounds 12288 \
    --ticks-per-seed 32 --chunk 16 --pipeline-depth 1 --coverage \
    --coverage-words 64 >"$ur" 2>/dev/null \
  && { timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu fuzz \
         --config corrupt --n-inst 128 --campaigns 2 --ticks-per-seed 64 \
         --chunk 32 >"$vr" 2>/dev/null; [ "$?" -eq 2 ]; } \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python - "$fr" "$ur" "$vr" <<'EOF' \
  && echo FUZZ_SMOKE=ok || { echo FUZZ_SMOKE=FAILED; rc=1; }
import json, sys
fuzz = json.load(open(sys.argv[1]))
uni = json.load(open(sys.argv[2]))
vio = json.load(open(sys.argv[3]))
# Equal budget: 6 guided campaigns vs 6 uniform rotating seeds.
assert fuzz["fuzz"]["campaigns"] == 6 and uni["seeds"] == 6, (
    fuzz["fuzz"], uni["seeds"])
gb, ub = fuzz["coverage"]["bits_set"], uni["coverage"]["bits_set"]
assert gb > ub, f"guided union {gb} must strictly exceed uniform {ub}"
assert fuzz["violations"] == 0, fuzz["violations"]
rep = vio.get("repro")
assert vio["violations"] > 0 and rep, "violating fuzz run carried no repro"
assert rep["replays"] is True, rep
assert "plan_atoms" in rep and "margin" in rep and "exposure" in rep, rep
assert rep["margin"]["min_quorum_slack"] == 0, rep["margin"]
EOF
fi
# Bounded-delay smoke: the delay fault dimension + SynchPaxos end to end.
# A delay-chaos campaign must account nonzero EFFECTIVE delay exposure
# (stamps that actually held messages back, not just sampled latencies);
# the fused engine must replay the delay-on stream bit-identically to the
# XLA reference for both a classic protocol and SynchPaxos; SynchPaxos
# must land its one-round fast path when latencies respect the synchrony
# window delta, and fall back with ZERO violations when they exceed it.
if [ "$rc" -eq 0 ]; then
  timeout -k 10 600 env JAX_PLATFORMS=cpu python - <<'EOF' >/dev/null 2>&1 \
  && echo DELAY_FAULT_SMOKE=ok || { echo DELAY_FAULT_SMOKE=FAILED; rc=1; }
import dataclasses
import hashlib
import jax
import jax.numpy as jnp
import numpy as np
from paxos_tpu.harness.config import config_delay_chaos
from paxos_tpu.harness.run import init_plan, init_state, run
from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS, fused_fns, reference_chunk
from paxos_tpu.obs.exposure import ExposureConfig, annotate_lit
from paxos_tpu.protocols.synchpaxos import fast_path_rate

def digest(state):
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()

# (a) Lit delay class => nonzero effective exposure, honest soak clean.
cfg = dataclasses.replace(
    config_delay_chaos(n_inst=512, seed=3),
    exposure=ExposureConfig(counters=True),
)
report = run(cfg, total_ticks=128, chunk=32)
assert report["violations"] == 0, report["violations"]
exposure = annotate_lit(report["exposure"], cfg.fault)
row = exposure["classes"]["delay"]
assert 0 < row["effective"] <= row["injected"], row
assert "delay" in exposure["lit"], exposure["lit"]
assert "delay" not in exposure["vacuous"], exposure

# (b) Delay-on stream: packed fused kernel (interpret) == XLA reference.
for protocol, c in (
    ("paxos", dataclasses.replace(
        config_delay_chaos(n_inst=256, seed=5), protocol="paxos")),
    ("synchpaxos", config_delay_chaos(n_inst=256, seed=5)),
):
    plan = init_plan(c)
    seed = jnp.int32(c.seed)
    fused = FUSED_CHUNKS[protocol](
        init_state(c), seed, plan, c.fault, 16, block=256, interpret=True)
    apply_fn, mask_fn, _ = fused_fns(protocol)
    ref = reference_chunk(
        init_state(c), seed, plan, c.fault, 16, apply_fn, mask_fn)
    assert digest(fused) == digest(ref), f"{protocol}: fused != reference"

# (c) The synchrony bet: fast path lands under delta-respecting latencies,
# honest fallback stays safe when the window is violated.
_, state = run(config_delay_chaos(n_inst=256, seed=7),
               until_all_chosen=True, max_ticks=256, return_state=True)
assert fast_path_rate(state) > 0.0, "fast path never landed under delta"
report = run(config_delay_chaos(n_inst=256, seed=1, violate_delta=True),
             total_ticks=256)
assert report["violations"] == 0, report["violations"]
assert report["proposer_disagree"] == 0, report["proposer_disagree"]
EOF
fi
# Fleet smoke: the fault-tolerant sharded fleet end to end, chaos ON.  A
# 2-worker CPU fleet over 4 soak records takes one seeded SIGKILL
# mid-claim, reclaims EXACTLY that one expired lease, re-dispatches the
# record, completes the whole budget with zero violations, and passes
# the built-in bench self-gate — crash recovery as a release criterion,
# not a best effort.
if [ "$rc" -eq 0 ]; then
  fd=/tmp/_t1_fleet; fo=/tmp/_t1_fleet.json; rm -rf "$fd" "$fo"
  timeout -k 10 600 env JAX_PLATFORMS=cpu python -m paxos_tpu fleet \
    --config config2 --n-inst 64 --mode soak --records 4 \
    --seeds-per-record 2 --ticks-per-seed 32 --chunk 16 \
    --coverage-words 64 --workers 2 --dir "$fd" --lease-s 6 \
    --poll-s 0.2 --timeout-s 420 --chaos --chaos-kills 1 \
    --chaos-seed 7 --hold-s 1.5 --bench-baseline BENCH_SWEEP.json \
    >"$fo" 2>/dev/null \
  && timeout -k 10 30 env JAX_PLATFORMS=cpu python - "$fo" <<'EOF' \
  && echo FLEET_SMOKE=ok || { echo FLEET_SMOKE=FAILED; rc=1; }
import json, sys
out = json.load(open(sys.argv[1]))
fleet = out["fleet"]
assert out["completed"] is True, fleet
assert fleet["records_done"] == fleet["records_total"] == 4, fleet
assert fleet["leases_reclaimed"] == 1, (
    f"chaos killed one worker, so exactly one lease must be reclaimed: "
    f"{fleet}")
assert out["chaos"]["kills_done"] == 1, out["chaos"]
assert fleet["workers_spawned"] > fleet["workers"], (
    "the killed worker was never respawned")
assert out["violations"] == 0, out["violations"]
assert int(out["union_hex"], 16) != 0, "merged coverage union is empty"
assert out["seeds"] == 8, out["seeds"]  # every planned seed accounted
assert out["bench_gate"]["ok"] is True, out["bench_gate"]
EOF
fi
# Observatory smoke: the fleet observatory end to end, chaos ON.  A
# 2-worker CPU fuzz fleet with per-campaign sampling must produce a
# merged time-series with monotone seq per worker, a valid Perfetto
# fleet trace, a lineage tree whose root count equals the planned roots
# (records x seed_entries, disjoint seed spaces), and a clean trend
# gate; a hand-planted flat-coverage fixture must exit 2 through
# `stats --series-gate` naming the stalled worker.
if [ "$rc" -eq 0 ]; then
  od=/tmp/_t1_obs; oo=/tmp/_t1_obs.json; rm -rf "$od" "$oo"
  timeout -k 10 600 env JAX_PLATFORMS=cpu python -m paxos_tpu fleet \
    --config config2 --n-inst 64 --mode fuzz --records 2 \
    --campaigns-per-record 4 --ticks-per-seed 32 --chunk 16 \
    --coverage-words 64 --workers 2 --dir "$od/q" --lease-s 6 \
    --poll-s 0.2 --timeout-s 420 --chaos --chaos-kills 1 \
    --chaos-seed 7 --hold-s 1.0 --sample-every 1 \
    --timeline "$od/trace.json" --corpus-out "$od/corpus.jsonl" \
    >"$oo" 2>/dev/null \
  && timeout -k 10 60 env JAX_PLATFORMS=cpu python - "$oo" "$od" <<'EOF' \
  && echo OBSERVATORY_SMOKE=ok || { echo OBSERVATORY_SMOKE=FAILED; rc=1; }
import json, subprocess, sys
out = json.load(open(sys.argv[1]))
od = sys.argv[2]
assert out["completed"] is True and out["chaos"]["kills_done"] == 1, out
# (a) Merged time-series: every planned campaign sampled once, seq
# strictly monotone per worker journal.
series = out["series"]
assert series["samples"] == 8, series  # 2 records x 4 campaigns
assert all(w["seq_monotone"] for w in series["workers"].values()), series
# (b) Clean trend gate on a healthy chaos run.
assert out["series_gate"]["ok"] is True, out["series_gate"]
# (c) Perfetto fleet trace: schema-valid, a track per worker plus the
# fleet-aggregate counter tracks.
from paxos_tpu.obs.export import validate_chrome_trace
trace = json.load(open(f"{od}/trace.json"))
assert validate_chrome_trace(trace) == []
procs = {e["args"]["name"] for e in trace["traceEvents"]
         if e["ph"] == "M" and e["name"] == "process_name"}
workers = {p for p in procs if p.startswith("worker ")}
assert len(workers) >= 2 and "fleet coordinator" in procs, procs
counters = {e["name"] for e in trace["traceEvents"] if e["ph"] == "C"}
assert {"fleet_records_done", "fleet_queue_depth",
        "union_bits"} <= counters, counters
# (d) Lineage: root count equals planned roots (disjoint seed spaces,
# so the merge dedups nothing), attribution sums match the journal.
assert out["lineage"]["roots"] == 4, out["lineage"]  # 2 recs x 2 entries
p = subprocess.run(
    [sys.executable, "-m", "paxos_tpu", "lineage", f"{od}/corpus.jsonl",
     "--json"], capture_output=True, text=True)
assert p.returncode == 0, p.stderr
lin = json.loads(p.stdout)
assert lin["summary"]["roots"] == 4, lin["summary"]
fb = [e for e in map(json.loads, open(f"{od}/corpus.jsonl"))
      if e.get("event") == "feedback"]
assert lin["totals"]["new_bits"] == sum(e["new_bits"] for e in fb), lin
# (e) Planted stall fixture: flat coverage for 6 samples must exit 2
# through the stats trend gate, naming the worker.
import pathlib
from paxos_tpu.fuzz.corpus import append_event
from paxos_tpu.obs.timeseries import sample_row
fake = pathlib.Path(od) / "fake"
(fake / "series").mkdir(parents=True)
with open(fake / "series" / "w0.jsonl", "a") as fh:
    for clock in range(6):
        append_event(fh, sample_row(
            worker="w0", record="c00000", attempt=0, seq=clock,
            clock=clock, gauges={"worker_union_bits": 64}))
p = subprocess.run(
    [sys.executable, "-m", "paxos_tpu", "stats", "--fleet-root",
     str(fake), "--series-gate"], capture_output=True, text=True)
assert p.returncode == 2, (p.returncode, p.stdout, p.stderr)
assert "w0" in p.stderr and "discovery_stall" in p.stderr, p.stderr
EOF
fi
# SLO smoke: the client-workload plane's end-to-end acceptance, kept
# cheap.  A bursty campaign through the `slo` subcommand must emit
# nonzero per-class latency histograms that account exactly for every
# served request (exit 0 with no SLO configured); the SAME campaign
# gated at an unmeetable 1-tick p99 must exit 2 naming the breaching
# class; and a planted late-latency regression in a fleet series must
# trip the `slo_degradation` trend detector through the stats gate.
if [ "$rc" -eq 0 ]; then
  so=/tmp/_t1_slo.json; sd=/tmp/_t1_slo_dir; rm -rf "$so" "$sd"
  timeout -k 10 300 env JAX_PLATFORMS=cpu python -m paxos_tpu slo \
    --config config2 --n-inst 256 --ticks 96 --chunk 32 --mix bursty \
    --rate 0.2 --sweep 0.5 1.0 --json >"$so" 2>/dev/null
  if [ $? -eq 0 ]; then
    timeout -k 10 600 env JAX_PLATFORMS=cpu python - "$so" "$sd" <<'EOF' \
    && echo SLO_SMOKE=ok || { echo SLO_SMOKE=FAILED; rc=1; }
import json, pathlib, subprocess, sys
out = json.load(open(sys.argv[1]))
assert out["breaches"] == [], out["breaches"]  # no SLO configured
pts = out["sweep"]
assert len(pts) == 2 and all(p["offered"] > 0 for p in pts), pts
at1 = next(p for p in pts if p["rate_scale"] == 1.0)
bursty = at1["classes"]["bursty"]
assert bursty["done"] > 0, bursty
assert sum(bursty["hist"]) == bursty["done"], bursty  # exact accounting
assert any(v > 0 for v in bursty["hist"]), bursty
# Queued bursts cannot all serve in one tick: guarantees the breach leg.
assert bursty["p99_ticks"] >= 2, bursty
flags = ["--config", "config2", "--n-inst", "256", "--ticks", "96",
         "--chunk", "32", "--mix", "bursty", "--rate", "0.2"]
p = subprocess.run(
    [sys.executable, "-m", "paxos_tpu", "slo", *flags,
     "--sweep", "1.0", "--slo-p99", "1"],
    capture_output=True, text=True)
assert p.returncode == 2, (p.returncode, p.stdout, p.stderr)
assert "SLO BREACH" in p.stdout and "bursty" in p.stdout, p.stdout
# Planted latency regression: steady p99 then a late 3x blow-up must
# exit 2 through the series trend gate as slo_degradation (coverage
# grows so the stall detector stays quiet — this is the SLO finding).
from paxos_tpu.fuzz.corpus import append_event
from paxos_tpu.obs.timeseries import sample_row
fake = pathlib.Path(sys.argv[2])
(fake / "series").mkdir(parents=True)
with open(fake / "series" / "w0.jsonl", "a") as fh:
    for i, p99 in enumerate([4, 4, 4, 4, 12]):
        append_event(fh, sample_row(
            worker="w0", record="c00000", attempt=0, seq=i, clock=i,
            gauges={"worker_union_bits": 10 * (i + 1),
                    "slo_p99_ticks": p99}))
g = subprocess.run(
    [sys.executable, "-m", "paxos_tpu", "stats", "--fleet-root",
     str(fake), "--series-gate"], capture_output=True, text=True)
assert g.returncode == 2, (g.returncode, g.stdout, g.stderr)
assert "slo_degradation" in g.stderr and "w0" in g.stderr, g.stderr
EOF
  else
    echo SLO_SMOKE=FAILED; rc=1
  fi
fi

exit $rc
