#!/usr/bin/env bash
# Causal round trace: run a short corrupt campaign with the flight
# recorder on, export the unified device+host Perfetto timeline, and
# schema-check the result.  The output loads directly in ui.perfetto.dev
# or chrome://tracing: one thread per decoded lane with async ballot-round
# spans and fault instants (device track, tick-time), plus the dispatch
# loop's wall-clock spans (host track).
#
# Usage: scripts/trace.sh [out.json] [extra `paxos_tpu trace` flags...]
#   scripts/trace.sh                            # trace.json, corrupt config
#   scripts/trace.sh /tmp/t.json --config gray-chaos --ticks 512
cd "$(dirname "$0")/.." || exit 1
out="${1:-trace.json}"; [ $# -gt 0 ] && shift
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m paxos_tpu trace \
  --config corrupt --ticks 256 --out "$out" "$@" || exit $?
env JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - "$out" <<'EOF' || exit 1
import json, sys
from paxos_tpu.obs.export import validate_chrome_trace
obj = json.load(open(sys.argv[1]))
errs = validate_chrome_trace(obj)
for e in errs:
    print(f"schema: {e}", file=sys.stderr)
raise SystemExit(1 if errs else 0)
EOF
echo "TRACE=$out (schema ok; load in ui.perfetto.dev)"
