"""Child process for the two-process distributed smoke test.

Usage: python tests/_dist_child.py <process_id> <coordinator_port>

Joins a 2-process CPU "cluster" via ``init_distributed`` (explicit
coordinator — the multi-controller rendezvous path that round 1 left
uncovered), builds the global instances mesh spanning both processes'
devices, runs a tiny sharded campaign entirely under ``jit`` (outputs are
replicated scalars, so both controllers must report identical metrics),
and prints one JSON line.
"""

import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    from paxos_tpu.parallel.distributed import (
        init_distributed,
        make_instances_mesh,
    )

    idx = init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
    assert idx == pid, (idx, pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4  # 2 local x 2 processes, globally visible

    from paxos_tpu.harness.config import config2_dueling_drop
    from paxos_tpu.harness.run import base_key, init_plan, init_state, run_chunk
    from paxos_tpu.parallel.mesh import INSTANCES_AXIS

    cfg = config2_dueling_drop(n_inst=64, seed=3)
    mesh = make_instances_mesh()

    def leaf_spec(x):
        if getattr(x, "ndim", 0) >= 1 and x.shape[-1] == cfg.n_inst:
            return P(*([None] * (x.ndim - 1)), INSTANCES_AXIS)
        return P()

    def spec_of(tree):
        return jax.tree.map(leaf_spec, tree)

    def constrain(tree):
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, leaf_spec(x))
            ),
            tree,
        )

    from paxos_tpu.harness.run import get_step_fn

    step = get_step_fn(cfg.protocol)

    @jax.jit
    def campaign():
        # State is built INSIDE jit and sharding-constrained, so each
        # controller materializes only its addressable shards — the
        # multi-controller idiom (no host-side global array assembly).
        state = constrain(init_state(cfg))
        plan = constrain(init_plan(cfg))
        state = run_chunk(state, base_key(cfg), plan, cfg.fault, 32, step)
        return {
            "chosen": state.learner.chosen.sum(),
            "violations": state.learner.violations.sum(),
            "evictions": state.learner.evictions.sum(),
            "tick": state.tick,
        }

    out = {k: int(v) for k, v in jax.device_get(campaign()).items()}

    # The sharded FUSED engine's stream over the same process-spanning mesh
    # (VERDICT r3 #6): the flagship path's global block-offset arithmetic
    # (fused_chunk_sharded: axis_index * blocks_per_shard) must hold when
    # the instances axis crosses a process boundary, not just on a
    # single-process 8-device mesh.  The Pallas TPU-interpret emulation
    # itself DEADLOCKS under a multi-process shard_map (minimal repro: a
    # 2-process 2-device mesh running a trivial `o_ref[...] = x_ref[...]+1`
    # interpret-mode pallas_call via shard_map blocks both controllers
    # indefinitely at ~10% CPU — a JAX emulation limitation, not a kernel
    # property; on real multi-host TPUs interpret mode is never used), so
    # the kernel body here is the fused engine's bit-exact stream oracle
    # `reference_chunk` with the shard's global block id from axis_index —
    # the exact arithmetic under test.  Each local shard is ONE block
    # (block = 64/4 = 16), so the parent can compare these metrics against
    # a single-process fused_chunk at block=16 bit-for-bit.
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)

    @jax.jit
    def fused_campaign():
        # Same multi-controller idiom as the XLA campaign: state materializes
        # as addressable shards under a sharding constraint, never as a
        # host-side global array.
        state = constrain(init_state(cfg))
        plan = constrain(init_plan(cfg))

        def local_fused(st, pln):
            blk = jax.lax.axis_index(INSTANCES_AXIS)
            return reference_chunk(
                st, jnp.int32(cfg.seed), pln, cfg.fault, 32,
                apply_fn, mask_fn, blk_id=blk,
            )

        state = jax.shard_map(
            local_fused, mesh=mesh,
            in_specs=(spec_of(state), spec_of(plan)),
            out_specs=spec_of(state), check_vma=False,
        )(state, plan)
        return {
            "chosen": state.learner.chosen.sum(),
            "violations": state.learner.violations.sum(),
            "evictions": state.learner.evictions.sum(),
            "tick": state.tick,
        }

    out["fused"] = {
        k: int(v) for k, v in jax.device_get(fused_campaign()).items()
    }

    # VERDICT r4 #7: the REAL Pallas lowering crossing process boundaries.
    # The interpret-mode emulation deadlocks under a multi-process
    # shard_map (documented above), so sidestep shard_map entirely: this
    # controller runs plain ``fused_chunk`` (the actual pallas_call,
    # interpret mode, no mesh) on its process's DISJOINT half of the lanes
    # with the manually-computed global ``block_offset`` the sharded
    # wrapper would have assigned (pid * blocks_per_shard).  The parent
    # concatenates both halves' state digests and asserts bit-equality
    # with a single-process full-width ``fused_chunk`` — the lowering
    # itself, not just the stream oracle, validated across processes.
    import hashlib

    import numpy as np

    from paxos_tpu.kernels.fused_tick import fused_chunk

    block = 16
    half = cfg.n_inst // 2
    blocks_per_shard = half // block

    def slice_half(tree):
        return jax.tree.map(
            lambda x: (
                x[..., pid * half:(pid + 1) * half]
                if getattr(x, "ndim", 0) >= 1 and x.shape[-1] == cfg.n_inst
                else x
            ),
            tree,
        )

    local = fused_chunk(
        slice_half(init_state(cfg)), jnp.int32(cfg.seed),
        slice_half(init_plan(cfg)), cfg.fault, 32, apply_fn, mask_fn,
        block=block, interpret=True, block_offset=pid * blocks_per_shard,
    )
    digest = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(local)):
        arr = np.asarray(leaf)
        digest.update(str((arr.dtype.str, arr.shape)).encode())
        digest.update(arr.tobytes())
    out["pallas_shard_digest"] = digest.hexdigest()

    out["process"] = pid
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
