"""Child process for the two-process distributed smoke test.

Usage: python tests/_dist_child.py <process_id> <coordinator_port>

Joins a 2-process CPU "cluster" via ``init_distributed`` (explicit
coordinator — the multi-controller rendezvous path that round 1 left
uncovered), builds the global instances mesh spanning both processes'
devices, runs a tiny sharded campaign entirely under ``jit`` (outputs are
replicated scalars, so both controllers must report identical metrics),
and prints one JSON line.
"""

import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def main() -> None:
    pid = int(sys.argv[1])
    port = sys.argv[2]

    from paxos_tpu.parallel.distributed import (
        init_distributed,
        make_instances_mesh,
    )

    idx = init_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
    assert idx == pid, (idx, pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4  # 2 local x 2 processes, globally visible

    from paxos_tpu.harness.config import config2_dueling_drop
    from paxos_tpu.harness.run import base_key, init_plan, init_state, run_chunk
    from paxos_tpu.parallel.mesh import INSTANCES_AXIS

    cfg = config2_dueling_drop(n_inst=64, seed=3)
    mesh = make_instances_mesh()

    def constrain(tree):
        def leaf(x):
            if getattr(x, "ndim", 0) >= 1 and x.shape[-1] == cfg.n_inst:
                spec = P(*([None] * (x.ndim - 1)), INSTANCES_AXIS)
            else:
                spec = P()
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec)
            )

        return jax.tree.map(leaf, tree)

    from paxos_tpu.harness.run import get_step_fn

    step = get_step_fn(cfg.protocol)

    @jax.jit
    def campaign():
        # State is built INSIDE jit and sharding-constrained, so each
        # controller materializes only its addressable shards — the
        # multi-controller idiom (no host-side global array assembly).
        state = constrain(init_state(cfg))
        plan = constrain(init_plan(cfg))
        state = run_chunk(state, base_key(cfg), plan, cfg.fault, 32, step)
        return {
            "chosen": state.learner.chosen.sum(),
            "violations": state.learner.violations.sum(),
            "evictions": state.learner.evictions.sum(),
            "tick": state.tick,
        }

    out = {k: int(v) for k, v in jax.device_get(campaign()).items()}
    out["process"] = pid
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
