"""Test env: force CPU with 8 virtual devices (SURVEY.md §5.2.4).

Multi-chip tests without a cluster — the TPU analog of Cloud Haskell's
`network-transport-inmemory`.  The image's sitecustomize registers the
`axon` TPU backend at interpreter start and pins `jax_platforms=axon,cpu`,
so an env var alone is not enough: re-point jax at CPU explicitly before
any backend is used.  XLA_FLAGS must be set before the CPU client is
created (lazily), which this module-level code guarantees.

``PAXOS_TPU_REAL=1`` opts OUT of the CPU rig and keeps the real TPU
backend — intended for the TPU-gated perf-regression suite only
(``PAXOS_TPU_REAL=1 pytest tests/test_perf_regression.py``); the
multi-device sharding tests assume the 8-device CPU mesh and are not
expected to pass against a single real chip.
"""

import os

if os.environ.get("PAXOS_TPU_REAL") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
