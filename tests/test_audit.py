"""Static determinism auditor (paxos_tpu.analysis): clean + mutation tests.

Two halves:

1. **Clean**: the shipped tree audits clean for every protocol x config
   cell — PRNG streams registered/collision-free/gated, traces pure, plan
   folds exact, structure goldens matching.  These pin the auditor AND
   the tree: either side regressing fails here first.
2. **Mutations**: each detector is fed a planted violation (stream
   collision, unregistered stream, host callback, unregistered fold,
   non-pruning default-off leaf, host-entropy import) and must produce a
   finding whose message NAMES the offender — an auditor that fires
   without saying where is a worse debugging experience than no auditor.

Everything here is trace-time only (no campaign executes), so the whole
module rides the fast ``-m 'not slow'`` tier.
"""

import dataclasses
import textwrap

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.analysis import jaxpr_tools as jt
from paxos_tpu.analysis import prng_audit, purity, structure
from paxos_tpu.analysis import trace as trace_mod
from paxos_tpu.analysis.audit import run_audit
from paxos_tpu.core import streams as streams_mod
from paxos_tpu.harness.run import init_plan, init_state
from paxos_tpu.kernels import counter_prng as cp

PROTOCOLS = trace_mod.PROTOCOLS
CONFIGS = tuple(trace_mod.CONFIG_MATRIX)


# ---------------------------------------------------------------- registry


def test_registry_validates():
    """The registry's own invariants hold at import (collisions, ranges)."""
    for fam in streams_mod.FAMILIES.values():
        fam.validate()
    assert streams_mod.family_of("paxos") is streams_mod.SINGLE_DECREE
    assert streams_mod.family_of("multipaxos") is streams_mod.MULTI_PAXOS


def test_registry_rejects_collision():
    fam = dataclasses.replace(
        streams_mod.SINGLE_DECREE,
        streams={**streams_mod.SINGLE_DECREE.streams, "EVIL": 0},
    )
    with pytest.raises(ValueError, match="EVIL|SEL"):
        fam.validate()


def test_salt_helper_matches_counter_bits():
    """stream_salt is the literal counter_bits embeds (recovery anchor)."""
    for s in (0, 5, 13, 63):
        closed = jax.make_jaxpr(
            lambda seed: cp.counter_bits(seed, s, (4,))
        )(jnp.int32(7))
        assert jt.counter_salt_streams(closed.jaxpr) == {s: 1}


# ------------------------------------------------------------------- clean


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_clean_audit_default_config(protocol):
    """Fast lane: the default cell of each protocol audits clean."""
    report = run_audit(
        protocols=[protocol], configs=["default"], structure=True, lint=False
    )
    assert report.ok, report.summary()


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_clean_audit_full_matrix(protocol):
    """Every config cell (incl. telemetry parity) audits clean."""
    report = run_audit(protocols=[protocol], structure=True, lint=False)
    assert report.ok, report.summary()


def test_ast_lint_clean_on_tree():
    assert purity.audit_traced_sources() == []


def test_default_trace_has_no_gray_draws():
    """The stream half of default-off-is-free, asserted directly."""
    for protocol in PROTOCOLS:
        cfg = trace_mod.build_config(protocol, "default")
        xla = trace_mod.trace_xla_step(protocol, cfg)
        assert not jt.fold_in_constants(xla.jaxpr), protocol
        ctr = trace_mod.trace_counter_tick(protocol, cfg)
        fam = streams_mod.family_of(protocol)
        gray = jt.counter_salt_streams(ctr.jaxpr).keys() & fam.gray_ids()
        assert not gray, (protocol, sorted(gray))


# --------------------------------------------------------------- mutations


def _ctr_audit(fn, protocol="paxos", config="default"):
    cfg = trace_mod.build_config(protocol, config)
    closed = jax.make_jaxpr(fn)(jnp.int32(3))
    return prng_audit.audit_counter_streams(protocol, config, closed, cfg.fault)


def test_mutation_stream_collision_detected():
    sel = streams_mod.SINGLE_DECREE.streams["SEL"]

    def twice(seed):
        return cp.counter_bits(seed, sel, (8,)) ^ cp.counter_bits(
            seed, sel, (8,)
        )

    findings = _ctr_audit(twice)
    assert any(
        f.check == "stream-collision" and f"stream {sel}" in f.message
        and "SEL" in f.message
        for f in findings
    ), findings


def test_mutation_unregistered_stream_detected():
    def rogue(seed):
        return cp.counter_bits(seed, 42, (8,))

    findings = _ctr_audit(rogue)
    assert any(
        f.check == "stream-registry" and "42" in f.message for f in findings
    ), findings


def test_mutation_gray_stream_when_knob_off_detected():
    link = streams_mod.SINGLE_DECREE.streams["LINK_BITS"]

    def gray(seed):
        return cp.counter_bits(seed, link, (8,))

    findings = _ctr_audit(gray)  # default config: p_flaky == 0
    assert any(
        f.check == "gray-gating" and "LINK_BITS" in f.message
        for f in findings
    ), findings


def test_mutation_jax_random_in_fused_path_detected():
    def leaky(seed):
        key = jax.random.PRNGKey(seed)
        return jax.random.bits(key, (8,), jnp.uint32)

    findings = _ctr_audit(leaky)
    assert any(f.check == "counter-engine-purity" for f in findings), findings


def test_mutation_unregistered_fold_detected():
    cfg = trace_mod.build_config("paxos", "default")

    def step_like(key):
        return jax.random.bits(jax.random.fold_in(key, 55), (4,), jnp.uint32)

    closed = jax.make_jaxpr(step_like)(jax.random.PRNGKey(0))
    findings = prng_audit.audit_xla_folds("paxos", "default", closed, cfg.fault)
    assert any(
        f.check == "fold-registry" and "55" in f.message for f in findings
    ), findings


def test_mutation_dead_draw_detected():
    def wasteful(key):
        dead = jax.random.bits(jax.random.fold_in(key, 102), (4,), jnp.uint32)
        del dead
        return jax.random.bits(key, (4,), jnp.uint32)

    closed = jax.make_jaxpr(wasteful)(jax.random.PRNGKey(0))
    findings = prng_audit.audit_dead_draws("paxos", "default", closed)
    assert any(
        f.check == "dead-draw" and "102" in f.message for f in findings
    ), findings


def test_mutation_host_callback_detected():
    import numpy as np

    def chatty(x):
        return jax.pure_callback(
            lambda v: np.asarray(v) + 1,
            jax.ShapeDtypeStruct((4,), jnp.int32),
            x,
        )

    closed = jax.make_jaxpr(chatty)(jnp.zeros(4, jnp.int32))
    findings = purity.audit_jaxpr_purity("mutant xla step", closed)
    assert any("pure_callback" in f.message for f in findings), findings


def test_mutation_nonpruning_default_off_leaf_detected():
    from paxos_tpu.core.telemetry import TelemetryConfig, TelemetryState

    cfg = trace_mod.build_config("paxos", "default")

    def leaky_builder(c):
        state = init_state(c)
        return state.replace(
            telemetry=TelemetryState.init(
                c.n_inst, TelemetryConfig(counters=True)
            )
        )

    findings = structure.audit_default_off_leaves(
        "paxos", "default", cfg, state_builder=leaky_builder
    )
    assert any(
        f.check == "structure" and "telemetry" in f.message
        and "prune" in f.message
        for f in findings
    ), findings


def test_mutation_treedef_drift_detected():
    from paxos_tpu.core.telemetry import TelemetryConfig, TelemetryState

    cfg = trace_mod.build_config("paxos", "default")

    def drifted_builder(c):
        state = init_state(c)
        return state.replace(
            telemetry=TelemetryState.init(
                c.n_inst, TelemetryConfig(counters=True)
            )
        )

    findings = structure.audit_goldens(
        "paxos", "default", cfg, state_builder=drifted_builder
    )
    assert any(
        f.check == "structure-golden" and "treedef" in f.message
        for f in findings
    ), findings


def test_mutation_host_entropy_import_detected(tmp_path):
    bad = tmp_path / "mutant_module.py"
    bad.write_text(textwrap.dedent("""\
        import numpy as np

        def seedy():
            return np.random.rand(4)
    """))
    findings = purity.lint_file(bad, "mutant_module.py")
    assert any(
        f.check == "ast-lint" and "np.random" in f.message
        and "mutant_module.py" in f.where
        for f in findings
    ), findings


def test_mutation_wall_clock_import_detected(tmp_path):
    bad = tmp_path / "timed.py"
    bad.write_text("import time\n\ndef now():\n    return time.time()\n")
    findings = purity.lint_file(bad, "timed.py")
    assert any("wall clock" in f.message for f in findings), findings


# --------------------------------------------------------------- plan audit


def test_plan_folds_exact_for_gray_chaos():
    # gray-chaos draws exactly its expected folds; LINK_DELAY only joins
    # when p_delay lights, so delay-chaos supplies it and together the two
    # configs must exercise the full PLAN_FOLDS registry.
    cfg = trace_mod.build_config("paxos", "gray-chaos")
    closed = trace_mod.trace_plan_sample(cfg)
    seen = set(jt.fold_in_constants(closed.jaxpr))
    assert seen == prng_audit.expected_plan_folds(cfg.fault)
    assert streams_mod.PLAN_FOLDS["LINK_DELAY"] not in seen

    dcfg = trace_mod.build_config("synchpaxos", "delay-chaos")
    dclosed = trace_mod.trace_plan_sample(dcfg)
    dseen = set(jt.fold_in_constants(dclosed.jaxpr))
    assert dseen == prng_audit.expected_plan_folds(dcfg.fault)
    assert streams_mod.PLAN_FOLDS["LINK_DELAY"] in dseen

    assert seen | dseen == set(streams_mod.PLAN_FOLDS.values())


def test_plan_missing_fold_detected():
    """A plan trace that skips an expected gray fold is flagged."""
    cfg = trace_mod.build_config("paxos", "gray-chaos")

    def partial_plan(key):
        # Draws PART_DIR but not CUT_REQ/FLAKY/... for a config where all
        # knobs are on.
        return jax.random.uniform(
            streams_mod.plan_fold(key, "PART_DIR"), (4,)
        )

    closed = jax.make_jaxpr(partial_plan)(jax.random.PRNGKey(0))
    findings = prng_audit.audit_plan_folds(
        "paxos", "gray-chaos", closed, cfg.fault
    )
    assert any(
        f.check == "plan-folds" and "CUT_REQ" in f.message for f in findings
    ), findings


# -------------------------------------------------------------- structural


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_default_off_leaves_prune(protocol):
    """Direct (golden-free) check: off-knob leaves are None on the tree."""
    cfg = trace_mod.build_config(protocol, "default")
    state = init_state(cfg)
    plan = init_plan(cfg)
    assert state.telemetry is None
    for field in ("part_dir", "link_drop", "link_dup", "ptimeout", "pboff"):
        assert getattr(plan, field) is None, field


def test_treedef_fingerprint_is_shape_independent():
    cfg64 = trace_mod.build_config("paxos", "default")
    cfg128 = dataclasses.replace(cfg64, n_inst=128)
    assert structure.treedef_fingerprint(
        init_state(cfg64)
    ) == structure.treedef_fingerprint(init_state(cfg128))
