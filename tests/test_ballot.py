"""Ballot packing: order, roundtrip, NIL."""

import jax.numpy as jnp

from paxos_tpu.core.ballot import (
    MAX_PROPOSERS,
    NIL,
    ballot_owner,
    ballot_round,
    make_ballot,
)


def test_roundtrip():
    for rnd in (0, 1, 7, 1000):
        for pid in range(MAX_PROPOSERS):
            b = make_ballot(rnd, pid)
            assert int(ballot_round(b)) == rnd
            assert int(ballot_owner(b)) == pid


def test_order_lexicographic():
    pairs = [(r, p) for r in (0, 1, 2, 50) for p in range(MAX_PROPOSERS)]
    bals = [int(make_ballot(r, p)) for (r, p) in pairs]
    assert bals == sorted(bals)
    assert all(b > NIL for b in bals)


def test_vectorized():
    r = jnp.array([[0, 1], [2, 3]])
    p = jnp.array([[0, 1], [2, 3]])
    b = make_ballot(r, p)
    assert b.shape == (2, 2)
    assert (ballot_round(b) == r).all()
    assert (ballot_owner(b) == p).all()
