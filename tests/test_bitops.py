"""Packed lane-state codecs: roundtrip exactness, boundaries, estimator.

The packing contract (utils/bitops + the core/*_state.py layout tables) is
``unpack(pack(s)) == s`` bit-exactly for every in-range state — in-range
meaning the field-width invariants the config/report-time guards enforce
(harness/run.py).  These tests pin that property for all four protocols
with randomized states, pin the boundary behavior (0, max roundtrip; max+1
WRAPS — pack masks to the declared width, which is why the runtime guards
exist), and pin the VMEM estimator that sizes the fused block from packed
bytes instead of unpacked leaf sums.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paxos_tpu.harness.config import (
    SimConfig,
    config2_dueling_drop,
    config3_long,
    config3_multipaxos,
    config5_sweep,
)
from paxos_tpu.harness.run import init_state
from paxos_tpu.utils import bitops

PROTOCOLS = ("paxos", "multipaxos", "fastpaxos", "raftcore")


def _cfg(protocol, n_inst=64, **kw):
    if protocol == "paxos":
        return config2_dueling_drop(n_inst=n_inst, **kw)
    if protocol == "multipaxos":
        return config3_multipaxos(n_inst=n_inst, **kw)
    sweep = {c.protocol: c for c in config5_sweep(n_inst=n_inst, **kw)}
    return sweep[protocol]


def _leaf_kinds(codec):
    """leaf index -> ("slot", _Slot) | ("stream", _PStream) | ("zero", dtype)
    | ("pt", None) | ("tick", None), from the resolved codec."""
    kinds = {}
    for w in codec.words:
        for s in w.slots:
            kinds[s.leaf] = ("slot", s)
    for st in codec.streams:
        kinds[st.leaf] = ("stream", st)
    for leaf, _like, dtype in codec.zeros:
        kinds[leaf] = ("zero", dtype)
    for _name, leaf in codec.passthroughs:
        kinds[leaf] = ("pt", None)
    kinds[codec.tick_leaf] = ("tick", None)
    return kinds


def _random_bv(rng, shape, bal_bits, val_bits):
    bal = rng.integers(0, 1 << bal_bits, shape)
    val = rng.integers(0, 1 << val_bits, shape)
    return jnp.asarray((bal << 16) | val, jnp.int32)


def _random_in_range_state(protocol, cfg, seed):
    """A state whose every leaf is random but within its declared field
    range — the domain the pack/unpack bijection is promised on."""
    state = init_state(cfg)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    codec = bitops.codec_for(protocol, state)
    kinds = _leaf_kinds(codec)
    rng = np.random.default_rng(seed)
    out = []
    for i, leaf in enumerate(leaves):
        kind, info = kinds[i]
        shape = tuple(leaf.shape)
        if kind == "slot":
            if info.bool_:
                out.append(jnp.asarray(rng.integers(0, 2, shape), jnp.bool_))
            elif info.bv is not None:
                out.append(_random_bv(rng, shape, *info.bv))
            elif info.signed:
                half = 1 << (info.bits - 1)
                out.append(jnp.asarray(
                    rng.integers(-half, half, shape), jnp.int32))
            else:
                out.append(jnp.asarray(
                    rng.integers(0, 1 << info.bits, shape), jnp.int32))
        elif kind == "stream":
            out.append(_random_bv(rng, shape, info.bal_bits, info.val_bits))
        elif kind == "zero":
            out.append(jnp.zeros(shape, info))
        elif kind == "tick":
            out.append(jnp.int32(rng.integers(0, 1 << 30)))
        else:  # passthrough: any value of the leaf's dtype roundtrips
            if leaf.dtype == jnp.bool_:
                out.append(jnp.asarray(rng.integers(0, 2, shape), jnp.bool_))
            else:
                out.append(jnp.asarray(
                    rng.integers(-(1 << 31), 1 << 31, shape), jnp.int32))
    return jax.tree_util.tree_unflatten(treedef, out), codec


def _assert_trees_bitexact(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for x, y in zip(la, lb):
        assert x.dtype == y.dtype
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# Roundtrip property: all four protocols, randomized in-range states.


@pytest.mark.parametrize("protocol", PROTOCOLS)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_roundtrip_random_in_range(protocol, seed):
    cfg = _cfg(protocol, n_inst=64, seed=seed)
    state, codec = _random_in_range_state(protocol, cfg, seed)
    _assert_trees_bitexact(codec.unpack(codec.pack(state)), state)


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_roundtrip_init_state_and_variants(protocol):
    """The real initial states (default, stale snapshots on, telemetry on)
    roundtrip too — optional words/streams and passthrough rings included."""
    from paxos_tpu.core.telemetry import TelemetryConfig

    base = _cfg(protocol, n_inst=64)
    variants = [
        base,
        dataclasses.replace(
            base, fault=dataclasses.replace(base.fault, stale_k=2)
        ),
        dataclasses.replace(
            base,
            telemetry=TelemetryConfig(counters=True, ring_depth=8, hist_bins=4),
        ),
    ]
    for cfg in variants:
        state = init_state(cfg)
        codec = bitops.codec_for(protocol, state)
        _assert_trees_bitexact(codec.unpack(codec.pack(state)), state)


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_roundtrip_field_boundaries(protocol):
    """0 and max roundtrip exactly; max+1 WRAPS to the masked value (the
    documented overflow behavior the runtime ballot/timer guards exist to
    rule out)."""
    cfg = _cfg(protocol, n_inst=8)
    state = init_state(cfg)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    codec = bitops.codec_for(protocol, state)
    kinds = _leaf_kinds(codec)

    def fill(value_of):
        out = []
        for i, leaf in enumerate(leaves):
            kind, info = kinds[i]
            if kind == "slot" and not info.bool_ and info.bv is None:
                out.append(jnp.full(leaf.shape, value_of(info), jnp.int32))
            else:
                out.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, out)

    def maxval(s):
        return (1 << (s.bits - 1)) - 1 if s.signed else (1 << s.bits) - 1

    zero = fill(lambda s: 0)
    _assert_trees_bitexact(codec.unpack(codec.pack(zero)), zero)
    top = fill(maxval)
    _assert_trees_bitexact(codec.unpack(codec.pack(top)), top)
    # max+1 wraps: unsigned fields drop to 0, signed fields to their minimum.
    over = fill(lambda s: maxval(s) + 1)
    got = jax.tree_util.tree_flatten(codec.unpack(codec.pack(over)))[0]
    for i, leaf in enumerate(leaves):
        kind, info = kinds[i]
        if kind == "slot" and not info.bool_ and info.bv is None:
            want = -(1 << (info.bits - 1)) if info.signed else 0
            np.testing.assert_array_equal(
                np.asarray(got[i]), np.full(leaf.shape, want, np.int32)
            )


# ---------------------------------------------------------------------------
# Write-set property fuzz: pack_delta == full pack under written mutations,
# and a write OUTSIDE the declared set is (by contract) dropped.


def _packed_words_bitexact(a, b):
    assert set(a.words) == set(b.words)
    for name in sorted(a.words):
        np.testing.assert_array_equal(
            np.asarray(a.words[name]), np.asarray(b.words[name]),
            err_msg=f"packed word {name!r} differs",
        )
    np.testing.assert_array_equal(np.asarray(a.tick), np.asarray(b.tick))


def _mutate_one_leaf(base, donor, idx):
    leaves, treedef = jax.tree_util.tree_flatten(base)
    leaves[idx] = jax.tree_util.tree_flatten(donor)[0][idx]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _fuzz_cfgs(protocol):
    """The fuzz domain: the protocol's bench config, plus (multipaxos) a
    log_len that is NOT a multiple of the 4-entry stream group, so the
    delta repack is exercised on a partial tail group too."""
    cfgs = [_cfg(protocol, n_inst=64)]
    if protocol == "multipaxos":
        cfgs.append(dataclasses.replace(cfgs[0], log_len=6))
    return cfgs


@pytest.mark.parametrize("protocol", PROTOCOLS)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_pack_delta_matches_full_pack_on_written_mutations(protocol, seed):
    """Property (the pack_delta contract): start from a random in-range
    state's packed words, mutate ONE leaf inside the declared write-set to
    another random in-range value, and ``pack_delta`` must equal a full
    ``pack`` of the mutated state, bit-exact — per leaf kind this covers
    the carried-word passthrough, the all-written rebuild, the mixed-word
    ``set_field`` merge, and the stream repack (partial groups included)."""
    for cfg in _fuzz_cfgs(protocol):
        base, codec = _random_in_range_state(protocol, cfg, seed)
        donor, _ = _random_in_range_state(protocol, cfg, seed + 100)
        pst = codec.pack(base)
        kinds = _leaf_kinds(codec)
        writable = [
            i for i in range(codec.n_leaves)
            if kinds[i][0] in ("slot", "stream", "pt")
            and codec.is_written(codec.paths[i])
        ]
        assert writable, "write-set unexpectedly empty"
        streams = [i for i in writable if kinds[i][0] == "stream"]
        rng = np.random.default_rng(10_000 + seed)
        picks = set(streams) | set(
            rng.choice(writable, size=min(8, len(writable)), replace=False)
        )
        for idx in sorted(picks):
            mutated = _mutate_one_leaf(base, donor, idx)
            _packed_words_bitexact(
                codec.pack_delta(pst, mutated), codec.pack(mutated)
            )
        # Multi-leaf mutation (every written leaf at once) holds too.
        every = base
        for idx in writable:
            every = _mutate_one_leaf(every, donor, idx)
        _packed_words_bitexact(codec.pack_delta(pst, every), codec.pack(every))


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_pack_delta_drops_write_outside_declared_set(protocol, monkeypatch):
    """Planted violation: mutating a word/stream leaf OUTSIDE the declared
    write-set must be dropped by ``pack_delta`` (the carried word passes
    through untouched) — the failure mode ``audit_write_set`` exists to
    catch at trace time before it can corrupt a campaign.

    paxos/fastpaxos/raftcore exercise their REAL exclusion
    (``proposer.own_val``); multipaxos's real exclusion (``base``) is a
    passthrough leaf — outside pack_delta's merge machinery, guarded by the
    audit alone — so its planted case narrows the cached codec's write-set
    (monkeypatch, restored at teardown) to un-declare the learner leaves."""
    cfg = _cfg(protocol, n_inst=64)
    base, codec = _random_in_range_state(protocol, cfg, 7)
    donor, _ = _random_in_range_state(protocol, cfg, 107)
    if protocol == "multipaxos":
        monkeypatch.setattr(
            codec, "writes",
            tuple(w for w in codec.writes if not w.startswith("learner")),
        )
        unwritten_path = next(
            p for p in codec.paths if p.startswith("learner.")
        )
    else:
        unwritten_path = "proposer.own_val"
    assert not codec.is_written(unwritten_path)
    idx = codec.paths.index(unwritten_path)
    mutated = _mutate_one_leaf(base, donor, idx)
    # Non-vacuity: the mutation really changed the leaf's value.
    assert not np.array_equal(
        np.asarray(jax.tree_util.tree_flatten(base)[0][idx]),
        np.asarray(jax.tree_util.tree_flatten(mutated)[0][idx]),
    )
    pst = codec.pack(base)
    delta = codec.pack_delta(pst, mutated)
    # The out-of-set write is dropped: delta equals the ORIGINAL packing...
    _packed_words_bitexact(delta, codec.pack(base))
    # ...and differs from a full pack of the mutated state (which would
    # have carried the rogue write through).
    full = codec.pack(mutated)
    assert any(
        not np.array_equal(np.asarray(delta.words[n]), np.asarray(full.words[n]))
        for n in delta.words
    )


def test_signed_negative_roundtrip():
    """Signed fields (timers, chosen_tick sentinels) keep negatives exact."""
    cfg = _cfg("paxos", n_inst=8)
    state = init_state(cfg)
    codec = bitops.codec_for("paxos", state)
    timer = jnp.full(state.proposer.timer.shape, -1, jnp.int32)
    st = dataclasses.replace(
        state, proposer=dataclasses.replace(state.proposer, timer=timer)
    )
    rt = codec.unpack(codec.pack(st))
    np.testing.assert_array_equal(np.asarray(rt.proposer.timer), -1)


# ---------------------------------------------------------------------------
# Primitive helpers.


def test_shr_logical_matches_uint_semantics():
    x = jnp.asarray([-1, -(1 << 31), 123, 0], jnp.int32)
    for k in (0, 1, 7, 13, 31):
        want = (np.asarray(x).astype(np.uint32) >> k).astype(np.int32)
        np.testing.assert_array_equal(
            np.asarray(bitops.shr_logical(x, k)), want
        )


def test_pack_unpack_set_field():
    w = bitops.pack_word([(jnp.int32(5), 0, 4), (jnp.int32(9), 4, 5)])
    assert int(bitops.unpack_field(w, 0, 4)) == 5
    assert int(bitops.unpack_field(w, 4, 5)) == 9
    w2 = bitops.set_field(w, jnp.int32(3), 4, 5)
    assert int(bitops.unpack_field(w2, 4, 5)) == 3
    assert int(bitops.unpack_field(w2, 0, 4)) == 5  # neighbor untouched
    # Overflow masks: a 4-bit field packed with 16+2 reads back as 2.
    w3 = bitops.set_field(w, jnp.int32(18), 0, 4)
    assert int(bitops.unpack_field(w3, 0, 4)) == 2


def test_bv_dense_transcode_roundtrip():
    rng = np.random.default_rng(0)
    bal = rng.integers(0, 1 << 11, (4, 64))
    val = rng.integers(0, 1 << 13, (4, 64))
    bv = jnp.asarray((bal << 16) | val, jnp.int32)
    dense = bitops.bv_to_dense(bv, 11, 13)
    assert int(jnp.max(dense)) < (1 << 24)
    np.testing.assert_array_equal(
        np.asarray(bitops.dense_to_bv(dense, 11, 13)), np.asarray(bv)
    )


@pytest.mark.parametrize("length", [1, 2, 3, 4, 5, 7, 8, 9, 16])
def test_stream_pack_partial_groups(length):
    """4-entries->3-words stream codec, including every partial-group tail."""
    rng = np.random.default_rng(length)
    bv = np.asarray(_random_bv(rng, (2, length, 32), 11, 13))
    packed = bitops._stream_pack(jnp.asarray(bv), 11, 13)
    assert packed.shape == (2, bitops.stream_words(length), 32)
    out = bitops._stream_unpack(packed, 11, 13, length)
    np.testing.assert_array_equal(np.asarray(out), bv)


# ---------------------------------------------------------------------------
# Codec structure: auto-split, PackedState pytree, byte accounting.


def test_word_autosplit_on_wide_lt_mask():
    """paxos ``lt`` = lt_bal(15) + lt_val(12) + lt_mask(n_acc): n_acc=5 fits
    one 32-bit word; n_acc=7 overflows and splits to lt_0/lt_1 — and both
    resolutions roundtrip (the split is a codec detail, not a layout
    change, so layout_fields is identical for both)."""
    names = {}
    for n_acc in (5, 7):
        cfg = SimConfig(n_inst=8, n_prop=2, n_acc=n_acc, protocol="paxos")
        state = init_state(cfg)
        codec = bitops.codec_for("paxos", state)
        names[n_acc] = {w.name for w in codec.words}
        _assert_trees_bitexact(codec.unpack(codec.pack(state)), state)
    assert "lt" in names[5] and "lt_0" not in names[5]
    assert "lt_0" in names[7] and "lt_1" in names[7] and "lt" not in names[7]


def test_packed_state_pytree_contract():
    """Flatten order is word arrays then tick LAST (the fused engine's
    single-scalar invariant), and treedef is stable across pack calls."""
    cfg = _cfg("paxos", n_inst=8)
    state = init_state(cfg)
    codec = bitops.codec_for("paxos", state)
    pst = codec.pack(state)
    leaves, treedef = jax.tree_util.tree_flatten(pst)
    assert leaves[-1].ndim == 0  # tick
    assert all(l.ndim > 0 for l in leaves[:-1])
    assert treedef == jax.tree_util.tree_flatten(codec.pack(state))[1]
    assert pst.word("acc").shape == state.acceptor.promised.shape
    assert int(pst.tick) == int(state.tick)


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_packed_bytes_reduced_at_least_30pct(protocol):
    """The acceptance floor: packed VMEM bytes/lane down >= 30% vs the
    one-int32-per-field representation, every protocol."""
    cfg = _cfg(protocol, n_inst=64)
    state = init_state(cfg)
    codec = bitops.codec_for(protocol, state)
    packed = codec.bytes_per_lane(state)
    unpacked = bitops.unpacked_bytes_per_lane(state)
    assert packed <= 0.7 * unpacked, (protocol, packed, unpacked)


def test_codec_cache_identity():
    cfg = _cfg("multipaxos", n_inst=64)
    s1, s2 = init_state(cfg), init_state(cfg)
    assert bitops.codec_for("multipaxos", s1) is bitops.codec_for(
        "multipaxos", s2
    )


# ---------------------------------------------------------------------------
# VMEM estimator: packed tables size the fused block.


def test_estimator_raises_multipaxos_block():
    """The headline win: multipaxos's packed footprint (904 B/lane at
    config3) lets the estimated block rise from the pre-packing 128 to
    >= 256 — and the static default in fused_fns is pinned to exactly the
    estimator's output, so the two can't silently diverge."""
    from paxos_tpu.kernels.fused_tick import (
        estimate_block, fused_fns, packed_fns,
    )

    for cfg in (config3_multipaxos(n_inst=64), config3_long(n_inst=64)):
        est = estimate_block("multipaxos", init_state(cfg))
        assert est >= 256
        assert est == fused_fns("multipaxos")[2] == packed_fns("multipaxos")[2]


def test_estimator_keeps_paxos_at_default():
    from paxos_tpu.kernels.fused_tick import (
        DEFAULT_BLOCK, estimate_block, fused_fns,
    )

    for protocol in ("paxos", "fastpaxos", "raftcore"):
        cfg = _cfg(protocol, n_inst=64)
        est = estimate_block(protocol, init_state(cfg))
        assert est == DEFAULT_BLOCK == fused_fns(protocol)[2]


def test_block_for_bytes_budget_halving():
    from paxos_tpu.kernels.fused_tick import (
        VMEM_STATE_BUDGET, block_for_bytes,
    )

    assert block_for_bytes(904.0) == 256  # config3-multipaxos packed
    assert 512 * 904.0 > VMEM_STATE_BUDGET  # 512 really would overflow
    assert block_for_bytes(356.0) == 1024  # config2-paxos packed
    assert block_for_bytes(1e9) == 128  # floor holds however heavy the lane


def test_degrade_warning_still_names_constraint():
    """`fit_block` reconciles the estimated block with n_inst divisibility
    and must still say WHICH constraint degraded the request and to what."""
    from paxos_tpu.kernels.fused_tick import fit_block

    with pytest.warns(
        UserWarning, match=r"block=256 does not tile n_inst=1920"
    ):
        assert fit_block(256, 1920) == 128


# ---------------------------------------------------------------------------
# Layout-version guard (audit satellite): goldens catch silent re-binning.


def test_layout_goldens_match_live_tables():
    from paxos_tpu.analysis import goldens
    from paxos_tpu.analysis.structure import audit_layout

    for protocol in PROTOCOLS:
        assert goldens.LAYOUT_GOLDENS[protocol]["version"] == (
            bitops.layout_version(protocol)
        )
        assert goldens.LAYOUT_GOLDENS[protocol]["fields"] == (
            bitops.layout_fields(protocol)
        )
        assert audit_layout(protocol) == []


def test_layout_mutation_without_version_bump_fails_audit(monkeypatch):
    """Planted mutation: shrink paxos requests.bal 15->14 without touching
    the version — the audit must fail and NAME the field."""
    from paxos_tpu.analysis.structure import audit_layout
    from paxos_tpu.core import state as state_mod

    mutated = []
    for e in state_mod.PAXOS_LAYOUT:
        if isinstance(e, bitops.Word) and e.name == "req":
            fields = [
                bitops.F(f.path, 14, signed=f.signed, bool_=f.bool_, bv=f.bv)
                if f.path == "requests.bal" else f
                for f in e.fields
            ]
            mutated.append(bitops.Word("req", *fields))
        else:
            mutated.append(e)
    monkeypatch.setattr(state_mod, "PAXOS_LAYOUT", tuple(mutated))

    findings = audit_layout("paxos")
    assert len(findings) == 1
    msg = findings[0].message
    assert "requests.bal" in msg
    assert "WITHOUT a version bump" in msg

    # Same mutation WITH a bump: still a finding (stale goldens need a
    # re-record), but it instructs the re-record instead of failing the bump.
    monkeypatch.setattr(
        state_mod, "PAXOS_LAYOUT_VERSION", "paxos-packed-v2-test"
    )
    findings = audit_layout("paxos")
    assert len(findings) == 1
    assert "re-record" in findings[0].message
    assert "requests.bal" in findings[0].message


def test_layout_version_folds_into_fingerprint(monkeypatch):
    """A version bump alone must re-key the config fingerprint — that is
    how checkpoints recorded under an old layout stop matching."""
    from paxos_tpu.core import state as state_mod

    cfg = config2_dueling_drop(n_inst=64)
    before = cfg.fingerprint()
    monkeypatch.setattr(
        state_mod, "PAXOS_LAYOUT_VERSION", "paxos-packed-v2-test"
    )
    assert cfg.fingerprint() != before


# ---------------------------------------------------------------------------
# Ticks-per-campaign bound (REVIEW fix): a budget beyond the packed
# chosen_tick width would wrap latency measurements negative on the fused
# engine — the guard fails at argument time, where the budget is accepted.


def test_tick_budget_bound_per_protocol():
    from paxos_tpu.harness.run import check_tick_budget

    # 18-bit signed for Multi-Paxos, 19-bit signed for the others.
    check_tick_budget("multipaxos", (1 << 17) - 1)
    with pytest.raises(ValueError, match="chosen_tick"):
        check_tick_budget("multipaxos", 1 << 17)
    for protocol in ("paxos", "fastpaxos", "raftcore"):
        check_tick_budget(protocol, (1 << 18) - 1)
        with pytest.raises(ValueError, match="chosen_tick"):
            check_tick_budget(protocol, 1 << 18)


def test_tick_budget_enforced_at_run_and_soak():
    from paxos_tpu.harness.run import run
    from paxos_tpu.harness.soak import soak

    cfg = config2_dueling_drop(n_inst=8, seed=0)
    with pytest.raises(ValueError, match="chosen_tick"):
        run(cfg, total_ticks=1 << 18)
    with pytest.raises(ValueError, match="chosen_tick"):
        run(cfg, until_all_chosen=True, max_ticks=1 << 18)
    with pytest.raises(ValueError, match="chosen_tick"):
        soak(cfg, target_rounds=1.0, ticks_per_seed=1 << 18)


def test_layout_field_width_lookup():
    bits, signed = bitops.layout_field_width("multipaxos", "learner.chosen_tick")
    assert (bits, signed) == (18, True)
    with pytest.raises(KeyError):
        bitops.layout_field_width("paxos", "no.such.field")
    with pytest.raises(ValueError, match="symbolic"):
        bitops.layout_field_width("paxos", "learner.lt_mask")


# ---------------------------------------------------------------------------
# Bench rows record what the ROW'S engine actually carries (REVIEW fix):
# packed codec bytes for fused rows, the unpacked pytree for xla rows.


def test_bench_state_bytes_match_engine():
    from bench import bench_case

    cfg = config2_dueling_drop(n_inst=128, seed=0)
    row = bench_case(cfg, "xla", chunk=4, timed_chunks=1, repeats=1)
    state = init_state(cfg)
    unpacked = bitops.unpacked_bytes_per_lane(state)
    packed = bitops.codec_for("paxos", state).bytes_per_lane(state)
    assert row["state_bytes_per_lane"] == pytest.approx(unpacked)
    assert unpacked > packed  # the xla row must not report the packed figure
