"""Checkpoint/resume: a resumed run must be bit-identical to an uninterrupted one."""

import jax
import jax.numpy as jnp

from paxos_tpu.harness import checkpoint as ckpt
from paxos_tpu.harness.config import config2_dueling_drop
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state, run_chunk


def test_resume_bit_identical(tmp_path):
    cfg = config2_dueling_drop(n_inst=512, seed=8)
    step = get_step_fn(cfg.protocol)
    key = base_key(cfg)

    # Uninterrupted: 48 ticks.
    s_full = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 48, step)

    # Interrupted: 24 ticks -> checkpoint -> restore -> 24 more.
    s_half = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 24, step)
    ckpt.save(tmp_path / "snap", s_half, init_plan(cfg), cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / "snap")
    assert cfg_rest == cfg  # config roundtrips exactly (incl. fault config)
    assert int(s_rest.tick) == 24
    s_resumed = run_chunk(s_rest, base_key(cfg_rest), plan_rest, cfg_rest.fault, 24, step)

    for a, b in zip(jax.tree.leaves(s_full), jax.tree.leaves(s_resumed)):
        assert jnp.array_equal(a, b), "resume diverged from uninterrupted run"


def test_restore_preserves_pytree_types(tmp_path):
    cfg = config2_dueling_drop(n_inst=64, seed=1)
    state, plan = init_state(cfg), init_plan(cfg)
    ckpt.save(tmp_path / "s", state, plan, cfg)
    s2, p2, c2 = ckpt.restore(tmp_path / "s")
    assert type(s2) is type(state)
    assert s2.acceptor.promised.dtype == jnp.int32
    assert p2.equivocate.dtype == jnp.bool_


def test_resume_multipaxos_bit_identical(tmp_path):
    """VERDICT r2 missing#3: resume exactness for a MultiPaxosState (the
    most state-complex pytree: per-slot logs, promise/accepted buffers,
    lease clocks)."""
    from paxos_tpu.harness.config import config3_multipaxos

    cfg = config3_multipaxos(n_inst=128, seed=6)
    step = get_step_fn(cfg.protocol)
    key = base_key(cfg)

    s_full = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 48, step)

    s_half = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 24, step)
    ckpt.save(tmp_path / "snap", s_half, init_plan(cfg), cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / "snap")
    assert cfg_rest == cfg
    assert int(s_rest.tick) == 24
    s_resumed = run_chunk(s_rest, base_key(cfg_rest), plan_rest, cfg_rest.fault, 24, step)

    for a, b in zip(jax.tree.leaves(s_full), jax.tree.leaves(s_resumed)):
        assert jnp.array_equal(a, b), "MP resume diverged from uninterrupted run"


def _longlog_resume_case(tmp_path, engine):
    """config3long save/restore mid-campaign with a rebased window
    (base > 0), then continue: must bit-equal an uninterrupted run.
    Compaction cadence = chunk cadence, preserved across the resume."""
    import numpy as np

    from paxos_tpu.harness.config import config3_long
    from paxos_tpu.harness.run import make_advance

    cfg = config3_long(n_inst=32, log_total=10, window=4, seed=5)
    plan = init_plan(cfg)
    adv = make_advance(cfg, plan, engine, compact=True)

    s_full = init_state(cfg)
    for _ in range(6):
        s_full = adv(s_full, 8)

    s_half = init_state(cfg)
    for _ in range(3):
        s_half = adv(s_half, 8)
    # The interesting case: the saved window is already rebased.
    assert (np.asarray(jax.device_get(s_half.base)) > 0).any(), (
        "vacuous: no instance compacted before the checkpoint"
    )
    ckpt.save(tmp_path / f"snap-{engine}", s_half, plan, cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / f"snap-{engine}")
    assert cfg_rest == cfg
    assert jnp.array_equal(s_rest.base, s_half.base)
    adv2 = make_advance(cfg_rest, plan_rest, engine, compact=True)
    s_resumed = s_rest
    for _ in range(3):
        s_resumed = adv2(s_resumed, 8)

    for a, b in zip(jax.tree.leaves(s_full), jax.tree.leaves(s_resumed)):
        assert jnp.array_equal(a, b), (
            f"long-log resume ({engine}) diverged from uninterrupted run"
        )


def test_resume_longlog_xla_bit_identical(tmp_path):
    _longlog_resume_case(tmp_path, "xla")


def test_resume_longlog_fused_bit_identical(tmp_path):
    _longlog_resume_case(tmp_path, "fused")


def test_checkpoint_resume_fused_stream_exact(tmp_path):
    """Resume replays the fused engine's counter-PRNG stream bit-exactly:
    24 ticks -> save -> restore -> 24 ticks == uninterrupted 48 ticks.

    (Stream seeds hash (seed, tick, block), so resume needs only the saved
    tick counter; runs the non-Pallas reference of the fused stream.)
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paxos_tpu.harness import checkpoint as ckpt
    from paxos_tpu.harness.config import config2_dueling_drop
    from paxos_tpu.harness.run import init_plan, init_state
    from paxos_tpu.kernels.fused_tick import reference_chunk

    cfg = config2_dueling_drop(n_inst=128, seed=4)
    plan = init_plan(cfg)
    seed = jnp.int32(cfg.seed)

    full = reference_chunk(init_state(cfg), seed, plan, cfg.fault, 48)

    half = reference_chunk(init_state(cfg), seed, plan, cfg.fault, 24)
    ckpt.save(tmp_path / "snap", half, plan, cfg)
    restored, rplan, rcfg = ckpt.restore(tmp_path / "snap")
    assert rcfg == cfg
    assert int(restored.tick) == 24
    resumed = reference_chunk(restored, seed, rplan, rcfg.fault, 24)

    la, _ = jax.tree.flatten(full)
    lb, _ = jax.tree.flatten(resumed)
    bad = [
        i
        for i, (a, b) in enumerate(zip(la, lb))
        if not np.array_equal(np.asarray(a), np.asarray(b))
    ]
    assert bad == []
