"""Checkpoint/resume: a resumed run must be bit-identical to an uninterrupted one."""

import jax
import jax.numpy as jnp

from paxos_tpu.harness import checkpoint as ckpt
from paxos_tpu.harness.config import config2_dueling_drop
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state, run_chunk


def test_resume_bit_identical(tmp_path):
    cfg = config2_dueling_drop(n_inst=512, seed=8)
    step = get_step_fn(cfg.protocol)
    key = base_key(cfg)

    # Uninterrupted: 48 ticks.
    s_full = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 48, step)

    # Interrupted: 24 ticks -> checkpoint -> restore -> 24 more.
    s_half = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 24, step)
    ckpt.save(tmp_path / "snap", s_half, init_plan(cfg), cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / "snap")
    assert cfg_rest == cfg  # config roundtrips exactly (incl. fault config)
    assert int(s_rest.tick) == 24
    s_resumed = run_chunk(s_rest, base_key(cfg_rest), plan_rest, cfg_rest.fault, 24, step)

    for a, b in zip(jax.tree.leaves(s_full), jax.tree.leaves(s_resumed)):
        assert jnp.array_equal(a, b), "resume diverged from uninterrupted run"


def test_restore_preserves_pytree_types(tmp_path):
    cfg = config2_dueling_drop(n_inst=64, seed=1)
    state, plan = init_state(cfg), init_plan(cfg)
    ckpt.save(tmp_path / "s", state, plan, cfg)
    s2, p2, c2 = ckpt.restore(tmp_path / "s")
    assert type(s2) is type(state)
    assert s2.acceptor.promised.dtype == jnp.int32
    assert p2.equivocate.dtype == jnp.bool_


def test_resume_multipaxos_bit_identical(tmp_path):
    """VERDICT r2 missing#3: resume exactness for a MultiPaxosState (the
    most state-complex pytree: per-slot logs, promise/accepted buffers,
    lease clocks)."""
    from paxos_tpu.harness.config import config3_multipaxos

    cfg = config3_multipaxos(n_inst=128, seed=6)
    step = get_step_fn(cfg.protocol)
    key = base_key(cfg)

    s_full = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 48, step)

    s_half = run_chunk(init_state(cfg), key, init_plan(cfg), cfg.fault, 24, step)
    ckpt.save(tmp_path / "snap", s_half, init_plan(cfg), cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / "snap")
    assert cfg_rest == cfg
    assert int(s_rest.tick) == 24
    s_resumed = run_chunk(s_rest, base_key(cfg_rest), plan_rest, cfg_rest.fault, 24, step)

    for a, b in zip(jax.tree.leaves(s_full), jax.tree.leaves(s_resumed)):
        assert jnp.array_equal(a, b), "MP resume diverged from uninterrupted run"


def _longlog_resume_case(tmp_path, engine):
    """config3long save/restore mid-campaign with a rebased window
    (base > 0), then continue: must bit-equal an uninterrupted run.
    Compaction cadence = chunk cadence, preserved across the resume."""
    import numpy as np

    from paxos_tpu.harness.config import config3_long
    from paxos_tpu.harness.run import make_advance

    cfg = config3_long(n_inst=32, log_total=10, window=4, seed=5)
    plan = init_plan(cfg)
    adv = make_advance(cfg, plan, engine, compact=True)

    s_full = init_state(cfg)
    for _ in range(6):
        s_full = adv(s_full, 8)

    s_half = init_state(cfg)
    for _ in range(3):
        s_half = adv(s_half, 8)
    # The interesting case: the saved window is already rebased.
    assert (np.asarray(jax.device_get(s_half.base)) > 0).any(), (
        "vacuous: no instance compacted before the checkpoint"
    )
    ckpt.save(tmp_path / f"snap-{engine}", s_half, plan, cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / f"snap-{engine}")
    assert cfg_rest == cfg
    assert jnp.array_equal(s_rest.base, s_half.base)
    adv2 = make_advance(cfg_rest, plan_rest, engine, compact=True)
    s_resumed = s_rest
    for _ in range(3):
        s_resumed = adv2(s_resumed, 8)

    for a, b in zip(jax.tree.leaves(s_full), jax.tree.leaves(s_resumed)):
        assert jnp.array_equal(a, b), (
            f"long-log resume ({engine}) diverged from uninterrupted run"
        )


def test_resume_longlog_xla_bit_identical(tmp_path):
    _longlog_resume_case(tmp_path, "xla")


def test_resume_longlog_fused_bit_identical(tmp_path):
    _longlog_resume_case(tmp_path, "fused")


def _reshard_resume_case(tmp_path, engine):
    """VERDICT r3 #5: a run checkpointed on N devices resumes on M.

    Save from an 8-device sharded campaign mid-run, restore (arrays land
    host-side, unsharded), then resume (a) on a single device and (b)
    re-sharded onto a 4-device sub-mesh.  Every resumption must bit-equal
    the uninterrupted 8-device run — the elastic-recovery contract
    ``harness/checkpoint.py`` promises ("checkpointed on N chips can
    resume on M").

    Stream note (fused): the counter-PRNG keys on GLOBAL block ids
    (``axis_index * blocks_per_shard + grid position``), so with a fixed
    block that divides every local shard the id sequence 0..n_blocks-1 is
    mesh-invariant — which is exactly what makes N->M resumption exact.
    """
    import numpy as np

    from paxos_tpu.harness.run import make_advance
    from paxos_tpu.parallel.mesh import make_mesh, shard_pytree
    from paxos_tpu.utils.trees import assert_trees_equal

    cfg = config2_dueling_drop(n_inst=64, seed=11)
    block = 8  # divides the local shard on 8, 4, and 1 device(s)
    plan = init_plan(cfg)

    def make_adv(mesh):
        p = plan if mesh is None else shard_pytree(plan, mesh, cfg.n_inst)
        if engine == "fused":
            return make_advance(cfg, p, "fused", block=block, mesh=mesh)
        return make_advance(cfg, p, "xla")

    mesh8 = make_mesh()
    assert mesh8.devices.size == 8

    # Uninterrupted: 48 ticks, sharded over all 8 devices.
    s_full = make_adv(mesh8)(
        shard_pytree(init_state(cfg), mesh8, cfg.n_inst), 48
    )

    # Interrupted at 24 ticks on 8 devices -> save -> restore.
    s_half = make_adv(mesh8)(
        shard_pytree(init_state(cfg), mesh8, cfg.n_inst), 24
    )
    ckpt.save(tmp_path / f"snap-{engine}", s_half, plan, cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / f"snap-{engine}")
    assert cfg_rest == cfg
    assert int(np.asarray(s_rest.tick)) == 24

    # (a) resume on ONE device (restore's default placement).  The restored
    # host tree is re-used for (b), so hand the engine its own device copy
    # (the fused path donates its input).
    s_one = make_advance(cfg_rest, plan_rest, engine,
                         block=block if engine == "fused" else None)(
        jax.tree.map(jnp.asarray, s_rest), 24
    )
    assert_trees_equal(s_full, s_one,
                       f"1-device resume ({engine}) diverged from 8-device run")

    # (b) resume re-sharded onto a DIFFERENT topology: a 4-device sub-mesh.
    mesh4 = make_mesh(jax.devices()[:4])
    s4 = shard_pytree(s_rest, mesh4, cfg.n_inst)
    adv4 = (make_advance(cfg_rest, shard_pytree(plan_rest, mesh4, cfg.n_inst),
                         "fused", block=block, mesh=mesh4)
            if engine == "fused"
            else make_advance(cfg_rest,
                              shard_pytree(plan_rest, mesh4, cfg.n_inst),
                              "xla"))
    s_re4 = adv4(s4, 24)
    assert len(jax.tree.leaves(s_re4)[0].sharding.device_set) == 4
    assert_trees_equal(s_full, s_re4,
                       f"4-device resume ({engine}) diverged from 8-device run")


def test_reshard_resume_xla_8_to_1_and_4(tmp_path):
    _reshard_resume_case(tmp_path, "xla")


def test_reshard_resume_fused_8_to_1_and_4(tmp_path):
    _reshard_resume_case(tmp_path, "fused")


def test_reshard_resume_longlog_fused_with_base(tmp_path):
    """The elastic-recovery case VERDICT r3 #5 calls out specifically: a
    config3long campaign saved SHARDED (8 devices) with already-rebased
    windows (base > 0), restored onto a 4-device mesh and onto one device,
    compaction cadence preserved — all bit-equal the uninterrupted
    8-device run."""
    import numpy as np

    from paxos_tpu.harness.config import config3_long
    from paxos_tpu.harness.run import make_advance
    from paxos_tpu.parallel.mesh import make_mesh, shard_pytree
    from paxos_tpu.utils.trees import assert_trees_equal

    cfg = config3_long(n_inst=32, log_total=10, window=4, seed=5)
    block = 4  # divides local shards on 8 devices (4), 4 devices (8), 1 (32)
    plan = init_plan(cfg)
    mesh8 = make_mesh()

    def adv8(state, n):
        return make_advance(
            cfg, shard_pytree(plan, mesh8, cfg.n_inst), "fused",
            block=block, compact=True, mesh=mesh8,
        )(state, n)

    s_full = shard_pytree(init_state(cfg), mesh8, cfg.n_inst)
    for _ in range(6):
        s_full = adv8(s_full, 8)

    s_half = shard_pytree(init_state(cfg), mesh8, cfg.n_inst)
    for _ in range(3):
        s_half = adv8(s_half, 8)
    assert (np.asarray(jax.device_get(s_half.base)) > 0).any(), (
        "vacuous: no instance compacted before the checkpoint"
    )
    ckpt.save(tmp_path / "snap-ll", s_half, plan, cfg)
    s_rest, plan_rest, cfg_rest = ckpt.restore(tmp_path / "snap-ll")
    assert cfg_rest == cfg

    # One device.
    adv1 = make_advance(cfg_rest, plan_rest, "fused", block=block, compact=True)
    s_one = jax.tree.map(jnp.asarray, s_rest)
    for _ in range(3):
        s_one = adv1(s_one, 8)
    assert_trees_equal(s_full, s_one,
                       "long-log 1-device resume diverged from 8-device run")

    # Four devices.
    mesh4 = make_mesh(jax.devices()[:4])
    adv4 = make_advance(
        cfg_rest, shard_pytree(plan_rest, mesh4, cfg.n_inst), "fused",
        block=block, compact=True, mesh=mesh4,
    )
    s_re4 = shard_pytree(s_rest, mesh4, cfg.n_inst)
    for _ in range(3):
        s_re4 = adv4(s_re4, 8)
    assert_trees_equal(s_full, s_re4,
                       "long-log 4-device resume diverged from 8-device run")


def test_stream_lineage_guard(tmp_path):
    """VERDICT r4 weak#3: the fused block is stream-relevant (schedules key
    on (seed, tick, block)), so a checkpoint written under block=128 (the
    pre-packing MP default) must REFUSE to resume under the current 256
    default — same seed, different schedule — unless the saved block is
    passed explicitly."""
    import warnings

    import pytest

    from paxos_tpu.harness.config import config3_multipaxos

    cfg = config3_multipaxos(n_inst=64, seed=3)
    state, plan = init_state(cfg), init_plan(cfg)

    ckpt.save(tmp_path / "s", state, plan, cfg, engine="fused", block=128)
    # Mismatched effective block (MP default is 256) -> refused.
    with pytest.raises(ValueError, match="DIFFERENT schedule"):
        ckpt.restore(tmp_path / "s", engine="fused")
    # Mismatched engine -> refused (XLA streams are keyed differently).
    with pytest.raises(ValueError, match="DIFFERENT schedule"):
        ckpt.restore(tmp_path / "s", engine="xla")
    # Matching lineage -> restores.
    s2, _, c2 = ckpt.restore(tmp_path / "s", engine="fused", block=128)
    assert c2 == cfg

    # Saved under the protocol default (block=None resolves at SAVE time),
    # resumed under the default -> matches.
    ckpt.save(tmp_path / "d", state, plan, cfg, engine="fused")
    ckpt.restore(tmp_path / "d", engine="fused")

    # Pre-stream-metadata snapshot: warn, not refuse (legacy compat).
    ckpt.save(tmp_path / "legacy", state, plan, cfg)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ckpt.restore(tmp_path / "legacy", engine="fused")
    assert any("stream metadata" in str(x.message) for x in w)
    # And a verification-free restore stays silent and unguarded.
    ckpt.restore(tmp_path / "legacy")


def test_checkpoint_resume_fused_stream_exact(tmp_path):
    """Resume replays the fused engine's counter-PRNG stream bit-exactly:
    24 ticks -> save -> restore -> 24 ticks == uninterrupted 48 ticks.

    (Stream seeds hash (seed, tick, block), so resume needs only the saved
    tick counter; runs the non-Pallas reference of the fused stream.)
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paxos_tpu.harness import checkpoint as ckpt
    from paxos_tpu.harness.config import config2_dueling_drop
    from paxos_tpu.harness.run import init_plan, init_state
    from paxos_tpu.kernels.fused_tick import reference_chunk

    cfg = config2_dueling_drop(n_inst=128, seed=4)
    plan = init_plan(cfg)
    seed = jnp.int32(cfg.seed)

    full = reference_chunk(init_state(cfg), seed, plan, cfg.fault, 48)

    half = reference_chunk(init_state(cfg), seed, plan, cfg.fault, 24)
    ckpt.save(tmp_path / "snap", half, plan, cfg)
    restored, rplan, rcfg = ckpt.restore(tmp_path / "snap")
    assert rcfg == cfg
    assert int(restored.tick) == 24
    resumed = reference_chunk(restored, seed, rplan, rcfg.fault, 24)

    la, _ = jax.tree.flatten(full)
    lb, _ = jax.tree.flatten(resumed)
    bad = [
        i
        for i, (a, b) in enumerate(zip(la, lb))
        if not np.array_equal(np.asarray(a), np.asarray(b))
    ]
    assert bad == []
