"""CLI and observability: run subcommand, JSONL metrics, liveness stats."""

import json

import jax.numpy as jnp

from paxos_tpu.check.liveness import chosen_tick_histogram, decided_by, stuck_mask
from paxos_tpu.harness.cli import main
from paxos_tpu.harness.config import config1_no_faults
from paxos_tpu.harness.run import run


def test_cli_run_writes_metrics_and_reports(tmp_path, capsys):
    log = tmp_path / "m.jsonl"
    rc = main([
        "run", "--config", "config1", "--n-inst", "256", "--ticks", "32",
        "--chunk", "16", "--log", str(log), "--until-all-chosen",
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["violations"] == 0
    assert report["chosen_frac"] == 1.0
    events = [json.loads(l) for l in log.read_text().splitlines()]
    kinds = [e["event"] for e in events]
    assert kinds[0] == "start" and kinds[-1] == "final"
    assert "chunk" in kinds


def test_cli_checkpoint_resume_roundtrip(tmp_path, capsys):
    ck = tmp_path / "ck"
    rc = main([
        "run", "--config", "config1", "--n-inst", "128", "--ticks", "16",
        "--chunk", "8", "--checkpoint-dir", str(ck),
    ])
    assert rc == 0
    capsys.readouterr()
    rc = main(["run", "--resume", str(ck), "--ticks", "16", "--chunk", "8"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["ticks"] == 32  # resumed at 16, ran 16 more


def test_liveness_stats():
    _, state = run(
        config1_no_faults(n_inst=256, seed=2),
        until_all_chosen=True,
        max_ticks=64,
        return_state=True,
    )
    lrn = state.learner
    assert float(decided_by(lrn, 64)) == 1.0
    assert float(decided_by(lrn, 0)) < 1.0
    hist = chosen_tick_histogram(lrn, n_bins=8, bin_width=8)
    assert int(hist.sum()) == 256
    assert not bool(stuck_mask(lrn, 64, state.tick).any())


def test_liveness_report_in_run_and_cli(capsys):
    """VERDICT r1 weak#2: the liveness block must reach user-facing reports."""
    # Library surface: run(liveness=True) appends the block.
    report = run(
        config1_no_faults(n_inst=256, seed=2),
        until_all_chosen=True,
        max_ticks=64,
        liveness=True,
    )
    curve = report["decided_by_curve"]
    fracs = [f for _, f in curve]
    assert fracs == sorted(fracs), "decided-by curve must be monotone"
    assert fracs[-1] == report["chosen_frac"] == 1.0
    assert sum(report["chosen_tick_hist"]) == 256
    assert report["stuck_lanes"] == 0
    assert report["hist_bin_width"] >= 1

    # CLI surface: --liveness lands the same keys in the printed JSON.
    rc = main([
        "run", "--config", "config2", "--n-inst", "128", "--seed", "3",
        "--ticks", "8", "--chunk", "8", "--liveness",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "decided_by_curve" in out and "stuck_lanes" in out
    # 8 ticks of config-2 dueling leaves stragglers: stuck lanes must show.
    assert out["stuck_lanes"] == round((1 - out["chosen_frac"]) * 128)
    # The last bin is reserved for undecided lanes — exactly the stuck count.
    assert out["chosen_tick_hist"][-1] == out["stuck_lanes"]


def test_liveness_report_multipaxos():
    """Shape-polymorphism: (L, I) Multi-Paxos learners count slot-lanes."""
    from paxos_tpu.harness.config import config3_multipaxos

    cfg = config3_multipaxos(n_inst=64, seed=1)
    report = run(cfg, total_ticks=48, liveness=True)
    assert sum(report["chosen_tick_hist"]) == cfg.log_len * 64
    assert report["stuck_lanes"] == round(
        (1 - report["chosen_frac"]) * cfg.log_len * 64
    )


def test_cli_check_subcommand(capsys):
    import json

    from paxos_tpu.harness.cli import main

    assert main(["--platform", "cpu", "check", "--max-round", "0"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["states"] > 3_000

    assert (
        main(["--platform", "cpu", "check", "--max-round", "0", "--unsafe-accept"])
        == 2
    )
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not out["ok"] and "invariant violated" in out["counterexample"]


def test_cli_check_fastpaxos(capsys):
    from paxos_tpu.harness.cli import main

    # Clean bounded space (tiny: both proposers fast-only).
    assert main([
        "--platform", "cpu", "check", "--protocol", "fastpaxos",
        "--n-acc", "4", "--max-round", "0",
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["states"] > 100

    # Injected wrong-recovery rule must produce a counterexample.
    assert main([
        "--platform", "cpu", "check", "--protocol", "fastpaxos",
        "--n-acc", "4", "--max-round", "1", "0", "--adopt-any",
    ]) == 2
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not out["ok"] and "invariant violated" in out["counterexample"]
