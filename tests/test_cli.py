"""CLI and observability: run subcommand, JSONL metrics, liveness stats."""

import json

import jax.numpy as jnp

from paxos_tpu.check.liveness import chosen_tick_histogram, decided_by, stuck_mask
from paxos_tpu.harness.cli import main
from paxos_tpu.harness.config import config1_no_faults
from paxos_tpu.harness.run import run


def test_cli_run_writes_metrics_and_reports(tmp_path, capsys):
    log = tmp_path / "m.jsonl"
    rc = main([
        "run", "--config", "config1", "--n-inst", "256", "--ticks", "32",
        "--chunk", "16", "--log", str(log), "--until-all-chosen",
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["violations"] == 0
    assert report["chosen_frac"] == 1.0
    events = [json.loads(l) for l in log.read_text().splitlines()]
    kinds = [e["event"] for e in events]
    assert kinds[0] == "start" and kinds[-1] == "final"
    assert "chunk" in kinds


def test_cli_checkpoint_resume_roundtrip(tmp_path, capsys):
    ck = tmp_path / "ck"
    rc = main([
        "run", "--config", "config1", "--n-inst", "128", "--ticks", "16",
        "--chunk", "8", "--checkpoint-dir", str(ck),
    ])
    assert rc == 0
    capsys.readouterr()
    rc = main(["run", "--resume", str(ck), "--ticks", "16", "--chunk", "8"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["ticks"] == 32  # resumed at 16, ran 16 more


def test_liveness_stats():
    _, state = run(
        config1_no_faults(n_inst=256, seed=2),
        until_all_chosen=True,
        max_ticks=64,
        return_state=True,
    )
    lrn = state.learner
    assert float(decided_by(lrn, 64)) == 1.0
    assert float(decided_by(lrn, 0)) < 1.0
    hist = chosen_tick_histogram(lrn, n_bins=8, bin_width=8)
    assert int(hist.sum()) == 256
    assert not bool(stuck_mask(lrn, 64, state.tick).any())


def test_liveness_report_in_run_and_cli(capsys):
    """VERDICT r1 weak#2: the liveness block must reach user-facing reports."""
    # Library surface: run(liveness=True) appends the block.
    report = run(
        config1_no_faults(n_inst=256, seed=2),
        until_all_chosen=True,
        max_ticks=64,
        liveness=True,
    )
    curve = report["decided_by_curve"]
    fracs = [f for _, f in curve]
    assert fracs == sorted(fracs), "decided-by curve must be monotone"
    assert fracs[-1] == report["chosen_frac"] == 1.0
    assert sum(report["chosen_tick_hist"]) == 256
    assert report["stuck_lanes"] == 0
    assert report["hist_bin_width"] >= 1

    # CLI surface: --liveness lands the same keys in the printed JSON.
    rc = main([
        "run", "--config", "config2", "--n-inst", "128", "--seed", "3",
        "--ticks", "8", "--chunk", "8", "--liveness",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "decided_by_curve" in out and "stuck_lanes" in out
    # 8 ticks of config-2 dueling leaves stragglers: stuck lanes must show.
    assert out["stuck_lanes"] == round((1 - out["chosen_frac"]) * 128)
    # The last bin is reserved for undecided lanes — exactly the stuck count.
    assert out["chosen_tick_hist"][-1] == out["stuck_lanes"]


def test_liveness_report_multipaxos():
    """Shape-polymorphism: (L, I) Multi-Paxos learners count slot-lanes."""
    from paxos_tpu.harness.config import config3_multipaxos

    cfg = config3_multipaxos(n_inst=64, seed=1)
    report = run(cfg, total_ticks=48, liveness=True)
    assert sum(report["chosen_tick_hist"]) == cfg.log_len * 64
    assert report["stuck_lanes"] == round(
        (1 - report["chosen_frac"]) * cfg.log_len * 64
    )


def test_cli_run_shard_longlog_smoke(tmp_path, capsys):
    """`run --shard --config config3long --engine xla` through argparse:
    the mesh event must record all 8 devices and the report must carry the
    long-log fields (cli.py's sharded long-log composition)."""
    log = tmp_path / "m.jsonl"
    rc = main([
        "run", "--config", "config3long", "--n-inst", "64", "--ticks", "16",
        "--chunk", "8", "--shard", "--log", str(log),
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["violations"] == 0
    assert report["log_total"] == 256  # config3long defaults
    assert "slots_replicated" in report
    events = [json.loads(l) for l in log.read_text().splitlines()]
    mesh_evts = [e for e in events if e["event"] == "mesh"]
    assert mesh_evts and mesh_evts[0]["devices"] == 8


def test_cli_check_subcommand(capsys):
    import json

    from paxos_tpu.harness.cli import main

    assert main(["--platform", "cpu", "check", "--max-round", "0"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["states"] > 3_000

    assert (
        main(["--platform", "cpu", "check", "--max-round", "0", "--unsafe-accept"])
        == 2
    )
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not out["ok"] and "invariant violated" in out["counterexample"]


def test_cli_sweep_member_configs(capsys):
    """fastpaxos/raftcore are runnable standalone (not only via `sweep`):
    the config5-* CLI names select one sweep member each."""
    for name, proto in (
        ("config5-fastpaxos", "fastpaxos"),
        ("config5-raftcore", "raftcore"),
    ):
        rc = main([
            "run", "--config", name, "--n-inst", "128", "--ticks", "32",
            "--chunk", "16",
        ])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert report["violations"] == 0
        assert report["chosen_frac"] > 0.0


def test_cli_trace_and_events_smoke(tmp_path, capsys):
    """VERDICT r2 weak#3: `--trace` and `--events` through the argparse
    path.  --trace must leave a profiler artifact in the logdir; --events
    must print per-chunk JSON records to stderr."""
    trace_dir = tmp_path / "trace"
    rc = main([
        "run", "--config", "config1", "--n-inst", "64", "--ticks", "16",
        "--chunk", "8", "--trace", str(trace_dir), "--events",
    ])
    assert rc == 0
    captured = capsys.readouterr()
    assert json.loads(captured.out.strip().splitlines()[-1])["violations"] == 0
    # Two chunks -> two event records, each valid JSON with the dump's keys.
    events = [
        json.loads(l) for l in captured.err.splitlines()
        if l.startswith("{")
    ]
    assert len(events) == 2
    assert all("chosen" in e and "round_max" in e for e in events)
    assert events[-1]["tick"] == 16
    # jax.profiler.trace wrote something under the logdir.
    assert trace_dir.exists() and any(trace_dir.rglob("*"))


def test_cli_check_multipaxos(capsys):
    from paxos_tpu.harness.cli import main

    # Clean bounded space (2 proposers x 3 acceptors x 2-slot logs).
    assert main([
        "--platform", "cpu", "check", "--protocol", "multipaxos",
        "--max-round", "1",
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["states"] > 25_000
    assert out["chosen_values"] == [1000, 1001, 2000, 2001]

    # Injected skipped-recovery bug must produce a counterexample.
    assert main([
        "--platform", "cpu", "check", "--protocol", "multipaxos",
        "--max-round", "1", "--no-recovery",
    ]) == 2
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not out["ok"] and "invariant violated" in out["counterexample"]

    # Flags that other protocols would silently ignore are rejected.
    assert main([
        "--platform", "cpu", "check", "--no-recovery",
    ]) == 1


def test_cli_check_fastpaxos(capsys):
    from paxos_tpu.harness.cli import main

    # Clean bounded space (tiny: both proposers fast-only).
    assert main([
        "--platform", "cpu", "check", "--protocol", "fastpaxos",
        "--n-acc", "4", "--max-round", "0",
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["states"] > 100

    # Injected wrong-recovery rule must produce a counterexample.
    assert main([
        "--platform", "cpu", "check", "--protocol", "fastpaxos",
        "--n-acc", "4", "--max-round", "1", "0", "--adopt-any",
    ]) == 2
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert not out["ok"] and "invariant violated" in out["counterexample"]


def test_cli_check_native(capsys):
    """`check --native` (paxos and multipaxos): counts match the recorded
    canonical spaces, unsupported combinations are refused."""
    import json

    from paxos_tpu.harness.cli import main

    assert main([
        "--platform", "cpu", "check", "--native", "--max-round", "1", "0",
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["native"] and out["states"] == 48_839

    assert main([
        "--platform", "cpu", "check", "--native", "--protocol", "multipaxos",
        "--max-round", "1",
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["native"] and out["states"] == 30_562

    # Round 5: the native matrix is square — raftcore and fastpaxos
    # dispatch natively too (counts = raw explored-state counts,
    # cross-validated against the Python checkers in
    # tests/test_native_oracle.py).
    assert main([
        "--platform", "cpu", "check", "--native", "--protocol", "raftcore",
        "--max-round", "1", "0",
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["native"] and out["states"] == 88_680
    assert main([
        "--platform", "cpu", "check", "--native", "--protocol", "fastpaxos",
        "--n-acc", "3", "--max-round", "1", "0",
    ]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["ok"] and out["native"] and out["states"] == 7_839

    # Still refused: native + liveness (liveness is Python-side).
    assert main([
        "--platform", "cpu", "check", "--native", "--liveness-bound", "20",
    ]) == 1


def test_cli_pipeline_degrade_is_loud_and_recorded(tmp_path, capsys):
    """[bugfix] --events (and friends) force the serial loop: the degrade
    must name the forcing flag on stderr and record the EFFECTIVE depth in
    the report and metrics gauges — never a silent fallback an operator
    could mistake for a pipelined run."""
    log = tmp_path / "m.jsonl"
    rc = main([
        "run", "--config", "config1", "--n-inst", "64", "--ticks", "16",
        "--chunk", "8", "--pipeline-depth", "4", "--events",
        "--log", str(log),
    ])
    assert rc == 0
    captured = capsys.readouterr()
    warnings = [
        l for l in captured.err.splitlines() if l.startswith("warning")
    ]
    assert warnings and "--events" in warnings[0]
    assert "explicit" in warnings[0]  # the user asked for depth 4
    report = json.loads(captured.out.strip().splitlines()[-1])
    assert report["pipeline_depth"] == 1  # effective, not requested
    records = [json.loads(l) for l in log.read_text().splitlines()]
    metrics = [r for r in records if r["event"] == "metrics"]
    assert metrics[-1]["gauges"]["pipeline_depth_effective"] == 1

    # The default depth (4) degrades too — still loud, labelled as such.
    rc = main([
        "run", "--config", "config1", "--n-inst", "64", "--ticks", "16",
        "--chunk", "8", "--events",
    ])
    assert rc == 0
    captured = capsys.readouterr()
    warnings = [
        l for l in captured.err.splitlines() if l.startswith("warning")
    ]
    assert warnings and "default" in warnings[0]

    # An undegraded run records its real depth.
    rc = main([
        "run", "--config", "config1", "--n-inst", "64", "--ticks", "16",
        "--chunk", "8", "--pipeline-depth", "2",
    ])
    assert rc == 0
    captured = capsys.readouterr()
    assert not [
        l for l in captured.err.splitlines() if l.startswith("warning")
    ]
    report = json.loads(captured.out.strip().splitlines()[-1])
    assert report["pipeline_depth"] == 2
