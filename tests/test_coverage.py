"""Fuzz-coverage probe (VERDICT r3 #3): soundness, measurement, anti-vacuity.

The probe's load-bearing claim is the SOUNDNESS dual: every in-bounds state
a fuzz lane occupies at a tick boundary must be reachable in the bounded
model under slot-transport semantics.  A projection bug, an engine/model
semantic drift, or a transport the model can't express would all surface as
``out_of_space > 0`` here.
"""

import pytest

from paxos_tpu.check.coverage import canon, coverage_probe, project_lane
from paxos_tpu.cpu_ref.exhaustive import check_exhaustive


@pytest.mark.slow
def test_probe_sound_and_measures():
    r = coverage_probe(
        max_round=(1, 0), n_inst=128, ticks=16, seeds=2, max_states=200_000
    )
    # Soundness: no fuzz state outside the slot-transport model space.
    assert r["out_of_space"] == 0, r["out_of_space_sample"]
    # It actually measures something.
    assert r["visited"] > 50
    assert 0 < r["coverage_slot"] <= 1
    assert r["visited_in_slot"] == r["visited"]
    # The transport quotient is real: the multiset model reaches states
    # (>= 2 same-edge in-flight messages) the slot transport cannot.
    # (That every OCCUPIED state is a slot state — the fuzzer's own
    # semantics — is exactly the out_of_space == 0 assertion above.)
    assert r["transport_excluded"] > 0
    # Growth curve is monotone, one entry per seed.
    assert r["growth"] == sorted(r["growth"]) and len(r["growth"]) == 2
    # The consequential corners are covered far more densely than the
    # transient average: decisions happen in every lane.
    assert r["decided_states"]["coverage"] > r["coverage_slot"]


def test_probe_catches_projection_drift(monkeypatch):
    """Anti-vacuity: the soundness leg must FIRE if the projection (or the
    engine semantics it mirrors) drifts — here a deliberately corrupted
    ballot-round mapping."""
    import paxos_tpu.check.coverage as cov

    real = cov.project_lane

    def corrupted(h, i, n_prop, n_acc):
        accs, props, net, voters = real(h, i, n_prop, n_acc)
        broken = tuple(
            (ph, rnd, heard, bb, bv, pv, dec + 7)  # impossible decided_val
            for (ph, rnd, heard, bb, bv, pv, dec) in props
        )
        return (accs, broken, net, voters)

    monkeypatch.setattr(cov, "project_lane", corrupted)
    r = cov.coverage_probe(
        max_round=(1, 0), n_inst=64, ticks=10, seeds=1, max_states=200_000
    )
    assert r["out_of_space"] > 0


def test_slot_space_cross_validates_at_trivial_bounds():
    """With a single proposer and no retries the slot and multiset spaces
    coincide (no re-send ever overwrites a live slot), so the slot_net
    variant must reproduce the classic count exactly."""
    multi = check_exhaustive(n_prop=1, n_acc=3, max_round=0, max_states=10_000)
    slot = check_exhaustive(
        n_prop=1, n_acc=3, max_round=0, max_states=10_000, slot_net=True
    )
    assert multi.states == slot.states
    assert multi.decided_states == slot.decided_states


def test_canon_is_idempotent_and_stable():
    seen = []
    check_exhaustive(
        n_prop=2, n_acc=3, max_round=(1, 0), max_states=200_000,
        visit=lambda s: seen.append(s) if len(seen) < 500 else None,
    )
    for s in seen[:500]:
        c = canon(s)
        assert canon(c) == c


@pytest.mark.slow
def test_mp_probe_sound_and_measures():
    """VERDICT r4 #3: the MP coverage probe's soundness dual — every
    conforming in-bounds MP fuzz state must be reachable in the bounded
    MP model under slot-transport semantics."""
    from paxos_tpu.check.mp_coverage import mp_coverage_probe

    r = mp_coverage_probe(
        n_inst=128, ticks=24, seeds=2, max_states=1_000_000
    )
    assert r["out_of_space"] == 0, r["out_of_space_sample"]
    assert r["visited"] > 50
    assert 0 < r["coverage_slot"] <= 1
    assert r["visited_in_slot"] == r["visited"]
    # Both transport quotients are real: multiset-only states (stacked
    # same-edge messages) AND slot-only states (an overwrite destroyed an
    # undelivered send — unreachable in the multiset model).  (Occupied
    # states being slot states is the out_of_space == 0 assertion above.)
    assert r["transport_excluded"] > 0
    assert r["slot_only"] > 0
    assert r["growth"] == sorted(r["growth"]) and len(r["growth"]) == 2
    # Exclusions are transient, not the common case.
    assert r["nonconforming_samples"] < r["samples"]


@pytest.mark.slow
def test_mp_probe_catches_projection_drift(monkeypatch):
    """Anti-vacuity for the MP leg: corrupting a LIVE field mapping
    (heard gains an impossible acceptor bit whenever a proposer is mid-
    election or leading) must surface as out_of_space > 0 — unlike a
    canon-zeroed field, this exercises the real projection path."""
    import paxos_tpu.check.mp_coverage as mcov
    from paxos_tpu.cpu_ref.mp_exhaustive import CAND, LEAD

    real = mcov.project_mp_lane

    def corrupted(h, i, n_prop, n_acc, log_len):
        st = real(h, i, n_prop, n_acc, log_len)
        if st is None:
            return None
        accs, props, net, votes = st
        broken = tuple(
            (ph, rnd,
             heard | (1 << 6) if ph in (CAND, LEAD) else heard,
             recov, ci, dec)
            for (ph, rnd, heard, recov, ci, dec) in props
        )
        return (accs, broken, net, votes)

    monkeypatch.setattr(mcov, "project_mp_lane", corrupted)
    r = mcov.mp_coverage_probe(
        n_inst=64, ticks=16, seeds=1, max_states=1_000_000
    )
    assert r["out_of_space"] > 0


def test_mp_canon_is_idempotent():
    from paxos_tpu.check.mp_coverage import canon_mp
    from paxos_tpu.cpu_ref.mp_exhaustive import check_mp_exhaustive

    seen = []
    check_mp_exhaustive(
        max_round=(1, 0), max_states=200_000,
        visit=lambda s: seen.append(s) if len(seen) < 500 else None,
    )
    for s in seen[:500]:
        c = canon_mp(s, quorum=2)
        assert canon_mp(c, quorum=2) == c


@pytest.mark.slow
def test_probe_sound_under_duplication():
    """VERDICT r4 weak#2: the dup-enabled adversary (consumed messages
    re-offer) stays inside the model space — redeliveries are idempotent
    and the projection drops already-folded copies — for BOTH measured
    protocols."""
    from paxos_tpu.check.mp_coverage import mp_coverage_probe

    r = coverage_probe(
        max_round=(1, 0), n_inst=128, ticks=20, seeds=1,
        max_states=200_000,
        probe_cfg_kw={"p_idle": 0.3, "p_hold": 0.3, "timeout": 3,
                      "backoff_max": 4, "p_dup": 0.5},
    )
    assert r["out_of_space"] == 0, r["out_of_space_sample"]
    assert r["visited"] > 50

    r = mp_coverage_probe(
        n_inst=96, ticks=20, seeds=1, max_states=1_000_000,
        probe_cfg_kw={"p_idle": 0.3, "p_hold": 0.3, "lease_len": 5,
                      "p_dup": 0.5},
    )
    assert r["out_of_space"] == 0, r["out_of_space_sample"]
    assert r["visited"] > 30
