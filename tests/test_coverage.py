"""Fuzz-coverage probe (VERDICT r3 #3): soundness, measurement, anti-vacuity.

The probe's load-bearing claim is the SOUNDNESS dual: every in-bounds state
a fuzz lane occupies at a tick boundary must be reachable in the bounded
model under slot-transport semantics.  A projection bug, an engine/model
semantic drift, or a transport the model can't express would all surface as
``out_of_space > 0`` here.
"""

import pytest

from paxos_tpu.check.coverage import canon, coverage_probe, project_lane
from paxos_tpu.cpu_ref.exhaustive import check_exhaustive


def test_probe_sound_and_measures():
    r = coverage_probe(
        max_round=(1, 0), n_inst=128, ticks=16, seeds=2, max_states=200_000
    )
    # Soundness: no fuzz state outside the slot-transport model space.
    assert r["out_of_space"] == 0, r["out_of_space_sample"]
    # It actually measures something.
    assert r["visited"] > 50
    assert 0 < r["coverage_slot"] <= 1
    assert r["visited_in_slot"] == r["visited"]
    # The transport quotient is real and EXACT: the multiset model reaches
    # states (>= 2 same-edge in-flight messages) the slot transport cannot,
    # and the two enumerations agree on the shared core — both sides of
    # |S_multi ∩ S_slot| computed from either space's totals must match.
    assert r["transport_excluded"] > 0
    assert (r["space_multiset"] - r["transport_excluded"]
            == r["space_slot"] - r["slot_only"])
    # Growth curve is monotone, one entry per seed.
    assert r["growth"] == sorted(r["growth"]) and len(r["growth"]) == 2
    # The consequential corners are covered far more densely than the
    # transient average: decisions happen in every lane.
    assert r["decided_states"]["coverage"] > r["coverage_slot"]


def test_probe_catches_projection_drift(monkeypatch):
    """Anti-vacuity: the soundness leg must FIRE if the projection (or the
    engine semantics it mirrors) drifts — here a deliberately corrupted
    ballot-round mapping."""
    import paxos_tpu.check.coverage as cov

    real = cov.project_lane

    def corrupted(h, i, n_prop, n_acc):
        accs, props, net, voters = real(h, i, n_prop, n_acc)
        broken = tuple(
            (ph, rnd, heard, bb, bv, pv, dec + 7)  # impossible decided_val
            for (ph, rnd, heard, bb, bv, pv, dec) in props
        )
        return (accs, broken, net, voters)

    monkeypatch.setattr(cov, "project_lane", corrupted)
    r = cov.coverage_probe(
        max_round=(1, 0), n_inst=64, ticks=10, seeds=1, max_states=200_000
    )
    assert r["out_of_space"] > 0


def test_slot_space_cross_validates_at_trivial_bounds():
    """With a single proposer and no retries the slot and multiset spaces
    coincide (no re-send ever overwrites a live slot), so the slot_net
    variant must reproduce the classic count exactly."""
    multi = check_exhaustive(n_prop=1, n_acc=3, max_round=0, max_states=10_000)
    slot = check_exhaustive(
        n_prop=1, n_acc=3, max_round=0, max_states=10_000, slot_net=True
    )
    assert multi.states == slot.states
    assert multi.decided_states == slot.decided_states


def test_canon_is_idempotent_and_stable():
    seen = []
    check_exhaustive(
        n_prop=2, n_acc=3, max_round=(1, 0), max_states=200_000,
        visit=lambda s: seen.append(s) if len(seen) < 500 else None,
    )
    for s in seen[:500]:
        c = canon(s)
        assert canon(c) == c
