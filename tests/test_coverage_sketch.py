"""Coverage sketch (PR 8): default-off is FREE, on is neutral, math is honest.

Four contracts guard the coverage plane:

1. **Default-off is free**: with coverage disabled (the default) the state's
   ``coverage`` leaf is ``None`` (pruned from the pytree), schedules are
   BIT-IDENTICAL to the PR-6 golden digests (tests/test_gray.py, re-pinned
   here), and the default config fingerprint is unchanged so recorded
   artifacts keep matching.
2. **On is outcome-neutral**: the sketch draws NO randomness — it hashes
   state the tick already produced — so enabling it leaves the protocol
   schedule bit-identical on BOTH engines (XLA key stream and fused counter
   stream), and the fused Pallas kernel carries the sketch arrays bit-exact
   vs its XLA reference via the generic packed-word passthrough.
3. **The Bloom math is honest**: the fill-fraction estimator lands within
   the propagated confidence band on known-cardinality insert sets, and the
   device hash positions match the pure-Python host mirror bit for bit.
4. **Calibration**: at exact-probe bounds the sketch's covered-set estimate
   matches the true distinct-digest count within the Bloom bound
   (``check.coverage.sketch_crosscheck``).
"""

import dataclasses
import hashlib
import random

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.harness import config as C
from paxos_tpu.harness.run import (
    base_key,
    get_step_fn,
    init_plan,
    init_state,
    run,
    run_chunk,
)
from paxos_tpu.obs import coverage as cov

COV = cov.CoverageConfig(words=8)


def _digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(jax.device_get(leaf).tobytes())
    return h.hexdigest()[:16]


def _xla_final(cfg, n_ticks=32):
    return run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, n_ticks,
        get_step_fn(cfg.protocol),
    )


def _ctr_final(cfg, n_ticks=32):
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    return reference_chunk(
        init_state(cfg), cfg.seed, init_plan(cfg), cfg.fault, n_ticks,
        apply_fn=apply_fn, mask_fn=mask_fn, blk_id=0,
    )


# The PR-6 goldens (tests/test_gray.py, n_inst=256, seed=7, 32 ticks, CPU):
# coverage-off must reproduce them, and coverage-ON minus the sketch leaf
# must reproduce them too (schedule unperturbed on both engines).
_GOLDEN_XLA = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "83347bc41b16a2aa"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "93a2dd9d7b8d66e4"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "c43658973b29e73e"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "4662db6b2c5a39d3"),
}
_GOLDEN_CTR = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "db6db6f40f16eb7b"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "4b6525460815d9c5"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "72beea3ccdacab94"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "eb285905571b709f"),
}

_FAST_XLA = ("config2", "config3")
_FAST_CTR = ("config2",)


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_XLA else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_XLA)
    ],
)
def test_coverage_on_schedule_identical_xla(name):
    mk, want = _GOLDEN_XLA[name]
    assert _digest(_xla_final(mk())) == want  # off == PR-6 golden
    fin = _xla_final(dataclasses.replace(mk(), coverage=COV))
    assert fin.coverage is not None
    assert int(jax.device_get(fin.coverage.new_bits).sum()) > 0
    assert _digest(fin.replace(coverage=None)) == want  # on == same schedule


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_CTR else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_CTR)
    ],
)
def test_coverage_on_schedule_identical_counter_stream(name):
    mk, want = _GOLDEN_CTR[name]
    assert _digest(_ctr_final(mk())) == want
    fin = _ctr_final(dataclasses.replace(mk(), coverage=COV))
    assert _digest(fin.replace(coverage=None)) == want


def test_default_off_prunes_to_none():
    """Disabled coverage leaves NO trace in the pytree or fingerprint."""
    for mk in (C.config1_no_faults, C.config3_multipaxos):
        cfg = mk(64, 0)
        state = init_state(cfg)
        assert state.coverage is None
        assert not cfg.coverage.enabled()
        on = init_state(dataclasses.replace(cfg, coverage=COV))
        off_n = len(jax.tree_util.tree_leaves(state))
        on_n = len(jax.tree_util.tree_leaves(on))
        assert on_n == off_n + 2  # bitmap + new_bits
        # All sketch leaves are non-scalar int32, instance-minor — the
        # fused engine's generic flattening rides them with no kernel edits.
        for leaf in jax.tree_util.tree_leaves(on.coverage):
            assert leaf.dtype == jnp.int32 and leaf.ndim >= 1
            assert leaf.shape[-1] == 64


def test_fingerprint_unchanged_by_default_coverage():
    """The default (off) CoverageConfig is dropped from the fingerprint, so
    pre-coverage artifacts keep matching; a non-default one IS keyed."""
    cfg = C.config2_dueling_drop(1 << 10)
    assert (
        dataclasses.replace(
            cfg, coverage=cov.CoverageConfig()
        ).fingerprint()
        == cfg.fingerprint()
    )
    assert (
        dataclasses.replace(cfg, coverage=COV).fingerprint()
        != cfg.fingerprint()
    )


def test_coverage_config_validation():
    with pytest.raises(ValueError):
        cov.CoverageConfig(words=-1)
    with pytest.raises(ValueError):
        cov.CoverageConfig(words=3)  # not a power of two
    assert cov.CoverageConfig(words=8).bits() == 256


def test_device_positions_match_host_mirror():
    """Every bit the device sketch sets is exactly the host mirror's set
    (same digests through host_hash_pos) — bit-for-bit, no estimate."""
    cfg = dataclasses.replace(C.config2_dueling_drop(128, 3), coverage=COV)
    fin = _xla_final(cfg, n_ticks=24)

    # Replay the run 1 tick at a time and collect every post-tick digest the
    # lanes hashed.  The step folds the base key by state.tick internally,
    # so 24 one-tick chunks reproduce exactly the 24-tick chunk above.
    digests: set = set()
    state = init_state(cfg)
    key, plan, step = base_key(cfg), init_plan(cfg), get_step_fn(cfg.protocol)
    for _ in range(24):
        state = run_chunk(state, key, plan, cfg.fault, 1, step)
        d = jax.device_get(cov.lane_digest(cov.digest_tree(state)))
        digests.update(int(v) & 0xFFFFFFFF for v in d)
    assert _digest(state.replace(coverage=None)) == _digest(
        fin.replace(coverage=None)
    )

    union = int(
        cov.union_hex(
            jax.device_get(cov.coverage_device(fin.coverage)["union_words"])
        ),
        16,
    )
    mirror = 0
    for p in cov.host_sketch_positions(digests, COV.words):
        mirror |= 1 << p
    assert union == mirror


def test_bloom_estimator_within_bound_on_known_sets():
    """FP-rate property: random known-cardinality insert sets must estimate
    within bloom_bound at several fill levels (seeded, deterministic)."""
    rng = random.Random(0xC0FFEE)
    words = 64  # m = 2048
    m = 32 * words
    for n in (10, 100, 400, 900):
        values = {rng.getrandbits(32) for _ in range(n)}
        bits = len(cov.host_sketch_positions(values, words))
        est = cov.bloom_estimate(m, cov.K_HASHES, bits)
        assert est is not None
        bound = cov.bloom_bound(m, cov.K_HASHES, len(values))
        assert abs(est - len(values)) <= bound, (n, est, bound)
        assert cov.host_sketch_estimate(values, words) == est


def test_bloom_estimate_edges():
    assert cov.bloom_estimate(256, 2, 0) == 0.0
    assert cov.bloom_estimate(256, 2, 256) is None  # saturated
    assert cov.bloom_estimate(256, 2, 300) is None
    mid = cov.bloom_estimate(256, 2, 128)
    assert mid is not None and mid > 0


def test_union_hex_is_mergeable():
    """OR of two runs' union_hex == Bloom union of their visited sets."""
    import numpy as np

    a = np.array([0b1010, 0, 1], dtype=np.int32)
    b = np.array([0b0101, 7, 0], dtype=np.int32)
    ua, ub = int(cov.union_hex(a), 16), int(cov.union_hex(b), 16)
    merged = ua | ub
    both = np.array([0b1111, 7, 1], dtype=np.int32)
    assert merged == int(cov.union_hex(both), 16)


def test_run_report_embeds_coverage():
    cfg = dataclasses.replace(C.config1_no_faults(64, 0), coverage=COV)
    rep = run(cfg, total_ticks=16, chunk=8)
    c = rep["coverage"]
    assert c["bits_total"] == COV.bits()
    assert 0 < c["bits_set"] <= c["bits_total"]
    assert c["hashes"] == cov.K_HASHES
    assert bin(int(c["union_hex"], 16)).count("1") == c["bits_set"]
    # And with the default config the report has NO coverage block.
    rep_off = run(C.config1_no_faults(64, 0), total_ticks=16, chunk=8)
    assert "coverage" not in rep_off


@pytest.mark.parametrize(
    "protocol",
    [
        "paxos",
        pytest.param("multipaxos", marks=pytest.mark.slow),
        pytest.param("fastpaxos", marks=pytest.mark.slow),
        pytest.param("raftcore", marks=pytest.mark.slow),
    ],
)
def test_fused_kernel_carries_sketch_bitexact(protocol):
    """fused_chunk(interpret) == reference_chunk with the sketch ON: the
    packed-word passthrough codec must round-trip the bitmap bit-exactly."""
    from paxos_tpu.kernels.fused_tick import (
        FUSED_CHUNKS,
        fused_fns,
        reference_chunk,
    )
    from paxos_tpu.utils.trees import tree_mismatches

    base = {
        "paxos": C.config2_dueling_drop,
        "multipaxos": C.config3_multipaxos,
        "fastpaxos": lambda n, s: C.config5_sweep(n, s)[1],
        "raftcore": lambda n, s: C.config5_sweep(n, s)[2],
    }[protocol](64, 7)
    cfg = dataclasses.replace(base, coverage=COV)
    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    plan = init_plan(cfg)
    sr = reference_chunk(
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        apply_fn=apply_fn, mask_fn=mask_fn,
    )
    sp = FUSED_CHUNKS[cfg.protocol](
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        block=64, interpret=True,
    )
    assert tree_mismatches(sp, sr) == []
    assert int(jax.device_get(sp.coverage.new_bits).sum()) > 0


def test_new_bits_curve_monotone_and_saturating():
    """Cumulative new_bits is nondecreasing and bounded by the union fill;
    re-running from a converged state adds (almost) nothing."""
    cfg = dataclasses.replace(C.config1_no_faults(64, 0), coverage=COV)
    state = init_state(cfg)
    key, plan, step = base_key(cfg), init_plan(cfg), get_step_fn(cfg.protocol)
    prev = 0
    totals = []
    for _ in range(6):
        state = run_chunk(state, key, plan, cfg.fault, 4, step)
        total = int(jax.device_get(state.coverage.new_bits).sum())
        assert total >= prev
        prev = total
        totals.append(total)
    # config1 converges: the tail chunks discover little or nothing new.
    assert totals[-1] - totals[-2] <= totals[1] - totals[0]
    rep = cov.coverage_report(state.coverage)
    assert rep["bits_set"] <= rep["bits_total"]
    assert rep["lane_bits"] >= rep["bits_set"]


@pytest.mark.slow
def test_sketch_crosschecks_exact_probe():
    """Acceptance: at coverage_probe bounds the sketch estimate matches the
    exact distinct-digest count within the Bloom bound, and the device
    union equals the host mirror bit for bit."""
    from paxos_tpu.check.coverage import sketch_crosscheck

    out = sketch_crosscheck(n_inst=256, ticks=24, seeds=2)
    assert out["union_matches_host_mirror"], out
    assert out["estimate_within_bound"], out
    assert out["exact_digests"] > 0
