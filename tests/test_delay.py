"""Bounded-delay fault channel: stamps, gating, and message conservation.

The delay channel's load-bearing property is CONSERVATION: a delayed
message is *late*, never *lost*.  ``delay_stamps`` writes ``until`` ticks
into the message buffers at send time and ``net.ready`` gates visibility
only — no mask ever clears a stamped slot — so delay composes with
partitions (a delayed message landing in a cut waits for BOTH the stamp
and the heal) without inventing a new loss mode.  With loss genuinely off,
every protocol must therefore still decide every lane; that end-to-end
check runs for all five protocols on both engines below.

The structural half of default-off-is-free (p_delay = 0 prunes the
``until`` leaves and ``plan.link_delay``) rides here too; the stream half
(bit-identical default digests) is pinned by tests/test_gray.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.core.messages import MsgBuf
from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.run import init_state, run
from paxos_tpu.obs.exposure import ExposureConfig
from paxos_tpu.transport import inmemory_tpu as net

PROTOCOLS = ("paxos", "multipaxos", "fastpaxos", "raftcore", "synchpaxos")


def delay_cfg(protocol, n_inst=128, seed=0, exposure=False, **fault_kw):
    fault_kw.setdefault("p_delay", 0.6)
    fault_kw.setdefault("delay_max", 3)
    cfg = SimConfig(
        n_inst=n_inst, n_prop=2, n_acc=5, seed=seed, protocol=protocol,
        fault=FaultConfig(**fault_kw),
    )
    if exposure:
        cfg = dataclasses.replace(cfg, exposure=ExposureConfig(counters=True))
    return cfg


# --- transport-level semantics -------------------------------------------


def test_until_stamp_gates_visibility_only():
    """A stamped slot is invisible until its tick, present throughout, and
    delivers unchanged after — the whole conservation argument in one
    buffer."""
    buf = MsgBuf.empty(4, 1, 1, delay=True)
    mask = jnp.ones((1, 1, 4), bool)
    until = jnp.full((1, 1, 4), 5, jnp.int32)
    buf = net.send(buf, 0, send_mask=mask, bal=jnp.int32(7),
                   v1=jnp.int32(1), v2=jnp.int32(0), until=until)
    for tick in (0, 4):
        rdy = net.ready(buf, jnp.int32(tick))
        assert not bool((rdy & buf.present)[0].any())
    assert bool(buf.present[0].all())  # in flight the whole wait
    rdy = net.ready(buf, jnp.int32(5))
    assert bool((rdy & buf.present)[0].all())
    assert bool((buf.bal[0] == 7).all())  # payload untouched by the wait


def test_delay_off_prunes_until_and_plan():
    """p_delay = 0: no ``until`` leaves anywhere in the state, no
    ``link_delay`` in the plan — the pre-delay pytree, structurally."""
    for protocol in PROTOCOLS:
        cfg = delay_cfg(protocol, n_inst=32, p_delay=0.0)
        state = init_state(cfg)
        for buf in jax.tree_util.tree_leaves(
            state, is_leaf=lambda x: isinstance(x, MsgBuf)
        ):
            if isinstance(buf, MsgBuf):
                assert buf.until is None, protocol
        assert net.ready(MsgBuf.empty(8, 2, 5), jnp.int32(0)) is None
    plan = FaultPlan.sample(
        jax.random.PRNGKey(0), FaultConfig(p_drop=0.1), 32, 5, 2
    )
    assert plan.link_delay is None


def test_delay_on_materializes_stamps():
    for protocol in PROTOCOLS:
        cfg = delay_cfg(protocol, n_inst=32)
        state = init_state(cfg)
        bufs = [
            b for b in jax.tree_util.tree_leaves(
                state, is_leaf=lambda x: isinstance(x, MsgBuf)
            ) if isinstance(b, MsgBuf)
        ]
        assert bufs, protocol
        for buf in bufs:
            assert buf.until is not None, protocol
    plan = FaultPlan.sample(
        jax.random.PRNGKey(0), FaultConfig(p_delay=0.6, delay_max=3),
        32, 5, 2,
    )
    caps = jax.device_get(plan.link_delay)
    assert caps.shape == (2, 5, 32)
    assert caps.min() >= 0 and caps.max() <= 3
    assert (caps > 0).any()  # some links actually slow at p=0.6


# --- conservation across a partition cut + heal, end to end --------------


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_delay_conservation_across_cut_and_heal_xla(protocol):
    """Delay + a guaranteed partition episode per lane, loss OFF: every
    message eventually delivers, so every lane must decide — a delayed
    message swallowed by the cut/heal would strand its lane below 1.0."""
    cfg = delay_cfg(
        protocol, n_inst=128, exposure=True,
        p_part=1.0, part_max_start=8, part_max_len=8, timeout=6,
    )
    report = run(cfg, until_all_chosen=True, max_ticks=768, chunk=64)
    assert report["violations"] == 0
    assert report["chosen_frac"] == 1.0, (protocol, report["chosen_frac"])
    assert report["proposer_disagree"] == 0
    classes = report["exposure"]["classes"]
    # Both faults genuinely bit: messages were held by stamps AND by cuts.
    assert classes["delay"]["effective"] > 0, protocol
    assert classes["partition"]["effective"] > 0, protocol


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_delay_conservation_across_cut_and_heal_fused(protocol):
    """Same conservation property under the fused engine's counter-PRNG
    stream (Pallas interpreter off-TPU) — smaller batch, same invariants."""
    cfg = delay_cfg(
        protocol, n_inst=64, seed=3,
        p_part=1.0, part_max_start=8, part_max_len=8, timeout=6,
    )
    report = run(
        cfg, until_all_chosen=True, max_ticks=384, chunk=64, engine="fused",
    )
    assert report["violations"] == 0
    assert report["chosen_frac"] == 1.0, (protocol, report["chosen_frac"])
    assert report["proposer_disagree"] == 0


def test_delay_composes_with_drop_safely():
    """Delay + real loss + dup: liveness is no longer guaranteed per lane,
    but safety and near-full progress are — the chaos regime delay ships
    in (config_delay_chaos's knob family, paxos side)."""
    cfg = delay_cfg(
        "paxos", n_inst=128, seed=1, exposure=True,
        p_drop=0.15, p_dup=0.1, p_delay=0.5, delay_max=4, timeout=6,
    )
    report = run(cfg, total_ticks=256, chunk=64)
    assert report["violations"] == 0
    assert report["chosen_frac"] > 0.9
    classes = report["exposure"]["classes"]
    assert classes["delay"]["effective"] > 0
    assert classes["drop"]["effective"] > 0
