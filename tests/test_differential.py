"""Schedule-exact differential tests: host interpreter vs the JAX kernels.

SURVEY.md §5.2.1 as written (round-1 verdict, "Missing #2"): the SAME
pre-sampled ``TickMasks``/``FaultPlan`` feed both the batched JAX
``apply_tick`` and the scalar per-lane interpreter
(``cpu_ref/interp``), and the ENTIRE per-lane state must be equal after
every tick — so a mask consumed by the wrong role, a biased selection, a
payload routed to the wrong slot, or a checker-table divergence fails on
the first tick it occurs, in every protocol, under every fault class.

Both engines' mask streams are exercised: ``xla`` (jax.random fold-in, what
``paxos_step``/``run_chunk`` draw) and ``counter`` (the counter-PRNG stream
the fused Pallas engine draws, block 0) — together with the existing
fused-vs-reference bit-exactness tests this closes the chain
interpreter == apply_tick == fused kernel.

Mutation-tested by hand (each perturbation was verified to fail here, then
reverted): (1) the acceptor's accept rule ``>=`` -> ``>``; (2) the ACCEPT
send-drop mask wired to ``keep_p1`` instead of ``keep_p2``; (3) the
transport's selection score degenerated to the slot id (selection bias);
(4) the learner's eviction admission ``b > min_bal`` -> ``>=`` (caught by
``test_differential_table_pressure``, which forces a full table with
same-ballot/different-value conflicts via the Fast Paxos fast round).
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.cpu_ref.interp import INTERP_TICKS, lane_of
from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.run import base_key, init_plan, init_state
from paxos_tpu.kernels.counter_prng import mix


def _protocol_fns(protocol):
    """(mask_sampler_xla, mask_sampler_counter, apply_fn) for a protocol."""
    if protocol == "multipaxos":
        from paxos_tpu.protocols.multipaxos import (
            apply_tick_mp,
            mp_counter_masks,
            sample_mp_masks,
        )

        return sample_mp_masks, mp_counter_masks, apply_tick_mp
    from paxos_tpu.protocols.paxos import counter_masks, sample_masks

    if protocol == "paxos":
        from paxos_tpu.protocols.paxos import apply_tick
    elif protocol == "fastpaxos":
        from paxos_tpu.protocols.fastpaxos import apply_tick_fast as apply_tick
    elif protocol == "raftcore":
        from paxos_tpu.protocols.raftcore import apply_tick_raft as apply_tick
    else:
        raise ValueError(protocol)
    return sample_masks, counter_masks, apply_tick


def _diff(a, b, path=""):
    """Paths at which two nested structures differ (for failure messages)."""
    if isinstance(a, dict) and isinstance(b, dict):
        out = []
        for k in a:
            out += _diff(a[k], b[k], f"{path}.{k}")
        return out
    if isinstance(a, list) and isinstance(b, list) and len(a) == len(b):
        out = []
        for i, (x, y) in enumerate(zip(a, b)):
            out += _diff(x, y, f"{path}[{i}]")
        return out
    return [] if a == b else [f"{path}: jax={a!r} interp={b!r}"]


def run_differential(cfg: SimConfig, ticks: int, stream: str, sampler=None):
    """Advance JAX kernel and interpreter in lockstep; compare every lane.

    ``sampler(t, state) -> masks`` overrides the mask source (used by the
    multi-block case to feed per-block counter streams); otherwise
    ``stream`` selects the xla or the block-0 counter stream.  Returns the
    final JAX state (for callers cross-checking it against an engine).
    """
    sample_xla, sample_counter, apply_fn = _protocol_fns(cfg.protocol)
    tick_fn = INTERP_TICKS[cfg.protocol]
    apply_j = jax.jit(apply_fn, static_argnums=(3,))

    state = init_state(cfg)
    plan = init_plan(cfg)
    key = base_key(cfg)
    lanes = range(cfg.n_inst)

    plan_h = jax.device_get(plan)
    plan_l = [lane_of(plan_h, i) for i in lanes]
    interp = [lane_of(jax.device_get(state), i) for i in lanes]

    for t in range(ticks):
        if sampler is not None:
            masks = sampler(t, state)
        elif stream == "xla":
            # Exactly what the protocol's *_step does per scan iteration.
            masks = sample_xla(
                jax.random.fold_in(key, t), cfg.fault,
                cfg.n_prop, cfg.n_acc, cfg.n_inst,
            )
        else:
            # Exactly what the fused engine draws for block 0 (n_inst fits
            # one block here, so this is the whole fused stream).
            masks = sample_counter(
                cfg.fault,
                mix(jnp.int32(cfg.seed), jnp.int32(t), jnp.int32(0)),
                state,
            )
        masks_h = jax.device_get(masks)
        state = apply_j(state, masks, plan, cfg.fault)
        state_h = jax.device_get(state)
        for i in lanes:
            tick_fn(interp[i], lane_of(masks_h, i), plan_l[i], cfg.fault)
            got = lane_of(state_h, i)
            if got != interp[i]:
                diffs = "\n".join(_diff(got, interp[i])[:20])
                raise AssertionError(
                    f"{cfg.protocol}/{stream}: lane {i} diverged at tick {t}:\n"
                    f"{diffs}"
                )
    return state


CHAOS = FaultConfig(
    p_drop=0.15, p_dup=0.15, p_idle=0.2, p_hold=0.2,
    p_crash=0.3, crash_max_start=24, crash_max_len=12,
    p_equiv=0.2, p_part=0.5, part_max_start=16, part_max_len=12,
    timeout=6, backoff_max=4,
)

CASES = [
    # Every fault class at once, on every protocol (the masks all fire).
    ("paxos", CHAOS, 64),
    ("fastpaxos", CHAOS, 64),
    ("raftcore", CHAOS, 64),
    # Flexible / Fast-Flexible quorums (the q1/q2/q_fast code paths).
    ("paxos", dataclasses.replace(CHAOS, q1=4, q2=2), 48),
    ("fastpaxos", dataclasses.replace(CHAOS, q1=4, q2=2, q_fast=4), 48),
    # Amnesia bug-injection branch (acceptor state loss on recovery).
    ("paxos", dataclasses.replace(CHAOS, amnesia=True), 48),
    # Clean network: the None-mask (fault disabled) branches.
    ("paxos", FaultConfig(timeout=4), 32),
]


@pytest.mark.parametrize("stream", ["xla", "counter"])
def test_differential_table_pressure(stream):
    """K=1 learner table under Fast Paxos: the shared fast ballot with two
    distinct proposer values forces same-ballot/different-value insert
    conflicts on a full table, so the eviction/insert policy (the checker's
    completeness bound, not just its happy path) actually exercises and any
    divergence in it is caught."""
    cfg = SimConfig(
        n_inst=4, n_prop=2, n_acc=5, k_slots=1, seed=5, protocol="fastpaxos",
        fault=dataclasses.replace(CHAOS, p_equiv=0.3, timeout=3),
    )
    run_differential(cfg, 64, stream)

MP_FAULTS = FaultConfig(
    p_drop=0.1, p_dup=0.1, p_idle=0.15, p_hold=0.15,
    p_crash=0.2, p_crash_prop=0.5, crash_max_start=40, crash_max_len=16,
    p_equiv=0.1, p_part=0.4, part_max_start=20, part_max_len=12,
    timeout=8, backoff_max=4, lease_len=10,
)


@pytest.mark.parametrize("stream", ["xla", "counter"])
@pytest.mark.parametrize("protocol,fault,ticks", CASES)
def test_differential(protocol, fault, ticks, stream):
    cfg = SimConfig(
        n_inst=4, n_prop=2, n_acc=5, seed=7, protocol=protocol, fault=fault
    )
    run_differential(cfg, ticks, stream)


@pytest.mark.parametrize("stream", ["xla", "counter"])
def test_differential_multipaxos(stream):
    cfg = SimConfig(
        n_inst=4, n_prop=2, n_acc=5, log_len=4, k_slots=4, seed=3,
        protocol="multipaxos", fault=MP_FAULTS,
    )
    run_differential(cfg, 96, stream)


def _py_mix(seed: int, tick: int, block: int) -> int:
    """Pure-Python reimplementation of ``kernels.counter_prng.mix``
    (splitmix32-style): an implementation-independent oracle for the
    per-(seed, tick, block) stream seeds — deliberately NOT the jnp code."""
    m = 0xFFFFFFFF
    h = (
        seed * 0x9E3779B1 + tick * 0x85EBCA77 + block * 0xC2B2AE3D + 0x165667B1
    ) & m
    h ^= h >> 16
    h = (h * 0x7FEB352D) & m
    h ^= h >> 15
    return h - (1 << 32) if h >= (1 << 31) else h


def _slice_lanes(tree, lo, hi):
    return jax.tree.map(
        lambda x: x[..., lo:hi] if getattr(x, "ndim", 0) else x, tree
    )


@pytest.mark.parametrize("protocol", ["paxos", "multipaxos"])
def test_differential_counter_multiblock(protocol):
    """VERDICT r2 weak#1: ``blk_id > 0`` stream offsets get an independent
    scalar check.  With n_inst = 2 x block, each block's masks are drawn
    per tick with a PURE-PYTHON splitmix seed ``_py_mix(seed, t, blk)`` and
    the block's state slice (the fused kernel's view); the interpreter
    advances every lane against those masks, and the fused kernel itself
    (2-block grid) must then bit-equal the mask-lockstep state.

    Fails under a deliberately broken block offset: hand-verified by
    mutating ``blk_id = blk0_ref[0, 0]`` (dropping ``program_id``) in
    ``fused_tick._kernel`` — the fused-vs-lockstep comparison trips at the
    first tick a block-1 mask matters (then reverted)."""
    from paxos_tpu.kernels.counter_prng import mix
    from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS

    # The jnp hash must agree with the independent Python one everywhere
    # the kernel evaluates it — including blk > 0 — and blocks must get
    # distinct streams (vacuity guard for everything below).
    for t in range(4):
        for b in range(3):
            assert int(mix(jnp.int32(9), jnp.int32(t), jnp.int32(b))) == \
                _py_mix(9, t, b)
    assert _py_mix(9, 0, 1) != _py_mix(9, 0, 0)

    block, ticks = 4, 48
    fault = MP_FAULTS if protocol == "multipaxos" else CHAOS
    kw = {"log_len": 4, "k_slots": 4} if protocol == "multipaxos" else {}
    cfg = SimConfig(
        n_inst=2 * block, n_prop=2, n_acc=5, seed=9, protocol=protocol,
        fault=fault, **kw,
    )
    _, sample_counter, _ = _protocol_fns(protocol)

    def per_block_sampler(t, state):
        parts = [
            sample_counter(
                cfg.fault,
                jnp.int32(_py_mix(cfg.seed, t, b)),
                _slice_lanes(state, b * block, (b + 1) * block),
            )
            for b in range(2)
        ]
        return jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=-1), *parts)

    state = run_differential(cfg, ticks, "multiblock", sampler=per_block_sampler)

    # The 2-block fused kernel must reproduce the lockstep state exactly:
    # its on-core blk_id arithmetic IS the _py_mix block argument above.
    fused = FUSED_CHUNKS[protocol](
        init_state(cfg), jnp.int32(cfg.seed), init_plan(cfg), cfg.fault,
        ticks, block=block, interpret=True,
    )
    from paxos_tpu.utils.trees import assert_trees_equal

    assert_trees_equal(fused, state, "fused 2-block run != per-block lockstep")


def test_differential_many_seeds():
    """Breadth: the full-chaos paxos case across distinct seeds/plans."""
    for seed in range(3):
        cfg = SimConfig(
            n_inst=4, n_prop=2, n_acc=5, seed=11 + seed,
            protocol="paxos", fault=CHAOS,
        )
        run_differential(cfg, 48, "xla")
