"""Multi-host helpers degrade correctly on the single-process CPU rig."""

import jax
import jax.numpy as jnp

from paxos_tpu.harness.config import config2_dueling_drop
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state, run_chunk
from paxos_tpu.parallel.distributed import (
    init_distributed,
    make_instances_mesh,
    process_local_batch,
    slice_major_devices,
)
from paxos_tpu.parallel.mesh import shard_pytree


def test_init_noop_single_process():
    assert init_distributed() == 0  # must not try to rendezvous


def test_slice_major_order_is_stable_without_slices():
    devs = jax.devices()
    assert slice_major_devices(devs) == list(devs)


def test_instances_mesh_spans_all_devices_and_runs():
    mesh = make_instances_mesh()
    assert mesh.devices.size == len(jax.devices())

    cfg = config2_dueling_drop(n_inst=16 * mesh.devices.size, seed=0)
    state = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    plan = shard_pytree(init_plan(cfg), mesh, cfg.n_inst)
    step = get_step_fn(cfg.protocol)
    state = run_chunk(state, base_key(cfg), plan, cfg.fault, 4, step)
    assert len(state.acceptor.promised.sharding.device_set) == mesh.devices.size
    assert int(state.tick) == 4


def test_process_local_batch():
    assert process_local_batch(1 << 20) == (1 << 20) // jax.process_count()
