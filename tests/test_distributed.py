"""Multi-host helpers degrade correctly on the single-process CPU rig."""

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.harness.config import config2_dueling_drop
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state, run_chunk
from paxos_tpu.parallel.distributed import (
    init_distributed,
    make_instances_mesh,
    process_local_batch,
    slice_major_devices,
)
from paxos_tpu.parallel.mesh import shard_pytree


def test_init_noop_single_process():
    assert init_distributed() == 0  # must not try to rendezvous


def test_slice_major_order_is_stable_without_slices():
    devs = jax.devices()
    assert slice_major_devices(devs) == list(devs)


def test_instances_mesh_spans_all_devices_and_runs():
    mesh = make_instances_mesh()
    assert mesh.devices.size == len(jax.devices())

    cfg = config2_dueling_drop(n_inst=16 * mesh.devices.size, seed=0)
    state = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    plan = shard_pytree(init_plan(cfg), mesh, cfg.n_inst)
    step = get_step_fn(cfg.protocol)
    state = run_chunk(state, base_key(cfg), plan, cfg.fault, 4, step)
    assert len(state.acceptor.promised.sharding.device_set) == mesh.devices.size
    assert int(state.tick) == 4


def test_process_local_batch():
    assert process_local_batch(1 << 20) == (1 << 20) // jax.process_count()


def test_two_process_rendezvous_smoke():
    """Round-1 verdict #8: the actual jax.distributed.initialize rendezvous.

    Two fresh CPU processes join via an explicit coordinator, build the
    global 4-device mesh, run the same tiny sharded campaign under jit,
    and must print IDENTICAL metrics (multi-controller SPMD: every
    controller sees the same replicated scalars)."""
    import json
    import pathlib
    import socket
    import subprocess
    import sys

    child = pathlib.Path(__file__).parent / "_dist_child.py"
    with socket.socket() as s:  # grab a free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    procs = [
        subprocess.Popen(
            [sys.executable, str(child), str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            if "aren't implemented on the CPU backend" in err:
                # jaxlib builds without CPU collectives (e.g. 0.4.x) cannot
                # run the rendezvous at all — an environment limitation, not
                # a regression in the helpers under test.
                pytest.skip("this jaxlib's CPU backend lacks multiprocess support")
            assert p.returncode == 0, f"child failed:\n{err[-2000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        # A hung rendezvous (e.g. the free-port TOCTOU race) must not leak
        # children blocking in distributed-init past the test.
        for p in procs:
            if p.poll() is None:
                p.kill()

    assert outs[0]["process"] == 0 and outs[1]["process"] == 1
    # The per-process Pallas shard digests are shard-LOCAL (disjoint lanes),
    # so pull them out before the replicated-metrics equality check.
    shard_digests = [o.pop("pallas_shard_digest") for o in outs]
    for o in outs:
        del o["process"]
    assert outs[0] == outs[1], outs  # identical metrics on both controllers
    assert outs[0]["violations"] == 0
    assert outs[0]["tick"] == 32
    assert outs[0]["chosen"] > 0

    # The child also ran the fused engine's stream over the process-spanning
    # 4-device mesh (VERDICT r3 #6; stream via reference_chunk + axis_index
    # block ids — _dist_child.py documents why interpret-mode Pallas cannot
    # run multi-process).  Global block ids are mesh-invariant at a fixed
    # block, so the 2-process run must equal a single-process UNSHARDED
    # fused run at block=16 (= the child's local shard) bit-for-bit —
    # validating the block-offset arithmetic across process boundaries.
    import jax.numpy as jnp

    from paxos_tpu.harness.run import init_plan, init_state
    from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS

    cfg = config2_dueling_drop(n_inst=64, seed=3)
    st = FUSED_CHUNKS["paxos"](
        init_state(cfg), jnp.int32(cfg.seed), init_plan(cfg), cfg.fault, 32,
        block=16, interpret=True,
    )
    expected = {
        "chosen": int(st.learner.chosen.sum()),
        "violations": int(st.learner.violations.sum()),
        "evictions": int(st.learner.evictions.sum()),
        "tick": int(st.tick),
    }
    assert outs[0]["fused"] == expected, (outs[0]["fused"], expected)
    assert expected["violations"] == 0 and expected["chosen"] > 0

    # VERDICT r4 #7: the REAL Pallas lowering across process boundaries.
    # Each child ran plain fused_chunk (the actual pallas_call, interpret
    # mode, NO shard_map — the emulation deadlocks there) on its disjoint
    # half of the lanes with the manually-computed global block_offset
    # (pid * blocks_per_shard).  The same kernel run single-process over
    # the full width, sliced per half and digested identically, must match
    # bit for bit — validating the lowering's block-offset arithmetic, not
    # just the reference_chunk stream oracle, in a multi-controller
    # program.
    import hashlib

    import numpy as np

    half = cfg.n_inst // 2

    def digest_half(tree, pid):
        d = hashlib.sha256()
        for leaf in jax.tree.leaves(jax.device_get(tree)):
            arr = np.asarray(leaf)
            if arr.ndim >= 1 and arr.shape[-1] == cfg.n_inst:
                arr = arr[..., pid * half:(pid + 1) * half]
            d.update(str((arr.dtype.str, arr.shape)).encode())
            d.update(arr.tobytes())
        return d.hexdigest()

    assert [digest_half(st, 0), digest_half(st, 1)] == shard_digests, (
        "per-process Pallas shards diverged from the single-process kernel"
    )
