"""End-to-end: BASELINE configs on scaled-down instance counts (CPU CI).

Config 1 is SURVEY.md §8.3's "minimum end-to-end slice": every instance
decides, decisions are valid, the checker is green.
"""

import jax.numpy as jnp

from paxos_tpu.harness.config import (
    config1_no_faults,
    config2_dueling_drop,
    config4_byzantine,
)
from paxos_tpu.harness.run import run


def test_config1_all_decide_no_violations():
    cfg = config1_no_faults(n_inst=512, seed=3)
    report, state = run(cfg, until_all_chosen=True, max_ticks=64, return_state=True)
    assert report["chosen_frac"] == 1.0
    assert report["decided_frac"] == 1.0
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["proposer_disagree"] == 0
    # Validity: the single proposer's value (100) is the only possible choice.
    assert bool((state.learner.chosen_val == 100).all())
    # Fault-free single-proposer runs decide in a handful of ticks.
    assert report["mean_choose_tick"] <= 8


def test_config2_dueling_proposers_drop_safe():
    cfg = config2_dueling_drop(n_inst=2048, seed=11)
    report, state = run(cfg, until_all_chosen=True, max_ticks=600, return_state=True)
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["proposer_disagree"] == 0
    assert report["chosen_frac"] > 0.99  # liveness under 10% drop
    # Validity: chosen values come from the proposers' own values {100, 101}.
    chosen = state.learner.chosen
    vals = state.learner.chosen_val
    assert bool(jnp.isin(vals[chosen], jnp.array([100, 101])).all())


def test_config4_byzantine_checker_lights_up():
    """The 0-violations claim must be falsifiable: equivocation MUST trip it."""
    cfg = config4_byzantine(n_inst=2048, seed=5)
    report = run(cfg, total_ticks=400)
    assert report["violations"] > 0
    # And the control: same run, no equivocation -> green.
    clean = config2_dueling_drop(n_inst=2048, seed=5)
    report2 = run(clean, total_ticks=400)
    assert report2["violations"] == 0
