"""Bounded exhaustive model checking: every schedule of small instances."""

import pytest

from paxos_tpu.cpu_ref.exhaustive import check_exhaustive
from paxos_tpu.cpu_ref.fp_exhaustive import check_fp_exhaustive
from paxos_tpu.cpu_ref.mp_exhaustive import check_mp_exhaustive
from paxos_tpu.cpu_ref.raft_exhaustive import check_raft_exhaustive


def test_exhaustive_no_retries_clean():
    r = check_exhaustive(n_prop=2, n_acc=3, max_round=0)
    assert r.counterexample is None
    assert r.states > 3_000  # the whole bounded space, not a truncation
    assert r.decided_states > 0
    # Across different schedules either value can win — but never both in
    # one schedule (that would have raised).
    assert r.chosen_values == {100, 101}


@pytest.mark.parametrize("bounds", [(1, 0), (0, 1)])
def test_exhaustive_with_preemption_clean(bounds):
    """One proposer may retry past the other: the full dueling/stale-accept
    interleaving family, every schedule, ~50k states."""
    r = check_exhaustive(n_prop=2, n_acc=3, max_round=bounds)
    assert r.counterexample is None
    assert r.states > 40_000
    assert r.chosen_values == {100, 101}


@pytest.mark.slow
def test_exhaustive_symmetric_retries_clean():
    """Both proposers retry: ~600k distinct states, all invariant-clean."""
    r = check_exhaustive(n_prop=2, n_acc=3, max_round=1)
    assert r.counterexample is None
    assert r.states > 500_000


def test_exhaustive_finds_injected_bug():
    """Accept-below-promise (THE classic Paxos bug) must yield a
    counterexample schedule — the model checker is falsifiable."""
    with pytest.raises(AssertionError, match="invariant violated"):
        check_exhaustive(
            n_prop=2, n_acc=3, max_round=(1, 0), unsafe_accept=True
        )


@pytest.mark.slow
def test_exhaustive_five_acceptors_clean():
    r = check_exhaustive(n_prop=2, n_acc=5, max_round=0)
    assert r.counterexample is None
    assert r.states > 10_000


# ---- Fast Paxos (cpu_ref/fp_exhaustive.py; round-1 verdict #3) ----


@pytest.mark.slow
def test_fp_exhaustive_clean():
    """Every schedule of 2 fast proposers x 4 acceptors with one recovery
    round: the fast round, vote-once rule, and choosable-rule recovery are
    agreement-clean across the whole bounded space (~120k states).  The
    n_acc=5 canonical space (4.01M states, ~3.5 min) is run via the CLI and
    recorded in BASELINE.md rather than per-commit here."""
    r = check_fp_exhaustive(n_prop=2, n_acc=4, max_round=(1, 0))
    assert r.counterexample is None
    assert r.states > 100_000
    assert r.decided_states > 10_000
    assert r.chosen_values == {100, 101}


def test_fp_exhaustive_finds_adopt_any_bug():
    """Wrong recovery (adopt any reported value instead of the choosable
    rule) must yield a counterexample: the coordinator classic-chooses one
    value while unheard acceptors complete the other's fast quorum."""
    for n_acc in (4, 5):
        with pytest.raises(AssertionError, match="invariant violated"):
            check_fp_exhaustive(n_prop=2, n_acc=n_acc, adopt_any=True)


def test_fp_exhaustive_finds_unsafe_ffp_quorum():
    """Fast Flexible Paxos soundness boundary: q_fast=3 with n=5, q1=3
    violates q1 + 2*q_fast > 2n, and the checker's exhaustive space finds
    the resulting split-brain — the safety condition is load-bearing, not
    folklore."""
    with pytest.raises(AssertionError, match="invariant violated"):
        check_fp_exhaustive(n_prop=2, n_acc=5, q_fast=3)


@pytest.mark.slow
def test_fp_exhaustive_safe_ffp_quorum_clean():
    """A SAFE non-default FFP triple (n=4: q1=3, q2=2, q_fast=3 satisfies
    q1+q2 > n and q1 + 2*q_fast > 2n) stays clean across the space."""
    r = check_fp_exhaustive(n_prop=2, n_acc=4, q1=3, q2=2, q_fast=3)
    assert r.counterexample is None
    assert r.states > 50_000


# ---- Multi-Paxos (cpu_ref/mp_exhaustive.py) ----


@pytest.mark.slow
def test_mp_exhaustive_clean():
    """Every schedule of 2 proposers x 3 acceptors x 2-slot logs with one
    election each: whole-log phase 1, per-slot max-ballot recovery, and
    slot-by-slot phase 2 are per-slot-agreement-clean, and every finished
    leader's log equals the chosen values (~30k states; ~1.7M at
    asymmetric 2 retries — CLI `check --protocol multipaxos`)."""
    r = check_mp_exhaustive(n_prop=2, n_acc=3, log_len=2, max_round=1)
    assert r.counterexample is None
    assert r.states > 25_000
    assert r.decided_states > 5_000
    # Either proposer can own either slot across schedules.
    assert r.chosen_values == {1000, 1001, 2000, 2001}


@pytest.mark.slow
def test_mp_exhaustive_three_slots_clean():
    r = check_mp_exhaustive(n_prop=2, n_acc=3, log_len=3, max_round=1)
    assert r.counterexample is None
    assert r.states > 300_000


def test_mp_exhaustive_finds_no_recovery_bug():
    """A leader that skips the promise-payload fold (drives its own values
    from slot 0) must produce a counterexample: the second leader
    overwrites an already-chosen slot with its own value."""
    with pytest.raises(AssertionError, match="invariant violated"):
        check_mp_exhaustive(
            n_prop=2, n_acc=3, log_len=2, max_round=1, no_recovery=True
        )


# ---- Raft-core (cpu_ref/raft_exhaustive.py) ----


@pytest.mark.slow
def test_raft_exhaustive_clean():
    """Every schedule of 2 candidates x 3 voters with one retry: election
    restriction + one-vote-per-term + adoption + append/ack commit are
    agreement-clean across the bounded space."""
    r = check_raft_exhaustive(n_prop=2, n_acc=3, max_round=(1, 0))
    assert r.counterexample is None
    assert r.states > 80_000
    assert r.decided_states > 10_000
    assert r.chosen_values == {100, 101}


@pytest.mark.slow
def test_raft_exhaustive_each_safety_leg_suffices():
    """The kernel's safety argument rests on TWO mechanisms — the election
    restriction (real Raft's) and entry adoption from vote replies (the
    Paxos-phase-1 analog).  Exhaustively: EITHER alone keeps the space
    clean..."""
    r = check_raft_exhaustive(max_round=(1, 0), no_restriction=True)
    assert r.counterexample is None and r.states > 100_000
    r = check_raft_exhaustive(max_round=(1, 0), no_adoption=True)
    assert r.counterexample is None and r.states > 50_000


def test_raft_exhaustive_finds_double_bug():
    """... while removing BOTH yields a counterexample (a stale candidate
    wins with an empty log and commits a second value over the first)."""
    with pytest.raises(AssertionError, match="invariant violated"):
        check_raft_exhaustive(
            max_round=(1, 0), no_restriction=True, no_adoption=True
        )


# ---- SynchPaxos (cpu_ref/sp_exhaustive.py; bounded-delay fast path) ----

from paxos_tpu.cpu_ref.sp_exhaustive import check_sp_exhaustive  # noqa: E402


def test_sp_exhaustive_fast_path_only_clean():
    """max_round=0: no fallbacks, just the leader's fast broadcast under
    every delivery order — and it decides (the fast path is reachable)."""
    r = check_sp_exhaustive(n_prop=2, n_acc=3, max_round=0)
    assert r.counterexample is None
    assert r.decided_states > 0
    assert r.chosen_values == {100}  # round 0 has a single owner


def test_sp_exhaustive_with_fallback_clean():
    """Every interleaving of the fast round with classic fallbacks from
    both proposers: delta is a liveness bet, never a safety assumption, so
    arbitrarily late fast-round traffic must stay agreement-clean."""
    r = check_sp_exhaustive(n_prop=2, n_acc=3, max_round=1)
    assert r.counterexample is None
    assert r.states > 40_000
    assert r.decided_states > 0
    # Either the fast value or the follower's recovery value can win —
    # across schedules, never within one.
    assert r.chosen_values == {100, 101}


@pytest.mark.slow
def test_sp_exhaustive_deep_fallback_clean():
    """Two retries each (~4.4M states): late ACCEPTED quorums from the
    abandoned fast round never contradict a classically chosen value."""
    r = check_sp_exhaustive(n_prop=2, n_acc=3, max_round=2)
    assert r.counterexample is None
    assert r.states > 4_000_000


def test_sp_exhaustive_finds_unsafe_fast_bug():
    """The delay-unsafe fast commit (decide on the FIRST ack — 'one ack
    implies synchrony held') must yield a counterexample schedule."""
    with pytest.raises(AssertionError, match="invariant violated"):
        check_sp_exhaustive(n_prop=2, n_acc=3, max_round=1, unsafe_fast=True)


# ---- Mechanized liveness (VERDICT r3 #2) ----
#
# The fair-completion leg (exhaustive.make_liveness_checker): from EVERY
# reachable state, the deterministic fair schedule — drain the network in
# sorted order, then let the highest-ballot live proposer retry — must
# decide within the bound.  Each protocol is checked clean AND shown to
# produce a lasso counterexample under its injected livelock bug, so the
# leg is falsifiable, not vacuous.

from paxos_tpu.cpu_ref.exhaustive import LivenessViolation  # noqa: E402


@pytest.mark.slow
def test_liveness_paxos_clean():
    r = check_exhaustive(max_round=1, liveness_bound=60)
    assert r.states == 602_641  # liveness leg must not perturb the space
    assert 0 < r.max_completion <= 60


def test_liveness_paxos_livelock_bug_found():
    """Retry without ballot increase: the retry's PREPAREs sit at/below
    every promise already extracted, so the proposer re-collects nothing
    — a pure lasso (same state revisited)."""
    with pytest.raises(LivenessViolation, match="LASSO"):
        check_exhaustive(
            n_prop=1, n_acc=2, max_round=1, liveness_bound=60,
            livelock_bug=True,
        )
    with pytest.raises(LivenessViolation, match="LASSO"):
        check_exhaustive(max_round=1, liveness_bound=60, livelock_bug=True)


@pytest.mark.slow
def test_liveness_fastpaxos_clean_and_collision_recovery():
    """Fast Paxos is where the timeout arm of the fair completion earns
    its keep: a collided fast round leaves an EMPTY network with nobody
    decided, so completion must drive the classic recovery round.  (The
    n_acc=5 canonical space — all 4,013,181 states complete in <= 25 fair
    actions, ~8 min — is run via the CLI and recorded in BASELINE.md, per
    the same convention as test_fp_exhaustive_clean.)"""
    r = check_fp_exhaustive(n_acc=3, max_round=(1, 0), liveness_bound=60)
    assert r.max_completion > 0
    r4 = check_fp_exhaustive(n_acc=4, max_round=(1, 0), liveness_bound=80)
    assert r4.max_completion > 0


def test_liveness_fastpaxos_fast_retry_bug_found():
    """The injected fp livelock: on timeout, retry the FAST round instead
    of escalating to classic recovery.  Vote-at-most-once makes every
    re-broadcast a no-op against the collided tally — lasso."""
    with pytest.raises(LivenessViolation, match="LASSO"):
        check_fp_exhaustive(
            n_acc=3, max_round=(1, 0), liveness_bound=60, livelock_bug=True
        )


def test_liveness_multipaxos_clean():
    """MP exercises the timeout arm from the FIRST state: the initial
    network is empty (all traffic comes from leadership challenges)."""
    r = check_mp_exhaustive(max_round=(1, 1), liveness_bound=80)
    assert r.max_completion > 0


def test_liveness_multipaxos_frozen_challenge_bug_found():
    with pytest.raises(LivenessViolation, match="LASSO"):
        check_mp_exhaustive(
            max_round=1, liveness_bound=80, livelock_bug=True
        )


@pytest.mark.slow
def test_liveness_raft_clean():
    r = check_raft_exhaustive(max_round=(1, 0), liveness_bound=80)
    assert r.max_completion > 0


def test_liveness_synchpaxos_clean():
    """From every reachable state — including a fast round stranded by
    undelivered acks — the fair completion (drain, then let the leader
    fall back to a classic ballot) decides within the bound."""
    r = check_sp_exhaustive(n_prop=2, n_acc=3, max_round=1,
                            liveness_bound=40)
    assert r.states == 42_404  # liveness leg must not perturb the space
    assert 0 < r.max_completion <= 40


def test_liveness_raft_same_term_reelection_bug_found():
    """Re-election WITHOUT a term bump: every voter's one vote for the
    term is spent, so re-runs collect only denials — the split-vote
    livelock Raft's randomized timeouts + term bump exist to prevent."""
    with pytest.raises(LivenessViolation, match="LASSO"):
        check_raft_exhaustive(
            max_round=1, liveness_bound=80, livelock_bug=True
        )
