"""Bounded exhaustive model checking: every schedule of small instances."""

import pytest

from paxos_tpu.cpu_ref.exhaustive import check_exhaustive


def test_exhaustive_no_retries_clean():
    r = check_exhaustive(n_prop=2, n_acc=3, max_round=0)
    assert r.counterexample is None
    assert r.states > 3_000  # the whole bounded space, not a truncation
    assert r.decided_states > 0
    # Across different schedules either value can win — but never both in
    # one schedule (that would have raised).
    assert r.chosen_values == {100, 101}


@pytest.mark.parametrize("bounds", [(1, 0), (0, 1)])
def test_exhaustive_with_preemption_clean(bounds):
    """One proposer may retry past the other: the full dueling/stale-accept
    interleaving family, every schedule, ~50k states."""
    r = check_exhaustive(n_prop=2, n_acc=3, max_round=bounds)
    assert r.counterexample is None
    assert r.states > 40_000
    assert r.chosen_values == {100, 101}


def test_exhaustive_symmetric_retries_clean():
    """Both proposers retry: ~600k distinct states, all invariant-clean."""
    r = check_exhaustive(n_prop=2, n_acc=3, max_round=1)
    assert r.counterexample is None
    assert r.states > 500_000


def test_exhaustive_finds_injected_bug():
    """Accept-below-promise (THE classic Paxos bug) must yield a
    counterexample schedule — the model checker is falsifiable."""
    with pytest.raises(AssertionError, match="invariant violated"):
        check_exhaustive(
            n_prop=2, n_acc=3, max_round=(1, 0), unsafe_accept=True
        )


def test_exhaustive_five_acceptors_clean():
    r = check_exhaustive(n_prop=2, n_acc=5, max_round=0)
    assert r.counterexample is None
    assert r.states > 10_000
