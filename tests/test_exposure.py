"""Fault-exposure accounting (PR 9): off is free, on is neutral and honest.

Four contracts guard the exposure plane:

1. **Default-off is free**: with exposure disabled (the default) the state's
   ``exposure`` leaf is ``None`` (pruned from the pytree), schedules are
   BIT-IDENTICAL to the PR-6 golden digests (tests/test_gray.py, re-pinned
   here), and the default config fingerprint is unchanged so recorded
   artifacts keep matching.
2. **On is outcome-neutral**: the counters draw NO randomness — they count
   signals the tick already produced — so enabling them leaves the protocol
   schedule bit-identical on BOTH engines, and the fused Pallas kernel
   carries the counter arrays bit-exact vs its XLA reference via the
   generic packed-word passthrough.
3. **The counts are honest (the oracle)**: over a corrupt-fault campaign
   the device leaf's injected/effective corruption totals equal an
   independent host-side replay — jax-sampled masks plus a pure-numpy
   reimplementation of ``select_from_scores`` — exactly, on both engines'
   schedules, for all four protocols.
4. **The plumbing round-trips**: checkpoints restore the exposure config
   and counters bit-exact (pre-exposure snapshots default off), run
   reports embed the per-class block, and the metrics registry exports
   deterministically ordered gauges with the vacuous-chaos alert.
"""

import copy
import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paxos_tpu.faults.injector import exposure_lit
from paxos_tpu.harness import checkpoint
from paxos_tpu.harness import config as C
from paxos_tpu.harness.metrics import MetricsRegistry
from paxos_tpu.harness.run import (
    base_key,
    get_step_fn,
    init_plan,
    init_state,
    run,
    run_chunk,
)
from paxos_tpu.obs import exposure as expo

EXP = expo.ExposureConfig(counters=True)


def _digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(jax.device_get(leaf).tobytes())
    return h.hexdigest()[:16]


def _xla_final(cfg, n_ticks=32):
    return run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, n_ticks,
        get_step_fn(cfg.protocol),
    )


def _ctr_final(cfg, n_ticks=32):
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    return reference_chunk(
        init_state(cfg), cfg.seed, init_plan(cfg), cfg.fault, n_ticks,
        apply_fn=apply_fn, mask_fn=mask_fn, blk_id=0,
    )


# The PR-6 goldens (tests/test_gray.py, n_inst=256, seed=7, 32 ticks, CPU):
# exposure-off must reproduce them, and exposure-ON minus the counter leaf
# must reproduce them too (schedule unperturbed on both engines).
_GOLDEN_XLA = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "83347bc41b16a2aa"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "93a2dd9d7b8d66e4"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "c43658973b29e73e"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "4662db6b2c5a39d3"),
}
_GOLDEN_CTR = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "db6db6f40f16eb7b"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "4b6525460815d9c5"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "72beea3ccdacab94"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "eb285905571b709f"),
}

_FAST_XLA = ("config2",)
_FAST_CTR = ("config2",)


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_XLA else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_XLA)
    ],
)
def test_exposure_on_schedule_identical_xla(name):
    mk, want = _GOLDEN_XLA[name]
    assert _digest(_xla_final(mk())) == want  # off == PR-6 golden
    fin = _xla_final(dataclasses.replace(mk(), exposure=EXP))
    assert fin.exposure is not None
    # Every golden config has p_drop > 0, so the drop arm must count.
    rep = expo.exposure_report(fin.exposure)
    assert rep["classes"]["drop"]["injected"] > 0
    assert _digest(fin.replace(exposure=None)) == want  # on == same schedule


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_CTR else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_CTR)
    ],
)
def test_exposure_on_schedule_identical_counter_stream(name):
    mk, want = _GOLDEN_CTR[name]
    assert _digest(_ctr_final(mk())) == want
    fin = _ctr_final(dataclasses.replace(mk(), exposure=EXP))
    assert _digest(fin.replace(exposure=None)) == want


def test_default_off_prunes_to_none():
    """Disabled exposure leaves NO trace in the pytree or fingerprint."""
    for mk in (C.config1_no_faults, C.config3_multipaxos):
        cfg = mk(64, 0)
        state = init_state(cfg)
        assert state.exposure is None
        assert not cfg.exposure.enabled()
        on = init_state(dataclasses.replace(cfg, exposure=EXP))
        off_n = len(jax.tree_util.tree_leaves(state))
        on_n = len(jax.tree_util.tree_leaves(on))
        assert on_n == off_n + 2  # injected + effective
        # All counter leaves are non-scalar int32, instance-minor — the
        # fused engine's generic flattening rides them with no kernel edits.
        for leaf in jax.tree_util.tree_leaves(on.exposure):
            assert leaf.dtype == jnp.int32
            assert leaf.shape == (len(expo.CLASSES), 64)


def test_fingerprint_unchanged_by_default_exposure():
    """The default (off) ExposureConfig is dropped from the fingerprint, so
    pre-exposure artifacts keep matching; a non-default one IS keyed."""
    cfg = C.config2_dueling_drop(1 << 10)
    assert (
        dataclasses.replace(
            cfg, exposure=expo.ExposureConfig()
        ).fingerprint()
        == cfg.fingerprint()
    )
    assert (
        dataclasses.replace(cfg, exposure=EXP).fingerprint()
        != cfg.fingerprint()
    )


def test_record_accumulates_rows():
    exp = expo.FaultExposure.init(4)
    exp = expo.record(
        exp,
        drop=(
            jnp.array([1, 0, 2, 0], jnp.int32),
            jnp.array([1, 0, 0, 0], jnp.int32),
        ),
        # Bool event arrays with leading axes reduce via lane_count.
        dup=(jnp.ones((2, 4), jnp.bool_), None),
        stale=(None, None),
    )
    rep = expo.exposure_report(exp)
    assert rep["classes"]["drop"] == {
        "injected": 3, "effective": 1, "lanes_exposed": 1,
    }
    assert rep["classes"]["dup"] == {
        "injected": 8, "effective": 0, "lanes_exposed": 0,
    }
    assert rep["classes"]["stale"]["injected"] == 0
    with pytest.raises(ValueError):
        expo.record(exp, frobnicate=(None, None))


def test_annotate_lit_gray_chaos():
    fcfg = C.config_gray_chaos().fault
    assert sorted(n for n, on in exposure_lit(fcfg).items() if on) == [
        "drop", "dup", "partition", "timeout",
    ]
    zero = {
        "classes": {
            n: {"injected": 0, "effective": 0, "lanes_exposed": 0}
            for n in expo.CLASSES
        }
    }
    out = expo.annotate_lit(zero, fcfg)
    assert out["lit"] == ["drop", "dup", "partition", "timeout"]
    assert out["vacuous"] == out["lit"]  # all-zero report: every lit knob
    # config_corrupt lights drop AND corrupt (p_drop=0.1, p_corrupt=0.2).
    lit_c = exposure_lit(C.config_corrupt().fault)
    assert lit_c["corrupt"] and lit_c["drop"]
    assert not lit_c["stale"] and not lit_c["partition"]


def test_effective_delta_and_attribution():
    zero = {
        "classes": {
            n: {"injected": 0, "effective": 0, "lanes_exposed": 0}
            for n in expo.CLASSES
        }
    }
    cur = copy.deepcopy(zero)
    cur["classes"]["drop"]["effective"] = 5
    cur["classes"]["corrupt"]["effective"] = 2
    d = expo.effective_delta(zero, cur)
    assert d["drop"] == 5 and d["corrupt"] == 2 and d["timeout"] == 0
    assert expo.effective_delta(None, cur) == d
    chunks = [
        {"effective_delta": d, "new_bits": 3, "violations_delta": 1},
        {"effective_delta": {"drop": 1}, "new_bits": 2},
        {"effective_delta": {"timeout": 0}},  # zero delta: not active
    ]
    table = expo.attribution(chunks)
    assert table["drop"] == {
        "chunks_active": 2, "effective": 6, "new_bits": 5, "violations": 1,
    }
    assert table["corrupt"] == {
        "chunks_active": 1, "effective": 2, "new_bits": 3, "violations": 1,
    }
    assert table["timeout"]["chunks_active"] == 0


def test_run_report_embeds_exposure():
    cfg = dataclasses.replace(C.config2_dueling_drop(64, 0), exposure=EXP)
    rep = run(cfg, total_ticks=32, chunk=16)
    classes = rep["exposure"]["classes"]
    assert classes["drop"]["injected"] > 0
    assert classes["drop"]["effective"] <= classes["drop"]["injected"]
    assert classes["corrupt"]["injected"] == 0  # knob off: arm never traced
    # And with the default config the report has NO exposure block.
    rep_off = run(C.config2_dueling_drop(64, 0), total_ticks=16, chunk=8)
    assert "exposure" not in rep_off


# ---------------------------------------------------------------------------
# The oracle: device injected/effective corruption totals == an independent
# host-side replay (jax-sampled masks + a pure-numpy reimplementation of
# transport.select_from_scores), exactly, on both engines' schedules.

_ORACLE_TICKS = 256


def _np_select(present, score_bits, busy):
    """Numpy mirror of ``transport.inmemory_tpu.select_from_scores``."""
    k, p, a, i = present.shape
    nbits = max((k * p - 1).bit_length(), 1)
    sid = (
        np.arange(k, dtype=np.int32).reshape(k, 1, 1, 1) * p
        + np.arange(p, dtype=np.int32).reshape(1, p, 1, 1)
    )
    score = (score_bits.astype(np.int32) & np.int32(~((1 << nbits) - 1))) | sid
    neg_inf = np.iinfo(np.int32).min
    score = np.where(present, score, neg_inf)
    fiber_max = score.max(axis=(0, 1), keepdims=True)
    sel = present & (score == fiber_max) & (fiber_max > neg_inf)
    if busy is not None:
        sel = sel & busy
    return sel


def _corrupt_cfg(protocol):
    return dataclasses.replace(
        C.config_corrupt(128, 11), protocol=protocol, exposure=EXP
    )


@pytest.mark.parametrize(
    "engine,protocol",
    [
        ("xla", "paxos"),
        ("ctr", "paxos"),
        pytest.param("xla", "multipaxos", marks=pytest.mark.slow),
        pytest.param("xla", "fastpaxos", marks=pytest.mark.slow),
        pytest.param("xla", "raftcore", marks=pytest.mark.slow),
        pytest.param("ctr", "multipaxos", marks=pytest.mark.slow),
        pytest.param("ctr", "fastpaxos", marks=pytest.mark.slow),
        pytest.param("ctr", "raftcore", marks=pytest.mark.slow),
    ],
)
def test_injected_vs_effective_oracle(engine, protocol):
    """Effective corruption = mask & "some acceptor selected a message":
    replaying the campaign tick by tick and recomputing the selection with
    an independent numpy mirror must reproduce the device leaf EXACTLY."""
    from paxos_tpu.core import streams as streams_mod

    cfg = _corrupt_cfg(protocol)
    plan = init_plan(cfg)
    state = init_state(cfg)
    if protocol == "multipaxos":
        from paxos_tpu.protocols.multipaxos import sample_mp_masks as sampler
    else:
        from paxos_tpu.protocols.paxos import sample_masks as sampler

    if engine == "xla":
        key = base_key(cfg)
        step = get_step_fn(cfg.protocol)

        def masks_at(t, st):
            return sampler(
                streams_mod.tick_key(key, jnp.int32(t)), cfg.fault,
                cfg.n_prop, cfg.n_acc, cfg.n_inst,
            )

        def advance(st):
            return run_chunk(st, key, plan, cfg.fault, 1, step)
    else:  # the fused engine's schedule via its bit-exact XLA reference
        from paxos_tpu.kernels.counter_prng import mix
        from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

        apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
        seed = jnp.int32(cfg.seed)

        # Jit the per-tick stepper and sampler ONCE — re-tracing
        # reference_chunk 256 times costs minutes; the compiled ticks are
        # bit-identical to the untraced ones.
        @jax.jit
        def _masks(t, st):
            return mask_fn(cfg.fault, mix(seed, t, jnp.int32(0)), st)

        @jax.jit
        def advance(st):
            return reference_chunk(
                st, seed, plan, cfg.fault, 1,
                apply_fn=apply_fn, mask_fn=mask_fn,
            )

        def masks_at(t, st):
            return _masks(jnp.int32(t), st)

    host_inj = host_eff = 0
    for t in range(_ORACLE_TICKS):
        present = np.asarray(jax.device_get(state.requests.present))
        m = masks_at(t, state)
        corrupt = np.asarray(jax.device_get(m.corrupt))
        sel = _np_select(
            present,
            np.asarray(jax.device_get(m.sel_score)),
            np.asarray(jax.device_get(m.busy)),
        )
        # config_corrupt has no crash/partition knobs, but apply the plan's
        # alive mask anyway — the mirror must track the protocol, not the
        # config we happen to test with.
        sel = sel & np.asarray(jax.device_get(plan.alive(jnp.int32(t))))[
            None, None
        ]
        eff = corrupt & sel.any(axis=(0, 1))
        host_inj += int(corrupt.sum())
        host_eff += int(eff.sum())
        state = advance(state)

    row = expo.exposure_report(state.exposure)["classes"]["corrupt"]
    assert row["injected"] == host_inj
    assert row["effective"] == host_eff
    assert 0 < host_eff <= host_inj


@pytest.mark.parametrize(
    "protocol",
    [
        "paxos",
        pytest.param("multipaxos", marks=pytest.mark.slow),
        pytest.param("fastpaxos", marks=pytest.mark.slow),
        pytest.param("raftcore", marks=pytest.mark.slow),
    ],
)
def test_fused_kernel_carries_exposure_bitexact(protocol):
    """fused_chunk(interpret) == reference_chunk with the counters ON: the
    packed-word passthrough codec must round-trip them bit-exactly."""
    from paxos_tpu.kernels.fused_tick import (
        FUSED_CHUNKS,
        fused_fns,
        reference_chunk,
    )
    from paxos_tpu.utils.trees import tree_mismatches

    cfg = dataclasses.replace(
        C.config_corrupt(64, 7), protocol=protocol, exposure=EXP
    )
    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    plan = init_plan(cfg)
    sr = reference_chunk(
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        apply_fn=apply_fn, mask_fn=mask_fn,
    )
    sp = FUSED_CHUNKS[cfg.protocol](
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        block=64, interpret=True,
    )
    assert tree_mismatches(sp, sr) == []
    rep = expo.exposure_report(sp.exposure)
    assert rep["classes"]["corrupt"]["injected"] > 0


# ---------------------------------------------------------------------------
# Checkpoint round-trip (satellite 1) and metrics determinism (satellite 2).


def test_checkpoint_roundtrip_with_exposure(tmp_path):
    """Save/restore rebuilds the exposure config AND the counter arrays, so
    a resumed campaign's exposure totals are bit-identical."""
    cfg = dataclasses.replace(C.config2_dueling_drop(64, 3), exposure=EXP)
    step = get_step_fn(cfg.protocol)
    key, plan = base_key(cfg), init_plan(cfg)
    state = run_chunk(init_state(cfg), key, plan, cfg.fault, 16, step)
    checkpoint.save(tmp_path / "ck", state, plan, cfg, engine="xla")
    st2, pl2, cfg2 = checkpoint.restore(tmp_path / "ck", engine="xla")
    assert cfg2.exposure == EXP
    assert st2.exposure is not None
    fin_a = run_chunk(state, key, plan, cfg.fault, 16, step)
    fin_b = run_chunk(st2, base_key(cfg2), pl2, cfg2.fault, 16, step)
    assert _digest(fin_a) == _digest(fin_b)  # exposure leaves included


def test_checkpoint_restore_pre_exposure_snapshot(tmp_path):
    """Snapshots written before the exposure plane (no key in the JSON)
    restore with the default-off config and a pruned leaf."""
    cfg = C.config2_dueling_drop(64, 3)
    checkpoint.save(tmp_path / "ck", init_state(cfg), init_plan(cfg), cfg)
    meta_path = tmp_path / "ck" / "simconfig.json"
    raw = json.loads(meta_path.read_text())
    raw.pop("exposure")
    meta_path.write_text(json.dumps(raw))
    st2, _, cfg2 = checkpoint.restore(tmp_path / "ck")
    assert cfg2.exposure == expo.ExposureConfig()
    assert st2.exposure is None


def test_exposure_metrics_sorted_and_pinned():
    """Registry exports are deterministically ordered regardless of ingest
    order, and lit-but-zero classes raise the vacuous-chaos gauge."""
    rep = {
        "classes": {
            n: {
                "injected": 10 * (i + 1),
                "effective": 0 if n == "timeout" else i + 1,
                "lanes_exposed": i,
            }
            for i, n in enumerate(expo.CLASSES)
        }
    }
    lit = {"drop": True, "timeout": True, "corrupt": False}
    reg = MetricsRegistry()
    reg.ingest_exposure(rep, lit=lit)
    gauges = reg.snapshot()["gauges"]
    keys = list(gauges)
    assert keys == sorted(keys)  # the JSONL/stats ordering pin
    assert gauges["exposure_injected{class=drop}"] == 10
    assert gauges["fault_vacuous{class=timeout}"] == 1.0
    assert gauges["fault_vacuous{class=drop}"] == 0.0
    assert "fault_vacuous{class=corrupt}" not in gauges  # unlit: no alert
    prom = reg.to_prometheus()
    assert 'paxos_tpu_fault_vacuous{class="timeout"} 1' in prom
    # Reversed-order ingest must serialize identically (sorted everywhere).
    rep2 = {"classes": dict(reversed(list(rep["classes"].items())))}
    reg2 = MetricsRegistry()
    reg2.ingest_exposure(rep2, lit=dict(reversed(list(lit.items()))))
    assert json.dumps(reg2.snapshot(), sort_keys=False) == json.dumps(
        reg.snapshot(), sort_keys=False
    )
    assert reg2.to_prometheus() == prom
