"""Fast Paxos: fast-path decisions, collision recovery, checker falsifiability.

SURVEY.md §5.2: property/invariant tests over random fault masks plus
adversarial configs; the checker itself is validated by injecting
equivocation (it must light up).
"""

import jax.numpy as jnp
import pytest

from paxos_tpu.core.fp_state import DONE, VALUE_BASE
from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.run import run


def fp_cfg(n_inst=1024, n_prop=2, n_acc=5, seed=0, k_slots=8, **fault_kw):
    return SimConfig(
        n_inst=n_inst,
        n_prop=n_prop,
        n_acc=n_acc,
        seed=seed,
        k_slots=k_slots,
        protocol="fastpaxos",
        fault=FaultConfig(**fault_kw),
    )


def test_fast_path_no_faults_single_proposer():
    """One proposer, clean network: every instance decides via the fast round."""
    cfg = fp_cfg(n_inst=512, n_prop=1, n_acc=5)
    report, state = run(cfg, until_all_chosen=True, max_ticks=64, return_state=True)
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["chosen_frac"] == 1.0
    # The sole value is proposer 0's; chosen in the fast round (ballot round 0
    # needs ceil(3*5/4)=4 acceptors, reachable by tick ~2 with no faults).
    assert bool((state.learner.chosen_val == VALUE_BASE).all())
    assert report["mean_choose_tick"] < 8.0
    assert bool((state.proposer.phase == DONE).all())


def test_dueling_proposers_collision_recovery():
    """Two proposers race the fast round; collided lanes recover classically."""
    cfg = fp_cfg(n_inst=2048, n_prop=2, n_acc=5, p_idle=0.2, p_hold=0.2)
    report, state = run(
        cfg, until_all_chosen=True, max_ticks=2048, return_state=True
    )
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["chosen_frac"] == 1.0
    assert report["proposer_disagree"] == 0
    vals = state.learner.chosen_val
    assert bool(((vals >= VALUE_BASE) & (vals < VALUE_BASE + 2)).all())
    # With two proposers colliding at 3-of-5 vs 2-of-5, some lanes MUST have
    # needed classic recovery: those chose at a classic (round >= 1) ballot,
    # visible as a later chosen_tick than any pure-fast decision.
    assert report["mean_choose_tick"] > 2.0


def test_chaos_safety():
    """Drop + dup + idle + hold + acceptor crashes: zero violations."""
    cfg = fp_cfg(
        n_inst=2048,
        n_prop=2,
        n_acc=5,
        seed=3,
        # Long chaotic duels visit many (ballot, value) pairs; keep the
        # checker's completeness bound (evictions == 0) with a deeper table.
        k_slots=12,
        p_drop=0.1,
        p_dup=0.1,
        p_idle=0.2,
        p_hold=0.2,
        p_crash=0.2,
        crash_max_start=64,
        crash_max_len=32,
    )
    report = run(cfg, total_ticks=512)
    assert report["violations"] == 0
    assert report["evictions"] == 0
    # Liveness under chaos: most lanes should still decide.
    assert report["chosen_frac"] > 0.9


def test_equivocation_lights_up_checker():
    """Config-4-style falsifiability: equivocating acceptors double-vote in the
    fast round, so conflicting values can both reach a fast quorum — the
    checker must catch it."""
    cfg = fp_cfg(
        n_inst=4096, n_prop=2, n_acc=5, seed=1, p_idle=0.2, p_equiv=0.5
    )
    report = run(cfg, total_ticks=256)
    assert report["violations"] > 0


def test_deterministic_replay():
    """Same seed => bit-identical outcome (SURVEY.md §6.2 determinism)."""
    cfg = fp_cfg(n_inst=256, n_prop=2, n_acc=5, seed=7, p_drop=0.1, p_idle=0.2)
    r1, s1 = run(cfg, total_ticks=200, return_state=True)
    r2, s2 = run(cfg, total_ticks=200, return_state=True)
    assert r1 == r2
    assert bool(jnp.array_equal(s1.learner.chosen_val, s2.learner.chosen_val))


def test_ffp_safe_quorums_clean():
    """Fast Flexible Paxos (arXiv:2008.02671): q1=4, q2=2, q_fast=4 over 5
    acceptors satisfies q1+q2>n and q1+2*q_fast>2n => safe under chaos."""
    from paxos_tpu.harness.config import config_ffp

    report = run(
        config_ffp(4, 2, 4, n_inst=4096, seed=1),
        until_all_chosen=True,
        max_ticks=512,
    )
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["chosen_frac"] == 1.0


def test_ffp_unsafe_fast_quorum_trips_checker():
    """q1=3, q2=3, q_fast=3: CLASSICALLY safe (3+3 > 5) but fast-unsafe
    (3 + 2*3 <= 10) — a phase-1 quorum can miss a fast-chosen value and
    choose another.  Violations here can only come from the q_fast path,
    so this test fails if cfg.q_fast is ever silently ignored."""
    from paxos_tpu.harness.config import config_ffp

    report = run(config_ffp(3, 3, 3, n_inst=8192, seed=1), total_ticks=256)
    assert report["violations"] > 0
