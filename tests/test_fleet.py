"""Fleet: durable queue, lease recovery, and merge determinism.

The load-bearing contract under test: campaigns are deterministic in
(config, seed, plan) and all fleet artifacts are wall-clock-free, so a
worker crash + lease reclaim + re-run produces a merged output
byte-identical to an uninterrupted run's.  The tier-1 tests drive the
whole state machine in-process with the deterministic preemption hook
(`WorkerPreempted`); the slow test does it for real with subprocess
workers and a seeded SIGKILL.
"""

import argparse
import json

import pytest

from paxos_tpu.fleet.coordinator import (
    chaos_kill_ordinals,
    merge_results,
    plan_records,
)
from paxos_tpu.fleet.queue import CampaignQueue, LeaseLost
from paxos_tpu.fleet.worker import WorkerPreempted, run_record
from paxos_tpu.harness.retry import (
    equal_jitter,
    jitter_stream,
    retry_schedule,
    run_with_retries,
)


# -- queue state machine (no jax, explicit clocks) ------------------------

def _rec(campaign, **kw):
    return {"campaign": campaign, "mode": "soak", "attempt": 0} | kw


def test_queue_lifecycle(tmp_path):
    q = CampaignQueue(tmp_path / "q")
    ids = [q.enqueue(_rec(i)) for i in range(3)]
    assert ids == ["c00000", "c00001", "c00002"]
    assert q.pending_count() == 3

    got = q.claim("w0", now=100.0, lease_s=10.0)
    assert got is not None
    rec_id, record = got
    assert rec_id == "c00000"  # canonical (sorted) claim order
    assert record["campaign"] == 0
    assert q.pending_count() == 2 and q.claimed_count() == 1
    assert q.leases()[rec_id]["worker"] == "w0"

    q.renew(rec_id, "w0", now=105.0, lease_s=10.0)
    assert q.leases()[rec_id]["expires"] == 115.0
    with pytest.raises(LeaseLost):
        q.renew(rec_id, "w1", now=105.0, lease_s=10.0)  # not the owner

    q.complete(rec_id, "w0", {"campaign": 0, "ok": True})
    assert q.done_count() == 1 and q.claimed_count() == 0
    assert rec_id not in q.leases()
    assert q.results() == {"c00000": {"campaign": 0, "ok": True}}


def test_queue_expiry_reclaim_and_lease_loss(tmp_path):
    q = CampaignQueue(tmp_path / "q")
    q.enqueue(_rec(0))
    rec_id, _ = q.claim("w0", now=0.0, lease_s=10.0)

    # A live lease is never reclaimed; an expired one goes back to
    # pending with attempt + 1, and the presumed-dead owner learns of it
    # exactly once — at its next renewal.
    assert q.reclaim_expired(now=5.0) == []
    assert q.reclaim_expired(now=10.1) == [rec_id]
    assert q.pending_count() == 1 and q.claimed_count() == 0
    assert q.record(rec_id)["attempt"] == 1
    with pytest.raises(LeaseLost):
        q.renew(rec_id, "w0", now=10.2, lease_s=10.0)
    with pytest.raises(LeaseLost):
        q.complete(rec_id, "w0", {"campaign": 0})

    # The replacement claims the same record at attempt 1.
    rec_id2, record2 = q.claim("w1", now=11.0, lease_s=10.0)
    assert rec_id2 == rec_id and record2["attempt"] == 1
    assert q.leases()[rec_id]["attempt"] == 1


def test_queue_claimed_without_lease_is_reclaimable(tmp_path):
    """A crash between the claim rename and the lease write leaves a
    claimed record with no lease — reclaim treats that as expired."""
    q = CampaignQueue(tmp_path / "q")
    q.enqueue(_rec(0))
    rec_id, _ = q.claim("w0", now=0.0, lease_s=10.0)
    (q.root / "leases" / f"{rec_id}.json").unlink()
    assert q.reclaim_expired(now=0.0) == [rec_id]


def test_queue_torn_record_is_quarantined(tmp_path):
    """Torn JSON (crash mid-enqueue) must not crash-loop every claimer:
    the bytes are quarantined and the claim moves on."""
    q = CampaignQueue(tmp_path / "q")
    (q.root / "pending" / "c00000.json").write_text('{"campaign": 0, "mo')
    q.enqueue(_rec(1))
    rec_id, _ = q.claim("w0", now=0.0, lease_s=10.0)
    assert rec_id == "c00001"
    assert q.torn_records == 1
    assert (q.root / "tmp" / "c00000.torn").exists()


# -- retry: pure-integer jitter ------------------------------------------

def test_retry_jitter_is_seeded_and_bounded():
    sched = retry_schedule(4, base_s=1.0, cap_s=4.0)
    assert sched == [1.0, 2.0, 4.0, 4.0]
    a = [equal_jitter(d, jitter_stream(9)) for d in sched]
    b = [equal_jitter(d, jitter_stream(9)) for d in sched]
    c = [equal_jitter(d, jitter_stream(10)) for d in sched]
    assert a == b, "same seed must pin the exact sleep sequence"
    assert a != c
    for delay, sleep in zip(sched, a):
        assert delay / 2.0 <= sleep <= delay  # equal jitter band


def test_run_with_retries_sleeps_deterministically(monkeypatch):
    from paxos_tpu.harness import retry as retry_mod

    slept = []
    monkeypatch.setattr(retry_mod.time, "sleep", slept.append)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("blip")
        return "ok"

    out, used = run_with_retries(
        flaky, lambda s: None, retries=3, backoff_s=1.0, jitter_seed=9
    )
    assert (out, used) == ("ok", 2)
    stream = jitter_stream(9)
    expected = [
        equal_jitter(d, stream) for d in retry_schedule(2, base_s=1.0)
    ]
    assert slept == expected

    with pytest.raises(ValueError):  # not in retry_on: no retry, no sleep
        run_with_retries(
            lambda: (_ for _ in ()).throw(ValueError("no")),
            lambda s: None, retries=3, jitter_seed=9,
        )


# -- chaos schedule and merge --------------------------------------------

def test_chaos_kill_ordinals_deterministic():
    a = chaos_kill_ordinals(7, kills=2, n_records=8)
    assert a == chaos_kill_ordinals(7, kills=2, n_records=8)
    assert len(a) == 2 and all(0 <= k < 8 for k in a)
    assert chaos_kill_ordinals(8, kills=2, n_records=8) != a
    assert len(chaos_kill_ordinals(0, kills=5, n_records=3)) == 3


def test_merge_results_order_union_and_repro_dedup():
    shard = lambda c, u, **kw: {
        "campaign": c, "union_hex": u, "bits_total": 8, "rounds": 10,
        "seeds": 1, "resumed_seeds": 0, "violations": 0,
        "violating_seeds": [], "attempt": 0,
    } | kw
    a = shard(1, "f0", attempt=1,
              repro={"config_fingerprint": "x", "seed": 3, "entry": 1})
    b = shard(0, "0f", violations=1, violating_seeds=[5],
              repro={"config_fingerprint": "x", "seed": 3, "entry": 9})
    merged = merge_results([a, b])           # completion order b-after-a
    merged2 = merge_results([b, a])
    assert merged == merged2, "merge must be canonical-order, not arrival"
    assert merged["union_hex"] == "ff"
    assert merged["coverage"]["bits_set"] == 8
    assert merged["violations"] == 1 and merged["violating_seeds"] == [5]
    assert merged["campaigns_retried"] == 1
    assert len(merged["repros"]) == 1 and merged["repro_dedup"] == 1
    assert merged["repros"][0]["entry"] == 9  # canonical-first (campaign
    # 0's shard) survives, regardless of which shard finished first


def test_partition_devices_contiguous():
    import jax

    from paxos_tpu.parallel.mesh import partition_devices

    devs = jax.devices()
    parts = partition_devices(3, devs)
    assert [d for part in parts for d in part] == devs  # contiguous cover
    sizes = [len(p) for p in parts]
    assert max(sizes) - min(sizes) <= 1
    solo = partition_devices(len(devs) + 2, devs)
    assert all(p == [devs[0]] for p in solo[len(devs):]) or all(
        p == [devs[0]] for p in solo
    )
    with pytest.raises(ValueError):
        partition_devices(0, devs)


# -- recovery determinism (in-process fake workers, tier-1) ---------------

_SOAK_KW = dict(
    config="config2", n_inst=64, fault=[], seed=0, records=2,
    seeds_per_record=2, ticks_per_seed=32, chunk=16, coverage_words=64,
)


def _run_all(queue, records, worker="w0", preempt_first=None):
    """Drain a queue in-process.  ``preempt_first`` kills the first
    record after N durable seeds, then reclaims and re-runs it — the
    deterministic stand-in for SIGKILL + coordinator recovery."""
    for rec in records:
        queue.enqueue(rec)
    results = []
    preempted = False
    wid = worker
    while True:
        claim = queue.claim(wid, now=0.0, lease_s=10.0)
        if claim is None:
            break
        rec_id, record = claim
        if preempt_first is not None and not preempted:
            preempted = True
            with pytest.raises(WorkerPreempted):
                run_record(queue, rec_id, record, wid,
                           stop_after_seeds=preempt_first)
            assert queue.reclaim_expired(now=1e9) == [rec_id]
            wid = "w1"  # the replacement claims it next pass
            continue
        res = run_record(queue, rec_id, record, wid)
        queue.complete(rec_id, wid, res)
        results.append(res)
    return merge_results(results)


def test_soak_recovery_matches_uninterrupted_baseline(tmp_path):
    """A soak record killed after one durable seed, reclaimed, and
    resumed by another worker must merge to the byte-identical coverage
    union and violation tally of an uninterrupted fleet — and the resume
    must actually be a resume (seed-granular, not a re-run)."""
    records = plan_records(mode="soak", **_SOAK_KW)
    base = _run_all(CampaignQueue(tmp_path / "base"), records)
    rec = _run_all(CampaignQueue(tmp_path / "rec"), records,
                   preempt_first=1)
    assert int(base["union_hex"], 16) != 0
    assert rec["union_hex"] == base["union_hex"]
    assert rec["violations"] == base["violations"] == 0
    assert rec["seeds"] == base["seeds"] == 4
    assert rec["resumed_seeds"] == 1 and base["resumed_seeds"] == 0
    assert rec["campaigns_retried"] == 1


def test_fuzz_recovery_matches_uninterrupted_baseline(tmp_path):
    """Fuzz records are atomic recovery units: the guided feedback loop
    is sequential, so recovery is deterministic FULL replay — the merged
    corpus journal digest must equal the uninterrupted baseline's."""
    records = plan_records(
        mode="fuzz", config="config2", n_inst=64, fault=[], seed=0,
        records=2, seeds_per_record=0, ticks_per_seed=32, chunk=16,
        coverage_words=64, seed_stride=100, rng_seed=0,
        campaigns_per_record=3,
    )
    base = _run_all(CampaignQueue(tmp_path / "base"), records)
    rec = _run_all(CampaignQueue(tmp_path / "rec"), records,
                   preempt_first=2)
    assert int(base["union_hex"], 16) != 0
    assert base["journal_entries"] > 0
    assert rec["journal_digest"] == base["journal_digest"]
    assert rec["journal_entries"] == base["journal_entries"]
    assert rec["union_hex"] == base["union_hex"]
    assert rec["violations"] == base["violations"]
    assert rec["campaigns_retried"] == 1


def test_stale_progress_journal_is_discarded(tmp_path):
    """Progress written under a different schedule stream (same record
    id, different config) must be discarded, not spliced: the re-run
    starts from scratch and still matches the clean baseline."""
    records = plan_records(mode="soak", **_SOAK_KW)[:1]
    base_q = CampaignQueue(tmp_path / "base")
    base = _run_all(base_q, records)

    q = CampaignQueue(tmp_path / "poisoned")
    from paxos_tpu.fuzz.corpus import append_event

    with open(q.progress_path("c00000"), "a") as fh:
        append_event(fh, {"event": "header", "record": "c00000",
                          "stream": {"algo": "other", "root": 1},
                          "fingerprint": "bogus", "attempt": 0})
        append_event(fh, {"event": "seed", "seed": 0,
                          "union_hex": "ffff", "violations": 7,
                          "rounds": 1})
    rec = _run_all(q, records)
    assert rec["resumed_seeds"] == 0, "stale progress must not resume"
    assert rec["union_hex"] == base["union_hex"]
    assert rec["violations"] == base["violations"]


# -- the real thing: subprocess workers + seeded SIGKILL ------------------

def _fleet_ns(**kw):
    ns = argparse.Namespace(
        workers=2, lease_s=6.0, poll_s=0.2, hold_s=0.0, timeout_s=420.0,
        chaos=False, chaos_kills=1, chaos_seed=7, platform="cpu",
        bench_baseline=None,
    )
    for k, v in kw.items():
        setattr(ns, k, v)
    return ns


@pytest.mark.slow
def test_chaos_fleet_matches_uninterrupted_baseline(tmp_path):
    """End to end with real subprocess workers: a chaos fleet (seeded
    SIGKILL mid-hold, lease reclaim, respawn) must complete its budget
    and produce the same merged union and violation tally as an
    in-process uninterrupted run of the same records."""
    from paxos_tpu.fleet.coordinator import run_fleet

    records = plan_records(mode="soak", **_SOAK_KW)
    base = _run_all(CampaignQueue(tmp_path / "base"), records)

    report, rc = run_fleet(
        records, tmp_path / "fleet",
        _fleet_ns(chaos=True, hold_s=1.5),
        log=lambda s: None,
    )
    assert rc == 0
    assert report["completed"]
    assert report["chaos"]["kills_done"] == 1
    assert report["fleet"]["leases_reclaimed"] >= 1
    assert report["fleet"]["records_done"] == len(records)
    assert report["union_hex"] == base["union_hex"]
    assert report["violations"] == base["violations"] == 0
