"""Flexible Paxos (FPaxos): separate phase-1/phase-2 quorums.

Safety requires every phase-1 quorum to intersect every phase-2 quorum:
q1 + q2 > n.  The safe pair must fuzz clean; the unsafe pair must light up
the agreement checker — the falsifiability twin of config 4.
"""

from paxos_tpu.harness.config import config_flex
from paxos_tpu.harness.run import run


def test_flex_safe_quorums_clean():
    # q1=4, q2=2 over 5 acceptors: intersecting (4 + 2 > 5) => safe.
    report = run(
        config_flex(4, 2, n_inst=8192, seed=11),
        until_all_chosen=True,
        max_ticks=512,
    )
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["chosen_frac"] == 1.0
    assert report["proposer_disagree"] == 0


def test_flex_unsafe_quorums_trip_checker():
    # q1=2, q2=2 over 5 acceptors: 2 + 2 <= 5, quorums need not intersect —
    # dueling proposers can each get a disjoint phase-2 quorum for different
    # values.  The checker MUST catch the agreement break.
    report = run(config_flex(2, 2, n_inst=8192, seed=11), total_ticks=256)
    assert report["violations"] > 0
