"""Dataflow non-interference auditor (paxos_tpu.analysis.flow): clean +
planted-violation tests.

Mirrors tests/test_audit.py's two halves:

1. **Clean**: the flow theorems (observer non-interference, fault-channel
   confinement, checker isolation, lane independence) hold over the full
   8-config x 4-protocol audit matrix, for BOTH engines' traces.  These
   pin the auditor AND the tree: a leaked observer value or a botched
   lane rule regresses here first.
2. **Mutations**: each theorem is fed a planted violation (observer leaf
   folded into ballot state, observer value steering a PRNG fold,
   fault-plan leaf applied outside its registered injection site, a
   cross-lane roll, the checker writing acceptor state, a margin counter
   read back into timeout logic, an unregistered fault_site tag) and must
   produce a finding that NAMES the source leaf and the sink — a taint
   auditor that fires without saying which leaf leaked where is a worse
   debugging experience than no auditor.

Everything here is trace-time only (no campaign executes), so the whole
module rides the fast ``-m 'not slow'`` tier.
"""

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.analysis import flow, purity
from paxos_tpu.analysis import trace as trace_mod
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state

PROTOCOLS = trace_mod.PROTOCOLS
CONFIGS = tuple(trace_mod.CONFIG_MATRIX)


def _probe(protocol, config, wrap):
    """Trace ``wrap``'s mutated step for one cell and run all theorems."""
    cfg = trace_mod.build_config(protocol, config)
    step = get_step_fn(protocol)
    fn = wrap(step, cfg)
    closed = jax.make_jaxpr(fn)(init_state(cfg), base_key(cfg), init_plan(cfg))
    return flow.analyze_step_jaxpr(
        closed, flow.build_spec(protocol, cfg), f"{protocol}/{config} probe"
    )


# ------------------------------------------------------------------- clean


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_clean_flow_full_matrix(protocol):
    """All four theorems hold for every config cell on both engines."""
    for config in CONFIGS:
        cfg = trace_mod.build_config(protocol, config)
        xla = trace_mod.trace_xla_step(protocol, cfg)
        ctr = trace_mod.trace_counter_tick(protocol, cfg)
        findings = flow.audit_flow(protocol, config, cfg, xla, ctr)
        assert findings == [], (config, [str(f) for f in findings])


def test_fault_sites_registered_for_every_protocol():
    """Every protocol registers the injector sites plus its step sites,
    each with at least one declared channel."""
    for protocol in PROTOCOLS:
        sites = flow.fault_sites(protocol)
        for name in ("alive", "link_ok", "equivocate", "flaky", "skew"):
            assert name in sites, (protocol, name)
            assert sites[name], (protocol, name)


def test_eqn_budget_clean_and_drift_detected():
    """Eqn counts match goldens; a synthetic 2x blowup is flagged."""
    cfg = trace_mod.build_config("paxos", "default")
    xla = trace_mod.trace_xla_step("paxos", cfg)
    ctr = trace_mod.trace_counter_tick("paxos", cfg)
    assert flow.audit_eqn_budget("paxos", "default", xla, ctr) == []

    def doubled(st, key, pl):
        step = get_step_fn("paxos")
        out = step(st, key, pl, cfg.fault)
        return step(out, key, pl, cfg.fault)

    fat = jax.make_jaxpr(doubled)(
        init_state(cfg), base_key(cfg), init_plan(cfg)
    )
    findings = flow.audit_eqn_budget("paxos", "default", fat, ctr)
    assert any(
        f.check == "eqn-budget" and "record-goldens" in f.message
        for f in findings
    ), findings


# --------------------------------------------------------------- mutations


def test_mutation_observer_leak_detected():
    """Theorem 1: a telemetry counter folded into ballot state is named."""

    def wrap(step, cfg):
        def leaky(st, key, pl):
            out = step(st, key, pl, cfg.fault)
            leak = out.telemetry.counters[0].astype(jnp.int32)
            return out.replace(
                proposer=out.proposer.replace(bal=out.proposer.bal + leak[None])
            )

        return leaky

    findings = _probe("paxos", "telemetry", wrap)
    assert any(
        f.check == "flow-observer"
        and "telemetry.counters" in f.message
        and "proposer.bal" in f.message
        and f.data["theorem"] == "observer"
        for f in findings
    ), [str(f) for f in findings]


def test_mutation_observer_prng_fold_detected():
    """Theorem 1 (PRNG corollary): a coverage value steering fold_in."""

    def wrap(step, cfg):
        def prngy(st, key, pl):
            key = jax.random.fold_in(key, st.coverage.new_bits[0])
            return step(st, key, pl, cfg.fault)

        return prngy

    findings = _probe("paxos", "coverage", wrap)
    assert any(
        f.check == "flow-prng"
        and "coverage.new_bits" in f.message
        and "random_fold_in" in f.message
        for f in findings
    ), [str(f) for f in findings]


def test_mutation_fault_outside_site_detected():
    """Theorem 2: plan.equivocate applied without a fault_site scope."""

    def wrap(step, cfg):
        def fleaky(st, key, pl):
            out = step(st, key, pl, cfg.fault)
            return out.replace(
                acceptor=out.acceptor.replace(
                    promised=out.acceptor.promised
                    + pl.equivocate.astype(jnp.int32)
                )
            )

        return fleaky

    findings = _probe("paxos", "default", wrap)
    assert any(
        f.check == "flow-fault"
        and "'equivocate'" in f.message
        and "acceptor.promised" in f.message
        and f.data["channel"] == "equiv"
        for f in findings
    ), [str(f) for f in findings]


def test_mutation_unregistered_site_detected():
    """Theorem 2: an unknown fault_site tag is itself a finding."""
    from paxos_tpu.faults.injector import fault_site

    def wrap(step, cfg):
        def rogue(st, key, pl):
            out = step(st, key, pl, cfg.fault)
            with fault_site("rogue"):
                promised = out.acceptor.promised + pl.equivocate.astype(
                    jnp.int32
                )
            return out.replace(
                acceptor=out.acceptor.replace(promised=promised)
            )

        return rogue

    findings = _probe("paxos", "default", wrap)
    assert any(
        f.check == "flow-site" and "'rogue'" in f.message for f in findings
    ), [str(f) for f in findings]


def test_mutation_cross_lane_roll_detected():
    """Theorem 3: jnp.roll across the instance axis (lowers to partial
    slices + concatenate) outside any lane_reduce allowlist."""

    def wrap(step, cfg):
        def rolled(st, key, pl):
            out = step(st, key, pl, cfg.fault)
            return out.replace(
                proposer=out.proposer.replace(
                    bal=jnp.roll(out.proposer.bal, 1, axis=-1)
                )
            )

        return rolled

    findings = _probe("paxos", "default", wrap)
    assert any(
        f.check == "flow-lane" and "instance axis" in f.message
        for f in findings
    ), [str(f) for f in findings]
    # The finding names a concrete primitive (roll lowers to slice/concat).
    lane = [f for f in findings if f.check == "flow-lane"]
    assert all(f.data["primitive"] for f in lane), lane


def test_mutation_checker_steering_detected():
    """Checker isolation: learner.violations written into acceptor state."""

    def wrap(step, cfg):
        def steering(st, key, pl):
            out = step(st, key, pl, cfg.fault)
            return out.replace(
                acceptor=out.acceptor.replace(
                    promised=out.acceptor.promised
                    + st.learner.violations[None, :]
                )
            )

        return steering

    findings = _probe("paxos", "default", wrap)
    assert any(
        f.check == "flow-checker"
        and "learner.violations" in f.message
        and "acceptor.promised" in f.message
        for f in findings
    ), [str(f) for f in findings]


def test_mutation_margin_into_timeout_detected():
    """Theorem 1: a near-miss margin counter read back into timeout logic
    (the exact feedback loop the margin plane promises never to close)."""

    def wrap(step, cfg):
        def adaptive(st, key, pl):
            out = step(st, key, pl, cfg.fault)
            hot = (st.margin.qslack_min[None, :] < 4).astype(jnp.int32)
            return out.replace(
                proposer=out.proposer.replace(timer=out.proposer.timer + hot)
            )

        return adaptive

    findings = _probe("paxos", "margin", wrap)
    assert any(
        f.check == "flow-observer"
        and "margin.qslack_min" in f.message
        and "proposer.timer" in f.message
        for f in findings
    ), [str(f) for f in findings]


def test_checker_exemption_is_multipaxos_only():
    """Multi-Paxos's lease legitimately reads learner.chosen; the spec
    disables checker seeding there and ONLY there."""
    for protocol in PROTOCOLS:
        cfg = trace_mod.build_config(protocol, "default")
        spec = flow.build_spec(protocol, cfg)
        assert spec.check_checker == (protocol != "multipaxos"), protocol


# ------------------------------------------------- fuzz purity (satellite)


def test_fuzz_package_is_lint_clean():
    """fuzz/ rides TRACED_PACKAGES: no host entropy or wall clock."""
    assert "fuzz" in purity.TRACED_PACKAGES
    findings = [
        f for f in purity.audit_traced_sources()
        if "/fuzz/" in f.where or f.where.startswith("paxos_tpu/fuzz")
    ]
    assert findings == [], findings


def test_splitmix64_streams_are_pure_integer():
    """Mutation/energy draws are plain Python ints, reproducible, and
    independent across forks — the replayable-campaign contract."""
    from paxos_tpu.fuzz.mutate import SplitMix64, entry_stream

    a, b = entry_stream(12345, 7), entry_stream(12345, 7)
    seq = [a.next_u64() for _ in range(8)]
    assert seq == [b.next_u64() for _ in range(8)]
    assert all(type(x) is int and 0 <= x < (1 << 64) for x in seq)
    c1, c2 = SplitMix64(99).fork(3), SplitMix64(99).fork(3)
    assert c1.next_u64() == c2.next_u64()
    assert type(c1.below(10)) is int
