"""Fused Pallas engine: bit-exact vs its XLA reference, safe, deterministic.

The fused engine's oracle is :func:`reference_chunk` — the same `apply_tick`
and the same counter-PRNG stream in plain XLA — so the Pallas lowering is
checked bit-for-bit (under the Pallas TPU interpreter on the CPU rig; the
driver's real-TPU bench revalidates compiled equality implicitly via the
violations counter).  Protocol-level properties are then asserted on the
reference twin, which is cheap on CPU.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paxos_tpu.harness.config import (
    SimConfig,
    config1_no_faults,
    config2_dueling_drop,
)
from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.run import init_plan, init_state
from paxos_tpu.kernels.fused_tick import fused_paxos_chunk, reference_chunk


from paxos_tpu.utils.trees import tree_mismatches as _trees_equal


def test_pallas_lowering_bitexact_vs_reference():
    """Interpreter-mode pallas == plain-XLA reference, faults on."""
    cfg = config2_dueling_drop(n_inst=64, seed=3)
    plan = init_plan(cfg)
    sp = fused_paxos_chunk(
        init_state(cfg), jnp.int32(3), plan, cfg.fault, 48, block=64, interpret=True
    )
    sr = reference_chunk(init_state(cfg), jnp.int32(3), plan, cfg.fault, 48)
    assert _trees_equal(sp, sr) == []
    assert int(sp.tick) == 48


def test_fused_stream_decides_and_safe():
    cfg = config2_dueling_drop(n_inst=4096, seed=1)
    state = reference_chunk(
        init_state(cfg), jnp.int32(1), init_plan(cfg), cfg.fault, 400
    )
    assert bool(state.learner.chosen.all())
    assert int(state.learner.violations.sum()) == 0
    assert int(state.learner.evictions.sum()) == 0
    # Fault-free config decides too (sanity on the no-mask trace branches).
    cfg0 = config1_no_faults(n_inst=1024, seed=0)
    s0 = reference_chunk(init_state(cfg0), jnp.int32(0), init_plan(cfg0), cfg0.fault, 64)
    assert bool(s0.learner.chosen.all())
    assert int(s0.learner.violations.sum()) == 0


def test_fused_stream_equivocation_trips_checker():
    cfg = SimConfig(
        n_inst=2048, n_prop=2, n_acc=5, seed=5,
        fault=FaultConfig(p_idle=0.2, p_hold=0.2, p_equiv=0.25),
    )
    state = reference_chunk(
        init_state(cfg), jnp.int32(5), init_plan(cfg), cfg.fault, 192
    )
    assert int(state.learner.violations.sum()) > 0


def test_pallas_lowering_bitexact_all_protocols():
    """Every protocol's fused kernel == its XLA reference, faults on."""
    from paxos_tpu.harness.config import SimConfig
    from paxos_tpu.kernels.fused_tick import FUSED_CHUNKS
    from paxos_tpu.protocols.fastpaxos import apply_tick_fast
    from paxos_tpu.protocols.multipaxos import apply_tick_mp, mp_counter_masks
    from paxos_tpu.protocols.paxos import counter_masks
    from paxos_tpu.protocols.raftcore import apply_tick_raft

    fns = {
        "fastpaxos": (apply_tick_fast, counter_masks),
        "raftcore": (apply_tick_raft, counter_masks),
        "multipaxos": (apply_tick_mp, mp_counter_masks),
    }
    fault = FaultConfig(p_drop=0.1, p_idle=0.2, p_hold=0.2, lease_len=10)
    for protocol, (apply_fn, mask_fn) in fns.items():
        cfg = SimConfig(
            n_inst=32, n_prop=2, n_acc=3, log_len=4, seed=7,
            protocol=protocol, fault=fault,
        )
        plan = init_plan(cfg)
        sp = FUSED_CHUNKS[protocol](
            init_state(cfg), jnp.int32(7), plan, cfg.fault, 32,
            block=32, interpret=True,
        )
        sr = reference_chunk(
            init_state(cfg), jnp.int32(7), plan, cfg.fault, 32,
            apply_fn=apply_fn, mask_fn=mask_fn,
        )
        assert _trees_equal(sp, sr) == [], protocol
        assert int(sp.tick) == 32, protocol


def test_fused_sharded_matches_unsharded():
    """shard_map'd fused engine == single-device fused at the same block."""
    from paxos_tpu.kernels.fused_tick import fused_chunk_sharded
    from paxos_tpu.parallel.mesh import make_mesh, shard_pytree
    from paxos_tpu.protocols.paxos import apply_tick, counter_masks

    devices = jax.devices()[:4]
    mesh = make_mesh(devices)
    cfg = config2_dueling_drop(n_inst=64, seed=2)
    plan = init_plan(cfg)

    single = fused_paxos_chunk(
        init_state(cfg), jnp.int32(2), plan, cfg.fault, 24,
        block=16, interpret=True,
    )
    sharded = fused_chunk_sharded(
        shard_pytree(init_state(cfg), mesh, cfg.n_inst),
        jnp.int32(2),
        shard_pytree(plan, mesh, cfg.n_inst),
        cfg.fault,
        24,
        apply_tick,
        counter_masks,
        mesh,
        block=16,
        interpret=True,
    )
    assert len(single.acceptor.promised.sharding.device_set) == 1
    assert len(sharded.acceptor.promised.sharding.device_set) == 4
    assert _trees_equal(single, jax.device_get(sharded)) == []


def test_fused_stream_chunk_split_invariant():
    """Seeds derive from (seed, tick, block): 2x24 ticks == 1x48 ticks."""
    cfg = config2_dueling_drop(n_inst=256, seed=9)
    plan = init_plan(cfg)
    one = reference_chunk(init_state(cfg), jnp.int32(9), plan, cfg.fault, 48)
    two = reference_chunk(init_state(cfg), jnp.int32(9), plan, cfg.fault, 24)
    two = reference_chunk(two, jnp.int32(9), plan, cfg.fault, 24)
    assert _trees_equal(one, two) == []


def test_fused_segmented_matches_single_call():
    """fused_chunk_auto above its lane ceiling == the single kernel at the
    same block, bit for bit: per-segment global block offsets reproduce the
    exact stream, so the 8M+ degradation path (VERDICT r2 #7) preserves
    the replay/shrink/checkpoint contract."""
    from paxos_tpu.kernels.fused_tick import fused_chunk, fused_chunk_auto
    from paxos_tpu.protocols.paxos import apply_tick, counter_masks

    cfg = config2_dueling_drop(n_inst=64, seed=4)
    plan = init_plan(cfg)

    single = fused_chunk(
        init_state(cfg), jnp.int32(4), plan, cfg.fault, 24,
        apply_tick, counter_masks, block=8, interpret=True,
    )
    # max_lanes=16 forces 4 segments of 2 blocks each.
    segmented = fused_chunk_auto(
        init_state(cfg), jnp.int32(4), plan, cfg.fault, 24,
        apply_tick, counter_masks, block=8, interpret=True, max_lanes=16,
    )
    assert _trees_equal(single, segmented) == []


def test_fused_segmented_multipaxos_longlog_compact():
    """The segmented path composes with decided-prefix compaction the same
    way the single-kernel path does (the 8M config3long story)."""
    import dataclasses

    from paxos_tpu.harness.config import config3_long
    from paxos_tpu.kernels.fused_tick import fused_chunk_auto, fused_fns
    from paxos_tpu.protocols.multipaxos import compact_mp

    cfg = config3_long(n_inst=32, log_total=8, window=4, seed=6)
    apply_fn, mask_fn, _ = fused_fns("multipaxos")
    plan = init_plan(cfg)

    def drive(max_lanes):
        st = init_state(cfg)
        for _ in range(3):
            st = fused_chunk_auto(
                st, jnp.int32(cfg.seed), plan, cfg.fault, 8,
                apply_fn, mask_fn, block=8, interpret=True,
                max_lanes=max_lanes,
            )
            st = compact_mp(st)[0]
        return st

    assert _trees_equal(drive(1 << 22), drive(16)) == []


def test_fused_nonpow2_instance_count_degrades_block():
    """Non-power-of-two instance counts degrade to the largest
    power-of-two-divisor block (deterministic -> replays reproduce)
    instead of refusing to run, down to the platform's lane-tiling floor
    (8 under the Pallas TPU interpreter, 128 on a compiled TPU — where
    the literal 1,000,000 has no admissible block at all and the error
    must steer to an aligned count or the XLA engine)."""
    import pytest

    from paxos_tpu.kernels.fused_tick import fit_block

    assert fit_block(1024, 1_000_000, floor=8) == 64
    assert fit_block(1024, 100_000, floor=8) == 32
    assert fit_block(16, 96, floor=8) == 16
    assert fit_block(1024, 1_048_576) == 1024  # 128-floor: 1<<20 is fine
    assert fit_block(1024, 3 * 256) == 256
    # A non-dividing explicit block must never truncate lanes: it rounds
    # down to a power of two that divides n (48 -> 32 for n=1024).
    assert fit_block(48, 1024, floor=8) == 32
    # An explicitly VALID block is returned unchanged, even non-power-of-
    # two (block is stream-relevant: replays pass the observing block).
    assert fit_block(393_216, 786_432) == 393_216
    # Small unalignable counts degrade to ONE full-array block (Mosaic
    # exempts full-dimension blocks from the 8/128 alignment rule).
    assert fit_block(1024, 20, floor=8) == 20
    assert fit_block(1024, 1000) == 1000
    with pytest.raises(ValueError, match="--engine xla"):
        fit_block(1024, 1_000_000)  # compiled floor: 64 < 128, too big
    with pytest.raises(ValueError, match="block=64 is below"):
        fit_block(64, 1 << 20)  # the BLOCK is at fault, not n_inst

    # End-to-end: a non-dividing request (48 on 64 lanes) degrades to the
    # dividing power of two below it (32) — bit-identical to asking for 32.
    cfg = config2_dueling_drop(n_inst=64, seed=3)
    plan = init_plan(cfg)
    degraded = fused_paxos_chunk(
        init_state(cfg), jnp.int32(3), plan, cfg.fault, 16,
        block=48, interpret=True,
    )
    explicit = fused_paxos_chunk(
        init_state(cfg), jnp.int32(3), plan, cfg.fault, 16,
        block=32, interpret=True,
    )
    assert _trees_equal(degraded, explicit) == []


def test_fused_block_degradation_warning_policy():
    """ADVICE r3 + r4 review: an EXPLICIT block request that degrades must
    warn (block is stream-relevant — a typo'd block silently running a
    different PRNG schedule is the failure mode); the library default
    (block=None) must degrade SILENTLY (the user typed nothing); and an
    oversized explicit request must not be pre-clamped past the warning."""
    import warnings

    from paxos_tpu.kernels.fused_tick import fit_block

    def degraded_warns(fn):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = fn()
            return out, [x for x in w if "fused block" in str(x.message)]

    # Explicit non-dividing request: warns, names both blocks.
    got, w = degraded_warns(lambda: fit_block(48, 1024, floor=8))
    assert got == 32 and len(w) == 1
    assert "block=48" in str(w[0].message) and "block=32" in str(w[0].message)
    # warn=False (the block=None resolution path): same result, silent.
    got, w = degraded_warns(lambda: fit_block(48, 1024, floor=8, warn=False))
    assert got == 32 and w == []
    # Valid request: unchanged AND silent in both modes.
    got, w = degraded_warns(lambda: fit_block(32, 1024, floor=8))
    assert got == 32 and w == []
    # Oversized requests reach fit_block un-clamped and warn (the old
    # min(block, n) pre-clamp made them silently "valid"): with an
    # admissible power-of-two divisor (8 >= floor 8) it degrades to that;
    # with none (floor 128 > p2 8) a small count degrades to one
    # full-array block.
    got, w = degraded_warns(lambda: fit_block(2048, 1000, floor=8))
    assert got == 8 and len(w) == 1
    got, w = degraded_warns(lambda: fit_block(2048, 1000))
    assert got == 1000 and len(w) == 1

    # End-to-end: the default path (block=None -> protocol default 1024,
    # degrading to 512 at n_inst=1536) is silent; the same degradation
    # from an explicit block=1024 warns.
    cfg = config2_dueling_drop(n_inst=1536, seed=5)
    plan = init_plan(cfg)
    _, w = degraded_warns(lambda: fused_paxos_chunk(
        init_state(cfg), jnp.int32(5), plan, cfg.fault, 2, interpret=True,
    ))
    assert w == []
    _, w = degraded_warns(lambda: fused_paxos_chunk(
        init_state(cfg), jnp.int32(5), plan, cfg.fault, 2, block=1024,
        interpret=True,
    ))
    assert len(w) == 1


# ---------------------------------------------------------------------------
# Ballot-overflow saturation (REVIEW fix): Codec.pack masks ballots to their
# field width, so without the packed_fns clamp an election-heavy campaign
# would WRAP proposer.bal mid-chunk and the report-time max_ballot guard
# could never fire on the fused engine.  The clamp pins overflowed ballots
# at the field capacity — sticky, since ballots are monotone — so both
# engines condemn the campaign at the same threshold.


def test_fused_ballot_overflow_saturates_and_guard_fires():
    import pytest

    from paxos_tpu.harness.run import MeasurementCorrupted, summarize
    from paxos_tpu.kernels.fused_tick import report_ballot_limit
    from paxos_tpu.utils import bitops

    # v2 layout: the packed field is WIDER than the report threshold (the
    # clamp-hoist headroom), and every clamp pins at the threshold — the
    # guard contract is unchanged from v1.
    cap = bitops.codec_for(
        "paxos", init_state(config2_dueling_drop(n_inst=32))
    ).field_capacity("proposer.bal")
    limit = report_ballot_limit("paxos")
    assert limit == (1 << 15) - 1
    assert cap > limit

    # All messages drop and timeouts are short, so proposers retry with
    # higher ballots every few ticks; pre-seeded near the limit, the
    # campaign crosses it well inside the chunk.
    cfg = SimConfig(
        n_inst=32, n_prop=2, n_acc=3, seed=9,
        fault=FaultConfig(p_drop=1.0, timeout=2, backoff_max=2),
    )
    plan = init_plan(cfg)

    def preseed():
        s = init_state(cfg)
        bump = jnp.int32(limit - 64)
        return s.replace(
            proposer=s.proposer.replace(bal=s.proposer.bal + bump),
            requests=s.requests.replace(bal=s.requests.bal + bump),
        )

    fused = fused_paxos_chunk(
        preseed(), jnp.int32(9), plan, cfg.fault, 64, block=32, interpret=True
    )
    # Saturated exactly at the report limit — a wrap would read small here.
    assert int(fused.proposer.bal.max()) == limit
    with pytest.raises(MeasurementCorrupted):
        summarize(fused)

    # The XLA twin of the same schedule grows through the limit unmasked
    # and trips the identical guard: the engines agree on condemnation.
    ref = reference_chunk(preseed(), jnp.int32(9), plan, cfg.fault, 64)
    assert int(ref.proposer.bal.max()) >= limit
    with pytest.raises(MeasurementCorrupted):
        summarize(ref)


def test_fused_multipaxos_overflowed_input_saturates_at_entry():
    """An already-overflowed ballot handed to the fused engine must read as
    at-limit (guard fires), not wrap small at the entry pack (guard
    blind).  Pins the MP guard limit at the v1 11-bit threshold: the v2
    packed field is one bit wider (clamp-hoist headroom) but every clamp
    still saturates at the report limit, so the condemnation threshold is
    unchanged."""
    import pytest

    from paxos_tpu.harness.config import config3_multipaxos
    from paxos_tpu.harness.run import MeasurementCorrupted, summarize
    from paxos_tpu.kernels.fused_tick import (
        fused_multipaxos_chunk, report_ballot_limit,
    )
    from paxos_tpu.utils import bitops

    cfg = config3_multipaxos(n_inst=32, seed=4)
    state = init_state(cfg)
    cap = bitops.codec_for("multipaxos", state).field_capacity("proposer.bal")
    limit = report_ballot_limit("multipaxos")
    assert limit == (1 << 11) - 1
    assert cap > limit

    over = state.replace(
        proposer=state.proposer.replace(bal=state.proposer.bal + jnp.int32(limit + 5))
    )
    # The unpacked (XLA-side) guard already condemns this state...
    with pytest.raises(MeasurementCorrupted):
        summarize(over, log_total=cfg.fault.log_total)
    # ...and so does the fused engine's output: the entry pack saturates.
    out = fused_multipaxos_chunk(
        over, jnp.int32(4), init_plan(cfg), cfg.fault, 4, block=32,
        interpret=True,
    )
    assert int(out.proposer.bal.max()) == limit
    with pytest.raises(MeasurementCorrupted):
        summarize(out, log_total=cfg.fault.log_total)
