"""Feedback-directed fuzzing: codec round-trip, mutator determinism,
energy policy, and the guided-beats-uniform acceptance gate."""

import dataclasses
import hashlib
import json

import jax
import numpy as np
import pytest

from paxos_tpu.faults.injector import (
    FaultConfig,
    FaultPlan,
    atom_key,
    atoms_to_plan,
    canonical_atoms,
    plan_to_atoms,
)
from paxos_tpu.fuzz.corpus import (
    Corpus,
    atoms_digest,
    entry_classes,
    exposure_weight,
    fitness,
    load_journal,
    margin_boost,
)
from paxos_tpu.fuzz.mutate import Dims, entry_stream, mutate
from paxos_tpu.fuzz.schedule import FuzzParams, GuidedSource, campaign_config
from paxos_tpu.harness.config import SimConfig, config1_no_faults
from paxos_tpu.obs.coverage import CoverageConfig

# Pinned by test_mutator_determinism_golden: the digest of a fixed
# mutation sequence.  It changes ONLY when the mutation op registry or the
# splitmix64 stream discipline changes — both are determinism-contract
# breaks that invalidate recorded corpus journals, which is exactly what
# this pin should make loud.
GOLDEN_MUTATION_DIGEST = (
    # Re-recorded for PR 20: MUTATION_OPS grew the set-workload op (id 15),
# which changes the op-selection modulus — a deliberate registry change.
    "0ca2c530e658b9d1b8529956bbb59f0c291fa7e429be22c2dae4945537a784fe"
)


def _mutation_sequence_digest(rng_seed: int, entry_id: int) -> str:
    dims = Dims(n_inst=64, n_acc=3, n_prop=2, max_tick=48)
    base = [{"kind": "crash", "role": "acceptor", "idx": 1, "lane": 5,
             "start": 4, "end": 12}]
    h = hashlib.sha256()
    for child in range(4):
        rng = entry_stream(rng_seed, entry_id).fork(child)
        atoms, knobs, ops = mutate(
            rng, base, {}, dims, n_ops=3, base_corrupt=0.25
        )
        h.update(atoms_digest(atoms).encode())
        h.update(json.dumps(knobs, sort_keys=True).encode())
        h.update("|".join(ops).encode())
    return h.hexdigest()


# --- satellite: atom codec round-trip property ---------------------------


def test_atoms_roundtrip_property():
    """plan -> atoms -> plan reproduces every schedule-relevant field
    bit-exactly, for configs spanning every atom kind; the wire form is
    JSON-stable (a second encode of the decoded plan is byte-identical)."""
    cases = [
        FaultConfig(p_crash=0.3, p_crash_prop=0.2, p_equiv=0.2, p_part=0.5,
                    p_asym=0.7, p_flaky=0.4, flaky_drop=0.4, flaky_dup=0.2,
                    timeout_skew=6, backoff_skew=3, p_drop=0.05, p_dup=0.05),
        FaultConfig(p_part=0.6),
        FaultConfig(p_drop=0.1, p_crash=0.25),
    ]
    for fc in cases:
        n_inst, n_acc, n_prop = 96, 3, 2
        plan = FaultPlan.sample(
            jax.random.PRNGKey(11), fc, n_inst, n_acc, n_prop
        )
        atoms = plan_to_atoms(plan, fc)
        back = atoms_to_plan(atoms, n_inst, n_acc, n_prop, cfg=fc)
        host, bhost = jax.device_get(plan), jax.device_get(back)
        for field in ("crash_start", "crash_end", "equivocate",
                      "pcrash_start", "pcrash_end", "part_start", "part_end",
                      "link_drop", "link_dup", "ptimeout", "pboff"):
            a, b = getattr(host, field), getattr(bhost, field)
            if a is None:
                assert b is None, field
            else:
                np.testing.assert_array_equal(a, b, err_msg=field)
        # Sides and cut direction are dead inputs outside a partition
        # window (link_ok is all-True there), so they round-trip only in
        # windowed lanes — verify both the windowed equality and the
        # link_ok equivalence that justifies the exception.
        windowed = np.asarray(host.part_start) != np.iinfo(np.int32).max
        for field in ("aside", "pside", "part_dir"):
            a, b = getattr(host, field), getattr(bhost, field)
            if a is None:
                continue
            np.testing.assert_array_equal(
                np.asarray(a)[..., windowed], np.asarray(b)[..., windowed],
                err_msg=field,
            )
        for tick in (0, 8, 24):
            for direction in (None, "req", "rep"):
                np.testing.assert_array_equal(
                    jax.device_get(plan.link_ok(tick, direction)),
                    jax.device_get(back.link_ok(tick, direction)),
                    err_msg=f"link_ok tick={tick} direction={direction}",
                )
        # JSON stability: re-encoding the decoded plan is byte-identical.
        again = plan_to_atoms(back, fc)
        assert json.dumps(atoms, sort_keys=True) == json.dumps(
            again, sort_keys=True
        )


# --- satellite: mutator determinism --------------------------------------


def test_mutator_determinism_golden():
    """Same (rng seed, corpus entry) => the identical mutation sequence,
    pinned by a golden digest; a perturbed stream (the planted
    nondeterminism) must NOT reproduce it."""
    assert _mutation_sequence_digest(7, 3) == GOLDEN_MUTATION_DIGEST
    # Stable across repeated evaluation in one process (no hidden state).
    assert _mutation_sequence_digest(7, 3) == GOLDEN_MUTATION_DIGEST
    # Planted nondeterminism: a different stream root, a different entry,
    # or a stolen draw (anything a nondeterministic mutator would exhibit
    # run-to-run) all fail the pin.
    assert _mutation_sequence_digest(8, 3) != GOLDEN_MUTATION_DIGEST
    assert _mutation_sequence_digest(7, 4) != GOLDEN_MUTATION_DIGEST
    dims = Dims(n_inst=64, n_acc=3, n_prop=2, max_tick=48)
    rng = entry_stream(7, 3).fork(0)
    rng.next_u64()  # the planted perturbation: one stolen draw
    atoms, knobs, ops = mutate(rng, [], {}, dims, n_ops=3)
    clean = mutate(entry_stream(7, 3).fork(0), [], {}, dims, n_ops=3)
    assert (atoms_digest(atoms), ops) != (atoms_digest(clean[0]), clean[2])


def test_mutate_pure_and_canonical():
    """mutate never modifies its inputs and always returns canonically
    ordered, key-unique atoms (the codec's stable wire order)."""
    dims = Dims(n_inst=32, n_acc=3, n_prop=1, max_tick=32)
    base = [{"kind": "equiv", "idx": 0, "lane": 3}]
    snapshot = json.dumps(base, sort_keys=True)
    knobs: dict = {}
    atoms, out_knobs, ops = mutate(
        entry_stream(1, 0), base, knobs, dims, n_ops=5
    )
    assert json.dumps(base, sort_keys=True) == snapshot
    assert knobs == {}
    assert atoms == canonical_atoms(atoms)
    keys = [atom_key(a) for a in atoms]
    assert len(keys) == len(set(keys))
    assert len(ops) == 5


def test_campaign_config_lights_workload_from_atom():
    """A wload atom lights SimConfig.workload (a campaign dimension, not a
    plan field): mix/rate come from the atom, every other workload knob
    keeps the base's value, the fault config never moves, and the plan
    decoder skips the kind entirely."""
    from paxos_tpu.fuzz.mutate import MUTATION_OPS
    from paxos_tpu.workload.generator import WorkloadConfig

    # Append-only op-id contract: the workload op rides id 15 at the end.
    assert (MUTATION_OPS[-1].op_id, MUTATION_OPS[-1].name) == (
        15, "set-workload"
    )

    base = config1_no_faults(n_inst=64, seed=0)
    step = (1 << 32) // 16
    atoms = [{"kind": "wload", "lane": 0, "mix": "bursty", "rate": 3 * step}]
    ccfg = campaign_config(base, 5, atoms, {})
    assert ccfg.workload.enabled()
    assert ccfg.workload.mix == "bursty"
    assert ccfg.workload.rate == 3 / 16  # exact binary float: stable keys
    assert ccfg.workload.queue_cap == WorkloadConfig().queue_cap
    assert ccfg.fault == base.fault  # no fault knob lit
    assert ccfg.fingerprint() != campaign_config(base, 5, [], {}).fingerprint()
    # One workload per campaign: the LAST wload atom wins (atom_key
    # ignores the payload, so the corpus dedup keeps a single entry).
    both = atoms + [{"kind": "wload", "lane": 0, "mix": "diurnal",
                     "rate": 8 * step}]
    assert campaign_config(base, 5, both, {}).workload.mix == "diurnal"
    assert atom_key(both[0]) == atom_key(both[1])
    # The plan decoder materializes nothing for the kind.
    plan = atoms_to_plan(atoms, 64, 3, 1, cfg=ccfg.fault)
    empty = atoms_to_plan([], 64, 3, 1, cfg=ccfg.fault)
    import jax

    assert jax.tree_util.tree_structure(plan) == (
        jax.tree_util.tree_structure(empty)
    )


# --- fitness model --------------------------------------------------------


def test_fitness_zero_for_vacuous_chaos():
    """An entry whose lit classes saw zero effective events weighs 0 —
    whatever bits it set; crash/equiv-only entries need no defense."""
    flaky = [{"kind": "flaky", "prop": 0, "acc": 1, "lane": 2,
              "drop": 123, "dup": 0}]
    assert entry_classes(flaky) == {"drop", "dup"}
    vacuous = {"drop": {"injected": 50, "effective": 0},
               "dup": {"injected": 0, "effective": 0}}
    assert exposure_weight(flaky, vacuous) == 0.0
    assert fitness(1000, flaky, vacuous, 0) == 0.0
    live = {"drop": {"injected": 50, "effective": 25},
            "dup": {"injected": 0, "effective": 0}}
    assert exposure_weight(flaky, live) == 0.25  # mean(0.5, 0.0)
    crash_only = [{"kind": "crash", "role": "acceptor", "idx": 0,
                   "lane": 0, "start": 0, "end": 4}]
    assert exposure_weight(crash_only, vacuous) == 1.0
    assert margin_boost(None) == 1.0
    assert margin_boost(0) == 2.0
    assert 1.0 < margin_boost(7) < 1.2
    assert fitness(10, crash_only, None, 0) == 20.0


def test_fitness_zero_for_vacuous_delay_chaos():
    """Satellite: the vacuous-chaos warning extends to the delay class — a
    delay-only entry whose slow links never actually held a message back
    (zero effective delay events) weighs 0, whatever coverage it bought."""
    slow = [{"kind": "delay", "prop": 0, "acc": 2, "lane": 7, "cap": 6}]
    assert entry_classes(slow) == {"delay"}
    vacuous = {"delay": {"injected": 40, "effective": 0}}
    assert exposure_weight(slow, vacuous) == 0.0
    assert fitness(1000, slow, vacuous, 0) == 0.0
    live = {"delay": {"injected": 40, "effective": 10}}
    assert exposure_weight(slow, live) == 0.25
    assert fitness(8, slow, live, None) == 2.0


def test_zero_energy_for_vacuous_entries():
    """The scheduler retires a vacuous entry on feedback: zero energy,
    never a mutation parent again (acceptance criterion)."""
    from paxos_tpu.harness.soak import CampaignSpec

    cfg = dataclasses.replace(
        config1_no_faults(n_inst=32, seed=0),
        coverage=CoverageConfig(words=8),
    )
    src = GuidedSource(cfg, FuzzParams(campaigns=8, seed_entries=1),
                       ticks_per_seed=16)
    vac = src.corpus.add(
        seed=0,
        atoms=[{"kind": "flaky", "prop": 0, "acc": 0, "lane": 1,
                "drop": 7, "dup": 0}],
        parent=0,
    )
    spec = CampaignSpec(cfg=src.cfg, meta={"entry_id": vac.entry_id})
    report = {
        "violations": 0,
        "exposure": {"classes": {
            "drop": {"injected": 9, "effective": 0, "lanes_exposed": 1},
            "dup": {"injected": 0, "effective": 0, "lanes_exposed": 0},
        }},
    }
    src.feedback(spec, report, {"new_bits": 500, "min_quorum_slack": 0})
    assert vac.retired and vac.fitness == 0.0
    src._refill()
    assert vac.entry_id not in src._queue


def test_corpus_journal_deterministic_and_wall_clock_free():
    def build():
        c = Corpus()
        root = c.add(seed=3, atoms=[], root=True)
        c.record(root, new_bits=12, classes=None, min_quorum_slack=None,
                 fingerprint="abc", violations=0)
        child = c.add(seed=3, atoms=[{"kind": "equiv", "idx": 0, "lane": 1}],
                      parent=root.entry_id, ops=("add-equiv",))
        c.retire(child, "plateau")
        return c

    a, b = build(), build()
    assert a.journal_lines() == b.journal_lines()
    assert a.digest() == b.digest()
    for line in a.journal_lines():
        rec = json.loads(line)
        assert not any(k in rec for k in ("wall_s", "t_wall", "time"))


def _journaled_corpus(path):
    c = Corpus(journal_path=path)
    root = c.add(seed=3, atoms=[], root=True)
    c.record(root, new_bits=12, classes=None, min_quorum_slack=None,
             fingerprint="abc", violations=0)
    child = c.add(seed=3, atoms=[{"kind": "equiv", "idx": 0, "lane": 1}],
                  parent=root.entry_id, ops=("add-equiv",))
    c.retire(child, "plateau")
    c.close()
    return c


def test_crash_safe_journal_matches_in_memory(tmp_path):
    """The write-through journal on disk is byte-for-byte the in-memory
    journal — crash-safety costs no canonical-form drift."""
    path = tmp_path / "corpus.jsonl"
    c = _journaled_corpus(path)
    loaded = load_journal(path)
    assert not loaded["torn_tail"]
    assert loaded["events"] == [json.loads(l) for l in c.journal_lines()]
    disk = hashlib.sha256(path.read_bytes()).hexdigest()
    mem = hashlib.sha256(
        ("".join(l + "\n" for l in c.journal_lines())).encode()
    ).hexdigest()
    assert disk == mem


def test_journal_torn_tail_tolerated_mid_file_corruption_raises(tmp_path):
    """Regression for the crash-mid-append contract: truncating the
    FINAL line (with or without its newline) loads as torn_tail=True
    with every complete event intact; a malformed line anywhere else is
    real corruption and raises."""
    path = tmp_path / "corpus.jsonl"
    _journaled_corpus(path)
    whole = load_journal(path)
    complete = whole["events"]
    assert len(complete) >= 3

    raw = path.read_text()
    lines = raw.splitlines(keepends=True)

    # Crash mid-final-append: the tail line loses its newline and half
    # its bytes.  Recovery keeps every durable event and reports it.
    torn = tmp_path / "torn.jsonl"
    torn.write_text("".join(lines[:-1]) + lines[-1][: len(lines[-1]) // 2])
    loaded = load_journal(torn)
    assert loaded["torn_tail"] is True
    assert loaded["events"] == complete[:-1]

    # Even a tail that still parses is torn if its newline never landed:
    # completeness is "newline durable", not "prefix happens to parse".
    unterm = tmp_path / "unterm.jsonl"
    unterm.write_text(raw.rstrip("\n"))
    loaded = load_journal(unterm)
    assert loaded["torn_tail"] is True
    assert loaded["events"] == complete[:-1]

    # Mid-file damage is NOT a torn append — single-write discipline
    # can't produce it — so it must raise, never silently drop events.
    corrupt = tmp_path / "corrupt.jsonl"
    corrupt.write_text(lines[0] + '{"event": "add", "seed"\n' +
                       "".join(lines[2:]))
    with pytest.raises(ValueError, match="malformed line 2"):
        load_journal(corrupt)


# --- knob lighting --------------------------------------------------------


def test_campaign_config_lights_exactly_needed_knobs():
    base = config1_no_faults(n_inst=64, seed=0)
    atoms = [
        {"kind": "partition", "lane": 1, "start": 0, "end": 8, "dir": 2,
         "aside": [1, 0, 0], "pside": [0]},
        {"kind": "flaky", "prop": 0, "acc": 1, "lane": 2,
         "drop": 99, "dup": 55},
        {"kind": "skew", "prop": 0, "lane": 3, "timeout": 5, "boff": 3},
    ]
    ccfg = campaign_config(base, 9, atoms, {"timeout": 4})
    f = ccfg.fault
    assert ccfg.seed == 9
    assert f.p_part > 0 and f.p_asym > 0 and f.p_flaky > 0
    assert f.flaky_dup > 0  # dup atom needs links_dup(cfg) true
    assert f.timeout_skew == 5 and f.backoff_skew == 3
    assert f.timeout == 4  # whitelisted knob override
    # Crash/equiv atoms are applied unconditionally: no knobs lit.
    crash = [{"kind": "crash", "role": "acceptor", "idx": 0, "lane": 0,
              "start": 0, "end": 4}]
    assert campaign_config(base, 0, crash, {}).fault == base.fault
    # Delay atoms light the bounded-delay channel and stretch delay_max to
    # the largest cap (the per-tick draw is U[1, delay_max] clamped per
    # link, so an unreachable cap would be silently inert).
    slow = [{"kind": "delay", "prop": 0, "acc": 1, "lane": 2, "cap": 9}]
    dly = campaign_config(base, 0, slow, {"ballot_stride": 3}).fault
    assert dly.p_delay > 0 and dly.delay_max == 9
    assert dly.ballot_stride == 3  # whitelisted knob override
    dplan = atoms_to_plan(slow, 64, 3, 1, cfg=dly)
    assert dplan.link_delay is not None
    assert int(dplan.link_delay[0, 1, 2]) == 9
    # The decoded plan materializes every field the lit config consults.
    plan = atoms_to_plan(atoms, 64, 3, 1, cfg=f)
    assert plan.link_drop is not None and plan.link_dup is not None
    assert plan.part_dir is not None
    assert plan.ptimeout is not None and plan.pboff is not None
    try:
        campaign_config(base, 0, [], {"p_drop": 0.9})
    except ValueError:
        pass
    else:
        raise AssertionError("non-whitelisted knob must be rejected")


# --- the acceptance gate: guided strictly beats uniform -------------------


def test_guided_union_strictly_exceeds_uniform():
    """Pinned CPU config, equal campaign budget: the guided scheduler's
    cross-seed coverage union strictly exceeds uniform sampling's, and the
    corpus journal digest is reproducible (replay determinism)."""
    from paxos_tpu.harness.soak import soak

    budget, ticks = 6, 32
    cfg = dataclasses.replace(
        config1_no_faults(n_inst=64, seed=0),
        coverage=CoverageConfig(words=64),
    )
    uniform = soak(cfg, target_rounds=budget * 64 * ticks,
                   ticks_per_seed=ticks, chunk=16, engine="xla",
                   pipeline_depth=1)
    assert uniform["seeds"] == budget

    def guided():
        src = GuidedSource(
            cfg, FuzzParams(campaigns=budget, seed_entries=2),
            ticks_per_seed=ticks,
        )
        rep = soak(src.cfg, target_rounds=float(budget * 64 * ticks),
                   ticks_per_seed=ticks, chunk=16, engine="xla",
                   pipeline_depth=1, campaigns=src)
        return rep, src

    rep1, src1 = guided()
    assert rep1["seeds"] == budget  # equal campaign budget
    assert (
        rep1["coverage"]["bits_set"] > uniform["coverage"]["bits_set"]
    ), (rep1["coverage"]["bits_set"], uniform["coverage"]["bits_set"])
    # Replay determinism: an identical second run reproduces the journal.
    rep2, src2 = guided()
    assert src1.corpus.digest() == src2.corpus.digest()
    assert rep2["coverage"]["bits_set"] == rep1["coverage"]["bits_set"]


# --- shared worker loop: default path unchanged ---------------------------


def test_soak_default_source_is_rotating_seeds():
    """soak(campaigns=None) and an explicit RotatingSeeds source produce
    the identical tally — the fuzz hook did not perturb plain soak."""
    from paxos_tpu.harness.soak import RotatingSeeds, soak

    cfg = dataclasses.replace(
        SimConfig(n_inst=32, n_prop=1, n_acc=3, seed=0,
                  fault=FaultConfig(p_drop=0.2)),
        coverage=CoverageConfig(words=8),
    )
    kw = dict(target_rounds=2 * 32 * 16, ticks_per_seed=16, chunk=8,
              engine="xla", pipeline_depth=1)
    a = soak(cfg, **kw)
    b = soak(cfg, campaigns=RotatingSeeds(cfg, kw["target_rounds"], 32 * 16),
             **kw)
    for key in ("rounds", "seeds", "violations", "stuck_lanes",
                "config_fingerprint", "stream"):
        assert a[key] == b[key], key
    assert a["coverage"]["bits_set"] == b["coverage"]["bits_set"]
    assert [r["seed"] for r in a["per_seed"]] == [0, 1]


# --- satellite: enriched per-seed events ----------------------------------


def test_seed_events_carry_fitness_signals():
    """With the observer planes on, each soak seed event carries new_bits,
    per-class effective totals, and min quorum slack — corpus fitness is
    reconstructable from the JSONL stream alone.  Planes off: the exact
    historical four keys."""
    from paxos_tpu.obs.exposure import ExposureConfig
    from paxos_tpu.obs.margin import MarginConfig
    from paxos_tpu.harness.soak import soak

    base = SimConfig(n_inst=32, n_prop=1, n_acc=3, seed=0,
                     fault=FaultConfig(p_drop=0.2))
    kw = dict(target_rounds=32 * 16, ticks_per_seed=16, chunk=8,
              engine="xla", pipeline_depth=1)
    plain: list = []
    soak(base, on_seed=plain.append, **kw)
    assert set(plain[0]) == {"seed", "wall_s", "rounds", "rounds_per_sec"}
    rich_cfg = dataclasses.replace(
        base, coverage=CoverageConfig(words=8),
        exposure=ExposureConfig(counters=True),
        margin=MarginConfig(counters=True),
    )
    rich: list = []
    soak(rich_cfg, on_seed=rich.append, **kw)
    rec = rich[0]
    assert rec["new_bits"] > 0
    assert "drop" in rec["effective"]  # per-class effective totals
    assert "min_quorum_slack" in rec
