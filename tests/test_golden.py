"""Golden model property tests: agreement, validity, liveness over many seeds."""

import pytest

from paxos_tpu.cpu_ref.golden import run_golden


@pytest.mark.parametrize("n_prop,n_acc", [(1, 3), (2, 3), (2, 5), (3, 5)])
def test_safety_across_seeds(n_prop, n_acc):
    for seed in range(40):
        rep = run_golden(seed, n_prop=n_prop, n_acc=n_acc)
        assert rep.agreement_ok, (seed, rep)
        assert rep.validity_ok, (seed, rep)


def test_safety_under_drop_and_dup():
    for seed in range(40):
        rep = run_golden(seed, n_prop=2, n_acc=5, p_drop=0.2, p_dup=0.1)
        assert rep.agreement_ok, (seed, rep)
        assert rep.validity_ok, (seed, rep)


def test_liveness_fair_scheduler():
    decided = sum(
        run_golden(seed, n_prop=2, n_acc=3).decided for seed in range(20)
    )
    assert decided >= 18  # fair random scheduling decides essentially always
