"""Gray-failure fault injection (PR 1): stream identity + knob semantics.

Two contracts guard this layer:

1. **Default-off is free**: every gray knob off means every gray plan field
   is ``None`` (pruned from the pytree), no extra PRNG draws happen, and the
   default-config schedule streams are BIT-IDENTICAL to the pre-gray build.
   The golden digests below were recorded at the pre-PR commit and must
   never drift — a digest change means the fuzzing schedules (and thus every
   recorded soak/BASELINE number) silently changed.
2. **Knobs do what they claim**: chaos knobs (asymmetric cuts, flaky links,
   timer skew) enrich the schedule space without breaking safety; bug
   injections (``p_corrupt``, ``stale_k``) must light up the checker.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.faults.injector import (
    NEVER,
    FaultConfig,
    FaultPlan,
    bits_below,
    rate_threshold,
)
from paxos_tpu.harness import config as C
from paxos_tpu.harness.checkpoint import stream_id
from paxos_tpu.harness.run import (
    base_key,
    get_step_fn,
    init_plan,
    init_state,
    run,
    run_chunk,
)


def _digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(jax.device_get(leaf).tobytes())
    return h.hexdigest()[:16]


def _xla_digest(cfg, n_ticks=32) -> str:
    state = run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, n_ticks,
        get_step_fn(cfg.protocol),
    )
    return _digest(state)


def _ctr_digest(cfg, n_ticks=32) -> str:
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    state = reference_chunk(
        init_state(cfg), cfg.seed, init_plan(cfg), cfg.fault, n_ticks,
        apply_fn=apply_fn, mask_fn=mask_fn, blk_id=0,
    )
    return _digest(state)


# Recorded at the pre-gray commit (n_inst=256, seed=7, 32 ticks, CPU):
# full-state sha256 prefixes per config, XLA engine (jax.random streams).
_GOLDEN_XLA = {
    "config1": (lambda: C.config1_no_faults(256, 7), "d8c7672c63eebd78"),
    "config2": (lambda: C.config2_dueling_drop(256, 7), "83347bc41b16a2aa"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "93a2dd9d7b8d66e4"),
    "config4": (lambda: C.config4_byzantine(256, 7), "7b0072765edd14f8"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "c43658973b29e73e"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "4662db6b2c5a39d3"),
}
# Same contract for the counter-PRNG stream (fused engine's reference twin).
_GOLDEN_CTR = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "db6db6f40f16eb7b"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "4b6525460815d9c5"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "72beea3ccdacab94"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "eb285905571b709f"),
}


@pytest.mark.parametrize("name", sorted(_GOLDEN_XLA))
def test_default_stream_bit_identical_xla(name):
    make, want = _GOLDEN_XLA[name]
    assert _xla_digest(make()) == want, (
        f"{name}: default-config XLA schedule stream drifted from the "
        "pre-gray build — gray knobs must be free when off"
    )


@pytest.mark.parametrize("name", sorted(_GOLDEN_CTR))
def test_default_stream_bit_identical_counter(name):
    make, want = _GOLDEN_CTR[name]
    assert _ctr_digest(make()) == want, (
        f"{name}: default-config counter-PRNG stream drifted from the "
        "pre-gray build — gray knobs must be free when off"
    )


def test_stream_id_unchanged_by_gray_knobs():
    """Stream lineage depends on engine/block/prng scheme only — turning a
    gray knob on (or the knobs existing at all) must not relabel streams."""
    plain = C.config2_dueling_drop(64, 0)
    gray = C.config_gray_chaos(64, 0)
    assert stream_id(plain, "xla") == stream_id(gray, "xla")
    assert stream_id(plain, "fused") == stream_id(gray, "fused")


def test_default_plan_prunes_gray_fields():
    """Every gray field is None when its knob is off — default plans keep
    their pre-gray pytree structure (and the fused engine's VMEM budget)."""
    cfg = FaultConfig(p_drop=0.1, p_part=0.5, p_crash=0.2)  # no gray knobs
    sampled = FaultPlan.sample(jax.random.PRNGKey(0), cfg, 32, 5, 2)
    for plan in (FaultPlan.none(32, 5, 2), sampled):
        assert plan.part_dir is None
        assert plan.link_drop is None
        assert plan.link_dup is None
        assert plan.ptimeout is None
        assert plan.pboff is None
    # Structural equality matters for checkpoint restore templates.
    none_t = jax.tree_util.tree_structure(FaultPlan.none(32, 5, 2, cfg=cfg))
    assert none_t == jax.tree_util.tree_structure(sampled)


def test_gray_plan_fields_present_and_shaped():
    cfg = C.config_gray_chaos(64, 3).fault
    plan = FaultPlan.sample(jax.random.PRNGKey(1), cfg, 64, 5, 2)
    assert plan.part_dir.shape == (64,)
    assert set(jax.device_get(plan.part_dir).tolist()) <= {0, 1, 2}
    assert plan.link_drop.shape == (2, 5, 64)
    assert plan.link_dup.shape == (2, 5, 64)
    assert plan.ptimeout.shape == (2, 64)
    assert int(plan.ptimeout.max()) <= cfg.timeout_skew
    assert plan.pboff.shape == (2, 64)
    assert int(plan.pboff.min()) >= 1
    assert int(plan.pboff.max()) <= cfg.backoff_skew
    # The checkpoint restore template must mirror the sampled structure.
    tmpl = FaultPlan.none(64, 5, 2, cfg=cfg)
    assert jax.tree_util.tree_structure(tmpl) == (
        jax.tree_util.tree_structure(plan)
    )


def test_rate_threshold_bernoulli_semantics():
    bits = jax.random.bits(
        jax.random.PRNGKey(0), (1 << 16,), jnp.uint32
    ).astype(jnp.int32)
    # Rate 0 never fires; rate ~1 (saturated) essentially always fires.
    assert not bool(bits_below(bits, rate_threshold(0.0)).any())
    assert float(bits_below(bits, rate_threshold(1.0)).mean()) > 0.999
    got = float(bits_below(bits, rate_threshold(0.3)).mean())
    assert abs(got - 0.3) < 0.02  # 256-sigma-safe at 2^16 draws


@pytest.mark.parametrize("protocol", ["paxos", "multipaxos", "raftcore"])
def test_flaky_zero_rates_are_neutral(protocol):
    """p_flaky > 0 with all-zero drop/dup rates reroutes delivery through the
    per-link threshold path but must not change a single outcome: the
    uniform global rates are the exact special case of the link matrices."""
    base = {
        "paxos": C.config2_dueling_drop,
        "multipaxos": C.config3_multipaxos,
        "raftcore": lambda n, s: C.config5_sweep(n, s)[2],
    }[protocol](128, 9)
    plain = dataclasses.replace(
        base, fault=dataclasses.replace(base.fault, p_drop=0.0, p_dup=0.0)
    )
    flaky = dataclasses.replace(
        plain,
        fault=dataclasses.replace(
            plain.fault, p_flaky=0.5, flaky_drop=0.0, flaky_dup=0.0
        ),
    )
    assert _xla_digest(plain) == _xla_digest(flaky)


def test_link_ok_directional_cuts():
    """part_dir semantics: 0 cuts both directions, 1 only requests (P->A),
    2 only replies (A->P); healed windows deliver everything."""
    n_inst, n_acc, n_prop = 3, 2, 1
    plan = FaultPlan.none(n_inst, n_acc, n_prop)
    plan = plan.replace(
        part_start=jnp.zeros((n_inst,), jnp.int32),
        part_end=jnp.full((n_inst,), 8, jnp.int32),
        pside=jnp.ones((n_prop, n_inst), jnp.bool_),
        aside=jnp.zeros((n_acc, n_inst), jnp.bool_),  # every link crosses
        part_dir=jnp.array([0, 1, 2], jnp.int32),
    )
    t = jnp.int32(3)
    req = jax.device_get(plan.link_ok(t, "req"))[0, 0]  # (I,)
    rep = jax.device_get(plan.link_ok(t, "rep"))[0, 0]
    sym = jax.device_get(plan.link_ok(t))[0, 0]
    assert req.tolist() == [False, False, True]  # dir 2 spares requests
    assert rep.tolist() == [False, True, False]  # dir 1 spares replies
    assert sym.tolist() == [False, False, False]  # direction-blind view
    healed = jax.device_get(plan.link_ok(jnp.int32(8), "req"))
    assert bool(healed.all())


def test_gray_chaos_config_safe_and_live():
    """The chaos side of the fault model: asymmetric cuts + flaky links +
    skewed timers must never trip the checker, and lanes must decide once
    partitions heal (windows end by tick 70 at the config's defaults)."""
    # k_slots=16: flaky duplication re-delivers ACCEPTs across ballots, which
    # is learner-table pressure; a bigger table keeps accounting complete at
    # test scale (soak-scale runs recheck evicting seeds instead).
    cfg = dataclasses.replace(C.config_gray_chaos(n_inst=2048, seed=3),
                              k_slots=16)
    report = run(cfg, total_ticks=192)
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["proposer_disagree"] == 0
    assert report["chosen_frac"] == 1.0


def test_corrupt_violates_within_256_ticks():
    """The bug-injection side: in-flight payload corruption makes acceptors
    vote for values nobody proposed — the agreement checker MUST flag it
    within one 256-tick campaign at config_corrupt's rate/scale."""
    report = run(C.config_corrupt(n_inst=1024, seed=0), total_ticks=256)
    assert report["violations"] > 0


def test_stale_snapshot_violates():
    """Stale-snapshot recovery (amnesia generalized): rolling acceptors back
    up to stale_k ticks on recovery forgets promises/accepts, which under
    crash-heavy dueling eventually yields conflicting choices."""
    base = C.config_stale(n_inst=4096, seed=3)
    violations = 0
    for protocol in ("paxos", "fastpaxos"):
        cfg = dataclasses.replace(base, protocol=protocol)
        violations += run(cfg, total_ticks=192)["violations"]
    assert violations > 0


@pytest.mark.parametrize(
    "protocol", ["paxos", "multipaxos", "fastpaxos", "raftcore"]
)
def test_fused_matches_reference_under_gray(protocol):
    """The fused Pallas kernel must stay bit-exact vs its XLA twin with
    EVERY gray knob lit: gray plan leaves thread through the generic
    pytree flattening and gray mask draws through the counter streams."""
    from paxos_tpu.kernels.fused_tick import (
        fused_chunk,
        fused_fns,
        reference_chunk,
    )

    gray = dict(
        p_part=0.5, part_max_start=20, part_max_len=12, p_asym=0.7,
        p_flaky=0.4, flaky_drop=0.4, flaky_dup=0.2, p_dup=0.05,
        timeout_skew=4, backoff_skew=3, p_corrupt=0.05, stale_k=8,
        p_crash=0.2, crash_max_start=20, crash_max_len=8,
    )
    base = {
        "paxos": C.config2_dueling_drop(64, 5),
        "multipaxos": C.config3_multipaxos(64, 5),
        "fastpaxos": C.config5_sweep(64, 5)[1],
        "raftcore": C.config5_sweep(64, 5)[2],
    }[protocol]
    cfg = dataclasses.replace(
        base, fault=dataclasses.replace(base.fault, **gray)
    )
    plan = init_plan(cfg)
    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    ref = reference_chunk(
        init_state(cfg), cfg.seed, plan, cfg.fault, 24,
        apply_fn=apply_fn, mask_fn=mask_fn, blk_id=0,
    )
    fus = fused_chunk(
        init_state(cfg), cfg.seed, plan, cfg.fault, 24,
        apply_fn, mask_fn, block=64, interpret=True,
    )
    assert _digest(ref) == _digest(fus)


def test_shrink_gray_repro():
    """A gray-failure violation must shrink to a minimized, replayable plan:
    the corruption drives the violation, so the shrinker should be able to
    strip the chaos atoms (flaky links, asymmetric cut, skew) and the
    result must still reproduce."""
    from paxos_tpu.harness.shrink import replay, shrink

    base = C.config_corrupt(n_inst=512, seed=5)
    cfg = dataclasses.replace(
        base,
        fault=dataclasses.replace(
            base.fault,
            p_part=0.4, part_max_start=30, part_max_len=20, p_asym=0.6,
            p_flaky=0.3, flaky_drop=0.3, timeout_skew=4, backoff_skew=3,
        ),
    )
    result = shrink(cfg, max_ticks=192, chunk=32)
    assert result is not None, "corruption config must violate within budget"
    assert replay(cfg, result)


def test_fault_override_parsing():
    cfg = C.config1_no_faults(64, 0)
    out = C.apply_fault_overrides(
        cfg, ["p_corrupt=0.1", "timeout_skew=4", "amnesia=true"]
    )
    assert out.fault.p_corrupt == 0.1
    assert out.fault.timeout_skew == 4
    assert out.fault.amnesia is True
    assert cfg.fault.p_corrupt == 0.0  # original untouched
    with pytest.raises(ValueError, match="unknown fault knob"):
        C.apply_fault_overrides(cfg, ["p_corupt=0.1"])
    with pytest.raises(ValueError, match="key=value"):
        C.apply_fault_overrides(cfg, ["p_corrupt"])


@pytest.mark.slow
def test_gray_chaos_soak_1e8_clean():
    """ISSUE acceptance: the asymmetric-partition chaos config soaks clean
    at >= 1e8 instance-rounds (rotating seeds)."""
    from paxos_tpu.harness.soak import soak

    report = soak(
        C.config_gray_chaos(n_inst=65_536, seed=0),
        target_rounds=1e8, ticks_per_seed=256, chunk=64,
    )
    assert report["rounds"] >= 1e8
    assert report["violations"] == 0
    assert report["evictions"] == 0
