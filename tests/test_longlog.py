"""Long-log Multi-Paxos: sliding window + decided-prefix compaction.

Round-1 verdict #4 / SURVEY.md §6.7, §8.4.6.6: log length must scale
without memory growth.  The window IS the state (O(log_len) HBM); the
replicated log grows to fault.log_total via compact_mp at chunk
boundaries.  Validation layers here:

1. schedule-exact differential: the JAX kernel + compact_mp vs the scalar
   interpreter + multipaxos_compact_lane, full per-lane state equality
   after EVERY tick and EVERY compaction (incl. shift and evicted values);
2. end-to-end: full replication, 0 violations, O(window) state shapes,
   and the global-slot value invariant (every decided slot's payload
   encodes its own global index — cross-slot routing bugs can't hide);
3. fused engine: the compaction loop over the fused kernel (Pallas TPU
   interpreter) bit-equals the same loop over reference_chunk.
"""

import dataclasses

import jax
import jax.numpy as jnp

from paxos_tpu.cpu_ref.interp import (
    lane_of,
    multipaxos_compact_lane,
    multipaxos_tick,
)
from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig, config3_long
from paxos_tpu.harness.run import base_key, init_plan, init_state, run

LL_FAULTS = FaultConfig(
    p_drop=0.1, p_dup=0.1, p_idle=0.15, p_hold=0.15,
    p_crash=0.2, p_crash_prop=0.5, crash_max_start=40, crash_max_len=16,
    timeout=8, backoff_max=4, lease_len=10, log_total=12,
)


def _diff(a, b, path=""):
    if isinstance(a, dict) and isinstance(b, dict):
        return [d for k in a for d in _diff(a[k], b[k], f"{path}.{k}")]
    if isinstance(a, list) and isinstance(b, list) and len(a) == len(b):
        return [
            d
            for i, (x, y) in enumerate(zip(a, b))
            for d in _diff(x, y, f"{path}[{i}]")
        ]
    return [] if a == b else [f"{path}: jax={a!r} interp={b!r}"]


def test_longlog_differential_with_compaction():
    """JAX tick+compaction lockstep-equals the scalar interpreter's."""
    from paxos_tpu.protocols.multipaxos import (
        apply_tick_mp,
        compact_mp,
        sample_mp_masks,
    )

    cfg = SimConfig(
        n_inst=4, n_prop=2, n_acc=5, log_len=4, k_slots=4, seed=3,
        protocol="multipaxos", fault=LL_FAULTS,
    )
    apply_j = jax.jit(apply_tick_mp, static_argnums=(3,))
    state = init_state(cfg)
    plan = init_plan(cfg)
    key = base_key(cfg)
    lanes = range(cfg.n_inst)
    plan_l = [lane_of(jax.device_get(plan), i) for i in lanes]
    interp = [lane_of(jax.device_get(state), i) for i in lanes]
    logs_j = [[] for _ in lanes]  # evicted values accumulated, JAX side
    logs_i = [[] for _ in lanes]  # ... and interpreter side

    for t in range(96):
        masks = sample_mp_masks(
            jax.random.fold_in(key, t), cfg.fault,
            cfg.n_prop, cfg.n_acc, cfg.n_inst,
        )
        masks_h = jax.device_get(masks)
        state = apply_j(state, masks, plan, cfg.fault)
        if (t + 1) % 8 == 0:  # the chunk boundary of the run() loop
            state, shift, evicted = compact_mp(state)
            shift_h = jax.device_get(shift)
            ev_h = jax.device_get(evicted)
        else:
            shift_h = None
        state_h = jax.device_get(state)
        for i in lanes:
            multipaxos_tick(interp[i], lane_of(masks_h, i), plan_l[i], cfg.fault)
            if shift_h is not None:
                s_i, ev_i = multipaxos_compact_lane(interp[i])
                assert s_i == int(shift_h[i]), f"lane {i} shift @ tick {t}"
                logs_i[i] += ev_i[:s_i]
                logs_j[i] += [int(ev_h[l, i]) for l in range(s_i)]
            got = lane_of(state_h, i)
            if got != interp[i]:
                raise AssertionError(
                    f"lane {i} diverged at tick {t}:\n"
                    + "\n".join(_diff(got, interp[i])[:15])
                )

    for i in lanes:
        assert logs_j[i] == logs_i[i]
        # Global-slot keying: slot g's decided payload is (p+1)*1000 + g.
        for g, v in enumerate(logs_j[i]):
            assert v % 1000 == g and v // 1000 in (1, 2), (i, g, v)


def test_longlog_completes_clean_o_window():
    cfg = config3_long(n_inst=128, log_total=64, window=8, seed=2)
    report, state = run(
        cfg, until_all_chosen=True, max_ticks=8192, chunk=32,
        return_state=True,
    )
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["replicated_frac"] == 1.0
    assert report["slots_replicated"] == 128 * 64
    # O(window) memory: no state array grew with log_total.
    assert state.acceptor.log.shape[1] == 8
    assert state.learner.chosen.shape[0] == 8
    assert state.promises.p_bv.shape[2] == 8


def test_longlog_liveness_window_relative():
    """ADVICE r2: `--liveness` on a long-log run must not report the
    window's never-decidable tail rows (global slot >= log_total) as
    stuck, and must surface compacted slots as decided work."""
    cfg = config3_long(n_inst=64, log_total=24, window=8, seed=4)
    report = run(
        cfg, until_all_chosen=True, max_ticks=8192, chunk=32, liveness=True,
    )
    assert report["replicated_frac"] == 1.0
    # decided_frac is GLOBAL replication progress for long-log configs
    # (the window-absolute definition reads ~0.0 on a fully healthy run,
    # which would poison the soak livelock signal it feeds).
    assert report["decided_frac"] == 1.0
    assert report["liveness_window_relative"] is True
    assert report["slots_compacted"] == 64 * 24
    # Fully replicated: nothing real is stuck — before the masking fix the
    # (window - residual) tail rows were all misreported here.
    assert report["stuck_lanes"] == 0
    assert report["chosen_tick_hist"][-1] == 0
    # The histogram counts only rows that were still valid at the end.
    assert sum(report["chosen_tick_hist"]) <= 64 * 8


def test_longlog_window_never_starves():
    """A window much smaller than the log still completes: compaction keeps
    opening headroom (window=4 driving a 48-slot log)."""
    cfg = config3_long(n_inst=32, log_total=48, window=4, seed=5)
    report = run(cfg, until_all_chosen=True, max_ticks=8192, chunk=16)
    assert report["replicated_frac"] == 1.0
    assert report["violations"] == 0


def test_longlog_fused_matches_reference_stream():
    """run(engine='fused') with compaction == the same loop over the
    non-Pallas reference replay of the identical counter-PRNG stream."""
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk
    from paxos_tpu.protocols.multipaxos import compact_mp

    cfg = dataclasses.replace(
        config3_long(n_inst=32, log_total=16, window=4, seed=7),
        fault=dataclasses.replace(LL_FAULTS, crash_max_start=24),
    )
    apply_fn, mask_fn, _ = fused_fns("multipaxos")

    _, fused_state = run(
        cfg, total_ticks=64, chunk=16, engine="fused", return_state=True
    )

    state = init_state(cfg)
    plan = init_plan(cfg)
    for _ in range(4):
        state = reference_chunk(
            state, jnp.int32(cfg.seed), plan, cfg.fault, 16,
            apply_fn=apply_fn, mask_fn=mask_fn,
        )
        state, _, _ = compact_mp(state)

    from paxos_tpu.utils.trees import assert_trees_equal

    assert_trees_equal(fused_state, state, "fused long-log != reference stream")
