"""Near-miss safety-margin plane (PR 12): off is free, on is neutral, honest.

Four contracts guard the margin plane (the exposure plane's template):

1. **Default-off is free**: with margin disabled (the default) the state's
   ``margin`` leaf is ``None`` (pruned from the pytree), schedules are
   BIT-IDENTICAL to the established golden digests (re-pinned from
   tests/test_exposure.py), and the default config fingerprint is
   unchanged so recorded artifacts keep matching.
2. **On is outcome-neutral**: the fold draws NO randomness — pure int32
   reductions over the learner table and acceptor fence the tick already
   produced — so enabling it leaves the protocol schedule bit-identical
   on BOTH engines, and the fused Pallas kernel carries the counter
   arrays bit-exact vs its XLA reference via the packed-word passthrough.
3. **The counters are honest (the oracle)**: over a 256-tick corrupt
   campaign the device leaves equal an independent host-side numpy replay
   of the fold — exactly, per lane, on both engines' schedules, for all
   four protocols.  And the headline semantics hold: min quorum slack 0
   iff the safety checker fired, healthy campaigns never dip below 1.
4. **The plumbing round-trips**: checkpoints restore the margin config
   and counters bit-exact (pre-margin snapshots default off), run reports
   embed the margin block plus the ``checker_complete`` gauge, and the
   metrics registry exports deterministic margin gauges (None minima and
   list-valued ranking rows are NOT gauges).
"""

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paxos_tpu.harness import checkpoint
from paxos_tpu.harness import config as C
from paxos_tpu.harness.metrics import MetricsRegistry
from paxos_tpu.harness.run import (
    base_key,
    get_step_fn,
    init_plan,
    init_state,
    run,
    run_chunk,
)
from paxos_tpu.kernels.quorum import fast_quorum, majority
from paxos_tpu.obs import margin as mar_mod

MAR = mar_mod.MarginConfig(counters=True)


def _digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(jax.device_get(leaf).tobytes())
    return h.hexdigest()[:16]


def _xla_final(cfg, n_ticks=32):
    return run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, n_ticks,
        get_step_fn(cfg.protocol),
    )


def _ctr_final(cfg, n_ticks=32):
    from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    return reference_chunk(
        init_state(cfg), cfg.seed, init_plan(cfg), cfg.fault, n_ticks,
        apply_fn=apply_fn, mask_fn=mask_fn, blk_id=0,
    )


# The established goldens (tests/test_exposure.py, n_inst=256, seed=7,
# 32 ticks, CPU): margin-off must reproduce them, and margin-ON minus the
# counter leaf must reproduce them too (schedule unperturbed, both engines).
_GOLDEN_XLA = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "83347bc41b16a2aa"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "93a2dd9d7b8d66e4"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "c43658973b29e73e"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "4662db6b2c5a39d3"),
}
_GOLDEN_CTR = {
    "config2": (lambda: C.config2_dueling_drop(256, 7), "db6db6f40f16eb7b"),
    "config3": (lambda: C.config3_multipaxos(256, 7), "4b6525460815d9c5"),
    "fastpaxos": (lambda: C.config5_sweep(256, 7)[1], "72beea3ccdacab94"),
    "raftcore": (lambda: C.config5_sweep(256, 7)[2], "eb285905571b709f"),
}

_FAST_XLA = ("config2",)
_FAST_CTR = ("config2",)


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_XLA else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_XLA)
    ],
)
def test_margin_on_schedule_identical_xla(name):
    mk, want = _GOLDEN_XLA[name]
    assert _digest(_xla_final(mk())) == want  # off == the pinned golden
    fin = _xla_final(dataclasses.replace(mk(), margin=MAR))
    assert fin.margin is not None
    assert _digest(fin.replace(margin=None)) == want  # on == same schedule


@pytest.mark.parametrize(
    "name",
    [
        n if n in _FAST_CTR else pytest.param(n, marks=pytest.mark.slow)
        for n in sorted(_GOLDEN_CTR)
    ],
)
def test_margin_on_schedule_identical_counter_stream(name):
    mk, want = _GOLDEN_CTR[name]
    assert _digest(_ctr_final(mk())) == want
    fin = _ctr_final(dataclasses.replace(mk(), margin=MAR))
    assert _digest(fin.replace(margin=None)) == want


def test_default_off_prunes_to_none():
    """Disabled margin leaves NO trace in the pytree or the fingerprint."""
    for mk in (C.config1_no_faults, C.config3_multipaxos):
        cfg = mk(64, 0)
        state = init_state(cfg)
        assert state.margin is None
        assert not cfg.margin.enabled()
        on = init_state(dataclasses.replace(cfg, margin=MAR))
        off_n = len(jax.tree_util.tree_leaves(state))
        on_n = len(jax.tree_util.tree_leaves(on))
        assert on_n == off_n + 4  # qslack/near_split/bal_gap/promise_slack
        # All leaves non-scalar int32 instance-minor — the fused engine's
        # generic packed-word flattening rides them with no kernel edits.
        for leaf in jax.tree_util.tree_leaves(on.margin):
            assert leaf.dtype == jnp.int32
            assert leaf.shape == (64,)


def test_fingerprint_unchanged_by_default_margin():
    """The default (off) MarginConfig is dropped from the fingerprint, so
    pre-margin artifacts keep matching; a non-default one IS keyed."""
    cfg = C.config2_dueling_drop(1 << 10)
    assert (
        dataclasses.replace(
            cfg, margin=mar_mod.MarginConfig()
        ).fingerprint()
        == cfg.fingerprint()
    )
    assert (
        dataclasses.replace(cfg, margin=MAR).fingerprint()
        != cfg.fingerprint()
    )


def test_margin_host_report_and_lane_ranking():
    """SENTINEL minima surface as None; the ranking is tightest-first and
    stops at the uncontested tail."""
    S = mar_mod.SENTINEL
    m = mar_mod.MarginState(
        qslack_min=jnp.array([S, 0, 2, 1], jnp.int32),
        near_split=jnp.array([0, 5, 0, 1], jnp.int32),
        bal_gap_min=jnp.full((4,), S, jnp.int32),
        promise_slack_min=jnp.array([S, 3, 3, 3], jnp.int32),
    )
    rep = mar_mod.margin_report(m)
    assert rep["min_quorum_slack"] == 0
    assert rep["min_ballot_gap"] is None  # sentinel never folded
    assert rep["min_promise_slack"] == 3
    assert rep["near_miss_lanes"] == 2  # slack <= 1: lanes 1 and 3
    assert rep["zero_slack_lanes"] == 1
    assert rep["contested_lanes"] == 3
    assert rep["near_split_ticks"] == 6
    assert rep["near_split_lanes"] == 2
    ranking = mar_mod.lane_ranking(m, top=8)
    assert [r["lane"] for r in ranking] == [1, 3, 2]  # lane 0 never ranks
    assert ranking[0] == {
        "lane": 1, "min_quorum_slack": 0, "near_split_ticks": 5,
    }


def test_correlation_table():
    chunks = [
        {"tightened": True, "new_bits": 3, "effective_total": 7,
         "violations_delta": 1},
        {"tightened": False, "new_bits": 2},
        {"tightened": True},
    ]
    table = mar_mod.correlation(chunks)
    assert table["tightened"] == {
        "chunks": 2, "new_bits": 3, "effective": 7, "violations": 1,
    }
    assert table["flat"] == {
        "chunks": 1, "new_bits": 2, "effective": 0, "violations": 0,
    }


def test_run_report_embeds_margin_and_checker_complete():
    """A corrupt campaign's report carries slack 0 exactly when the safety
    checker fired; a healthy campaign never dips below slack 1; margin-off
    reports have no margin block but always carry checker_complete."""
    cfg = dataclasses.replace(C.config_corrupt(128, 11), margin=MAR)
    rep = run(cfg, total_ticks=64, chunk=32)
    assert rep["violations"] > 0
    assert rep["margin"]["min_quorum_slack"] == 0
    assert rep["margin"]["zero_slack_lanes"] > 0
    assert rep["checker_complete"] == (rep["evictions"] == 0)
    # Healthy: no violations, so slack never 0 — either >= 1 or None
    # (healthy lanes are typically never contested at all).
    rep_h = run(
        dataclasses.replace(C.config2_dueling_drop(64, 0), margin=MAR),
        total_ticks=32, chunk=16,
    )
    assert rep_h["violations"] == 0
    s = rep_h["margin"]["min_quorum_slack"]
    assert s is None or s >= 1
    rep_off = run(C.config2_dueling_drop(64, 0), total_ticks=16, chunk=8)
    assert "margin" not in rep_off
    assert rep_off["checker_complete"] is True


# ---------------------------------------------------------------------------
# The oracle: replay the campaign tick by tick, refold the margins in numpy
# from device_get'd learner/acceptor snapshots, and match the device leaves
# bit for bit — per lane, both engines, all four protocols.

_ORACLE_TICKS = 256


def _corrupt_cfg(protocol):
    return dataclasses.replace(
        C.config_corrupt(128, 11), protocol=protocol, margin=MAR
    )


def _learner_leaves(learner):
    return {
        f.name: np.asarray(jax.device_get(getattr(learner, f.name)))
        for f in dataclasses.fields(learner)
    }


def _np_fold(protocol, cfg, counters, pre, post):
    """One tick of the margin fold in numpy, mirroring the hook site."""
    pre_l = _learner_leaves(pre.learner)
    post_l = _learner_leaves(post.learner)
    honest = ~np.asarray(jax.device_get(init_plan(cfg).equivocate))
    q = majority(cfg.n_acc)
    if protocol == "multipaxos":
        from paxos_tpu.core.mp_state import bv_bal

        acc_bal = np.asarray(
            jax.device_get(bv_bal(post.acceptor.log).max(axis=1))
        )
        return mar_mod.np_mp_margin_tick(
            counters, pre_l, post_l,
            np.asarray(jax.device_get(post.acceptor.promised)),
            acc_bal, honest, q,
        )
    if protocol == "raftcore":
        promised = np.asarray(jax.device_get(post.acceptor.voted))
        acc_bal = np.asarray(jax.device_get(post.acceptor.ent_term))
        kw = {}
    else:
        promised = np.asarray(jax.device_get(post.acceptor.promised))
        acc_bal = np.asarray(jax.device_get(post.acceptor.acc_bal))
        q = cfg.fault.q2 or q
        kw = {}
        if protocol == "fastpaxos":
            from paxos_tpu.core.ballot import ballot_round

            kw = {
                "fast_quorum": cfg.fault.q_fast or fast_quorum(cfg.n_acc),
                "fast_round": np.asarray(
                    jax.device_get(ballot_round(post.learner.lt_bal))
                ) == 0,
            }
    return mar_mod.np_margin_tick(
        counters, pre_l, post_l, promised, acc_bal, honest, q, **kw
    )


@pytest.mark.parametrize(
    "engine,protocol",
    [
        ("xla", "paxos"),
        ("ctr", "paxos"),
        pytest.param("xla", "multipaxos", marks=pytest.mark.slow),
        pytest.param("xla", "fastpaxos", marks=pytest.mark.slow),
        pytest.param("xla", "raftcore", marks=pytest.mark.slow),
        pytest.param("ctr", "multipaxos", marks=pytest.mark.slow),
        pytest.param("ctr", "fastpaxos", marks=pytest.mark.slow),
        pytest.param("ctr", "raftcore", marks=pytest.mark.slow),
    ],
)
def test_margin_counters_vs_numpy_replay(engine, protocol):
    """The device fold == the numpy fold over the same tick trajectory,
    bit for bit per lane — and slack 0 co-occurs exactly with checker
    violations on this corrupt campaign."""
    cfg = _corrupt_cfg(protocol)
    plan = init_plan(cfg)
    state = init_state(cfg)
    if engine == "xla":
        key = base_key(cfg)
        step = get_step_fn(cfg.protocol)

        @jax.jit
        def advance(st):
            return run_chunk(st, key, plan, cfg.fault, 1, step)
    else:  # the fused engine's schedule via its bit-exact XLA reference
        from paxos_tpu.kernels.fused_tick import fused_fns, reference_chunk

        apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
        seed = jnp.int32(cfg.seed)

        @jax.jit
        def advance(st):
            return reference_chunk(
                st, seed, plan, cfg.fault, 1,
                apply_fn=apply_fn, mask_fn=mask_fn,
            )

    counters = mar_mod.np_margin_init(cfg.n_inst)
    for _ in range(_ORACLE_TICKS):
        nxt = advance(state)
        counters = _np_fold(protocol, cfg, counters, state, nxt)
        state = nxt

    dev = jax.device_get(state.margin)
    for name, host in counters.items():
        assert np.array_equal(host, np.asarray(getattr(dev, name))), name
    # Headline semantics on the real campaign: the corrupt config fires
    # the checker, and slack 0 is exactly that event (not a lagging echo).
    viol = np.asarray(jax.device_get(state.learner.violations))
    rep = mar_mod.margin_report(state.margin)
    assert viol.sum() > 0
    assert rep["min_quorum_slack"] == 0
    assert rep["contested_lanes"] > 0


@pytest.mark.parametrize(
    "protocol",
    [
        "paxos",
        pytest.param("multipaxos", marks=pytest.mark.slow),
        pytest.param("fastpaxos", marks=pytest.mark.slow),
        pytest.param("raftcore", marks=pytest.mark.slow),
    ],
)
def test_fused_kernel_carries_margin_bitexact(protocol):
    """fused_chunk(interpret) == reference_chunk with the counters ON: the
    packed-word passthrough codec must round-trip them bit-exactly."""
    from paxos_tpu.kernels.fused_tick import (
        FUSED_CHUNKS,
        fused_fns,
        reference_chunk,
    )
    from paxos_tpu.utils.trees import tree_mismatches

    cfg = dataclasses.replace(
        C.config_corrupt(64, 7), protocol=protocol, margin=MAR
    )
    apply_fn, mask_fn, _ = fused_fns(cfg.protocol)
    plan = init_plan(cfg)
    sr = reference_chunk(
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        apply_fn=apply_fn, mask_fn=mask_fn,
    )
    sp = FUSED_CHUNKS[cfg.protocol](
        init_state(cfg), jnp.int32(cfg.seed), plan, cfg.fault, 24,
        block=64, interpret=True,
    )
    assert tree_mismatches(sp, sr) == []
    assert mar_mod.margin_report(sp.margin)["contested_lanes"] > 0


# ---------------------------------------------------------------------------
# Checkpoint round-trip and metrics determinism.


def test_checkpoint_roundtrip_with_margin(tmp_path):
    """Save/restore rebuilds the margin config AND the counter arrays, so
    a resumed campaign's margins are bit-identical."""
    cfg = dataclasses.replace(C.config2_dueling_drop(64, 3), margin=MAR)
    step = get_step_fn(cfg.protocol)
    key, plan = base_key(cfg), init_plan(cfg)
    state = run_chunk(init_state(cfg), key, plan, cfg.fault, 16, step)
    checkpoint.save(tmp_path / "ck", state, plan, cfg, engine="xla")
    st2, pl2, cfg2 = checkpoint.restore(tmp_path / "ck", engine="xla")
    assert cfg2.margin == MAR
    assert st2.margin is not None
    fin_a = run_chunk(state, key, plan, cfg.fault, 16, step)
    fin_b = run_chunk(st2, base_key(cfg2), pl2, cfg2.fault, 16, step)
    assert _digest(fin_a) == _digest(fin_b)  # margin leaves included


def test_checkpoint_restore_pre_margin_snapshot(tmp_path):
    """Snapshots written before the margin plane (no key in the JSON)
    restore with the default-off config and a pruned leaf."""
    cfg = C.config2_dueling_drop(64, 3)
    checkpoint.save(tmp_path / "ck", init_state(cfg), init_plan(cfg), cfg)
    meta_path = tmp_path / "ck" / "simconfig.json"
    raw = json.loads(meta_path.read_text())
    raw.pop("margin")
    meta_path.write_text(json.dumps(raw))
    st2, _, cfg2 = checkpoint.restore(tmp_path / "ck")
    assert cfg2.margin == mar_mod.MarginConfig()
    assert st2.margin is None


def test_margin_metrics_gauges_pinned():
    """Numeric margin fields become gauges; None minima and list-valued
    ranking rows do NOT (a None is 'never contested', not zero; a list
    would break the Prometheus rendering)."""
    rep = {
        "min_quorum_slack": None,
        "near_miss_lanes": 3,
        "zero_slack_lanes": 0,
        "min_ballot_gap": 2,
        "seed_ranking": [{"seed": 7, "min_quorum_slack": 1}],
    }
    reg = MetricsRegistry()
    reg.ingest_margin(rep, checker_complete=False)
    gauges = reg.snapshot()["gauges"]
    assert list(gauges) == sorted(gauges)  # the JSONL/stats ordering pin
    assert "margin_min_quorum_slack" not in gauges
    assert "margin_seed_ranking" not in gauges
    assert gauges["margin_near_miss_lanes"] == 3
    assert gauges["margin_zero_slack_lanes"] == 0
    assert gauges["margin_min_ballot_gap"] == 2
    assert gauges["checker_complete"] == 0.0
    prom = reg.to_prometheus()
    assert "paxos_tpu_margin_near_miss_lanes 3" in prom
    assert "paxos_tpu_checker_complete 0" in prom
    # checker_complete omitted -> no gauge claimed either way.
    reg2 = MetricsRegistry()
    reg2.ingest_margin({"near_miss_lanes": 1})
    assert "checker_complete" not in reg2.snapshot()["gauges"]
