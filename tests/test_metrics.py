"""harness/metrics.py + harness/trace.py coverage (PR 2, satellite).

MetricsLog emit/close/context-manager semantics, the MetricsRegistry's
counter/histogram accounting and Prometheus rendering, trace_scope as a
no-op wrapper, event_dump's shape-polymorphism and registry routing, and
the `paxos_tpu stats` subcommand end to end.
"""

import dataclasses
import json

import pytest

from paxos_tpu.harness.metrics import MetricsLog, MetricsRegistry, trace_scope


def test_metricslog_writes_jsonl(tmp_path):
    path = tmp_path / "m.jsonl"
    log = MetricsLog(path)
    rec = log.emit("start", config="config2", n_inst=64)
    assert rec["event"] == "start" and rec["n_inst"] == 64
    assert "t_wall" in rec
    log.emit("final", violations=0)
    log.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert [l["event"] for l in lines] == ["start", "final"]


def test_metricslog_context_manager_closes(tmp_path):
    path = tmp_path / "m.jsonl"
    with MetricsLog(path) as log:
        log.emit("start")
    assert log._fh is None
    with pytest.raises(ValueError, match="closed"):
        log.emit("late")
    # Closes on the error path too (the CLI's early-return contract).
    with pytest.raises(RuntimeError):
        with MetricsLog(path) as log2:
            log2.emit("start")
            raise RuntimeError("boom")
    assert log2._fh is None


def test_metricslog_pathless_is_noop():
    with MetricsLog(None) as log:
        rec = log.emit("chunk", ticks=64)
    assert rec["ticks"] == 64  # record still returned for callers
    log.close()  # idempotent


def test_trace_scope_noop():
    with trace_scope("deliver"):
        x = 1 + 1
    assert x == 2


def test_registry_counters_and_labels():
    reg = MetricsRegistry()
    reg.inc("log_records_total", record="chunk")
    reg.inc("log_records_total", record="chunk")
    reg.inc("log_records_total", record="final")
    reg.inc("plain_total")
    snap = reg.snapshot()
    assert snap["counters"]["log_records_total{record=chunk}"] == 2
    assert snap["counters"]["log_records_total{record=final}"] == 1
    assert snap["counters"]["plain_total"] == 1


def test_registry_hist_merge_and_layout_guard():
    reg = MetricsRegistry()
    reg.observe_hist("lat", [1, 2, 3], bin_width=8)
    reg.observe_hist("lat", [1, 0, 1], bin_width=8)
    assert reg.snapshot()["histograms"]["lat"] == {
        "counts": [2, 2, 4], "bin_width": 8,
    }
    with pytest.raises(ValueError, match="layout changed"):
        reg.observe_hist("lat", [1, 1], bin_width=8)


def test_registry_ingest_is_cumulative_overwrite():
    """Device telemetry is cumulative; the LAST report wins, not the sum."""
    reg = MetricsRegistry()
    reg.ingest({"counters": {"decide": 10}, "hist": [10, 0],
                "hist_ticks_per_bin": 8})
    reg.ingest({"counters": {"decide": 25}, "hist": [20, 5],
                "hist_ticks_per_bin": 8})
    snap = reg.snapshot()
    assert snap["counters"]["events_total{event=decide}"] == 25
    assert snap["histograms"]["ticks_to_decide"]["counts"] == [20, 5]


def test_registry_prometheus_format():
    reg = MetricsRegistry()
    reg.inc("events_total", 7, event="promise")
    reg.observe_hist("ticks_to_decide", [5, 2, 1], bin_width=8)
    text = reg.to_prometheus()
    assert '# TYPE paxos_tpu_events_total counter' in text
    assert 'paxos_tpu_events_total{event="promise"} 7' in text
    # Finite buckets are cumulative; the device catch-all bin folds into +Inf.
    assert 'paxos_tpu_ticks_to_decide_bucket{le="8"} 5' in text
    assert 'paxos_tpu_ticks_to_decide_bucket{le="16"} 7' in text
    assert 'paxos_tpu_ticks_to_decide_bucket{le="+Inf"} 8' in text
    assert 'paxos_tpu_ticks_to_decide_count 8' in text
    assert text.endswith("\n")


def test_prometheus_label_value_escaping():
    """Backslash, double-quote, and newline in a label value must escape
    per the exposition format — an unescaped quote splits the sample line
    at scrape time.  Backslash escapes FIRST (regression: escaping it last
    re-breaks the quote/newline escapes' own backslashes)."""
    reg = MetricsRegistry()
    reg.inc("events_total", 3, path='a\\b"c\nd')
    reg.gauge("disk_free", 1.5, mount='m"nt')
    text = reg.to_prometheus()
    assert 'paxos_tpu_events_total{path="a\\\\b\\"c\\nd"} 3' in text
    assert 'paxos_tpu_disk_free{mount="m\\"nt"} 1.5' in text
    # The raw newline must not survive to split the sample line.
    assert 'c\nd"} 3' not in text


def test_registry_ingest_coverage_gauges():
    """Coverage host reports land as gauges; new_per_chunk is the delta of
    bits_set across ingests (the live coverage-curve slope)."""
    reg = MetricsRegistry()
    reg.ingest_coverage({
        "bits_set": 40, "bits_total": 256, "saturation": 0.15625,
        "est_states": 21.5,
    })
    reg.ingest_coverage({
        "bits_set": 50, "bits_total": 256, "saturation": 0.195312,
        "est_states": 28.0,
    })
    g = reg.snapshot()["gauges"]
    assert g["coverage_bits_set"] == 50
    assert g["coverage_bits_total"] == 256
    assert g["coverage_new_per_chunk"] == 10
    assert g["coverage_est_states"] == 28.0
    # A saturated report (est_states None) keeps the last finite estimate.
    reg.ingest_coverage({
        "bits_set": 256, "bits_total": 256, "saturation": 1.0,
        "est_states": None,
    })
    g = reg.snapshot()["gauges"]
    assert g["coverage_saturation"] == 1.0
    assert g["coverage_est_states"] == 28.0
    text = reg.to_prometheus()
    assert "# TYPE paxos_tpu_coverage_bits_set gauge" in text
    assert "paxos_tpu_coverage_bits_set 256" in text


def test_registry_ingest_fleet_gauges():
    """Fleet coordinator gauges land under the fleet_ prefix — the exact
    set is pinned so a renamed gauge breaks a test, not a dashboard."""
    reg = MetricsRegistry()
    reg.ingest_fleet({
        "workers": 2, "workers_alive": 1, "workers_dead": 1,
        "workers_spawned": 3, "queue_depth": 4, "records_total": 8,
        "records_done": 4, "leases_held_peak": 2, "leases_expired": 1,
        "leases_reclaimed": 1, "campaigns_retried": 1, "merge_dedup": 0,
        "torn_tails": 0, "resumed_seeds": 2,
    })
    assert reg.snapshot()["gauges"] == {
        "fleet_workers": 2,
        "fleet_workers_alive": 1,
        "fleet_workers_dead": 1,
        "fleet_workers_spawned": 3,
        "fleet_queue_depth": 4,
        "fleet_records_total": 8,
        "fleet_records_done": 4,
        "fleet_leases_held_peak": 2,
        "fleet_leases_expired": 1,
        "fleet_leases_reclaimed": 1,
        "fleet_campaigns_retried": 1,
        "fleet_merge_dedup": 0,
        "fleet_torn_tails": 0,
        "fleet_resumed_seeds": 2,
    }
    # Later ticks overwrite (point-in-time gauges); partial blocks only
    # touch the keys they carry.
    reg.ingest_fleet({"queue_depth": 0, "records_done": 8,
                      "workers_alive": 0})
    g = reg.snapshot()["gauges"]
    assert g["fleet_queue_depth"] == 0
    assert g["fleet_records_done"] == 8
    assert g["fleet_leases_reclaimed"] == 1
    text = reg.to_prometheus()
    assert "# TYPE paxos_tpu_fleet_leases_reclaimed gauge" in text
    assert "paxos_tpu_fleet_queue_depth 0" in text


def test_registry_ingest_fleet_worker_label_no_collision():
    """The PR 16 collision fix, pinned: per-worker blocks land as
    worker-labeled series BESIDE the unlabeled aggregate — N workers are
    N series, the last-ingested block no longer wins."""
    reg = MetricsRegistry()
    reg.ingest_fleet({"records_done": 4, "queue_depth": 0})  # aggregate
    reg.ingest_fleet({"records": 3, "seeds": 12, "rounds": 600,
                      "violations": 1}, worker="w0")
    reg.ingest_fleet({"records": 1, "seeds": 4, "rounds": 200,
                      "violations": 0}, worker="w1r")
    assert reg.snapshot()["gauges"] == {
        "fleet_records_done": 4,
        "fleet_queue_depth": 0,
        "fleet_records{worker=w0}": 3,
        "fleet_records{worker=w1r}": 1,
        "fleet_seeds{worker=w0}": 12,
        "fleet_seeds{worker=w1r}": 4,
        "fleet_rounds{worker=w0}": 600,
        "fleet_rounds{worker=w1r}": 200,
        "fleet_violations{worker=w0}": 1,
        "fleet_violations{worker=w1r}": 0,
    }
    text = reg.to_prometheus()
    assert 'paxos_tpu_fleet_seeds{worker="w0"} 12' in text
    assert 'paxos_tpu_fleet_seeds{worker="w1r"} 4' in text
    # Label values go through the exposition escaping (reused helper).
    reg.ingest_fleet({"records": 1}, worker='w"x')
    assert 'paxos_tpu_fleet_records{worker="w\\"x"} 1' in reg.to_prometheus()


def test_registry_ingest_lineage_gauges():
    """Lineage roll-up + per-op attribution land as lineage_* gauges
    with op-labeled payoff series."""
    reg = MetricsRegistry()
    reg.ingest_lineage(
        {"entries": 8, "roots": 4, "executed": 8, "retired": 1,
         "depth_max": 2, "best_fitness": 99.5},
        ops={"add-skew": {"campaigns": 0.5, "new_bits": 59,
                          "effective": 0, "violations": 0,
                          "margin_tightened": 0, "fitness": 59.0}},
    )
    g = reg.snapshot()["gauges"]
    assert g["lineage_entries"] == 8
    assert g["lineage_roots"] == 4
    assert g["lineage_best_fitness"] == 99.5
    assert g["lineage_op_new_bits{op=add-skew}"] == 59
    assert 'paxos_tpu_lineage_op_new_bits{op="add-skew"} 59' in (
        reg.to_prometheus()
    )


def _tiny_state(protocol: str):
    from paxos_tpu.harness import config as C
    from paxos_tpu.harness.run import (
        base_key, get_step_fn, init_plan, init_state, run_chunk,
    )

    cfg = (
        C.config3_multipaxos(32, 0)
        if protocol == "multipaxos"
        else C.config1_no_faults(32, 0)
    )
    return run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, 8,
        get_step_fn(cfg.protocol),
    )


@pytest.mark.parametrize("protocol", ["paxos", "multipaxos"])
def test_event_dump_shapes(protocol, capsys):
    """event_dump handles (I,) and (L, I) learner shapes; prints JSON."""
    from paxos_tpu.harness.trace import event_dump

    state = _tiny_state(protocol)
    rec = event_dump(state)
    err = capsys.readouterr().err
    assert json.loads(err.strip().splitlines()[-1]) == rec
    assert rec["tick"] == 8
    assert 0 <= rec["chosen"] <= rec["chosen_total"]
    assert rec["violations"] == 0
    # round_mean can be negative (idle MP proposers sit at round -1).
    assert isinstance(rec["round_mean"], float)
    assert rec["round_max"] >= rec["round_mean"]


def test_event_dump_registry_routing(capsys):
    """With a registry, nothing hits stderr; telemetry folds in."""
    from paxos_tpu.harness import config as C
    from paxos_tpu.core.telemetry import TelemetryConfig
    from paxos_tpu.harness.run import (
        base_key, get_step_fn, init_plan, init_state, run_chunk,
    )
    from paxos_tpu.harness.trace import event_dump

    cfg = dataclasses.replace(
        C.config1_no_faults(32, 0),
        telemetry=TelemetryConfig(counters=True, hist_bins=4),
    )
    state = run_chunk(
        init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, 8,
        get_step_fn(cfg.protocol),
    )
    reg = MetricsRegistry()
    rec = event_dump(state, registry=reg)
    assert capsys.readouterr().err == ""
    assert rec["tick"] == 8
    snap = reg.snapshot()
    assert snap["counters"]["event_dump_records_total"] == 1
    assert snap["counters"]["events_total{event=decide}"] == rec["chosen"]
    assert "ticks_to_decide" in snap["histograms"]


def test_stats_cli(tmp_path, capsys):
    from paxos_tpu.harness.cli import main

    path = tmp_path / "m.jsonl"
    tel = {
        "counters": {"promise": 9, "decide": 4},
        "hist": [3, 1],
        "hist_ticks_per_bin": 8,
    }
    lines = [
        {"event": "start", "config": "config2"},
        {"event": "chunk", "ticks": 8, "t_wall": 0.5, "violations": 0},
        {"event": "chunk", "ticks": 16, "t_wall": 0.9, "violations": 0,
         "telemetry": tel},
        {"event": "final", "ticks": 16, "chosen_frac": 1.0, "violations": 0,
         "engine": "xla", "telemetry": tel},
    ]
    path.write_text(
        "\n".join(json.dumps(l) for l in lines) + "\nnot json\n"
    )
    assert main(["stats", str(path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["records"] == {"start": 1, "chunk": 2, "final": 1}
    assert out["malformed_lines"] == 1
    assert out["chunks"] == 2 and out["last_tick"] == 16
    assert out["final"]["violations"] == 0
    assert out["telemetry"]["counters"]["decide"] == 4

    assert main(["stats", str(path), "--prometheus"]) == 0
    text = capsys.readouterr().out
    assert 'paxos_tpu_events_total{event="decide"} 4' in text
    assert 'paxos_tpu_log_records_total{record="chunk"} 2' in text
    assert 'paxos_tpu_ticks_to_decide_bucket{le="+Inf"} 4' in text


def test_stats_cli_missing_and_empty(tmp_path, capsys):
    from paxos_tpu.harness.cli import main

    assert main(["stats", str(tmp_path / "nope.jsonl")]) == 1
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main(["stats", str(empty)]) == 1
    capsys.readouterr()


def test_registry_ingest_slo_gauges():
    """SLO block lands as slo_* gauges: per-class series labelled by class,
    latency quantiles in summary idiom, unserved classes export NO
    quantiles (never a faked -1), and the configured SLO target rides
    along as the dashboard breach line."""
    reg = MetricsRegistry()
    reg.ingest_slo(
        {
            "classes": {
                "bursty": {"lanes": 8, "offered": 20, "done": 16,
                           "shed": 4, "goodput": 0.8, "hist": [16, 0],
                           "p50_ticks": 1, "p95_ticks": 3, "p99_ticks": 7},
                "diurnal": {"lanes": 8, "offered": 0, "done": 0,
                            "shed": 0, "goodput": 0.0, "hist": [0, 0],
                            "p50_ticks": -1, "p95_ticks": -1,
                            "p99_ticks": -1},
            },
            "offered": 20, "done": 16, "shed": 4, "goodput": 0.8,
            "queue_depth": 3, "depth_peak": 4, "p99_ticks": 7,
        },
        slo_p99_ticks=16,
    )
    g = reg.snapshot()["gauges"]
    assert g["slo_offered"] == 20
    assert g["slo_goodput"] == 0.8
    assert g["slo_queue_depth"] == 3
    assert g["slo_depth_peak"] == 4
    assert g["slo_p99_ticks"] == 7
    assert g["slo_target_p99_ticks"] == 16
    assert g["slo_offered{class=bursty}"] == 20
    assert g["slo_latency_ticks{class=bursty,quantile=p99}"] == 7
    # The unserved class exports counters but no latency series at all.
    assert g["slo_offered{class=diurnal}"] == 0
    assert not any(
        k.startswith("slo_latency_ticks{class=diurnal") for k in g
    )
    assert 'paxos_tpu_slo_latency_ticks{class="bursty",quantile="p50"} 1' in (
        reg.to_prometheus()
    )


# One representative payload per ingest family — every plane that exports
# gauges into the shared registry.  Growing a new plane?  Add it here so
# the prefix-partition test below covers it.
_INGEST_FAMILIES = {
    "telemetry": ("telemetry_", lambda reg: reg.ingest(
        {"counters": {"decide": 4}, "hist": [4, 0],
         "hist_ticks_per_bin": 4, "hist_overflow": 1})),
    "coverage": ("coverage_", lambda reg: reg.ingest_coverage(
        {"bits_set": 5, "bits_total": 64, "saturation": 5 / 64,
         "est_states": 7.0})),
    "exposure": ("exposure_", lambda reg: reg.ingest_exposure(
        {"classes": {"drop": {"injected": 3, "effective": 1,
                              "lanes_exposed": 2}}},
        lit={"drop": True})),
    "margin": ("margin_", lambda reg: reg.ingest_margin(
        {"min_quorum_slack": 1, "near_misses": 4}, checker_complete=True)),
    "perf": ("perf_", lambda reg: reg.ingest_perf(
        {"dispatches": 2, "rounds_per_sec": 5.0,
         "chunk_latency_us": {"p50": 3.0},
         "vmem": {"vmem_limit_bytes": 1 << 20}})),
    "fleet": ("fleet_", lambda reg: reg.ingest_fleet(
        {"workers": 1, "queue_depth": 0, "records_done": 2})),
    "lineage": ("lineage_", lambda reg: reg.ingest_lineage(
        {"entries": 2, "roots": 1, "best_fitness": 1.0},
        ops={"add-skew": {"fitness": 1.0}})),
    "slo": ("slo_", lambda reg: reg.ingest_slo(
        {"classes": {"poisson": {"lanes": 4, "offered": 2, "done": 2,
                                 "shed": 0, "goodput": 1.0, "hist": [2],
                                 "p50_ticks": 1, "p95_ticks": 1,
                                 "p99_ticks": 1}},
         "offered": 2, "done": 2, "shed": 0, "goodput": 1.0,
         "queue_depth": 0, "depth_peak": 1, "p99_ticks": 1},
        slo_p99_ticks=8)),
    "spans": ("round_latency_", lambda reg: reg.ingest_span_aggregates(
        {"round_latency_p50": 3, "rounds_total": 5, "rounds_decided": 4})),
}

# Pre-plane legacy gauges that intentionally live at the namespace root.
# This list must only ever SHRINK — new planes get a prefix, full stop.
_UNPREFIXED_LEGACY = {
    "hist_overflow_decides",  # telemetry
    "fault_vacuous",          # exposure's vacuous-chaos alert
    "checker_complete",       # margin's oracle-completeness bit
    "rounds_total", "rounds_decided", "rounds_preempted",  # spans
    "preemption_depth_max", "faults_per_decided_round",
}


def test_gauge_prefix_partition():
    """Every plane's gauges stay inside its own prefix: no family may emit
    a gauge under another family's prefix, and anything outside every
    prefix must be a known pre-plane legacy name — so one shared registry
    (fleet mode folds ALL planes into one) can never silently collide."""
    prefixes = {fam: p for fam, (p, _) in _INGEST_FAMILIES.items()}
    for fa, pa in prefixes.items():
        for fb, pb in prefixes.items():
            if fa != fb:
                assert not pa.startswith(pb), (
                    f"prefix {pa!r} ({fa}) shadows {pb!r} ({fb})"
                )
    for fam, (_, drive) in _INGEST_FAMILIES.items():
        reg = MetricsRegistry()
        drive(reg)
        own = prefixes[fam]
        names = {k.split("{")[0] for k in reg.snapshot()["gauges"]}
        assert any(n.startswith(own) for n in names) or fam == "telemetry", (
            f"{fam} ingest emitted nothing under its own prefix {own!r}"
        )
        for n in names:
            hits = [
                (f, p) for f, p in prefixes.items() if n.startswith(p)
            ]
            if hits:
                assert hits == [(fam, own)], (
                    f"gauge {n!r} (emitted by {fam}) collides with the "
                    f"{hits[0][0]} plane's prefix {hits[0][1]!r}"
                )
            else:
                assert n in _UNPREFIXED_LEGACY, (
                    f"gauge {n!r} (emitted by {fam}) squats the root "
                    f"namespace — give it the {own!r} prefix or add it to "
                    f"the legacy list"
                )
