"""Multi-Paxos (config 3): log replication, leader lease, leader crash, recovery."""

import jax.numpy as jnp

from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig, config3_multipaxos
from paxos_tpu.harness.run import run


def test_mp_no_faults_full_logs():
    cfg = SimConfig(
        n_inst=256, n_prop=2, n_acc=5, log_len=8, seed=3, protocol="multipaxos",
        fault=FaultConfig(lease_len=12),
    )
    report, state = run(cfg, until_all_chosen=True, max_ticks=400, return_state=True)
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["decided_frac"] == 1.0  # every instance's full log chosen
    # Validity: chosen values are real proposals: (pid+1)*1000 + slot.
    vals = state.learner.chosen_val  # (L, I)
    slots = jnp.arange(vals.shape[0])[:, None]
    pid = vals // 1000 - 1
    assert bool(((pid >= 0) & (pid < 2)).all())
    assert bool((vals % 1000 == slots).all())


def test_mp_leader_crash_safe_and_live():
    cfg = config3_multipaxos(n_inst=1024, seed=7)
    report, state = run(cfg, total_ticks=700, return_state=True)
    assert report["violations"] == 0
    # Evictions bound checker completeness; with re-confirmation suppression
    # and K=4 rows they should be rare even across many leadership changes.
    assert report["evictions"] < cfg.n_inst * 0.01
    # Leader crashes + 5% drop: not all logs need be complete by 700 ticks,
    # but the vast majority of slots must be (liveness through re-election).
    assert report["chosen_frac"] > 0.95
    assert report["decided_frac"] > 0.80


def test_mp_amnesia_trips_checker():
    """Durable-storage-loss injection (acceptors forget on recovery) MUST
    surface as agreement violations — Paxos safety depends on persistence."""
    cfg = SimConfig(
        n_inst=4096, n_prop=2, n_acc=5, log_len=4, seed=13, protocol="multipaxos",
        fault=FaultConfig(
            p_crash=0.7, crash_max_start=60, crash_max_len=10, amnesia=True,
            p_idle=0.2, p_hold=0.2, lease_len=10, p_crash_prop=0.3,
        ),
    )
    report = run(cfg, total_ticks=400)
    assert report["violations"] > 0


def test_mp_equivocation_trips_checker():
    cfg = SimConfig(
        n_inst=1024, n_prop=2, n_acc=5, log_len=4, seed=5, protocol="multipaxos",
        fault=FaultConfig(p_idle=0.2, p_hold=0.2, p_equiv=0.25, lease_len=12),
    )
    report = run(cfg, total_ticks=400)
    assert report["violations"] > 0  # the MP checker must be falsifiable too


def test_mp_ballot_overflow_guard():
    """ADVICE r4: the packed (ballot, value) layout needs bal < 2^15; a
    campaign whose ballots cross that line must FAIL its report rather
    than silently corrupt lexicographic compares."""
    import pytest

    from paxos_tpu.harness.run import init_state, summarize

    cfg = config3_multipaxos(n_inst=8, seed=0)
    state = init_state(cfg)
    bad = state.replace(
        proposer=state.proposer.replace(
            bal=state.proposer.bal + jnp.int32(1 << 15)
        )
    )
    with pytest.raises(RuntimeError, match="overflow"):
        summarize(bad)
    summarize(state)  # healthy ballots pass


def test_mp_checker_ignores_out_of_window_slots():
    """ADVICE r4: an ACCEPT event with a slot outside [0, n_slots) must be
    dropped by the learner fold, not miscounted as an eviction (min_bv
    reads 0x7FFFFFFF when no one-hot row matches)."""
    from paxos_tpu.check.mp_safety import mp_learner_observe
    from paxos_tpu.core.mp_state import MPLearnerState

    n_inst, n_slots, n_acc = 4, 2, 3
    lrn = MPLearnerState.init(n_inst, n_slots, k=2)
    flag = jnp.ones((n_acc, n_inst), bool)
    bal = jnp.full((n_acc, n_inst), 9, jnp.int32)
    val = jnp.full((n_acc, n_inst), 1005, jnp.int32)
    for bad_slot in (-1, n_slots, n_slots + 7):
        out = mp_learner_observe(
            lrn, flag, bal, jnp.full((n_acc, n_inst), bad_slot, jnp.int32),
            val, jnp.int32(0), quorum=2,
        )
        assert int(out.evictions.sum()) == 0
        assert not bool(out.chosen.any())
    # Control: the same event at a VALID slot does land.
    out = mp_learner_observe(
        lrn, flag, bal, jnp.zeros((n_acc, n_inst), jnp.int32), val,
        jnp.int32(0), quorum=2,
    )
    assert bool(out.chosen[0].all())
