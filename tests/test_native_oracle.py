"""Native C++ oracle: build, fuzz, and triangulate against the Python golden.

SURVEY.md §5.2.1-§5.2.2: three independent implementations (C++ oracle,
Python golden model, batched JAX kernels) must all satisfy agreement +
validity on every seed; the native one covers orders of magnitude more
schedules per second.
"""

import shutil

import pytest

from paxos_tpu.cpu_ref.golden import run_golden
from paxos_tpu.cpu_ref.native import (
    bench_native_steps,
    run_native_batch,
    run_native_fp_batch,
    run_native_mp_batch,
    run_native_raft_batch,
)

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")


@needs_gxx
def test_native_oracle_clean_network():
    """No faults: every seed decides, exactly one value chosen."""
    batch = run_native_batch(seed0=0, n_runs=2000, n_prop=2, n_acc=3)
    assert batch.decided.all()
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert (batch.n_chosen == 1).all()


@needs_gxx
def test_native_oracle_chaos():
    """Drops + duplicates + adversarial timeouts: safety on every seed."""
    batch = run_native_batch(
        seed0=10_000, n_runs=2000, n_prop=2, n_acc=5, p_drop=0.2, p_dup=0.2,
        timeout_weight=0.1,
    )
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    # Chaos hurts liveness, never safety: most seeds should still decide.
    assert batch.decided.mean() > 0.9


@needs_gxx
def test_native_agrees_with_python_golden_propertywise():
    """The two host-side implementations (no shared code/RNG) agree on the
    property level: same safety verdicts, comparable liveness."""
    n = 200
    batch = run_native_batch(seed0=0, n_runs=n, n_prop=2, n_acc=3, p_drop=0.1)
    assert batch.agreement_ok.all() and batch.validity_ok.all()
    py_decided = 0
    for seed in range(n):
        rep = run_golden(seed, n_prop=2, n_acc=3, p_drop=0.1)
        assert rep.agreement_ok and rep.validity_ok, seed
        py_decided += rep.decided
    # Both schedulers are fair: decision rates within a few percent.
    assert abs(py_decided / n - batch.decided.mean()) < 0.05


@needs_gxx
def test_native_bench_counts_steps():
    total = bench_native_steps(seed0=0, n_runs=50, n_prop=1, n_acc=3)
    # A clean 1-proposer instance needs ~a dozen events; 50 runs well under cap.
    assert 50 * 5 < total < 50 * 20_000


# ---- Multi-Paxos oracle (round-1 verdict #9: second protocol) ----


@needs_gxx
def test_native_mp_clean_network():
    """No faults: some proposer replicates the whole log on most seeds, and
    every chosen slot is agreement/validity-clean on all of them."""
    batch = run_native_mp_batch(
        seed0=0, n_runs=1000, n_prop=2, n_acc=3, log_len=4
    )
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert batch.decided.mean() > 0.9
    assert (batch.n_chosen[batch.decided] == 4).all()


@needs_gxx
def test_native_mp_chaos():
    """Drops/dups/preemption storms: per-slot safety on every seed, and a
    finished leader's decided log always equals the chosen values."""
    batch = run_native_mp_batch(
        seed0=7_000, n_runs=1000, n_prop=3, n_acc=5, log_len=6,
        p_drop=0.2, p_dup=0.2, timeout_weight=0.1,
    )
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert batch.decided.mean() > 0.5  # chaos hurts liveness, never safety


# ---- Fast Paxos oracle (round-2 verdict #5: third protocol) ----


@needs_gxx
def test_native_fp_clean_network():
    """No faults, no timeouts: the fast round alone decides every seed —
    but only when uncontended.  With one proposer every seed fast-decides
    on its own value; exactly one value chosen."""
    batch = run_native_fp_batch(seed0=0, n_runs=2000, n_prop=1, n_acc=5)
    assert batch.decided.all()
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert (batch.n_chosen == 1).all()


@needs_gxx
def test_native_fp_collision_recovery():
    """Dueling fast proposers + timeouts: collisions force classic
    recovery rounds; the choosable rule keeps agreement on every seed."""
    batch = run_native_fp_batch(
        seed0=3_000, n_runs=2000, n_prop=2, n_acc=5, timeout_weight=0.05,
    )
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert batch.decided.mean() > 0.9
    # Contention must actually exercise recovery: some runs need > the
    # ~n_prop * n_acc * 2 events an uncontested fast round takes.
    assert (batch.steps > 30).mean() > 0.1


@needs_gxx
def test_native_fp_chaos():
    """Drops + dups + recovery storms: safety on every seed."""
    batch = run_native_fp_batch(
        seed0=11_000, n_runs=2000, n_prop=3, n_acc=7,
        p_drop=0.2, p_dup=0.2, timeout_weight=0.1,
    )
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert batch.decided.mean() > 0.5


@needs_gxx
def test_native_fp_unsafe_quorum_caught():
    """Falsifiability: an FFP triple violating q1 + 2*q_fast > 2n (here
    3 + 2*3 <= 10) must yield agreement violations the oracle reports —
    proving the fp oracle's checker actually bites.  The same triple made
    safe (q_fast=4) is clean across the same seeds."""
    unsafe = run_native_fp_batch(
        seed0=500, n_runs=4000, n_prop=2, n_acc=5, q1=3, q2=3, q_fast=3,
        timeout_weight=0.08,
    )
    assert not unsafe.agreement_ok.all(), "unsafe q_fast must violate"
    safe = run_native_fp_batch(
        seed0=500, n_runs=4000, n_prop=2, n_acc=5, q1=3, q2=3, q_fast=4,
        timeout_weight=0.08,
    )
    assert safe.agreement_ok.all()
    assert safe.validity_ok.all()


# ---- Raft-core oracle (round 3: the native matrix is square) ----


@needs_gxx
def test_native_raft_clean_network():
    """No faults: elections + appends commit exactly one value per seed."""
    batch = run_native_raft_batch(seed0=0, n_runs=2000, n_prop=2, n_acc=3)
    assert batch.decided.all()
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert (batch.n_chosen == 1).all()


@needs_gxx
def test_native_raft_chaos():
    """Drops + dups + elections: safety on every seed.  timeout_weight
    stays moderate (0.05): Raft's vote-once-per-term rule means a
    preemption rate faster than one full election livelocks on split
    votes — authentic Raft behavior (its paper's randomized-timeout
    motivation; the JAX kernel's backoff jitter plays that role).  The
    storm case below fuzzes the aggressive rate for SAFETY only."""
    batch = run_native_raft_batch(
        seed0=13_000, n_runs=2000, n_prop=3, n_acc=5,
        p_drop=0.2, p_dup=0.2, timeout_weight=0.05,
    )
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()
    assert batch.decided.mean() > 0.9


@needs_gxx
def test_native_raft_election_storm_safety():
    """Preemption faster than an election completes: split-vote livelock
    (few seeds decide — expected for vote-once-per-term) must still never
    break agreement across ~75M scheduler events."""
    batch = run_native_raft_batch(
        seed0=21_000, n_runs=2000, n_prop=3, n_acc=5,
        p_drop=0.2, p_dup=0.2, timeout_weight=0.1,
    )
    assert batch.agreement_ok.all()
    assert batch.validity_ok.all()


@needs_gxx
def test_native_raft_two_leg_safety():
    """Event-driven counterpart of the exhaustive two-leg decomposition:
    the election restriction alone is safe, adoption alone is safe,
    removing BOTH lets a stale empty-logged candidate win and commit a
    second value — the oracle must find it."""
    kw = dict(
        seed0=700, n_runs=4000, n_prop=2, n_acc=3,
        p_drop=0.1, timeout_weight=0.1,
    )
    only_restriction = run_native_raft_batch(no_adoption=True, **kw)
    assert only_restriction.agreement_ok.all()
    only_adoption = run_native_raft_batch(no_restriction=True, **kw)
    assert only_adoption.agreement_ok.all()
    neither = run_native_raft_batch(
        no_restriction=True, no_adoption=True, **kw
    )
    assert not neither.agreement_ok.all(), "both legs off must violate"


# ---- Native bounded exhaustive explorer (VERDICT r3 #4) ----


@pytest.mark.slow
def test_native_explorer_cross_validates_python_counts():
    """The C++ explorer mirrors cpu_ref/exhaustive.py's transition system
    (same actions, same GC reductions) — distinct-state AND decided-state
    counts must match the Python set-based checker EXACTLY at shared
    bounds, which also validates the 128-bit fingerprint dedup (zero
    collisions at these sizes would already be expected, but equality
    PROVES no drift)."""
    from paxos_tpu.cpu_ref.exhaustive import check_exhaustive
    from paxos_tpu.cpu_ref.native import explore_native

    py = check_exhaustive(n_prop=1, n_acc=2, max_round=1)
    nat = explore_native(n_prop=1, n_acc=2, max_round=1)
    assert (nat.states, nat.decided_states) == (py.states, py.decided_states)
    assert nat.chosen_values == py.chosen_values == {100}

    py = check_exhaustive(n_prop=2, n_acc=3, max_round=1)
    nat = explore_native(n_prop=2, n_acc=3, max_round=1)
    assert nat.states == py.states == 602_641
    assert nat.decided_states == py.decided_states
    assert nat.chosen_values == py.chosen_values == {100, 101}

    # Asymmetric bounds and a wider quorum, straight from BASELINE.md's
    # recorded Python spaces (the native run takes seconds, not minutes).
    nat = explore_native(n_prop=2, n_acc=3, max_round=(2, 1))
    assert nat.states == 5_804_454  # BASELINE.md deeper-bound row
    nat4 = explore_native(n_prop=2, n_acc=4, max_round=(1, 0))
    py4 = check_exhaustive(
        n_prop=2, n_acc=4, max_round=(1, 0), max_states=10_000_000
    )
    assert (nat4.states, nat4.decided_states) == (py4.states, py4.decided_states)


def test_native_explorer_finds_injected_bug():
    """unsafe_accept must yield a violation at the same bounds the Python
    checker finds one (falsifiability of the native leg)."""
    import pytest

    from paxos_tpu.cpu_ref.native import explore_native

    with pytest.raises(AssertionError, match="invariant violated"):
        explore_native(n_prop=2, n_acc=3, max_round=1, unsafe_accept=True)


def test_native_explorer_max_states_guard():
    import pytest

    from paxos_tpu.cpu_ref.native import explore_native

    with pytest.raises(RuntimeError, match="max_states"):
        explore_native(n_prop=2, n_acc=3, max_round=1, max_states=10_000)


@pytest.mark.slow
def test_native_mp_explorer_cross_validates_python_counts():
    """The C++ Multi-Paxos explorer mirrors cpu_ref/mp_exhaustive.py —
    whole-log phase 1, slot-by-slot phase 2, per-slot max recovery, same
    GC — with values riding as order-isomorphic compact ids; state AND
    decided counts (and the decoded chosen-value sets) must match the
    Python checker EXACTLY at shared bounds."""
    from paxos_tpu.cpu_ref.mp_exhaustive import check_mp_exhaustive
    from paxos_tpu.cpu_ref.native import explore_mp_native

    for kw in (
        {"max_round": (1, 0)},
        {"max_round": 1},
        {"log_len": 1, "max_round": 1},
        {"n_acc": 5, "max_round": (1, 0)},
    ):
        py = check_mp_exhaustive(max_states=10_000_000, **kw)
        nat = explore_mp_native(**kw)
        assert (nat.states, nat.decided_states) == (
            py.states, py.decided_states,
        ), kw
        assert nat.chosen_values == py.chosen_values, kw


def test_native_mp_explorer_reproduces_canonical_bound():
    """BASELINE.md's recorded (2,1)-retry 2-slot Python space (1,663,138
    states, 318,457 fully-replicated) in seconds instead of ~9 minutes."""
    from paxos_tpu.cpu_ref.native import explore_mp_native

    nat = explore_mp_native(max_round=(2, 1))
    assert nat.states == 1_663_138
    assert nat.decided_states == 318_457


def test_native_mp_explorer_finds_skipped_recovery_bug():
    """no_recovery (a new leader drives its own values from slot 0) must
    yield a violation, as the Python checker does."""
    import pytest

    from paxos_tpu.cpu_ref.native import explore_mp_native

    with pytest.raises(AssertionError, match="invariant violated"):
        explore_mp_native(max_round=(2, 1), no_recovery=True)


@pytest.mark.slow
def test_native_fp_explorer_cross_validates_python_counts():
    """The C++ Fast Paxos explorer (round-5 matrix completion) mirrors
    cpu_ref/fp_exhaustive.py — shared fast ballot, vote-at-most-once
    acceptors, choosable-rule recovery, same GC; state AND decided counts
    and chosen-value sets must match the Python checker EXACTLY at shared
    bounds, including an FFP quorum triple (non-majority code path)."""
    from paxos_tpu.cpu_ref.fp_exhaustive import check_fp_exhaustive
    from paxos_tpu.cpu_ref.native import explore_fp_native

    for kw in (
        {"max_round": (0, 0), "n_acc": 5},
        {"max_round": (1, 0), "n_acc": 3},
        {"max_round": (1, 1), "n_acc": 3},
        {"max_round": (1, 0), "n_acc": 5, "q1": 4, "q2": 2, "q_fast": 4},
    ):
        py = check_fp_exhaustive(max_states=10_000_000, **kw)
        nat = explore_fp_native(**kw)
        assert (nat.states, nat.decided_states) == (
            py.states, py.decided_states,
        ), kw
        assert nat.chosen_values == py.chosen_values, kw


def test_native_fp_explorer_reproduces_canonical_bound():
    """BASELINE.md's recorded FP bound (2 fast proposers x 5 acceptors, one
    coordinated recovery round: 4,013,181 states, ~3.5 min Python) in
    seconds."""
    from paxos_tpu.cpu_ref.native import explore_fp_native

    nat = explore_fp_native(n_acc=5, max_round=(1, 0))
    assert nat.states == 4_013_181
    assert nat.chosen_values == {100, 101}


def test_native_fp_explorer_finds_injected_bugs():
    """Both FP falsifiability legs fire natively: adopt_any (skip the
    choosable rule) and an unsafe FFP fast quorum (q_fast=3 over n=5
    violates the intersection condition)."""
    import pytest

    from paxos_tpu.cpu_ref.native import explore_fp_native

    with pytest.raises(AssertionError, match="invariant violated"):
        explore_fp_native(n_acc=5, max_round=(1, 0), adopt_any=True)
    with pytest.raises(AssertionError, match="invariant violated"):
        explore_fp_native(n_acc=5, max_round=(1, 0), q_fast=3)


@pytest.mark.slow
def test_native_raft_explorer_cross_validates_python_counts():
    """The C++ Raft-core explorer (round-5 matrix completion) mirrors
    cpu_ref/raft_exhaustive.py — election restriction, one-vote-per-term,
    adoption from grants AND denials, same conservative GC; counts must
    match the Python checker EXACTLY at shared bounds."""
    from paxos_tpu.cpu_ref.native import explore_raft_native
    from paxos_tpu.cpu_ref.raft_exhaustive import check_raft_exhaustive

    for kw in (
        {"max_round": (0, 0)},
        {"max_round": (1, 0)},
        {"max_round": (1, 1)},
        # 5-acceptor quorum path with one candidate: the cheapest bound
        # that exercises the wide-quorum encoding in both checkers (two
        # candidates at 5 acceptors start at 4.5M states — native-only
        # territory; see the BASELINE.md deep-bound rows).
        {"n_prop": 1, "max_round": (2,), "n_acc": 5},
    ):
        py = check_raft_exhaustive(max_states=10_000_000, **kw)
        nat = explore_raft_native(**kw)
        assert (nat.states, nat.decided_states) == (
            py.states, py.decided_states,
        ), kw
        assert nat.chosen_values == py.chosen_values, kw


def test_native_raft_explorer_two_leg_decomposition():
    """The mechanized safety decomposition reproduces natively: either leg
    alone (restriction or adoption) keeps the bounded space clean;
    disabling BOTH yields a violation."""
    import pytest

    from paxos_tpu.cpu_ref.native import explore_raft_native

    assert explore_raft_native(max_round=1, no_restriction=True).states > 0
    assert explore_raft_native(max_round=1, no_adoption=True).states > 0
    with pytest.raises(AssertionError, match="invariant violated"):
        explore_raft_native(max_round=1, no_restriction=True, no_adoption=True)


def test_native_explorer_three_proposers_cross_validates():
    """VERDICT r4 #8: a third proposer reaches schedule corners two cannot
    (three-way promise splits, simultaneous duels); the native 3-proposer
    space must match Python exactly at a shared bound, with all three
    values chosen somewhere in the space."""
    from paxos_tpu.cpu_ref.exhaustive import check_exhaustive
    from paxos_tpu.cpu_ref.native import explore_native

    py = check_exhaustive(n_prop=3, n_acc=3, max_round=0, max_states=1_000_000)
    nat = explore_native(n_prop=3, n_acc=3, max_round=0)
    assert (nat.states, nat.decided_states) == (py.states, py.decided_states)
    assert nat.states == 206_317
    assert nat.chosen_values == py.chosen_values == {100, 101, 102}
