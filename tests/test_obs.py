"""Causal round tracing (obs/): span reconstruction, export, host spans.

Contracts:

1. **Reconstruction is exact and pure**: a synthetic timeline maps to the
   documented span semantics (decide/timeout/preemption closes, fault
   annotations, trailing-open), and decoding the SAME campaign twice
   yields byte-identical spans — the builder is a pure function of the
   ring, never of wall time or entropy.
2. **The exporter is schema-honest**: ``validate_chrome_trace`` passes on
   everything we emit (both process tracks, matched async begin/end,
   monotonic ts) and actually rejects broken traces.
3. **End-to-end**: ``paxos_tpu trace`` on a corrupt campaign produces a
   Perfetto-loadable file whose device track names the corruption and
   whose host track shows the dispatch loop; ``stats`` folds the span
   aggregates into gauges.
"""

import json

import pytest

from paxos_tpu.harness import config as C
from paxos_tpu.obs.export import (
    DEVICE_PID,
    HOST_PID,
    chrome_trace,
    spans_jsonl,
    validate_chrome_trace,
    write_chrome_trace,
)
from paxos_tpu.obs.host_spans import (
    HostSpanRecorder,
    NullSpanRecorder,
    ensure_recorder,
)
from paxos_tpu.obs.spans import RoundSpan, build_spans, span_aggregates

# A synthetic lane history exercising every close rule:
#   round 0: leader at 0, promise, accept+drop, decide at 5   -> decided
#   round 1: timeout at 7 (opens round 2 at the same tick)    -> timeout
#   round 2: leader at 9, second leader at 11                 -> preempted
#   round 3: opens at 11, trailing                            -> open
TIMELINE = [
    {"tick": 0, "events": ["leader"]},
    {"tick": 2, "events": ["promise"]},
    {"tick": 3, "events": ["accept", "drop"]},
    {"tick": 5, "events": ["decide"]},
    {"tick": 7, "events": ["timeout"]},
    {"tick": 9, "events": ["leader", "corrupt"]},
    {"tick": 11, "events": ["leader"]},
    {"tick": 12, "events": ["promise"]},
]


def test_build_spans_semantics():
    spans = build_spans(TIMELINE, lane=3)
    assert [s.outcome for s in spans] == [
        "decided", "timeout", "preempted", "open",
    ]
    assert [s.round for s in spans] == [0, 1, 2, 3]
    assert all(s.lane == 3 for s in spans)

    decided = spans[0]
    assert (decided.start, decided.end) == (0, 5)
    assert decided.leader_tick == 0
    assert decided.p1_tick == 2 and decided.p2_tick == 3
    assert decided.faults == [{"tick": 3, "kind": "drop"}]
    assert decided.events["promise"] == 1

    # Timeout closes AND re-opens at the same tick (ballot retry).
    assert (spans[1].start, spans[1].end) == (7, 7)
    assert spans[2].start == 7

    # Second leader without a decide = preemption; the corrupt fault
    # annotates the span it landed in.
    assert spans[2].leader_tick == 9
    assert {"tick": 9, "kind": "corrupt"} in spans[2].faults
    assert spans[2].events["leader"] == 2

    # Trailing span stays open and ends at the last seen tick.
    assert (spans[3].start, spans[3].end) == (11, 12)

    # to_json is JSON-serializable and round-trips the key fields.
    j = [s.to_json() for s in spans]
    json.dumps(j)
    assert j[0]["outcome"] == "decided" and j[0]["p2_tick"] == 3


def test_decide_beats_timeout_and_leader_on_shared_tick():
    spans = build_spans(
        [{"tick": 4, "events": ["decide", "timeout", "leader"]}], lane=0
    )
    assert [s.outcome for s in spans] == ["decided"]


def test_span_aggregates_exact():
    agg = span_aggregates(build_spans(TIMELINE, lane=3))
    assert agg["rounds_total"] == 4
    assert agg["rounds_decided"] == 1
    assert agg["rounds_timeout"] == 1
    assert agg["rounds_preempted"] == 1
    assert agg["rounds_open"] == 1
    # One decided round of latency 5; nearest-rank puts every quantile there.
    assert agg["round_latency_p50"] == 5.0
    assert agg["round_latency_p99"] == 5.0
    assert agg["preemption_depth_max"] == 0  # the decide came first
    assert agg["faults_total"] == 2
    assert agg["faults_per_decided_round"] == 2.0

    # No decided rounds: latency sentinel, faults counted raw.
    agg0 = span_aggregates(build_spans(
        [{"tick": 1, "events": ["timeout"]}, {"tick": 2, "events": ["drop"]}],
        lane=0,
    ))
    assert agg0["round_latency_p50"] == -1.0
    assert agg0["rounds_decided"] == 0 and agg0["faults_total"] == 1


def test_preemption_depth_counts_burned_attempts():
    tl = [
        {"tick": 1, "events": ["timeout"]},
        {"tick": 3, "events": ["timeout"]},
        {"tick": 6, "events": ["decide"]},
        {"tick": 8, "events": ["decide"]},
    ]
    agg = span_aggregates(build_spans(tl, lane=0))
    # Two timed-out attempts before the first decide, none before the next.
    assert agg["preemption_depth_max"] == 2
    assert agg["preemption_depth_mean"] == 1.0


def test_chrome_trace_schema_and_tracks():
    spans = build_spans(TIMELINE, lane=3)
    host = HostSpanRecorder(_FakeClock().now)
    with host.span("dispatch", tick_start=0, ticks=64, groups=4):
        pass
    host.instant("probe_done")
    obj = chrome_trace({3: spans}, host=host, meta={"config": "test"})
    assert validate_chrome_trace(obj) == []
    evs = obj["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert pids == {DEVICE_PID, HOST_PID}
    # One async b/e pair per span, on the lane's thread.
    bs = [e for e in evs if e["ph"] == "b"]
    assert len(bs) == len(spans) and all(e["tid"] == 3 for e in bs)
    assert len([e for e in evs if e["ph"] == "e"]) == len(spans)
    # Faults render as instants on the device track.
    faults = [e for e in evs if e["ph"] == "i" and e.get("cat") == "fault"]
    assert {e["name"] for e in faults} == {"drop", "corrupt"}
    # Host spans render as complete events with wall-us timestamps.
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 1 and xs[0]["args"]["groups"] == 4
    assert obj["otherData"]["config"] == "test"


def test_validator_rejects_broken_traces():
    good = chrome_trace({0: build_spans(TIMELINE, lane=0)})
    assert validate_chrome_trace(good) == []
    assert validate_chrome_trace({"nope": 1})
    assert validate_chrome_trace({"traceEvents": "not-a-list"})

    # Unmatched async end.
    bad_e = {"traceEvents": [
        {"ph": "e", "name": "r", "pid": 0, "tid": 0, "ts": 1,
         "cat": "round", "id": "L0R0"},
    ]}
    assert any("end without begin" in e for e in validate_chrome_trace(bad_e))

    # Dangling async begin.
    bad_b = {"traceEvents": [
        {"ph": "b", "name": "r", "pid": 0, "tid": 0, "ts": 1,
         "cat": "round", "id": "L0R0"},
    ]}
    assert any("begin without end" in e for e in validate_chrome_trace(bad_b))

    # Non-monotonic ts.
    bad_ts = {"traceEvents": [
        {"ph": "i", "name": "a", "pid": 0, "ts": 5, "s": "t"},
        {"ph": "i", "name": "b", "pid": 0, "ts": 2, "s": "t"},
    ]}
    assert any("ts" in e for e in validate_chrome_trace(bad_ts))

    # Missing required keys per phase.
    bad_keys = {"traceEvents": [{"ph": "X", "name": "x", "pid": 0, "ts": 0}]}
    assert any("missing keys" in e for e in validate_chrome_trace(bad_keys))


class _FakeClock:
    """Deterministic injected clock: advances 1 ms per reading."""

    def __init__(self):
        self.t = 0.0

    def now(self) -> float:
        self.t += 0.001
        return self.t


def test_host_span_recorder_with_injected_clock():
    clk = _FakeClock()
    rec = HostSpanRecorder(clk.now)
    with rec.span("outer", k=1):
        with rec.span("inner"):
            pass
        rec.instant("mark")
    # Inner closes before outer; each clock read adds exactly 1000 us.
    assert [s["name"] for s in rec.spans] == ["inner", "outer"]
    inner, outer = rec.spans
    assert inner["dur"] == 1000 and outer["args"] == {"k": 1}
    assert outer["ts"] < inner["ts"] and outer["dur"] > inner["dur"]
    assert rec.instants[0]["name"] == "mark"

    # The None guard returns the no-op recorder; real recorders pass through.
    assert isinstance(ensure_recorder(None), NullSpanRecorder)
    assert ensure_recorder(rec) is rec
    with ensure_recorder(None).span("ignored"):
        pass


def test_spans_jsonl_roundtrip():
    spans = build_spans(TIMELINE, lane=1)
    text = spans_jsonl(spans)
    parsed = [json.loads(line) for line in text.splitlines()]
    assert parsed == [s.to_json() for s in spans]


def test_reconstruction_deterministic_across_decodes():
    """Same campaign, decoded twice: identical spans, bit for bit — and
    enabling the host span layer never perturbs the schedule."""
    from paxos_tpu.obs.capture import capture_round_trace

    cfg = C.config_corrupt(128, 0)
    kw = dict(ticks=48, chunk=16, max_lanes=3)
    a = capture_round_trace(cfg, **kw)
    b = capture_round_trace(cfg, recorder=HostSpanRecorder(_FakeClock().now),
                            **kw)
    assert a.lanes == b.lanes
    for lane in a.lanes:
        assert [s.to_json() for s in a.spans[lane]] == [
            s.to_json() for s in b.spans[lane]
        ]
    assert a.aggregates == b.aggregates
    assert a.report["violations"] == b.report["violations"]


def test_corrupt_campaign_spans_name_corruption():
    """Acceptance: the corrupt config's reconstructed spans carry the
    injected corruption as fault annotations with their ticks."""
    from paxos_tpu.obs.capture import capture_round_trace

    cap = capture_round_trace(C.config_corrupt(128, 0), ticks=48, chunk=16,
                              max_lanes=4)
    all_spans = [s for lane in cap.lanes for s in cap.spans[lane]]
    corrupt = [
        f for s in all_spans for f in s.faults if f["kind"] == "corrupt"
    ]
    assert corrupt, "corrupt campaign must annotate spans with corruption"
    assert all(isinstance(f["tick"], int) for f in corrupt)
    assert cap.aggregates["faults_total"] >= len(corrupt)
    # Violating lanes decode first (the corrupt config trips the checker).
    assert cap.report["violations"] > 0


def test_registry_span_gauges_and_prometheus():
    from paxos_tpu.harness.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.ingest_span_aggregates(span_aggregates(build_spans(TIMELINE, 0)))
    snap = reg.snapshot()
    assert snap["gauges"]["round_latency_ticks{quantile=p50}"] == 5.0
    assert snap["gauges"]["rounds_total"] == 4
    text = reg.to_prometheus()
    assert "# TYPE paxos_tpu_round_latency_ticks gauge" in text
    assert 'paxos_tpu_round_latency_ticks{quantile="p99"} 5' in text
    assert "paxos_tpu_faults_per_decided_round 2" in text

    # Undecided aggregates: the -1.0 sentinel must NOT leak into gauges.
    reg2 = MetricsRegistry()
    reg2.ingest_span_aggregates(span_aggregates([]))
    assert "round_latency_ticks{quantile=p50}" not in (
        reg2.snapshot().get("gauges", {})
    )


def test_cli_trace_end_to_end(tmp_path, capsys):
    """`paxos_tpu trace` exports a valid Perfetto file (device + host
    tracks), a parseable span JSONL, and a stats-consumable log."""
    from paxos_tpu.harness.cli import main

    out = tmp_path / "trace.json"
    sj = tmp_path / "spans.jsonl"
    log = tmp_path / "m.jsonl"
    rc = main([
        "trace", "--config", "corrupt", "--n-inst", "128", "--ticks", "48",
        "--chunk", "16", "--lanes", "3", "--out", str(out),
        "--spans-out", str(sj), "--log", str(log),
    ])
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary["rounds_total"] > 0 and summary["host_spans"] > 0

    obj = json.loads(out.read_text())
    assert validate_chrome_trace(obj) == []
    pids = {e["pid"] for e in obj["traceEvents"]}
    assert pids == {DEVICE_PID, HOST_PID}
    assert any(
        e["ph"] == "i" and e.get("cat") == "fault" and e["name"] == "corrupt"
        for e in obj["traceEvents"]
    )
    dispatch = [
        e for e in obj["traceEvents"]
        if e["ph"] == "X" and e["name"] == "dispatch"
    ]
    assert dispatch and all("tick_start" in e["args"] for e in dispatch)

    for line in sj.read_text().splitlines():
        assert json.loads(line)["outcome"] in (
            "decided", "timeout", "preempted", "open",
        )
    records = [json.loads(l) for l in log.read_text().splitlines()]
    kinds = [r["event"] for r in records]
    assert "spans" in kinds and kinds[-1] == "final"

    # stats folds the span aggregates into the summary and the registry.
    capsys.readouterr()
    assert main(["stats", str(log)]) == 0
    stats = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert stats["span_aggregates"]["rounds_total"] == (
        summary["rounds_total"]
    )


def test_write_chrome_trace_host_only(tmp_path):
    """--span-trace's host-only export: no device track, still valid."""
    rec = HostSpanRecorder(_FakeClock().now)
    with rec.span("dispatch", tick_start=0, ticks=8, groups=1):
        pass
    obj = write_chrome_trace(str(tmp_path / "h.json"), {}, host=rec)
    assert validate_chrome_trace(obj) == []
    assert {e["pid"] for e in obj["traceEvents"]} == {HOST_PID}
