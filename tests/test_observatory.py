"""Fleet observatory: time-series journal, merge, trend gate, lineage.

The load-bearing contracts under test:

- the time-series journal has the corpus journal's crash-safety (torn
  final line dropped, everything before it recovered, mid-file
  corruption loud);
- ``merge_series`` is canonical — shuffled worker completion order and
  replayed (duplicate-clock) rows produce byte-identical merged series;
- sampled fleet recovery is byte-identical: a preempted + resumed/
  replayed record's merged series equals the uninterrupted baseline's;
- ``compare_series`` names the worker and record for every finding and
  stays quiet on healthy runs;
- the lineage plane reconstructs the family tree (re-parented and
  retired entries included) and its per-op attribution sums match the
  journal's feedback totals EXACTLY;
- the fleet Chrome trace passes ``validate_chrome_trace`` with a track
  per worker plus fleet-aggregate counters.
"""

import json

import pytest

from paxos_tpu.fuzz.corpus import append_event, event_line, load_journal
from paxos_tpu.fuzz.lineage import (
    build_lineage,
    lineage_summary,
    margin_tightened,
    op_attribution,
    render_op_table,
    render_tree,
)
from paxos_tpu.obs.export import fleet_chrome_trace, validate_chrome_trace
from paxos_tpu.obs.timeseries import (
    SeriesSampler,
    compare_series,
    load_series,
    merge_series,
    sample_row,
    write_series,
)


class _Reg:
    """Stand-in for MetricsRegistry.snapshot() (no jax import needed)."""

    def __init__(self, gauges):
        self.gauges = gauges

    def snapshot(self):
        return {"gauges": dict(self.gauges)}


# -- journal crash-safety -------------------------------------------------

def _write_samples(path, worker, n, every=1, record="c00000"):
    with open(path, "a") as fh:
        s = SeriesSampler(fh, worker, every=every)
        for clock in range(n):
            s.sample(record=record, attempt=0, clock=clock,
                     registry=_Reg({"worker_union_bits": 10 + clock}))
    return s


def test_sampler_cadence_and_seq(tmp_path):
    p = tmp_path / "w0.jsonl"
    s = _write_samples(p, "w0", 6, every=2)
    assert s.samples == 3 and s.seq == 3  # clocks 0, 2, 4
    loaded = load_series(p)
    assert not loaded["torn_tail"]
    rows = loaded["rows"]
    assert [r["clock"] for r in rows] == [0, 2, 4]
    assert [r["seq"] for r in rows] == [0, 1, 2]
    assert all(r["worker"] == "w0" and r["record"] == "c00000"
               for r in rows)
    assert rows[0]["gauges"] == {"worker_union_bits": 10}


def test_sampler_off_writes_nothing(tmp_path):
    p = tmp_path / "w0.jsonl"
    with open(p, "a") as fh:
        s = SeriesSampler(fh, "w0", every=0)
        assert not s.sample(record="c00000", attempt=0, clock=0,
                            registry=_Reg({}))
    assert p.read_text() == ""


def test_torn_tail_mid_line_recovers(tmp_path):
    """A crash mid-append tears the final line at an arbitrary byte —
    every earlier row must survive, at every possible tear point."""
    p = tmp_path / "w0.jsonl"
    _write_samples(p, "w0", 3)
    whole = p.read_text()
    lines = whole.splitlines(keepends=True)
    last = lines[-1]
    for cut in range(len(last) - 1):  # tear anywhere inside the record
        torn = tmp_path / f"torn{cut}.jsonl"
        torn.write_text("".join(lines[:-1]) + last[:cut])
        loaded = load_series(torn)
        assert loaded["torn_tail"] == (cut > 0)
        assert [r["clock"] for r in loaded["rows"]] == [0, 1]


def test_torn_tail_mid_record_boundary(tmp_path):
    """Truncation exactly at a line boundary is a clean (shorter)
    journal, not a torn tail."""
    p = tmp_path / "w0.jsonl"
    _write_samples(p, "w0", 3)
    lines = p.read_text().splitlines(keepends=True)
    p.write_text("".join(lines[:2]))
    loaded = load_series(p)
    assert not loaded["torn_tail"]
    assert [r["clock"] for r in loaded["rows"]] == [0, 1]


def test_mid_file_corruption_raises(tmp_path):
    p = tmp_path / "w0.jsonl"
    _write_samples(p, "w0", 3)
    lines = p.read_text().splitlines(keepends=True)
    p.write_text(lines[0] + "{garbage\n" + lines[2])
    with pytest.raises(ValueError):
        load_series(p)


# -- merge determinism ----------------------------------------------------

def _rows(worker, record, clocks, seq0=0, bits=None):
    return [
        sample_row(worker=worker, record=record, attempt=0, seq=seq0 + i,
                   clock=c,
                   gauges={"worker_union_bits": (bits or {}).get(c, c)})
        for i, c in enumerate(clocks)
    ]


def test_merge_shuffled_streams_byte_identical():
    """Stream order is completion order — the merge must not care."""
    a = _rows("w0", "c00000", [0, 1, 2])
    b = _rows("w1", "c00001", [0, 1, 2])
    c = _rows("w2", "c00002", [0, 1])
    m1 = merge_series([a, b, c])
    m2 = merge_series([c, a, b])
    m3 = merge_series([b, c, a])
    assert m1["digest"] == m2["digest"] == m3["digest"]
    assert m1["lines"] == m2["lines"]
    assert m1["samples"] == 8 and m1["dedup"] == 0
    # Canonical order: by record then clock, never by arrival.
    keys = [(e["record"], e["clock"]) for e in m1["events"]]
    assert keys == sorted(keys)


def test_merge_dedups_replayed_clocks():
    """A killed worker's durable samples + its replacement's full replay
    carry duplicate (record, clock) keys with identical deterministic
    gauges — one copy survives and the digest matches a clean run."""
    clean = merge_series([_rows("w0", "c00000", [0, 1, 2, 3])])
    dead = _rows("w0", "c00000", [0, 1])  # killed after clock 1
    replay = _rows("w1r", "c00000", [0, 1, 2, 3])  # atomic re-run
    chaos = merge_series([dead, replay])
    assert chaos["dedup"] == 2
    assert chaos["digest"] == clean["digest"]
    assert chaos["workers"]["w0"]["samples"] == 2
    assert chaos["workers"]["w1r"]["seq_monotone"] is True


def test_merge_flags_non_monotone_seq():
    bad = _rows("w0", "c00000", [0, 1]) + _rows("w0", "c00001", [0])
    # Third row restarts seq at 0 — a corrupted or spliced journal.
    assert merge_series([bad])["workers"]["w0"]["seq_monotone"] is False
    good = _rows("w0", "c00000", [0, 1]) + _rows(
        "w0", "c00001", [0], seq0=2
    )
    assert merge_series([good])["workers"]["w0"]["seq_monotone"] is True


def test_write_series_roundtrip(tmp_path):
    merged = merge_series([_rows("w0", "c00000", [0, 1])])
    out = tmp_path / "merged.jsonl"
    digest = write_series(out, merged)
    loaded = load_journal(out)
    assert not loaded["torn_tail"]
    assert loaded["digest"] == digest  # trailing digest line, separated
    canon = [e for e in loaded["events"] if e["event"] == "sample"]
    assert [event_line(e) for e in canon] == merged["lines"]
    assert "worker" not in canon[0] and "seq" not in canon[0]


# -- the trend gate -------------------------------------------------------

def test_compare_series_clean_run_is_ok():
    rows = _rows("w0", "c00000", list(range(6)),
                 bits={c: 100 + 10 * c for c in range(6)})
    gate = compare_series(rows)
    assert gate["ok"] and gate["compared"] == 6
    assert gate["findings"] == []


def test_compare_series_discovery_stall_names_worker_and_record():
    flat = {c: 64 for c in range(6)}
    rows = _rows("w0", "c00000", list(range(6)), bits=flat)
    rows += _rows("w1", "c00001", list(range(6)),
                  bits={c: 10 * (c + 1) for c in range(6)})
    gate = compare_series(rows)
    assert not gate["ok"]
    assert [f["kind"] for f in gate["findings"]] == ["discovery_stall"]
    f = gate["findings"][0]
    assert f["worker"] == "w0" and f["record"] == "c00000"
    # Below the sample threshold the same flat series is not a finding
    # (a short record legitimately plateaus).
    short = _rows("w0", "c00000", list(range(4)), bits=flat)
    assert compare_series(short)["findings"] == []


def _wall_rows(worker, record, walls):
    rows = _rows(worker, record, list(range(len(walls))),
                 bits={c: 10 * (c + 1) for c in range(len(walls))})
    for r, w in zip(rows, walls):
        r["wall"] = w
    return rows


def test_compare_series_rps_degradation():
    rows = _wall_rows("w0", "c00000", [
        {"t": 0.0, "rps": 100.0}, {"t": 1.0, "rps": 110.0},
        {"t": 2.0, "rps": 90.0}, {"t": 3.0, "rps": 10.0},
    ])
    gate = compare_series(rows)
    assert [f["kind"] for f in gate["findings"]] == ["rps_degradation"]
    f = gate["findings"][0]
    assert f["worker"] == "w0" and f["record"] == "c00000"
    assert f["last_rps"] == 10.0


def test_compare_series_heartbeat_gap():
    rows = _wall_rows("w0", "c00000", [
        {"t": 0.0, "rps": 100.0}, {"t": 10.0, "rps": 100.0},
        {"t": 20.0, "rps": 100.0}, {"t": 300.0, "rps": 100.0},
    ])
    gate = compare_series(rows)
    assert [f["kind"] for f in gate["findings"]] == ["heartbeat_gap"]
    f = gate["findings"][0]
    assert f["worker"] == "w0" and f["gap_s"] == 280.0
    # The absolute floor keeps small-scale gaps (slow CI) quiet even
    # when they dwarf the median.
    calm = _wall_rows("w0", "c00000", [
        {"t": 0.0, "rps": 1.0}, {"t": 1.0, "rps": 1.0},
        {"t": 2.0, "rps": 1.0}, {"t": 60.0, "rps": 1.0},
    ])
    assert compare_series(calm)["findings"] == []


def _slo_rows(worker, record, p99s):
    rows = _rows(worker, record, list(range(len(p99s))),
                 bits={c: 10 * (c + 1) for c in range(len(p99s))})
    for r, v in zip(rows, p99s):
        r["gauges"]["slo_p99_ticks"] = v
    return rows


def test_compare_series_slo_degradation():
    """A record whose LAST p99 sample blows past slo_k x its own median
    is a finding naming worker and record; the campaign-total percentile
    would blur the late blow-up away."""
    rows = _slo_rows("w0", "c00000", [4, 4, 4, 4, 12])
    gate = compare_series(rows)
    assert [f["kind"] for f in gate["findings"]] == ["slo_degradation"]
    f = gate["findings"][0]
    assert f["worker"] == "w0" and f["record"] == "c00000"
    assert f["last_p99_ticks"] == 12.0 and f["median_p99_ticks"] == 4.0
    # Steady latency is quiet, even when nonzero.
    assert compare_series(_slo_rows("w0", "c0", [4, 4, 5, 4, 6]))[
        "findings"] == []
    # Below 4 samples a spike is not a trend.
    assert compare_series(_slo_rows("w0", "c0", [4, 4, 12]))[
        "findings"] == []
    # An all-unserved record (median 0) never divides into a finding.
    assert compare_series(_slo_rows("w0", "c0", [0, 0, 0, 0, 9]))[
        "findings"] == []
    # The knob is honest: a looser gate admits the same series.
    assert compare_series(rows, slo_k=4.0)["findings"] == []


def test_compare_series_empty_is_not_ok():
    gate = compare_series([])
    assert not gate["ok"] and gate["compared"] == 0


# -- unified fleet timeline ----------------------------------------------

def test_fleet_chrome_trace_validates():
    timeline = {
        "t0": 1000.0,
        "instants": [
            {"t": 1000.0, "name": "spawn", "worker": "w0"},
            {"t": 1000.1, "name": "spawn", "worker": "w1"},
            {"t": 1001.0, "name": "claim", "worker": "w0",
             "args": {"record": "c00000"}},
            {"t": 1001.5, "name": "sigkill", "worker": "w1"},
            {"t": 1002.0, "name": "reclaim"},
            {"t": 1002.5, "name": "lease_renew", "worker": "w0"},
        ],
        "spans": [
            {"worker": "w0", "record": "c00000", "attempt": 0,
             "t_start": 1001.0, "t_end": 1004.0},
            {"worker": "w1", "record": "c00001", "attempt": 0,
             "t_start": 1001.2, "t_end": 1001.5},
        ],
        "gauges": [
            {"t": 1001.0, "gauges": {"records_done": 0, "queue_depth": 2,
                                     "workers_alive": 2}},
            {"t": 1004.0, "gauges": {"records_done": 2, "queue_depth": 0,
                                     "workers_alive": 1}},
        ],
    }
    rows = _wall_rows("w0", "c00000", [
        {"t": 1001.5, "rps": 50.0}, {"t": 1002.5, "rps": 60.0},
    ])
    trace = fleet_chrome_trace(timeline, rows, meta={"records": 2})
    assert validate_chrome_trace(trace) == []
    events = trace["traceEvents"]
    procs = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {"fleet coordinator", "worker w0", "worker w1"}
    counters = {(e["pid"], e["name"]) for e in events if e["ph"] == "C"}
    assert ("fleet_records_done" in {n for _, n in counters})
    assert any(n == "union_bits" for _, n in counters)
    # Worker tracks are distinct pids; spans live on their worker's pid.
    span_pids = {e["pid"] for e in events if e["ph"] == "b"}
    assert len(span_pids) == 2 and 1 not in span_pids


def test_fleet_chrome_trace_clamps_degenerate_spans():
    """A span whose end precedes its start (clock skew between observer
    ticks) must clamp, not produce a negative-duration pair."""
    timeline = {"t0": 100.0, "instants": [], "gauges": [], "spans": [
        {"worker": "w0", "record": "c00000", "attempt": 1,
         "t_start": 101.0, "t_end": 100.5},
    ]}
    trace = fleet_chrome_trace(timeline)
    assert validate_chrome_trace(trace) == []
    b = next(e for e in trace["traceEvents"] if e["ph"] == "b")
    e = next(e for e in trace["traceEvents"] if e["ph"] == "e")
    assert e["ts"] == b["ts"]


# -- corpus lineage -------------------------------------------------------

_J = [
    {"event": "add", "id": 0, "seed": 5, "parent": None, "ops": [],
     "root": True, "atoms_digest": "a0"},
    {"event": "feedback", "id": 0, "fingerprint": "f0", "new_bits": 100,
     "effective": {"crash": 2}, "min_quorum_slack": None,
     "violations": 0, "fitness": 100.0},
    {"event": "add", "id": 1, "seed": 5, "parent": 0,
     "ops": ["add-partition", "add-skew"], "root": False,
     "atoms_digest": "a1"},
    {"event": "feedback", "id": 1, "fingerprint": "f1", "new_bits": 30,
     "effective": {"partition": 4}, "min_quorum_slack": 2,
     "violations": 0, "fitness": 60.5},
    {"event": "add", "id": 2, "seed": 5, "parent": 1,
     "ops": ["ballot-pressure"], "root": False, "atoms_digest": "a2"},
    {"event": "feedback", "id": 2, "fingerprint": "f2", "new_bits": 7,
     "effective": {"partition": 4}, "min_quorum_slack": 1,
     "violations": 1, "fitness": 33.25},
    {"event": "retire", "id": 1, "reason": "plateau"},
    # A merge-re-parented entry: its original parent deduped away, the
    # merge re-linked it onto the surviving id 0.
    {"event": "add", "id": 3, "seed": 9, "parent": 0,
     "ops": ["add-delay", "add-skew"], "root": False,
     "atoms_digest": "a3"},
]


def test_build_lineage_tree_reconstruction():
    lin = build_lineage(_J)
    assert lin["roots"] == [0]
    assert lin["order"] == [0, 1, 2, 3]
    n = lin["nodes"]
    assert n[0]["children"] == [1, 3]  # re-parented child linked
    assert n[1]["children"] == [2]
    assert [n[i]["depth"] for i in (0, 1, 2, 3)] == [0, 1, 2, 1]
    assert lin["depth_max"] == 2
    assert n[1]["retired"] == "plateau"
    assert n[3]["executed"] is False and n[3]["new_bits"] is None
    s = lineage_summary(lin)
    assert s == {"entries": 4, "roots": 1, "executed": 3, "retired": 1,
                 "depth_max": 2, "best_fitness": 100.0}


def test_margin_tightened_semantics():
    lin = build_lineage(_J)
    n = lin["nodes"]
    assert margin_tightened(n[0], n) is False  # uncontested
    assert margin_tightened(n[1], n) is True  # parent uncontested
    assert margin_tightened(n[2], n) is True  # 1 < 2, strictly tighter
    equal = dict(n[2], min_quorum_slack=2)
    assert margin_tightened(equal, n) is False  # equal is not tighter


def test_op_attribution_sums_match_feedback_totals_exactly():
    """The acceptance cross-check: per-op columns sum back to totals
    computed independently from the raw feedback events — exactly."""
    lin = build_lineage(_J)
    att = op_attribution(lin)
    fb = [e for e in _J if e["event"] == "feedback"]
    assert att["totals"]["campaigns"] == len(fb)
    assert att["totals"]["new_bits"] == sum(e["new_bits"] for e in fb)
    assert att["totals"]["violations"] == sum(e["violations"] for e in fb)
    assert att["totals"]["effective"] == sum(
        sum(e["effective"].values()) for e in fb
    )
    assert att["totals"]["fitness"] == sum(e["fitness"] for e in fb)
    # Exact column sums via the Fraction ledger — no rounding drift.
    for col, total in att["_exact_totals"].items():
        assert sum(v[col] for v in att["_exact"].values()) == total
    # Equal split: entry 1's feedback halves across its two ops.
    assert att["ops"]["add-partition"]["new_bits"] == 15
    assert att["ops"]["add-skew"]["new_bits"] == 15
    assert att["ops"]["ballot-pressure"]["new_bits"] == 7
    assert att["ops"]["root"]["campaigns"] == 1
    # The unexecuted re-parented entry contributes nothing.
    assert "add-delay" not in att["ops"]


def test_lineage_renders():
    lin = build_lineage(_J)
    tree = render_tree(lin)
    assert "#0 seed=5 ops=root" in tree
    assert "[retired: plateau]" in tree
    assert "(pending)" in tree
    table = render_op_table(op_attribution(lin))
    assert table.splitlines()[0].startswith("op")
    assert "TOTAL" in table.splitlines()[-1]
    assert "add-skew" in table


# -- sampled fleet recovery (in-process, jax) -----------------------------

from paxos_tpu.fleet.coordinator import plan_records  # noqa: E402
from paxos_tpu.fleet.queue import CampaignQueue  # noqa: E402
from paxos_tpu.fleet.worker import WorkerPreempted, run_record  # noqa: E402

_SOAK_KW = dict(
    config="config2", n_inst=64, fault=[], seed=0, records=2,
    seeds_per_record=2, ticks_per_seed=32, chunk=16, coverage_words=64,
)


def _run_all_sampled(queue, records, preempt_first=None):
    """Drain a queue in-process with per-worker samplers attached (the
    test_fleet _run_all pattern + the observatory), returning the merged
    series over every worker journal written."""
    for rec in records:
        queue.enqueue(rec)
    fhs, samplers = {}, {}

    def sampler_for(w):
        if w not in samplers:
            fhs[w] = open(queue.series_path(w), "a")
            samplers[w] = SeriesSampler(fhs[w], w, every=1)
        return samplers[w]

    preempted = False
    wid = "w0"
    try:
        while True:
            claim = queue.claim(wid, now=0.0, lease_s=10.0)
            if claim is None:
                break
            rec_id, record = claim
            if preempt_first is not None and not preempted:
                preempted = True
                with pytest.raises(WorkerPreempted):
                    run_record(queue, rec_id, record, wid,
                               stop_after_seeds=preempt_first,
                               sampler=sampler_for(wid))
                assert queue.reclaim_expired(now=1e9) == [rec_id]
                wid = "w1"  # the replacement claims it next pass
                continue
            res = run_record(queue, rec_id, record, wid,
                             sampler=sampler_for(wid))
            queue.complete(rec_id, wid, res)
    finally:
        for fh in fhs.values():
            fh.close()
    streams = [
        load_series(p)["rows"]
        for p in sorted((queue.root / "series").glob("*.jsonl"))
    ]
    return merge_series(streams)


def test_soak_recovery_series_matches_uninterrupted(tmp_path):
    """A soak record preempted after one durable (sample, progress)
    pair and resumed by another worker yields a merged time-series
    byte-identical to the uninterrupted baseline's: the resumed record
    skips already-sampled clocks and its cumulative gauges pick up from
    the durable progress."""
    records = plan_records(mode="soak", **_SOAK_KW)
    base = _run_all_sampled(CampaignQueue(tmp_path / "base"), records)
    rec = _run_all_sampled(CampaignQueue(tmp_path / "rec"), records,
                           preempt_first=1)
    assert base["samples"] == 4  # 2 records x 2 seeds, every=1
    assert rec["digest"] == base["digest"]
    assert rec["lines"] == base["lines"]
    assert all(w["seq_monotone"] for w in rec["workers"].values())


def test_fuzz_recovery_series_matches_uninterrupted(tmp_path):
    """Fuzz records replay atomically: the replacement re-emits the dead
    worker's clocks with identical deterministic gauges, merge dedup
    collapses them, and the digest matches the clean baseline."""
    records = plan_records(
        mode="fuzz", config="config2", n_inst=64, fault=[], seed=0,
        records=2, seeds_per_record=0, ticks_per_seed=32, chunk=16,
        coverage_words=64, seed_stride=100, rng_seed=0,
        campaigns_per_record=3,
    )
    base = _run_all_sampled(CampaignQueue(tmp_path / "base"), records)
    rec = _run_all_sampled(CampaignQueue(tmp_path / "rec"), records,
                           preempt_first=2)
    assert base["samples"] == 6  # 2 records x 3 campaigns
    assert rec["dedup"] == 2  # the preempted attempt's durable clocks
    assert rec["digest"] == base["digest"]


def test_work_loop_sampling_off_writes_no_journal(tmp_path):
    """Default-off-is-free: sample_every=0 opens no file and the series
    directory stays empty; turning it on writes the journal."""
    from paxos_tpu.fleet.worker import work_loop

    records = plan_records(mode="soak", **dict(_SOAK_KW, records=1,
                                               seeds_per_record=1))
    q = CampaignQueue(tmp_path / "off")
    for r in records:
        q.enqueue(r)
    stats = work_loop(tmp_path / "off", "w0", lease_s=30.0, poll_s=0.05)
    assert stats["records_done"] == 1
    assert "samples" not in stats
    assert list((tmp_path / "off" / "series").glob("*")) == []

    q2 = CampaignQueue(tmp_path / "on")
    for r in records:
        q2.enqueue(r)
    stats = work_loop(tmp_path / "on", "w0", lease_s=30.0, poll_s=0.05,
                      sample_every=1)
    assert stats["samples"] == 1
    rows = load_series(q2.series_path("w0"))["rows"]
    assert len(rows) == 1 and rows[0]["worker"] == "w0"


def test_workload_record_samples_slo_gauge(tmp_path):
    """A workload-on fleet record rides its per-seed campaign p99 into
    the sampled series (the slo_degradation detector's input); a
    workload-off record's rows carry no slo_* gauges at all."""
    from paxos_tpu.fleet.worker import work_loop

    records = plan_records(mode="soak", **dict(
        _SOAK_KW, records=1, seeds_per_record=2,
        workload="bursty", workload_rate=0.3, slo_p99=64))
    q = CampaignQueue(tmp_path / "wl")
    for r in records:
        q.enqueue(r)
    stats = work_loop(tmp_path / "wl", "w0", lease_s=30.0, poll_s=0.05,
                      sample_every=1)
    assert stats["records_done"] == 1
    rows = load_series(q.series_path("w0"))["rows"]
    assert len(rows) == 2
    served = [r["gauges"] for r in rows if "slo_p99_ticks" in r["gauges"]]
    assert served, "no sampled row carried the SLO gauge"
    for g in served:
        assert g["slo_p99_ticks"] >= 1 and g["slo_queue_depth"] >= 0

    off = plan_records(mode="soak", **dict(_SOAK_KW, records=1,
                                           seeds_per_record=1))
    q2 = CampaignQueue(tmp_path / "off")
    for r in off:
        q2.enqueue(r)
    work_loop(tmp_path / "off", "w0", lease_s=30.0, poll_s=0.05,
              sample_every=1)
    for row in load_series(q2.series_path("w0"))["rows"]:
        assert not any(k.startswith("slo_") for k in row["gauges"])


def test_planted_stall_fixture_exits_2_via_stats(tmp_path):
    """The satellite wiring end to end: a hand-planted fleet root with a
    flat-coverage worker drives `stats --fleet-root --series-gate` to
    exit 2 naming the worker (the tier-1 smoke's negative leg uses the
    same fixture shape)."""
    root = tmp_path / "fake"
    (root / "series").mkdir(parents=True)
    with open(root / "series" / "w0.jsonl", "a") as fh:
        for clock in range(6):
            append_event(fh, sample_row(
                worker="w0", record="c00000", attempt=0, seq=clock,
                clock=clock, gauges={"worker_union_bits": 64,
                                     "worker_seeds": clock + 1},
            ))
    rows = load_series(root / "series" / "w0.jsonl")["rows"]
    gate = compare_series(rows)
    assert not gate["ok"]
    assert gate["findings"][0]["kind"] == "discovery_stall"
    assert gate["findings"][0]["worker"] == "w0"

    from paxos_tpu.harness.cli import main

    rc = main(["--platform", "cpu", "stats", "--fleet-root", str(root),
               "--series-gate"])
    assert rc == 2


def test_stats_fleet_root_renders_last_samples(tmp_path, capsys):
    root = tmp_path / "fleet"
    (root / "series").mkdir(parents=True)
    for w, bits in (("w0", 10), ("w1", 20)):
        with open(root / "series" / f"{w}.jsonl", "a") as fh:
            for clock in range(2):
                append_event(fh, sample_row(
                    worker=w, record="c00000", attempt=0, seq=clock,
                    clock=clock,
                    gauges={"worker_union_bits": bits + clock,
                            "worker_seeds": clock + 1,
                            "worker_rounds": 100 * (clock + 1),
                            "worker_violations": 0},
                ))
    from paxos_tpu.harness.cli import main

    assert main(["--platform", "cpu", "stats",
                 "--fleet-root", str(root)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["metric"] == "fleet_series"
    assert out["fleet"]["workers"] == 2
    assert out["fleet"]["seeds"] == 4 and out["fleet"]["rounds"] == 400
    assert out["workers"]["w0"]["clock"] == 1
    assert out["workers"]["w1"]["gauges"]["worker_union_bits"] == 21
