"""Network partitions: bipartition windows stall cross-cut links.

The partition is sampled per instance in the fault plan (window + side
assignment); ``FaultPlan.link_ok`` gates both request selection and reply
delivery, so cross-cut messages stall in flight (nothing is lost) until the
window closes.  Safety must hold during the partition, liveness must resume
after it heals.
"""

import jax
import jax.numpy as jnp

from paxos_tpu.core.messages import PREPARE, PROMISE
from paxos_tpu.core.state import PaxosState
from paxos_tpu.faults.injector import NEVER, FaultConfig, FaultPlan
from paxos_tpu.harness.config import SimConfig, config_partition
from paxos_tpu.harness.run import base_key, run, run_chunk
from paxos_tpu.protocols.paxos import paxos_step


def test_partition_safe_and_live_after_heal():
    report = run(
        config_partition(n_inst=8192, seed=4),
        until_all_chosen=True,
        max_ticks=1024,
    )
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["proposer_disagree"] == 0
    # Windows end by tick 70; decisions must complete well within budget.
    assert report["chosen_frac"] == 1.0


def test_cross_cut_links_stall_and_heal():
    """Deterministic: proposer cut from acceptors 1,2 reaches only acceptor 0
    while the partition is active, and all three after it heals."""
    n_inst, n_acc = 4, 3
    cfg = FaultConfig(p_part=1.0, timeout=1000)  # no retries: pure delivery
    state = PaxosState.init(n_inst, 1, n_acc)
    plan = FaultPlan.none(n_inst, n_acc, 1)
    plan = plan.replace(
        part_start=jnp.zeros((n_inst,), jnp.int32),
        part_end=jnp.full((n_inst,), 8, jnp.int32),  # heals at tick 8
        # proposer on side True together with acceptor 0 only
        pside=jnp.ones((1, n_inst), jnp.bool_),
        aside=jnp.zeros((n_acc, n_inst), jnp.bool_).at[0].set(True),
    )
    key = jax.random.PRNGKey(0)

    state = run_chunk(state, key, plan, cfg, 6, paxos_step)
    heard = jax.device_get(state.proposer.heard[0])  # (I,) bitmask
    assert set(heard.tolist()) <= {0, 1}  # only acceptor 0's promise, if any
    assert bool((jax.device_get(state.requests.present[0, 0, 1:]) == True).all()), (
        "cross-cut PREPAREs must still be in flight, not lost"
    )

    state = run_chunk(state, key, plan, cfg, 30, paxos_step)
    heard = jax.device_get(state.proposer.heard[0])
    assert (heard == 0b111).all(), "after healing every acceptor must answer"


def _asym_plan(n_inst, n_acc, part_dir):
    """Every link crosses the cut; window [0, 8); one-way per ``part_dir``."""
    plan = FaultPlan.none(n_inst, n_acc, 1)
    return plan.replace(
        part_start=jnp.zeros((n_inst,), jnp.int32),
        part_end=jnp.full((n_inst,), 8, jnp.int32),
        pside=jnp.ones((1, n_inst), jnp.bool_),
        aside=jnp.zeros((n_acc, n_inst), jnp.bool_),
        part_dir=jnp.full((n_inst,), part_dir, jnp.int32),
    )


def test_asymmetric_cut_requests_stall_and_heal():
    """part_dir=1 — requests P->A cut, replies spared: PREPAREs must STALL
    in flight (not be lost) for the whole window, then deliver on heal."""
    n_inst, n_acc = 4, 3
    cfg = FaultConfig(p_part=1.0, p_asym=1.0, timeout=1000)
    state = PaxosState.init(n_inst, 1, n_acc)
    plan = _asym_plan(n_inst, n_acc, part_dir=1)
    key = jax.random.PRNGKey(0)

    state = run_chunk(state, key, plan, cfg, 6, paxos_step)
    assert not jax.device_get(state.proposer.heard).any(), (
        "no acceptor may receive a request across a one-way request cut"
    )
    assert bool(jax.device_get(state.requests.present[PREPARE, 0]).all()), (
        "cut PREPAREs must still be in flight, not lost"
    )

    state = run_chunk(state, key, plan, cfg, 30, paxos_step)
    assert (jax.device_get(state.proposer.heard[0]) == 0b111).all(), (
        "after healing the preserved PREPAREs must deliver and be answered"
    )


def test_asymmetric_cut_replies_stall_and_heal():
    """part_dir=2 — replies A->P cut, requests spared: acceptors promise,
    but the PROMISEs must STALL in flight until the window closes."""
    n_inst, n_acc = 4, 3
    cfg = FaultConfig(p_part=1.0, p_asym=1.0, timeout=1000)
    state = PaxosState.init(n_inst, 1, n_acc)
    plan = _asym_plan(n_inst, n_acc, part_dir=2)
    key = jax.random.PRNGKey(0)

    state = run_chunk(state, key, plan, cfg, 6, paxos_step)
    assert not jax.device_get(state.proposer.heard).any(), (
        "replies may not cross a one-way reply cut"
    )
    # Requests DID flow: acceptors processed the PREPAREs and promised...
    assert bool((jax.device_get(state.acceptor.promised) > 0).all())
    # ...and the resulting PROMISEs are parked in flight, preserved.
    assert bool(jax.device_get(state.replies.present[PROMISE, 0]).all()), (
        "cut PROMISEs must still be in flight, not lost"
    )

    state = run_chunk(state, key, plan, cfg, 30, paxos_step)
    assert (jax.device_get(state.proposer.heard[0]) == 0b111).all(), (
        "after healing the preserved PROMISEs must deliver"
    )


def test_link_ok_shape_and_default():
    plan = FaultPlan.none(16, 5, 2)
    ok = plan.link_ok(jnp.int32(3))
    assert ok.shape == (2, 5, 16)
    assert bool(ok.all())  # no partitions configured => all links up
