"""Performance plane (obs.perf): gauges, bench provenance, regression gate.

Contracts under test:

1. **Pure decode**: ``perf_summary``/``perf_counter_tracks`` derive
   throughput, occupancy, compile-vs-steady split, and chunk-latency
   percentiles from a span stream recorded under a FAKE injected clock —
   fully deterministic, no wall clock in the assertions.
2. **Default-off is free**: a ``--perf`` run's report equals the bare
   run's report minus the ``perf`` block (the plane is host-side only and
   cannot perturb the campaign).
3. **Bench provenance**: reworked ``bench.py`` rows validate against
   ``BENCH_ROW_SCHEMA`` (per-run samples, explicit warm-up vs measured
   counts, layout version, fingerprint) and ``compare_benches`` passes a
   self-comparison, flags a planted regression, and widens its band for
   noisy baselines (the noise-aware tolerance model).
4. **One registry, all planes**: telemetry + coverage + exposure + perf
   gauges coexist in a single registry export with no sample-line
   collisions, and the combined overhead of running every plane at once
   stays within a stated factor of the bare run.
"""

import json
import time

import pytest

from paxos_tpu.harness.cli import main
from paxos_tpu.harness.metrics import MetricsRegistry
from paxos_tpu.obs import perf
from paxos_tpu.obs.host_spans import HostSpanRecorder


# ---------------------------------------------------------------- fake clock


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _recorded_loop(n_dispatches=6, compile_s=0.5, dispatch_s=0.01,
                   probe_s=0.09, gap_s=0.002, ticks=128, groups=2):
    """A synthetic pipelined loop: slow first dispatch, steady tail."""
    clock = FakeClock()
    rec = HostSpanRecorder(clock)
    tick = 0
    for i in range(n_dispatches):
        with rec.span("dispatch", tick_start=tick, ticks=ticks,
                      groups=groups):
            clock.advance(compile_s if i == 0 else dispatch_s)
        tick += ticks
        with rec.span("probe", tick=tick):
            clock.advance(probe_s)
        clock.advance(gap_s)
    with rec.span("report"):
        clock.advance(0.05)
    return rec


def test_perf_summary_fake_clock():
    rec = _recorded_loop()
    s = perf.perf_summary(rec, n_inst=1000, window=4)
    assert s["dispatches"] == 6
    assert s["chunks"] == 12
    assert s["rounds_total"] == 6 * 128 * 1000
    assert s["compile_s"] == pytest.approx(0.5)
    # busy = all dispatch/probe/report time; gaps are host bookkeeping
    assert 0.0 <= s["occupancy"] <= 1.0
    assert s["occupancy"] > 0.95  # gaps are tiny in the synthetic loop
    # steady-state excludes the compile-heavy first dispatch
    assert s["rounds_per_sec_steady"] > s["rounds_per_sec"]
    assert s["window_dispatches"] == 4
    lat = s["chunk_latency_us"]
    assert lat["samples"] == 12
    assert lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    # the compile dispatch dominates the tail percentile
    assert lat["max"] > 5 * lat["p50"]


def test_perf_summary_empty_and_single():
    assert perf.perf_summary([], 10) == {"dispatches": 0, "rounds_total": 0}
    clock = FakeClock()
    rec = HostSpanRecorder(clock)
    with rec.span("dispatch", tick_start=0, ticks=64, groups=1):
        clock.advance(0.25)
    s = perf.perf_summary(rec, n_inst=100)
    assert s["dispatches"] == 1
    assert "rounds_per_sec_steady" not in s  # needs >= 2 dispatches
    assert s["rounds_per_sec"] == pytest.approx(100 * 64 / 0.25)
    assert s["occupancy"] == 1.0


def test_perf_counter_tracks_shape():
    rec = _recorded_loop()
    tracks = perf.perf_counter_tracks(rec, n_inst=1000)
    assert set(tracks) == {"host_rounds_per_sec", "host_occupancy_pct"}
    for name, series in tracks.items():
        assert len(series) == 6
        ticks = [t for t, _ in series]
        assert ticks == sorted(ticks)
        assert ticks[-1] == 6 * 128  # stamped at dispatch END ticks
    for _, pct in tracks["host_occupancy_pct"]:
        assert 0.0 <= pct <= 100.0
    assert perf.perf_counter_tracks([], 10) == {}


def test_percentile_nearest_rank():
    vals = list(range(1, 101))
    assert perf.percentile(vals, 0.50) == 50
    assert perf.percentile(vals, 0.95) == 95
    assert perf.percentile(vals, 0.99) == 99
    assert perf.percentile([7], 0.99) == 7
    assert perf.percentile([], 0.5) is None


def test_vmem_and_roofline_gauges():
    g = perf.vmem_gauges(356, 1024)
    assert g["vmem_state_bytes"] == 356 * 1024
    assert 0 < g["vmem_occupancy"] <= 1.0
    assert g["vmem_budget_bytes"] > 0
    assert perf.vmem_gauges(356, None) == {}
    r = perf.roofline_gauges(
        3.7e8, {"alu_per_lane_tick": 5329.0},
        {"vpu_ops_per_sec": 2.35e12},
    )
    assert r["roofline_ceiling_rps"] == pytest.approx(2.35e12 / 5329.0, rel=1e-3)
    assert 0 < r["roofline_occupancy"] < 1.5
    assert perf.roofline_gauges(1.0, {}, {}) == {}
    # r11 census split: codec shifts folded back into the ceiling's ops so
    # alu=5329 and alu=4448+codec=881 describe the same program.
    split = perf.roofline_gauges(
        3.7e8,
        {"alu_per_lane_tick": 4448.0, "codec_alu_per_lane_tick": 881.0},
        {"vpu_ops_per_sec": 2.35e12},
    )
    assert split["roofline_ceiling_rps"] == r["roofline_ceiling_rps"]


# ----------------------------------------------------------- bench provenance


def _fake_row(**over):
    row = {
        "schema": perf.BENCH_ROW_SCHEMA,
        "metric": "quorum-rounds/sec/chip",
        "value": 100.0,
        "unit": "instance-rounds/sec",
        "samples": [98.0, 100.0, 99.0],
        "median": 99.0,
        "min": 98.0,
        "stdev": 1.0,
        "warmup_groups": 1,
        "timed_groups": 3,
        "n_instances": 1024,
        "chunk": 64,
        "pipeline_depth": 1,
        "ticks": 256,
        "platform": "cpu",
        "engine": "xla",
        "protocol": "paxos",
        "ops_per_lane_tick": 4426.1,
        "layout_version": "paxos-packed-v3",
        "config_fingerprint": "deadbeef00000000",
        "case": "case-a",
    }
    row.update(over)
    return row


def test_validate_bench_row():
    assert perf.validate_bench_row(_fake_row()) == []
    assert perf.validate_bench_row("nope")
    errs = perf.validate_bench_row(_fake_row(samples=[]))
    assert any("samples" in e for e in errs)
    errs = perf.validate_bench_row({k: v for k, v in _fake_row().items()
                                    if k != "layout_version"})
    assert any("layout_version" in e for e in errs)
    errs = perf.validate_bench_row(_fake_row(schema="bogus-v9"))
    assert any("schema" in e for e in errs)


def test_validate_bench_row_pins_both_schema_versions():
    """v2 is current; v1 rows (committed r5-r10 artifacts) stay valid."""
    assert perf.BENCH_ROW_SCHEMA == "paxos-tpu-bench-row-v2"
    assert perf.BENCH_ROW_SCHEMAS == (
        "paxos-tpu-bench-row-v1", "paxos-tpu-bench-row-v2",
    )
    # A v1 row has no ops_per_lane_tick — the legacy compat path accepts it.
    v1 = _fake_row(schema="paxos-tpu-bench-row-v1")
    del v1["ops_per_lane_tick"]
    assert perf.validate_bench_row(v1) == []
    # A v2 row must carry a positive census op count.
    v2 = _fake_row()
    assert perf.validate_bench_row(v2) == []
    del v2["ops_per_lane_tick"]
    errs = perf.validate_bench_row(v2)
    assert any("ops_per_lane_tick" in e for e in errs)
    errs = perf.validate_bench_row(_fake_row(ops_per_lane_tick=-1.0))
    assert any("positive" in e for e in errs)
    errs = perf.validate_bench_row(_fake_row(ops_per_lane_tick=True))
    assert any("ops_per_lane_tick" in e for e in errs)


def test_compare_benches_self_and_regression():
    rows = [_fake_row(), _fake_row(case="case-b", engine="fused")]
    ok = perf.compare_benches(rows, rows)
    assert ok["ok"] and ok["compared"] == 2 and not ok["regressions"]
    # planted regression: 50% drop >> 10% tolerance
    slow = [dict(rows[0], samples=[49.0, 50.0, 49.5], value=50.0), rows[1]]
    bad = perf.compare_benches(rows, slow)
    assert not bad["ok"]
    assert [r["case"] for r in bad["regressions"]] == ["case-a"]
    assert bad["regressions"][0]["ratio"] == pytest.approx(50 / 99, rel=1e-3)


def test_compare_benches_noise_widens_band():
    # Baseline CV ~20% -> allowed drop 3*0.2 = 60%: a 50% drop passes.
    noisy = [_fake_row(samples=[60.0, 100.0, 140.0], median=100.0)]
    slow = [_fake_row(samples=[50.0], value=50.0)]
    res = perf.compare_benches(noisy, slow)
    assert res["ok"], res
    assert res["rows"][0]["allowed_drop"] > 0.5
    # Quiet baseline: same 50% drop regresses.
    quiet = [_fake_row()]
    assert not perf.compare_benches(quiet, slow)["ok"]


def test_compare_benches_no_overlap_is_not_ok():
    a = [_fake_row(case="only-a")]
    b = [_fake_row(case="only-b")]
    res = perf.compare_benches(a, b)
    assert res["compared"] == 0 and not res["ok"]
    assert res["fresh_only"] and res["baseline_only"]


def test_compare_benches_legacy_rows():
    """Pre-schema BENCH_SWEEP.json rows (throughput_runs) still compare."""
    legacy = {"case": "old", "engine": "xla", "platform": "tpu",
              "value": 100.0, "throughput_runs": [99.0, 100.0, 98.0]}
    fresh = _fake_row(case="old", platform="tpu")
    res = perf.compare_benches([legacy], [fresh])
    assert res["compared"] == 1 and res["ok"]


def test_bench_case_schema_and_warmup_split():
    """A real (tiny) bench_case run emits a schema-valid provenance row."""
    from bench import bench_case
    from paxos_tpu.harness.config import config1_no_faults

    row = bench_case(config1_no_faults(n_inst=64), "xla", chunk=16,
                     timed_chunks=2, repeats=2, warmup_groups=1)
    assert perf.validate_bench_row(row) == []
    assert row["warmup_groups"] == 1 and len(row["warmup_runs"]) == 1
    assert row["timed_groups"] == 2 and len(row["samples"]) == 2
    assert row["layout_version"] == "paxos-packed-v4"
    assert row["ops_per_lane_tick"] > 0
    assert row["perf"]["dispatches"] >= 2
    assert 0.0 <= row["perf"]["occupancy"] <= 1.0
    # warm-up (compile) must not leak into the measured samples
    assert row["perf"]["compile_s"] > 0


# ------------------------------------------------------------------ CLI paths


def _run_cli(tmp_path, capsys, *extra):
    log = tmp_path / f"m{abs(hash(extra)) % 997}.jsonl"
    rc = main([
        "run", "--config", "config1", "--n-inst", "128", "--ticks", "64",
        "--chunk", "32", "--pipeline-depth", "2", "--log", str(log), *extra,
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    return report, log


def test_cli_run_perf_gauges(tmp_path, capsys):
    report, log = _run_cli(tmp_path, capsys, "--perf")
    p = report["perf"]
    assert p["dispatches"] >= 1
    assert 0.0 <= p["occupancy"] <= 1.0
    assert p["rounds_total"] == 128 * 64
    assert {"p50", "p95", "p99"} <= set(p["chunk_latency_us"])
    # gauges land in the JSONL metrics record too
    recs = [json.loads(l) for l in log.read_text().splitlines()]
    snap = [r for r in recs if r["event"] == "metrics"][-1]
    assert "perf_occupancy" in snap["gauges"]
    assert "perf_rounds_per_sec" in snap["gauges"]


def test_cli_run_perf_default_off_report_identical(tmp_path, capsys):
    """Default-off guarantee at the report level: --perf only ADDS a key."""
    bare, _ = _run_cli(tmp_path, capsys)
    perf_on, _ = _run_cli(tmp_path, capsys, "--perf")
    assert "perf" not in bare
    perf_on.pop("perf")
    assert perf_on == bare


def test_cli_stats_perf_prometheus_and_follow(tmp_path, capsys):
    _, log = _run_cli(tmp_path, capsys, "--perf")
    rc = main(["stats", str(log), "--prometheus"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "paxos_tpu_perf_occupancy" in text
    assert "paxos_tpu_perf_chunk_latency_us{quantile=\"p95\"}" in text
    # --follow stops on the final record already present in the stream
    rc = main(["stats", str(log), "--follow", "--interval", "0.05"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["perf"]["dispatches"] >= 1


def test_cli_stats_follow_max_renders_without_final(tmp_path, capsys):
    log = tmp_path / "partial.jsonl"
    log.write_text(json.dumps({"event": "start"}) + "\n"
                   + json.dumps({"event": "seed", "seed": 0, "wall_s": 1.0,
                                 "rounds": 100, "rounds_per_sec": 100.0})
                   + "\n")
    rc = main(["stats", str(log), "--follow", "--interval", "0.05",
               "--max-renders", "2"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 2  # rendered exactly max-renders times
    assert json.loads(lines[-1])["last_seed"]["rounds_per_sec"] == 100.0


def test_cli_bench_compare(tmp_path, capsys):
    base = tmp_path / "base.json"
    base.write_text(json.dumps([_fake_row()]))
    # self-comparison: exit 0
    assert main(["bench-compare", "--baseline", str(base)]) == 0
    capsys.readouterr()
    # planted >= tolerance regression: exit 2
    slow = tmp_path / "slow.json"
    slow.write_text(json.dumps(
        [_fake_row(samples=[60.0, 61.0], median=60.5, min=60.0, value=61.0)]
    ))
    rc = main(["bench-compare", "--baseline", str(base),
               "--fresh", str(slow)])
    assert rc == 2
    out = capsys.readouterr()
    assert "REGRESSION" in out.err
    # missing artifact: exit 1
    assert main(["bench-compare", "--baseline",
                 str(tmp_path / "absent.json")]) == 1
    capsys.readouterr()
    # schema-invalid fresh row: exit 1
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([_fake_row(samples=[])]))
    assert main(["bench-compare", "--baseline", str(base),
                 "--fresh", str(bad)]) == 1
    capsys.readouterr()


# --------------------------------------------------------- soak per-seed trend


def test_soak_per_seed_throughput_trend():
    from paxos_tpu.harness.config import config1_no_faults
    from paxos_tpu.harness.soak import soak

    streamed = []
    report = soak(
        config1_no_faults(n_inst=64),
        target_rounds=3 * 64 * 32,
        ticks_per_seed=32,
        chunk=16,
        engine="xla",
        on_seed=streamed.append,
    )
    assert report["seeds"] == 3
    assert len(report["per_seed"]) == 3
    assert streamed == report["per_seed"]
    for rec in report["per_seed"]:
        assert rec["rounds"] == 64 * 32
        assert rec["rounds_per_sec"] > 0
        assert rec["wall_s"] >= 0
    assert [r["seed"] for r in report["per_seed"]] == [0, 1, 2]


# ------------------------------------------- all planes in one registry/budget


def test_all_planes_one_registry_no_collisions():
    """Telemetry + coverage + exposure + spans + perf share one registry."""
    registry = MetricsRegistry()
    registry.ingest({"counters": {"decide": 7}, "hist": [1, 2, 3],
                     "hist_ticks_per_bin": 4})
    registry.ingest_coverage({"bits_set": 10, "bits_total": 64,
                              "saturation": 0.15, "est_states": 12})
    registry.ingest_exposure(
        {"classes": {"drop": {"injected": 5, "effective": 3,
                              "lanes_exposed": 2}}},
        lit={"drop": True},
    )
    registry.ingest_span_aggregates({"round_latency_p50": 3,
                                     "rounds_total": 9})
    registry.ingest_perf(perf.perf_summary(_recorded_loop(), 1000))
    text = registry.to_prometheus()
    sample_lines = [l for l in text.splitlines()
                    if l and not l.startswith("#")]
    names = [l.split(" ")[0] for l in sample_lines]
    assert len(names) == len(set(names)), "label collision in shared registry"
    for expected in ("paxos_tpu_events_total", "paxos_tpu_coverage_bits_set",
                     "paxos_tpu_exposure_effective",
                     "paxos_tpu_round_latency_ticks",
                     "paxos_tpu_perf_occupancy",
                     "paxos_tpu_perf_rounds_per_sec"):
        assert any(n.startswith(expected) for n in names), expected


@pytest.mark.slow
def test_all_planes_on_overhead_budget(tmp_path, capsys):
    """Stated budget: every observability plane on at once stays within
    15x of the bare run, steady-state.  Each variant runs once to compile
    (the planes add device state, so their computation is distinct and
    compiles separately) and the SECOND run is timed — the overhead being
    pinned is the per-campaign cost of readbacks + host decode, not the
    one-time compile."""
    def timed(*extra):
        _run_cli(tmp_path, capsys, *extra)  # warm: compile both variants
        t0 = time.perf_counter()
        _run_cli(tmp_path, capsys, *extra)
        return time.perf_counter() - t0

    bare = timed()
    allon = timed("--telemetry", "--coverage", "--coverage-words", "8",
                  "--exposure", "--perf")
    assert allon < 15 * bare, f"all-planes overhead {allon / bare:.1f}x"
