"""Perf-regression gate (SURVEY.md §5.2.5; round-1 verdict "Missing #1").

Each (config x engine) case of the sweep bench must stay within a band
(>= 0.7x) of its recorded TPU value in ``BENCH_SWEEP.json`` (produced by
``python bench.py --sweep --record BENCH_SWEEP.json`` on a v5e-1).  A
silent 10x regression — e.g. a layout revert undoing the instance-minor
win (BASELINE.md row "before instance-minor layout refactor": 35x slower)
— fails here long before it eats the 32x cushion over the north star.

The CPU rig skips: interpreter-mode timings say nothing about the chip.
Run with ``PAXOS_TPU_REAL=1 python -m pytest tests/test_perf_regression.py``
on a machine with a real TPU (the conftest otherwise forces the CPU mesh).
"""

import json
import pathlib
import sys

import jax
import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

ARTIFACT = ROOT / "BENCH_SWEEP.json"
BAND = 0.7  # min acceptable fraction of the recorded throughput

pytestmark = pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="perf gate needs a real TPU (set PAXOS_TPU_REAL=1 to disable the CPU rig)",
)


def _recorded():
    if not ARTIFACT.exists():
        return []
    return [c for c in json.loads(ARTIFACT.read_text()) if c["platform"] == "tpu"]


@pytest.fixture(scope="module", autouse=True)
def _bench_prng():
    # Match the conditions the artifact was recorded under (bench.py main);
    # restore afterwards so later modules keep the default stream impl.
    prev = jax.config.jax_default_prng_impl
    jax.config.update("jax_default_prng_impl", "rbg")
    yield
    jax.config.update("jax_default_prng_impl", prev)


@pytest.mark.parametrize(
    "case", _recorded(), ids=lambda c: f"{c['case']}-{c['engine']}"
)
def test_perf_band(case):
    from bench import _configs, bench_case

    table = {
        (name, eng): (cfg, chunk, depth)
        for name, cfg, eng, chunk, depth in _configs("tpu")
    }
    cfg, chunk, depth = table[(case["case"], case["engine"])]
    # The recorded number must refer to this exact config, else the band
    # compares apples to oranges (a config change requires re-recording).
    assert cfg.fingerprint() == case["config_fingerprint"], (
        f"{case['case']}: config changed since BENCH_SWEEP.json was recorded; "
        "re-run `python bench.py --sweep --record BENCH_SWEEP.json`"
    )
    # Chunk must match the recording EXACTLY — chunk moves the measured
    # value by ~17% between 64 and 1024 (dispatch amortization), so a
    # mismatched chunk quietly eats the 0.7 band cushion.  The artifact
    # records chunk directly; ticks == timed_chunks * chunk is the
    # equivalent exact check for the default timed_chunks=4.
    assert case.get("chunk", case["ticks"] // 4) == chunk, (
        f"{case['case']}: bench chunk {chunk} != recorded "
        f"{case.get('chunk', case['ticks'] // 4)}; re-record BENCH_SWEEP.json"
    )
    # Same exactness for the dispatch-pipeline depth: grouping moves the
    # measured value by the very dispatch tax this PR exists to recover
    # (pre-pipeline artifact rows carry no key — those ran serial, depth 1).
    assert case.get("pipeline_depth", 1) == depth, (
        f"{case['case']}: bench pipeline_depth {depth} != recorded "
        f"{case.get('pipeline_depth', 1)}; re-record BENCH_SWEEP.json"
    )
    out = bench_case(cfg, case["engine"], chunk=chunk, pipeline_depth=depth)
    assert out["violations"] == 0
    assert out["value"] >= BAND * case["value"], (
        f"{case['case']} ({case['engine']}): {out['value']:.3e} < "
        f"{BAND} x recorded {case['value']:.3e} — perf regression"
    )


def test_artifact_present():
    """The gate must not pass vacuously because the artifact vanished."""
    assert ARTIFACT.exists(), "BENCH_SWEEP.json missing — perf gate is vacuous"
    assert len(_recorded()) >= 8, "expected >= 8 TPU cases (4 protocols x 2 engines)"


def test_fused_unaligned_count_on_hardware():
    """VERDICT r3 weak#4: `fit_block`'s full-array escape hatch for counts
    with no 128-aligned divisor (n_inst=1000: largest power-of-two divisor
    8) was hardware-verified only anecdotally — a Mosaic behavior change
    would regress the spec's literal 100k/1M counts silently.  This gated
    smoke compiles+runs the compiled (non-interpret) kernel at n_inst=1000
    and checks it against the XLA engine's end state."""
    import jax.numpy as jnp

    from paxos_tpu.harness.config import config2_dueling_drop
    from paxos_tpu.harness.run import init_plan, init_state, make_advance

    cfg = config2_dueling_drop(n_inst=1000, seed=9)
    plan = init_plan(cfg)
    state = make_advance(cfg, plan, "fused", interpret=False)(
        init_state(cfg), 64
    )
    assert int(state.tick) == 64
    assert int(state.learner.violations.sum()) == 0
    assert int(state.learner.chosen.sum()) > 0  # the kernel really ran
