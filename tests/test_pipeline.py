"""Dispatch pipeline (PR 3): bit-identity, early exit, depth rules.

The contract of ``harness.pipeline`` is that grouping chunk dispatches is
*invisible to the schedule*: per-tick PRNG streams derive from
``state.tick`` (xla) or the (seed, tick, block) counter (fused), never from
dispatch boundaries, so a pipelined loop at ANY depth must reproduce the
serial loop's final state bit-for-bit.  A digest drift here means the
fuzzing schedules silently changed — the same severity as a gray-knob
default-on drift (tests/test_gray.py).

Three contracts guard the layer:

1. **Bit-identity**: full-state sha256 digests for pipelined (depth 2, 4)
   loops equal the serial loop's on both engines across all four
   protocols, including long-log compaction (where the chunk cadence is
   schedule-relevant and grouping must preserve it *inside* the dispatch).
2. **Early exit**: an ``until_all_chosen`` pipelined run exits within
   ``depth * chunk`` ticks of the serial exit tick and reports identical
   chosen values — the async done-flag probe may only coarsen granularity,
   never change outcomes.
3. **Depth rules**: depth is a host-loop knob (never in fingerprints or
   reports at depth 1), validated at config time, and refused by the CLI
   together with ``--resume`` (checkpoint cadence was recorded serially).
"""

import dataclasses
import hashlib
import json

import jax
import jax.numpy as jnp
import pytest

from paxos_tpu.harness import config as C
from paxos_tpu.harness.pipeline import AsyncSummary, pipelined_run
from paxos_tpu.harness.run import (
    init_plan,
    init_state,
    make_advance,
    make_advance_grouped,
    make_longlog,
    run,
    summarize,
)

TICKS, CHUNK = 48, 16  # depth 4 exercises a partial group (3 chunks left)


def _digest(state) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        h.update(jax.device_get(leaf).tobytes())
    return h.hexdigest()[:16]


def _cfg(protocol: str) -> C.SimConfig:
    if protocol == "paxos":
        return C.config2_dueling_drop(n_inst=64, seed=7)
    if protocol == "multipaxos":
        return C.config3_multipaxos(n_inst=64, seed=7)
    sweep = {c.protocol: c for c in C.config5_sweep(n_inst=64, seed=7)}
    return sweep[protocol]


# Serial references are shared across the depth parametrization — the
# serial chunk loop is the fixed point every depth is measured against.
_serial_cache: dict = {}


def _serial_digest(protocol: str, engine: str) -> str:
    key = (protocol, engine)
    if key not in _serial_cache:
        cfg = _cfg(protocol)
        plan = init_plan(cfg)
        advance = make_advance(cfg, plan, engine)
        state = init_state(cfg)
        for _ in range(TICKS // CHUNK):
            state = advance(state, CHUNK)
        _serial_cache[key] = _digest(state)
    return _serial_cache[key]


@pytest.mark.parametrize("engine", ["xla", "fused"])
@pytest.mark.parametrize(
    "protocol", ["paxos", "multipaxos", "fastpaxos", "raftcore"]
)
@pytest.mark.parametrize("depth", [2, 4])
def test_pipelined_digest_matches_serial(protocol, engine, depth):
    cfg = _cfg(protocol)
    plan = init_plan(cfg)
    advance = make_advance_grouped(cfg, plan, engine)
    state, done, exit_tick = pipelined_run(
        init_state(cfg), advance, budget=TICKS, chunk=CHUNK, depth=depth
    )
    assert done == TICKS and exit_tick is None
    assert _digest(state) == _serial_digest(protocol, engine), (
        f"{protocol}/{engine}: depth-{depth} stream diverged from serial — "
        "dispatch grouping leaked into the schedule"
    )


@pytest.mark.parametrize("engine", ["xla", "fused"])
def test_pipelined_longlog_compaction_cadence(engine):
    """Grouped compact dispatches must compact at every inner chunk
    boundary, exactly like the serial loop — the cadence is
    schedule-relevant (SURVEY.md §6.7), not just a perf knob."""
    cfg = C.config3_long(n_inst=64, seed=2, log_total=24, window=8)
    plan = init_plan(cfg)
    serial = init_state(cfg)
    adv1 = make_advance(cfg, plan, engine, compact=True)
    for _ in range(TICKS // CHUNK):
        serial = adv1(serial, CHUNK)

    advg = make_advance_grouped(cfg, plan, engine, compact=True)
    piped, done, _ = pipelined_run(
        init_state(cfg), advg, budget=TICKS, chunk=CHUNK, depth=4
    )
    assert done == TICKS
    assert _digest(piped) == _digest(serial)
    # The one-device_get composite report agrees with the serial state's.
    r1 = summarize(serial, log_total=cfg.fault.log_total)
    r4 = AsyncSummary(piped, log_total=cfg.fault.log_total).get()
    assert r1 == r4


def test_until_all_chosen_exit_bound():
    """The async done-flag probe runs per dispatch: the pipelined exit may
    overshoot the serial exit tick, but by strictly less than
    depth * chunk, and the chosen values must be identical."""
    cfg = C.config1_no_faults(n_inst=256, seed=3)
    depth, chunk = 4, 8
    r1, s1 = run(cfg, until_all_chosen=True, chunk=chunk, max_ticks=4096,
                 return_state=True)
    r4, s4 = run(cfg, until_all_chosen=True, chunk=chunk, max_ticks=4096,
                 return_state=True, pipeline_depth=depth)
    assert r1["chosen_frac"] == 1.0 and r4["chosen_frac"] == 1.0
    assert r1["ticks"] <= r4["ticks"] < r1["ticks"] + depth * chunk
    assert bool(s1.learner.chosen.all()) and bool(s4.learner.chosen.all())
    assert jnp.array_equal(s1.learner.chosen_val, s4.learner.chosen_val), (
        "overshoot ticks changed chosen values — chosen lanes must be stable"
    )


def test_depth1_report_is_byte_identical():
    """Depth 1 routes through the same module-level jit caches as the
    serial loop and must not even *label* the report — resumed/recorded
    artifacts diff clean against pre-pipeline runs."""
    cfg = C.config2_dueling_drop(n_inst=128, seed=5)
    r_serial = run(cfg, total_ticks=32, chunk=16)
    r_d1 = run(cfg, total_ticks=32, chunk=16, pipeline_depth=1)
    assert r_d1 == r_serial
    assert "pipeline_depth" not in r_d1

    r_d4 = run(cfg, total_ticks=32, chunk=16, pipeline_depth=4)
    assert r_d4.pop("pipeline_depth") == 4
    assert r_d4 == r_serial  # same stream, same report body


def test_depth_is_not_schedule_relevant():
    """pipeline_depth is a host-loop knob: it must never enter the config
    fingerprint (checkpoints, stream ids, and perf-gate lineage all key on
    the fingerprint, and any depth replays any recording)."""
    cfg = C.config2_dueling_drop(n_inst=128, seed=5)
    assert "pipeline_depth" not in [f.name for f in dataclasses.fields(cfg)]
    r_d2 = run(cfg, total_ticks=32, chunk=16, pipeline_depth=2)
    assert r_d2["config_fingerprint"] == cfg.fingerprint()


def test_pipeline_depth_validation():
    for bad in (0, -1, 2.5, "4", True):
        with pytest.raises(ValueError):
            C.validate_pipeline_depth(bad)
    assert C.validate_pipeline_depth(1) == 1
    assert C.validate_pipeline_depth(16) == 16
    with pytest.raises(ValueError):
        run(C.config1_no_faults(n_inst=64), total_ticks=8, chunk=8,
            pipeline_depth=0)


def test_soak_pipelined_tally_matches_serial():
    """The overlap-by-one soak loop (dispatch seed N+1 while seed N
    executes, tally from AsyncSummary) must produce the same tally as the
    serial campaign loop — campaigns are deterministic in (config, seed)."""
    from paxos_tpu.harness.soak import soak

    cfg = C.config2_dueling_drop(n_inst=256, seed=7)
    rounds = 2 * 256 * 32
    r1 = soak(cfg, target_rounds=rounds, ticks_per_seed=32, chunk=16)
    r4 = soak(cfg, target_rounds=rounds, ticks_per_seed=32, chunk=16,
              pipeline_depth=4)
    assert r4.pop("pipeline_depth") == 4
    assert "pipeline_depth" not in r1
    for key in ("seeds", "rounds", "violations", "evictions",
                "evictions_first_pass", "rechecked_seeds", "stuck_lanes",
                "stuck_frac", "decided_frac_mean", "decided_frac_min"):
        assert r1[key] == r4[key], f"soak tally field {key!r} diverged"


def test_soak_pipelined_longlog_tally():
    from paxos_tpu.harness.soak import soak

    cfg = C.config3_long(n_inst=64, seed=2, log_total=24, window=8)
    rounds = 2 * 64 * 64
    kw = dict(target_rounds=rounds, ticks_per_seed=64, chunk=16,
              min_slots_per_lane_tick=1e-4)
    r1 = soak(cfg, **kw)
    r4 = soak(cfg, pipeline_depth=4, **kw)
    assert r4.pop("pipeline_depth") == 4
    for key in ("seeds", "rounds", "violations", "slots_replicated",
                "replication_ok", "slots_per_lane_tick_min"):
        assert r1[key] == r4[key], f"longlog soak field {key!r} diverged"


def test_cli_pipelined_run_and_rules(tmp_path, capsys):
    from paxos_tpu.harness.cli import main

    # A pipelined run completes, labels its report, and logs per dispatch.
    log = tmp_path / "m.jsonl"
    rc = main([
        "run", "--config", "config1", "--n-inst", "256", "--ticks", "32",
        "--chunk", "8", "--pipeline-depth", "4", "--log", str(log),
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["pipeline_depth"] == 4
    assert report["ticks"] == 32
    events = [json.loads(l) for l in log.read_text().splitlines()]
    assert [e["event"] for e in events][0] == "start"
    assert any(e.get("pipelined") for e in events if e["event"] == "chunk")

    # Depth must be a positive integer — rejected at arg-validation time.
    assert main([
        "run", "--config", "config1", "--n-inst", "64", "--ticks", "8",
        "--chunk", "8", "--pipeline-depth", "0",
    ]) == 1
    capsys.readouterr()

    # --resume refuses an explicit depth (same rule as --record): the
    # checkpoint cadence was recorded under the serial per-chunk loop.
    ck = tmp_path / "ck"
    assert main([
        "run", "--config", "config1", "--n-inst", "64", "--ticks", "16",
        "--chunk", "8", "--checkpoint-dir", str(ck),
    ]) == 0
    capsys.readouterr()
    assert main([
        "run", "--resume", str(ck), "--ticks", "16", "--chunk", "8",
        "--pipeline-depth", "2",
    ]) == 1
    err = capsys.readouterr().err
    assert "--pipeline-depth" in err and "--resume" in err
