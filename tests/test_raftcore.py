"""Raft-core: election, commit, the election restriction, leader completeness.

SURVEY.md §5.2: property tests under random fault masks plus hand-built
adversarial states (the known-answer tests that break wrong implementations:
a stale candidate must not win against a majority that holds a committed
entry, and the eventual leader must re-propose that entry, not its own).
"""

import jax.numpy as jnp
import pytest

from paxos_tpu.core.ballot import make_ballot
from paxos_tpu.core.raft_state import CAND, DONE, VALUE_BASE, RaftState
from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.harness.config import SimConfig
from paxos_tpu.harness.run import base_key, init_plan, run, run_chunk
from paxos_tpu.protocols.raftcore import raftcore_step


def raft_cfg(n_inst=1024, n_prop=2, n_acc=5, seed=0, **fault_kw):
    return SimConfig(
        n_inst=n_inst,
        n_prop=n_prop,
        n_acc=n_acc,
        seed=seed,
        protocol="raftcore",
        fault=FaultConfig(**fault_kw),
    )


def test_single_candidate_no_faults():
    """One candidate, clean network: elected then committed within a few ticks."""
    cfg = raft_cfg(n_inst=512, n_prop=1, n_acc=5)
    report, state = run(cfg, until_all_chosen=True, max_ticks=64, return_state=True)
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["chosen_frac"] == 1.0
    assert bool((state.learner.chosen_val == VALUE_BASE).all())
    assert bool((state.proposer.phase == DONE).all())


def test_dueling_candidates_with_drops():
    """Two candidates race elections under loss/idle/hold: agreement holds."""
    cfg = raft_cfg(
        n_inst=2048, n_prop=2, n_acc=5, p_drop=0.1, p_idle=0.2, p_hold=0.2
    )
    report, state = run(
        cfg, until_all_chosen=True, max_ticks=2048, return_state=True
    )
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["chosen_frac"] == 1.0
    assert report["proposer_disagree"] == 0
    vals = state.learner.chosen_val
    assert bool(((vals >= VALUE_BASE) & (vals < VALUE_BASE + 2)).all())


def test_chaos_safety():
    """Drop + dup + idle + hold + voter crashes: zero violations."""
    cfg = raft_cfg(
        n_inst=2048,
        n_prop=2,
        n_acc=5,
        seed=3,
        p_drop=0.1,
        p_dup=0.1,
        p_idle=0.2,
        p_hold=0.2,
        p_crash=0.2,
        crash_max_start=64,
        crash_max_len=32,
    )
    report = run(cfg, total_ticks=512)
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["chosen_frac"] > 0.9


def test_election_restriction_and_leader_completeness():
    """A candidate with a stale log must lose to voters holding a committed
    entry, and the eventually elected leader must re-propose that entry.

    Adversarial hand-built state (SURVEY.md §5.2.3): voters 0-2 (a majority)
    hold entry (b0, 777); the sole candidate starts with an empty log, so
    its first candidacies are denied by the majority (election restriction).
    Denial gossip teaches it the entry; once its term passes b0 it wins and
    MUST commit 777 — committing its own value is the classic
    leader-completeness bug.
    """
    cfg = raft_cfg(n_inst=8, n_prop=1, n_acc=5, timeout=6, backoff_max=2)
    state = RaftState.init(cfg.n_inst, cfg.n_prop, cfg.n_acc)
    b0 = int(make_ballot(3, 0))
    seeded = jnp.zeros((cfg.n_acc, cfg.n_inst), jnp.bool_).at[:3, :].set(True)
    state = state.replace(
        acceptor=state.acceptor.replace(
            voted=jnp.where(seeded, b0, state.acceptor.voted),
            ent_term=jnp.where(seeded, b0, state.acceptor.ent_term),
            ent_val=jnp.where(seeded, 777, state.acceptor.ent_val),
        )
    )
    plan = FaultPlan.none(cfg.n_inst, cfg.n_acc, cfg.n_prop)
    key = base_key(cfg)

    # Early: the stale candidate cannot have been elected yet.
    state = run_chunk(state, key, plan, cfg.fault, 4, raftcore_step)
    assert bool((state.proposer.phase == CAND).all())
    assert not bool(state.learner.chosen.any())

    state = run_chunk(state, key, plan, cfg.fault, 200, raftcore_step)
    assert bool(state.learner.chosen.all())
    assert bool((state.learner.chosen_val == 777).all())
    assert int(state.learner.violations.sum()) == 0


def test_equivocation_lights_up_checker():
    """Double-granting/accepting voters let two leaders commit conflicting
    values — the checker must catch it (config-4 falsifiability)."""
    cfg = raft_cfg(
        n_inst=4096, n_prop=2, n_acc=5, seed=1, p_idle=0.2, p_equiv=0.5
    )
    report = run(cfg, total_ticks=256)
    assert report["violations"] > 0


def test_deterministic_replay():
    cfg = raft_cfg(n_inst=256, n_prop=2, n_acc=5, seed=7, p_drop=0.1, p_idle=0.2)
    r1, s1 = run(cfg, total_ticks=200, return_state=True)
    r2, s2 = run(cfg, total_ticks=200, return_state=True)
    assert r1 == r2
    assert bool(jnp.array_equal(s1.learner.chosen_val, s2.learner.chosen_val))
