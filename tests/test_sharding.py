"""Multi-chip without a cluster: 8 virtual CPU devices (SURVEY.md §5.2.4).

The simulator is deterministic by construction (counter-based PRNG keyed on
(seed, tick)), so sharding the instances axis across a mesh must produce
bit-identical results to the single-device run.  Long-log Multi-Paxos
(window + decided-prefix compaction, SURVEY.md §6.7) is covered here too:
compaction composed over a sharded chunk — both engines — must equal the
unsharded composition lane for lane.
"""

import jax
import jax.numpy as jnp

from paxos_tpu.harness.config import config2_dueling_drop, config3_long
from paxos_tpu.harness.run import (
    base_key,
    get_step_fn,
    init_plan,
    init_state,
    make_advance,
    run_chunk,
)
from paxos_tpu.parallel.mesh import make_mesh, shard_pytree
from paxos_tpu.utils.trees import assert_trees_equal as _assert_trees_equal


def test_eight_device_mesh_matches_single_device():
    assert jax.device_count() >= 8, "conftest must force 8 virtual CPU devices"
    cfg = config2_dueling_drop(n_inst=1024, seed=2)
    step = get_step_fn(cfg.protocol)

    # Single device.
    s1 = run_chunk(init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, 40, step)

    # Sharded over the full 8-device mesh.
    mesh = make_mesh()
    state = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    plan = shard_pytree(init_plan(cfg), mesh, cfg.n_inst)
    s8 = run_chunk(state, base_key(cfg), plan, cfg.fault, 40, step)

    # The state must be sharded across all 8 devices, and bit-identical.
    assert len(s8.acceptor.promised.sharding.device_set) == 8
    for l1, l8 in zip(jax.tree.leaves(s1), jax.tree.leaves(s8)):
        assert jnp.array_equal(l1, jax.device_get(l8)), "sharded run diverged"


def test_sharded_xla_longlog_compact_matches_unsharded():
    """Sharded XLA chunk + decided-prefix compaction == unsharded, lane for
    lane — the engine×sharding×config cell the CLI composes at
    cli.py (run --shard --config config3long --engine xla)."""
    cfg = config3_long(n_inst=64, log_total=12, window=4, seed=3)

    s1 = init_state(cfg)
    adv1 = make_advance(cfg, init_plan(cfg), "xla", compact=True)
    for _ in range(6):
        s1 = adv1(s1, 8)

    mesh = make_mesh()
    s8 = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    adv8 = make_advance(cfg, shard_pytree(init_plan(cfg), mesh, cfg.n_inst),
                        "xla", compact=True)
    for _ in range(6):
        s8 = adv8(s8, 8)

    assert len(s8.acceptor.log.sharding.device_set) == 8
    assert (jax.device_get(s8.base) > 0).any(), "vacuous: nothing compacted"
    _assert_trees_equal(s1, s8, "sharded xla long-log diverged")


def test_sharded_fused_longlog_compact_matches_unsharded():
    """The sharded fused long-log path (the CLI's composition, now owned by
    ``make_advance(mesh=...)``) == the unsharded fused+compact path at the
    same block — covering the mesh branch of the ONE engine dispatch."""
    cfg = config3_long(n_inst=64, log_total=12, window=4, seed=7)
    block = 8  # == local shard size, so global block ids match unsharded

    s1 = init_state(cfg)
    adv1 = make_advance(cfg, init_plan(cfg), "fused", block=block, compact=True)
    for _ in range(6):
        s1 = adv1(s1, 8)

    mesh = make_mesh()
    plan8 = shard_pytree(init_plan(cfg), mesh, cfg.n_inst)
    s8 = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    adv8 = make_advance(
        cfg, plan8, "fused", block=block, compact=True, mesh=mesh
    )
    for _ in range(6):
        s8 = adv8(s8, 8)

    assert len(s8.acceptor.log.sharding.device_set) == 8
    assert (jax.device_get(s8.base) > 0).any(), "vacuous: nothing compacted"
    _assert_trees_equal(s1, s8, "sharded fused long-log diverged")


def test_metrics_reduce_across_shards():
    cfg = config2_dueling_drop(n_inst=1024, seed=4)
    step = get_step_fn(cfg.protocol)
    mesh = make_mesh()
    state = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    plan = shard_pytree(init_plan(cfg), mesh, cfg.n_inst)
    state = run_chunk(state, base_key(cfg), plan, cfg.fault, 60, step)
    from paxos_tpu.harness.run import summarize

    rep = summarize(state)
    assert rep["violations"] == 0
    assert 0.0 <= rep["chosen_frac"] <= 1.0
