"""Multi-chip without a cluster: 8 virtual CPU devices (SURVEY.md §5.2.4).

The simulator is deterministic by construction (counter-based PRNG keyed on
(seed, tick)), so sharding the instances axis across a mesh must produce
bit-identical results to the single-device run.
"""

import jax
import jax.numpy as jnp

from paxos_tpu.harness.config import config2_dueling_drop
from paxos_tpu.harness.run import base_key, get_step_fn, init_plan, init_state, run_chunk
from paxos_tpu.parallel.mesh import make_mesh, shard_pytree


def test_eight_device_mesh_matches_single_device():
    assert jax.device_count() >= 8, "conftest must force 8 virtual CPU devices"
    cfg = config2_dueling_drop(n_inst=1024, seed=2)
    step = get_step_fn(cfg.protocol)

    # Single device.
    s1 = run_chunk(init_state(cfg), base_key(cfg), init_plan(cfg), cfg.fault, 40, step)

    # Sharded over the full 8-device mesh.
    mesh = make_mesh()
    state = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    plan = shard_pytree(init_plan(cfg), mesh, cfg.n_inst)
    s8 = run_chunk(state, base_key(cfg), plan, cfg.fault, 40, step)

    # The state must be sharded across all 8 devices, and bit-identical.
    assert len(s8.acceptor.promised.sharding.device_set) == 8
    for l1, l8 in zip(jax.tree.leaves(s1), jax.tree.leaves(s8)):
        assert jnp.array_equal(l1, jax.device_get(l8)), "sharded run diverged"


def test_metrics_reduce_across_shards():
    cfg = config2_dueling_drop(n_inst=1024, seed=4)
    step = get_step_fn(cfg.protocol)
    mesh = make_mesh()
    state = shard_pytree(init_state(cfg), mesh, cfg.n_inst)
    plan = shard_pytree(init_plan(cfg), mesh, cfg.n_inst)
    state = run_chunk(state, base_key(cfg), plan, cfg.fault, 60, step)
    from paxos_tpu.harness.run import summarize

    rep = summarize(state)
    assert rep["violations"] == 0
    assert 0.0 <= rep["chosen_frac"] <= 1.0
