"""Shrinker: a violating fault plan reduces to a minimal, replayable repro."""

import dataclasses

from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig, config_flex
from paxos_tpu.harness.shrink import replay, shrink


def test_shrink_equivocation_repro():
    """Config-4-style equivocation: the shrinker must isolate one lane, strip
    it to the equivocators actually needed, and the result must replay."""
    cfg = SimConfig(
        n_inst=512, n_prop=2, n_acc=5, seed=5,
        fault=FaultConfig(p_idle=0.2, p_hold=0.2, p_equiv=0.25),
    )
    result = shrink(cfg, max_ticks=192, chunk=32)
    assert result is not None, "equivocation config must violate within budget"
    # Everything that survived is an equivocation atom; at least one remains
    # (removing every fault would also remove the violation).
    assert result.atoms
    assert all(a.startswith("equiv[") for a in result.atoms)
    assert replay(cfg, result)
    # Minimality (chunk granularity): one chunk earlier must NOT reproduce.
    if result.ticks > 32:
        shorter = dataclasses.replace(result, ticks=result.ticks - 32)
        assert not replay(cfg, shorter)


def test_shrink_clean_config_returns_none():
    assert shrink(config_flex(4, 2, n_inst=256, seed=0), max_ticks=96) is None


def test_shrink_longlog_cadence_exact_repro():
    """Long-log configs compact at chunk boundaries, so the compaction
    CADENCE is part of the schedule: the shrinker must wrap its replay
    advance with the same per-chunk compaction (run.make_longlog) and
    record the chunk, and the repro must replay at that recorded chunk."""
    from paxos_tpu.harness.config import config3_long

    cfg = config3_long(n_inst=64, log_total=16, window=4, seed=2)
    cfg = dataclasses.replace(
        cfg, fault=dataclasses.replace(cfg.fault, p_equiv=0.5)
    )
    result = shrink(cfg, max_ticks=128, chunk=64)
    assert result is not None, "equivocating long-log config must violate"
    assert result.chunk == 64  # recorded for cadence-exact replay
    assert result.atoms
    assert replay(cfg, result)


def test_shrink_fused_engine_repro():
    """A violation observed under the fused stream must shrink and replay
    under the SAME stream (soak defaults to --engine fused; ADVICE round 1:
    replaying a fused seed under the XLA stream explores a different
    schedule).  Off-TPU this runs the Pallas TPU interpreter, bit-identical
    to the compiled kernel."""
    cfg = SimConfig(
        n_inst=256, n_prop=2, n_acc=5, seed=3,
        fault=FaultConfig(p_idle=0.2, p_hold=0.2, p_equiv=0.3),
    )
    result = shrink(cfg, max_ticks=96, chunk=32, engine="fused")
    assert result is not None, "equivocation config must violate within budget"
    assert result.engine == "fused"
    assert result.atoms
    assert replay(cfg, result)
