"""Soak harness: rotating-seed accumulation and clean reporting."""

import dataclasses

import pytest

from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig, config2_dueling_drop
from paxos_tpu.harness.soak import soak


def test_soak_accumulates_rotating_seeds():
    cfg = config2_dueling_drop(n_inst=512, seed=7)
    report = soak(cfg, target_rounds=3 * 512 * 64, ticks_per_seed=64, chunk=32)
    assert report["seeds"] == 3  # ceil(target / (n_inst * ticks_per_seed))
    assert report["rounds"] == 3 * 512 * 64
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["evictions_first_pass"] == 0
    assert report["rechecked_seeds"] == []
    assert report["rounds_per_sec"] > 0


def test_soak_reports_liveness():
    """VERDICT r2 missing#6: the soak tally must carry liveness fields so a
    livelock regression shows in the headline report.  A partition-heavy
    config on a short budget leaves lanes undecided -> stuck lanes; a
    clean config decides everything -> zero."""
    from paxos_tpu.harness.config import config1_no_faults, config_partition

    part = soak(
        config_partition(n_inst=256, seed=3),
        target_rounds=2 * 256 * 24, ticks_per_seed=24, chunk=24,
    )
    assert part["stuck_lanes"] > 0, "partitions on a short budget must stick"
    assert part["stuck_lanes_max"] > 0
    assert 0.0 < part["stuck_frac"] <= 1.0
    assert part["decided_frac_min"] <= part["decided_frac_mean"] < 1.0

    clean = soak(
        config1_no_faults(n_inst=256, seed=3),
        target_rounds=256 * 64, ticks_per_seed=64, chunk=32,
    )
    assert clean["stuck_lanes"] == 0
    assert clean["stuck_frac"] == 0.0
    assert clean["decided_frac_mean"] == 1.0


def test_soak_gates_longlog_replication_rate():
    """VERDICT r3 #8: a long-log soak must GATE the replication rate, not
    just report it — a 2x replication slowdown previously only moved a
    statistic nobody failed on.  A healthy mini-soak reports the rate and
    passes a band below it; the same run judged against a band above the
    measured rate must say replication_ok=False (anti-vacuity: the gate can
    actually fire)."""
    from paxos_tpu.harness.config import config3_long

    cfg = config3_long(n_inst=64, seed=2, log_total=24, window=8)
    rounds = 2 * 64 * 64  # two campaigns of 64 ticks
    healthy = soak(
        cfg, target_rounds=rounds, ticks_per_seed=64, chunk=16,
        min_slots_per_lane_tick=1e-4,
    )
    assert healthy["slots_replicated"] > 0
    assert healthy["slots_per_lane_tick_min"] > 0
    assert (healthy["slots_per_lane_tick_mean"]
            >= healthy["slots_per_lane_tick_min"])
    assert healthy["replication_ok"] is True

    rate = healthy["slots_per_lane_tick_min"]
    gated = soak(
        cfg, target_rounds=rounds, ticks_per_seed=64, chunk=16,
        min_slots_per_lane_tick=rate * 2,  # pretend the recorded rate was 2x
    )
    assert gated["replication_ok"] is False, (
        "a sub-band replication rate must fail the gate"
    )

    # Non-long-log configs must not grow replication fields at all.
    plain = soak(
        config2_dueling_drop(n_inst=128, seed=1),
        target_rounds=128 * 32, ticks_per_seed=32, chunk=16,
    )
    assert "slots_replicated" not in plain
    assert "replication_ok" not in plain


def test_cli_soak_band_derivation_and_exit_codes(capsys):
    """The cmd_soak wiring around the gate (VERDICT r3 #8 + review): the
    auto band must respect BOTH achievable-rate ceilings (whole log done:
    log_total/ticks_per_seed; compaction cadence: window/chunk), a healthy
    coarse-chunk soak must exit 0, an explicit impossible band must exit 3,
    and --min-replication on a non-long-log config must be refused."""
    import json

    from paxos_tpu.harness.cli import main

    # Coarse chunk: the achievable ceiling is window/chunk = 16/128 = 0.125,
    # BELOW 0.7x the recorded 0.249 — the auto band must shrink to match,
    # so this healthy run exits 0 (pre-fix: exit 3 at band 0.1743).
    rc = main([
        "--platform", "cpu", "soak", "--config", "config3long", "--engine",
        "xla", "--n-inst", "64", "--target-rounds", "16384",
        "--ticks-per-seed", "256", "--chunk", "128",
    ])
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0, report
    assert report["replication_band"] == round(0.7 * (16 / 128), 6)
    assert report["replication_ok"] is True

    # Short budgets are warmup-dominated (election + first-decide latency),
    # so NO default band applies below the recorded cadence: the rate is
    # still reported, the gate stays off, and a healthy run exits 0.
    rc = main([
        "--platform", "cpu", "soak", "--config", "config3long", "--engine",
        "xla", "--n-inst", "64", "--target-rounds", "4096",
        "--ticks-per-seed", "64", "--chunk", "32",
    ])
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert "slots_per_lane_tick_min" in report
    assert "replication_band" not in report

    # The exit-3 leg: a band above the mathematical ceiling cannot pass.
    rc = main([
        "--platform", "cpu", "soak", "--config", "config3long", "--engine",
        "xla", "--n-inst", "64", "--target-rounds", "4096",
        "--ticks-per-seed", "64", "--chunk", "64", "--min-replication", "0.9",
    ])
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 3
    assert report["replication_ok"] is False

    # Misuse: an explicit band on a config that never reports a replication
    # rate must be refused, not silently ignored (vacuous exit 0).
    rc = main([
        "--platform", "cpu", "soak", "--config", "config2", "--engine",
        "xla", "--n-inst", "64", "--target-rounds", "1024",
        "--min-replication", "0.2",
    ])
    assert rc == 1


def test_retry_backoff_schedule():
    """Retry delays grow exponentially from the base and cap at ~60 s —
    a blip costs one short wait, a minutes-long outage stops being hammered
    — and the soak report records the planned schedule."""
    from paxos_tpu.harness.soak import _retry_schedule, soak

    assert _retry_schedule(6) == [5.0, 10.0, 20.0, 40.0, 60.0, 60.0]
    assert _retry_schedule(0) == []
    assert _retry_schedule(3, base_s=1.0) == [1.0, 2.0, 4.0]
    assert max(_retry_schedule(40), default=0.0) == 60.0  # capped forever

    cfg = config2_dueling_drop(n_inst=128, seed=0)
    report = soak(cfg, target_rounds=128 * 32, ticks_per_seed=32, chunk=16)
    assert report["retry_schedule_s"] == _retry_schedule(2)  # default budget


def test_retry_sleeps_follow_schedule_with_jitter(monkeypatch):
    """The actual sleeps must draw from [delay/2, delay] of the scheduled
    exponential delays (equal jitter), not a constant backoff."""
    import jax

    from paxos_tpu.harness import soak as soak_mod

    sleeps: list[float] = []
    monkeypatch.setattr(soak_mod.time, "sleep", sleeps.append)

    calls = {"n": 0}

    def always_fails():
        calls["n"] += 1
        raise jax.errors.JaxRuntimeError("INTERNAL: synthetic outage")

    with pytest.raises(jax.errors.JaxRuntimeError):
        soak_mod._run_with_retries(
            always_fails, lambda s: None, transient_retries=4, backoff_s=5.0
        )
    assert calls["n"] == 5  # initial try + 4 retries
    assert len(sleeps) == 4
    for got, planned in zip(sleeps, [5.0, 10.0, 20.0, 40.0]):
        assert planned / 2 <= got <= planned


def test_soak_retries_transient_backend_errors(monkeypatch):
    """A transient backend failure (tunnel remote-compile 500s) mid-soak
    must retry the campaign — an exact replay, campaigns being
    deterministic in (config, seed) — instead of killing a long run.
    A persistent failure still raises once the retry budget is spent."""
    import jax

    from paxos_tpu.harness import soak as soak_mod

    real_run = soak_mod.run
    fails = {"left": 1}

    def flaky_run(*a, **kw):
        if fails["left"] > 0:
            fails["left"] -= 1
            raise jax.errors.JaxRuntimeError(
                "INTERNAL: remote_compile: HTTP 500 (synthetic)"
            )
        return real_run(*a, **kw)

    monkeypatch.setattr(soak_mod, "run", flaky_run)
    cfg = config2_dueling_drop(n_inst=256, seed=11)
    report = soak_mod.soak(
        cfg, target_rounds=2 * 256 * 32, ticks_per_seed=32, chunk=16,
        retry_backoff_s=0.0,
    )
    assert report["transient_retries_used"] == 1
    assert report["seeds"] == 2
    assert report["violations"] == 0

    # Persistent failure: budget exhausted -> the error surfaces.
    fails["left"] = 10**9
    with pytest.raises(jax.errors.JaxRuntimeError):
        soak_mod.soak(
            cfg, target_rounds=256 * 32, ticks_per_seed=32, chunk=16,
            transient_retries=1, retry_backoff_s=0.0,
        )


def test_soak_rechecks_evicting_seeds():
    """VERDICT r1 missing#6: campaigns that hit the learner's K-slot bound
    must be re-checked at larger tables until the accounting is complete —
    the headline "0 violations" then covers 100% of lanes, not 1 - 2e-6."""
    # K=1 Fast Paxos under equivocation floods the one-slot table with
    # same-ballot/different-value conflicts (the test_differential
    # table-pressure recipe) — guaranteed evictions on the first pass.
    cfg = SimConfig(
        n_inst=64, n_prop=2, n_acc=5, k_slots=1, seed=5, protocol="fastpaxos",
        fault=FaultConfig(
            p_drop=0.15, p_dup=0.15, p_idle=0.2, p_hold=0.2,
            p_equiv=0.3, timeout=3, backoff_max=4,
        ),
    )
    report = soak(cfg, target_rounds=64 * 64, ticks_per_seed=64, chunk=32)
    assert report["evictions_first_pass"] > 0, "recipe must force evictions"
    assert report["rechecked_seeds"], "evicting campaign must be rechecked"
    rec = report["rechecked_seeds"][0]
    assert rec["k_slots"] > 1  # escalated
    assert rec["evictions"] == 0  # ... until complete
    assert report["evictions"] == 0  # headline tally is post-recheck


def test_soak_recheck_reports_unresolved():
    """With escalation capped below what the pressure needs, the report must
    say so rather than claim completeness."""
    cfg = SimConfig(
        n_inst=64, n_prop=2, n_acc=5, k_slots=1, seed=5, protocol="fastpaxos",
        fault=FaultConfig(
            p_drop=0.15, p_dup=0.15, p_idle=0.2, p_hold=0.2,
            p_equiv=0.3, timeout=3, backoff_max=4,
        ),
    )
    report = soak(
        cfg, target_rounds=64 * 64, ticks_per_seed=64, chunk=32,
        recheck_doublings=0,
    )
    assert report["evictions"] > 0
    assert report["rechecked_seeds"][0]["evictions"] == report["evictions"]
