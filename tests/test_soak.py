"""Soak harness: rotating-seed accumulation and clean reporting."""

from paxos_tpu.harness.config import config2_dueling_drop
from paxos_tpu.harness.soak import soak


def test_soak_accumulates_rotating_seeds():
    cfg = config2_dueling_drop(n_inst=512, seed=7)
    report = soak(cfg, target_rounds=3 * 512 * 64, ticks_per_seed=64, chunk=32)
    assert report["seeds"] == 3  # ceil(target / (n_inst * ticks_per_seed))
    assert report["rounds"] == 3 * 512 * 64
    assert report["violations"] == 0
    assert report["evictions"] == 0
    assert report["rounds_per_sec"] > 0
