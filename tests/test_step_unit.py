"""Deterministic known-answer tests for the paxos step.

With at most one in-flight request per acceptor and p_idle = p_hold = 0, the
adversarial scheduler has no freedom: selection must pick the lone
message and replies deliver the next tick.  That determinism lets us
hand-construct the interleavings that famously break wrong Paxos
implementations (SURVEY.md §5.2.3) and assert exact state transitions.
"""

import jax
import jax.numpy as jnp

from paxos_tpu.core.ballot import make_ballot
from paxos_tpu.core.messages import ACCEPT, ACCEPTED, PREPARE, PROMISE
from paxos_tpu.core.state import DONE, P1, P2, PaxosState
from paxos_tpu.faults.injector import FaultConfig, FaultPlan
from paxos_tpu.protocols.paxos import paxos_step

CFG = FaultConfig(timeout=1000)  # no timeouts, no faults: fully deterministic
KEY = jax.random.PRNGKey(7)


def fresh(n_inst=2, n_prop=1, n_acc=3):
    """Init state with the automatic initial PREPAREs cleared out."""
    s = PaxosState.init(n_inst, n_prop, n_acc)
    s = s.replace(
        requests=s.requests.replace(present=jnp.zeros_like(s.requests.present))
    )
    return s, FaultPlan.none(n_inst, n_acc)


def put(buf, kind, p, a, bal, v1=0, v2=0):
    return buf.replace(
        bal=buf.bal.at[kind, p, a].set(bal),
        v1=buf.v1.at[kind, p, a].set(v1),
        v2=buf.v2.at[kind, p, a].set(v2),
        present=buf.present.at[kind, p, a].set(True),
    )


def test_prepare_granted_and_rejected():
    s, plan = fresh()
    reqs = s.requests.replace(present=jnp.zeros_like(s.requests.present))
    b = int(make_ballot(1, 0))
    reqs = put(reqs, PREPARE, p=0, a=0, bal=b)
    # Instance 1's acceptor 0 already promised higher.
    acc = s.acceptor.replace(promised=s.acceptor.promised.at[0, 1].set(b + 8))
    s = s.replace(requests=reqs, acceptor=acc)

    s2 = paxos_step(s, KEY, plan, CFG)
    assert int(s2.acceptor.promised[0, 0]) == b  # granted
    assert int(s2.acceptor.promised[0, 1]) == b + 8  # unchanged
    assert bool(s2.replies.present[PROMISE, 0, 0, 0])  # promise sent
    assert not bool(s2.replies.present[PROMISE, 0, 0, 1])  # silent reject
    assert int(s2.replies.bal[PROMISE, 0, 0, 0]) == b
    assert not bool(s2.requests.present[PREPARE, 0, 0, 0])  # consumed


def test_stale_accept_after_higher_promise_rejected():
    """THE killer interleaving: ACCEPT(b1) delivered after PROMISE(b2>b1)."""
    s, plan = fresh()
    b1, b2 = int(make_ballot(0, 0)), int(make_ballot(5, 0))
    reqs = s.requests.replace(present=jnp.zeros_like(s.requests.present))
    reqs = put(reqs, ACCEPT, p=0, a=0, bal=b1, v1=42)
    acc = s.acceptor.replace(promised=jnp.full_like(s.acceptor.promised, b2))
    s = s.replace(requests=reqs, acceptor=acc)

    s2 = paxos_step(s, KEY, plan, CFG)
    assert int(s2.acceptor.acc_bal[0, 0]) == 0  # NOT accepted
    assert int(s2.acceptor.acc_val[0, 0]) == 0
    assert not bool(s2.replies.present[ACCEPTED, 0, 0, 0])
    assert int(s2.learner.lt_mask.sum()) == 0  # no accept event observed
    assert int(s2.learner.violations.sum()) == 0


def test_accept_at_or_above_promise_accepted():
    s, plan = fresh()
    b = int(make_ballot(2, 0))
    reqs = s.requests.replace(present=jnp.zeros_like(s.requests.present))
    reqs = put(reqs, ACCEPT, p=0, a=1, bal=b, v1=42)
    acc = s.acceptor.replace(promised=s.acceptor.promised.at[1, :].set(b))
    s = s.replace(requests=reqs, acceptor=acc)

    s2 = paxos_step(s, KEY, plan, CFG)
    assert int(s2.acceptor.acc_bal[1, 0]) == b
    assert int(s2.acceptor.acc_val[1, 0]) == 42
    assert bool(s2.replies.present[ACCEPTED, 0, 1, 0])
    # Learner recorded the accept event for (b, 42) by acceptor 1.
    assert int(s2.learner.lt_mask.sum(axis=0)[0]) == 2  # bit 1
    assert int(s2.learner.violations.sum()) == 0


def test_proposer_adopts_highest_accepted_value():
    s, plan = fresh(n_inst=1, n_prop=1, n_acc=3)
    b = int(s.proposer.bal[0, 0])  # round-0 ballot, phase P1
    reps = s.replies
    reps = put(reps, PROMISE, p=0, a=0, bal=b, v1=0, v2=0)
    # Acceptor 1 previously accepted (5, 77): its promise carries the pair.
    reps = put(reps, PROMISE, p=0, a=1, bal=b, v1=5, v2=77)
    s = s.replace(replies=reps)
    s = s.replace(requests=s.requests.replace(present=jnp.zeros_like(s.requests.present)))

    s2 = paxos_step(s, KEY, plan, CFG)
    assert int(s2.proposer.phase[0, 0]) == P2  # quorum of 2/3 promises
    assert int(s2.proposer.prop_val[0, 0]) == 77  # adopted, NOT own value
    for a in range(3):
        assert bool(s2.requests.present[ACCEPT, 0, a, 0])
        assert int(s2.requests.v1[ACCEPT, 0, a, 0]) == 77
        assert int(s2.requests.bal[ACCEPT, 0, a, 0]) == b


def test_proposer_decides_on_accepted_quorum():
    s, plan = fresh(n_inst=1, n_prop=1, n_acc=3)
    b = int(s.proposer.bal[0, 0])
    prop = s.proposer.replace(
        phase=s.proposer.phase.at[0, 0].set(P2),
        prop_val=s.proposer.prop_val.at[0, 0].set(100),
    )
    reps = s.replies
    reps = put(reps, ACCEPTED, p=0, a=0, bal=b, v1=100)
    reps = put(reps, ACCEPTED, p=0, a=2, bal=b, v1=100)
    s = s.replace(
        proposer=prop,
        replies=reps,
        requests=s.requests.replace(present=jnp.zeros_like(s.requests.present)),
    )

    s2 = paxos_step(s, KEY, plan, CFG)
    assert int(s2.proposer.phase[0, 0]) == DONE
    assert int(s2.proposer.decided_val[0, 0]) == 100


def test_stale_ballot_replies_ignored():
    s, plan = fresh(n_inst=1, n_prop=1, n_acc=3)
    stale = 999  # not the proposer's current ballot
    reps = put(s.replies, PROMISE, p=0, a=0, bal=stale, v1=0, v2=0)
    s = s.replace(
        replies=reps,
        requests=s.requests.replace(present=jnp.zeros_like(s.requests.present)),
    )
    s2 = paxos_step(s, KEY, plan, CFG)
    assert int(s2.proposer.heard[0, 0]) == 0
    assert int(s2.proposer.phase[0, 0]) == P1
    assert not bool(s2.replies.present[PROMISE, 0, 0, 0])  # consumed anyway
