"""Config 5: Paxos vs Fast-Paxos vs Raft-core under identical fault masks.

SURVEY.md §8.2 M7 / BASELINE config 5: the three vote kernels run behind the
shared step-fn interface over the same topology and the *same sampled fault
plan*, so liveness differences are attributable to the protocols, not the
schedule; safety must hold for all three.
"""

import jax
import jax.numpy as jnp

from paxos_tpu.harness.config import config5_sweep
from paxos_tpu.harness.run import init_plan, run


def test_sweep_shares_fault_plans():
    """The sampled fault plan is bit-identical across the three protocols."""
    cfgs = config5_sweep(n_inst=64, seed=5)
    assert [c.protocol for c in cfgs] == ["paxos", "fastpaxos", "raftcore"]
    plans = [init_plan(c) for c in cfgs]
    for other in plans[1:]:
        assert all(
            bool(jnp.array_equal(a, b))
            for a, b in zip(jax.tree.leaves(plans[0]), jax.tree.leaves(other))
        )


def test_sweep_all_protocols_safe_and_live():
    reports = {}
    for cfg in config5_sweep(n_inst=1024, seed=2):
        rep = run(cfg, until_all_chosen=True, max_ticks=2048)
        reports[cfg.protocol] = rep
        assert rep["violations"] == 0, cfg.protocol
        assert rep["evictions"] == 0, cfg.protocol
        assert rep["chosen_frac"] == 1.0, cfg.protocol
    # The sweep's point: comparable liveness numbers out of one harness.
    assert set(reports) == {"paxos", "fastpaxos", "raftcore"}
    for rep in reports.values():
        assert rep["mean_choose_tick"] > 0.0
