"""SynchPaxos: the synchrony bet, the fallback, and the planted bug.

SynchPaxos (arXiv 2507.12792) decides in one round trip whenever message
delays respect the synchrony window Δ, and falls back to classic ballots
when they don't.  Crucially Δ is a LIVENESS bet, never a safety
assumption: when the bound is violated the honest protocol merely loses
its fast path, while the ``sp_unsafe_fast`` planted bug — deciding on the
first fast ack instead of a quorum — becomes a catchable agreement
violation (the ``proposer_disagree`` checker plane, since the learner
itself never sees the premature decide).

The ``ballot_stride`` sweep (arXiv 2006.01885) rides here too: proposers
that advance retry ballots by an odd stride > 1 still satisfy every
safety invariant — the knob only has to keep per-proposer ballot
sequences disjoint, which any stride preserves.
"""

import dataclasses

import jax
import pytest

from paxos_tpu.faults.injector import FaultConfig
from paxos_tpu.harness.config import SimConfig, config_delay_chaos
from paxos_tpu.harness.run import run
from paxos_tpu.protocols.synchpaxos import fast_path_rate


def _small(cfg, n_inst=256):
    return dataclasses.replace(cfg, n_inst=n_inst)


def test_fast_path_fault_free():
    """No faults: every instance decides inside Δ on the round-0 ballot."""
    cfg = SimConfig(n_inst=256, n_prop=2, n_acc=5, protocol="synchpaxos")
    report, state = run(
        cfg, until_all_chosen=True, max_ticks=64, return_state=True
    )
    assert report["violations"] == 0
    assert report["proposer_disagree"] == 0
    assert report["chosen_frac"] == 1.0
    assert fast_path_rate(state) == 1.0


def test_fast_path_survives_delta_respecting_delay():
    """Latencies capped under Δ: the synchrony bet pays off — the fast
    path still lands despite real per-link delay queues (and some loss)."""
    cfg = _small(config_delay_chaos(seed=7))
    assert cfg.fault.delay_max < cfg.fault.delta  # the regime's premise
    report, state = run(
        cfg, until_all_chosen=True, max_ticks=256, return_state=True
    )
    assert report["violations"] == 0
    assert report["proposer_disagree"] == 0
    assert fast_path_rate(state) > 0.5


def test_delta_violation_honest_falls_back_safely():
    """Latencies above Δ: the bet loses, the honest protocol falls back to
    classic ballots — slower, but zero safety violations and zero
    cross-proposer disagreement."""
    cfg = _small(config_delay_chaos(seed=1, violate_delta=True))
    assert cfg.fault.delay_max > cfg.fault.delta
    report = run(cfg, total_ticks=256)
    assert report["violations"] == 0
    assert report["proposer_disagree"] == 0
    assert report["chosen_frac"] > 0.0  # fallback makes progress anyway


@pytest.mark.parametrize("seed", [1, 7, 13])
def test_unsafe_fast_bug_caught_within_one_campaign(seed):
    """``sp_unsafe_fast`` decides on the first fast ack: under Δ-violating
    delay + loss, a stale fast decide and a newer fallback decide disagree
    within a single 256-tick campaign — flagged by ``proposer_disagree``
    (the learner's own chosen-value plane stays clean, which is exactly
    why the cross-proposer checker exists)."""
    cfg = _small(config_delay_chaos(seed=seed, violate_delta=True))
    # Heavier loss than the soak regime: dropped fast acks force the
    # fallback re-proposals whose decide the stale fast decide contradicts.
    cfg = dataclasses.replace(
        cfg,
        fault=dataclasses.replace(cfg.fault, sp_unsafe_fast=True, p_drop=0.4),
    )
    report = run(cfg, total_ticks=256)
    assert report["violations"] == 0  # the learner plane alone stays blind
    assert report["proposer_disagree"] >= 1, seed


def test_unsafe_fast_needs_delta_violation_to_fire():
    """The same bug under Δ-respecting latencies stays latent: every fast
    ack the buggy decide trusts is also inside the window, so the quorum
    it skipped would have agreed anyway."""
    cfg = _small(config_delay_chaos(seed=7))
    cfg = dataclasses.replace(
        cfg, fault=dataclasses.replace(cfg.fault, sp_unsafe_fast=True)
    )
    report = run(cfg, total_ticks=256)
    assert report["violations"] == 0
    assert report["proposer_disagree"] == 0


# --- ballot_stride sweep (arXiv 2006.01885) ------------------------------


@pytest.mark.parametrize("protocol", ["paxos", "synchpaxos"])
def test_ballot_stride_sweep_safe_and_live(protocol):
    """Strides 1/3/7 under dueling-proposer contention: safety and full
    liveness hold at every stride, and larger strides visibly reach
    higher ballots (the rounds really do advance by the stride)."""
    max_bals = {}
    for stride in (1, 3, 7):
        cfg = SimConfig(
            n_inst=128, n_prop=2, n_acc=3, seed=11, protocol=protocol,
            fault=FaultConfig(p_drop=0.25, timeout=6, ballot_stride=stride),
        )
        report, state = run(
            cfg, until_all_chosen=True, max_ticks=1024, return_state=True
        )
        assert report["violations"] == 0, (protocol, stride)
        assert report["proposer_disagree"] == 0, (protocol, stride)
        assert report["chosen_frac"] == 1.0, (protocol, stride)
        max_bals[stride] = int(jax.device_get(state.proposer.bal.max()))
    assert max_bals[7] > max_bals[1], max_bals
